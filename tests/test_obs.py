"""Unified runtime telemetry (ISSUE 11): the metric registry, span
tracing, SLO evaluation, and the fit-loop integration."""

import json
import threading

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_embeddings_tpu import obs, training
from distributed_embeddings_tpu.obs.registry import LatencyHistogram
from distributed_embeddings_tpu.parallel.mesh import create_mesh

from test_sparse_train import TinyModel


# ------------------------------------------------------------- registry
def test_registry_families_and_identity():
    reg = obs.MetricRegistry()
    c = reg.counter("train/steps")
    c.inc()
    c.inc(3)
    assert reg.counter("train/steps") is c and c.value == 4
    # labels split families into distinct instruments
    g0 = reg.gauge("vocab/occupancy", table=0)
    g1 = reg.gauge("vocab/occupancy", table=1)
    assert g0 is not g1
    g0.set(0.5)
    g1.set(0.9)
    h = reg.histogram("serve/request_seconds")
    assert isinstance(h, LatencyHistogram)
    assert reg.histogram("serve/request_seconds") is h


def test_registry_kind_conflict_raises():
    reg = obs.MetricRegistry()
    reg.counter("x")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x")


def test_registry_histogram_layout_first_wins():
    reg = obs.MetricRegistry()
    reg.histogram("h", bins_per_decade=32)
    with pytest.raises(ValueError, match="bucket layout"):
        reg.histogram("h", bins_per_decade=8)
    with pytest.raises(ValueError, match="bucket layout"):
        reg.histogram("h", hi=1.0)      # bins derive from hi: refuses too
    assert reg.histogram("h") is reg.histogram("h")   # same layout: fine


def test_snapshot_schema_and_flat_keys():
    reg = obs.MetricRegistry()
    reg.counter("a/b").inc(2)
    reg.gauge("g", table=3, stage="x").set(1.5)
    reg.histogram("lat").record(0.01)
    snap = reg.snapshot()
    assert set(snap) == {"counters", "gauges", "histograms"}
    assert snap["counters"] == {"a/b": 2}
    # labels sorted into the flat key
    assert snap["gauges"] == {"g{stage=x,table=3}": 1.5}
    assert snap["histograms"]["lat"]["count"] == 1
    assert {"p50_ms", "p95_ms", "p99_ms", "mean_ms",
            "max_ms"} <= set(snap["histograms"]["lat"])


def test_jsonl_export_appends_parseable_lines(tmp_path):
    reg = obs.MetricRegistry()
    reg.counter("n").inc()
    path = str(tmp_path / "m.jsonl")
    reg.export_jsonl(path, extra={"source": "test"})
    reg.counter("n").inc()
    reg.export_jsonl(path)
    lines = [json.loads(ln) for ln in open(path)]
    assert len(lines) == 2
    assert lines[0]["source"] == "test" and lines[0]["counters"]["n"] == 1
    assert lines[1]["counters"]["n"] == 2
    assert all("ts" in ln for ln in lines)


def test_prometheus_dump():
    reg = obs.MetricRegistry()
    reg.counter("train/steps").inc(7)
    reg.gauge("vocab/occupancy", table=0).set(0.25)
    h = reg.histogram("serve/request_seconds")
    for _ in range(10):
        h.record(0.002)
    text = reg.to_prometheus()
    assert "# TYPE train_steps_total counter" in text
    assert "train_steps_total 7" in text
    assert 'vocab_occupancy{table="0"} 0.25' in text
    assert 'serve_request_seconds{quantile="0.99"}' in text
    assert "serve_request_seconds_count 10" in text


def test_default_registry_process_local():
    obs.reset_default_registry()
    try:
        a = obs.default_registry()
        assert obs.default_registry() is a
        a.counter("x").inc()
        obs.reset_default_registry()
        assert obs.default_registry() is not a
    finally:
        obs.reset_default_registry()


# --------------------------------------------- histogram merge property
def test_latency_histogram_merge_matches_concatenated_samples():
    """merge(a, b) must equal the histogram over the concatenated
    sample stream: identical bucket counts, hence identical
    percentiles (the interpolation reads only counts/edges/max), max
    exact, mean within float-summation tolerance. Property-tested over
    random log-uniform streams including overflow-bucket values."""
    rng = np.random.RandomState(7)
    for trial in range(5):
        s1 = 10.0 ** rng.uniform(-6.5, 2.5, size=rng.randint(1, 400))
        s2 = 10.0 ** rng.uniform(-6.5, 2.5, size=rng.randint(1, 400))
        a, b, ref = (LatencyHistogram(), LatencyHistogram(),
                     LatencyHistogram())
        for v in s1:
            a.record(v)
        for v in s2:
            b.record(v)
        for v in np.concatenate([s1, s2]):
            ref.record(v)
        merged = a.merge(b)
        assert merged is a                      # in-place, chainable
        np.testing.assert_array_equal(merged._counts, ref._counts)
        for p in (1, 25, 50, 90, 95, 99, 100):
            assert merged.percentile(p) == ref.percentile(p), (trial, p)
        assert merged._max == ref._max
        assert merged.summary()["mean_ms"] == pytest.approx(
            ref.summary()["mean_ms"], rel=1e-9)


def test_latency_histogram_merge_layout_mismatch_raises():
    a = LatencyHistogram()
    b = LatencyHistogram(bins_per_decade=8)
    with pytest.raises(ValueError, match="bucket layouts"):
        a.merge(b)


# ----------------------------------------------------------------- spans
def test_spans_nest_paths_and_record():
    reg = obs.MetricRegistry()
    with obs.span("train", reg):
        assert obs.current_span() == "train"
        with obs.span("step", reg) as path:
            assert path == "train/step"
            assert obs.current_span() == "train/step"
    assert obs.current_span() is None
    h = reg.snapshot()["histograms"]
    assert h["span_seconds{span=train}"]["count"] == 1
    assert h["span_seconds{span=train/step}"]["count"] == 1


def test_span_records_on_exception():
    reg = obs.MetricRegistry()
    with pytest.raises(RuntimeError):
        with obs.span("boom", reg):
            raise RuntimeError("x")
    assert reg.histogram("span_seconds", span="boom").count == 1
    assert obs.current_span() is None           # stack unwound


def test_span_stack_is_thread_local():
    reg = obs.MetricRegistry()
    seen = {}

    def worker():
        with obs.span("worker", reg):
            seen["inner"] = obs.current_span()

    with obs.span("outer", reg):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    # the worker thread's span must NOT nest under the main thread's
    assert seen["inner"] == "worker"
    assert reg.histogram("span_seconds", span="worker").count == 1


# ------------------------------------------------------------------- slo
def _snap(**over):
    base = {"counters": {"train/steps": 8},
            "gauges": {"lookahead/compiles{stage=fused}": 1.0},
            "histograms": {"serve/request_seconds": {
                "count": 10, "mean_ms": 1.0, "p50_ms": 1.0,
                "p95_ms": 2.0, "p99_ms": 3.0, "max_ms": 4.0}}}
    base.update(over)
    return base


def test_slo_green_and_violation():
    rules = [{"name": "one-compile",
              "metric": "lookahead/compiles{stage=fused}",
              "op": "==", "threshold": 1},
             {"name": "p99", "metric": "serve/request_seconds:p99_ms",
              "op": "<=", "threshold": 5}]
    assert obs.evaluate_rules(rules, _snap()) == []
    bad = _snap(gauges={"lookahead/compiles{stage=fused}": 2.0})
    findings = obs.evaluate_rules(rules, bad)
    assert len(findings) == 1
    f = findings[0]
    # analysis.passes.Finding shape — gated like audit findings
    from distributed_embeddings_tpu.analysis.passes import Finding
    assert isinstance(f, Finding)
    assert f.pass_name == "slo" and f.fid == "slo:one-compile"
    assert f.severity == "error" and "2" in f.message


def test_slo_absent_metric_is_a_finding():
    rules = [{"name": "occ", "metric": "vocab/occupancy", "op": "<=",
              "threshold": 0.9}]
    findings = obs.evaluate_rules(rules, _snap())
    assert [f.fid for f in findings] == ["slo:occ:absent"]


def test_slo_window_over_snapshot_sequence():
    rules = [{"name": "p99", "metric": "serve/request_seconds:p99_ms",
              "op": "<=", "threshold": 5, "window": 2}]
    spike = _snap(histograms={"serve/request_seconds": {
        "count": 10, "mean_ms": 1.0, "p50_ms": 1.0, "p95_ms": 2.0,
        "p99_ms": 50.0, "max_ms": 60.0}})
    # spike outside the window: green
    assert obs.evaluate_rules(rules, [spike, _snap(), _snap()]) == []
    # spike inside the window: violation
    assert len(obs.evaluate_rules(rules, [_snap(), _snap(), spike])) == 1


def test_slo_malformed_rules_raise():
    with pytest.raises(ValueError, match="missing"):
        obs.evaluate_rules([{"metric": "x", "op": "==", "threshold": 1}],
                           _snap())
    with pytest.raises(ValueError, match="op"):
        obs.evaluate_rules([{"name": "n", "metric": "x", "op": "~",
                             "threshold": 1}], _snap())
    # histogram addressed without a field = rule bug, loud
    with pytest.raises(ValueError, match="summary field"):
        obs.evaluate_rules([{"name": "n",
                             "metric": "serve/request_seconds",
                             "op": "<=", "threshold": 1}], _snap())


def test_slo_load_rules_file(tmp_path):
    path = tmp_path / "rules.json"
    path.write_text(json.dumps({"rules": [
        {"name": "a", "metric": "train/steps", "op": ">=",
         "threshold": 1}]}))
    rules = obs.load_rules(str(path))
    assert rules[0]["name"] == "a"
    assert obs.evaluate_rules(rules, _snap()) == []


def test_checked_in_tier1_rule_file_is_valid():
    # the CI smoke's rule file must always load/validate — a malformed
    # checked-in rule would otherwise only fail inside the smoke
    import os
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "slo_tier1.json")
    rules = obs.load_rules(path)
    assert len(rules) >= 6
    names = [r["name"] for r in rules]
    assert "one-fused-compile" in names and "zero-audit-findings" in names


# ------------------------------------------------------- fit integration
SPECS = [(50, 8, "sum")] * 6


def _data(step):
    r = np.random.RandomState(step % 4)
    cats = [r.randint(0, 50, (16, 2)) for _ in SPECS]
    return (np.zeros((16, 1), np.float32), cats,
            r.randn(16).astype(np.float32))


def test_fit_reports_through_one_registry():
    mesh = create_mesh(jax.devices()[:8])
    model = TinyModel(SPECS, mesh)
    rng = np.random.RandomState(0)
    params = {
        "embedding": model.embedding.init(jax.random.PRNGKey(0)),
        "head": {"w": jnp.asarray(rng.randn(48, 1).astype(np.float32)
                                  * 0.1)},
    }
    reg = obs.MetricRegistry()
    params, _, hist = training.fit(
        model, params, (_data(i) for i in range(6)), steps=6,
        optimizer="adagrad", lr=0.1, log_every=0, registry=reg)
    snap = hist["metrics_snapshot"]
    assert snap["counters"]["train/steps"] == 6
    assert snap["counters"]["train/examples"] == 6 * 16
    assert snap["histograms"]["span_seconds{span=train/step}"][
        "count"] == 6
    assert snap["gauges"]["train/examples_per_sec"] > 0
    # ingest stage histograms share the SAME registry (and agree with
    # the history's own stage accounting)
    assert snap["histograms"]["ingest/stage_seconds{stage=read}"][
        "count"] == 6
    assert (hist["ingest_stages"]["read"]
            == snap["histograms"]["ingest/stage_seconds{stage=read}"])
    # the static exchange gauges rode along
    assert "exchange/touched_rows_per_step" in snap["gauges"]
    assert snap == reg.snapshot()
