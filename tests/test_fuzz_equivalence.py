"""Seeded randomized-config equivalence sweep.

The hand-written tests cover the reference's named cases; this sweep walks
random corners of the planner x forward configuration space (table
counts/sizes/widths, combiners, shared tables, thresholds, strategies) and
requires exact reference-model equivalence for each. Seeds are fixed —
failures reproduce.
"""

import numpy as np
import pytest

from test_dist_model_parallel import check_equivalence

STRATEGIES = ["basic", "memory_balanced", "memory_optimized"]


def gen_config(seed):
    rng = np.random.RandomState(1000 + seed)
    n = int(rng.randint(4, 11))
    specs = []
    for _ in range(n):
        vocab = int(rng.choice([8, 40, 120, 500, 1300, 5000]))
        width = int(rng.choice([4, 8, 16]))
        combiner = [None, "sum", "mean"][rng.randint(3)]
        specs.append((vocab, width, combiner))
    # occasionally share a table between two inputs
    table_map = list(range(n))
    if n >= 4 and rng.rand() < 0.5:
        table_map.append(int(rng.randint(n)))
    kw = {"strategy": STRATEGIES[rng.randint(3)]}
    if rng.rand() < 0.5:
        kw["data_parallel_threshold"] = int(rng.choice([64, 400]))
    if rng.rand() < 0.5:
        kw["column_slice_threshold"] = int(rng.choice([2000, 8000]))
    if rng.rand() < 0.5:
        kw["row_slice_threshold"] = int(rng.choice([8000, 40000]))
    return specs, table_map, kw


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(8))
def test_random_config_equivalence(seed):
    specs, table_map, kw = gen_config(seed)
    try:
        check_equivalence(specs, input_table_map=table_map, seed=seed,
                          check_train=(seed % 4 == 0), **kw)
    except ValueError as e:
        if "Not enough tables" in str(e):
            pytest.skip(f"seed {seed}: config unplaceable on 8 devices")
        raise
