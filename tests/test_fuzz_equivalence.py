"""Seeded randomized-config equivalence sweep.

The hand-written tests cover the reference's named cases; this sweep walks
random corners of the planner x forward configuration space (table
counts/sizes/widths, combiners, shared tables, thresholds, strategies) and
requires exact reference-model equivalence for each. Seeds are fixed —
failures reproduce.
"""

import numpy as np
import pytest

from test_dist_model_parallel import check_equivalence

STRATEGIES = ["basic", "memory_balanced", "memory_optimized",
              "comm_balanced", "auto"]


def gen_config(seed):
    rng = np.random.RandomState(1000 + seed)
    n = int(rng.randint(4, 11))
    specs = []
    for _ in range(n):
        vocab = int(rng.choice([8, 40, 120, 500, 1300, 5000]))
        width = int(rng.choice([4, 8, 16]))
        combiner = [None, "sum", "mean"][rng.randint(3)]
        specs.append((vocab, width, combiner))
    # occasionally share a table between two inputs
    table_map = list(range(n))
    if n >= 4 and rng.rand() < 0.5:
        table_map.append(int(rng.randint(n)))
    kw = {"strategy": STRATEGIES[rng.randint(len(STRATEGIES))]}
    if rng.rand() < 0.5:
        kw["data_parallel_threshold"] = int(rng.choice([64, 400]))
    if rng.rand() < 0.5:
        kw["column_slice_threshold"] = int(rng.choice([2000, 8000]))
    if rng.rand() < 0.5:
        kw["row_slice_threshold"] = int(rng.choice([8000, 40000]))
    if rng.rand() < 0.3:
        # host-offload the biggest buckets (pinned_host on the CPU backend)
        kw["gpu_embedding_size"] = int(rng.choice([3000, 12000]))
    if rng.rand() < 0.3:
        import jax.numpy as jnp
        kw["compute_dtype"] = jnp.bfloat16
        kw.update(rtol=4e-2, atol=4e-2, train_rtol=4e-2, train_atol=4e-2)
    if rng.rand() < 0.3:
        # wire-dtype axis (ISSUE 5): bf16 exchange wire, f32 local math —
        # one rounding per wire crossing, so the bf16 compute tolerance
        # covers it (combiner-None buckets keep f32 by the plan gate)
        kw["exchange_wire"] = "bf16"
        kw.update(rtol=4e-2, atol=4e-2, train_rtol=4e-2, train_atol=4e-2)
    if rng.rand() < 0.35:
        # store-backed axis (ISSUE 6): params materialize through the
        # table store's publish/consume path (snapshot file -> consumer
        # apply — bit-exact by contract), so every equivalence property
        # in this sweep also runs against store-backed parameters
        kw["store_roundtrip"] = True
    if rng.rand() < 0.3:
        # vocab axis (ISSUE 7): the batch arrives as RAW int64 keys and
        # reaches the forward through a VocabManager binding over a
        # slack-inflated plan — every equivalence property also holds
        # for dynamically-bound vocabularies
        kw["vocab_axis"] = True
    if rng.rand() < 0.3:
        # lookahead axis (ISSUE 9): train the same plan through the
        # staged prefetch/patch/drain pipeline and require BIT-exact
        # agreement with the monolithic sparse step (engine-refused
        # configs — offloaded buckets, all-dp plans — skip the axis)
        kw["lookahead_axis"] = True
    if rng.rand() < 0.3:
        # storage-dtype axis (ISSUE 15 + 17): quantized at-rest rows on
        # BOTH residencies. Half the draws force an offload budget
        # (cold buckets: decode in the host exchange path); the other
        # half leave whatever residency the config already drew — under
        # the ISSUE 17 lifted gate device-resident buckets ALSO
        # quantize, exercising the decode-at-gather branch inside the
        # jitted forward. One decode per gather either way: the
        # bf16-class tolerance covers it. (LookaheadEngine refuses
        # quantized buckets, so that axis self-skips here.)
        kw["storage_dtype"] = "int8"
        if rng.rand() < 0.5:
            kw.setdefault("gpu_embedding_size",
                          int(rng.choice([3000, 12000])))
        kw.update(rtol=4e-2, atol=4e-2, train_rtol=4e-2, train_atol=4e-2)
    return specs, table_map, kw


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(8))
def test_random_config_equivalence(seed):
    specs, table_map, kw = gen_config(seed)
    try:
        check_equivalence(specs, input_table_map=table_map, seed=seed,
                          check_train=(seed % 4 == 0), **kw)
    except ValueError as e:
        if "Not enough tables" in str(e):
            pytest.skip(f"seed {seed}: config unplaceable on 8 devices")
        raise


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(4))
def test_random_config_ragged_and_weighted(seed):
    """Same sweep but inputs arrive as RaggedIds / (ids, weights) tuples
    for combiner tables — the other two prepared-input forms."""
    import jax.numpy as jnp
    from distributed_embeddings_tpu.ops.embedding_ops import RaggedIds
    from test_dist_model_parallel import BATCH

    specs, table_map, kw = gen_config(seed)
    rng = np.random.RandomState(2000 + seed)
    inputs, max_hot = [], []
    for i, t in enumerate(table_map):
        v, _, c = specs[t]
        if c is None:
            inputs.append(jnp.asarray(rng.randint(0, v, size=(BATCH,))))
            max_hot.append(1)
        elif rng.rand() < 0.5:
            k = int(rng.randint(2, 6))
            lengths = rng.randint(1, k + 1, size=BATCH)
            values = rng.randint(0, v, size=int(lengths.sum()))
            splits = np.cumsum([0] + list(lengths))
            inputs.append(RaggedIds(jnp.asarray(values.astype(np.int32)),
                                    jnp.asarray(splits.astype(np.int32))))
            max_hot.append(k)
        else:
            k = int(rng.randint(2, 5))
            ids = rng.randint(0, v, size=(BATCH, k))
            w = (rng.rand(BATCH, k) > 0.3).astype(np.float32)
            inputs.append((jnp.asarray(ids), jnp.asarray(w)))
            max_hot.append(k)
    try:
        check_equivalence(specs, input_table_map=table_map, inputs=inputs,
                          input_max_hotness=max_hot, seed=seed,
                          check_train=(seed == 0), **kw)
    except ValueError as e:
        if "Not enough tables" in str(e):
            pytest.skip(f"seed {seed}: config unplaceable on 8 devices")
        raise


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(6))
def test_storage_dtype_stream_and_stash_fuzz(seed, tmp_path):
    """Storage-dtype axis over the train-to-serve row stores (ISSUE 15):
    random configs through publish -> consume (random delta dtype) and
    admit -> evict -> re-admit (random stash dtype), asserting the
    documented per-row decode bounds — and BIT-exactness at f32."""
    import jax
    import jax.numpy as jnp
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.ops import wire as wire_ops
    from distributed_embeddings_tpu.store import TableStore, scan_published
    from distributed_embeddings_tpu.vocab import VocabManager
    from test_dist_model_parallel import make_mesh

    rng = np.random.RandomState(4000 + seed)
    dtypes = ["f32", "int8"] + (["fp8"] if wire_ops.fp8_supported()
                                else [])
    delta_dtype = dtypes[rng.randint(len(dtypes))]
    stash_dtype = dtypes[rng.randint(len(dtypes))]
    n = int(rng.randint(6, 10))
    specs = [(int(rng.choice([40, 120, 500, 1500])),
              int(rng.choice([8, 16, 32])), "sum") for _ in range(n)]
    kw = {}
    if rng.rand() < 0.5:
        # offload the big tables so the STORED-quantized read/apply
        # seam (not just the stream codec) is on the fuzzed path
        kw["gpu_embedding_size"] = 3000
        kw["storage_dtype"] = delta_dtype
    mesh = make_mesh(8)
    W = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in specs]

    def build():
        return DistributedEmbedding(
            [Embedding(v, w, combiner=c) for v, w, c in specs],
            mesh=mesh, **kw)

    # ---- publish -> consume at the random delta dtype
    emb = build()
    store = TableStore(emb, emb.set_weights(W), delta_dtype=delta_dtype)
    d = str(tmp_path / "pub")
    store.publish(d)
    ins = [jnp.asarray(rng.randint(0, v, size=(16, 2)).astype(np.int32))
           for v, _, _ in specs]
    store.observe(ins)
    store.commit(store.params)
    info = store.publish(d)
    assert info["dtype"] == delta_dtype
    assert info["payload_bytes"] == info["model_payload_bytes"]
    c_emb = build()
    consumer = TableStore(c_emb, c_emb.init(jax.random.PRNGKey(seed)))
    for _, _, path in scan_published(d):
        consumer.apply_published(path)
    for a, b in zip(store.get_weights(), consumer.get_weights()):
        if delta_dtype == "f32" and not kw.get("storage_dtype"):
            np.testing.assert_array_equal(a, b)
        else:
            # one encode on publish + (for quantized-at-rest consumers)
            # one re-encode on apply: two quantization steps bound it
            bound = 2 * wire_ops.store_decode_bound(a, delta_dtype
                                                    if delta_dtype != "f32"
                                                    else kw.get(
                                                        "storage_dtype",
                                                        "f32"))
            assert (np.abs(a - b).max(axis=-1) <= bound + 1e-6).all()

    # ---- admit -> evict -> re-admit with the quantized stash
    v_emb = DistributedEmbedding(
        [Embedding(v, w, combiner=c) for v, w, c in specs],
        mesh=mesh, vocab_slack=16)
    mgr = VocabManager(v_emb, use_native=False, stash_dtype=stash_dtype)
    gtid = min(mgr.vocabs)
    mv = mgr.vocabs[gtid]
    width = v_emb.strategy.global_configs[gtid]["output_dim"]
    kcount = int(rng.randint(3, 9))
    keys = rng.randint(10_000, 20_000, size=kcount).astype(np.int64)
    keys = np.unique(keys)
    rows = rng.randn(len(keys), width).astype(np.float32)
    mv.bind(keys)
    mv.unbind(keys, rows)
    for i, k in enumerate(keys):
        back = mv.stash_take(int(k))
        assert back is not None
        if stash_dtype == "f32":
            np.testing.assert_array_equal(back, rows[i])
        else:
            bound = float(wire_ops.store_decode_bound(
                rows[i], stash_dtype).max())
            assert np.abs(back - rows[i]).max() <= bound + 1e-6


@pytest.mark.slow
def test_sparse_ids_through_distributed_forward():
    """COO SparseIds inputs through the full distributed forward — the one
    prepared-input form the named tests don't cover (reference sparse-input
    path, embedding_lookup_ops.py:90-96)."""
    import jax.numpy as jnp
    from distributed_embeddings_tpu.ops.embedding_ops import SparseIds
    from test_dist_model_parallel import BATCH

    specs = [(300, 8, "sum"), (500, 8, "mean"), (120, 8, "sum"),
             (800, 8, "sum"), (256, 8, "mean"), (640, 8, "sum"),
             (90, 8, "sum"), (410, 8, "sum")]
    rng = np.random.RandomState(11)
    inputs, max_hot = [], []
    for v, _, _ in specs:
        k = int(rng.randint(2, 5))
        rows, cols, vals = [], [], []
        for b in range(BATCH):
            nnz = int(rng.randint(1, k + 1))
            for j in range(nnz):
                rows.append(b)
                cols.append(j)
                vals.append(int(rng.randint(0, v)))
        idx = np.stack([rows, cols], axis=1).astype(np.int32)
        inputs.append(SparseIds(jnp.asarray(idx),
                                jnp.asarray(np.asarray(vals, np.int32)),
                                (BATCH, k)))
        max_hot.append(k)
    check_equivalence(specs, inputs=inputs, input_max_hotness=max_hot,
                      strategy="memory_balanced", check_train=False)


@pytest.mark.slow
def test_comm_balanced_equivalence():
    """comm_balanced placement is numerically identical to the reference
    model, hotness hints and all (mixed one-hot + multi-hot + shared)."""
    specs = [(96, 8, "sum"), (50, 8), (300, 8, "sum"), (80, 8, "mean"),
             (120, 8), (700, 8, "sum"), (60, 8), (210, 8, "sum")]
    table_map = list(range(8)) + [0, 2]
    hot = []
    rng = np.random.RandomState(5)
    import jax.numpy as jnp
    inputs = []
    for i, t in enumerate(table_map):
        v = specs[t][0]
        c = specs[t][2] if len(specs[t]) > 2 else None
        if c is None:
            inputs.append(jnp.asarray(rng.randint(0, v, size=(16,))))
            hot.append(1)
        else:
            k = 2 + (i % 4)
            inputs.append(jnp.asarray(rng.randint(0, v, size=(16, k))))
            hot.append(k)
    check_equivalence(specs, input_table_map=table_map, inputs=inputs,
                      input_max_hotness=hot, strategy="comm_balanced")


@pytest.mark.slow
def test_mp_input_mixed_forms_equivalence():
    """apply_mp (feature-sharded input) with mixed dense/ragged/weighted
    forms matches the unsharded reference — per-rank input routing plus
    every prepared-input form at once."""
    import jax
    import jax.numpy as jnp
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.ops.embedding_ops import RaggedIds
    from test_dist_model_parallel import make_mesh, ref_apply, BATCH

    specs = [(96, 8, "sum"), (50, 8, "mean"), (300, 8, "sum"), (80, 8, None),
             (120, 8, "sum"), (700, 8, "sum"), (60, 8, None), (210, 8, "sum")]
    hot = [5, 3, 4, 1, 2, 6, 1, 3]
    rng = np.random.RandomState(9)
    dist = DistributedEmbedding(
        [Embedding(v, w, combiner=c) for v, w, c in specs],
        mesh=make_mesh(), strategy="comm_balanced", dp_input=False,
        input_max_hotness=hot)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in specs]
    params = dist.set_weights(weights)

    # one global input per feature, then routed per owning rank
    flat_inputs = []
    for i, (v, w, c) in enumerate(specs):
        k = hot[i]
        if c is None:
            flat_inputs.append(jnp.asarray(
                rng.randint(0, v, size=(BATCH,)).astype(np.int32)))
        elif i % 3 == 0:
            lengths = rng.randint(1, k + 1, size=BATCH)
            values = rng.randint(0, v, size=int(lengths.sum()))
            splits = np.cumsum([0] + list(lengths))
            flat_inputs.append(RaggedIds(
                jnp.asarray(values.astype(np.int32)),
                jnp.asarray(splits.astype(np.int32))))
        else:
            ids = rng.randint(0, v, size=(BATCH, k))
            wts = (rng.rand(BATCH, k) > 0.3).astype(np.float32)
            flat_inputs.append((jnp.asarray(ids), jnp.asarray(wts)))

    mp_inputs = [
        [flat_inputs[dist.strategy.input_groups[1][pos]] for pos in rank_ids]
        for rank_ids in dist.strategy.input_ids_list]
    outs = dist.apply_mp(params, mp_inputs)

    refs = ref_apply([jnp.asarray(w) for w in weights], flat_inputs,
                     list(range(len(specs))), [c for _, _, c in specs])
    for i, (a, b) in enumerate(zip(refs, outs)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-5,
                                   atol=1e-5, err_msg=f"output {i}")


def _offload_vs_device_sparse(specs, optimizer, dedup, placement, budget,
                              seed):
    """Sparse train steps on an offloaded model must equal the same steps
    on the all-device model (same lazy rules both sides, so ALL optimizers
    incl. adam are valid here — unlike the dense-reference comparison)."""
    import jax
    import jax.numpy as jnp
    from test_sparse_train import TinyModel, BATCH
    from distributed_embeddings_tpu.training import make_sparse_train_step
    from distributed_embeddings_tpu.parallel.mesh import create_mesh

    rng = np.random.RandomState(seed)
    mesh = create_mesh(jax.devices()[:8])
    weights = [rng.randn(s[0], s[1]).astype(np.float32) * 0.1 for s in specs]
    head = rng.randn(sum(s[1] for s in specs), 1).astype(np.float32)
    results = []
    for off in (False, True):
        model = TinyModel(specs, mesh, strategy=placement,
                          gpu_embedding_size=(budget if off else None))
        if off and not any(b.offload
                           for b in model.embedding.plan.tp_buckets):
            pytest.skip("budget did not offload anything")
        init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.05,
                                                  strategy=dedup)
        params = {"embedding": model.embedding.set_weights(weights),
                  "head": {"w": jnp.asarray(head)}}
        state = init_fn(params)
        r2 = np.random.RandomState(seed + 1)
        losses = []
        for _ in range(3):
            cats = [jnp.asarray(r2.randint(0, v, size=(BATCH, 2)))
                    for v, _, _ in specs]
            labels = jnp.asarray(r2.randn(BATCH).astype(np.float32))
            params, state, loss = step_fn(params, state,
                                          jnp.zeros((BATCH, 1)), cats,
                                          labels)
            losses.append(float(loss))
        results.append((losses,
                        model.embedding.get_weights(params["embedding"])))
    (l_dev, w_dev), (l_off, w_off) = results
    np.testing.assert_allclose(l_off, l_dev, rtol=1e-5, atol=1e-6)
    for t, (a, b) in enumerate(zip(w_dev, w_off)):
        np.testing.assert_allclose(b, a, rtol=2e-5, atol=2e-5,
                                   err_msg=f"table {t} ({optimizer})")


@pytest.mark.slow
@pytest.mark.parametrize("optimizer", ["sgd", "adagrad", "adam"])
@pytest.mark.parametrize("ragged", [False, True])
@pytest.mark.parametrize("weighted", [False, True])
def test_sparse_train_wire_axis(optimizer, ragged, weighted, monkeypatch):
    """Wire-dtype axis over the sparse training path (ISSUE 5): the bf16
    exchange wire must match the f32 wire within the documented
    tolerance across every optimizer x exchange-path x weightedness
    combination, and the f32 wire must match the seam-less default
    BIT-exactly. (adam is compared bf16-vs-f32 wire, both lazy — the
    dense-reference caveat of run_equivalence does not apply here.)"""
    import jax
    import jax.numpy as jnp
    from test_sparse_train import TinyModel, BATCH
    from distributed_embeddings_tpu.training import make_sparse_train_step
    from distributed_embeddings_tpu.parallel.mesh import create_mesh

    monkeypatch.setenv("DET_RAGGED_EXCHANGE", "1" if ragged else "0")
    specs = [(96, 8, "sum"), (50, 8, "sum"), (70, 8, "mean"),
             (300, 8, "sum"), (64, 8, "sum"), (120, 8, "sum"),
             (80, 8, "sum"), (45, 8, "sum")]
    rng = np.random.RandomState(31)
    mesh = create_mesh(jax.devices()[:8])
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in specs]
    head = rng.randn(sum(w for _, w, _ in specs), 1).astype(np.float32)
    batches = []
    r2 = np.random.RandomState(32)
    for _ in range(2):
        cats = []
        for v, _, _ in specs:
            ids = jnp.asarray(r2.randint(0, v, size=(BATCH, 3)))
            if weighted:
                cats.append((ids, jnp.asarray(
                    np.abs(r2.rand(BATCH, 3)).astype(np.float32))))
            else:
                cats.append(ids)
        batches.append((cats, jnp.asarray(r2.randn(BATCH)
                                          .astype(np.float32))))

    def run(wire):
        kw = {"input_max_hotness": [3] * len(specs)}
        if wire is not None:
            kw["exchange_wire"] = wire
        model = TinyModel(specs, mesh, **kw)
        init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.1)
        params = {"embedding": model.embedding.set_weights(weights),
                  "head": {"w": jnp.asarray(head)}}
        state = init_fn(params)
        losses = []
        for cats, labels in batches:
            params, state, loss = step_fn(params, state,
                                          jnp.zeros((BATCH, 1)), cats,
                                          labels)
            losses.append(float(loss))
        return losses, model.embedding.get_weights(params["embedding"])

    l_def, w_def = run(None)
    l_f32, w_f32 = run("f32")
    assert l_f32 == l_def
    for t, (a, b) in enumerate(zip(w_def, w_f32)):
        assert (a == b).all(), f"table {t} ({optimizer})"
    l_bf, w_bf = run("bf16")
    np.testing.assert_allclose(l_bf, l_f32, rtol=2e-2, atol=2e-2)
    for t, (a, b) in enumerate(zip(w_f32, w_bf)):
        np.testing.assert_allclose(b, a, rtol=3e-2, atol=3e-3,
                                   err_msg=f"table {t} ({optimizer})")


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(10))
def test_random_sparse_train_equivalence(seed):
    """Randomized sparse TRAINING equivalence: optimizer x dedup strategy x
    placement x host-offload corners (the named cases in test_sparse_train /
    test_offload walk fixed configs; this walks random ones). Two modes:

      * no offload: sparse path vs dense optax — sgd/adagrad only (the
        rules that match dense EXACTLY on any id stream; lazy adam equals
        dense adam only under full row coverage, pinned by
        test_sparse_train_adam_full_coverage);
      * offload: sparse-offload vs sparse-device — all three optimizers
        (same lazy rules both sides), covering the round-3 host-adam rule.
    """
    from test_sparse_train import run_equivalence

    rng = np.random.RandomState(3000 + seed)
    n = int(rng.randint(5, 9))
    specs = []
    for _ in range(n):
        vocab = int(rng.choice([30, 90, 400, 1500, 4000]))
        width = int(rng.choice([4, 8, 16]))
        combiner = ["sum", "mean"][rng.randint(2)]
        specs.append((vocab, width, combiner))
    # scatter_impl axis (ISSUE 12): the fused pallas strategy rides the
    # sweep next to the XLA aggregation strategies — every random corner
    # that holds for 'sort' must hold for the deduped-row tile walk too
    dedup = ["sort", "dense", "auto", "pallas"][rng.randint(4)]
    placement = ["memory_balanced", "comm_balanced", "basic"][rng.randint(3)]
    offload = rng.rand() < 0.5
    try:
        if offload:
            optimizer = ["sgd", "adagrad", "adam"][rng.randint(3)]
            total = sum(s[0] * s[1] for s in specs)
            # gpu_embedding_size is a PER-DEVICE element budget: a third
            # of the fair per-rank share forces the biggest buckets out
            _offload_vs_device_sparse(specs, optimizer, dedup, placement,
                                      budget=total // 24, seed=seed)
        else:
            optimizer = ["sgd", "adagrad"][rng.randint(2)]
            kw = {"placement": placement}
            if rng.rand() < 0.4:
                kw["data_parallel_threshold"] = 256
            run_equivalence(specs, optimizer, strategy=dedup, seed=seed,
                            **kw)
    except ValueError as e:
        if "Not enough tables" in str(e):
            pytest.skip(f"seed {seed}: config unplaceable on 8 devices")
        raise
