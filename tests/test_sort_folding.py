"""Sort folding (ISSUE 2): folded vs unfolded tapped steps are bit-exact,
and the compiled tapped step carries at most one sort op per
(bucket, hotness) exchange group.

The fold threads the forward's canonical id sort through
TapResiduals.tp_sort/row_sort into the sparse update (dedup_sum /
sparse_sgd-adagrad-adam / the tiled kernels), mirroring the reference CUDA
backward's reuse of forward-sorted ids (embedding_lookup_kernels.cu:706-773).
Because the folded and fresh sorts run the identical lax.sort_key_val over
identical canonical keys, every downstream value is the same ARRAY — the
parity assertions here are exact equality, not allclose.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.training import make_sparse_train_step

BATCH = 16


class _TapModel:
    def __init__(self, specs, mesh, **kw):
        self.embedding = DistributedEmbedding(
            [Embedding(v, w, combiner=(s[2] if len(s) > 2 else None))
             for s, (v, w) in zip(specs, [(s[0], s[1]) for s in specs])],
            mesh=mesh, **kw)

    def loss_fn(self, params, numerical, cats, labels, taps=None,
                return_residuals=False):
        out = self.embedding(params["embedding"], list(cats), taps=taps,
                             return_residuals=return_residuals)
        outs, res = out if return_residuals else (out, None)
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                            axis=1).astype(jnp.float32)
        loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
        return (loss, res) if return_residuals else loss


SPECS = [(40, 4, "sum"), (60, 8, "sum"), (30, 4, "sum"), (50, 8, "sum"),
         (25, 4, "sum"), (70, 8, "sum"), (45, 4, "sum"), (35, 8, "sum")]


def _run(optimizer, strategy, fold, specs=SPECS, steps=2, seed=0, **kw):
    rng = np.random.RandomState(seed)
    mesh = create_mesh(jax.devices()[:8])
    model = _TapModel(specs, mesh, **kw)
    weights = [rng.randn(s[0], s[1]).astype(np.float32) * 0.1 for s in specs]
    params = {"embedding": model.embedding.set_weights(weights)}
    init_fn, step_fn = make_sparse_train_step(
        model, optimizer, lr=0.05, strategy=strategy, fold_sort=fold)
    state = init_fn(params)
    losses = []
    data = np.random.RandomState(7)
    for _ in range(steps):
        cats = [jnp.asarray(data.randint(0, s[0], size=(BATCH, 2)))
                for s in specs]
        labels = jnp.asarray(data.randn(BATCH).astype(np.float32))
        params, state, loss = step_fn(params, state, jnp.zeros((BATCH, 1)),
                                      cats, labels)
        losses.append(float(loss))
    return losses, model.embedding.get_weights(params["embedding"])


def _assert_bitexact(optimizer, strategy, **kw):
    lf, wf = _run(optimizer, strategy, True, **kw)
    lu, wu = _run(optimizer, strategy, False, **kw)
    assert lf == lu, f"losses diverged: {lf} vs {lu}"
    for t, (a, b) in enumerate(zip(wf, wu)):
        np.testing.assert_array_equal(
            a, b, err_msg=f"table {t} ({optimizer}/{strategy})")


@pytest.mark.parametrize("strategy", ["sort", "tiled"])
def test_fold_parity_adagrad(strategy):
    _assert_bitexact("adagrad", strategy)


# execution-bound on the single-core CPU test host: the remaining
# optimizer x strategy combos run in the `-m slow` tier
@pytest.mark.slow
@pytest.mark.parametrize("optimizer", ["sgd", "adam"])
@pytest.mark.parametrize("strategy", ["sort", "tiled"])
def test_fold_parity_optimizers(optimizer, strategy):
    _assert_bitexact(optimizer, strategy)


def test_fold_parity_row_slice():
    """Row-sliced tables fold too (single-input tables; the sentinel-masked
    id stream is sorted once in the forward)."""
    specs = [(512, 8, "sum"), (40, 8, "sum"), (300, 8, "mean"),
             (64, 8, "sum"), (128, 8, "sum"), (96, 8, "sum"),
             (80, 8, "sum"), (72, 8, "sum")]
    _assert_bitexact("adagrad", "sort", specs=specs, row_slice_threshold=2000)


def test_fold_off_without_scope():
    """residual_sort defaults keep the change strictly additive: a tapped
    forward OUTSIDE residual_sort_scope produces no sort artifacts, and
    sparse_update accepts such residuals unchanged."""
    mesh = create_mesh(jax.devices()[:8])
    model = _TapModel(SPECS, mesh)
    rng = np.random.RandomState(3)
    weights = [rng.randn(s[0], s[1]).astype(np.float32) * 0.1 for s in SPECS]
    params = model.embedding.set_weights(weights)
    cats = [jnp.asarray(rng.randint(0, s[0], size=(BATCH, 2)))
            for s in SPECS]
    _, res = model.embedding(params, cats, return_residuals=True)
    assert res.tp_sort is not None and all(s is None for s in res.tp_sort)
    with model.embedding.residual_sort_scope(("adagrad", "sort")):
        _, res2 = model.embedding(params, cats, return_residuals=True)
    assert any(s is not None for s in res2.tp_sort)
    for s in res2.tp_sort:
        if s is not None:
            assert s.sid.dtype == jnp.int32 and s.seg_start.dtype == bool


def _lower_sorts(strategy, fold, lookup_path=None, optimizer="adagrad",
                 monkeypatch=None):
    from tests import conftest  # noqa: F401 - platform already forced
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "det_hlo_audit", os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools",
            "hlo_audit.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.audit_tapped_step(strategy=strategy, fold=fold,
                                 lookup_path=lookup_path,
                                 optimizer=optimizer)


@pytest.mark.parametrize("strategy", ["sort", "tiled"])
def test_tapped_step_hlo_one_sort_per_group(strategy):
    """Acceptance gate: the compiled tapped step (default forward) carries
    <= 1 sort op per exchange group for both the 'sort' (XLA dedup) and
    'tiled' (Pallas kernel) aggregation strategies. Companion to
    test_tiled_step_hlo_scatter_free."""
    rec = _lower_sorts(strategy, fold=True)
    assert rec["hlo_sort"] <= rec["n_exchange_groups"], rec


def test_tapped_step_hlo_tiled_forward_two_sorts():
    """With the tiled forward gather active (DET_LOOKUP_PATH=tiled) the
    folded step carries exactly the forward sort + its inverse-permute
    sort (2 per group, down from 3 unfolded): the unpermute's second sort
    is irreducible without reintroducing a scatter (the round-3
    ~100 ns/row lowering the tiled family exists to avoid)."""
    folded = _lower_sorts("tiled", fold=True, lookup_path="tiled")
    unfolded = _lower_sorts("tiled", fold=False, lookup_path="tiled")
    assert folded["hlo_sort"] <= 2 * folded["n_exchange_groups"], folded
    assert unfolded["hlo_sort"] >= folded["hlo_sort"] + 1, (folded, unfolded)
