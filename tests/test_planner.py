"""Planner unit tests — pure python, no devices (SURVEY.md §7 step 2)."""

import pytest

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.parallel.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.parallel.plan import lower_strategy


def tables(*dims):
    return [Embedding(v, w) for v, w in dims]


def test_table_groups_thresholds():
    embs = tables((10, 4), (100, 4), (10000, 4))
    s = DistEmbeddingStrategy(embs, 4, data_parallel_threshold=50,
                              row_slice_threshold=10000)
    assert s.table_groups == [[0], [1], [2]]


def test_no_thresholds_all_col():
    embs = tables((10, 4), (100, 4), (10000, 4))
    s = DistEmbeddingStrategy(embs, 4)
    assert s.table_groups == [[], [0, 1, 2], []]


def test_column_slice_pow2():
    s = DistEmbeddingStrategy(tables((8, 8)), 8, column_slice_threshold=16)
    # 64 elements, threshold 16 -> 4 slices of width 2
    widths = [cfg["output_dim"] for rank in s.local_preconcat_configs
              for cfg in rank]
    assert sorted(widths) == [2, 2, 2, 2]


def test_column_slice_remainder():
    s = DistEmbeddingStrategy(tables((4, 7)), 4, column_slice_threshold=7)
    widths = [cfg["output_dim"] for rank in s.local_preconcat_configs
              for cfg in rank]
    assert sorted(widths) == [1, 2, 2, 2]


def test_column_slice_capped_by_width():
    # table of width 2 can't be split into more than 2 slices
    s = DistEmbeddingStrategy(tables((1000, 2)), 8, column_slice_threshold=10)
    widths = [cfg["output_dim"] for rank in s.local_preconcat_configs
              for cfg in rank]
    assert sorted(widths) == [1, 1]


def test_auto_slice_fewer_tables_than_workers():
    # 2 tables, 4 workers: every worker must get at least one slice
    s = DistEmbeddingStrategy(tables((64, 8), (64, 8)), 4)
    assert all(len(r) >= 1 for r in s.local_preconcat_configs)


def test_merge_slices_same_rank():
    # 4 slices, 2 workers -> 2 slices per worker, re-merged into 1 config each
    s = DistEmbeddingStrategy(tables((8, 8)), 2, column_slice_threshold=16)
    for rank_cfgs in s.local_preconcat_configs:
        assert len(rank_cfgs) == 1
        assert rank_cfgs[0]["output_dim"] == 4


def test_basic_round_robin():
    s = DistEmbeddingStrategy(tables((10, 4), (11, 4), (12, 4), (13, 4)), 2,
                              strategy="basic")
    assert s.table_ids[0] == [0, 2]
    assert s.table_ids[1] == [1, 3]


def test_memory_balanced_even_counts():
    embs = tables((10, 4), (20, 4), (30, 4), (40, 4), (50, 4), (60, 4),
                  (70, 4), (80, 4))
    s = DistEmbeddingStrategy(embs, 4, strategy="memory_balanced")
    counts = [len(ids) for ids in s.table_ids]
    assert counts == [2, 2, 2, 2]
    sizes = [sum(embs[t].input_dim * embs[t].output_dim for t in ids)
             for ids in s.table_ids]
    assert max(sizes) - min(sizes) <= 120  # paired largest+smallest


def test_memory_optimized_all_assigned():
    embs = tables((10, 4), (200, 4), (30, 4), (400, 4), (55, 4))
    s = DistEmbeddingStrategy(embs, 2, strategy="memory_optimized")
    assigned = sorted(t for ids in s.table_ids for t in ids)
    assert assigned == [0, 1, 2, 3, 4]


def test_concat_fusion_same_width():
    embs = tables((10, 4), (20, 4), (30, 4), (40, 4))
    s = DistEmbeddingStrategy(embs, 2, strategy="basic")
    # rank 0 gets tables 0, 2 (both width 4) -> fused into one config
    assert len(s.local_configs[0]) == 1
    assert s.local_configs[0][0]["input_dim"] == 40
    assert s.local_input_offsets[0] == [0, 10]


def test_concat_no_fusion_across_widths():
    embs = [Embedding(10, 4), Embedding(20, 8), Embedding(30, 8),
            Embedding(40, 8)]
    s = DistEmbeddingStrategy(embs, 2, strategy="basic")
    # rank 0 gets tables 0 (w4) and 2 (w8): different widths, no fusion
    assert len(s.local_configs[0]) == 2


def test_offload_flags_largest():
    embs = tables((10, 4), (1000, 4), (20, 4))
    s = DistEmbeddingStrategy(embs, 1, gpu_embedding_size=200)
    flags = {cfg["input_dim"]: cfg["cpu_offload"]
             for cfg in s.local_preconcat_configs[0]}
    assert flags[1000] is True
    assert flags[10] is False and flags[20] is False


def test_row_slice_configs():
    embs = tables((103, 4))
    s = DistEmbeddingStrategy(embs, 4, row_slice_threshold=100)
    assert s.table_groups[2] == [0]
    rows = [s.row_sliced_configs[r][0]["input_dim"] for r in range(4)]
    assert rows == [26, 26, 26, 25]
    offs = [s.row_inputs_offsets[r][0] for r in range(4)]
    assert offs == [0, -26, -52, -78]


def test_shared_tables_input_map():
    embs = tables((10, 4), (20, 4))
    s = DistEmbeddingStrategy(embs, 2, input_table_map=[0, 1, 0])
    assert s.map_groups[1] == [0, 1, 0]
    plan = lower_strategy(s)
    # input 0 and 2 both hit table 0: two slots somewhere
    assert len(plan.tp_input_slots[0]) == 1
    assert len(plan.tp_input_slots[2]) == 1


def test_rev_group_ids_restore_order():
    embs = tables((10, 4), (1000, 4), (100000, 4))
    s = DistEmbeddingStrategy(embs, 2, data_parallel_threshold=50,
                              row_slice_threshold=100000)
    flat = s.input_groups[0] + s.input_groups[1] + s.input_groups[2]
    restored = [flat[idx] for idx in s.rev_group_ids]
    assert restored == [0, 1, 2]


def test_lowered_plan_placements_cover_tables():
    embs = tables((64, 8), (32, 8), (16, 4))
    s = DistEmbeddingStrategy(embs, 4, column_slice_threshold=128)
    plan = lower_strategy(s)
    for t, emb in enumerate(embs):
        places = [p for p in plan.tp_placements if p.table_id == t]
        assert sum((p.col_end - p.col_start) * 1 for p in places) >= 0
        total_cols = sorted((p.col_start, p.col_end) for p in places)
        # col ranges tile [0, width) without gaps
        assert total_cols[0][0] == 0
        assert total_cols[-1][1] == emb.output_dim
        for (a, b), (c, d) in zip(total_cols, total_cols[1:]):
            assert b == c
        for p in places:
            assert p.rows == emb.input_dim


def test_world1_single_rank():
    embs = tables((10, 4), (20, 4))
    s = DistEmbeddingStrategy(embs, 1, strategy="memory_balanced")
    assert s.strategy == "basic"
    assert len(s.table_ids) == 1
    assert sorted(s.table_ids[0]) == [0, 1]


def test_column_slice_merge_no_dup_table_per_rank():
    # slices of one table landing on the same rank are re-merged, so no rank
    # holds the same table twice (reference test_column_slice_merge :412-424)
    embs = tables((1000, 16), (10, 4), (10, 4), (10, 4))
    s = DistEmbeddingStrategy(embs, 2, column_slice_threshold=1000)
    for rank_ids in s.table_ids:
        assert len(rank_ids) == len(set(rank_ids))


def test_auto_concat_fuses_same_width_tables():
    # 8 same-width tables over 2 ranks -> exactly 1 fused table per rank
    # (reference test_8table_width2_auto_concat :449-459)
    embs = tables(*[(100 + i, 2) for i in range(8)])
    s = DistEmbeddingStrategy(embs, 2, strategy="basic")
    for rank_configs in s.local_configs:
        assert len(rank_configs) == 1
    plan = lower_strategy(s)
    assert len(plan.tp_buckets) == 1
    assert plan.tp_buckets[0].rows == [
        sum(100 + i for i in range(0, 8, 2)),
        sum(100 + i for i in range(1, 8, 2))]


def test_offload_tables_not_fused_with_resident():
    embs = tables((1000, 8), (900, 8), (10, 8), (20, 8))
    s = DistEmbeddingStrategy(embs, 2, gpu_embedding_size=500)
    plan = lower_strategy(s)
    offloads = {b.offload for b in plan.tp_buckets}
    assert offloads == {True, False}


def test_comm_balanced_placement_complete():
    from distributed_embeddings_tpu.layers.embedding import Embedding
    specs = [(96, 8), (50, 8), (100, 16), (120, 8), (40, 16), (70, 8),
             (60, 8), (81, 8), (44, 8)]
    s = DistEmbeddingStrategy([Embedding(v, w) for v, w in specs], 8,
                              "comm_balanced",
                              input_hotness=[1, 5, 1, 5, 1, 1, 5, 1, 1])
    placed = sorted(t for ids in s.table_ids for t in ids)
    assert placed == list(range(9))
    assert all(s.local_configs[r] for r in range(8))


def test_comm_balanced_reduces_exchange_volume():
    """On the synthetic 'small' config at 8 ranks the comm_balanced
    strategy exchanges strictly less padded volume than memory_balanced
    (measured 1.47x vs 2.64x of ideal). Pure planning — no arrays built."""
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.models.synthetic import (
        SYNTHETIC_MODELS, expand_embedding_configs)
    from distributed_embeddings_tpu.parallel.plan import lower_strategy

    world = 8
    specs, tmap, hot = expand_embedding_configs(SYNTHETIC_MODELS["small"])
    total = sum(v * w for v, w in specs)

    def volume(strategy):
        s = DistEmbeddingStrategy(
            [Embedding(v, w, combiner="sum") for v, w in specs],
            world, strategy, input_table_map=tmap,
            column_slice_threshold=total // world, input_hotness=hot)
        plan = lower_strategy(s)
        k_of_tp = {pos: hot[s.input_groups[1][pos]]
                   for pos in range(len(s.input_groups[1]))}
        vol = 0
        for bucket in plan.tp_buckets:
            per_k = {}
            for r, slots in enumerate(bucket.slots):
                for sl in slots:
                    per_k.setdefault(k_of_tp[sl.tp_input],
                                     [0] * world)[r] += 1
            vol += sum(world * max(counts) * k
                       for k, counts in per_k.items())
        return vol

    v_mem, v_comm = volume("memory_balanced"), volume("comm_balanced")
    assert v_comm < v_mem, (v_comm, v_mem)
