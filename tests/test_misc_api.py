"""Coverage for parity-surface pieces not exercised elsewhere:
ConcatOneHotEmbedding (reference embedding.py:173-198), the training API
shims, staging helpers, initializers, and the DLRM LR schedule."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.layers.embedding import ConcatOneHotEmbedding
from distributed_embeddings_tpu.models.dlrm import (dlrm_initializer,
                                                    make_lr_schedule)
from distributed_embeddings_tpu.ops.embedding_ops import read_var_no_copy
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.parallel.staging import stage_replicated
from distributed_embeddings_tpu.training import (
    BroadcastGlobalVariablesCallback, DistributedGradientTape,
    broadcast_variables)
from distributed_embeddings_tpu.utils.initializers import get_initializer


def test_concat_one_hot_embedding_matches_separate_tables():
    sizes = [7, 13, 5]
    width = 4
    layer = ConcatOneHotEmbedding(sizes, width)
    params = layer.init(jax.random.PRNGKey(0))
    assert params["params"].shape == (sum(sizes), width)

    rng = np.random.RandomState(0)
    ids = np.stack([rng.randint(0, v, size=6) for v in sizes], axis=1)
    out = layer(params, jnp.asarray(ids))
    assert out.shape == (6, len(sizes), width)

    # manual per-table lookup against the fused table's offset ranges
    offs = np.concatenate([[0], np.cumsum(sizes)])
    table = np.asarray(params["params"])
    for f, v in enumerate(sizes):
        sub = table[offs[f]:offs[f + 1]]
        np.testing.assert_allclose(np.asarray(out[:, f, :]), sub[ids[:, f]])

    # single fused gather is differentiable end to end
    g = jax.grad(lambda p: jnp.sum(layer(p, jnp.asarray(ids)) ** 2))(params)
    assert g["params"].shape == table.shape


def test_concat_one_hot_grad_routes_to_correct_rows():
    layer = ConcatOneHotEmbedding([3, 3], 2)
    params = {"params": jnp.ones((6, 2))}
    ids = jnp.asarray([[1, 2]])
    g = jax.grad(lambda p: jnp.sum(layer(p, ids)))(params)["params"]
    expect = np.zeros((6, 2))
    expect[1] = 1.0       # table 0 row 1
    expect[3 + 2] = 1.0   # table 1 row 2 at offset 3
    np.testing.assert_allclose(np.asarray(g), expect)


def test_training_shims_single_process():
    params = {"w": jnp.arange(4.0)}
    assert broadcast_variables(params) is params
    cb = BroadcastGlobalVariablesCallback()
    assert cb.on_train_begin(params) is params
    # second call is a no-op too
    assert cb.on_train_begin(params) is params
    with pytest.raises(NotImplementedError):
        BroadcastGlobalVariablesCallback(root_rank=1)

    tape = DistributedGradientTape()
    loss, grads = tape.gradient(lambda p: jnp.sum(p["w"] ** 2), params)
    assert float(loss) == float(jnp.sum(params["w"] ** 2))
    np.testing.assert_allclose(np.asarray(grads["w"]),
                               2 * np.arange(4.0))


def test_read_var_no_copy_identity():
    x = jnp.ones((3, 2))
    assert read_var_no_copy(x) is x


def test_stage_replicated():
    mesh = create_mesh(jax.devices()[:8])
    tree = {"a": np.arange(6.0).reshape(2, 3)}
    out = stage_replicated(mesh, tree)
    assert out["a"].sharding.is_fully_replicated
    np.testing.assert_allclose(np.asarray(out["a"]), tree["a"])


def test_dlrm_initializer_range():
    init = dlrm_initializer()
    w = init(jax.random.PRNGKey(0), (100, 8))
    bound = 1.0 / np.sqrt(100)
    assert float(jnp.max(jnp.abs(w))) <= bound
    assert float(jnp.std(w)) > 0.3 * bound  # actually uniform, not zeros


def test_make_lr_schedule_phases():
    sched = make_lr_schedule(2.0, warmup_steps=10, decay_start_step=20,
                             decay_steps=10, poly_power=2)
    # warmup is linear from 1/10 to 1
    np.testing.assert_allclose(float(sched(0)), 2.0 * (1 - 10 / 10), atol=1e-6)
    np.testing.assert_allclose(float(sched(5)), 2.0 * 0.5, atol=1e-6)
    # constant plateau
    np.testing.assert_allclose(float(sched(15)), 2.0, atol=1e-6)
    # poly-2 decay hits zero at decay end and stays there
    np.testing.assert_allclose(float(sched(25)), 2.0 * 0.25, atol=1e-6)
    np.testing.assert_allclose(float(sched(30)), 0.0, atol=1e-6)
    np.testing.assert_allclose(float(sched(40)), 0.0, atol=1e-6)


@pytest.mark.parametrize("spec", ["uniform", "zeros",
                                  {"class_name": "RandomUniform",
                                   "config": {"minval": -0.5,
                                              "maxval": 0.5}}])
def test_get_initializer_specs(spec):
    init = get_initializer(spec)
    w = init(jax.random.PRNGKey(1), (16, 4), jnp.float32)
    assert w.shape == (16, 4)
    if spec == "zeros":
        np.testing.assert_allclose(np.asarray(w), 0.0)


def test_set_weights_error_paths():
    """Analogue of the reference's set_weight error test (:461): wrong
    weight count / shape fail loudly, not silently."""
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.layers.embedding import Embedding

    dist = DistributedEmbedding([Embedding(10, 4), Embedding(20, 4)],
                                mesh=create_mesh(jax.devices()[:8]))
    with pytest.raises(ValueError, match="Expected 2 weights"):
        dist.set_weights([np.zeros((10, 4), np.float32)])
    with pytest.raises(ValueError, match="shape"):
        dist.set_weights([np.zeros((10, 4), np.float32),
                          np.zeros((21, 4), np.float32)])


def test_prefetch_to_device_order_and_content():
    from distributed_embeddings_tpu.utils.prefetch import prefetch_to_device

    batches = [{"x": np.full((2, 2), i, np.float32)} for i in range(5)]
    out = list(prefetch_to_device(iter(batches), size=2))
    assert len(out) == 5
    for i, b in enumerate(out):
        assert isinstance(b["x"], jax.Array)
        np.testing.assert_allclose(np.asarray(b["x"]), i)
    # fewer batches than queue depth
    out = list(prefetch_to_device(iter(batches[:1]), size=3))
    assert len(out) == 1


def test_top_level_api_matches_reference():
    """Every name the reference exports at package top level
    (reference distributed_embeddings/__init__.py:17-27) must exist here."""
    import distributed_embeddings_tpu as d

    for name in ["embedding_lookup", "Embedding", "IntegerLookup",
                 "dist_model_parallel", "DistEmbeddingStrategy",
                 "DistributedEmbedding", "broadcast_variables",
                 "DistributedGradientTape", "DistributedOptimizer",
                 "BroadcastGlobalVariablesCallback", "__version__"]:
        assert hasattr(d, name), name


def test_gather_global_chunked_device_bucket(monkeypatch):
    """ADVICE r5: the chunked gather must take the jit-sliced path on a
    DEVICE bucket too (eager indexing of non-fully-addressable arrays is
    backend-dependent). Force chunk < rows and check exact reassembly."""
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.layers.embedding import Embedding

    mesh = create_mesh(jax.devices()[:8])
    dist = DistributedEmbedding([Embedding(640, 8), Embedding(320, 8)],
                                mesh=mesh)
    params = dist.init(jax.random.PRNGKey(0))
    arr = params["tp"][0]                       # [8, rows, 8] device bucket
    world, rows, tail = arr.shape
    assert rows > 3
    # chunk = GATHER_CHUNK_ELEMS // (world * tail) -> rows // 3 (< rows)
    monkeypatch.setattr(DistributedEmbedding, "GATHER_CHUNK_ELEMS",
                        world * tail * (rows // 3))
    out = dist._gather_global_chunked(arr)
    np.testing.assert_array_equal(out, np.asarray(arr))


def test_donation_cache_guard_skips_donated_modules(tmp_path):
    """The conftest-installed persistent-cache guard (compat.py: jaxlib
    0.4.36 XLA:CPU mis-executes cache-LOADED donated executables) must
    keep donated modules out of the cache while undonated ones still
    cache. Functional check against a throwaway cache dir."""
    from jax._src import compilation_cache
    from distributed_embeddings_tpu import compat

    assert compat.install_cpu_donation_cache_guard()

    cache_dir = str(tmp_path / "jaxcache")
    cfg = jax.config
    old_dir = cfg.jax_compilation_cache_dir
    old_min_time = cfg.jax_persistent_cache_min_compile_time_secs
    try:
        cfg.update("jax_compilation_cache_dir", cache_dir)
        cfg.update("jax_persistent_cache_min_compile_time_secs", 0)
        # the cache object binds its directory on first use; rebind it
        # to the throwaway dir for the duration of this test
        compilation_cache.reset_cache()

        import os
        os.makedirs(cache_dir, exist_ok=True)  # nothing may cache at all
        donated = jax.jit(lambda a, b: (a * 2 + b, b + 1),
                          donate_argnums=(0,))
        donated(jnp.arange(1024, dtype=jnp.float32),
                jnp.ones(1024, jnp.float32))
        entries = {e.split("-")[0] for e in os.listdir(cache_dir)
                   if e.endswith("-cache")}
        assert "jit__lambda_" not in entries, entries

        undonated = jax.jit(lambda a, b: (a * 3 - b, b - 1))
        undonated(jnp.arange(1024, dtype=jnp.float32),
                  jnp.ones(1024, jnp.float32))
        entries = {e.split("-")[0] for e in os.listdir(cache_dir)
                   if e.endswith("-cache")}
        assert "jit__lambda_" in entries, entries
    finally:
        cfg.update("jax_compilation_cache_dir", old_dir)
        cfg.update("jax_persistent_cache_min_compile_time_secs",
                   old_min_time)
        compilation_cache.reset_cache()
