"""measured_default: bench-written hardware defaults for DET_* knobs.

bench.py persists winning A/B knob values to tools/measured_defaults.json
(decision rule 5, docs/perf_model.md); the dispatch reads them as the
TPU-backend default. Env always overrides; CPU backends never consult the
file (test equivalence must not change because a TPU bench ran)."""

import json

import jax
import pytest

from distributed_embeddings_tpu.ops import sparse_update


@pytest.fixture
def defaults_file(tmp_path, monkeypatch):
    path = tmp_path / "measured_defaults.json"
    path.write_text(json.dumps({
        "DET_SCATTER_IMPL": {"value": "tiled", "git_sha": "abc",
                             "measured_at": "2026-07-31T00:00:00Z"},
        "DET_DEDUP_IMPL": "cumsum",          # bare-string form accepted
    }))
    monkeypatch.setenv("DET_MEASURED_DEFAULTS_PATH", str(path))
    monkeypatch.setattr(sparse_update, "_MEASURED_DEFAULTS", None)
    yield path
    monkeypatch.setattr(sparse_update, "_MEASURED_DEFAULTS", None)


def test_env_overrides_file(defaults_file, monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("DET_SCATTER_IMPL", "xla")
    assert sparse_update.measured_default("DET_SCATTER_IMPL", "xla") == "xla"


def test_file_used_on_tpu_backend(defaults_file, monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("DET_SCATTER_IMPL", raising=False)
    assert sparse_update.measured_default("DET_SCATTER_IMPL",
                                          "xla") == "tiled"
    assert sparse_update.measured_default("DET_DEDUP_IMPL",
                                          "sort") == "cumsum"
    # unknown knob falls back
    assert sparse_update.measured_default("DET_LOOKUP_PATH",
                                          "auto") == "auto"


def test_cpu_backend_ignores_file(defaults_file, monkeypatch):
    monkeypatch.delenv("DET_SCATTER_IMPL", raising=False)
    assert jax.default_backend() == "cpu"
    assert sparse_update.measured_default("DET_SCATTER_IMPL", "xla") == "xla"


def test_missing_file_falls_back(tmp_path, monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("DET_MEASURED_DEFAULTS_PATH",
                       str(tmp_path / "nope.json"))
    monkeypatch.setattr(sparse_update, "_MEASURED_DEFAULTS", None)
    monkeypatch.delenv("DET_SCATTER_IMPL", raising=False)
    assert sparse_update.measured_default("DET_SCATTER_IMPL", "xla") == "xla"
    monkeypatch.setattr(sparse_update, "_MEASURED_DEFAULTS", None)


def _load_bench():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "det_bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_bench_writer_round_trip(tmp_path, monkeypatch):
    """bench._maybe_write_measured_defaults with agreeing winners on BOTH
    workloads writes the file the library reads back; anything less flips
    nothing."""
    bench = _load_bench()
    out = tmp_path / "measured_defaults.json"
    monkeypatch.setattr(bench, "_MEASURED_DEFAULTS_PATH", str(out))

    class _FakeDev:
        platform = "tpu"

    monkeypatch.setattr(bench.jax, "devices", lambda: [_FakeDev()])
    record = {"tiny_best_path": "tiled-fwd+bwd",
              "dlrm_best_path": "tiled-fwd+bwd",
              "git_sha": "deadbeef", "value": 90.0,
              "dlrm_samples_per_sec": 2.6e6}
    bench._maybe_write_measured_defaults(record)
    assert record["measured_defaults_written"] == {
        "DET_SCATTER_IMPL": "tiled", "DET_LOOKUP_PATH": "tiled"}
    data = json.loads(out.read_text())
    assert data["DET_SCATTER_IMPL"]["value"] == "tiled"
    assert data["DET_LOOKUP_PATH"]["value"] == "tiled"
    assert data["DET_SCATTER_IMPL"]["git_sha"] == "deadbeef"

    # disagreeing winners flip nothing
    record2 = {"tiny_best_path": "default(xla)",
               "dlrm_best_path": "tiled-onehot-matmul", "git_sha": "x"}
    bench._maybe_write_measured_defaults(record2)
    assert "measured_defaults_written" not in record2

    # a MISSING workload (dlrm errored) must not weaken the rule to
    # single-workload agreement
    record3 = {"tiny_best_path": "tiled-onehot-matmul", "git_sha": "x"}
    bench._maybe_write_measured_defaults(record3)
    assert "measured_defaults_written" not in record3

    # cumsum wall-clock wins never auto-flip numerics defaults
    record4 = {"tiny_best_path": "xla+cumsum-dedup",
               "dlrm_best_path": "cumsum", "git_sha": "x"}
    bench._maybe_write_measured_defaults(record4)
    assert "measured_defaults_written" not in record4


def test_bench_isolation_pins_reader(monkeypatch):
    """_isolate_from_measured_defaults points the in-process reader at an
    unparsable path and drops the cache, so the bench's baseline arms can
    never be contaminated by an earlier flip."""
    import os
    bench = _load_bench()
    monkeypatch.setenv("DET_MEASURED_DEFAULTS_PATH", "/tmp/whatever.json")
    bench._isolate_from_measured_defaults()
    assert os.environ["DET_MEASURED_DEFAULTS_PATH"] == os.devnull
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("DET_SCATTER_IMPL", raising=False)
    assert sparse_update.measured_default("DET_SCATTER_IMPL", "xla") == "xla"
    monkeypatch.setattr(sparse_update, "_MEASURED_DEFAULTS", None)
