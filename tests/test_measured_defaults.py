"""measured_default: bench-written hardware defaults for DET_* knobs.

bench.py persists winning A/B knob values to tools/measured_defaults.json
(decision rule 5, docs/perf_model.md); the dispatch reads them as the
TPU-backend default. Env always overrides; CPU backends never consult the
file (test equivalence must not change because a TPU bench ran).

Since ISSUE 18 `measured_default` delegates to `tune.resolve.knob_value`
(env > tuned config > measured defaults > fallback) — the tests here
cover the measured-defaults layer and the bench writer; the tuned layer
is tests/test_tune.py's."""

import json

import jax
import pytest

from distributed_embeddings_tpu.ops import sparse_update
from distributed_embeddings_tpu.tune import resolve as tune_resolve


@pytest.fixture
def defaults_file(tmp_path, monkeypatch):
    path = tmp_path / "measured_defaults.json"
    path.write_text(json.dumps({
        "DET_SCATTER_IMPL": {"value": "tiled", "git_sha": "abc",
                             "measured_at": "2026-07-31T00:00:00Z"},
        "DET_DEDUP_IMPL": "cumsum",          # bare-string form accepted
    }))
    monkeypatch.setenv("DET_MEASURED_DEFAULTS_PATH", str(path))
    monkeypatch.delenv("DET_TUNED_PATH", raising=False)
    monkeypatch.delenv("DET_TUNED_WORKLOAD", raising=False)
    tune_resolve.reset_cache()
    yield path
    tune_resolve.reset_cache()


def test_env_overrides_file(defaults_file, monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("DET_SCATTER_IMPL", "xla")
    assert sparse_update.measured_default("DET_SCATTER_IMPL", "xla") == "xla"


def test_file_used_on_tpu_backend(defaults_file, monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("DET_SCATTER_IMPL", raising=False)
    assert sparse_update.measured_default("DET_SCATTER_IMPL",
                                          "xla") == "tiled"
    assert sparse_update.measured_default("DET_DEDUP_IMPL",
                                          "sort") == "cumsum"
    # unknown knob falls back
    assert sparse_update.measured_default("DET_LOOKUP_PATH",
                                          "auto") == "auto"


def test_cpu_backend_ignores_file(defaults_file, monkeypatch):
    monkeypatch.delenv("DET_SCATTER_IMPL", raising=False)
    assert jax.default_backend() == "cpu"
    assert sparse_update.measured_default("DET_SCATTER_IMPL", "xla") == "xla"


def test_missing_file_falls_back(tmp_path, monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("DET_MEASURED_DEFAULTS_PATH",
                       str(tmp_path / "nope.json"))
    tune_resolve.reset_cache()
    monkeypatch.delenv("DET_SCATTER_IMPL", raising=False)
    assert sparse_update.measured_default("DET_SCATTER_IMPL", "xla") == "xla"
    tune_resolve.reset_cache()


def _load_bench():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "det_bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def _winning_record(**overrides):
    """A record where the tiled family wins BOTH workloads with > 3%
    margin on every arm (the flip-eligible shape)."""
    rec = {"tiny_best_path": "tiled-fwd+bwd",
           "dlrm_best_path": "tiled-fwd+bwd",
           "git_sha": "deadbeef", "value": 90.0,
           "dlrm_samples_per_sec": 2.6e6,
           "tiny_ab_default_ms": 100.0, "tiny_ab_cumsum_ms": 101.0,
           "tiny_ab_tiled_ms": 95.0, "tiny_ab_tiled_full_ms": 90.0,
           "dlrm_ab_sort_ms": 50.0, "dlrm_ab_dense_ms": 52.0,
           "dlrm_ab_tiled_ms": 47.0, "dlrm_ab_tiled_full_ms": 45.0}
    rec.update(overrides)
    return rec


def test_bench_writer_round_trip(tmp_path, monkeypatch):
    """bench._maybe_write_measured_defaults with agreeing winners on BOTH
    workloads AND a >= 3% margin on each writes the file the library reads
    back; anything less flips nothing."""
    bench = _load_bench()
    out = tmp_path / "measured_defaults.json"
    monkeypatch.setattr(bench, "_MEASURED_DEFAULTS_PATH", str(out))

    class _FakeDev:
        platform = "tpu"

    monkeypatch.setattr(bench.jax, "devices", lambda: [_FakeDev()])
    record = _winning_record()
    bench._maybe_write_measured_defaults(record)
    assert record["measured_defaults_written"] == {
        "DET_SCATTER_IMPL": "tiled", "DET_LOOKUP_PATH": "tiled"}
    data = json.loads(out.read_text())
    assert data["DET_SCATTER_IMPL"]["value"] == "tiled"
    assert data["DET_LOOKUP_PATH"]["value"] == "tiled"
    assert data["DET_SCATTER_IMPL"]["git_sha"] == "deadbeef"
    # ADVICE r5: the margin is part of the evidence block
    margins = data["DET_SCATTER_IMPL"]["evidence"]["margins"]
    assert margins["tiny_scatter"] == pytest.approx(100 / 90, abs=1e-3)
    assert margins["dlrm_lookup"] == pytest.approx(50 / 45, abs=1e-3)
    assert data["DET_SCATTER_IMPL"]["evidence"][
        "min_margin_required"] == bench.MEASURED_DEFAULTS_MIN_MARGIN

    # disagreeing winners flip nothing
    record2 = {"tiny_best_path": "default(xla)",
               "dlrm_best_path": "tiled-onehot-matmul", "git_sha": "x"}
    bench._maybe_write_measured_defaults(record2)
    assert "measured_defaults_written" not in record2

    # a MISSING workload (dlrm errored) must not weaken the rule to
    # single-workload agreement
    record3 = {"tiny_best_path": "tiled-onehot-matmul", "git_sha": "x"}
    bench._maybe_write_measured_defaults(record3)
    assert "measured_defaults_written" not in record3

    # cumsum wall-clock wins never auto-flip numerics defaults
    record4 = {"tiny_best_path": "xla+cumsum-dedup",
               "dlrm_best_path": "cumsum", "git_sha": "x"}
    bench._maybe_write_measured_defaults(record4)
    assert "measured_defaults_written" not in record4


def test_bench_writer_requires_margin(tmp_path, monkeypatch):
    """ADVICE r5: a within-noise win (< 3% on either workload) or missing
    arm timings must not persist a defaults flip."""
    bench = _load_bench()
    out = tmp_path / "measured_defaults.json"
    monkeypatch.setattr(bench, "_MEASURED_DEFAULTS_PATH", str(out))

    class _FakeDev:
        platform = "tpu"

    monkeypatch.setattr(bench.jax, "devices", lambda: [_FakeDev()])

    # 1.001x "win" on dlrm: no flip at all
    rec = _winning_record(dlrm_ab_tiled_ms=49.96, dlrm_ab_tiled_full_ms=49.95)
    bench._maybe_write_measured_defaults(rec)
    assert "measured_defaults_written" not in rec
    assert not out.exists()

    # scatter margin clears on both, but the fwd+bwd arm is within noise on
    # tiny: only DET_SCATTER_IMPL flips
    rec = _winning_record(tiny_ab_tiled_full_ms=98.0, tiny_ab_tiled_ms=90.0)
    bench._maybe_write_measured_defaults(rec)
    assert rec["measured_defaults_written"] == {"DET_SCATTER_IMPL": "tiled"}

    # winner labels without the arm timings (older cached record shape):
    # margins cannot be computed -> no flip
    rec = {"tiny_best_path": "tiled-fwd+bwd",
           "dlrm_best_path": "tiled-fwd+bwd", "git_sha": "x"}
    bench._maybe_write_measured_defaults(rec)
    assert "measured_defaults_written" not in rec


def test_bench_isolation_pins_reader(tmp_path, monkeypatch):
    """_isolate_from_measured_defaults points the in-process reader at an
    unparsable path, drops BOTH tuned selectors and resets the resolve
    caches, so the bench's baseline arms can never be contaminated by an
    earlier flip — measured-defaults OR a prior --mode tune record
    (ISSUE 18)."""
    import os
    bench = _load_bench()
    monkeypatch.setenv("DET_MEASURED_DEFAULTS_PATH", "/tmp/whatever.json")
    tuned = tmp_path / "tuned.json"
    tuned.write_text("{}")
    monkeypatch.setenv("DET_TUNED_PATH", str(tuned))
    monkeypatch.setenv("DET_TUNED_WORKLOAD", "dlrm")
    bench._isolate_from_measured_defaults()
    assert os.environ["DET_MEASURED_DEFAULTS_PATH"] == os.devnull
    assert "DET_TUNED_PATH" not in os.environ
    assert "DET_TUNED_WORKLOAD" not in os.environ
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("DET_SCATTER_IMPL", raising=False)
    assert sparse_update.measured_default("DET_SCATTER_IMPL", "xla") == "xla"
    tune_resolve.reset_cache()
