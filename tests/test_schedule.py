"""Lookahead execution engine (ISSUE 9): parity, patching, compile
stability, overlap structure, refusals.

The contract under test: `schedule.LookaheadEngine` at lookahead=0 IS
the monolithic `make_sparse_train_step` (delegation), and at lookahead=1
is BIT-exact against it — the prefetched activations are patched for the
previous step's touched rows before the dense stage consumes them —
across optimizers and both exchange wire paths, with a constant compile
count and no extra sort ops.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.parallel.staging import DoubleBufferSlots
from distributed_embeddings_tpu.schedule import (LookaheadEngine,
                                                 default_lookahead)
from distributed_embeddings_tpu.training import fit, make_sparse_train_step

BATCH = 16
SPECS = [(60, 8, "sum"), (40, 8, "sum"), (500, 16, "mean"), (120, 8, "sum")]


class TinyModel:
    """Embeddings -> concat -> linear head (a real dot for the dense
    stage to overlap against)."""

    def __init__(self, mesh, specs=SPECS, **kw):
        self.specs = specs
        self.embedding = DistributedEmbedding(
            [Embedding(v, w, combiner=c) for v, w, c in specs],
            mesh=mesh, **kw)

    def loss_fn(self, params, numerical, cats, labels, taps=None,
                return_residuals=False):
        if taps is not None or return_residuals:
            outs, res = self.embedding(params["embedding"], list(cats),
                                       taps=taps, return_residuals=True)
        else:
            outs = self.embedding(params["embedding"], list(cats))
            res = None
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                            axis=1).astype(jnp.float32)
        out = x @ params["head"]["w"]
        loss = jnp.mean((out[:, 0] - labels.reshape(-1)) ** 2)
        return (loss, res) if return_residuals else loss


def _build(mesh, specs=SPECS, seed=0, **kw):
    from jax.sharding import NamedSharding, PartitionSpec as P
    rng = np.random.RandomState(seed)
    model = TinyModel(mesh, specs=specs, **kw)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1
               for v, w, _ in specs]
    head = rng.randn(sum(w for _, w, _ in specs), 1).astype(np.float32)
    # the dense head enters REPLICATED: an uncommitted single-device
    # array would re-specialize the step once its first output comes
    # back replicated (true of the monolithic step too)
    head = jax.device_put(jnp.asarray(head), NamedSharding(mesh, P()))
    params = {"embedding": model.embedding.set_weights(weights),
              "head": {"w": head}}
    return model, params, weights


def _batches(steps, specs=SPECS, seed=1):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        cats = [jnp.asarray(rng.randint(0, v, size=(BATCH, 2)))
                for v, w, c in specs]
        out.append((jnp.zeros((BATCH, 1)),
                    cats,
                    jnp.asarray(rng.randn(BATCH).astype(np.float32))))
    return out


def run_parity(optimizer, steps=5, patch_capacity=BATCH, stale_ok=False,
               specs=SPECS, **engine_kw):
    """Monolithic vs engine from identical init/data; returns
    (mono_losses, eng_losses, engine) with final weights compared
    bit-exactly when stale_ok is False."""
    mesh = create_mesh(jax.devices()[:8])
    model, params, _ = _build(mesh, specs=specs)
    batches = _batches(steps, specs=specs)

    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.05,
                                              donate=False)
    p, s = params, init_fn(params)
    mono = []
    for num, cats, labels in batches:
        p, s, loss = step_fn(p, s, num, list(cats), labels)
        mono.append(float(loss))

    model2, params2, _ = _build(mesh, specs=specs)
    eng = LookaheadEngine(model2, optimizer, lr=0.05, donate=False,
                          patch_capacity=patch_capacity,
                          stale_ok=stale_ok, **engine_kw)
    p2, s2 = params2, eng.init(params2)
    got = []
    for i, b in enumerate(batches):
        nxt = batches[i + 1] if i + 1 < steps else None
        p2, s2, loss = eng.step(p2, s2, b, nxt)
        got.append(float(loss))

    if not stale_ok:
        assert mono == got, f"{optimizer}: loss trace diverged"
        w1 = model.embedding.get_weights(p["embedding"])
        w2 = model2.embedding.get_weights(p2["embedding"])
        for t, (a, b) in enumerate(zip(w1, w2)):
            np.testing.assert_array_equal(a, b, err_msg=f"table {t}")
        np.testing.assert_array_equal(np.asarray(p["head"]["w"]),
                                      np.asarray(p2["head"]["w"]))
    return mono, got, eng


# ---------------------------------------------------------------- parity
def test_lookahead_bitexact_adagrad_padded():
    _, _, eng = run_parity("adagrad")
    # tiny vocab: nearly every prefetched sample touches a just-updated
    # row — the patch path itself must have run, not just the fallback
    assert eng.stats["patched_steps"] > 0


def test_lookahead_bitexact_sgd_ragged(monkeypatch):
    monkeypatch.setenv("DET_RAGGED_EXCHANGE", "1")
    _, _, eng = run_parity("sgd")
    assert eng.stats["patched_steps"] > 0


@pytest.mark.slow
@pytest.mark.parametrize("optimizer", ["sgd", "adagrad", "adam"])
@pytest.mark.parametrize("ragged", [False, True])
def test_lookahead_bitexact_matrix(optimizer, ragged, monkeypatch):
    monkeypatch.setenv("DET_RAGGED_EXCHANGE", "1" if ragged else "0")
    run_parity(optimizer)


def test_lookahead_bitexact_adam_padded():
    run_parity("adam")


@pytest.mark.slow
def test_lookahead_bitexact_scheduled_lr():
    """A schedule callable threads the step count through opt_state; the
    engine's drain stage must rebuild the per-step sparse optimizer at
    the same count the monolithic step would."""
    sched = lambda step: 0.1 / (1.0 + jnp.asarray(step, jnp.float32))
    mesh = create_mesh(jax.devices()[:8])
    model, params, _ = _build(mesh)
    batches = _batches(4)
    init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=sched,
                                              donate=False)
    p, s = params, init_fn(params)
    mono = []
    for num, cats, labels in batches:
        p, s, loss = step_fn(p, s, num, list(cats), labels)
        mono.append(float(loss))
    model2, params2, _ = _build(mesh)
    eng = LookaheadEngine(model2, "adagrad", lr=sched, donate=False,
                          patch_capacity=BATCH)
    p2, s2 = params2, eng.init(params2)
    got = []
    for i, b in enumerate(batches):
        p2, s2, loss = eng.step(p2, s2, b,
                                batches[i + 1] if i + 1 < 4 else None)
        got.append(float(loss))
    assert mono == got
    w1 = model.embedding.get_weights(p["embedding"])
    w2 = model2.embedding.get_weights(p2["embedding"])
    for t, (a, b) in enumerate(zip(w1, w2)):
        np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_patch_overflow_fallback_bitexact():
    """A patch capacity smaller than the stale set per step forces the
    full-reprefetch fallback — still bit-exact, zero extra compiles."""
    _, _, eng = run_parity("adagrad", patch_capacity=8)
    assert eng.stats["patch_overflows"] > 0
    assert eng.compile_counts() == {"prefetch": 1, "fused": 1}


def test_stale_ok_runs_and_diverges_boundedly():
    """stale_ok skips the patch: losses stay finite and close, but the
    bit-exact contract is explicitly forfeited (documented semantics)."""
    mono, got, eng = run_parity("adagrad", stale_ok=True)
    assert all(np.isfinite(got))
    assert eng.stats["patched_steps"] == 0
    dev = np.max(np.abs(np.asarray(mono) - np.asarray(got)))
    assert dev < 1.0, f"one-step staleness blew up: {dev}"


def test_lookahead_zero_delegates_to_monolithic():
    mesh = create_mesh(jax.devices()[:8])
    model, params, _ = _build(mesh)
    batches = _batches(3)
    init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=0.05,
                                              donate=False)
    p, s = params, init_fn(params)
    model2, params2, _ = _build(mesh)
    eng = LookaheadEngine(model2, "adagrad", lr=0.05, lookahead=0,
                          donate=False)
    p2, s2 = params2, eng.init(params2)
    for i, (num, cats, labels) in enumerate(batches):
        p, s, l1 = step_fn(p, s, num, list(cats), labels)
        p2, s2, l2 = eng.step(p2, s2, batches[i],
                              batches[i + 1] if i + 1 < 3 else None)
        assert float(l1) == float(l2)


# ------------------------------------------------------ compile stability
def test_compile_count_stable():
    """ONE compile per stage per (plan, batch-shape), regardless of how
    many steps run or how often the patch/fallback paths alternate."""
    mesh = create_mesh(jax.devices()[:8])
    model, params, _ = _build(mesh)
    eng = LookaheadEngine(model, "adagrad", lr=0.05, donate=False,
                          patch_capacity=BATCH)
    s = eng.init(params)
    p = params
    batches = _batches(6)
    for i, b in enumerate(batches):
        p, s, _ = eng.step(p, s, b, batches[i + 1] if i + 1 < 6 else None)
    first = eng.compile_counts()
    assert first == {"prefetch": 1, "fused": 1}, first
    more = _batches(6, seed=7)
    for i, b in enumerate(more):
        p, s, _ = eng.step(p, s, b, more[i + 1] if i + 1 < 6 else None)
    assert eng.compile_counts() == first, "recompiled under steady state"


def test_pipeline_reset_and_cold_restart():
    """reset() flushes the carry; the next step cold-fills from the
    current tables and stays correct."""
    mesh = create_mesh(jax.devices()[:8])
    model, params, _ = _build(mesh)
    batches = _batches(4)
    init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=0.05,
                                              donate=False)
    p, s = params, init_fn(params)
    mono = []
    for num, cats, labels in batches:
        p, s, loss = step_fn(p, s, num, list(cats), labels)
        mono.append(float(loss))
    model2, params2, _ = _build(mesh)
    eng = LookaheadEngine(model2, "adagrad", lr=0.05, donate=False,
                          patch_capacity=BATCH)
    p2, s2 = params2, eng.init(params2)
    got = []
    for i, b in enumerate(batches):
        if i == 2:
            eng.reset()      # mid-run flush: forces a cold re-fill
        nxt = batches[i + 1] if i + 1 < 4 else None
        p2, s2, loss = eng.step(p2, s2, b, nxt)
        got.append(float(loss))
    assert mono == got
    assert eng.stats["cold_fills"] >= 2


# ------------------------------------------------------------ fit wiring
def _fit_pair(lookahead, **fit_kw):
    mesh = create_mesh(jax.devices()[:8])
    model, params, _ = _build(mesh)
    batches = _batches(6, seed=3)
    p, s, hist = fit(model, params, iter(batches), steps=6,
                     optimizer="adagrad", lr=0.05, log_every=0,
                     lookahead=lookahead, **fit_kw)
    return hist["loss"], hist


def test_fit_lookahead_matches_sequential():
    base, _ = _fit_pair(0)
    ahead, hist = _fit_pair(1)
    assert base == ahead
    st = hist["lookahead_stats"]
    assert st["steps"] == 6 and st["cold_fills"] >= 1


def test_fit_lookahead_env_default(monkeypatch):
    monkeypatch.setenv("DET_LOOKAHEAD", "1")
    assert default_lookahead() == 1
    losses, hist = _fit_pair(None)      # None -> DET_LOOKAHEAD
    assert "lookahead_stats" in hist
    monkeypatch.setenv("DET_LOOKAHEAD", "7")
    with pytest.raises(ValueError, match="DET_LOOKAHEAD"):
        default_lookahead()


# -------------------------------------------------------------- refusals
def test_refuses_hot_rows():
    mesh = create_mesh(jax.devices()[:8])
    model = TinyModel(mesh, hot_rows=8)
    with pytest.raises(NotImplementedError, match="hot-row"):
        LookaheadEngine(model, "adagrad", lr=0.05)


def test_refuses_depth_beyond_one():
    mesh = create_mesh(jax.devices()[:8])
    model = TinyModel(mesh)
    with pytest.raises(ValueError, match="lookahead"):
        LookaheadEngine(model, "adagrad", lookahead=2)


def test_refuses_all_dp_plan():
    mesh = create_mesh(jax.devices()[:8])
    model = TinyModel(mesh, specs=[(32, 8, "sum"), (16, 8, "sum")],
                      data_parallel_threshold=10_000)
    with pytest.raises(ValueError, match="nothing to prefetch"):
        LookaheadEngine(model, "adagrad", lr=0.05)


def test_refuses_ragged_input_form():
    from distributed_embeddings_tpu.ops.embedding_ops import RaggedIds
    mesh = create_mesh(jax.devices()[:8])
    model, params, _ = _build(mesh)
    eng = LookaheadEngine(model, "adagrad", lr=0.05, donate=False)
    s = eng.init(params)
    num, cats, labels = _batches(1)[0]
    ragged = RaggedIds(jnp.arange(BATCH, dtype=jnp.int32),
                       jnp.arange(BATCH + 1, dtype=jnp.int32))
    bad = (num, [ragged] + cats[1:], labels)
    with pytest.raises(NotImplementedError, match="dense id inputs"):
        eng.step(params, s, bad, None)


def test_fit_refuses_vocab_rebinds_and_hot_and_dense():
    mesh = create_mesh(jax.devices()[:8])
    model, params, _ = _build(mesh)
    batches = _batches(2)

    class _FakeVocab:     # fit's guard fires before any vocab use
        emb = model.embedding

    with pytest.raises(NotImplementedError, match="vocab_every"):
        fit(model, params, iter(batches), steps=2, lookahead=1,
            vocab=_FakeVocab(), vocab_every=4, log_every=0)
    with pytest.raises(NotImplementedError, match="hot-row"):
        fit(model, params, iter(batches), steps=2, lookahead=1,
            hot_sync_every=2, log_every=0)
    with pytest.raises(ValueError, match="sparse"):
        fit(model, params, iter(batches), steps=2, lookahead=1,
            sparse=False, log_every=0)


# --------------------------------------------------- structure / overlap
def test_hlo_collective_overlap_unit():
    """The dependency classifier on a hand-written module: one collective
    feeding a dot (serialized), one collective fed by a dot (serialized),
    one free-floating (candidate), helpers reached via call."""
    from distributed_embeddings_tpu.utils.profiling import (
        hlo_collective_overlap)
    text = """
module @m {
  func.func public @main(%arg0: tensor<8xf32>, %arg1: tensor<8xf32>) -> tensor<8xf32> {
    %0 = "stablehlo.all_to_all"(%arg0) : (tensor<8xf32>) -> tensor<8xf32>
    %1 = stablehlo.dot_general %0, %arg1, contracting_dims = [0] x [0] : (tensor<8xf32>, tensor<8xf32>) -> tensor<8xf32>
    %2 = "stablehlo.all_gather"(%1) : (tensor<8xf32>) -> tensor<8xf32>
    %3 = call @helper(%arg1) : (tensor<8xf32>) -> tensor<8xf32>
    %4 = stablehlo.add %2, %3 : tensor<8xf32>
    return %4 : tensor<8xf32>
  }
  func.func private @helper(%arg0: tensor<8xf32>) -> tensor<8xf32> {
    %0 = "stablehlo.all_to_all"(%arg0) : (tensor<8xf32>) -> tensor<8xf32>
    return %0 : tensor<8xf32>
  }
}
"""
    ov = hlo_collective_overlap(text)
    assert ov["collectives_total"] == 3
    assert ov["overlap_candidates"] == 1
    assert ov["candidates_by_op"] == {"all_to_all": 1}
    assert ov["serialized_collectives"] == 2


def test_hlo_collective_overlap_region_conservative():
    """Collectives inside control-flow REGIONS (a scanned step's while
    body) fold into the enclosing node: a body mixing a collective with
    a dot must classify as serialized, never as an overlap candidate —
    the flat SSA graph cannot see the region's internal edges, so the
    safe answer is 'no overlap'."""
    from distributed_embeddings_tpu.utils.profiling import (
        hlo_collective_overlap)
    text = """
module @m {
  func.func public @main(%arg0: tensor<8xf32>) -> tensor<8xf32> {
    %0 = "stablehlo.while"(%arg0) ({
    ^bb0(%arg1: tensor<8xf32>):
      %1 = "stablehlo.all_to_all"(%arg1) : (tensor<8xf32>) -> tensor<8xf32>
      %2 = stablehlo.dot_general %1, %arg1, contracting_dims = [0] x [0] : (tensor<8xf32>, tensor<8xf32>) -> tensor<8xf32>
      stablehlo.return %2 : tensor<8xf32>
    }, {
    ^bb1(%arg2: tensor<8xf32>):
      stablehlo.return %arg2 : tensor<8xf32>
    }) : (tensor<8xf32>) -> tensor<8xf32>
    %3 = "stablehlo.all_gather"(%arg0) : (tensor<8xf32>) -> tensor<8xf32>
    %4 = stablehlo.add %0, %3 : tensor<8xf32>
    return %4 : tensor<8xf32>
  }
}
"""
    ov = hlo_collective_overlap(text)
    assert ov["collectives_total"] == 2
    # the while-body all_to_all shares a node with the dot -> serialized;
    # the free-floating all_gather feeds only an add -> candidate
    assert ov["overlap_candidates"] == 1
    assert ov["candidates_by_op"] == {"all_gather": 1}


def test_fused_step_overlap_audit():
    """The real gate, on the real lowering: prefetch collectives carry no
    dependency on the dense compute, the monolithic baseline audits to
    zero candidates, and the fused step adds no sort ops."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "hlo_audit", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "hlo_audit.py"))
    ha = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ha)
    rec = ha.audit_lookahead_overlap(vocab=512, width=8, tables=2,
                                     batch=16, hotness=2)
    assert "skipped" not in rec, rec
    assert rec["prefetch_collectives"] > 0
    assert (rec["fused_overlap_candidates"]
            >= rec["prefetch_collectives"]), rec
    assert rec["baseline_overlap_candidates"] == 0, rec
    assert rec["extra_sorts"] == 0, rec
    assert rec["over_bound"] is False


# -------------------------------------------------------- staging slots
def test_double_buffer_slots():
    s = DoubleBufferSlots()
    assert s.current is None and s.take() is None
    assert s.stage("a", tag=1) is None
    assert s.current == "a" and s.tag == 1
    assert s.stage("b", tag=2) is None          # "a" retired, not evicted
    assert s.stage("c", tag=3) == "a"           # now "a" falls off
    assert s.take() == "c"
    assert s.current is None
    s.clear()
    assert s.stage("d") is None and s.current == "d"
