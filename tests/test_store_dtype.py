"""Quantized row storage (ISSUE 15): the storage-dtype seam.

One codec (`ops/wire.encode_rows*`/`decode_rows*`) behind every row
store on the train-to-serve spine: cold/offloaded bucket tables (decode
at gather), `store/` delta + snapshot stream payloads (container header
dtype), and the vocab demotion stash. Contracts pinned here — the
tier-1 CI smoke of the ISSUE 15 acceptance gates:

  * f32 default bit-exact: no scale leaf, identical pytrees, identical
    forwards — `exchange_wire='f32'`'s early-return contract applied
    to memory;
  * quantized forward/training within the documented per-row bounds,
    per optimizer (the PR 5 wire-parity matrix pattern);
  * publish->consume parity: 0.0 at f32, bounded at int8/fp8; payload
    bytes reconciled EXACTLY against the shared byte model, with the
    >= 3.5x reduction gate at width 128;
  * ONE compile per (plan, batch-shape) across storage-dtype configs;
  * the storage-dtype analysis pass: quantized buffers attributable in
    a real lowering, and its blind-mutation fixture fires;
  * quantized stash: evict -> re-admit restores within one quantization
    step, ~4x more tenants under one byte budget, state round trip.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.ops import wire as wire_ops
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.training import make_sparse_train_step

from test_dist_model_parallel import make_mesh

# one big table past the per-rank budget (offloads -> quantizable) +
# seven small ones. At BUDGET every table offloads into ONE cold bucket
# (100x32 = 3200 > 3000); at MIXED_BUDGET the big table offloads and the
# small ones stay HBM-resident in a second bucket — the two-residency
# plan the ISSUE 17 lifted gate quantizes end to end.
SPECS = [(4000, 32, "sum")] + [(100 + i, 32, "sum") for i in range(7)]
BUDGET = 3000
MIXED_BUDGET = 4000
BATCH = 16

QUANT_DTYPES = ["int8"] + (["fp8"] if wire_ops.fp8_supported() else [])


def build(storage_dtype=None, specs=SPECS, budget=BUDGET, **kw):
    mesh = make_mesh(8)
    return DistributedEmbedding(
        [Embedding(v, w, combiner=c) for v, w, c in specs],
        mesh=mesh, gpu_embedding_size=budget,
        storage_dtype=storage_dtype, **kw)


def rand_weights(rng, specs=SPECS, scale=0.1):
    return [rng.randn(v, w).astype(np.float32) * scale
            for v, w, _ in specs]


def rand_inputs(rng, specs=SPECS, batch=BATCH, k=2):
    return [jnp.asarray(rng.randint(0, v, size=(batch, k))
                        .astype(np.int32)) for v, _, _ in specs]


# --------------------------------------------------------------- codec
def test_codec_roundtrip_bounds_and_f32_identity():
    rng = np.random.RandomState(0)
    x = rng.randn(64, 32).astype(np.float32)
    # f32: identity, no scale materialized (the bit-exact early return)
    p, s = wire_ops.encode_rows_np(x, "f32")
    assert s is None and p is x or np.array_equal(p, x)
    for dtype in QUANT_DTYPES:
        p, s = wire_ops.encode_rows_np(x, dtype)
        assert p.dtype.itemsize == 1 and s.shape == (64, 1)
        y = wire_ops.decode_rows_np(p, s, dtype)
        bound = wire_ops.store_decode_bound(x, dtype)
        assert (np.abs(y - x).max(axis=-1) <= bound + 1e-7).all()
        # jnp twin agrees with the numpy codec — bit-equal at int8 (both
        # RNE on an exact grid); fp8 casts may differ by one ulp between
        # XLA and ml_dtypes on ties, so parity there is the shared bound
        pj, sj = wire_ops.encode_rows(jnp.asarray(x), dtype)
        if dtype == "int8":
            assert np.array_equal(np.asarray(pj), np.asarray(p))
        yj = wire_ops.decode_rows(pj, sj, dtype)
        assert (np.abs(np.asarray(yj) - x).max(axis=-1)
                <= bound + 1e-7).all()
        # SR stays within one full grid step and is deterministic
        pj1, sj1 = wire_ops.encode_rows(jnp.asarray(x), "int8", sr=True)
        pj2, _ = wire_ops.encode_rows(jnp.asarray(x), "int8", sr=True)
        assert np.array_equal(np.asarray(pj1), np.asarray(pj2))
        ysr = wire_ops.decode_rows(pj1, sj1, "int8")
        bsr = wire_ops.store_decode_bound(x, "int8", sr=True)
        assert (np.abs(np.asarray(ysr) - x).max(axis=-1)
                <= bsr + 1e-6).all()
    # zero rows round-trip to exact zeros at every dtype
    z = np.zeros((4, 8), np.float32)
    for dtype in QUANT_DTYPES:
        p, s = wire_ops.encode_rows_np(z, dtype)
        assert (wire_ops.decode_rows_np(p, s, dtype) == 0).all()


def test_registries_and_byte_model():
    from distributed_embeddings_tpu.utils.checkpoint import (
        STREAM_PAYLOAD_DTYPES)
    # the container's dtype registry must not drift from the codec's
    assert tuple(STREAM_PAYLOAD_DTYPES) == tuple(wire_ops.STORE_DTYPES)
    # the ONE shared byte formula: f32 reproduces the historical model
    assert wire_ops.delta_row_bytes(32, "f32") == 8 + 4 * 32
    assert wire_ops.delta_row_bytes(32, "int8") == 8 + 32 + 4
    assert wire_ops.snapshot_row_bytes(128, "int8") == 128 + 4
    with pytest.raises(ValueError, match="unknown storage dtype"):
        wire_ops.resolve_store_dtype("int4")


# ----------------------------------------------------- plan eligibility
def test_plan_gate_and_f32_default(monkeypatch):
    d8 = build("int8", budget=MIXED_BUDGET)
    # ISSUE 17 lifted the offloaded-only gate: EVERY bucket quantizes —
    # cold (host-offloaded) and HBM-resident alike — so the mixed plan
    # holds both residencies in quantized form (the offloaded big table
    # plus the device-resident small-table bucket)
    assert all(bk.storage_dtype == "int8" for bk in d8.plan.tp_buckets)
    assert any(bk.offload for bk in d8.plan.tp_buckets)
    assert any(not bk.offload for bk in d8.plan.tp_buckets)
    assert all(rt.storage_dtype == "f32" for rt in d8.plan.row_tables)
    assert d8.quantized_buckets == list(range(len(d8.plan.tp_buckets)))
    # every quantized bucket gets a scale leaf, device-resident included
    p8 = d8.init(jax.random.PRNGKey(0))
    for b in d8.quantized_buckets:
        assert p8["tp"][b].dtype.itemsize == 1
        assert p8["tp_scale"][b] is not None
    # the one residual gate: a bucket with a hot shard stays f32 (hot
    # write-back moves raw rows; re-encoding on membership change would
    # re-quantize exactly the hottest rows, unbounded drift)
    dh = build("int8", budget=MIXED_BUDGET, hot_rows=32)
    assert any(bk.hot_rows > 0 for bk in dh.plan.tp_buckets)
    for bk in dh.plan.tp_buckets:
        assert bk.storage_dtype == ("f32" if bk.hot_rows > 0 else "int8")
    # default layer: no quantization anywhere, no scale leaf in params
    d32 = build(None)
    assert d32.quantized_buckets == []
    p32 = d32.init(jax.random.PRNGKey(0))
    assert "tp_scale" not in p32
    # DET_STORE_DTYPE is the env default; explicit argument wins
    monkeypatch.setenv("DET_STORE_DTYPE", "int8")
    assert build(None).quantized_buckets
    assert build("f32").quantized_buckets == []
    with pytest.raises(ValueError, match="unknown storage dtype"):
        build("int4")


def test_quantized_forward_parity_and_compile_count():
    rng = np.random.RandomState(1)
    W = rand_weights(rng)
    ins = rand_inputs(rng)
    d32 = build("f32")
    p32 = d32.set_weights(W)
    base = d32.apply(p32, ins)
    for dtype in QUANT_DTYPES:
        dq = build(dtype)
        pq = dq.set_weights(W)
        b0 = dq.quantized_buckets[0]
        assert pq["tp"][b0].dtype.itemsize == 1
        assert pq["tp_scale"][b0] is not None
        # ONE compile per (plan, batch-shape) across dtype configs: the
        # jitted forward reuses its executable on fresh same-shape data
        fwd = jax.jit(lambda p, i: dq.apply(p, list(i)))
        out = fwd(pq, ins)
        fwd(pq, rand_inputs(np.random.RandomState(2)))
        assert fwd._cache_size() == 1, \
            f"{dtype}: forward recompiled across same-shape batches"
        # decode-at-gather parity: one quantization of the big table's
        # rows, summed over hotness 2
        err = max(float(jnp.abs(a - b).max()) for a, b in zip(base, out))
        assert err < (0.01 if dtype == "int8" else 0.06), (dtype, err)


@pytest.mark.parametrize("optimizer", ["sgd", "adagrad", "adam"])
def test_train_convergence_parity_matrix(optimizer):
    """The per-optimizer convergence-bound parity matrix (the PR 5 wire
    pattern): N steps through quantized storage track the f32 run within
    documented bounds. Under the ISSUE 17 lifted gate every bucket
    quantizes, so sgd/adagrad exercise BOTH residencies at once — the
    master-weight-free HBM row update (decode touched -> f32 math ->
    hash-SR re-encode, no f32 shadow) on device buckets AND the
    touched-rows host apply on the offloaded one. adam has no
    master-weight-free rule: it must refuse LOUDLY on HBM-quantized
    buckets, and its parity leg runs on an all-offloaded plan where the
    host apply keeps f32 math end-to-end."""
    import jax.numpy as jnp

    class _M:
        def __init__(self, sd, budget=BUDGET):
            self.embedding = build(sd, budget=budget)

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            out = self.embedding(p["embedding"], list(cats), taps=taps,
                                 return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    rng = np.random.RandomState(3)
    W = rand_weights(rng)
    num = jnp.zeros((BATCH, 1), jnp.float32)
    cats = rand_inputs(rng)
    lab = jnp.asarray(rng.randn(BATCH).astype(np.float32))
    budget = MIXED_BUDGET
    if optimizer == "adam":
        # the loud refusal: HBM-resident quantized buckets under adam
        m = _M("int8", budget=MIXED_BUDGET)
        assert any(not m.embedding.plan.tp_buckets[b].offload
                   for b in m.embedding.quantized_buckets)
        init_fn, step_fn = make_sparse_train_step(m, "adam", lr=0.01,
                                                  donate=False)
        params = {"embedding": m.embedding.set_weights(W)}
        state = init_fn(params)
        with pytest.raises(NotImplementedError,
                           match="master-weight-free"):
            step_fn(params, state, num, cats, lab)
        # parity leg: a budget of 1 offloads EVERY bucket, so adam's
        # quantized path is the touched-rows host apply throughout
        budget = 1
    runs = {}
    for sd in ["f32", "int8"]:
        m = _M(sd, budget=budget)
        if optimizer == "adam" and sd == "int8":
            assert all(m.embedding.plan.tp_buckets[b].offload
                       for b in m.embedding.quantized_buckets)
        init_fn, step_fn = make_sparse_train_step(m, optimizer, lr=0.01,
                                                  donate=False)
        params = {"embedding": m.embedding.set_weights(W)}
        state = init_fn(params)
        losses = []
        for _ in range(4):
            params, state, loss = step_fn(params, state, num, cats, lab)
            losses.append(float(loss))
        runs[sd] = (losses, m.embedding.get_weights(params["embedding"]))
    # RELATIVE per-step loss deviation: the loss scale is shape-driven
    # (sum over 8 tables x hotness 2), so an absolute bar would just
    # measure the harness
    loss_dev = max(abs(a - b) / max(abs(a), 1.0) for a, b in
                   zip(runs["f32"][0], runs["int8"][0]))
    table_dev = max(float(np.abs(a - b).max())
                    for a, b in zip(runs["f32"][1], runs["int8"][1]))
    assert loss_dev < 0.02, (optimizer, runs["f32"][0], runs["int8"][0])
    assert table_dev < 0.05, (optimizer, table_dev)


# ------------------------------------------------------ stream payloads
def wide_specs(width=128):
    return [(1500, width, "sum")] + [(80 + i, width, "sum")
                                     for i in range(7)]


@pytest.mark.parametrize("dtype", ["f32"] + QUANT_DTYPES)
def test_publish_consume_parity_and_byte_model(dtype, tmp_path):
    """Quantized publish->consume round trip: f32 parity EXACTLY 0.0,
    quantized within the per-row decode bound; measured stream payload
    bytes == the shared byte model, and the >= 3.5x reduction gate at
    width 128 (the ISSUE 15 acceptance number)."""
    from distributed_embeddings_tpu.store import TableStore, scan_published
    from distributed_embeddings_tpu.utils.checkpoint import (
        load_row_delta_meta)

    specs = wide_specs()
    rng = np.random.RandomState(5)
    W = rand_weights(rng, specs)
    emb = build("f32", specs=specs)
    store = TableStore(emb, emb.set_weights(W), delta_dtype=dtype)
    d = str(tmp_path / dtype)
    snap = store.publish(d)
    ins = rand_inputs(rng, specs)
    store.observe(ins)
    store.commit(store.params)
    delta = store.publish(d)
    # header self-describes; payload reconciles exactly against the
    # shared model on both kinds
    assert load_row_delta_meta(snap["path"])["dtype"] == dtype
    assert load_row_delta_meta(delta["path"])["dtype"] == dtype
    assert snap["payload_bytes"] == snap["model_payload_bytes"]
    assert delta["payload_bytes"] == delta["model_payload_bytes"]
    c_emb = build("f32", specs=specs)
    con = TableStore(c_emb, c_emb.init(jax.random.PRNGKey(7)))
    for _, _, path in scan_published(d):
        con.apply_published(path)
    errs = [float(np.abs(a - b).max())
            for a, b in zip(store.get_weights(), con.get_weights())]
    if dtype == "f32":
        assert max(errs) == 0.0
    else:
        bounds = [float(wire_ops.store_decode_bound(w, dtype).max())
                  for w in W]
        for e, b in zip(errs, bounds):
            assert e <= b + 1e-6
        # the capacity claim, measured: delta AND snapshot payloads
        # >= 3.5x smaller than the f32 stream of the same rows
        emb2 = build("f32", specs=specs)
        st32 = TableStore(emb2, emb2.set_weights(W), delta_dtype="f32")
        d32 = str(tmp_path / "base_f32")
        snap32 = st32.publish(d32)
        st32.observe(ins)
        st32.commit(st32.params)
        delta32 = st32.publish(d32)
        assert snap32["payload_bytes"] / snap["payload_bytes"] >= 3.5
        assert delta32["payload_bytes"] / delta["payload_bytes"] >= 3.5


def test_quantized_table_storage_through_store_reads(tmp_path):
    """`read_rows` (THE versioned read) decodes quantized buckets; a
    consumed delta re-encodes into the quantized leaves and the next
    read round-trips within one extra quantization step — on BOTH
    residencies (the offloaded pinned-host bucket and an HBM-resident
    one, whose payload/scale leaves stay on device through the device
    gather/scatter path)."""
    from distributed_embeddings_tpu.store import TableStore

    rng = np.random.RandomState(11)
    W = rand_weights(rng)
    emb = build("int8", budget=MIXED_BUDGET)
    off = [b for b in emb.quantized_buckets
           if emb.plan.tp_buckets[b].offload]
    hbm = [b for b in emb.quantized_buckets
           if not emb.plan.tp_buckets[b].offload]
    assert off and hbm, "lifted gate must quantize both residencies"
    b0 = off[0]
    store = TableStore(emb, emb.set_weights(W))
    keys = np.arange(0, 64, dtype=np.int64)
    got = store.read_rows(b0, keys)
    # the placement maps bucket-b0 keys onto the big table's rows: the
    # read must match the decoded set_weights payload, i.e. within ONE
    # quantization of the original weights
    bound = float(wire_ops.store_decode_bound(W[0][:64], "int8").max())
    assert np.abs(got - W[0][:64]).max() <= bound + 1e-6
    # write through _apply_tp_rows (the delta-apply seam): values land
    # re-encoded, next read decodes them back within one more step
    new_rows = rng.randn(8, 32).astype(np.float32) * 0.1
    table, scale = store._apply_tp_rows(b0, keys[:8], new_rows)
    store._params["tp"][b0] = table
    store._params["tp_scale"][b0] = scale
    got2 = store.read_rows(b0, keys[:8])
    b2 = float(wire_ops.store_decode_bound(new_rows, "int8").max())
    assert np.abs(got2 - new_rows).max() <= b2 + 1e-6
    # HBM-resident bucket through the same seam: scatter lands i8
    # payload + f32 scale on the device leaves, the next read decodes
    bh = hbm[0]
    kh = np.arange(0, 8, dtype=np.int64)
    hr = rng.randn(8, 32).astype(np.float32) * 0.1
    table_h, scale_h = store._apply_tp_rows(bh, kh, hr)
    assert table_h.dtype.itemsize == 1
    store._params["tp"][bh] = table_h
    store._params["tp_scale"][bh] = scale_h
    got3 = store.read_rows(bh, kh)
    b3 = float(wire_ops.store_decode_bound(hr, "int8").max())
    assert np.abs(got3 - hr).max() <= b3 + 1e-6


def test_publish_consume_through_quantized_hbm_bucket(tmp_path):
    """Store round trip where producer AND consumer hold HBM-resident
    int8 buckets (ISSUE 17): the published snapshot+delta stream decodes
    from the producer's quantized leaves and re-encodes into the
    consumer's through the device scatter seam — the consumer's at-rest
    payload stays 1-byte, and its decoded weights land within ONE RNE
    quantization of the producer's decoded truth."""
    from distributed_embeddings_tpu.store import TableStore, scan_published

    rng = np.random.RandomState(23)
    W = rand_weights(rng)
    emb = build("int8", budget=MIXED_BUDGET)
    assert any(not emb.plan.tp_buckets[b].offload
               for b in emb.quantized_buckets)
    store = TableStore(emb, emb.set_weights(W))
    d = str(tmp_path / "hbm_stream")
    store.publish(d)
    ins = rand_inputs(rng)
    store.observe(ins)
    store.commit(store.params)
    store.publish(d)
    c_emb = build("int8", budget=MIXED_BUDGET)
    con = TableStore(c_emb, c_emb.init(jax.random.PRNGKey(7)))
    for _, _, path in scan_published(d):
        con.apply_published(path)
    for b in c_emb.quantized_buckets:
        assert con._params["tp"][b].dtype.itemsize == 1
        assert con._params["tp_scale"][b] is not None
    for a, c in zip(store.get_weights(), con.get_weights()):
        bound = float(wire_ops.store_decode_bound(a, "int8").max())
        assert np.abs(a - c).max() <= bound + 1e-6


def test_quantized_host_apply_moves_touched_rows_only():
    """The offloaded quantized apply is O(touched rows), not O(bucket):
    layer byte totals reconcile EXACTLY against `wire.delta_row_bytes` x
    rows applied, the rows applied over a small working set stay far
    below what whole-bucket re-encodes would move, and the
    `store/quantized_rows_applied_total` counter mirrors the layer
    total through the default registry."""
    from distributed_embeddings_tpu.obs.registry import (
        default_registry, reset_default_registry)
    from distributed_embeddings_tpu.training import make_sparse_train_step

    reset_default_registry()

    class _M:
        def __init__(self):
            self.embedding = build("int8")

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            out = self.embedding(p["embedding"], list(cats), taps=taps,
                                 return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    rng = np.random.RandomState(29)
    m = _M()
    emb = m.embedding
    off = [b for b in emb.quantized_buckets
           if emb.plan.tp_buckets[b].offload]
    assert off, "need an offloaded quantized bucket for the host apply"
    capacity = sum(sum(emb.plan.tp_buckets[b].rows) for b in off)
    init_fn, step_fn = make_sparse_train_step(m, "sgd", lr=0.01,
                                              donate=False)
    params = {"embedding": emb.set_weights(rand_weights(rng))}
    state = init_fn(params)
    num = jnp.zeros((BATCH, 1), jnp.float32)
    lab = jnp.asarray(rng.randn(BATCH).astype(np.float32))
    # a SMALL working set on the big (offloaded) table: 8 distinct ids
    cats = rand_inputs(rng)
    cats[0] = jnp.asarray(
        rng.randint(0, 8, size=(BATCH, 2)).astype(np.int32))
    steps = 3
    for _ in range(steps):
        params, state, _ = step_fn(params, state, num, cats, lab)
    rows = emb.quantized_rows_applied_total
    assert rows > 0
    # EXACT byte reconciliation through the one shared formula
    width = emb.plan.tp_buckets[off[0]].width
    assert emb.quantized_apply_bytes_total == \
        rows * wire_ops.delta_row_bytes(width, "int8")
    # O(touched): the v1 whole-bucket roundtrip re-encodes `capacity`
    # rows EVERY step; the touched-rows walk must move well under one
    # such sweep across all steps combined (the harness replicates the
    # batch per rank, so rows is ~ids x world, still << capacity)
    assert rows < (steps * capacity) // 10, (rows, capacity)
    assert default_registry().counter(
        "store/quantized_rows_applied_total").value == rows


# ----------------------------------------------------------- vocab stash
def test_quantized_stash_evict_readmit_and_byte_budget():
    from distributed_embeddings_tpu.vocab.manager import ManagedVocab

    rng = np.random.RandomState(13)
    width = 32
    rows = rng.randn(6, width).astype(np.float32)
    mv = ManagedVocab(0, capacity=64, base_rows=48, slack=16,
                      admit_threshold=2, decay=0.99, use_native=False,
                      stash_dtype="int8")
    keys = np.arange(100, 106, dtype=np.int64)
    mv.bind(keys)
    mv.unbind(keys, rows)
    # parked compressed: ~(8 + width + 4) bytes/row, not 8 + 4*width
    assert mv.stash_bytes() == 6 * (8 + width + 4)
    for i, k in enumerate(keys):
        back = mv.stash_take(int(k))
        bound = float(wire_ops.store_decode_bound(rows[i], "int8").max())
        assert np.abs(back - rows[i]).max() <= bound + 1e-7
    assert mv.stash_bytes() == 0
    # byte budget: the same budget holds ~4x more int8 tenants than f32
    budget = 10 * (8 + 4 * width)          # ten f32 rows' worth
    held = {}
    for sd in ("f32", "int8"):
        m2 = ManagedVocab(0, capacity=256, base_rows=128, slack=128,
                          admit_threshold=2, decay=0.99, use_native=False,
                          stash_dtype=sd, stash_max_bytes=budget)
        ks = np.arange(1000, 1100, dtype=np.int64)
        m2.bind(ks)
        m2.unbind(ks, rng.randn(100, width).astype(np.float32))
        assert m2.stash_bytes() <= budget
        held[sd] = len(m2.stash)
    assert held["f32"] == 10
    assert held["int8"] >= 3 * held["f32"]


def test_quantized_stash_state_roundtrip(tmp_path):
    """save_state/load_state with a quantized stash: payloads persist
    compressed (+ scale sibling), and a loader decodes with the SAVED
    dtype — including a loader configured at a different stash dtype."""
    rng = np.random.RandomState(17)
    specs = [(64, 8, "sum"), (48, 8, "sum"), (40, 8, "sum"),
             (32, 8, "sum"), (30, 8, "sum"), (28, 8, "sum"),
             (26, 8, "sum"), (24, 8, "sum")]
    from distributed_embeddings_tpu.vocab import VocabManager

    def mk(stash_dtype):
        emb = DistributedEmbedding(
            [Embedding(v, w, combiner=c) for v, w, c in specs],
            mesh=make_mesh(8), vocab_slack=8)
        return VocabManager(emb, use_native=False,
                            stash_dtype=stash_dtype)

    mgr = mk("int8")
    gtid = min(mgr.vocabs)
    mv = mgr.vocabs[gtid]
    keys = np.arange(500, 508, dtype=np.int64)
    rows = rng.randn(8, 8).astype(np.float32)
    mv.bind(keys)
    mv.unbind(keys, rows)
    path = mgr.save_state(str(tmp_path / "vocab_state"))
    from distributed_embeddings_tpu.utils.checkpoint import (
        load_row_delta_meta)
    assert load_row_delta_meta(path)["stash_dtype"] == "int8"
    for loader_dtype in ("int8", "f32"):
        m2 = mk(loader_dtype)
        m2.load_state(path)
        back = m2.vocabs[gtid].stash_take(502)
        bound = float(wire_ops.store_decode_bound(rows[2], "int8",
                                                  sr=True).max())
        assert back is not None
        assert np.abs(back - rows[2]).max() <= bound + 1e-6


# ------------------------------------------------------- analysis gate
def test_storage_dtype_pass_on_real_lowering_and_mutation():
    """The storage-dtype pass on a REAL quantized serve lowering (every
    i8 buffer attributable -> zero findings; the same program audited
    under an all-f32 declaration -> flagged), plus the checked-in blind
    mutation fixture."""
    from distributed_embeddings_tpu.analysis import ir, passes
    from distributed_embeddings_tpu.analysis import programs as programs_mod
    from distributed_embeddings_tpu.analysis.passes import PlanContext

    emb = build("int8")
    params = {"e": emb.init(jax.random.PRNGKey(0))}
    ins = rand_inputs(np.random.RandomState(19))
    text = jax.jit(
        lambda p, i: emb.apply(p["e"], list(i))).lower(params,
                                                       ins).as_text()
    mod = ir.parse_module(text)
    n_i8 = sum(1 for _, inst in mod.walk()
               for t in inst.operand_types + inst.result_types
               if t.dtype == "i8")
    assert n_i8 > 0, "quantized serve lowering carries no i8 buffer"
    ok = passes.run_passes(
        mod, PlanContext(program="q", storage_dtypes=("f32", "int8")),
        passes=["storage-dtype"])
    assert ok == []
    bad = passes.run_passes(
        mod, PlanContext(program="q", storage_dtypes=("f32",)),
        passes=["storage-dtype"])
    assert [f.fid for f in bad] == ["storage-dtype/undeclared.i8"]
    # the registered blind-mutation fixture fires through the same
    # driver path hlo_audit --assert uses
    cases = [c for c in programs_mod.mutation_cases()
             if c.pass_name == "storage-dtype"]
    assert cases, "storage-dtype pass has no mutation fixture"
    for case in cases:
        got = tuple(f.fid for f in passes.run_passes(
            ir.parse_module(case.text), case.ctx,
            passes=[case.pass_name]))
        assert got == case.expect_fids
