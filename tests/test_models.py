"""Model-level smoke + equivalence tests (DLRM, synthetic zoo)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.models.dlrm import DLRM, dot_interact
from distributed_embeddings_tpu.models.synthetic import (
    SYNTHETIC_MODELS, SyntheticModel, InputGenerator)
from distributed_embeddings_tpu.parallel.mesh import create_mesh

SIZES = [50, 60, 200, 300, 400, 500, 600, 700]


def _mesh(n=8):
    return create_mesh(jax.devices()[:n])


def test_dlrm_dp_input_forward_and_grad():
    mesh = _mesh()
    model = DLRM(table_sizes=SIZES, embedding_dim=8, bottom_mlp_dims=(16, 8),
                 top_mlp_dims=(16, 1), num_numerical_features=4, mesh=mesh)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    B = 32
    numerical = jnp.asarray(rng.rand(B, 4).astype(np.float32))
    cats = [jnp.asarray(rng.randint(0, v, (B,)).astype(np.int32))
            for v in SIZES]
    labels = jnp.asarray(rng.randint(0, 2, (B, 1)).astype(np.float32))
    loss, grads = jax.value_and_grad(model.loss_fn)(params, numerical, cats,
                                                    labels)
    assert np.isfinite(float(loss))
    assert jnp.all(jnp.isfinite(grads["top_mlp"][0]["w"]))


def test_dlrm_mp_input_forward():
    # dp_input=False: the model takes nested per-rank categorical inputs
    mesh = _mesh()
    model = DLRM(table_sizes=SIZES, embedding_dim=8, bottom_mlp_dims=(16, 8),
                 top_mlp_dims=(16, 1), num_numerical_features=4, mesh=mesh,
                 dp_input=False)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    B = 32
    numerical = jnp.asarray(rng.rand(B, 4).astype(np.float32))
    global_cats = [jnp.asarray(rng.randint(0, v, (B,)).astype(np.int32))
                   for v in SIZES]
    strat = model.embedding.strategy
    mp_cats = [[global_cats[strat.input_groups[1][pos]] for pos in rank_ids]
               for rank_ids in strat.input_ids_list]
    out = model.apply(params, numerical, mp_cats)
    assert out.shape == (B, 1)
    assert np.all(np.isfinite(np.asarray(out)))

    # must equal the dp_input model's output with identical weights
    model_dp = DLRM(table_sizes=SIZES, embedding_dim=8,
                    bottom_mlp_dims=(16, 8), top_mlp_dims=(16, 1),
                    num_numerical_features=4, mesh=mesh)
    weights = model.embedding.get_weights(params["embedding"])
    params_dp = dict(params)
    params_dp["embedding"] = model_dp.embedding.set_weights(weights)
    out_dp = model_dp.apply(params_dp, numerical, global_cats)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_dp),
                               rtol=1e-5, atol=1e-5)


def test_dot_interact_shape():
    B, F, d = 8, 5, 16
    rng = np.random.RandomState(0)
    embs = [jnp.asarray(rng.randn(B, d).astype(np.float32))
            for _ in range(F)]
    bottom = jnp.asarray(rng.randn(B, d).astype(np.float32))
    out = dot_interact(embs, bottom)
    n = F + 1
    assert out.shape == (B, n * (n - 1) // 2 + d)


def test_synthetic_tiny_step():
    cfg = SYNTHETIC_MODELS["tiny"]
    # shrink vocabs so this runs fast on CPU: replace configs with tiny rows
    small = cfg._replace(embedding_configs=[
        c._replace(num_rows=min(c.num_rows, 1000))
        for c in cfg.embedding_configs])
    mesh = _mesh()
    model = SyntheticModel(small, mesh=mesh, distributed=True)
    params = model.init(jax.random.PRNGKey(0))
    gen = InputGenerator(small, 32, alpha=1.05, num_batches=1, seed=0)
    numerical, cats, labels = gen[0]
    loss = model.loss_fn(params, numerical, cats, labels)
    assert np.isfinite(float(loss))
