"""Flight recorder, version lineage, device-time attribution and
postmortem artifacts (ISSUE 14).

The contracts under test: (a) the recorder ring stays within its
configured bound under a long synthetic run and the chrome-trace
export ALWAYS balances (orphaned ends dropped, open spans/tracks
synthetically closed) — including after eviction cut the window;
(b) `obs.span` feeds the recorder, so the exported timeline reproduces
the `span_seconds{span=}` nesting; (c) a store version's life is one
async lineage track — commit opens, publish/scan/apply ride,
the first predict at >= V closes — version-monotonic across a real
publish->poll->predict loop; (d) the attribution parser assigns every
device op to the innermost enclosing span window with the
spans+unattributed == total identity exact, measures collective
exposure, exports the `device/*` gauges, and reconciles projections;
(e) degraded-mode ENTRY dumps a postmortem artifact (ring + snapshot)
when `DET_OBS_POSTMORTEM_DIR` is set; (f) the registry export
satellites — per-line JSONL flush/fsync and Prometheus label
escaping."""

import gzip
import json
import os
import threading

import numpy as np
import jax
import pytest

from distributed_embeddings_tpu import faults, obs
from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.obs import attribution
from distributed_embeddings_tpu.obs.trace import FlightRecorder
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.serving import InferenceEngine
from distributed_embeddings_tpu.store import TableStore

SIZES = [(96, 8), (200, 8)]


def make_dist():
    mesh = create_mesh(jax.devices()[:8])
    return DistributedEmbedding([Embedding(v, w) for v, w in SIZES],
                                mesh=mesh, strategy="memory_balanced",
                                row_slice_threshold=30000)


def _weights(rng):
    return [rng.randn(v, w).astype(np.float32) * 0.1 for v, w in SIZES]


def _touched(dist, rng, n=8):
    import jax.numpy as jnp
    cats = [jnp.asarray(rng.randint(0, v, (n,)).astype(np.int32))
            for v, _ in SIZES]
    return dist.touched_row_keys(cats)


def _balance(doc):
    """Per-thread B/E depth check; returns the final depths."""
    depth = {}
    for ev in doc["traceEvents"]:
        if ev["ph"] == "B":
            depth[ev["tid"]] = depth.get(ev["tid"], 0) + 1
        elif ev["ph"] == "E":
            depth[ev["tid"]] = depth.get(ev["tid"], 0) - 1
            assert depth[ev["tid"]] >= 0, "E without a B"
    return depth


def _async_balance(doc):
    """Nestable-async b/e pairing per id; returns open ids (must be
    empty for a balanced export)."""
    open_ids = set()
    for ev in doc["traceEvents"]:
        if ev["ph"] == "b":
            assert ev["id"] not in open_ids, "double async begin"
            open_ids.add(ev["id"])
        elif ev["ph"] == "n":
            assert ev["id"] in open_ids, "async instant off-track"
        elif ev["ph"] == "e":
            assert ev["id"] in open_ids, "async end without begin"
            open_ids.discard(ev["id"])
    return open_ids


# ---------------------------------------------------------------- ring
def test_ring_bounded_under_long_run_and_export_balances(tmp_path):
    """A long synthetic span stream must hold the ring at its bound
    (no unbounded growth) and still export a balanced, loadable
    chrome trace despite the eviction cut."""
    rec = FlightRecorder(capacity=64)
    reg = obs.MetricRegistry()
    for i in range(500):
        rec.begin(f"step{i}")
        rec.instant("tick", i=i)
        rec.end(f"step{i}")
    assert len(rec.events()) == 64
    assert rec.dropped == 500 * 3 - 64
    doc = rec.to_chrome_trace()
    assert _balance(doc) == {} or all(
        v == 0 for v in _balance(doc).values())
    assert _async_balance(doc) == set()
    # a cut mid-span: begin evicted, orphan end must be dropped; open
    # begin at export must be synthetically closed
    rec2 = FlightRecorder(capacity=4)
    rec2.begin("a")
    for i in range(10):
        rec2.instant(f"x{i}")       # evicts the begin
    rec2.end("a")                   # orphan: its B left the ring
    rec2.begin("open")              # never closed before export
    doc2 = rec2.to_chrome_trace()
    assert all(v == 0 for v in _balance(doc2).values())
    names = [e["name"] for e in doc2["traceEvents"] if e["ph"] == "E"]
    assert "a" not in names and "open" in names
    # export file round-trips as plain JSON
    path = tmp_path / "t.json"
    rec2.export(str(path))
    assert json.load(open(path))["traceEvents"]
    del reg


def test_capacity_validation_and_env_default(monkeypatch):
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder(capacity=1)
    monkeypatch.setenv("DET_OBS_TRACE_EVENTS", "128")
    assert FlightRecorder().capacity == 128


# ------------------------------------------------------- span -> ring
def test_spans_feed_recorder_and_nesting_matches_histogram_paths():
    obs.reset_default_recorder()
    reg = obs.MetricRegistry()
    with obs.span("train", reg):
        with obs.span("step", reg):
            pass
        with obs.span("publish", reg):
            pass
    doc = obs.default_recorder().to_chrome_trace()
    seq = [(e["ph"], e["name"]) for e in doc["traceEvents"]
           if e["ph"] in "BE"]
    assert seq == [("B", "train"), ("B", "train/step"),
                   ("E", "train/step"), ("B", "train/publish"),
                   ("E", "train/publish"), ("E", "train")]
    # the recorded names ARE the registry's span_seconds paths
    hist_paths = {k[len("span_seconds{span="):-1]
                  for k in reg.snapshot()["histograms"]}
    assert {n for _, n in seq} == hist_paths
    assert all(v == 0 for v in _balance(doc).values())


def test_recorder_is_thread_safe_across_span_threads():
    obs.reset_default_recorder()
    reg = obs.MetricRegistry()

    def worker(i):
        for _ in range(50):
            with obs.span(f"w{i}", reg):
                pass

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    doc = obs.default_recorder().to_chrome_trace()
    assert all(v == 0 for v in _balance(doc).values())
    assert sum(1 for e in doc["traceEvents"] if e["ph"] == "B") == 200


# ------------------------------------------------------------- lineage
def test_lineage_tracks_through_publish_poll_predict(tmp_path):
    """The real seams: commit opens V's async track, publish/scan/apply
    ride it, the first predict at >= V closes it — version-monotonic
    begins, balanced pairing, later versions closed by one predict."""
    obs.reset_default_recorder()
    dist = make_dist()
    rng = np.random.RandomState(3)
    store = TableStore(dist, dist.set_weights(_weights(rng)))
    d = str(tmp_path / "pub")
    store.commit(store.params)
    store.publish(d)                               # v1 snapshot
    store.commit(store.params, touched=_touched(dist, rng))
    store.publish(d)                               # v2 delta
    eng = InferenceEngine(
        dist, dist.set_weights([np.zeros((v, w), np.float32)
                                for v, w in SIZES]))
    assert [i["version"] for i in eng.poll_updates(d)] == [1, 2]
    req = [np.zeros((4,), np.int32) for _ in SIZES]
    eng.predict(req)                               # closes v1 AND v2

    rec = obs.default_recorder()
    assert rec.lineage_versions() == [1, 2]
    assert rec.lineage_open_versions() == []       # predict closed both
    evs = [e for e in rec.to_chrome_trace()["traceEvents"]
           if e.get("cat") == "version"]
    begins = [e["id"] for e in evs if e["ph"] == "b"]
    assert begins == sorted(begins) == [1, 2]      # version-monotonic
    assert _async_balance({"traceEvents": evs}) == set()
    phases = {(e["id"], e.get("args", {}).get("phase")) for e in evs}
    for v in (1, 2):
        assert (v, "publish") in phases
        assert (v, "scan") in phases
        assert (v, "apply") in phases
    # the serve close carries the version it was answered at
    closes = [e for e in evs if e["ph"] == "e"]
    assert {e["id"] for e in closes} == {1, 2}
    # a SECOND predict at the same version must not re-close anything
    # (its serve/predict span edges still record; lineage stays quiet)
    n_lineage = sum(1 for e in rec.events() if e[4] == "version")
    eng.predict(req)
    assert sum(1 for e in rec.events() if e[4] == "version") == n_lineage


def test_lineage_rejects_unknown_phase_and_autoopens_consumer_side():
    rec = FlightRecorder(capacity=64)
    with pytest.raises(ValueError, match="phase"):
        rec.lineage(1, "observe")
    # a consumer that never saw the publisher's commit still gets a
    # track (synthetic open on first sight)
    rec.lineage(7, "apply")
    evs = rec.events()
    assert [e[0] for e in evs] == ["b", "n"]
    assert rec.lineage_versions() == [7]


# --------------------------------------------------------- attribution
def _fixture_events():
    """Synthetic chrome trace: two nested span windows on a host
    thread, device ops on a /device: process. Timings in us."""
    return [
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        # span windows (host annotations; the shape heuristic needs a
        # "/" in the path — exactly what composed span paths carry)
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 1000,
         "name": "bench/outer"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 100, "dur": 300,
         "name": "bench/outer/inner"},
        # python-tracer noise: must never become a window
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 2000,
         "name": "$runpy.py:1 run"},
        # device ops: midpoint decides the window, innermost wins
        {"ph": "X", "pid": 9, "tid": 2, "ts": 150, "dur": 100,
         "name": "fusion.1", "args": {"hlo_op": "fusion.1"}},      # inner
        {"ph": "X", "pid": 9, "tid": 2, "ts": 500, "dur": 200,
         "name": "all-to-all.2",
         "args": {"hlo_op": "all-to-all.2"}},                      # outer
        {"ph": "X", "pid": 9, "tid": 3, "ts": 550, "dur": 100,
         "name": "fusion.3", "args": {"hlo_op": "fusion.3"}},      # outer
        {"ph": "X", "pid": 9, "tid": 2, "ts": 1500, "dur": 50,
         "name": "copy.4", "args": {"hlo_op": "copy.4"}},    # outside all
    ]


def test_attribution_innermost_window_sum_identity_and_exposure():
    att = attribution.attribute_device_time(_fixture_events())
    assert att["spans"] == {"bench/outer": pytest.approx(300e-6),
                            "bench/outer/inner": pytest.approx(100e-6)}
    assert att["unattributed_seconds"] == pytest.approx(50e-6)
    assert att["total_device_seconds"] == pytest.approx(450e-6)
    total = sum(att["spans"].values()) + att["unattributed_seconds"]
    assert total == pytest.approx(att["total_device_seconds"])
    assert att["device_op_count"] == 4
    assert att["span_window_count"] == 2     # the $-frame is excluded
    # exposure: the 200us all-to-all overlaps fusion.3 on [550, 650]
    coll = att["collective"]
    assert coll["device_seconds"] == pytest.approx(200e-6)
    assert coll["overlapped_seconds"] == pytest.approx(100e-6)
    assert coll["exposed_seconds"] == pytest.approx(100e-6)
    assert coll["exposed_fraction"] == pytest.approx(0.5)
    assert coll["per_span"]["bench/outer"]["exposed_fraction"] == \
        pytest.approx(0.5)
    # single host thread: nothing is cross-thread ambiguous
    assert att["ambiguous_seconds"] == 0.0
    # explicit span set: restricting to the outer span folds inner's
    # ops into it
    att2 = attribution.attribute_device_time(
        _fixture_events(), span_paths={"bench/outer"})
    assert att2["spans"] == {"bench/outer": pytest.approx(400e-6)}


def test_attribution_flags_cross_thread_window_ambiguity():
    """Concurrent spans on DIFFERENT host threads (a serving span under
    a background trainer's window) make midpoint attribution a guess —
    the overlap region's device time must be totaled as ambiguous,
    while single-thread nesting stays unambiguous."""
    events = [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 1000,
         "name": "train/step"},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 400, "dur": 200,
         "name": "serve/predict"},            # overlaps on another thread
        {"ph": "X", "pid": 9, "tid": 5, "ts": 450, "dur": 100,
         "name": "fusion.1", "args": {"hlo_op": "fusion.1"}},  # in both
        {"ph": "X", "pid": 9, "tid": 5, "ts": 700, "dur": 100,
         "name": "fusion.2", "args": {"hlo_op": "fusion.2"}},  # train only
    ]
    att = attribution.attribute_device_time(events)
    # the contested op went to the shortest window; flagged ambiguous
    assert att["spans"]["serve/predict"] == pytest.approx(100e-6)
    assert att["spans"]["train/step"] == pytest.approx(100e-6)
    assert att["ambiguous_seconds"] == pytest.approx(100e-6)


def test_attribution_logdir_gauges_and_reconciliation(tmp_path):
    run = tmp_path / "plugins" / "profile" / "2026_01_01"
    os.makedirs(run)
    with gzip.open(run / "host.trace.json.gz", "wb") as f:
        f.write(json.dumps(
            {"traceEvents": _fixture_events()}).encode())
    reg = obs.MetricRegistry()
    # the registry's recorded span paths pin the window set
    reg.histogram("span_seconds", span="bench/outer").record(0.001)
    reg.histogram("span_seconds", span="bench/outer/inner").record(0.0003)
    att = attribution.attribute_logdir(str(tmp_path), registry=reg)
    assert att["trace_file"] == "host.trace.json.gz"
    g = reg.snapshot()["gauges"]
    assert g["device/span_seconds{span=bench/outer/inner}"] == \
        pytest.approx(100e-6)
    assert g["device/unattributed_seconds"] == pytest.approx(50e-6)
    assert g["device/total_seconds"] == pytest.approx(450e-6)
    assert g["device/exposed_exchange_fraction"] == pytest.approx(0.5)
    rows = attribution.reconciliation_table(
        att, {"bench/outer/inner": 0.1, "bench/outer": 10.0,
              "nope": 1.0})
    by = {r["phase"]: r for r in rows}
    assert by["bench/outer/inner"]["verdict"] == "settled"  # 0.1 ~ 0.1ms
    assert by["bench/outer"]["verdict"] == "falsified"      # 0.3 vs 10ms
    assert by["nope"]["verdict"] == "unmeasured"
    with pytest.raises(FileNotFoundError, match="chrome trace"):
        attribution.find_trace_file(str(tmp_path / "empty"))


# ---------------------------------------------------------- postmortem
def test_degraded_entry_dumps_postmortem_artifact(tmp_path, monkeypatch):
    """Entering a serve/degraded{reason=} state writes the incident
    artifact — ring + snapshot + context — once per reason activation;
    a healthy->degraded->healthy->degraded cycle dumps twice."""
    pm = str(tmp_path / "pm")
    monkeypatch.setenv("DET_OBS_POSTMORTEM_DIR", pm)
    obs.reset_default_recorder()
    dist = make_dist()
    rng = np.random.RandomState(5)
    reg = obs.MetricRegistry()
    store = TableStore(dist, dist.set_weights(_weights(rng)))
    d = str(tmp_path / "pub")
    store.commit(store.params)
    store.publish(d)
    eng = InferenceEngine(
        dist, dist.set_weights([np.zeros((v, w), np.float32)
                                for v, w in SIZES]), registry=reg)
    plan = faults.FaultPlan([{"point": "consumer.poll",
                              "kind": "io_error", "at": [0, 1, 3]}])
    with faults.use_plan(plan):
        eng.poll_updates(d)                  # occ 0: degraded entry #1
        assert len(eng.postmortems) == 1
        eng.poll_updates(d)                  # occ 1: STILL degraded —
        assert len(eng.postmortems) == 1     # an active reason never re-dumps
        eng.poll_updates(d)                  # occ 2: healthy, heals
        assert eng.degraded_reasons() == frozenset()
        eng.poll_updates(d)                  # occ 3: entry #2, dumps again
    assert len(eng.postmortems) == 2
    doc = json.load(open(eng.postmortems[0]))
    assert doc["reason"] == "degraded:poll_error"
    assert doc["snapshot"]["gauges"][
        "serve/degraded{reason=poll_error}"] == 1
    assert doc["extra"]["publish_dir"] == d
    assert isinstance(doc["trace"]["traceEvents"], list)
    # the ring marked the entry as an instant event too
    marks = [e for e in doc["trace"]["traceEvents"]
             if e.get("name") == "serve/degraded_entry"]
    assert marks and marks[0]["args"]["reason"] == "poll_error"
    assert reg.counter("obs/postmortems_total",
                       reason="degraded_poll_error").value == 2
    # two dumps in the same second must not collide
    assert len(set(eng.postmortems)) == 2


def test_postmortem_not_dumped_without_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("DET_OBS_POSTMORTEM_DIR", raising=False)
    dist = make_dist()
    rng = np.random.RandomState(6)
    eng = InferenceEngine(dist, dist.set_weights(_weights(rng)))
    plan = faults.FaultPlan([{"point": "consumer.poll",
                              "kind": "io_error", "at": [0]}])
    with faults.use_plan(plan):
        eng.poll_updates(str(tmp_path / "nowhere"))
    assert eng.degraded_reasons() == frozenset({"poll_error"})
    assert eng.postmortems == []


# --------------------------------------------- registry export satellites
def test_export_jsonl_flushes_per_line_and_fsyncs_final(tmp_path):
    reg = obs.MetricRegistry()
    reg.counter("n").inc()
    path = str(tmp_path / "m.jsonl")
    reg.export_jsonl(path)
    reg.export_jsonl(path, extra={"source": "final"}, fsync=True)
    lines = [json.loads(ln) for ln in open(path)]
    assert len(lines) == 2 and lines[1]["source"] == "final"


def test_prometheus_label_values_escaped():
    """The exposition-format fixture (satellite): quarantine paths and
    degraded reasons put quotes/backslashes/newlines into label values;
    each must escape per the Prometheus text-format spec."""
    reg = obs.MetricRegistry()
    reg.gauge("serve/degraded", reason='C:\\tmp\\"bad"\nfile').set(1)
    reg.counter("ok", plain="simple").inc()
    text = reg.to_prometheus()
    line = [ln for ln in text.splitlines()
            if ln.startswith("serve_degraded{")][0]
    assert line == ('serve_degraded{reason="C:\\\\tmp\\\\\\"bad\\"'
                    '\\nfile"} 1.0')
    assert "\n\n" not in text            # the newline never split a line
    assert 'plain="simple"' in text      # plain values untouched
    # every non-comment line still parses as <name>{<labels>} <value>
    import re
    for ln in text.splitlines():
        if ln.startswith("#"):
            continue
        assert re.match(r'^[a-zA-Z0-9_:]+(\{([a-zA-Z0-9_]+="(\\.|[^"\\])*")'
                        r'(,[a-zA-Z0-9_]+="(\\.|[^"\\])*")*\})? \S+$', ln), ln
