"""RawBinaryDataset: split-binary Criteo format, native prefetch path."""

import os

import numpy as np
import pytest

from distributed_embeddings_tpu.models.data import (
    DummyDataset, RawBinaryDataset, get_categorical_feature_type)

BATCH = 32
N_BATCHES = 5
N_NUM = 4
TABLE_SIZES = [100, 40000, 7]


def write_split_binary(root, n_rows, seed=0):
    rng = np.random.RandomState(seed)
    os.makedirs(os.path.join(root, "train"), exist_ok=True)
    base = os.path.join(root, "train")
    labels = rng.randint(0, 2, n_rows).astype(np.bool_)
    labels.tofile(os.path.join(base, "label.bin"))
    numerical = rng.rand(n_rows, N_NUM).astype(np.float16)
    numerical.tofile(os.path.join(base, "numerical.bin"))
    cats = []
    for i, size in enumerate(TABLE_SIZES):
        dtype = get_categorical_feature_type(size)
        c = rng.randint(0, size, n_rows).astype(dtype)
        c.tofile(os.path.join(base, f"cat_{i}.bin"))
        cats.append(c)
    return labels, numerical, cats


@pytest.mark.parametrize("use_native", [True, False])
def test_raw_binary_roundtrip(tmp_path, use_native):
    n_rows = BATCH * N_BATCHES
    labels, numerical, cats = write_split_binary(str(tmp_path), n_rows)
    ds = RawBinaryDataset(
        str(tmp_path), batch_size=BATCH, numerical_features=N_NUM,
        categorical_features=list(range(len(TABLE_SIZES))),
        categorical_feature_sizes=TABLE_SIZES,
        use_native_prefetch=use_native, prefetch_depth=3)
    assert len(ds) == N_BATCHES
    for b in range(N_BATCHES):
        num_b, cats_b, labels_b = ds[b]
        sl = slice(b * BATCH, (b + 1) * BATCH)
        np.testing.assert_allclose(
            num_b, numerical[sl].astype(np.float32), rtol=1e-3)
        np.testing.assert_array_equal(
            labels_b[:, 0], labels[sl].astype(np.float32))
        for i, c in enumerate(cats_b):
            np.testing.assert_array_equal(c, cats[i][sl].astype(np.int32))


def test_raw_binary_mp_input_reads_own_tables(tmp_path):
    # model-parallel input: this process loads only its own tables
    # (reference utils.py:260-266)
    n_rows = BATCH * N_BATCHES
    _, _, cats = write_split_binary(str(tmp_path), n_rows)
    ds = RawBinaryDataset(
        str(tmp_path), batch_size=BATCH, numerical_features=N_NUM,
        categorical_features=[2],
        categorical_feature_sizes=TABLE_SIZES,
        use_native_prefetch=False)
    _, cats_b, _ = ds[1]
    assert len(cats_b) == 1
    np.testing.assert_array_equal(cats_b[0],
                                  cats[2][BATCH:2 * BATCH].astype(np.int32))


def test_raw_binary_dp_batch_shard(tmp_path):
    n_rows = BATCH * N_BATCHES
    _, _, cats = write_split_binary(str(tmp_path), n_rows)
    ds = RawBinaryDataset(
        str(tmp_path), batch_size=BATCH, numerical_features=N_NUM,
        categorical_features=[0], categorical_feature_sizes=TABLE_SIZES,
        dp_input=True, offset=8, local_batch_size=8,
        use_native_prefetch=False)
    _, cats_b, labels_b = ds[0]
    assert labels_b.shape == (8, 1)
    np.testing.assert_array_equal(cats_b[0],
                                  cats[0][8:16].astype(np.int32))


@pytest.mark.parametrize("use_native", [True, False])
def test_read_raw_preprocess_split(tmp_path, use_native):
    # the ingestion-pipeline seam: __getitem__ == preprocess(read_raw(idx)),
    # and raw_batches() + preprocess reproduce indexed iteration exactly
    n_rows = BATCH * N_BATCHES
    write_split_binary(str(tmp_path), n_rows)

    def make_ds():
        return RawBinaryDataset(
            str(tmp_path), batch_size=BATCH, numerical_features=N_NUM,
            categorical_features=list(range(len(TABLE_SIZES))),
            categorical_feature_sizes=TABLE_SIZES,
            use_native_prefetch=use_native, prefetch_depth=3)

    # two instances: the async prefetch window is strictly-once sequential
    ds, ds_ref = make_ds(), make_ds()
    for b in range(N_BATCHES):
        num_a, cats_a, lab_a = ds.preprocess(ds.read_raw(b))
        num_b, cats_b, lab_b = ds_ref[b]
        np.testing.assert_array_equal(num_a, num_b)
        np.testing.assert_array_equal(lab_a, lab_b)
        for ca, cb in zip(cats_a, cats_b):
            np.testing.assert_array_equal(ca, cb)


def test_raw_batches_through_pipeline(tmp_path):
    from distributed_embeddings_tpu.utils.pipeline import IngestPipeline
    n_rows = BATCH * N_BATCHES
    write_split_binary(str(tmp_path), n_rows)
    ds = RawBinaryDataset(
        str(tmp_path), batch_size=BATCH, numerical_features=N_NUM,
        categorical_features=list(range(len(TABLE_SIZES))),
        categorical_feature_sizes=TABLE_SIZES, use_native_prefetch=False)
    # steps > len(ds): wraps like the train loop's i % len(dataset)
    steps = N_BATCHES + 2
    pipe = IngestPipeline(ds.raw_batches(steps),
                          [("preprocess", ds.preprocess)])
    out = list(pipe)
    assert len(out) == steps
    for i, (num, cats, lab) in enumerate(out):
        ref_num, ref_cats, ref_lab = ds[i % N_BATCHES]
        np.testing.assert_array_equal(num, ref_num)
        np.testing.assert_array_equal(lab, ref_lab)
        for ca, cb in zip(cats, ref_cats):
            np.testing.assert_array_equal(ca, cb)


def test_dummy_dataset_shapes():
    ds = DummyDataset(16, N_NUM, TABLE_SIZES, num_batches=2, hotness=[1, 3, 2])
    numerical, cats, labels = ds[0]
    assert numerical.shape == (16, N_NUM)
    assert [c.shape for c in cats] == [(16, 1), (16, 3), (16, 2)]
    assert labels.shape == (16, 1)
    with pytest.raises(IndexError):
        ds[2]
