"""Multi-process SPMD worker for tests/test_multiprocess.py.

Each invocation is ONE process of an N-process run over a shared 8-device
CPU mesh (4 local virtual devices per process when N=2) — the TPU-native
equivalent of the reference's `horovodrun -np N` test harness
(reference dist_model_parallel_test.py launches every case under real
multiprocess Horovod; SURVEY.md §4). The run is world-size-generic: the
SAME script with --nproc 1 is the single-process reference, and the parent
test asserts bit-identical checksums across launch shapes.

Covers, under real cross-process gloo collectives:
  * DistributedEmbedding planning + set_weights (per-process shard staging),
  * dp-input forward with dp/col-slice/row-slice groups active,
  * per-process input staging (stage_dp_batch / make_array_from_process_local_data),
  * a dense SGD train step through the sharded autodiff path,
  * get_weights reassembly (process 0 checksums the global tables).

Writes a JSON line of checksums to --out.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..")))  # repo root


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pid", type=int, required=True)
    ap.add_argument("--nproc", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--local_devices", type=int, required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--ckpt", default=None,
                    help="shared dir for the orbax checkpoint phase")
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.local_devices}")
    import jax
    jax.config.update("jax_platforms", "cpu")
    # config.update, not env: sitecustomize pre-imports jax (see conftest)
    jax.config.update("jax_compilation_cache_dir", os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache")))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    if args.nproc > 1:
        from distributed_embeddings_tpu.parallel.mesh import (
            initialize_distributed)
        initialize_distributed(
            coordinator_address=f"127.0.0.1:{args.port}",
            num_processes=args.nproc, process_id=args.pid)

    import numpy as np
    import jax.numpy as jnp
    import optax
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.parallel.staging import stage_dp_batch

    world = args.nproc * args.local_devices
    devs = jax.devices()
    assert len(devs) == world, (len(devs), world)
    mesh = create_mesh(devs)

    # mixed groups: 40 -> dp, 300..1000 -> table-parallel (largest ones
    # column-sliced by threshold), 4000 -> row-sliced
    sizes = ([(40, 8)] + [(300 + 100 * i, 8) for i in range(8)] + [(4000, 8)])
    dist = DistributedEmbedding(
        [Embedding(v, w, combiner=None) for v, w in sizes], mesh=mesh,
        strategy="memory_balanced",
        data_parallel_threshold=512,
        column_slice_threshold=6000,
        row_slice_threshold=20000)

    rng = np.random.RandomState(7)
    weights = [rng.randn(v, w).astype(np.float32) * 0.05 for v, w in sizes]
    params = dist.set_weights(weights)

    batch = 16
    ids_global = [rng.randint(0, v, size=batch).astype(np.int32)
                  for v, _ in sizes]
    lo = args.pid * (batch // args.nproc)
    hi = lo + batch // args.nproc
    inputs = stage_dp_batch(mesh, [g[lo:hi] for g in ids_global])

    # checksums computed INSIDE jit: eager ops on non-fully-addressable
    # global arrays are illegal under multi-process, replicated jit outputs
    # are readable everywhere
    fwd = jax.jit(
        lambda p, xs: [jnp.sum(o * o) for o in dist.apply(p, xs)])
    checks = {"fwd": [round(float(s), 4) for s in fwd(params, inputs)]}

    # dense SGD step through sharded autodiff (grads follow param shardings
    # across processes), then a second forward
    opt = optax.sgd(0.5)
    opt_state = opt.init(params)

    def loss_fn(p, xs):
        outs = dist.apply(p, xs)
        return sum(jnp.sum(o * o) for o in outs) / batch

    @jax.jit
    def step(p, s, xs):
        loss, g = jax.value_and_grad(loss_fn)(p, xs)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s, loss

    params, opt_state, loss = step(params, opt_state, inputs)
    checks["loss"] = round(float(loss), 5)
    checks["fwd2"] = [round(float(s), 4) for s in fwd(params, inputs)]

    # global weight reassembly after the update (collective under
    # multi-process — every process calls it together)
    got = dist.get_weights(params)
    checks["weights"] = [round(float(np.sum(np.abs(w))), 3) for w in got]

    # distributed orbax checkpoint: every process writes its own shards,
    # restore honors the plan shardings (multi-host checkpoint/resume)
    if args.ckpt:
        from distributed_embeddings_tpu.utils import checkpoint as ckpt
        ckpt.save_checkpoint(args.ckpt, params, force=True)
        restored = ckpt.restore_checkpoint(
            args.ckpt, params, shardings=dist.param_shardings())
        checks["ckpt_fwd"] = [round(float(s), 4)
                              for s in fwd(restored, inputs)]
        assert checks["ckpt_fwd"] == checks["fwd2"], (
            checks["ckpt_fwd"], checks["fwd2"])

    # sparse tapped train step (the production path): row-wise adagrad
    # updates flowing through shard_map across processes
    from distributed_embeddings_tpu.ops.sparse_update import (
        make_sparse_optimizer)
    sopt = make_sparse_optimizer("adagrad", 0.1)
    sstate = dist.init_sparse_state(params, sopt)

    def tap_loss(taps, p, xs):
        outs, res = dist.apply(p, xs, taps=taps, return_residuals=True)
        return sum(jnp.sum(o * o) for o in outs) / batch, res

    @jax.jit
    def sparse_step(p, s, xs):
        taps = dist.make_taps(xs)
        (loss, res), g_taps = jax.value_and_grad(
            tap_loss, has_aux=True)(taps, p, xs)
        new_p, new_s, _pending = dist.sparse_update(p, s, g_taps, res, sopt)
        return new_p, new_s, loss

    sparams, sstate, sloss = sparse_step(params, sstate, inputs)
    checks["sparse_loss"] = round(float(sloss), 5)
    checks["sparse_fwd"] = [round(float(s), 4)
                            for s in fwd(sparams, inputs)]

    # dp_input=False: each process supplies only its own ranks' features
    # (remote ranks are None), global batch everywhere
    dist_mp = DistributedEmbedding(
        [Embedding(v, w, combiner=None) for v, w in sizes[1:-1]], mesh=mesh,
        strategy="memory_balanced", dp_input=False,
        input_max_hotness=[1] * len(sizes[1:-1]))
    mp_params = dist_mp.set_weights(weights[1:-1])
    local_ranks = {r for r, _ in dist_mp._rank_of_device()}
    mp_inputs = []
    for r, rank_ids in enumerate(dist_mp.strategy.input_ids_list):
        if r not in local_ranks:
            mp_inputs.append(None)
            continue
        rr = np.random.RandomState(100 + r)
        mp_inputs.append([
            jnp.asarray(rr.randint(
                0, sizes[1:-1][dist_mp.strategy.input_groups[1][pos]][0],
                size=batch).astype(np.int32))
            for pos in rank_ids])
    mp_outs = dist_mp.apply_mp(mp_params, mp_inputs)
    sums = jax.jit(lambda *os: [jnp.sum(o * o) for o in os])(*mp_outs)
    checks["mp_fwd"] = [round(float(s), 4) for s in sums]

    # true-splits exchange under REAL cross-process collectives: the
    # ragged-exchange emulation (all_gather + masked gather) must produce
    # the same forward as the padded path over gloo, not just on the
    # single-process virtual mesh
    prev_rg = os.environ.get("DET_RAGGED_EXCHANGE")
    os.environ["DET_RAGGED_EXCHANGE"] = "1"
    try:
        dist_rg = DistributedEmbedding(
            [Embedding(v, w, combiner="sum") for v, w in sizes[1:-1]],
            mesh=mesh, strategy="comm_balanced",
            input_max_hotness=[3] * len(sizes[1:-1]))
        rg_params = dist_rg.set_weights(weights[1:-1])
        rg_rng = np.random.RandomState(31)
        rg_global = [rg_rng.randint(0, v, size=(batch, 3)).astype(np.int32)
                     for v, _ in sizes[1:-1]]
        rg_inputs = stage_dp_batch(mesh, [g[lo:hi] for g in rg_global])
        rg_fwd = jax.jit(
            lambda p, xs: [jnp.sum(o * o) for o in dist_rg.apply(p, xs)])
        rg_sums = [float(s) for s in rg_fwd(rg_params, rg_inputs)]
        checks["ragged_exchange_fwd"] = [round(s, 4) for s in rg_sums]
    finally:
        if prev_rg is None:
            os.environ.pop("DET_RAGGED_EXCHANGE", None)
        else:
            os.environ["DET_RAGGED_EXCHANGE"] = prev_rg
    # and the padded path on the same model/inputs must agree in-process
    # (tolerance, not bit equality: the two paths reduce in different
    # orders — same contract as test_exchange's allclose). Force the flag
    # OFF here — if the caller exported DET_RAGGED_EXCHANGE=1 the restore
    # above would otherwise make this a vacuous ragged-vs-ragged compare.
    os.environ["DET_RAGGED_EXCHANGE"] = "0"
    try:
        dist_pd = DistributedEmbedding(
            [Embedding(v, w, combiner="sum") for v, w in sizes[1:-1]],
            mesh=mesh, strategy="comm_balanced",
            input_max_hotness=[3] * len(sizes[1:-1]))
    finally:
        if prev_rg is None:
            os.environ.pop("DET_RAGGED_EXCHANGE", None)
        else:
            os.environ["DET_RAGGED_EXCHANGE"] = prev_rg
    pd_fwd = jax.jit(
        lambda p, xs: [jnp.sum(o * o) for o in dist_pd.apply(p, xs)])
    pd_sums = [float(s)
               for s in pd_fwd(dist_pd.set_weights(weights[1:-1]),
                               rg_inputs)]
    np.testing.assert_allclose(rg_sums, pd_sums, rtol=1e-5, atol=1e-5)

    # fit loop with ITERABLE per-process data: exercises fit's default
    # mesh-aware staging (stage_dp_batch / make_array_from_process_local_
    # data) — a committed single-device device_put cannot be resharded
    # onto a non-addressable global mesh, so this path only works if the
    # default stage is mesh-aware (round-3 fix), and the sync_every=1
    # lockstep default keeps the processes' collectives aligned
    class _FitModel:
        def __init__(self, emb):
            self.embedding = emb

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            del numerical
            out = self.embedding(p["embedding"], list(cats), taps=taps,
                                 return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = jnp.concatenate(
                [o.reshape(o.shape[0], -1) for o in outs],
                axis=1).astype(jnp.float32)
            loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    from distributed_embeddings_tpu import training

    b_local = batch // args.nproc
    rngf = np.random.RandomState(21)          # same stream on every process
    fit_batches = []
    for _ in range(6):
        cats_g = [rngf.randint(0, v, size=batch).astype(np.int32)
                  for v, _ in sizes]
        labs_g = rngf.randn(batch).astype(np.float32)
        fit_batches.append(
            (np.zeros((b_local, 1), np.float32),
             [c[lo:lo + b_local] for c in cats_g],
             labs_g[lo:lo + b_local]))
    fit_params, _, fit_hist = training.fit(
        _FitModel(dist), {"embedding": dist.set_weights(weights)},
        iter(fit_batches), steps=6, optimizer="adagrad", lr=0.1,
        sparse=True, log_every=0, log_fn=lambda *_: None)
    checks["fit_loss"] = [round(l, 5) for l in fit_hist["loss"]]
    checks["fit_fwd"] = [round(float(s), 4)
                         for s in fwd(fit_params["embedding"], inputs)]

    # offloaded-bucket sparse training under TRUE multi-process: the
    # pershard host apply must assemble non-fully-addressable pinned-host
    # buckets from each process's LOCAL shards only, with no device
    # round-trip (VERDICT r4 item 3 at world > 1; single-process coverage
    # is tests/test_offload.py)
    off_sizes = [(5000, 8), (40, 8), (5000, 8), (64, 8),
                 (128, 8), (96, 8), (80, 8), (72, 8)]
    import warnings as _warnings
    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore", RuntimeWarning)  # no-host-mem case
        dist_off = DistributedEmbedding(
            [Embedding(v, w, combiner="sum") for v, w in off_sizes],
            mesh=mesh, gpu_embedding_size=2500 * 8)
    # the layer's own capability probe decides (no duplicated memory-kind
    # probe here); skip the phase only where the backend has no host memory
    if dist_off._offload_enabled:
        assert any(b.offload for b in dist_off.plan.tp_buckets)
        rngo = np.random.RandomState(40)
        off_w = [rngo.randn(v, w).astype(np.float32) * 0.1
                 for v, w in off_sizes]
        off_model = _FitModel(dist_off)
        off_init, off_step = training.make_sparse_train_step(
            off_model, "adam", lr=0.05)
        off_p = {"embedding": dist_off.set_weights(off_w)}
        off_s = off_init(off_p)
        rngb = np.random.RandomState(41)       # same stream on every process
        for _ in range(2):
            cats_g = [rngb.randint(0, v, size=batch).astype(np.int32)
                      for v, _ in off_sizes]
            labs_g = rngb.randn(batch).astype(np.float32)
            off_cats = stage_dp_batch(mesh, [c[lo:hi] for c in cats_g])
            off_labs = stage_dp_batch(mesh, [labs_g[lo:hi]])[0]
            off_p, off_s, off_loss = off_step(
                off_p, off_s, np.zeros((batch // args.nproc, 1), np.float32),
                off_cats, off_labs)
            off_loss = float(off_loss)
        checks["offload_loss"] = round(off_loss, 5)
        modes = dist_off.host_apply_modes()
        assert modes and all(m in ("native", "pershard")
                             for m in modes.values()), (
            f"multi-process offloaded apply took a round-trip: {modes}")
        off_got = dist_off.get_weights(off_p["embedding"])
        checks["offload_weights"] = [round(float(np.sum(np.abs(w))), 3)
                                     for w in off_got]

    if args.pid == 0:
        with open(args.out, "w") as f:
            json.dump(checks, f)
    print(f"proc {args.pid}/{args.nproc}: {json.dumps(checks)[:200]}",
          flush=True)


if __name__ == "__main__":
    main()
