"""host_apply_rows_inplace: the XLA-free offload apply kernels.

C++ (native/host_apply.cpp) vs numpy fallback parity, agreement with the
jax HOST_SPARSE_APPLY rules they mirror, and the f32-only guard."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.ops import sparse_update
from distributed_embeddings_tpu.native import loader


def _rows(seed, v=64, w=8, n=32):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, v, n).astype(np.int32)
    contribs = rng.randn(n, w).astype(np.float32)
    rep, sums, valid = jax.device_get(
        sparse_update.prepare_safe_grad(jnp.asarray(ids),
                                        jnp.asarray(contribs), v))
    table = rng.randn(v, w).astype(np.float32)
    return table, rep, sums, valid


def _state(kind, table, seed=3):
    rng = np.random.RandomState(seed)
    if kind == "sgd":
        return ()
    if kind == "adagrad":
        return (np.abs(rng.randn(*table.shape)).astype(np.float32) + 0.1,)
    return (rng.randn(*table.shape).astype(np.float32) * 0.01,
            np.abs(rng.randn(*table.shape)).astype(np.float32) * 0.01,
            np.float32(3.0))        # count AFTER increment (caller contract)


@pytest.mark.parametrize("kind", ["sgd", "adagrad", "adam"])
def test_cpp_matches_numpy_fallback(kind, monkeypatch):
    table, rep, sums, valid = _rows(0)
    st = _state(kind, table)
    if not hasattr(loader.load(), "ha_sgd"):
        pytest.skip("native kernels unavailable on this host")

    t_cpp = table.copy()
    s_cpp = tuple(x.copy() if getattr(x, "ndim", 0) else x for x in st)
    sparse_update.host_apply_rows_inplace(kind, t_cpp, s_cpp, rep, sums,
                                          valid, 0.05)

    monkeypatch.setattr(loader, "load",
                        lambda: (_ for _ in ()).throw(OSError("no native")))
    t_np = table.copy()
    s_np = tuple(x.copy() if getattr(x, "ndim", 0) else x for x in st)
    sparse_update.host_apply_rows_inplace(kind, t_np, s_np, rep, sums,
                                          valid, 0.05)

    np.testing.assert_allclose(t_cpp, t_np, rtol=1e-6, atol=1e-6)
    for a, b in zip(s_cpp, s_np):
        if getattr(a, "ndim", 0):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("kind", ["sgd", "adagrad", "adam"])
def test_matches_jax_host_rule(kind):
    """In-place kernels == the compute_on host rules they mirror
    (HOST_SPARSE_APPLY), row for row."""
    table, rep, sums, valid = _rows(1)
    st = _state(kind, table)

    jt = jnp.asarray(table)
    if kind == "adam":
        # jax rule increments count itself: pass the PRE-increment count
        js = (jnp.asarray(st[0]), jnp.asarray(st[1]),
              jnp.asarray(st[2] - 1.0))
    else:
        js = tuple(jnp.asarray(x) for x in st)
    want_t, want_s = sparse_update.HOST_SPARSE_APPLY[kind](
        jt, js, jnp.asarray(rep), jnp.asarray(sums), jnp.asarray(valid),
        jnp.float32(0.05))

    got_t = table.copy()
    got_s = tuple(x.copy() if getattr(x, "ndim", 0) else x for x in st)
    sparse_update.host_apply_rows_inplace(kind, got_t, got_s, rep, sums,
                                          valid, 0.05)

    np.testing.assert_allclose(got_t, np.asarray(want_t), rtol=2e-5,
                               atol=2e-6)
    for a, b in zip(got_s, want_s):
        if getattr(a, "ndim", 0):
            np.testing.assert_allclose(a, np.asarray(b), rtol=2e-5,
                                       atol=2e-6)
        else:
            assert float(a) == float(b)


def test_set_kind_replaces_rows():
    """kind='set' (ISSUE 6, the weight-streaming delta apply seam):
    valid reps get their rows REPLACED by the payload, invalid (padded)
    slots — which alias row 0 — leave the table untouched."""
    table, rep, sums, valid = _rows(7)
    before = table.copy()
    payload = np.random.RandomState(9).randn(*sums.shape) \
        .astype(np.float32)
    sparse_update.host_apply_rows_inplace("set", table, (), rep, payload,
                                          valid, 0.0)
    ok = valid > 0.0
    np.testing.assert_array_equal(table[rep[ok]], payload[ok])
    untouched = np.ones(len(table), bool)
    untouched[rep[ok]] = False
    np.testing.assert_array_equal(table[untouched], before[untouched])
    # zero-valid call (all slots padded): a pure no-op
    t2 = before.copy()
    sparse_update.host_apply_rows_inplace(
        "set", t2, (), np.zeros_like(rep), payload,
        np.zeros_like(valid), 0.0)
    np.testing.assert_array_equal(t2, before)


def test_non_f32_rejected():
    table, rep, sums, valid = _rows(2)
    with pytest.raises(TypeError, match="float32-only"):
        sparse_update.host_apply_rows_inplace(
            "sgd", table.astype(np.float16), (), rep, sums, valid, 0.05)


def test_unknown_kind_rejected():
    table, rep, sums, valid = _rows(4)
    with pytest.raises(NotImplementedError):
        sparse_update.host_apply_rows_inplace("rmsprop", table, (), rep,
                                              sums, valid, 0.05)


def test_rejects_noncontiguous_buffers():
    """ADVICE r5: the in-place apply consumes raw pointers with dense
    row-major stride assumptions — non-contiguous views must be refused,
    not silently corrupted."""
    table, rep, sums, valid = _rows(11)
    bad_table = np.asfortranarray(table)
    assert not bad_table.flags["C_CONTIGUOUS"]
    with pytest.raises(ValueError, match="C-contiguous"):
        sparse_update.host_apply_rows_inplace(
            "sgd", bad_table, (), rep, sums, valid, 0.1)
    acc = np.zeros_like(table)
    bad_acc = acc[:, ::2]                       # strided state view
    with pytest.raises(ValueError, match="C-contiguous"):
        sparse_update.host_apply_rows_inplace(
            "adagrad", table, (bad_acc,), rep, sums[:, ::2].copy(),
            valid, 0.1)
