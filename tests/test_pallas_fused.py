"""Fused Pallas sparse path (ISSUE 12): DET_SCATTER_IMPL=pallas.

The contract under test: the fused strategy — exact `dedup_sum`
aggregation feeding one tile-walk RMW kernel per bucket
(ops/pallas_tiled.tiled_*_rows), plus the fused gather->combine forward
(fused_lookup_combine) — runs the full sparse train step BIT-exactly
against the XLA sort strategy (f32, interpret mode on CPU) across
sgd/adagrad/adam x padded/ragged exchange x hot-rows on/off, composes
with lookahead=1, and falls back LOUDLY (never silently) when its gate
fails. Bit-exactness rests on the shared dedup aggregation, exact
one-hot placement of unique rows, and the fp_round rounding pins (see
ops/sparse_update.fp_round).
"""

import warnings

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.ops import pallas_tiled as pt
from distributed_embeddings_tpu.ops import sparse_update as su
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.training import make_sparse_train_step

from test_sparse_train import TinyModel, BATCH

SPECS = [(96, 8, "sum"), (50, 8, "mean"), (70, 8, "sum")]


def _grad_case(seed, v=200, w=8, n=513):
    rng = np.random.RandomState(seed)
    ids = rng.randint(-5, v + 8, n).astype(np.int32)  # dupes + OOB both ways
    contribs = rng.randn(n, w).astype(np.float32)
    table = rng.randn(v, w).astype(np.float32)
    return (su.SparseRowGrad(jnp.asarray(ids), jnp.asarray(contribs)),
            jnp.asarray(table), v, w)


# ------------------------------------------------- kernel-level parity
@pytest.mark.parametrize("optimizer", ["sgd", "adagrad", "adam"])
def test_pallas_strategy_update_bitexact_vs_sort(optimizer):
    """sparse_sgd/adagrad/adam(strategy='pallas') == strategy='sort'
    bit-for-bit under jit (traced ids keep the rounding pins opaque),
    over multiple accumulating steps."""
    g, table, v, w = _grad_case(3)

    def run(strategy):
        if optimizer == "sgd":
            f = jax.jit(lambda t, i, c: (su.sparse_sgd(
                t, su.SparseRowGrad(i, c), 0.05, strategy=strategy),))
            state = (table,)
        elif optimizer == "adagrad":
            f = jax.jit(lambda t, a, i, c: su.sparse_adagrad(
                t, a, su.SparseRowGrad(i, c), 0.05, strategy=strategy))
            state = (table, jnp.full((v, w), 0.1, jnp.float32))
        else:
            f = jax.jit(lambda t, m, u, c0, i, c: su.sparse_adam(
                t, m, u, c0, su.SparseRowGrad(i, c), 0.01,
                strategy=strategy))
            state = (table, jnp.zeros((v, w)), jnp.zeros((v, w)),
                     jnp.zeros((), jnp.int32))
        for _ in range(3):
            state = f(*state, g.ids, g.contribs)
        return state

    got = run("pallas")
    want = run("sort")
    for i, (a, b) in enumerate(zip(got, want)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"{optimizer} leaf {i}")


def test_rows_appliers_exact_placement():
    """The deduped-row appliers place each unique row's total EXACTLY
    (one-hot matmul with a unique stream): sgd_rows at lr=-1 over a zero
    table reproduces the dedup sums bit-for-bit, fillers dropped."""
    g, table, v, w = _grad_case(5)
    rep, sums = su.dedup_sum(g.ids, g.contribs, sentinel=v)
    placed = pt.tiled_sgd_rows(jnp.zeros((v, w)), rep, sums, -1.0,
                               interpret=True)
    want = jnp.zeros((v, w)).at[rep].add(sums, mode="drop",
                                         **su.dedup_flags())
    np.testing.assert_array_equal(np.asarray(placed), np.asarray(want))


def test_fused_lookup_matches_reference():
    """fused_lookup_combine == the XLA gather+einsum formulation (sum and
    mean, weighted and not) to f32 tolerance, with exact grads in params
    and weights, and the presorted path bit-identical to the fresh-sort
    path."""
    rng = np.random.RandomState(7)
    v, w = 120, 8
    table = jnp.asarray(rng.randn(v, w).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, v, (24, 3)).astype(np.int32))
    wts = jnp.asarray(rng.rand(24, 3).astype(np.float32))
    for comb in ("sum", "mean"):
        for weights in (wts, None):
            got = pt.fused_lookup_combine(table, ids, weights, comb,
                                          interpret=True)
            wv = weights if weights is not None else jnp.ones(
                ids.shape, jnp.float32)
            ref = jnp.einsum("bk,bkw->bw", wv,
                             jnp.take(table, ids, axis=0))
            if comb == "mean":
                ref = ref / jnp.maximum(jnp.sum(wv, axis=1,
                                                keepdims=True), 1.0)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)
    # grads (dense path, scatter-free by construction)
    cot = jnp.asarray(rng.randn(24, w).astype(np.float32))

    def f(t, wv):
        return jnp.vdot(pt.fused_lookup_combine(t, ids, wv, "sum",
                                                interpret=True), cot)

    def fr(t, wv):
        return jnp.vdot(jnp.einsum("bk,bkw->bw", wv,
                                   jnp.take(t, ids, axis=0)), cot)

    gt, gw = jax.grad(f, argnums=(0, 1))(table, wts)
    rt, rw = jax.grad(fr, argnums=(0, 1))(table, wts)
    np.testing.assert_allclose(np.asarray(gt), np.asarray(rt), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), rtol=1e-4,
                               atol=1e-4)
    # presorted == fresh sort, bit-identical
    from distributed_embeddings_tpu.ops.embedding_ops import (
        canonical_id_sort)
    gs = canonical_id_sort(ids, v, want_inv=True)
    a = pt.fused_lookup_combine(table, ids, wts, "sum", interpret=True)
    b = pt.fused_lookup_combine(table, ids, wts, "sum", interpret=True,
                                presorted=(gs.sid, gs.perm, gs.inv))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_lookup_invalid_ids_clamp():
    """Positive OOB ids clamp to the last row (XLA gather parity);
    zero-weight lanes contribute nothing even at OOB ids."""
    table = jnp.asarray(np.arange(40, dtype=np.float32).reshape(5, 8))
    ids = jnp.asarray([[0, 9], [2, 3]], jnp.int32)
    wts = jnp.asarray([[1.0, 1.0], [1.0, 0.0]], jnp.float32)
    got = np.asarray(pt.fused_lookup_combine(table, ids, wts, "sum",
                                             interpret=True))
    want = np.stack([np.asarray(table[0] + table[4]),
                     np.asarray(table[2])])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


# ------------------------------------------------- full train-step matrix
def _run_steps(model, optimizer, strategy, weights, head, batches):
    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.05,
                                              strategy=strategy)
    params = {"embedding": model.embedding.set_weights(weights),
              "head": {"w": jnp.asarray(head)}}
    state = init_fn(params)
    losses = []
    for cats, labels in batches:
        params, state, loss = step_fn(params, state,
                                      jnp.zeros((BATCH, 1)), cats, labels)
        losses.append(float(loss))
    return losses, model.embedding.get_weights(params["embedding"])


@pytest.mark.parametrize("optimizer", ["sgd", "adagrad", "adam"])
@pytest.mark.parametrize("ragged", [False, True])
def test_pallas_train_step_bitexact_matrix(optimizer, ragged, monkeypatch):
    """The acceptance gate: DET_SCATTER_IMPL strategy 'pallas' runs the
    full distributed sparse train step (8-device mesh, interpret-mode
    kernels) BIT-exactly vs the 'sort' strategy, across optimizers and
    the padded/ragged exchange axis."""
    monkeypatch.setenv("DET_RAGGED_EXCHANGE", "1" if ragged else "0")
    rng = np.random.RandomState(17)
    mesh = create_mesh(jax.devices()[:8])
    weights = [rng.randn(v, w).astype(np.float32) * 0.1
               for v, w, _ in SPECS]
    head = rng.randn(sum(w for _, w, _ in SPECS), 1).astype(np.float32)
    r2 = np.random.RandomState(23)
    batches = []
    for _ in range(2):
        cats = [jnp.asarray(r2.randint(0, v, size=(BATCH, 3)))
                for v, _, _ in SPECS]
        batches.append((cats, jnp.asarray(r2.randn(BATCH)
                                          .astype(np.float32))))

    def build():
        return TinyModel(SPECS, mesh, input_max_hotness=[3] * len(SPECS))

    l_p, w_p = _run_steps(build(), optimizer, "pallas", weights, head,
                          batches)
    l_s, w_s = _run_steps(build(), optimizer, "sort", weights, head,
                          batches)
    assert l_p == l_s, f"losses diverged: {l_p} vs {l_s}"
    for t, (a, b) in enumerate(zip(w_s, w_p)):
        np.testing.assert_array_equal(b, a, err_msg=f"table {t}")


def test_pallas_train_step_bitexact_hot_rows():
    """Hot-rows axis of the matrix: with a replicated hot shard admitted
    mid-run (observe -> sync), the pallas and sort strategies still agree
    bit-for-bit — the hot shard's dense psum update is strategy-
    independent and the sentinel-masked miss stream rides the same dedup
    seam."""
    specs = [(60, 8, "sum"), (90, 8, "sum")]
    rng = np.random.RandomState(31)
    mesh = create_mesh(jax.devices()[:8])
    weights = [rng.randn(v, w).astype(np.float32) * 0.1
               for v, w, _ in specs]
    head = rng.randn(16, 1).astype(np.float32)
    data = np.random.RandomState(41)
    batches = []
    for _ in range(4):
        cats = [jnp.asarray(np.minimum(
            data.zipf(1.3, size=(BATCH, 2)) - 1, v - 1).astype(np.int32))
            for v, _, _ in specs]
        batches.append((cats, jnp.asarray(data.randn(BATCH)
                                          .astype(np.float32))))

    def run(strategy):
        model = TinyModel(specs, mesh, hot_rows=8,
                          input_max_hotness=[2, 2])
        init_fn, step_fn = make_sparse_train_step(model, "adagrad",
                                                  lr=0.05,
                                                  strategy=strategy)
        params = {"embedding": model.embedding.set_weights(weights),
                  "head": {"w": jnp.asarray(head)}}
        state = init_fn(params)
        losses = []
        for i, (cats, labels) in enumerate(batches):
            model.embedding.observe_hot_ids(cats)
            if i == 1:      # admit mid-run: steps 2+ exercise hot hits
                p_emb, s_emb = model.embedding.sync_hot_rows(
                    params["embedding"], state["emb"], admit=True)
                params = {**params, "embedding": p_emb}
                state = {**state, "emb": s_emb}
            params, state, loss = step_fn(params, state,
                                          jnp.zeros((BATCH, 1)), cats,
                                          labels)
            losses.append(float(loss))
        p_sync, _ = model.embedding.sync_hot_rows(params["embedding"],
                                                  state["emb"])
        return losses, model.embedding.get_weights(p_sync)

    l_p, w_p = run("pallas")
    l_s, w_s = run("sort")
    assert l_p == l_s
    for t, (a, b) in enumerate(zip(w_s, w_p)):
        np.testing.assert_array_equal(b, a, err_msg=f"table {t}")


def test_pallas_composes_with_lookahead():
    """LookaheadEngine(strategy='pallas') at lookahead=1 is bit-exact vs
    the monolithic pallas step (the drain stage dispatches through the
    same fused kernels), and compile counts hold at one executable per
    stage per (plan, batch-shape)."""
    from distributed_embeddings_tpu.schedule import LookaheadEngine

    specs = [(80, 8, "sum"), (50, 8, "sum")]
    rng = np.random.RandomState(53)
    mesh = create_mesh(jax.devices()[:8])
    weights = [rng.randn(v, w).astype(np.float32) * 0.1
               for v, w, _ in specs]
    head = rng.randn(16, 1).astype(np.float32)
    r2 = np.random.RandomState(59)
    batches = []
    for _ in range(4):
        cats = [jnp.asarray(r2.randint(0, v, size=(BATCH, 2)))
                for v, _, _ in specs]
        batches.append((jnp.zeros((BATCH, 1)), cats,
                        jnp.asarray(r2.randn(BATCH).astype(np.float32))))

    from jax.sharding import NamedSharding, PartitionSpec as P
    # replicated head, like test_schedule._build: an uncommitted
    # single-device head would re-specialize the fused step once its
    # first output comes back replicated
    head_r = jax.device_put(jnp.asarray(head), NamedSharding(mesh, P()))

    def params_for(model):
        return {"embedding": model.embedding.set_weights(weights),
                "head": {"w": head_r}}

    m1 = TinyModel(specs, mesh)
    init_fn, step_fn = make_sparse_train_step(m1, "adagrad", lr=0.05,
                                              strategy="pallas")
    p1 = params_for(m1)
    s1 = init_fn(p1)
    mono = []
    for num, cats, lab in batches:
        p1, s1, loss = step_fn(p1, s1, num, cats, lab)
        mono.append(float(loss))

    m2 = TinyModel(specs, mesh)
    # patch_capacity=BATCH: the compile-stability configuration (the
    # default capacity overflows at these tiny zipf-free shapes and the
    # full-reprefetch fallback re-specializes — same posture as
    # test_schedule.test_compile_count_stable)
    engine = LookaheadEngine(m2, "adagrad", lr=0.05, strategy="pallas",
                             patch_capacity=BATCH)
    p2 = params_for(m2)
    s2 = engine.init(p2)
    eng = []
    for i, b in enumerate(batches):
        nxt = batches[i + 1] if i + 1 < len(batches) else None
        p2, s2, loss = engine.step(p2, s2, b, nxt)
        eng.append(float(loss))
    assert eng == mono
    assert engine.compile_counts() == {"prefetch": 1, "fused": 1}
    for t, (a, b) in enumerate(zip(m1.embedding.get_weights(
            p1["embedding"]), m2.embedding.get_weights(p2["embedding"]))):
        np.testing.assert_array_equal(b, a, err_msg=f"table {t}")


# ------------------------------------------------- gate + dispatch edges
def test_kernel_gate_fallback_loud_and_harmless(monkeypatch):
    """Forced probe failure on a 'TPU' backend: the requested pallas path
    warns LOUDLY and falls back with NO behavior change (output equals
    the XLA path bit-for-bit — the gate never silently alters
    numerics)."""
    g, table, v, w = _grad_case(11)
    want, _ = su.sparse_adagrad(table, jnp.full((v, w), 0.1), g, 0.05,
                                strategy="sort")

    def boom(width):
        raise RuntimeError("remote_compile HTTP 500 (simulated)")

    gate = su._ShapedKernelGate(boom, "DET_SCATTER_IMPL=pallas (test)")
    monkeypatch.setattr(su, "_PALLAS_FUSED_GATE", gate)
    monkeypatch.setattr(su.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(su, "_PALLAS_FALLBACK_WARNED", set())
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        got, _ = su.sparse_adagrad(table, jnp.full((v, w), 0.1), g, 0.05,
                                   strategy="pallas")
    msgs = [str(c.message) for c in caught]
    assert any("failed to compile" in m for m in msgs), msgs
    assert any("dispatches to the xla path" in m for m in msgs), msgs
    assert gate.verdicts == {8: False}
    monkeypatch.setattr(su.jax, "default_backend", lambda: "cpu")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_kernel_gate_shape_class_cache():
    """One compile-probe verdict per (backend, width shape-class): a
    second prevalidate at the same class consults the cache instead of
    re-running the validator."""
    calls = []

    def validator(cls):
        calls.append(cls)
        return True

    gate = su._ShapedKernelGate(validator, "test-gate")
    assert gate.prevalidate(16)
    assert gate.prevalidate(12)       # same pow2 class
    assert gate.prevalidate(100)      # class 128
    assert calls == [16, 128]
    assert su._width_class(8) == 8 and su._width_class(9) == 16
    assert su._width_class(4096) == 512


def test_interpret_probe_cached_per_process(monkeypatch):
    """ISSUE 12 satellite bugfix: the interpret default is probed ONCE
    per process — a backend flip mid-process can no longer diverge the
    forward gather and the update kernels within one step."""
    assert pt._interpret_default(None) is True      # CPU test process
    monkeypatch.setattr(pt.jax, "default_backend", lambda: "tpu")
    assert pt._interpret_default(None) is True      # cached, not re-probed
    assert pt._interpret_default(False) is False    # explicit always wins
    assert pt._interpret_default(True) is True


def test_pallas_requested_env_inert_off_tpu(monkeypatch):
    """DET_SCATTER_IMPL=pallas via env is TPU-only: CPU runs keep the XLA
    path under strategy='auto' (the env route must never flip CPU test
    numerics); explicit strategy='pallas' opts into interpret kernels."""
    monkeypatch.setenv("DET_SCATTER_IMPL", "pallas")
    assert not su._pallas_requested("auto")
    assert su._scatter_route("auto", jnp.zeros((4, 4))) == "xla"
    assert su._pallas_requested("pallas")
    assert su._scatter_route("pallas", jnp.zeros((4, 4))) == "pallas"
    assert su.active_scatter_impl("auto") == "xla"
    assert su.active_scatter_impl("pallas") == "pallas"


def test_gate_verdicts_shape():
    v = su.gate_verdicts()
    assert set(v) == {"tiled", "pallas", "pallas-dma"}
    assert all(x in (-1, 0, 1) for x in v.values())


def test_update_consumes_sort_pallas():
    """The fold planner must know the pallas strategy consumes the
    forward's canonical sort for ALL optimizer kinds (its dedup rides
    the artifact), and that explicit sort-strategy sgd now dedups."""
    for kind in ("sgd", "adagrad", "adam"):
        assert su.update_consumes_sort(kind, "pallas", 1000, 8)
    assert su.update_consumes_sort("sgd", "sort", 10**7, 8)
    assert not su.update_consumes_sort("sgd", "auto", 10**7, 8)


def test_pallas_step_hlo_sort_bound():
    """The lowered pallas-strategy tapped step holds the one-sort-per-
    exchange-group bound (dedup consumes the folded forward sort), and
    the fully-fused form (fused forward + pallas update) holds the
    tiled-forward 2-per-group bound."""
    import importlib.util as ilu
    import os
    spec = ilu.spec_from_file_location(
        "det_hlo_audit_pf", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "hlo_audit.py"))
    ha = ilu.module_from_spec(spec)
    spec.loader.exec_module(ha)
    rec = ha.audit_tapped_step(vocab=100_000, strategy="pallas")
    assert rec["hlo_sort"] <= rec["sort_bound"], rec
    rec2 = ha.audit_tapped_step(vocab=100_000, strategy="pallas",
                                lookup_path="fused")
    assert rec2["sort_bound"] == 2 * rec2["n_exchange_groups"]
    assert rec2["hlo_sort"] <= rec2["sort_bound"], rec2
