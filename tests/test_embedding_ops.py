"""Op-level numeric tests for embedding_lookup — mirrors the reference's
embedding_lookup_ops_test.py strategy: compare the fused paths against
composed-native references."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.ops.embedding_ops import (
    RaggedIds, SparseIds, embedding_lookup, embedding_lookup_weighted,
    ragged_to_padded, row_to_split)


def _ref_rows(table, rows_of_ids, combiner):
    out = []
    for ids in rows_of_ids:
        if len(ids) == 0:
            out.append(np.zeros(table.shape[1], np.float32))
            continue
        embs = table[np.asarray(ids)]
        out.append(embs.sum(0) if combiner == "sum" else embs.mean(0))
    return np.stack(out)


@pytest.fixture
def table():
    rng = np.random.RandomState(0)
    return rng.randn(50, 8).astype(np.float32)


def test_dense_no_combiner(table):
    ids = np.array([[1, 2], [3, 4]])
    out = embedding_lookup(jnp.asarray(table), jnp.asarray(ids))
    np.testing.assert_allclose(out, table[ids], rtol=1e-6)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_dense_combiner(table, combiner):
    ids = np.array([[1, 2, 3], [4, 5, 6]])
    out = embedding_lookup(jnp.asarray(table), jnp.asarray(ids), combiner)
    ref = _ref_rows(table, list(ids), combiner)
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_dense_hotness1_combiner(table):
    ids = np.array([[7], [9]])
    out = embedding_lookup(jnp.asarray(table), jnp.asarray(ids), "sum")
    np.testing.assert_allclose(out, table[ids[:, 0]], rtol=1e-6)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_ragged(table, combiner):
    rows = [[1, 2, 3], [4], [5, 6], []]
    values = np.concatenate([np.asarray(r, np.int32) for r in rows if r])
    splits = np.cumsum([0] + [len(r) for r in rows]).astype(np.int32)
    ragged = RaggedIds(jnp.asarray(values), jnp.asarray(splits))
    out = embedding_lookup(jnp.asarray(table), ragged, combiner)
    np.testing.assert_allclose(out, _ref_rows(table, rows, combiner), rtol=1e-5)


def test_ragged_padded_values(table):
    # values buffer longer than row_splits[-1]: padding must be dropped
    rows = [[1, 2], [3]]
    values = np.array([1, 2, 3, 7, 7, 7], np.int32)
    splits = np.array([0, 2, 3], np.int32)
    ragged = RaggedIds(jnp.asarray(values), jnp.asarray(splits))
    out = embedding_lookup(jnp.asarray(table), ragged, "sum")
    np.testing.assert_allclose(out, _ref_rows(table, rows, "sum"), rtol=1e-5)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_sparse(table, combiner):
    rows_of_ids = [[1, 2], [], [3, 4, 5]]
    indices, values = [], []
    for r, ids in enumerate(rows_of_ids):
        for c, v in enumerate(ids):
            indices.append([r, c])
            values.append(v)
    sp = SparseIds(jnp.asarray(np.asarray(indices, np.int32)),
                   jnp.asarray(np.asarray(values, np.int32)),
                   (3, 3))
    out = embedding_lookup(jnp.asarray(table), sp, combiner)
    np.testing.assert_allclose(out, _ref_rows(table, rows_of_ids, combiner),
                               rtol=1e-5)


def test_row_to_split():
    row_ids = jnp.asarray(np.array([0, 0, 2, 2, 2, 3], np.int32))
    splits = row_to_split(row_ids, 4)
    np.testing.assert_array_equal(splits, [0, 2, 2, 5, 6])


def test_weighted_lookup(table):
    ids = np.array([[1, 2, 0], [3, 4, 4]])
    w = np.array([[1.0, 1.0, 0.0], [1.0, 0.5, 0.5]], np.float32)
    out = embedding_lookup_weighted(jnp.asarray(table), jnp.asarray(ids),
                                    jnp.asarray(w), "sum")
    ref = np.einsum("bk,bkw->bw", w, table[ids])
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_ragged_to_padded(table):
    rows = [[1, 2, 3], [4], []]
    values = np.array([1, 2, 3, 4], np.int32)
    splits = np.array([0, 3, 4, 4], np.int32)
    ragged = RaggedIds(jnp.asarray(values), jnp.asarray(splits))
    ids, w = ragged_to_padded(ragged, 4)
    out = embedding_lookup_weighted(jnp.asarray(table), ids, w, "sum")
    np.testing.assert_allclose(out, _ref_rows(table, rows, "sum"), rtol=1e-5)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_ragged_grad_matches_dense(table, combiner):
    """Backward of the CSR path == backward of an explicit per-row reference."""
    rows = [[1, 2, 3], [4], [5, 6]]
    values = np.concatenate([np.asarray(r, np.int32) for r in rows])
    splits = np.cumsum([0] + [len(r) for r in rows]).astype(np.int32)
    ragged = RaggedIds(jnp.asarray(values), jnp.asarray(splits))
    cotangent = np.random.RandomState(1).randn(3, 8).astype(np.float32)

    def loss(tbl):
        return jnp.sum(embedding_lookup(tbl, ragged, combiner)
                       * jnp.asarray(cotangent))

    grad = jax.grad(loss)(jnp.asarray(table))
    ref = np.zeros_like(table)
    for r, ids in enumerate(rows):
        scale = 1.0 if combiner == "sum" else 1.0 / len(ids)
        for i in ids:
            ref[i] += cotangent[r] * scale
    np.testing.assert_allclose(grad, ref, rtol=1e-5, atol=1e-6)


def test_jit_static_shapes(table):
    ids = jnp.asarray(np.array([[1, 2], [3, 4]], np.int32))
    f = jax.jit(lambda t, i: embedding_lookup(t, i, "sum"))
    out = f(jnp.asarray(table), ids)
    assert out.shape == (2, 8)
