"""Dynamic vocabulary manager tests (ISSUE 7): frequency-gated
admission, watermark eviction with host-side demotion, recompile-free
growth over pre-reserved slack rows, binding round-trips, and the
fit/publish/serve integration."""

import os
import warnings

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.vocab import (VocabManager,
                                              latest_vocab_state,
                                              vocab_state_path)

SIZES = [(48, 8), (32, 8), (100, 8), (64, 8)]


def make_emb(slack=16, **kw):
    mesh = create_mesh(jax.devices()[:8])
    kw.setdefault("strategy", "memory_balanced")
    return DistributedEmbedding(
        [Embedding(v, w, combiner="sum") for v, w in SIZES],
        mesh=mesh, vocab_slack=slack, **kw)


class _M:
    def __init__(self, emb):
        self.embedding = emb

    def loss_fn(self, params, numerical, cats, labels, taps=None,
                return_residuals=False):
        if taps is not None or return_residuals:
            outs, res = self.embedding.apply(
                params["embedding"], cats, taps=taps, return_residuals=True)
        else:
            outs, res = self.embedding.apply(params["embedding"], cats), None
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                            axis=1)
        loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
        return (loss, res) if return_residuals else loss


def test_slack_zero_is_plan_noop(monkeypatch):
    """vocab_slack=0 (and the env default) must produce byte-identical
    plans to the pre-slack code path — the bit-exactness acceptance for
    managed-off mode rides on this."""
    mesh = create_mesh(jax.devices()[:8])

    def build(**kw):
        return DistributedEmbedding(
            [Embedding(v, w, combiner="sum") for v, w in SIZES],
            mesh=mesh, strategy="memory_balanced", **kw)

    base = build()
    z = build(vocab_slack=0)
    assert [b.rows for b in z.plan.tp_buckets] == \
        [b.rows for b in base.plan.tp_buckets]
    assert all(b.slack_rows == 0 for b in z.plan.tp_buckets)
    assert all("vocab_slack" not in c for c in z.strategy.global_configs)
    monkeypatch.setenv("DET_VOCAB_SLACK", "8")
    env = build()
    assert env.strategy.vocab_slack == 8
    assert all(b.slack_rows > 0 for b in env.plan.tp_buckets)
    for gtid in env.strategy.table_groups[1]:
        cfg = env.strategy.global_configs[gtid]
        assert cfg["input_dim"] == cfg["vocab_base_rows"] + 8


def test_admission_eviction_growth_cycle():
    """The core policy loop: unknown keys ride the fallback row until
    their decayed count crosses the threshold; admission binds them to
    free slots (zero-initialized rows); drift pressure evicts the cold
    tail at the watermark (rows stashed host-side); a re-admitted key
    gets its stashed row back; occupancy never exceeds the high
    watermark."""
    emb = make_emb(slack=8)
    params = emb.init(jax.random.PRNGKey(0))
    mgr = VocabManager(emb, admit_threshold=2, decay=0.9, use_native=False,
                       high_watermark=0.5, low_watermark=0.25)
    rng = np.random.RandomState(0)
    raw = [rng.randint(10**9, 2 * 10**9, size=(16, 2)).astype(np.int64)
           for _ in SIZES]

    # below threshold: everything translates to the fallback row
    t0 = mgr.translate(raw, observe=True)
    assert all((np.asarray(x) == 0).all() for x in t0)
    params, _ = mgr.maintain(params)
    assert mgr.stats()["admissions"] == 0       # count 1 < threshold 2

    # sustained signal crosses the threshold -> bound to private rows
    for _ in range(3):
        mgr.translate(raw, observe=True)
    params, _ = mgr.maintain(params)
    t1 = mgr.translate(raw)
    assert any((np.asarray(x) > 0).any() for x in t1)
    st = mgr.stats()
    assert st["admissions"] > 0

    # admitted rows were zero-initialized (slack rows carried init noise)
    w = emb.get_weights(params)
    rows0 = np.unique(np.asarray(t1[0]).reshape(-1))
    rows0 = rows0[rows0 > 0]
    assert (w[0][rows0] == 0).all()

    # drift: new key universes force watermark eviction, occupancy
    # stays <= high watermark at every cycle
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        for r in range(6):
            raw2 = [rng.randint(10**9, 2 * 10**9,
                                size=(16, 2)).astype(np.int64)
                    for _ in SIZES]
            for _ in range(3):
                mgr.translate(raw2, observe=True)
            params, _ = mgr.maintain(params)
            for mv in mgr.vocabs.values():
                assert mv.occupancy <= 0.5 + 1e-9
    st = mgr.stats()
    assert st["evictions"] > 0
    assert any(len(mv.stash) for mv in mgr.vocabs.values())

    # a stable universe must NOT churn once resident
    emb2 = make_emb(slack=8)
    p2 = emb2.init(jax.random.PRNGKey(1))
    m2 = VocabManager(emb2, admit_threshold=1, decay=0.9, use_native=False)
    fixed = [rng.randint(0, 30, size=(16, 2)).astype(np.int64) + 10**9
             for _ in SIZES]
    for _ in range(6):
        m2.translate(fixed, observe=True)
        p2, _ = m2.maintain(p2)
    assert m2.stats()["evictions"] == 0


def test_stash_restores_trained_row():
    """Evict -> re-admit must hand the key its trained row back (the
    host-offloaded demotion storage), not a fresh zero row."""
    emb = make_emb(slack=8)
    params = emb.init(jax.random.PRNGKey(1))
    mgr = VocabManager(emb, admit_threshold=1, decay=0.9, use_native=False,
                       high_watermark=0.9, low_watermark=0.3)
    rng = np.random.RandomState(3)
    key_a = np.full((4, 2), 777_777, np.int64)
    quiet = np.zeros((4, 2), np.int64)
    mgr.translate([key_a, quiet, quiet, quiet], observe=True)
    params, _ = mgr.maintain(params)
    row_a = int(mgr.vocabs[0].binding.lookup(np.array([777_777]))[0])
    assert row_a > 0
    w = emb.get_weights(params)
    w[0][row_a] = 42.0
    params = emb.set_weights(w)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        for _ in range(60):     # key_a goes cold; hot flood drifts in
            flood = rng.randint(10**6, 10**7,
                                size=(16, 4)).astype(np.int64)
            mgr.translate([flood, quiet, quiet, quiet], observe=True)
            mgr.translate([flood, quiet, quiet, quiet], observe=True)
            params, _ = mgr.maintain(params)
            if mgr.vocabs[0].binding.lookup(np.array([777_777]))[0] == 0:
                break
    assert mgr.vocabs[0].binding.lookup(np.array([777_777]))[0] == 0
    assert (mgr.vocabs[0].stash[777_777] == 42.0).all()

    for _ in range(30):
        mgr.translate([key_a, quiet, quiet, quiet], observe=True)
    params, _ = mgr.maintain(params)
    row_a2 = int(mgr.vocabs[0].binding.lookup(np.array([777_777]))[0])
    assert row_a2 > 0
    w2 = emb.get_weights(params)
    np.testing.assert_array_equal(w2[0][row_a2], np.full((8,), 42.0))


def test_translate_forms_and_drop_mode():
    """Translation preserves every prepared-input form; on_miss='drop'
    zero-weights unadmitted lanes instead of routing them to row 0."""
    from distributed_embeddings_tpu.ops.embedding_ops import (RaggedIds,
                                                              SparseIds)
    emb = make_emb(slack=8)
    mgr = VocabManager(emb, admit_threshold=1, use_native=False)
    known = mgr.vocabs[0]
    rows = known.bind([111, 222])
    assert (np.asarray(rows) > 0).all()

    dense = np.array([[111, 222], [333, 111]], np.int64)
    out = mgr.translate([dense, np.zeros((2, 2), np.int64),
                         np.zeros((2, 2), np.int64),
                         np.zeros((2, 2), np.int64)])
    o = np.asarray(out[0])
    assert o[0, 0] == rows[0] and o[0, 1] == rows[1]
    assert o[1, 0] == 0 and o[1, 1] == rows[0]      # 333 unadmitted

    wts = np.ones((2, 2), np.float32)
    out_t = mgr.translate([(dense, wts)] + [np.zeros((2, 2), np.int64)] * 3)
    ids_t, w_t = out_t[0]
    np.testing.assert_array_equal(np.asarray(ids_t), o)
    np.testing.assert_array_equal(np.asarray(w_t), wts)

    rag = RaggedIds(jnp.asarray(np.array([111, 333, 222], np.int32)),
                    jnp.asarray(np.array([0, 2, 3], np.int32)))
    out_r = mgr.translate([rag] + [np.zeros((2, 2), np.int64)] * 3)
    np.testing.assert_array_equal(np.asarray(out_r[0].values),
                                  [rows[0], 0, rows[1]])
    sp = SparseIds(jnp.asarray(np.array([[0, 0], [1, 1]], np.int32)),
                   jnp.asarray(np.array([222, 333], np.int32)), (2, 2))
    out_s = mgr.translate([sp] + [np.zeros((2, 2), np.int64)] * 3)
    np.testing.assert_array_equal(np.asarray(out_s[0].values), [rows[1], 0])

    # drop mode: unadmitted lanes become zero-weight (no fallback-row
    # gradient traffic); bound lanes keep their weight
    mgr_d = VocabManager(emb, admit_threshold=1, use_native=False,
                         on_miss="drop")
    mgr_d.vocabs[0].bind([111])
    dense8 = np.zeros((8, 2), np.int64)     # batch divisible by the mesh
    dense8[0] = [111, 222]                  # one bound, one unadmitted
    dense8[1] = [333, 444]                  # both unadmitted
    ids_d, w_d = mgr_d.translate(
        [dense8] + [np.zeros((8, 2), np.int64)] * 3)[0]
    assert w_d[0, 0] == 1.0 and w_d[0, 1] == 0.0 and (w_d[1] == 0.0).all()
    ones = [np.ones((cfg["input_dim"], cfg["output_dim"]), np.float32)
            for cfg in emb.strategy.global_configs]
    out_fwd = emb.apply(emb.set_weights(ones),
                        [(ids_d, w_d)] + [np.zeros((8, 2), np.int32)] * 3)
    # exactly one surviving lane in sample 0; sample 1 fully dropped
    np.testing.assert_allclose(np.asarray(out_fwd[0])[0], np.ones((8,)))
    np.testing.assert_allclose(np.asarray(out_fwd[0])[1], np.zeros((8,)))


def test_compile_count_stable_across_growth():
    """Admission/eviction/growth never change jitted step shapes: ONE
    compile per (plan, batch shape) for both the serving forward and the
    sparse train step, across cycles that bind, evict and rebind rows."""
    from distributed_embeddings_tpu.training import make_sparse_train_step

    emb = make_emb(slack=8)
    model = _M(emb)
    params = {"embedding": emb.init(jax.random.PRNGKey(0))}
    init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=0.05,
                                              donate=False)
    state = init_fn(params)
    mgr = VocabManager(emb, admit_threshold=1, decay=0.9, use_native=False,
                       high_watermark=0.5, low_watermark=0.25)
    fwd = jax.jit(lambda p, cats: emb.apply(p, cats))
    step = jax.jit(step_fn, donate_argnums=())
    rng = np.random.RandomState(0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        for r in range(4):
            raw = [rng.randint(10**9, 2 * 10**9,
                               size=(16, 2)).astype(np.int64)
                   for _ in SIZES]
            for _ in range(2):
                cats = mgr.translate(raw, observe=True)
            p_emb, s_emb = mgr.maintain(params["embedding"], state["emb"])
            params = {**params, "embedding": p_emb}
            state = {**state, "emb": s_emb}
            fwd(params["embedding"], [jnp.asarray(c) for c in cats])
            params, state, loss = step(
                params, state, jnp.zeros((16, 1)),
                [jnp.asarray(c) for c in cats],
                jnp.zeros((16,), jnp.float32))
            assert np.isfinite(float(loss))
    st = mgr.stats()
    assert st["admissions"] > 0 and st["evictions"] > 0
    assert fwd._cache_size() == 1, "forward recompiled under growth"
    assert step._cache_size() == 1, "train step recompiled under growth"


def test_fit_publish_serve_roundtrip(tmp_path, monkeypatch):
    """training.fit(vocab=) on raw keys publishes rows + the binding
    sidecar; a fresh consumer engine polls both and serves the SAME raw
    keys bit-exactly against the publisher's view."""
    from distributed_embeddings_tpu import training
    from distributed_embeddings_tpu.serving import InferenceEngine
    from distributed_embeddings_tpu.store import TableStore

    monkeypatch.setenv("DET_STEP_DONATE", "0")
    emb = make_emb(slack=16)
    model = _M(emb)
    params = {"embedding": emb.init(jax.random.PRNGKey(0))}
    mgr = VocabManager(emb, admit_threshold=1, decay=0.99,
                       use_native=False)
    rng = np.random.RandomState(7)

    def data(step):
        cats = [rng.randint(10**8, 10**8 + 60,
                            size=(16, 2)).astype(np.int64) for _ in SIZES]
        return (np.zeros((16, 1), np.float32), cats,
                rng.randn(16).astype(np.float32))

    init_fn, _ = training.make_sparse_train_step(model, "adagrad", lr=0.05)
    store = TableStore(emb, params["embedding"], init_fn(params)["emb"])
    d = str(tmp_path / "stream")
    params, opt, hist = training.fit(
        model, params, data, steps=9, optimizer="adagrad", lr=0.05,
        vocab=mgr, vocab_every=3, store=store, publish_every=3,
        publish_dir=d, log_every=0)
    assert hist["vocab_stats"]["admissions"] > 0
    assert hist["published"][0]["kind"] == "snapshot"
    assert latest_vocab_state(d) is not None
    assert os.path.exists(vocab_state_path(d, store.version))

    emb_c = make_emb(slack=16)
    mgr_c = VocabManager(emb_c, use_native=False)
    eng = InferenceEngine(emb_c, emb_c.init(jax.random.PRNGKey(9)),
                          vocab_manager=mgr_c)
    infos = eng.poll_updates(d)
    assert infos and infos[0]["kind"] == "snapshot"
    for t in mgr.vocabs:
        np.testing.assert_array_equal(mgr_c.vocabs[t].resident_keys(),
                                      mgr.vocabs[t].resident_keys())
    raw = [rng.randint(10**8, 10**8 + 60, size=(8, 2)).astype(np.int64)
           for _ in SIZES]
    out_c = eng.predict(raw)
    out_p = emb.apply(params["embedding"], mgr.translate(raw))
    for i, (a, b) in enumerate(zip(out_p, out_c)):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(a),
                                      err_msg=f"output {i}")


def test_shared_table_decays_once_per_batch():
    """A table fed by k inputs (input_table_map) must age its admission
    counters ONCE per batch, not k times — the aging window is a
    property of the table, not of its input fan-in."""
    mesh = create_mesh(jax.devices()[:8])
    emb = DistributedEmbedding(
        [Embedding(v, w, combiner="sum") for v, w in SIZES],
        mesh=mesh, strategy="memory_balanced", vocab_slack=8,
        input_table_map=[0, 1, 2, 3, 0])      # table 0 shared by 2 inputs
    mgr = VocabManager(emb, admit_threshold=3, decay=0.5, use_native=False)
    key = np.full((8, 1), 123_456, np.int64)
    quiet = np.zeros((8, 1), np.int64)
    batch = [key, quiet, quiet, quiet, quiet]
    mgr.translate(batch, observe=True)        # count(K) = 8 (8 lanes)
    mgr.translate(batch, observe=True)        # one tick: 8*0.5 + 8 = 12
    got = mgr.vocabs[0].tracker.counts_for(np.array([123_456]))[0]
    assert got == pytest.approx(12.0), got    # double-tick would give 10
    # the shared inputs' streams still AGGREGATE into one observation
    batch2 = [key, quiet, quiet, quiet, key]  # K now via both inputs
    mgr.translate(batch2, observe=True)       # 12*0.5 + 16 = 22
    got = mgr.vocabs[0].tracker.counts_for(np.array([123_456]))[0]
    assert got == pytest.approx(22.0), got


def test_stash_is_bounded():
    """The demotion stash must not grow with run length: past stash_max
    the oldest demotion drops (its key re-admits from zeros)."""
    emb = make_emb(slack=8)
    mgr = VocabManager(emb, admit_threshold=1, use_native=False,
                       stash_max=3)
    mv = mgr.vocabs[0]
    for k in range(10):
        mv.bind([1000 + k])
        mv.unbind(np.array([1000 + k]),
                  np.full((1, 8), float(k), np.float32))
    assert len(mv.stash) == 3
    assert sorted(mv.stash) == [1007, 1008, 1009]   # newest survive


def test_poll_picks_up_late_sidecar_without_new_rows(tmp_path):
    """A consumer that applied rows before the matching binding sidecar
    was visible must pick the sidecar up on its NEXT poll — even though
    no new row files arrive in between (the publisher writes sidecars
    first, but a consumer can race a partially-synced directory)."""
    from distributed_embeddings_tpu.serving import InferenceEngine
    from distributed_embeddings_tpu.store import TableStore

    emb = make_emb(slack=16)
    params = emb.init(jax.random.PRNGKey(0))
    mgr = VocabManager(emb, admit_threshold=1, use_native=False)
    mgr.vocabs[0].bind([111, 222])
    store = TableStore(emb, params)
    d = str(tmp_path)
    store.commit(params)
    store.publish(d)                      # rows v1, NO sidecar yet

    emb_c = make_emb(slack=16)
    mgr_c = VocabManager(emb_c, use_native=False)
    eng = InferenceEngine(emb_c, emb_c.init(jax.random.PRNGKey(1)),
                          vocab_manager=mgr_c)
    infos = eng.poll_updates(d)
    assert infos and mgr_c.vocabs[0].bound == 0   # sidecar wasn't there

    mgr.save_state(vocab_state_path(d, 1))        # sidecar lands late
    assert eng.poll_updates(d) == []              # no new rows...
    assert mgr_c.vocabs[0].bound == 2             # ...binding loaded anyway


def test_vocab_manager_rejects_bad_configs():
    emb = make_emb(slack=8)
    with pytest.raises(ValueError):
        VocabManager(emb, on_miss="nonsense")
    with pytest.raises(ValueError):
        VocabManager(emb, high_watermark=0.5, low_watermark=0.9)
    with pytest.raises(ValueError):
        VocabManager(emb, tables=[999])
    # combiner-None tables cannot ride drop mode
    mesh = create_mesh(jax.devices()[:8])
    emb_n = DistributedEmbedding(
        [Embedding(v, w, combiner=None) for v, w in SIZES[:4]],
        mesh=mesh, vocab_slack=4)
    with pytest.raises(ValueError):
        VocabManager(emb_n, on_miss="drop")
    # hot-row-replicated buckets are refused: eviction/rebind would
    # fight sync_hot_rows' write-back over physical rows
    emb_h = DistributedEmbedding(
        [Embedding(v, w, combiner="sum") for v, w in SIZES],
        mesh=mesh, strategy="memory_balanced", vocab_slack=8, hot_rows=8)
    assert emb_h._hot_buckets
    with pytest.raises(ValueError, match="hot"):
        VocabManager(emb_h, tables=[0])
    with pytest.raises(ValueError, match="manageable"):
        VocabManager(emb_h)          # nothing left to manage -> loud


def test_replan_recommendation_logged():
    """Admission demand beyond post-eviction capacity must surface the
    re-plan recommendation (the operator's cue to raise DET_VOCAB_SLACK)."""
    emb = make_emb(slack=0)
    params = emb.init(jax.random.PRNGKey(0))
    logs = []
    mgr = VocabManager(emb, admit_threshold=1, decay=1.0, use_native=False,
                       tables=[1], log_fn=logs.append)
    rng = np.random.RandomState(0)
    quiet = np.zeros((4, 2), np.int64)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for _ in range(3):
            flood = rng.randint(10**6, 10**7,
                                size=(64, 2)).astype(np.int64)
            mgr.translate([quiet, flood, quiet, quiet], observe=True)
            params, _ = mgr.maintain(params)
    assert any("vocab_slack" in str(x.message) for x in w)
    assert logs and "re-plan" in logs[0]
