"""Static program auditor (ISSUE 10): IR layer, pass framework, legacy
parity, mutation fixtures.

The parity tests are the port's acceptance gate: the three regex-era
auditors (`hlo_op_counts`, `hlo_collective_bytes`,
`hlo_collective_overlap`) were run over the recorded program fixtures
BEFORE deletion and their outputs frozen in
tests/fixtures/hlo/expected_legacy.json — the IR-based measurements
must reproduce them EXACTLY. The fixtures cannot be regenerated against
the old code (it is gone); the JSON is the behavior contract.
"""

import gzip
import json
import os

import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.analysis import ir, passes, programs

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "hlo")

FIXTURES = ("meshed_step_f32", "meshed_step_bf16_weighted",
            "meshed_step_int32_ids", "meshed_step_donated",
            "unfolded_sorts", "lookahead_fused", "lookahead_prefetch",
            "serve_forward")

_WIDE_OPS = ("sort", "scatter", "gather", "all_to_all", "all_gather",
             "reduce_scatter", "while", "dot_general", "custom_call")


def _fixture(name: str) -> str:
    with gzip.open(os.path.join(FIXTURE_DIR, name + ".mlir.gz"),
                   "rt") as f:
        return f.read()


@pytest.fixture(scope="module")
def legacy_expected():
    with open(os.path.join(FIXTURE_DIR, "expected_legacy.json")) as f:
        return json.load(f)


# ------------------------------------------------------------- parity
@pytest.mark.parametrize("name", FIXTURES)
def test_legacy_parser_parity(name, legacy_expected):
    """The ported measurements reproduce the regex era bit-for-bit on
    every recorded program — op counts (default + wide op set, incl.
    the attribute-mention semantics: #stablehlo.gather<> references
    count), collective bytes by dtype, and the full overlap
    classification."""
    want = legacy_expected[name]
    mod = ir.parse_module(_fixture(name))
    assert ir.op_counts(mod) == want["op_counts"]
    assert ir.op_counts(mod, ops=_WIDE_OPS) == want["op_counts_wide"]
    assert ir.collective_bytes(mod) == want["collective_bytes"]
    assert ir.collective_overlap(mod) == want["collective_overlap"]


def test_profiling_delegates_to_ir():
    """utils.profiling keeps the public API (bench.py, the audit arms
    and old tests all import it) but the implementation is the ONE IR
    parse — same outputs on a real lowered text, and Module inputs are
    accepted directly."""
    from distributed_embeddings_tpu.utils import profiling
    text = _fixture("meshed_step_f32")
    mod = ir.parse_module(text)
    assert profiling.hlo_op_counts(text) == ir.op_counts(mod)
    assert profiling.hlo_collective_bytes(text) == \
        ir.collective_bytes(mod)
    assert profiling.hlo_collective_overlap(text) == \
        ir.collective_overlap(mod)
    assert profiling.hlo_op_counts(mod) == ir.op_counts(mod)


# ------------------------------------------------------------ IR layer
def test_empty_and_garbage_modules():
    """The parser never throws: empty text, whitespace, and non-MLIR
    garbage all produce a Module that measures as zero."""
    for text in ("", "   \n\n", "not mlir at all\n{ unbalanced"):
        mod = ir.parse_module(text)
        assert mod.entry is None or mod.entry.instructions == []
        assert ir.op_counts(mod)["sort"] == 0
        assert ir.collective_bytes(mod)["total"] == {}
        assert ir.collective_overlap(mod)["collectives_total"] == 0


def test_type_parsing():
    t = ir.Type.parse("tensor<8x4xbf16>")
    assert (t.dtype, t.shape, t.nbytes) == ("bf16", (8, 4), 64)
    assert ir.Type.parse("tensor<f32>").shape == ()
    assert ir.Type.parse("tensor<f32>").nbytes == 4
    dyn = ir.Type.parse("tensor<?x4xf32>")
    assert dyn.shape == (None, 4) and dyn.nbytes == 0
    assert ir.Type.parse("!stablehlo.token").dtype is None
    # float8 element types are registered at 1 byte (ISSUE 15 — the
    # storage-dtype pass measures quantized buffers); genuinely unknown
    # element types still charge the historical 4 bytes/element
    assert ir.Type.parse("tensor<2xf8E4M3FN>").nbytes == 2
    assert ir.Type.parse("tensor<2xmystery99>").nbytes == 8


def test_instruction_structure_and_regions():
    """Multi-result instructions, region folding, attrs, arg attrs."""
    text = """
module @m {
  func.func public @main(%arg0: tensor<8xi32> {jax.buffer_donor = true}, %arg1: tensor<8xf32>) -> tensor<8xf32> {
    %0:2 = "stablehlo.sort"(%arg0, %arg1) <{dimension = 0 : i64, is_stable = true}> ({
    ^bb0(%a: tensor<i32>, %b: tensor<i32>, %c: tensor<f32>, %d: tensor<f32>):
      %cmp = stablehlo.compare LT, %a, %b : (tensor<i32>, tensor<i32>) -> tensor<i1>
      stablehlo.return %cmp : tensor<i1>
    }) : (tensor<8xi32>, tensor<8xf32>) -> (tensor<8xi32>, tensor<8xf32>)
    %1 = stablehlo.add %0#1, %arg1 : tensor<8xf32>
    return %1 : tensor<8xf32>
  }
}
"""
    mod = ir.parse_module(text)
    fn = mod.entry
    assert fn.name == "main" and fn.visibility == "public"
    assert [a.donated for a in fn.args] == [True, False]
    assert fn.donated_args[0].name == "%arg0"
    sort, add = fn.instructions
    assert sort.kind == "sort" and sort.num_results == 2
    assert ("stablehlo", "compare") in sort.region_ops
    assert "is_stable" in sort.attrs
    # the region-closing line's signature is the instruction's signature
    assert [t.dtype for t in sort.operand_types] == ["i32", "f32"]
    assert [t.dtype for t in sort.result_types] == ["i32", "f32"]
    assert add.operands == ["%0", "%arg1"]       # %0#1 -> base name
    assert fn.returns == ["%1"]
    assert fn.producers() == {"%0": 0, "%1": 1}


def test_nested_call_graph_two_deep():
    """Interprocedural summaries through a two-deep private call chain
    (jax's shmap_body-within-helper structure): the inner collective
    surfaces at the entry call site, and classification follows the
    call-site's edges."""
    text = """
module @m {
  func.func public @main(%arg0: tensor<8xf32>, %arg1: tensor<8x8xf32>) -> tensor<8xf32> {
    %0 = call @shmap_body(%arg0) : (tensor<8xf32>) -> tensor<8xf32>
    %1 = stablehlo.dot_general %arg1, %arg1, contracting_dims = [1] x [0] : (tensor<8x8xf32>, tensor<8x8xf32>) -> tensor<8x8xf32>
    return %0 : tensor<8xf32>
  }
  func.func private @shmap_body(%arg0: tensor<8xf32>) -> tensor<8xf32> {
    %0 = call @shmap_body_0(%arg0) : (tensor<8xf32>) -> tensor<8xf32>
    return %0 : tensor<8xf32>
  }
  func.func private @shmap_body_0(%arg0: tensor<8xf32>) -> tensor<8xf32> {
    %0 = "stablehlo.all_to_all"(%arg0) <{concat_dimension = 0 : i64, split_count = 8 : i64, split_dimension = 0 : i64}> : (tensor<8xf32>) -> tensor<8xf32>
    return %0 : tensor<8xf32>
  }
}
"""
    mod = ir.parse_module(text)
    assert mod.call_graph()["main"] == ["shmap_body"]
    assert mod.call_graph()["shmap_body"] == ["shmap_body_0"]
    ov = ir.collective_overlap(mod)
    # the collective two calls down is visible at main's call site, and
    # nothing orders it against the dot -> candidate
    assert ov["collectives_total"] == 1
    assert ov["overlap_candidates"] == 1
    # bytes surface from the inner function's own instruction
    assert ir.collective_bytes(mod)["total"] == {"f32": 32}


def test_recursive_call_graph_tolerated():
    """A (hand-made) call cycle must not hang or crash the summaries."""
    text = """
module @m {
  func.func public @main(%arg0: tensor<8xf32>) -> tensor<8xf32> {
    %0 = call @a(%arg0) : (tensor<8xf32>) -> tensor<8xf32>
    return %0 : tensor<8xf32>
  }
  func.func private @a(%arg0: tensor<8xf32>) -> tensor<8xf32> {
    %0 = call @a(%arg0) : (tensor<8xf32>) -> tensor<8xf32>
    return %0 : tensor<8xf32>
  }
}
"""
    assert ir.collective_overlap(text)["collectives_total"] == 0


def test_dp_only_plan_zero_collectives():
    """A data-parallel-only plan (every table under the dp threshold)
    lowers with ZERO exchange collectives — the auditor must report the
    empty program faithfully, not crash on it."""
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(jax.devices()[:8])
    d = DistributedEmbedding(
        [Embedding(64, 8, combiner="sum") for _ in range(2)],
        mesh=mesh, data_parallel_threshold=10**9)
    assert not d.plan.tp_buckets        # everything went dp
    p = d.init(jax.random.PRNGKey(0))
    ins = [jnp.zeros((16, 2), jnp.int32)] * 2
    text = jax.jit(lambda p, i: d.apply(p, list(i))).lower(
        p, ins).as_text()
    mod = ir.parse_module(text)
    assert ir.collective_bytes(mod)["total"] == {}
    ov = ir.collective_overlap(mod)
    assert ov["collectives_total"] == 0 == ov["overlap_candidates"]


def test_prefetch_arm_standalone_ir():
    """The lookahead prefetch arm lowered standalone (the recorded
    fixture): private shmap bodies in the call graph, all collectives
    overlap candidates (no dense compute in the arm), forward-only
    byte profile."""
    mod = ir.parse_module(_fixture("lookahead_prefetch"))
    assert any(f.startswith("shmap_body") for f in mod.functions)
    assert mod.entry.name == "main"
    ov = ir.collective_overlap(mod)
    assert ov["collectives_total"] > 0
    assert ov["overlap_candidates"] == ov["collectives_total"]
    assert ov["compute_sites"] == 0
    b = ir.collective_bytes(mod)
    assert b["float_bytes"] > 0 and b["int_bytes"] > 0


# ------------------------------------------------------ pass framework
def test_all_passes_registered():
    names = [n for n, _ in passes.list_passes()]
    assert names == ["op-counts", "collective-bytes",
                     "collective-overlap", "wire-seam", "donation",
                     "dtype-promotion", "storage-dtype",
                     "dead-dup-collective"]


@pytest.mark.parametrize("case", programs.mutation_cases(),
                         ids=lambda c: c.name)
def test_mutation_fixture_flags(case):
    """Every pass flags its seeded violation with EXACTLY the expected
    finding ids — an auditor that cannot fail is not a gate. (The same
    check gates CI through `hlo_audit.py --assert`.)"""
    mod = ir.parse_module(case.text)
    got = tuple(f.fid for f in passes.run_passes(
        mod, case.ctx, passes=[case.pass_name]))
    assert got == case.expect_fids, (case.name, got)
    # and the finding ids are stable across re-parses (allowlist key)
    again = tuple(f.fid for f in passes.run_passes(
        ir.parse_module(case.text), case.ctx,
        passes=[case.pass_name]))
    assert again == got


def test_finding_shape_and_severity():
    f = passes.run_passes(
        ir.parse_module(programs._MUT_F64),
        passes.PlanContext(program="t"),
        passes=["dtype-promotion"])[0]
    d = f.to_dict()
    assert d["severity"] == "error" and d["pass_name"] == \
        "dtype-promotion"
    assert set(d) == {"pass_name", "fid", "severity", "message",
                      "func", "line", "op"}
    assert d["func"] == "main" and d["line"] > 0


def test_context_free_run_is_silent():
    """A default PlanContext disables every bounded check: green
    programs produce zero findings, and nothing crashes on the fixture
    set."""
    ctx = passes.PlanContext(program="t", id_wire_dtypes=("auto",))
    for name in ("meshed_step_f32", "serve_forward"):
        mod = ir.parse_module(_fixture(name))
        assert passes.run_passes(mod, ctx) == []


def test_donation_pass_both_directions():
    donated = ir.parse_module(_fixture("meshed_step_donated"))
    clean = ir.parse_module(_fixture("meshed_step_f32"))
    on = passes.PlanContext(program="t", donate_expected=True)
    off = passes.PlanContext(program="t", donate_expected=False)
    assert [f.fid for f in passes.run_passes(
        donated, off, passes=["donation"])] == \
        ["donation/unexpected-donation"]
    assert passes.run_passes(donated, on, passes=["donation"]) == []
    missing = passes.run_passes(clean, on, passes=["donation"])
    assert [f.fid for f in missing] == ["donation/missing-donation"]
    assert missing[0].severity == "warning"
    assert passes.run_passes(clean, off, passes=["donation"]) == []


def test_wire_seam_attributes_real_programs():
    """The recorded real programs attribute cleanly under their actual
    plan wires, and FAIL attribution under a deliberately wrong
    context — the pass reads the plan, not the program."""
    mod = ir.parse_module(_fixture("meshed_step_f32"))
    ok = passes.PlanContext(program="t", wire_dtypes=("f32",),
                            id_wire_dtypes=("int16",))
    assert passes.run_passes(mod, ok, passes=["wire-seam"]) == []
    wrong = passes.PlanContext(program="t", wire_dtypes=("bf16",),
                               id_wire_dtypes=("int32",))
    fids = {f.fid for f in passes.run_passes(mod, wrong,
                                             passes=["wire-seam"])}
    assert "wire-seam/escape.all_to_all.f32" in fids
    assert "wire-seam/escape.all_to_all.i16" in fids


def test_expected_bytes_cross_check_on_fixture():
    """The reconciled byte model == the HLO measurement on the recorded
    bf16 weighted program (the tricky config: narrowed int16 ids at
    2 B/element on the wire, activations twice — fwd + gradient
    transpose — and the weight block forward-ONLY, because weights are
    inputs, not params)."""
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    mesh = create_mesh(jax.devices()[:8])
    model = programs.build_model(512, 8, "sum", tables=2, mesh=mesh,
                                 exchange_wire="bf16")
    want = programs.expected_collective_bytes(
        model.embedding, [2, 2], batch=16, weighted=True, train=True)
    got = ir.collective_bytes(
        _fixture("meshed_step_bf16_weighted"))["total"]
    assert got == want


def test_bf16_sr_wire_format_not_false_flagged():
    """'bf16-sr' is a supported wire FORMAT that puts bf16 payloads on
    the wire: a plan declaring it must neither trip the
    zero-compressed-bytes contract (collective-bytes) nor fail open on
    the f32-leak check (dtype-promotion) — formats map to payload
    dtypes through the ops/wire.py seam hooks, never by string
    comparison."""
    bf16_prog = ir.parse_module(_fixture("meshed_step_bf16_weighted"))
    sr_ctx = passes.PlanContext(program="t", wire_dtypes=("bf16-sr",),
                                id_wire_dtypes=("int16",))
    # a bf16-payload program under a bf16-sr plan: clean
    assert passes.run_passes(bf16_prog, sr_ctx,
                             passes=["collective-bytes"]) == []
    assert passes.run_passes(bf16_prog, sr_ctx,
                             passes=["wire-seam"]) == []
    # a uniformly-bf16-sr plan is COMPRESSED: an f32 payload on a seam
    # collective must still flag (the check may not fail open)
    leak = ir.parse_module(programs._MUT_FREE_COLLECTIVE)
    fids = [f.fid for f in passes.run_passes(
        leak, sr_ctx, passes=["dtype-promotion"])]
    assert fids == ["dtype-promotion/f32-wire-leak.all_to_all"]


def test_duplicate_detection_ignores_channel_handles():
    """jax stamps every collective with a UNIQUE channel_handle; two
    otherwise byte-identical exchanges must still compare equal (with
    raw-attr keys the duplicate check could never fire on a real
    lowering — the 'auditor that cannot fail' failure mode)."""
    text = """
module @m {
  func.func public @main(%arg0: tensor<8xf32>) -> tensor<64xf32> {
    %0 = "stablehlo.all_gather"(%arg0) <{all_gather_dim = 0 : i64, channel_handle = #stablehlo.channel_handle<handle = 1, type = 1>}> : (tensor<8xf32>) -> tensor<64xf32>
    %1 = "stablehlo.all_gather"(%arg0) <{all_gather_dim = 0 : i64, channel_handle = #stablehlo.channel_handle<handle = 2, type = 1>}> : (tensor<8xf32>) -> tensor<64xf32>
    %2 = stablehlo.add %0, %1 : tensor<64xf32>
    return %2 : tensor<64xf32>
  }
}
"""
    fids = [f.fid for f in passes.run_passes(
        ir.parse_module(text), passes.PlanContext(program="t"),
        passes=["dead-dup-collective"])]
    assert fids == ["dead-dup-collective/duplicate.all_gather"]
    # ...while genuinely different collectives (operands differ) on the
    # real recorded program stay clean
    real = ir.parse_module(_fixture("meshed_step_f32"))
    assert passes.run_passes(real, passes.PlanContext(program="t"),
                             passes=["dead-dup-collective"]) == []


def test_program_matrix_modules_preparsed():
    """Each matrix program is parsed exactly once: the Program carries
    its Module, and the driver runs passes on it directly."""
    progs = programs.program_matrix()
    for prog in progs:
        assert isinstance(prog.module, ir.Module)
        assert prog.module.source == prog.text


# ------------------------------------------------------ driver / matrix
def test_audit_driver_matrix_green_and_mutations_flag():
    """The acceptance gate run the way CI runs it: the full program
    matrix passes every applicable pass with an EMPTY allowlist, and
    every mutation fixture is flagged. (~15 s: one lowering per
    program, shared across passes.)"""
    import importlib.util as ilu
    spec = ilu.spec_from_file_location(
        "det_hlo_audit_t", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "hlo_audit.py"))
    ha = ilu.module_from_spec(spec)
    spec.loader.exec_module(ha)
    assert ha.load_baseline() == set()      # the healthy state: empty
    records, failures = ha.run_matrix(set())
    assert failures == [], failures
    assert {r["program"] for r in records} == {
        "monolithic_f32", "monolithic_bf16", "vocab_slack_step",
        "monolithic_tiled", "pallas_strategy_step",
        "lookahead_prefetch", "lookahead_fused", "serve_forward",
        "quantized_store_serve", "quantized_hbm_serve"}
    mrecords, mfailures = ha.run_mutations()
    assert mfailures == [], mfailures
    assert len(mrecords) == len(programs.mutation_cases())
