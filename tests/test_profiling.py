"""Timing utilities: fetch_sync contract + slope-based chained timing.

These became load-bearing in round 3: on the axon TPU tunnel,
block_until_ready returns early and unfetched work may never execute
(docs/round3_notes.md), so every benchmark in the repo routes through
fetch_sync / benchmark_chained. The tests pin the API contract on CPU.
"""

import numpy as np
import jax
import jax.numpy as jnp

from distributed_embeddings_tpu.utils import profiling


def test_fetch_sync_handles_leaf_zoo():
    out = {
        "f32": jnp.ones((4, 4)),
        "bf16": jnp.ones((2,), jnp.bfloat16),
        "int": jnp.arange(3),
        "bool": jnp.ones((2,), bool),          # fetched as 1.0
        "empty": jnp.zeros((0, 8)),            # skipped
        "scalar": jnp.float32(2.5),
        "none": None,                          # not an array leaf
    }
    total = profiling.fetch_sync(out)
    # 1.0 (f32[0]) + 1.0 (bf16[0]) + 0 (int[0]) + 1.0 (bool[0]) + 2.5
    assert abs(total - 5.5) < 1e-6


def test_fetch_sync_no_fetchable_leaves_still_syncs():
    # ADVICE r3: an output of only empty/non-array leaves must not silently
    # time dispatch-only; fetch_sync falls back to block_until_ready and
    # returns 0.0 without raising
    assert profiling.fetch_sync({"e": jnp.zeros((0,)), "n": None}) == 0.0
    assert profiling.fetch_sync(None) == 0.0


def test_benchmark_chained_measures_real_work():
    def step(s):
        x, acc = s
        y = x @ x
        return y / (jnp.max(jnp.abs(y)) + 1.0), acc + y[0, 0]

    x = jnp.asarray(np.random.RandomState(0).randn(128, 128),
                    dtype=jnp.float32)
    res = profiling.benchmark_chained(step, (x, jnp.float32(0)), iters=4)
    assert res.mean_s > 0
    assert res.compile_s > res.mean_s          # compile dominates tiny work
    assert np.isfinite(res.mean_s)


def test_benchmark_fetches_each_iteration():
    calls = []

    def fn(x):
        calls.append(1)
        return x + 1.0

    res = profiling.benchmark(fn, jnp.zeros((2, 2)), iters=3, warmup=1)
    assert len(calls) == 1 + 1 + 3             # compile + warmup + iters
    assert res.iters == 3 and res.min_s > 0
