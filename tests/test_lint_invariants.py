"""tools/lint_invariants.py: the Python-side AST lint (ISSUE 10).

Every rule is exercised by a violating fixture AND its allow-escape; the
final test runs the lint over the real package, which must be clean —
the same gate CI runs next to ruff.
"""

import importlib.util
import os

import pytest

_spec = importlib.util.spec_from_file_location(
    "det_lint_invariants", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "lint_invariants.py"))
lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(lint)


def _lint_src(tmp_path, src: str, rel: str):
    p = tmp_path / "fixture.py"
    p.write_text(src)
    return lint.lint_file(str(p), rel=rel)


PKG = "distributed_embeddings_tpu"


# ------------------------------------------------------ naked-collective
def test_naked_collective_flagged(tmp_path):
    src = (
        "from jax import lax\n"
        "import jax\n"
        "def f(x):\n"
        "    y = lax.all_to_all(x, 'mp', 0, 0)\n"
        "    z = jax.lax.all_gather(y, 'mp')\n"
        "    w = lax.psum_scatter(z, 'mp')\n"
        "    return lax.ppermute(w, 'mp', [(0, 1)])\n"
        "    # lax.psum is fine (accumulation, not an exchange)\n"
    )
    fs = _lint_src(tmp_path, src,
                   rel=os.path.join(PKG, "schedule", "other.py"))
    assert [f.rule for f in fs] == ["naked-collective"] * 4
    assert fs[0].line == 4


def test_naked_collective_allowed_in_wire_and_by_escape(tmp_path):
    src = ("from jax import lax\n"
           "def f(x):\n"
           "    return lax.all_to_all(x, 'mp', 0, 0)\n")
    # the seam module itself is exempt
    assert _lint_src(tmp_path, src,
                     rel=os.path.join(PKG, "ops", "wire.py")) == []
    escaped = ("from jax import lax\n"
               "def f(x):\n"
               "    # lint: allow(naked-collective)\n"
               "    return lax.all_to_all(x, 'mp', 0, 0)\n")
    assert _lint_src(tmp_path, escaped,
                     rel=os.path.join(PKG, "ops", "other.py")) == []
    same_line = ("from jax import lax\n"
                 "def f(x):\n"
                 "    return lax.all_to_all(x, 'mp', 0, 0)"
                 "  # lint: allow(naked-collective)\n")
    assert _lint_src(tmp_path, same_line,
                     rel=os.path.join(PKG, "ops", "other.py")) == []
    # psum / all_reduce-style accumulations are NOT exchange collectives
    psum = ("from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'mp')\n")
    assert _lint_src(tmp_path, psum,
                     rel=os.path.join(PKG, "ops", "other.py")) == []


def test_naked_collective_from_import_and_alias_forms(tmp_path):
    """from-imports and module aliases cannot evade the rule."""
    rel = os.path.join(PKG, "layers", "x.py")
    fi = ("from jax.lax import all_to_all\n"
          "def f(x):\n"
          "    return all_to_all(x, 'mp', 0, 0)\n")
    assert [f.rule for f in _lint_src(tmp_path, fi, rel=rel)] == \
        ["naked-collective"]
    aliased = ("from jax.lax import all_gather as ag\n"
               "def f(x):\n"
               "    return ag(x, 'mp')\n")
    assert [f.rule for f in _lint_src(tmp_path, aliased, rel=rel)] == \
        ["naked-collective"]
    mod_alias = ("import jax.lax as jl\n"
                 "def f(x):\n"
                 "    return jl.psum_scatter(x, 'mp')\n")
    assert [f.rule for f in _lint_src(tmp_path, mod_alias, rel=rel)] == \
        ["naked-collective"]
    from_jax = ("from jax import lax as l2\n"
                "def f(x):\n"
                "    return l2.ppermute(x, 'mp', [(0, 1)])\n")
    assert [f.rule for f in _lint_src(tmp_path, from_jax, rel=rel)] == \
        ["naked-collective"]
    # a NON-collective from jax.lax stays fine
    ok = ("from jax.lax import psum\n"
          "def f(x):\n"
          "    return psum(x, 'mp')\n")
    assert _lint_src(tmp_path, ok, rel=rel) == []
    # the ragged exchange op is an exchange collective too
    ragged = ("from jax import lax\n"
              "def f(x, o, a, b, c, d):\n"
              "    return lax.ragged_all_to_all(x, o, a, b, c, d,"
              " axis_name='mp')\n")
    assert [f.rule for f in _lint_src(tmp_path, ragged, rel=rel)] == \
        ["naked-collective"]


def test_wallclock_from_import_forms(tmp_path):
    rel = os.path.join(PKG, "ops", "x.py")
    fi = ("from time import time\n"
          "def f():\n"
          "    return time()\n")
    assert [f.rule for f in _lint_src(tmp_path, fi, rel=rel)] == \
        ["wallclock-in-jit"]
    dt = ("from datetime import datetime as dt\n"
          "def f():\n"
          "    return dt.now()\n")
    assert [f.rule for f in _lint_src(tmp_path, dt, rel=rel)] == \
        ["wallclock-in-jit"]
    # an unrelated object with a .time() method is NOT a wall clock
    ok = ("def f(profiler):\n"
          "    return profiler.time()\n")
    assert _lint_src(tmp_path, ok, rel=rel) == []


# ----------------------------------------------------- hot-params-access
def test_hot_params_access_flagged(tmp_path):
    src = ("def f(params):\n"
           "    return params['hot'][0]\n")
    fs = _lint_src(tmp_path, src,
                   rel=os.path.join(PKG, "utils", "other.py"))
    assert [f.rule for f in fs] == ["hot-params-access"]


def test_hot_params_access_owners_and_escape(tmp_path):
    src = ("def f(params):\n"
           "    return params['hot']\n")
    for owner in (os.path.join(PKG, "layers", "dist_model_parallel.py"),
                  os.path.join(PKG, "ops", "sparse_update.py")):
        assert _lint_src(tmp_path, src, rel=owner) == []
    escaped = ("def f(params):\n"
               "    return params['hot']  # lint: allow(hot-params-access)\n")
    assert _lint_src(tmp_path, escaped,
                     rel=os.path.join(PKG, "serving", "engine.py")) == []
    # a docstring MENTIONING params['hot'] is not an access
    doc = '"""docs about params["hot"] live here"""\n'
    assert _lint_src(tmp_path, doc,
                     rel=os.path.join(PKG, "utils", "checkpoint.py")) == []


# ------------------------------------------------------ wallclock-in-jit
def test_wallclock_in_jit_flagged(tmp_path):
    src = ("import time, datetime\n"
           "def f():\n"
           "    t = time.time()\n"
           "    d = datetime.datetime.now()\n"
           "    return t, d\n")
    fs = _lint_src(tmp_path, src,
                   rel=os.path.join(PKG, "ops", "fancy_kernel.py"))
    assert [f.rule for f in fs] == ["wallclock-in-jit"] * 2


def test_wallclock_outside_jit_modules_ok(tmp_path):
    src = ("import time\n"
           "def f():\n"
           "    return time.time()\n")
    # store/ and utils/ are host-side: publish timestamps etc. are fine
    for rel in (os.path.join(PKG, "store", "table_store.py"),
                os.path.join(PKG, "utils", "metrics.py"),
                os.path.join("tools", "some_tool.py")):
        assert _lint_src(tmp_path, src, rel=rel) == []
    escaped = ("import time\n"
               "def f():\n"
               "    return time.time()  # lint: allow(wallclock-in-jit)\n")
    assert _lint_src(tmp_path, escaped,
                     rel=os.path.join(PKG, "parallel", "staging.py")) == []


# ------------------------------------------------------------- plumbing
# ---------------------------------------------------- shadow-metric
def test_shadow_metric_flagged_outside_obs(tmp_path):
    src = (
        "from distributed_embeddings_tpu.utils.metrics import "
        "LatencyHistogram\n"
        "from distributed_embeddings_tpu.obs import registry as r\n"
        "from collections import Counter\n"
        "h = LatencyHistogram()\n"
        "c = r.Counter('x', {})\n"
        "g = r.Gauge('y', {})\n"
        "ok = Counter([1, 2])\n"          # collections.Counter untouched
    )
    fs = _lint_src(tmp_path, src,
                   rel=os.path.join(PKG, "serving", "other.py"))
    assert [f.rule for f in fs] == ["shadow-metric"] * 3
    assert [f.line for f in fs] == [4, 5, 6]


def test_shadow_metric_alias_and_deep_import_forms(tmp_path):
    src = (
        "from distributed_embeddings_tpu.obs.registry import "
        "LatencyHistogram as LH\n"
        "import distributed_embeddings_tpu.obs.registry as reg\n"
        "a = LH()\n"
        "b = reg.Gauge('g', {})\n"
    )
    fs = _lint_src(tmp_path, src,
                   rel=os.path.join(PKG, "store", "other.py"))
    assert [f.rule for f in fs] == ["shadow-metric"] * 2


def test_shadow_metric_allowed_in_obs_and_by_escape(tmp_path):
    src = (
        "from distributed_embeddings_tpu.utils.metrics import "
        "LatencyHistogram\n"
        "h = LatencyHistogram()\n"
    )
    # anywhere under obs/ is the sanctioned construction home
    assert _lint_src(tmp_path, src,
                     rel=os.path.join(PKG, "obs", "registry.py")) == []
    assert _lint_src(tmp_path, src,
                     rel=os.path.join(PKG, "obs", "spans.py")) == []
    escaped = (
        "from distributed_embeddings_tpu.utils.metrics import "
        "LatencyHistogram\n"
        "h = LatencyHistogram()  # lint: allow(shadow-metric)\n"
    )
    assert _lint_src(tmp_path, escaped,
                     rel=os.path.join(PKG, "serving", "other.py")) == []
    # registry USE is exactly what the rule steers toward: never flagged
    use = (
        "def f(reg):\n"
        "    reg.histogram('serve/request_seconds').record(0.01)\n"
        "    reg.counter('n').inc()\n"
    )
    assert _lint_src(tmp_path, use,
                     rel=os.path.join(PKG, "serving", "other.py")) == []


def test_syntax_error_reported_not_raised(tmp_path):
    fs = _lint_src(tmp_path, "def broken(:\n",
                   rel=os.path.join(PKG, "ops", "x.py"))
    assert [f.rule for f in fs] == ["parse-error"]


def test_multi_rule_escape(tmp_path):
    src = ("from jax import lax\n"
           "import time\n"
           "def f(x, params):\n"
           "    # lint: allow(naked-collective, wallclock-in-jit)\n"
           "    return lax.all_gather(x, 'mp'), time.time()\n")
    assert _lint_src(tmp_path, src,
                     rel=os.path.join(PKG, "layers", "x.py")) == []


def test_finding_str_and_json_shape(tmp_path):
    fs = _lint_src(tmp_path, "import time\nt = time.time()\n",
                   rel=os.path.join(PKG, "ops", "x.py"))
    d = fs[0].to_dict()
    assert set(d) == {"rule", "path", "line", "message"}
    assert "wallclock-in-jit" in str(fs[0])


def test_repo_package_is_clean():
    """The gate itself: the shipped package has zero violations (every
    exchange collective behind ops/wire.py, hot-shard access confined
    to its two owners, no wall clocks in jitted modules)."""
    findings = []
    for path in lint.default_files():
        findings.extend(lint.lint_file(path))
    assert findings == [], [str(f) for f in findings]


def test_cli_exit_codes(tmp_path):
    assert lint.main([]) == 0            # the package is clean
    bad = tmp_path / "bad.py"
    bad.write_text("from jax import lax\ny = lax.all_gather(1, 'mp')\n")
    assert lint.main([str(bad)]) == 1
