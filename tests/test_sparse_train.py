"""Sparse (tapped) train step vs dense optax train step: full equivalence.

The reference's contract: training through its sparse backward + IndexedSlices
optimizer apply equals dense-gradient training (reference tests compare
post-optimizer weights, dist_model_parallel_test.py:280-291). Here: the tapped
sparse path (make_sparse_train_step) must reproduce the dense optax path's
losses and final weights on the same model, across optimizers, parallelism
modes and combiners — on the 8-virtual-CPU mesh.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.training import make_sparse_train_step

BATCH = 16


class TinyModel:
    """Embeddings -> concat -> linear head; the minimal model shape
    make_sparse_train_step expects (.embedding + params['embedding'])."""

    def __init__(self, specs, mesh, input_table_map=None, **kw):
        self.embedding = DistributedEmbedding(
            [Embedding(v, w, combiner=(s[2] if len(s) > 2 else None))
             for s, (v, w) in zip(specs, [(s[0], s[1]) for s in specs])],
            mesh=mesh, input_table_map=input_table_map, **kw)
        self.specs = specs

    def init_head(self, key, n_outputs, widths):
        return {"w": jax.random.normal(key, (sum(widths), 1)) * 0.1}

    def apply(self, params, numerical, cats, taps=None,
              return_residuals=False):
        res = None
        if taps is not None or return_residuals:
            outs, res = self.embedding(params["embedding"], list(cats),
                                       taps=taps, return_residuals=True)
        else:
            outs = self.embedding(params["embedding"], list(cats))
        outs = [o.reshape(o.shape[0], -1) for o in outs]
        x = jnp.concatenate(outs, axis=1).astype(jnp.float32)
        out = x @ params["head"]["w"]
        return (out, res) if return_residuals else out

    def loss_fn(self, params, numerical, cats, labels, taps=None,
                return_residuals=False):
        out = self.apply(params, numerical, cats, taps=taps,
                         return_residuals=return_residuals)
        logits, res = out if return_residuals else (out, None)
        loss = jnp.mean((logits[:, 0] - labels.reshape(-1)) ** 2)
        return (loss, res) if return_residuals else loss


def run_equivalence(specs, optimizer, input_table_map=None, steps=3,
                    strategy="sort", seed=0, lr=0.05, rtol=5e-5, atol=5e-5,
                    inputs_fn=None, placement=None, **dist_kwargs):
    # `strategy` is the sparse DEDUP strategy; `placement` (if given) is the
    # planner strategy, forwarded as DistributedEmbedding(strategy=...)
    if placement is not None:
        dist_kwargs["strategy"] = placement
    rng = np.random.RandomState(seed)
    mesh = create_mesh(jax.devices()[:8])
    table_map = (list(input_table_map) if input_table_map
                 else list(range(len(specs))))

    def build():
        return TinyModel(specs, mesh, input_table_map=input_table_map,
                         **dist_kwargs)

    model = build()
    weights = [rng.randn(s[0], s[1]).astype(np.float32) * 0.1 for s in specs]
    emb_params = model.embedding.set_weights(weights)
    widths = []
    for i, t in enumerate(table_map):
        s = specs[t]
        k = 2 + (i % 3)
        widths.append(s[1] * (k if len(s) > 2 and s[2] is None else 1)
                      if False else s[1])
    # widths: combiner None multihot flattens; keep hotness-1 for None tables
    head = {"w": jnp.asarray(rng.randn(sum(widths), 1).astype(np.float32))}
    params = {"embedding": emb_params, "head": head}

    batches = []
    for _ in range(steps):
        cats = []
        for i, t in enumerate(table_map):
            s = specs[t]
            comb = s[2] if len(s) > 2 else None
            if inputs_fn is not None:
                cats.append(inputs_fn(rng, i, s))
            elif comb is None:
                cats.append(jnp.asarray(rng.randint(0, s[0], size=(BATCH,))))
            else:
                cats.append(jnp.asarray(
                    rng.randint(0, s[0], size=(BATCH, 2 + (i % 3)))))
        labels = jnp.asarray(rng.randn(BATCH).astype(np.float32))
        batches.append((jnp.zeros((BATCH, 1)), cats, labels))

    # --- dense reference: plain value_and_grad + optax over everything
    dense_opt = {"sgd": optax.sgd(lr), "adagrad": optax.adagrad(lr),
                 "adam": optax.adam(lr)}[optimizer]
    dparams = jax.tree.map(lambda x: x, params)
    dstate = dense_opt.init(dparams)
    dlosses = []
    for num, cats, labels in batches:
        loss, grads = jax.value_and_grad(model.loss_fn)(dparams, num, cats,
                                                        labels)
        upd, dstate = dense_opt.update(grads, dstate, dparams)
        dparams = optax.apply_updates(dparams, upd)
        dlosses.append(float(loss))

    # --- sparse tapped path
    model2 = build()
    init_fn, step_fn = make_sparse_train_step(model2, optimizer, lr=lr,
                                              strategy=strategy)
    sparams = {"embedding": model2.embedding.set_weights(weights),
               "head": jax.tree.map(lambda x: x, head)}
    sstate = init_fn(sparams)
    slosses = []
    for num, cats, labels in batches:
        sparams, sstate, loss = step_fn(sparams, sstate, num, cats, labels)
        slosses.append(float(loss))

    np.testing.assert_allclose(slosses, dlosses, rtol=1e-4, atol=1e-5)
    got = model2.embedding.get_weights(sparams["embedding"])
    want = model.embedding.get_weights(dparams["embedding"])
    for t, (a, b) in enumerate(zip(want, got)):
        np.testing.assert_allclose(b, a, rtol=rtol, atol=atol,
                                   err_msg=f"table {t} (opt={optimizer})")
    np.testing.assert_allclose(np.asarray(sparams["head"]["w"]),
                               np.asarray(dparams["head"]["w"]),
                               rtol=rtol, atol=atol)


SPECS_BASIC = [(40, 4), (60, 8), (30, 4), (50, 8), (25, 4), (70, 8),
               (45, 4), (35, 8)]


@pytest.mark.parametrize("optimizer", ["sgd", "adagrad"])
def test_sparse_train_basic(optimizer):
    run_equivalence(SPECS_BASIC, optimizer)


def test_sparse_train_adam_full_coverage():
    """Lazy sparse Adam == dense Adam only when every row is touched every
    step (untouched-row momentum decay is skipped by design — the standard
    sparse-Adam compromise). Cover every row each batch."""
    specs = [(8, 4, "sum"), (12, 8, "sum"), (6, 4, "sum"), (10, 8, "sum"),
             (8, 4, "sum"), (12, 8, "sum"), (8, 4, "sum"), (8, 8, "sum")]

    def inputs_fn(rng, i, s):
        v = s[0]
        k = max(2, -(-v // BATCH) + 1)
        ids = np.concatenate([np.arange(v), rng.randint(0, v, BATCH * k - v)])
        rng.shuffle(ids)
        return jnp.asarray(ids.reshape(BATCH, k))

    run_equivalence(specs, "adam", inputs_fn=inputs_fn, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("strategy", ["sort", "dense"])
def test_sparse_train_strategies(strategy):
    run_equivalence(SPECS_BASIC, "adagrad", strategy=strategy)


# execution-bound on the single-core CPU test host (see
# .claude/skills/verify/SKILL.md): runs in the `-m slow` tier so the
# not-slow tier-1 sweep completes inside its time budget
@pytest.mark.slow
def test_sparse_train_multihot_combiners():
    specs = [(40, 4, "sum"), (60, 8, "mean"), (30, 4, "sum"), (50, 8, "mean"),
             (25, 4, "sum"), (70, 8, "sum"), (45, 4, "mean"), (35, 8, "sum")]
    run_equivalence(specs, "adagrad")


def test_sparse_train_shared_tables():
    specs = [(40, 4, "sum"), (60, 8, "sum"), (30, 4, "sum"), (50, 8, "sum"),
             (25, 4, "sum"), (70, 8, "sum"), (45, 4, "sum"), (35, 8, "sum")]
    run_equivalence(specs, "adagrad",
                    input_table_map=[0, 1, 2, 3, 4, 5, 6, 7, 0, 3])


def test_sparse_train_row_slice():
    specs = [(512, 8, "sum"), (40, 8, "sum"), (300, 8, "mean"), (64, 8, "sum"),
             (128, 8, "sum"), (96, 8, "sum"), (80, 8, "sum"), (72, 8, "sum")]
    run_equivalence(specs, "adagrad", row_slice_threshold=2000, rtol=2e-4,
                    atol=2e-4)


# execution-bound on the single-core CPU test host (see
# .claude/skills/verify/SKILL.md): runs in the `-m slow` tier so the
# not-slow tier-1 sweep completes inside its time budget
@pytest.mark.slow
def test_sparse_train_hybrid_dp_col_row():
    specs = [(512, 8, "sum"), (300, 8, "sum"), (8, 4), (6, 4),
             (100, 8, "sum"), (90, 8, "sum"), (80, 8, "sum"), (70, 8, "sum"),
             (60, 8, "sum"), (50, 8, "sum")]
    run_equivalence(specs, "adagrad", row_slice_threshold=2000,
                    data_parallel_threshold=64, rtol=2e-4, atol=2e-4)


def test_sparse_train_mp_input_matches_dp():
    """dp_input=False sparse training == dp_input=True sparse training on
    the same global data (the mp loader just pre-shards by feature)."""
    specs = [(40, 4, "sum"), (60, 8, "sum"), (30, 4, "sum"), (50, 8, "sum"),
             (25, 4, "sum"), (70, 8, "sum"), (45, 4, "sum"), (35, 8, "sum")]
    rng = np.random.RandomState(11)
    mesh = create_mesh(jax.devices()[:8])
    weights = [rng.randn(s[0], s[1]).astype(np.float32) * 0.1 for s in specs]
    batches = []
    for _ in range(3):
        cats = [jnp.asarray(rng.randint(0, s[0], size=(BATCH, 2)))
                for s in specs]
        labels = jnp.asarray(rng.randn(BATCH).astype(np.float32))
        batches.append((cats, labels))

    results = []
    for dp_input in (True, False):
        model = TinyModel(specs, mesh, dp_input=dp_input)
        strat = model.embedding.strategy

        def to_inputs(cats, dp=dp_input):
            if dp:
                return cats
            return [[cats[strat.input_groups[1][pos]] for pos in rank_ids]
                    for rank_ids in strat.input_ids_list]

        init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=0.05,
                                                  strategy="sort")
        params = {"embedding": model.embedding.set_weights(weights),
                  "head": {"w": jnp.asarray(np.random.RandomState(7).randn(
                      sum(s[1] for s in specs), 1).astype(np.float32))}}
        state = init_fn(params)
        losses = []
        for cats, labels in batches:
            params, state, loss = step_fn(params, state,
                                          jnp.zeros((BATCH, 1)),
                                          to_inputs(cats), labels)
            losses.append(float(loss))
        results.append((losses,
                        model.embedding.get_weights(params["embedding"])))

    (l_dp, w_dp), (l_mp, w_mp) = results
    np.testing.assert_allclose(l_mp, l_dp, rtol=1e-5, atol=1e-6)
    for t, (a, b) in enumerate(zip(w_dp, w_mp)):
        np.testing.assert_allclose(b, a, rtol=2e-5, atol=2e-5,
                                   err_msg=f"table {t}")


def test_sparse_train_ragged_inputs():
    """RaggedIds flow through make_taps / residuals / sparse updates (the
    padded slots must contribute nothing)."""
    from distributed_embeddings_tpu.ops.embedding_ops import RaggedIds

    rng_r = np.random.RandomState(55)

    def inputs_fn(rng, i, s):
        lengths = rng_r.randint(1, 5, size=BATCH)
        values = rng_r.randint(0, s[0], size=int(lengths.sum()))
        splits = np.cumsum([0] + list(lengths)).astype(np.int32)
        return RaggedIds(jnp.asarray(values.astype(np.int32)),
                         jnp.asarray(splits))

    specs = [(40, 4, "sum"), (60, 8, "mean"), (30, 4, "sum"), (50, 8, "sum"),
             (25, 4, "sum"), (70, 8, "mean"), (45, 4, "sum"), (35, 8, "sum")]
    run_equivalence(specs, "adagrad", inputs_fn=inputs_fn,
                    input_max_hotness=[6] * 8)


# execution-bound on the single-core CPU test host (see
# .claude/skills/verify/SKILL.md): runs in the `-m slow` tier so the
# not-slow tier-1 sweep completes inside its time budget
@pytest.mark.slow
def test_sparse_train_weighted_inputs():
    rng_w = np.random.RandomState(99)

    def inputs_fn(rng, i, s):
        k = 2 + (i % 3)
        ids = jnp.asarray(rng.randint(0, s[0], size=(BATCH, k)))
        w = jnp.asarray(np.abs(rng_w.rand(BATCH, k)).astype(np.float32))
        return (ids, w)

    specs = [(40, 4, "sum"), (60, 8, "mean"), (30, 4, "sum"), (50, 8, "mean"),
             (25, 4, "sum"), (70, 8, "sum"), (45, 4, "sum"), (35, 8, "mean")]
    run_equivalence(specs, "adagrad", inputs_fn=inputs_fn)


def test_sparse_step_hlo_scatter_promises(monkeypatch):
    """The lowered train step must carry the scatter promises the round-3
    hardware data demands (XLA's duplicate-safe scatter measured at
    100-280 ns/row): both row-update scatters say unique_indices=true, and
    the cumsum dedup impl removes the segment-sum + rep-build scatters
    (2 fewer stablehlo.scatter ops per bucket)."""
    import re
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.training import make_sparse_train_step

    class _Tiny:
        def __init__(self, emb):
            self.embedding = emb

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            out = self.embedding(p["embedding"], list(cats), taps=taps,
                                 return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    def lower_text():
        # big-vocab single bucket so the auto strategy takes the sort path;
        # abstract avals only — lowering needs shapes, not a 1 GiB table
        emb = DistributedEmbedding([Embedding(30_000_000, 8)], mesh=None)
        model = _Tiny(emb)
        init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=0.01)
        params = jax.eval_shape(
            lambda: {"embedding": emb.init(jax.random.PRNGKey(0))})
        state = jax.eval_shape(init_fn, params)
        num = jax.ShapeDtypeStruct((8, 1), jnp.float32)
        cats = [jax.ShapeDtypeStruct((8,), jnp.int32)]
        lab = jax.ShapeDtypeStruct((8,), jnp.float32)
        return jax.jit(step_fn).lower(params, state, num, cats, lab).as_text()

    monkeypatch.setenv("DET_DEDUP_IMPL", "sort")
    txt_sort = lower_text()
    n_scatter_sort = len(re.findall(r'"stablehlo.scatter"', txt_sort))
    assert len(re.findall(r"unique_indices\s*=\s*true", txt_sort)) >= 2

    monkeypatch.setenv("DET_DEDUP_IMPL", "cumsum")
    txt_cs = lower_text()
    n_scatter_cs = len(re.findall(r'"stablehlo.scatter"', txt_cs))
    assert len(re.findall(r"unique_indices\s*=\s*true", txt_cs)) >= 2
    assert n_scatter_cs <= n_scatter_sort - 2, (
        f"cumsum impl should drop >=2 scatters: {n_scatter_sort} -> "
        f"{n_scatter_cs}")
