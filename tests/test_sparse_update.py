"""Sparse row-wise optimizer updates vs dense reference (optax formulas).

The contract (reference: IndexedSlices consumption of the grad kernel's
(unique_ids, unique_grads) output, embedding_lookup_ops.py:105-122): a sparse
update with per-contribution (ids, rows) must equal the dense update with the
scatter-added dense gradient, on every strategy.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu.ops import sparse_update as su


def make_case(rng, n=257, v=50, w=8, oob=False):
    ids = rng.integers(0, v, size=(n,)).astype(np.int32)
    contribs = rng.standard_normal((n, w)).astype(np.float32)
    if oob:
        # padded slots: id == v with zero rows must be dropped
        ids[::7] = v
        contribs[::7] = 0.0
    dense = np.zeros((v, w), np.float32)
    np.add.at(dense, ids[ids < v], contribs[ids < v])
    return ids, contribs, dense


def test_dedup_sum_exact():
    rng = np.random.default_rng(0)
    ids, contribs, dense = make_case(rng)
    rep, sums = su.dedup_sum(jnp.asarray(ids), jnp.asarray(contribs),
                             sentinel=50)
    rep, sums = np.asarray(rep), np.asarray(sums)
    got = np.zeros_like(dense)
    for r, s in zip(rep, sums):
        if r < 50:
            got[r] += s
    np.testing.assert_allclose(got, dense, rtol=1e-6, atol=1e-6)
    # each id appears exactly once among rep
    real = rep[rep < 50]
    assert len(real) == len(set(real.tolist()))
    # promise contract: rep must be strictly increasing (unique AND sorted —
    # downstream scatters assert these to XLA; see dedup_flags)
    assert (np.diff(rep.astype(np.int64)) > 0).all()


def test_dedup_sum_cumsum_impl(monkeypatch):
    """DET_DEDUP_IMPL=cumsum: scatter-free aggregation must match the exact
    sort impl to f32-cumsum tolerance, keep rep unique, and drop OOB."""
    monkeypatch.setenv("DET_DEDUP_IMPL", "cumsum")
    rng = np.random.default_rng(3)
    for oob in (False, True):
        ids, contribs, dense = make_case(rng, n=1023, oob=oob)
        rep, sums = su.dedup_sum(jnp.asarray(ids), jnp.asarray(contribs),
                                 sentinel=50)
        rep, sums = np.asarray(rep), np.asarray(sums)
        got = np.zeros_like(dense)
        for r, s in zip(rep, sums):
            if r < 50:
                got[r] += s
        np.testing.assert_allclose(got, dense, rtol=1e-4, atol=1e-4)
        assert len(rep) == len(set(rep.tolist()))   # unique incl. fillers
        flags = su.dedup_flags()
        assert flags["unique_indices"] and not flags["indices_are_sorted"]


@pytest.mark.parametrize("kind", ["adagrad", "adam"])
def test_sparse_update_cumsum_impl_matches_sort(monkeypatch, kind):
    """Full row-wise update under the cumsum dedup impl == sort impl to
    tolerance (the opt-in trades exactness for scatter-free aggregation)."""
    rng = np.random.default_rng(4)
    ids, contribs, _ = make_case(rng, n=511, oob=True)
    table = rng.standard_normal((50, 8)).astype(np.float32)
    g = su.SparseRowGrad(jnp.asarray(ids), jnp.asarray(contribs))

    def run():
        if kind == "adagrad":
            t, acc = su.sparse_adagrad(
                jnp.asarray(table), jnp.full((50, 8), 0.1, jnp.float32), g,
                0.05, strategy="sort")
            return np.asarray(t), np.asarray(acc)
        t, mu, nu, c = su.sparse_adam(
            jnp.asarray(table), jnp.zeros((50, 8), jnp.float32),
            jnp.zeros((50, 8), jnp.float32), jnp.zeros((), jnp.int32), g,
            0.05, strategy="sort")
        return np.asarray(t), np.asarray(mu), np.asarray(nu)

    monkeypatch.setenv("DET_DEDUP_IMPL", "sort")
    want = run()
    monkeypatch.setenv("DET_DEDUP_IMPL", "cumsum")
    got = run()
    for a, b in zip(want, got):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("strategy", ["sort", "dense"])
@pytest.mark.parametrize("oob", [False, True])
def test_sparse_adagrad_matches_optax(strategy, oob):
    rng = np.random.default_rng(1)
    ids, contribs, dense = make_case(rng, oob=oob)
    table = rng.standard_normal((50, 8)).astype(np.float32)
    lr, eps, acc0 = 0.05, 1e-7, 0.1

    opt = optax.adagrad(lr, initial_accumulator_value=acc0, eps=eps)
    state = opt.init(jnp.asarray(table))
    upd, _ = opt.update(jnp.asarray(dense), state, jnp.asarray(table))
    want = np.asarray(jnp.asarray(table) + upd)

    t2, acc2 = su.sparse_adagrad(
        jnp.asarray(table), jnp.full((50, 8), acc0, jnp.float32),
        su.SparseRowGrad(jnp.asarray(ids), jnp.asarray(contribs)),
        lr, eps=eps, strategy=strategy)
    np.testing.assert_allclose(np.asarray(t2), want, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(acc2), acc0 + dense * dense,
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("oob", [False, True])
def test_sparse_sgd_matches_dense(oob):
    rng = np.random.default_rng(2)
    ids, contribs, dense = make_case(rng, oob=oob)
    table = rng.standard_normal((50, 8)).astype(np.float32)
    got = su.sparse_sgd(jnp.asarray(table),
                        su.SparseRowGrad(jnp.asarray(ids),
                                         jnp.asarray(contribs)), 0.1)
    np.testing.assert_allclose(np.asarray(got), table - 0.1 * dense,
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("strategy", ["sort", "dense"])
def test_sparse_adam_touched_rows_match_optax(strategy):
    """Lazy sparse Adam == dense Adam on rows where the dense grad is
    nonzero, over multiple steps with every row touched."""
    rng = np.random.default_rng(3)
    v, w = 30, 4
    table = rng.standard_normal((v, w)).astype(np.float32)
    lr = 0.01
    opt = optax.adam(lr)
    dstate = opt.init(jnp.asarray(table))
    dtable = jnp.asarray(table)

    sopt = su.make_sparse_optimizer("adam", lr, strategy=strategy)
    stable = jnp.asarray(table)
    sstate = sopt.init(stable)

    for step in range(3):
        # every row touched (ids = permutation + extras) so lazy == dense
        ids = np.concatenate([rng.permutation(v),
                              rng.integers(0, v, 17)]).astype(np.int32)
        contribs = rng.standard_normal((len(ids), w)).astype(np.float32)
        dense = np.zeros((v, w), np.float32)
        np.add.at(dense, ids, contribs)

        upd, dstate = opt.update(jnp.asarray(dense), dstate, dtable)
        dtable = dtable + upd
        stable, sstate = sopt.update(
            stable, sstate, su.SparseRowGrad(jnp.asarray(ids),
                                             jnp.asarray(contribs)))
        np.testing.assert_allclose(np.asarray(stable), np.asarray(dtable),
                                   rtol=3e-5, atol=3e-5,
                                   err_msg=f"step {step}")


def test_sparse_adagrad_untouched_rows_unchanged():
    rng = np.random.default_rng(4)
    table = rng.standard_normal((50, 8)).astype(np.float32)
    ids = jnp.asarray([3, 3, 7], jnp.int32)
    contribs = jnp.asarray(rng.standard_normal((3, 8)), jnp.float32)
    for strategy in ("sort", "dense"):
        t2, _ = su.sparse_adagrad(
            jnp.asarray(table), jnp.full((50, 8), 0.1, jnp.float32),
            su.SparseRowGrad(ids, contribs), 0.1, strategy=strategy)
        t2 = np.asarray(t2)
        mask = np.ones(50, bool)
        mask[[3, 7]] = False
        np.testing.assert_array_equal(t2[mask], table[mask])
        assert not np.allclose(t2[3], table[3])


def test_concat_grads_and_jit():
    rng = np.random.default_rng(5)
    g1 = su.SparseRowGrad(jnp.asarray(rng.integers(0, 20, 10), jnp.int32),
                          jnp.asarray(rng.standard_normal((10, 4)),
                                      jnp.float32))
    g2 = su.SparseRowGrad(jnp.asarray(rng.integers(0, 20, 6), jnp.int32),
                          jnp.asarray(rng.standard_normal((6, 4)),
                                      jnp.float32))
    g = su.concat_grads([g1, g2])
    assert g.ids.shape == (16,) and g.contribs.shape == (16, 4)

    table = jnp.asarray(rng.standard_normal((20, 4)), jnp.float32)
    acc = jnp.full((20, 4), 0.1, jnp.float32)
    f = jax.jit(lambda t, a, i, c: su.sparse_adagrad(
        t, a, su.SparseRowGrad(i, c), 0.1, strategy="sort"))
    t2, a2 = f(table, acc, g.ids, g.contribs)
    t3, a3 = su.sparse_adagrad(table, acc, g, 0.1, strategy="dense")
    np.testing.assert_allclose(np.asarray(t2), np.asarray(t3), rtol=2e-5,
                               atol=2e-5)


def test_scatter_impl_pallas_ignored_off_tpu(monkeypatch):
    """DET_SCATTER_IMPL=pallas must be inert off-TPU (CPU tests and CPU
    meshes take the XLA scatter unconditionally)."""
    monkeypatch.setenv("DET_SCATTER_IMPL", "pallas")
    rng = np.random.default_rng(7)
    ids, contribs, _ = make_case(rng, n=129)
    table = rng.standard_normal((50, 8)).astype(np.float32)
    g = su.SparseRowGrad(jnp.asarray(ids), jnp.asarray(contribs))
    t1, a1 = su.sparse_adagrad(jnp.asarray(table),
                               jnp.full((50, 8), 0.1, jnp.float32), g, 0.05,
                               strategy="sort")
    monkeypatch.delenv("DET_SCATTER_IMPL")
    t2, a2 = su.sparse_adagrad(jnp.asarray(table),
                               jnp.full((50, 8), 0.1, jnp.float32), g, 0.05,
                               strategy="sort")
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))


def test_sparse_adagrad_traced_lr(monkeypatch):
    """lr as a traced value (schedule through jit args) must work on every
    path — the Pallas fused kernel needs static lr, so the dispatch falls
    back rather than crashing (review finding r03)."""
    monkeypatch.setenv("DET_SCATTER_IMPL", "pallas")
    rng = np.random.default_rng(13)
    ids, contribs, _ = make_case(rng, n=129)
    table = rng.standard_normal((50, 8)).astype(np.float32)
    g = su.SparseRowGrad(jnp.asarray(ids), jnp.asarray(contribs))

    @jax.jit
    def step(t, acc, lr):
        return su.sparse_adagrad(t, acc, g, lr, strategy="sort")

    t2, a2 = step(jnp.asarray(table), jnp.full((50, 8), 0.1, jnp.float32),
                  jnp.float32(0.05))
    want_t, want_a = su.sparse_adagrad(
        jnp.asarray(table), jnp.full((50, 8), 0.1, jnp.float32), g, 0.05,
        strategy="sort")
    np.testing.assert_allclose(np.asarray(t2), np.asarray(want_t),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(a2), np.asarray(want_a),
                               rtol=1e-6, atol=1e-6)
