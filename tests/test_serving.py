"""Serving subsystem: engine parity, HBM hot-row cache, refresh contract.

Acceptance contract (ISSUE 1): (a) `InferenceEngine.predict` is numerically
identical to the training forward (no optimizer state, taps disabled);
(b) zipfian traffic over an offloaded bucket serves bit-exact through the
hot-row cache with a >50% hit rate; (c) after a sparse train step mutates
an offloaded table, `refresh()` restores bit-exact serving; (d) the
`bench.py --mode serve` benchmark runs on CPU and emits throughput,
hit-rate and latency-percentile fields.
"""

import json
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.fleet import AdmissionController
from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.obs import MetricRegistry
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.serving import (HotRowCache, InferenceEngine,
                                                MicroBatcher)
from distributed_embeddings_tpu.training import make_sparse_train_step
from distributed_embeddings_tpu.utils.metrics import LatencyHistogram

from test_sparse_train import TinyModel, BATCH

# same plan as tests/test_offload.py: one fused width-16 bucket whose two
# 5000-row tables blow the budget -> the whole bucket host-offloads
SPECS = [(5000, 16, "sum"), (40, 16, "sum"), (5000, 16, "sum"),
         (64, 16, "sum"), (128, 16, "sum"), (96, 16, "sum"),
         (80, 16, "sum"), (72, 16, "sum")]
BUDGET = 2500 * 16


def _zipf(rng, vocab, n, alpha=1.5):
    ranks = np.arange(1, vocab + 1, dtype=np.float64)
    p = ranks ** -alpha
    p /= p.sum()
    return rng.choice(vocab, size=n, p=p).astype(np.int32)


def _build_offloaded(mesh, **kw):
    dist = DistributedEmbedding(
        [Embedding(v, w, combiner=c) for v, w, c in SPECS], mesh=mesh,
        gpu_embedding_size=BUDGET, **kw)
    assert dist._offload_enabled
    assert any(b.offload for b in dist.plan.tp_buckets)
    return dist


@pytest.fixture(scope="module")
def std_dist():
    """One offloaded layer + weights shared by the engine tests (engines
    and caches are per-test; the layer itself is stateless per forward)."""
    rng = np.random.RandomState(1)
    mesh = create_mesh(jax.devices()[:8])
    dist = _build_offloaded(mesh)
    params = dist.set_weights(
        [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS])
    return dist, params


def test_engine_matches_training_forward():
    """(a) apply-only predict == the tapped training forward's outputs —
    same numerics with optimizer state stripped and taps disabled."""
    rng = np.random.RandomState(0)
    mesh = create_mesh(jax.devices()[:8])
    model = TinyModel(SPECS, mesh, gpu_embedding_size=BUDGET)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]
    params = {"embedding": model.embedding.set_weights(weights),
              "head": {"w": jnp.asarray(np.random.RandomState(7).randn(
                  sum(w for _, w, _ in SPECS), 1).astype(np.float32))}}

    # the engine strips a checkpoint-shaped {"params", "opt_state"} dict
    engine = InferenceEngine(model, {"params": params, "opt_state": {"x": 1}},
                             cache_capacity=0)
    assert engine.params is params

    numerical = np.zeros((BATCH, 1), np.float32)
    cats = [rng.randint(0, v, size=(BATCH,)).astype(np.int32)
            for v, _, _ in SPECS]
    got = np.asarray(engine.predict((numerical, cats)))

    # tapless reference forward, jitted like every training-path forward
    # (an eager CPU matmul fuses differently at the 1e-7 level)
    want = np.asarray(jax.jit(
        lambda p, n, c: model.apply(p, n, c))(
            params, jnp.asarray(numerical),
            [jnp.asarray(c) for c in cats]))
    np.testing.assert_array_equal(got, want)

    # and the TRAINING forward (zero taps + residual export) — identical
    taps = model.embedding.make_taps([jnp.asarray(c) for c in cats])
    tapped, _ = model.apply(params, jnp.asarray(numerical),
                            [jnp.asarray(c) for c in cats], taps=taps,
                            return_residuals=True)
    np.testing.assert_allclose(got, np.asarray(tapped), rtol=1e-6, atol=1e-7)


def test_cached_lookups_bitmatch_and_hit_rate(std_dist):
    """(b) zipfian stream over the offloaded bucket: cached lookups
    bit-match the uncached host path batch for batch, and the cumulative
    hit rate (cold start included) crosses 50%."""
    rng = np.random.RandomState(1)
    dist, params = std_dist

    engine = InferenceEngine(dist, params, cache_capacity=1024,
                             promote_threshold=1)
    engine.warmup([BATCH])
    # uncached reference: the stock host-lookup forward, compiled once
    uncached = jax.jit(lambda p, c: dist.apply(p, c))
    for step in range(24):
        cats = [_zipf(rng, v, BATCH) for v, _, _ in SPECS]
        got = engine.predict(cats)
        want = uncached(params, [jnp.asarray(c) for c in cats])
        for i, (a, b) in enumerate(zip(want, got)):
            np.testing.assert_array_equal(
                np.asarray(b), np.asarray(a),
                err_msg=f"step {step} output {i} diverged from host path")
    stats = engine.cache_stats()
    assert stats["hit_rate"] > 0.5, stats
    assert stats["buckets"][0]["promotions"] > 0


def test_cache_weighted_and_multihot_bitmatch():
    """Cache numerics hold for multi-hot inputs with explicit weights and
    mean combiners (the `_effective_weights` path)."""
    rng = np.random.RandomState(2)
    mesh = create_mesh(jax.devices()[:8])
    specs = [(5000, 16, "mean"), (40, 16, "mean"), (5000, 16, "sum"),
             (64, 16, "mean"), (128, 16, "sum"), (96, 16, "mean"),
             (80, 16, "sum"), (72, 16, "mean")]
    dist = DistributedEmbedding(
        [Embedding(v, w, combiner=c) for v, w, c in specs], mesh=mesh,
        gpu_embedding_size=BUDGET)
    assert any(b.offload for b in dist.plan.tp_buckets)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in specs]
    params = dist.set_weights(weights)
    engine = InferenceEngine(dist, params, cache_capacity=512,
                             promote_threshold=1)
    uncached = jax.jit(lambda p, c: dist.apply(p, c))
    for _ in range(4):
        cats = [(_zipf(rng, v, BATCH * 3).reshape(BATCH, 3),
                 np.abs(rng.rand(BATCH, 3)).astype(np.float32))
                for v, _, _ in specs]
        got = engine.predict(cats)
        want = uncached(params, [(jnp.asarray(i), jnp.asarray(w))
                                 for i, w in cats])
        for i, (a, b) in enumerate(zip(want, got)):
            np.testing.assert_array_equal(np.asarray(b), np.asarray(a),
                                          err_msg=f"output {i}")
    assert engine.cache_stats()["hits"] > 0


def test_refresh_restores_bit_exact_serving():
    """(c) a sparse train step mutates the offloaded table; cached rows are
    stale until refresh(), after which serving is bit-exact again."""
    rng = np.random.RandomState(3)
    mesh = create_mesh(jax.devices()[:8])
    model = TinyModel(SPECS, mesh, gpu_embedding_size=BUDGET)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]
    params = {"embedding": model.embedding.set_weights(weights),
              "head": {"w": jnp.asarray(np.random.RandomState(7).randn(
                  sum(w for _, w, _ in SPECS), 1).astype(np.float32))}}

    engine = InferenceEngine(model, params, cache_capacity=1024,
                             promote_threshold=1)
    numerical = np.zeros((BATCH, 1), np.float32)
    # a small hot id set: guaranteed cached AND touched by the train step
    hot = [np.tile(np.arange(4, dtype=np.int32), BATCH // 4)
           for _ in SPECS]
    for _ in range(3):     # count -> promote -> serve from cache
        engine.predict((numerical, hot))
    assert engine.cache_stats()["hits"] > 0

    init_fn, step_fn = make_sparse_train_step(model, "sgd", lr=0.5,
                                              strategy="sort")
    opt_state = init_fn(params)
    labels = jnp.asarray(rng.randn(BATCH).astype(np.float32))
    new_params, _, _ = step_fn(params, opt_state, jnp.zeros((BATCH, 1)),
                               [jnp.asarray(c) for c in hot], labels)
    fresh = np.asarray(jax.jit(
        lambda p, n, c: model.apply(p, n, c))(
            new_params, jnp.asarray(numerical),
            [jnp.asarray(c) for c in hot]))

    engine.set_params(new_params)
    stale = np.asarray(engine.predict((numerical, hot)))
    assert not np.array_equal(stale, fresh), \
        "cached rows must be stale after the table mutated"

    refreshed_rows = engine.refresh()
    assert refreshed_rows > 0
    again = np.asarray(engine.predict((numerical, hot)))
    np.testing.assert_array_equal(again, fresh)


def test_warmup_pads_and_slices(std_dist):
    """Compile-ahead shapes: a smaller request pads to the warmed shape and
    outputs slice back to the true batch, matching the unpadded forward."""
    rng = np.random.RandomState(4)
    dist, params = std_dist
    engine = InferenceEngine(dist, params, cache_capacity=64)
    assert engine.warmup([BATCH]) == [BATCH]
    small = 5
    cats = [rng.randint(0, v, size=(small,)).astype(np.int32)
            for v, _, _ in SPECS]
    got = engine.predict(cats)
    # unpadded reference at a world-divisible batch: pad manually, slice
    padded = [np.concatenate([c, np.zeros((BATCH - small,), c.dtype)])
              for c in cats]
    want = jax.jit(lambda p, c: dist.apply(p, c))(
        params, [jnp.asarray(c) for c in padded])
    for a, b in zip(want, got):
        assert np.asarray(b).shape[0] == small
        np.testing.assert_array_equal(np.asarray(b), np.asarray(a)[:small])
    assert engine.rows_padded == BATCH - small


def test_micro_batcher_coalesces_and_records(std_dist):
    rng = np.random.RandomState(5)
    dist, params = std_dist
    engine = InferenceEngine(dist, params, cache_capacity=256,
                             promote_threshold=1)
    engine.warmup([BATCH])

    now = [0.0]
    batcher = MicroBatcher(engine, max_batch=BATCH, clock=lambda: now[0])
    reqs = {}
    for n in (3, 5, 2, 7, 4):          # 21 rows -> two coalesced forwards
        cats = [_zipf(rng, v, n) for v, _, _ in SPECS]
        reqs[batcher.submit(cats)] = cats
        now[0] += 0.001
    assert batcher.queue_depth == 5
    now[0] += 0.010
    results = batcher.flush()
    assert batcher.queue_depth == 0
    assert set(results) == set(reqs)
    uncached = jax.jit(lambda p, c: dist.apply(p, c))
    for handle, cats in reqs.items():
        want = uncached(params, [
            jnp.asarray(np.concatenate([c, np.zeros((BATCH - len(c),),
                                                    c.dtype)]))
            for c in cats])
        for a, b in zip(want, results[handle]):
            assert np.asarray(b).shape[0] == len(cats[0])
            np.testing.assert_array_equal(np.asarray(b),
                                          np.asarray(a)[:len(cats[0])])
    s = batcher.summary()
    assert s["requests"] == 5 and s["batches"] == 2
    assert s["queue_depth_max"] == 5
    assert 0 < s["batch_occupancy"] <= 1
    assert s["count"] == 5 and s["p99_ms"] >= s["p50_ms"] > 0
    assert "hit_rate" in s
    with pytest.raises(ValueError, match="max_batch"):
        batcher.submit([np.zeros((BATCH + 1,), np.int32)
                        for _ in SPECS])


def test_hot_row_cache_admission_and_eviction(std_dist):
    """Counter-based admission: rows promote when the threshold crosses;
    at capacity, only strictly hotter rows evict the coldest resident."""
    rng = np.random.RandomState(6)
    dist, params = std_dist
    b = next(i for i, bk in enumerate(dist.plan.tp_buckets) if bk.offload)
    table = params["tp"][b]
    cache = HotRowCache(dist, b, capacity=2, promote_threshold=2)

    keys = np.asarray([10, 11, 12], np.int64)
    assert (cache.lookup_slots(keys) == -1).all()        # all cold
    assert cache.admit(table) == 0                       # below threshold
    cache.lookup_slots(keys)                             # counts -> 2 each
    assert cache.admit(table) == 2                       # capacity-bound
    slots = cache.lookup_slots(keys)
    assert (slots[:2] >= 0).sum() + (slots[2] >= 0) == 2
    # the cached rows are bit-exact copies of the table rows
    rows_max = max(dist.plan.tp_buckets[b].rows_max, 1)
    for key, slot in cache._index.items():
        w_idx, row = divmod(int(key), rows_max)
        want = np.asarray(table)[w_idx, row]
        np.testing.assert_array_equal(cache._slots_np[slot], want)
    # a strictly hotter newcomer evicts the coldest resident
    hot_key = np.asarray([99], np.int64)
    for _ in range(6):
        cache.lookup_slots(hot_key)
    assert cache.admit(table) == 1
    assert cache.evictions == 1
    assert (cache.lookup_slots(hot_key) >= 0).all()
    # invalid lanes never count or map
    before = cache.hits + cache.misses
    out = cache.lookup_slots(np.asarray([99, 99]),
                             valid=np.asarray([True, False]))
    assert out[1] == -1 and cache.hits + cache.misses == before + 1


def test_hot_row_cache_counter_pruning(std_dist):
    """Long-lived-server bound: the counter dict prunes back to the
    hottest half (residents always kept) instead of growing with every
    unique id ever seen."""
    dist, params = std_dist
    b = next(i for i, bk in enumerate(dist.plan.tp_buckets) if bk.offload)
    cache = HotRowCache(dist, b, capacity=4, promote_threshold=1,
                        max_tracked=64)
    hot = np.asarray([1, 2, 3, 4], np.int64)
    for _ in range(5):
        cache.lookup_slots(hot)
    cache.admit(params["tp"][b])
    assert set(cache._index) == set(hot.tolist())
    rng = np.random.RandomState(0)
    for i in range(40):
        cache.lookup_slots(rng.randint(100, 3000, size=8).astype(np.int64))
    assert len(cache._counts) <= 64
    # residents survive pruning; their counts still rank evictions
    assert set(hot.tolist()) <= set(cache._counts)


def test_masked_two_source_gather_unit():
    from distributed_embeddings_tpu.ops.embedding_ops import (
        masked_two_source_gather, miss_only_ids)
    slots = jnp.asarray(np.arange(8, dtype=np.float32).reshape(4, 2))
    slot_idx = jnp.asarray([[0, -1], [3, -1]])
    fallback = jnp.full((2, 2, 2), 9.0)
    out = np.asarray(masked_two_source_gather(slots, slot_idx, fallback))
    np.testing.assert_array_equal(out[0, 0], [0.0, 1.0])
    np.testing.assert_array_equal(out[1, 0], [6.0, 7.0])
    np.testing.assert_array_equal(out[0, 1], [9.0, 9.0])
    ids = jnp.asarray([[5, 6], [7, 8]])
    np.testing.assert_array_equal(np.asarray(miss_only_ids(ids, slot_idx)),
                                  [[0, 6], [0, 8]])


def test_latency_histogram_percentiles():
    h = LatencyHistogram()
    for ms in range(1, 101):                    # 1..100 ms uniform
        h.record(ms / 1e3)
    s = h.summary()
    assert s["count"] == 100
    assert 0.040 <= h.percentile(50) <= 0.060
    assert 0.090 <= h.percentile(95) <= 0.105
    assert 0.094 <= h.percentile(99) <= 0.107
    assert s["p50_ms"] <= s["p95_ms"] <= s["p99_ms"] <= s["max_ms"]
    assert LatencyHistogram().percentile(99) == 0.0


def test_serve_bench_cpu_emits_fields():
    """(d) `bench.py --mode serve` runs on CPU and emits throughput,
    hit-rate and latency-percentile fields in its one JSON line — plus
    the concurrent-updater arm's weight-streaming schema (ISSUE 6):
    delta-vs-full bytes, staleness, monotonic versions, and bit-exact
    publisher/consumer parity after the async delta applies."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)          # single CPU device is enough
    # reuse the suite's persistent compile cache where the env honors it
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(repo, ".jax_cache"))
    p = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--mode", "serve",
         "--requests", "12", "--batch", "16", "--capacity", "256",
         "--alpha", "1.5", "--updater_steps", "6", "--publish_every", "2",
         "--train_batch", "32"],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo)
    assert p.returncode == 0, p.stderr[-2000:]
    line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")][-1]
    record = json.loads(line)
    assert record["backend"] == "cpu"
    assert record["serve_throughput_rows_per_sec"] > 0
    assert 0.0 <= record["serve_hit_rate"] <= 1.0
    for k in ("serve_p50_ms", "serve_p95_ms", "serve_p99_ms",
              "serve_batch_occupancy", "serve_queue_depth_max"):
        assert k in record, k
    assert record["serve_p50_ms"] > 0
    # weight-streaming arm schema + contract
    for k in ("serve_updates_published", "serve_updates_applied",
              "serve_updates_applied_deltas",
              "serve_full_table_bytes", "serve_delta_bytes_mean",
              "serve_delta_full_ratio", "serve_delta_apply_rows_per_sec",
              "serve_staleness_versions_max", "serve_staleness_s_mean",
              "serve_version_monotonic", "serve_update_parity_max_dev"):
        assert k in record, k
    assert "serve_updater_error" not in record, record
    # the DELTA count gates the streaming path — the pre-clock snapshot
    # sync alone must never satisfy this
    assert record["serve_updates_applied_deltas"] >= 1
    assert record["serve_version_monotonic"] is True
    assert record["serve_update_parity_max_dev"] == 0.0
    # row deltas at zipfian touched-row rates stay far under a full copy
    assert record["serve_delta_full_ratio"] <= 0.1, record


def test_micro_batcher_admission_pressure_instruments(std_dist):
    """Fleet admission control (ISSUE 16 satellite) reads the batcher's
    queue instruments at submit time: `queue_depth` high-water survives
    the flush, `queued_rows` tracks TRUE rows (not padded), and a
    depth/row-capped `AdmissionController` sheds typed over them."""
    rng = np.random.RandomState(8)
    dist, params = std_dist
    engine = InferenceEngine(dist, params, cache_capacity=128,
                             promote_threshold=1)
    engine.warmup([BATCH])
    batcher = MicroBatcher(engine, max_batch=BATCH)
    sizes = (3, 5, 2, 7)
    for n in sizes:
        batcher.submit([_zipf(rng, v, n) for v, _, _ in SPECS])
    assert batcher.queue_depth == 4
    assert batcher.queued_rows == sum(sizes)

    adm = AdmissionController(max_queue_depth=4, max_queue_rows=None)
    assert adm.shed_reason(batcher, 1) == "queue_depth"
    adm = AdmissionController(max_queue_depth=64,
                              max_queue_rows=sum(sizes) + 2)
    assert adm.shed_reason(batcher, 3) == "queue_rows"
    assert adm.shed_reason(batcher, 2) is None

    batcher.flush()
    assert batcher.queue_depth == 0 and batcher.queued_rows == 0
    assert batcher.queue_depth_max == 4          # high-water survives
    assert adm.shed_reason(batcher, 3) is None   # pressure released


def test_micro_batcher_partial_batch_flush_ordering(std_dist):
    """A queue larger than max_batch splits across several forwards;
    every handle still gets ITS rows (order-preserving slicing across
    the partial-batch boundary), bit-matching the per-request forward."""
    rng = np.random.RandomState(9)
    dist, params = std_dist
    engine = InferenceEngine(dist, params, cache_capacity=0)
    engine.warmup([16])
    batcher = MicroBatcher(engine, max_batch=16)
    reqs = {}
    for n in (10, 9, 12, 5, 11):       # never two whole requests fit
        cats = [rng.randint(0, v, size=(n,)).astype(np.int32)
                for v, _, _ in SPECS]
        reqs[batcher.submit(cats)] = cats
    results = batcher.flush()
    assert set(results) == set(reqs)
    assert batcher.batches == 4        # 10 | 9+5 | 12 | 11 fills
    uncached = jax.jit(lambda p, c: dist.apply(p, c))
    for handle, cats in reqs.items():
        n = len(cats[0])
        padded = [np.concatenate([c, np.zeros((16 - n,), c.dtype)])
                  for c in cats]
        want = uncached(params, [jnp.asarray(c) for c in padded])
        for a, b in zip(want, results[handle]):
            assert np.asarray(b).shape[0] == n
            np.testing.assert_array_equal(np.asarray(b), np.asarray(a)[:n])


def test_micro_batcher_shed_keeps_latency_accounting_clean(std_dist):
    """A shed decided over the instruments (without submitting) leaves
    NO trace in the latency family: histogram count == admitted
    requests, and the shed wait never contaminates p50/p99."""
    rng = np.random.RandomState(10)
    dist, params = std_dist
    engine = InferenceEngine(dist, params, cache_capacity=0)
    engine.warmup([BATCH])
    reg = MetricRegistry()
    now = [0.0]
    batcher = MicroBatcher(engine, max_batch=BATCH, clock=lambda: now[0],
                           registry=reg)
    adm = AdmissionController(max_queue_depth=2)
    admitted = 0
    for i in range(6):
        cats = [_zipf(rng, v, 3) for v, _, _ in SPECS]
        if adm.shed_reason(batcher, 3) is None:
            batcher.submit(cats)
            admitted += 1
        now[0] += 5.0          # sheds "wait" forever; must not be timed
    assert admitted == 2
    now[0] += 0.001
    batcher.flush()
    h = reg.histogram("serve/request_seconds")
    assert h.count == admitted
    assert reg.counter("serve/requests").value == admitted
    # queueing time of the ADMITTED requests is still accounted: the
    # first queued 10.001s before the flush stamped completion
    assert h.summary()["max_ms"] >= 10000


def test_micro_batcher_replica_labels_coexist(std_dist):
    """Two replicas' batchers on ONE registry: the `replica=` label
    keeps their serve families separate (per-replica p50/count stay
    addressable), and the unlabeled family stays untouched."""
    rng = np.random.RandomState(11)
    dist, params = std_dist
    reg = MetricRegistry()
    engines = {name: InferenceEngine(dist, params, cache_capacity=0,
                                     registry=reg, replica=name)
               for name in ("ra", "rb")}
    for e in engines.values():
        e.warmup([BATCH])
    # replica= defaults from the engine: no explicit batcher arg needed
    batchers = {name: MicroBatcher(e, max_batch=BATCH, registry=reg)
                for name, e in engines.items()}
    assert batchers["ra"].replica == "ra"
    for name, b in batchers.items():
        for _ in range(3 if name == "ra" else 1):
            b.submit([_zipf(rng, v, 4) for v, _, _ in SPECS])
        b.flush()
    assert reg.histogram("serve/request_seconds", replica="ra").count == 3
    assert reg.histogram("serve/request_seconds", replica="rb").count == 1
    assert reg.counter("serve/requests", replica="ra").value == 3
    assert reg.counter("serve/batches", replica="rb").value == 1
    assert reg.histogram("serve/request_seconds").count == 0


def test_quantized_bucket_cache_decode_seam():
    """ISSUE 17 satellite: quantized buckets cache through the decode
    seam — the PR 16 bypass (and its RuntimeWarning) is gone, slots hold
    DECODED f32 rows, cached serving bit-matches the stock
    decode-at-gather host lookup, and `serve/cache_bypassed_buckets`
    is pinned at 0."""
    import warnings

    rng = np.random.RandomState(12)
    mesh = create_mesh(jax.devices()[:8])
    dist = DistributedEmbedding(
        [Embedding(v, w, combiner=c) for v, w, c in SPECS], mesh=mesh,
        gpu_embedding_size=BUDGET, storage_dtype="int8")
    quant = [b for b, bk in enumerate(dist.plan.tp_buckets)
             if bk.offload and bk.storage_dtype != "f32"]
    assert quant, "plan must quantize the offloaded bucket"
    W = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]
    params = dist.set_weights(W)
    reg = MetricRegistry()
    with warnings.catch_warnings():
        warnings.simplefilter("error")      # the bypass warning is GONE
        engine = InferenceEngine(dist, params, cache_capacity=1024,
                                 promote_threshold=1, registry=reg)
    assert set(engine.caches) == set(quant)
    assert reg.gauge("serve/cache_bypassed_buckets").value == 0
    # slots are decoded f32 regardless of the at-rest payload dtype
    cache = engine.caches[quant[0]]
    assert cache.store_dtype == "int8"
    assert cache.slots.dtype == jnp.float32
    # cached serving bit-matches the uncached quantized host lookup —
    # hit lanes (decoded slots) and miss lanes (decode in the host
    # region) agree with the stock path's decode-at-gather numerics
    uncached = jax.jit(lambda p, c: dist.apply(p, c))
    for step in range(16):
        cats = [_zipf(rng, v, BATCH) for v, _, _ in SPECS]
        got = engine.predict(cats)
        want = uncached(params, [jnp.asarray(c) for c in cats])
        for i, (a, b) in enumerate(zip(want, got)):
            np.testing.assert_array_equal(
                np.asarray(b), np.asarray(a),
                err_msg=f"step {step} output {i} diverged from host path")
    stats = engine.cache_stats()
    assert stats["hit_rate"] > 0.5, stats
    assert stats["buckets"][0]["promotions"] > 0
