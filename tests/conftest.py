"""Test config: run JAX on 8 virtual CPU devices so the full multi-chip
sharding story is exercised without a TPU pod (SURVEY.md §4 implication).

Note: the environment may pre-import jax with a TPU platform selected (e.g.
an `axon` sitecustomize), so setting env vars alone is not enough — the
config must be forced post-import, before any backend is initialized.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# persistent compilation cache: CPU test compiles of grad-of-shard_map are
# slow; cache them across pytest runs. Repo-local so it survives reboots
# (a /tmp cache is lost and the cold suite takes ~20 min).
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                            os.path.join(_repo_root, ".jax_cache"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# config.update, NOT env vars: the axon sitecustomize pre-imports jax, so
# the cache env vars would be read before this file runs and mostly
# ignored (observed: 11 cache entries after a 20-minute suite)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

# jaxlib 0.4.36 XLA:CPU intermittently mis-executes persistent-cache-
# LOADED executables that carry buffer donation (~1 in 5 loaded donated
# train steps computes wrong numerics — see the guard's docstring for
# the isolation evidence). Two-part mitigation:
#   * the suite builds train steps WITHOUT donation (DET_STEP_DONATE=0,
#     numerically identical, out-of-place update) so the expensive
#     grad-of-shard_map step compiles stay safely cacheable — the cache
#     is what keeps the tier-1 suite inside its time budget;
#   * the compat guard below is the backstop for anything still donated
#     (tests passing donate=True explicitly): those modules bypass the
#     persistent cache and always compile fresh.
os.environ["DET_STEP_DONATE"] = "0"

from distributed_embeddings_tpu import compat  # noqa: E402

assert compat.install_cpu_donation_cache_guard(), (
    "persistent-cache donation guard failed to install; either disable "
    "the compilation cache for this run or update the guard for this "
    "jax version")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-minute tests (multi-process spawns)")

assert len(jax.devices()) >= 8, (
    f"tests need 8 virtual CPU devices, got {jax.devices()}")
