"""Serving fleet tier (ISSUE 16): consistent-hash routing, admission
control, elastic membership, canaried rollout.

Acceptance contract: (a) the ring is process-independent and moves ONLY
the affected key ranges on membership change (bounded movement, asserted
exactly); (b) overload returns typed shed results — the serve path never
raises — without disturbing the admitted requests' latency accounting;
(c) replicas join/leave mid-traffic without an exception, and a joiner
enters rotation only once caught up to the pinned version; (d) a
published version serves fleet-wide only after the canaries report
bit-exact parity (0.0 f32) against the publisher, a corrupted canary
apply rolls the fleet back to the pinned version leaving a
flight-recorder event, and later versions promote THROUGH the condemned
one over the same on-disk stream.
"""

import numpy as np
import jax
import pytest

from distributed_embeddings_tpu import faults, obs
from distributed_embeddings_tpu.fleet import (AdmissionController,
                                              FleetRouter, HashRing,
                                              RouteResult, stable_hash64)
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.serving import InferenceEngine
from distributed_embeddings_tpu.store import TableStore

SPECS = [(600, 8, "sum"), (600, 8, "sum")]


# --------------------------------------------------------------- hash ring
def test_stable_hash_is_process_independent():
    """blake2b, not the salted builtin hash(): fixed values pin the
    function across processes and releases — a drifting hash silently
    remaps every key and voids cache affinity."""
    assert stable_hash64("r0#0") == stable_hash64("r0#0")
    assert stable_hash64(7) == stable_hash64(np.int64(7))
    assert stable_hash64(7) != stable_hash64("7")  # ints hash as bytes
    # pinned sample: fails if the construction ever changes silently
    assert stable_hash64("replica-a") == 0xD873391571CC4E3A


def test_ring_routes_deterministically_and_covers():
    ring = HashRing(vnodes=64)
    for n in ("a", "b", "c", "d"):
        ring.add(n)
    keys = range(2000)
    assign = ring.assignments(keys)
    # stable: a second pass routes identically
    assert assign == ring.assignments(keys)
    counts = {n: 0 for n in ring.nodes()}
    for owner in assign.values():
        counts[owner] += 1
    assert all(c > 0 for c in counts.values())          # coverage
    assert max(counts.values()) < 3 * min(counts.values())  # vnode balance


def test_ring_bounded_movement_on_join_and_leave():
    """THE consistent-hashing property: adding a node moves keys only
    INTO it; removing moves only ITS keys; add+remove round-trips the
    whole assignment map exactly."""
    ring = HashRing(vnodes=64)
    for n in ("a", "b", "c"):
        ring.add(n)
    keys = list(range(2000))
    before = ring.assignments(keys)

    ring.add("d")
    with_d = ring.assignments(keys)
    moved = [k for k in keys if with_d[k] != before[k]]
    assert moved, "a new node must take some load"
    assert all(with_d[k] == "d" for k in moved)
    # ~1/4 of keys move, never the modulo-router's ~3/4
    assert len(moved) < len(keys) / 2

    ring.remove("d")
    assert ring.assignments(keys) == before   # exact round-trip
    ring.remove("b")
    after = ring.assignments(keys)
    for k in keys:
        if before[k] != "b":
            assert after[k] == before[k]      # only b's keys moved
        else:
            assert after[k] in ("a", "c")


def test_ring_add_is_idempotent_and_empty_routes_none():
    ring = HashRing(vnodes=8)
    assert ring.route(1) is None
    ring.add("a")
    ring.add("a")
    assert len(ring) == 1 and "a" in ring
    assert ring.route(123) == "a"


# ---------------------------------------------------------------- admission
class _FakeBatcher:
    def __init__(self, depth, rows):
        self.queue_depth = depth
        self.queued_rows = rows


def test_admission_sheds_typed_on_depth_and_rows():
    adm = AdmissionController(max_queue_depth=4, max_queue_rows=100)
    assert adm.shed_reason(_FakeBatcher(0, 0), 16) is None
    assert adm.shed_reason(_FakeBatcher(4, 0), 16) == "queue_depth"
    assert adm.shed_reason(_FakeBatcher(1, 90), 16) == "queue_rows"
    assert adm.shed_reason(_FakeBatcher(1, 84), 16) is None
    # rows cap optional
    assert AdmissionController(4).shed_reason(
        _FakeBatcher(1, 10 ** 9), 16) is None


def test_admission_env_defaults(monkeypatch):
    monkeypatch.setenv("DET_FLEET_MAX_QUEUE_DEPTH", "7")
    monkeypatch.setenv("DET_FLEET_MAX_QUEUE_ROWS", "33")
    adm = AdmissionController()
    assert adm.max_queue_depth == 7 and adm.max_queue_rows == 33


def test_route_result_truthiness():
    ok = RouteResult(True, replica="r0", handle=3, key=9)
    shed = RouteResult(False, shed_reason="queue_depth", key=9)
    assert ok and not shed
    assert shed.shed_reason == "queue_depth"
    assert "queue_depth" in repr(shed) and "r0" in repr(ok)


# ------------------------------------------------------------- fleet rig
def _build():
    mesh = create_mesh(jax.devices()[:8])
    # gpu_embedding_size=1 host-offloads every bucket: the serving-tier
    # memory shape, and the HotRowCache is in the predict path
    return DistributedEmbedding(
        [Embedding(v, w, combiner=c) for v, w, c in SPECS],
        mesh=mesh, gpu_embedding_size=1)


def _mk_engine(reg, name, seed=0):
    emb = _build()
    zeros = [np.zeros((v, w), np.float32) for v, w, _ in SPECS]
    return InferenceEngine(emb, emb.set_weights(zeros),
                           cache_capacity=64, registry=reg, replica=name)


@pytest.fixture()
def pub(tmp_path):
    """A publisher with three clean published versions (all forced
    snapshots so each version carries full bytes)."""
    rng = np.random.RandomState(0)
    emb = _build()
    w1 = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]
    store = TableStore(emb, emb.set_weights(w1), snapshot_every=2)
    d = str(tmp_path / "pub")
    versions = {}
    for k in range(3):
        wk = [t + 0.25 * k for t in w1]
        store.commit(emb.set_weights(wk), None)
        store.publish(d, force_snapshot=True)
        versions[store.version] = wk
    return emb, store, d, versions


def _fleet(reg, store, d, n=3, **kw):
    kw.setdefault("admission", AdmissionController(max_queue_depth=4))
    kw.setdefault("reference_weights", lambda v: store.get_weights())
    router = FleetRouter(d, registry=reg, vnodes=32, canaries=1, **kw)
    for i in range(n):
        router.add_replica(f"r{i}", _mk_engine(reg, f"r{i}"))
    return router


def _req(key, rows=4):
    ids = np.full((rows, 2), (key * 37) % 600, np.int64)
    return [(ids + t) % 600 for t in range(len(SPECS))]


# ----------------------------------------------------------------- rollout
def test_promote_requires_bitexact_parity_and_fleet_converges(pub):
    emb, store, d, versions = pub
    reg = obs.MetricRegistry()
    obs.reset_default_recorder()
    router = _fleet(reg, store, d)
    ev = router.step()["event"]
    assert ev["event"] == "promote"
    assert ev["parity_devs"] == [0.0]          # bit-exact, not approx
    assert router.pinned_version == store.version
    # EVERY serving member (not just the canary) is at the promoted
    # version with the publisher's exact bytes
    want = [np.asarray(t) for t in store.get_weights()]
    for m in router._members.values():
        assert m.state == "serving"
        assert int(m.engine.store.version) == store.version
        for a, b in zip(want, m.engine.store.get_weights()):
            np.testing.assert_array_equal(a, np.asarray(b))
    names = {e[1] for e in obs.default_recorder().events()}
    assert "fleet/canary_promote" in names


def test_corrupt_canary_rolls_back_and_next_version_promotes(pub):
    """The rollout acceptance chain: a bit-flipped canary apply condemns
    the version (pin unchanged, canary re-anchored, recorder event), the
    condemned version NEVER serves fleet-wide, and the next clean
    version promotes through the same on-disk files."""
    emb, store, d, versions = pub
    reg = obs.MetricRegistry()
    obs.reset_default_recorder()
    router = _fleet(reg, store, d)
    assert router.step()["event"]["event"] == "promote"
    v1 = router.pinned_version

    plan = faults.FaultPlan.from_json({"seed": 3, "faults": [
        {"point": "fleet.canary_apply", "kind": "bit_flip", "at": [0]}]})
    with faults.use_plan(plan):
        w_next = [np.asarray(t) + 1.5 for t in store.get_weights()]
        store.commit(emb.set_weights(w_next), None)
        store.publish(d, force_snapshot=True)
        bad = store.version
        ev = router.step()["event"]
    assert ev["event"] == "rollback" and ev["version"] == bad
    assert ev["parity_devs"][0] == pytest.approx(1.0)   # the injected flip
    assert router.pinned_version == v1
    assert bad in router.rollout.bad_versions
    # containment: every member is back at (or still at) the pin
    for m in router._members.values():
        assert int(m.engine.store.version) == v1
        assert int(m.engine.store.version) not in router.rollout.bad_versions
    names = {e[1] for e in obs.default_recorder().events()}
    assert "fleet/canary_rollback" in names
    # a condemned version is never retried...
    assert router.step()["event"] is None
    # ...but the NEXT version promotes through the same stream, and the
    # whole fleet lands bit-exact on it
    w_good = [np.asarray(t) + 0.125 for t in store.get_weights()]
    store.commit(emb.set_weights(w_good), None)
    store.publish(d, force_snapshot=True)
    ev = router.step()["event"]
    assert ev["event"] == "promote" and ev["version"] == store.version
    assert ev["parity_devs"] == [0.0]
    for m in router._members.values():
        for a, b in zip(store.get_weights(), m.engine.store.get_weights()):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert reg.counter("fleet/rollbacks_total").value == 1
    assert reg.counter("fleet/promotes_total").value == 2


def test_step_idle_when_fully_rolled_out(pub):
    """With everything promoted there is no candidate: the control tick
    is a no-op (no event, no condemnation, no spurious polls)."""
    emb, store, d, versions = pub
    reg = obs.MetricRegistry()
    router = _fleet(reg, store, d)
    router.step()
    assert router.rollout.candidate() is None
    assert router.step()["event"] is None
    assert router.errors == []


# -------------------------------------------------------- routing + sheds
def test_routing_covers_fleet_and_affinity_holds(pub):
    emb, store, d, versions = pub
    reg = obs.MetricRegistry()
    router = _fleet(reg, store, d)
    router.step()
    owners = {}
    for key in range(64):
        r = router.submit(_req(key), key=key)
        assert r.accepted, r
        owners[key] = r.replica
        router.flush()
    assert set(owners.values()) == {"r0", "r1", "r2"}   # coverage
    for key in range(64):                               # affinity
        r = router.submit(_req(key), key=key)
        assert r.replica == owners[key]
        router.flush()


def test_overload_sheds_typed_and_latency_accounting_clean(pub):
    """Burst past max_queue_depth: sheds are typed RouteResults (never
    an exception), and the latency histogram counts EXACTLY the admitted
    requests — a shed must not leave a phantom latency sample."""
    emb, store, d, versions = pub
    reg = obs.MetricRegistry()
    router = _fleet(reg, store, d)
    router.step()
    accepted, shed = [], []
    for i in range(12):                    # one key -> one replica's queue
        r = router.submit(_req(99), key=99)
        (accepted if r else shed).append(r)
    assert len(accepted) == 4              # max_queue_depth
    assert {s.shed_reason for s in shed} == {"queue_depth"}
    assert all(s.replica == accepted[0].replica for s in shed)
    out = router.flush()
    assert set(out) == {r.handle for r in accepted}
    h = reg.histogram("serve/request_seconds",
                      replica=accepted[0].replica)
    assert h.count == len(accepted)
    assert reg.counter("fleet/shed_total", reason="queue_depth").value \
        == len(shed)
    assert router.errors == []


def test_submit_with_no_replicas_sheds_typed():
    reg = obs.MetricRegistry()
    router = FleetRouter("/nonexistent", registry=reg)
    r = router.submit([np.zeros((2, 2), np.int32)], key=1)
    assert not r and r.shed_reason == "no_replicas"


def test_oversize_request_sheds_typed(pub):
    emb, store, d, versions = pub
    reg = obs.MetricRegistry()
    router = _fleet(reg, store, d, max_batch=8)
    router.step()
    r = router.submit(_req(5, rows=9), key=5)
    assert not r and r.shed_reason == "oversize"


# ------------------------------------------------------ elastic membership
def test_join_and_leave_mid_traffic_never_raise(pub):
    emb, store, d, versions = pub
    reg = obs.MetricRegistry()
    router = _fleet(reg, store, d)
    router.step()
    pinned = router.pinned_version
    for key in range(8):
        router.submit(_req(key), key=key)
    drained = router.remove_replica("r1")      # queued work drains
    assert all(v is not None for v in drained.values())
    for key in range(8, 16):
        assert router.submit(_req(key), key=key).replica in ("r0", "r2")
    # joiner catches up to the pin BEFORE entering rotation
    router.add_replica("r9", _mk_engine(reg, "r9"))
    m = router._members["r9"]
    assert m.state == "serving"
    assert int(m.engine.store.version) == pinned
    router.flush()
    assert router.errors == []
    assert "r9" in router.ring and "r1" not in router.ring


def test_duplicate_replica_name_raises_control_plane(pub):
    emb, store, d, versions = pub
    reg = obs.MetricRegistry()
    router = _fleet(reg, store, d, n=1)
    with pytest.raises(ValueError, match="already in the fleet"):
        router.add_replica("r0", _mk_engine(reg, "r0"))


# --------------------------------------------- poll(upto=) + reanchor seams
def test_poll_upto_is_a_version_ceiling_not_degraded(pub):
    """`upto=` pins a replica mid-stream: it reads as caught-up (healthy,
    no degraded reason) at the ceiling even though newer files exist,
    and a later uncapped poll drains the rest."""
    emb, store, d, versions = pub
    reg = obs.MetricRegistry()
    eng = _mk_engine(reg, "pin")
    vs = sorted(versions)
    eng.poll_updates(d, upto=vs[1])
    assert int(eng.store.version) == vs[1]
    assert not eng.degraded_reasons()
    np.testing.assert_array_equal(
        np.asarray(eng.store.get_weights()[0]), versions[vs[1]][0])
    eng.poll_updates(d)
    assert int(eng.store.version) == vs[-1]


def test_reanchor_published_adopts_publisher_version_space(pub):
    emb, store, d, versions = pub
    reg = obs.MetricRegistry()
    eng = _mk_engine(reg, "re")
    vs = sorted(versions)
    got = eng.reanchor_published(d, upto=vs[0])
    assert got == vs[0] and int(eng.store.version) == vs[0]
    assert not eng.store._chain_broken
    np.testing.assert_array_equal(
        np.asarray(eng.store.get_weights()[0]), versions[vs[0]][0])
    # and the stream continues from there without a re-anchor
    eng.poll_updates(d)
    assert int(eng.store.version) == vs[-1]
