"""The unattended-window pipeline rehearsal as a regression test.

VERDICT r5 weak #5 / next #2: the composed watcher-stage → quickab →
bench → measured-defaults-write → dispatch-flip sequence must be runnable
end to end on CPU so the first real hardware window cannot be lost to a
plumbing bug. tools/window_rehearsal.py is the composition; this test runs
it as the watcher would (one subprocess, bounded) and asserts the green
verdict. Slow tier: the bench stage alone compiles the tiny synthetic
model on the CPU backend (execution-bound on the single-core test host).
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_window_rehearsal_green(tmp_path):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)       # stages set their own cpu forcing
    p = subprocess.run(
        [sys.executable, "-u",
         os.path.join(ROOT, "tools", "window_rehearsal.py")],
        capture_output=True, text=True, timeout=3300, env=env, cwd=ROOT)
    assert p.returncode == 0, (
        f"rehearsal failed rc={p.returncode}\nstdout:\n{p.stdout[-2000:]}\n"
        f"stderr:\n{p.stderr[-2000:]}")
    json_line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")][-1]
    summary = json.loads(json_line)
    assert summary["verdict"] == "GREEN"
    assert summary["flip_verified"] is True
    assert summary["stages"] == ["bench", "quickab"]
    assert summary["defaults_knobs_written"] == ["DET_LOOKUP_PATH",
                                                 "DET_SCATTER_IMPL"]
    # the committed green-log artifact regenerates on every run
    log = os.path.join(ROOT, "tools", "window_rehearsal_cpu.out")
    with open(log) as f:
        text = f.read()
    assert "rehearsal GREEN" in text
