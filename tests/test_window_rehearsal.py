"""The unattended-window pipeline rehearsal as a regression test.

VERDICT r5 weak #5 / next #2, rebuilt on ISSUE 18: the composed
search → config-of-record → fresh-process flip-adoption sequence must
be runnable end to end on CPU so the first real hardware window cannot
be lost to a plumbing bug. tools/window_rehearsal.py is now a thin
wrapper over `bench.py --mode tune --rehearse` (the knob list lives in
the tune registry, not the rehearsal script); this test runs it as the
watcher would (one subprocess, bounded) and asserts the green verdict.
Slow tier: the tune stage measures several arms of the tiny synthetic
model on the CPU backend (execution-bound on the single-core test host).
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_window_rehearsal_green(tmp_path):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)       # stages set their own cpu forcing
    p = subprocess.run(
        [sys.executable, "-u",
         os.path.join(ROOT, "tools", "window_rehearsal.py")],
        capture_output=True, text=True, timeout=3300, env=env, cwd=ROOT)
    assert p.returncode == 0, (
        f"rehearsal failed rc={p.returncode}\nstdout:\n{p.stdout[-2000:]}\n"
        f"stderr:\n{p.stderr[-2000:]}")
    json_line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")][-1]
    summary = json.loads(json_line)
    assert summary["verdict"] == "GREEN"
    assert summary["stages"] == ["tune"]
    # the record the search emitted passed the shared validator, with
    # real prune + measurement evidence (defaults always measured)
    assert summary["tune_prune_audit_ok"] is True
    assert summary["tune_pruned"] > 0
    assert summary["tune_measured_arms"] >= 2
    # the reader seam rehearsed in BOTH directions in fresh processes:
    # DET_TUNED_PATH adopts the grafted winner, unset keeps the fallback
    assert summary["flip_verified"] is True
    # the committed green-log artifact regenerates on every run
    log = os.path.join(ROOT, "tools", "window_rehearsal_cpu.out")
    with open(log) as f:
        text = f.read()
    assert "rehearsal GREEN" in text
