"""Physical host offload: placement, forward equivalence, sparse training.

Reference behavior: tables past the gpu_embedding_size budget are built under
/CPU:0 and looked up there (reference dist_model_parallel.py:449-476,
:829-831, :1186-1189). Here: offloaded buckets live in pinned_host memory
(assert via sharding.memory_kind — the device-memory-exclusion proof), their
lookups run in a compute_on host region, and sparse training updates them in
host memory.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu import compat
from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.training import make_sparse_train_step

from test_sparse_train import TinyModel, BATCH

# 8 one-hot tables; the two 5000-row ones blow a 20k-element device budget
SPECS = [(5000, 16, "sum"), (40, 16, "sum"), (5000, 16, "sum"),
         (64, 16, "sum"), (128, 16, "sum"), (96, 16, "sum"),
         (80, 16, "sum"), (72, 16, "sum")]
# total tp elements ~ 166k: a 40k budget forces the two 5000-row tables out
BUDGET = 2500 * 16

# expected memory spaces, derived from the backend: pinned_host vs device on
# TPU; older XLA:CPU has a single unpinned_host space (placement is a no-op
# there but the offload code path still runs end to end)
HOST_KIND = compat.host_memory_kind(jax.devices()[0])
DEV_KIND = compat.default_memory_kind(jax.devices()[0])


def _build(mesh, offload: bool, **kw):
    return DistributedEmbedding(
        [Embedding(v, w, combiner=c) for v, w, c in SPECS], mesh=mesh,
        gpu_embedding_size=(BUDGET if offload else None), **kw)


def test_offload_placement_and_forward():
    rng = np.random.RandomState(0)
    mesh = create_mesh(jax.devices()[:8])
    dist_off = _build(mesh, True)
    dist_dev = _build(mesh, False)
    assert dist_off._offload_enabled
    offloaded = [b for b, bk in enumerate(dist_off.plan.tp_buckets)
                 if bk.offload]
    assert offloaded, "budget should force at least one offloaded bucket"

    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]
    p_off = dist_off.set_weights(weights)
    p_dev = dist_dev.set_weights(weights)

    # device-memory exclusion: offloaded buckets are host-space arrays
    for b, bk in enumerate(dist_off.plan.tp_buckets):
        kind = p_off["tp"][b].sharding.memory_kind
        assert kind == (HOST_KIND if bk.offload else DEV_KIND), \
            f"bucket {b}: {kind}"

    inputs = [jnp.asarray(rng.randint(0, v, size=(BATCH, 2)))
              for v, _, _ in SPECS]
    out_off = dist_off.apply(p_off, inputs)
    out_dev = dist_dev.apply(p_dev, inputs)
    for i, (a, b) in enumerate(zip(out_dev, out_off)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-5,
                                   atol=1e-5, err_msg=f"output {i}")
    # weights round-trip through the host placement
    got = dist_off.get_weights(p_off)
    for t, (a, b) in enumerate(zip(weights, got)):
        np.testing.assert_array_equal(a, b, err_msg=f"table {t}")


def test_offload_weighted_mean_forward():
    """Regression: mean-combiner offloaded lookups with explicit weights must
    not get the uniform 1/k scale on top of the normalized weights."""
    rng = np.random.RandomState(5)
    mesh = create_mesh(jax.devices()[:8])
    specs = [(5000, 16, "mean"), (40, 16, "mean"), (5000, 16, "sum"),
             (64, 16, "mean"), (128, 16, "sum"), (96, 16, "mean"),
             (80, 16, "sum"), (72, 16, "mean")]

    def build(offload):
        return DistributedEmbedding(
            [Embedding(v, w, combiner=c) for v, w, c in specs], mesh=mesh,
            gpu_embedding_size=(BUDGET if offload else None))

    dist_off, dist_dev = build(True), build(False)
    assert any(b.offload for b in dist_off.plan.tp_buckets)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in specs]
    p_off = dist_off.set_weights(weights)
    p_dev = dist_dev.set_weights(weights)
    inputs = [(jnp.asarray(rng.randint(0, v, size=(BATCH, 3))),
               jnp.asarray(np.abs(rng.rand(BATCH, 3)).astype(np.float32)))
              for v, _, _ in specs]
    out_off = dist_off.apply(p_off, inputs)
    out_dev = dist_dev.apply(p_dev, inputs)
    for i, (a, b) in enumerate(zip(out_dev, out_off)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-5,
                                   atol=1e-5, err_msg=f"output {i}")


@pytest.mark.parametrize("optimizer", ["sgd", "adagrad", "adam"])
def test_offload_sparse_train_matches_device(optimizer):
    """Offloading must not change training numerics: sparse train steps on an
    offloaded model == the same steps on the all-device model."""
    rng = np.random.RandomState(1)
    mesh = create_mesh(jax.devices()[:8])
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]

    results = []
    for offload in (False, True):
        model = TinyModel(SPECS, mesh,
                          gpu_embedding_size=(BUDGET if offload else None))
        if offload:
            assert any(b.offload for b in model.embedding.plan.tp_buckets)
        init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.05,
                                                  strategy="sort")
        params = {"embedding": model.embedding.set_weights(weights),
                  "head": {"w": jnp.asarray(
                      np.random.RandomState(7).randn(
                          sum(w for _, w, _ in SPECS), 1).astype(np.float32))}}
        opt_state = init_fn(params)
        rng2 = np.random.RandomState(3)
        losses = []
        for _ in range(3):
            cats = [jnp.asarray(rng2.randint(0, v, size=(BATCH, 2)))
                    for v, _, _ in SPECS]
            labels = jnp.asarray(rng2.randn(BATCH).astype(np.float32))
            params, opt_state, loss = step_fn(params, opt_state,
                                              jnp.zeros((BATCH, 1)), cats,
                                              labels)
            losses.append(float(loss))
        results.append((losses, model.embedding.get_weights(
            params["embedding"])))

    (l_dev, w_dev), (l_off, w_off) = results
    np.testing.assert_allclose(l_off, l_dev, rtol=1e-5, atol=1e-6)
    for t, (a, b) in enumerate(zip(w_dev, w_off)):
        np.testing.assert_allclose(b, a, rtol=2e-5, atol=2e-5,
                                   err_msg=f"table {t}")


@pytest.mark.parametrize("optimizer", ["sgd", "adagrad", "adam"])
def test_offload_apply_no_roundtrip_warning(optimizer):
    """VERDICT r4 item 3: at world>1 the offloaded apply must NOT fall back
    to the full-bucket device round-trip. Where the backend cannot partition
    host placements (this CPU mesh), the XLA-free per-shard host apply takes
    over silently — row-only wire traffic, no RuntimeWarning."""
    import warnings

    rng = np.random.RandomState(2)
    mesh = create_mesh(jax.devices()[:8])
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]
    model = TinyModel(SPECS, mesh, gpu_embedding_size=BUDGET)
    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.05,
                                              strategy="sort")
    params = {"embedding": model.embedding.set_weights(weights),
              "head": {"w": jnp.asarray(
                  np.random.RandomState(7).randn(
                      sum(w for _, w, _ in SPECS), 1).astype(np.float32))}}
    opt_state = init_fn(params)
    rng2 = np.random.RandomState(3)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        for _ in range(2):
            cats = [jnp.asarray(rng2.randint(0, v, size=(BATCH, 2)))
                    for v, _, _ in SPECS]
            labels = jnp.asarray(rng2.randn(BATCH).astype(np.float32))
            params, opt_state, _ = step_fn(params, opt_state,
                                           jnp.zeros((BATCH, 1)), cats,
                                           labels)
    modes = model.embedding.host_apply_modes()
    assert modes and all(m in ("native", "pershard") for m in modes.values()), \
        modes


def test_offload_apply_forced_modes_agree(monkeypatch):
    """The three DET_HOST_APPLY implementations are numerically
    interchangeable: forced pershard == forced roundtrip, step for step."""
    rng = np.random.RandomState(4)
    mesh = create_mesh(jax.devices()[:8])
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]

    def run(mode):
        monkeypatch.setenv("DET_HOST_APPLY", mode)
        model = TinyModel(SPECS, mesh, gpu_embedding_size=BUDGET)
        init_fn, step_fn = make_sparse_train_step(model, "adam", lr=0.05,
                                                  strategy="sort")
        params = {"embedding": model.embedding.set_weights(weights),
                  "head": {"w": jnp.asarray(
                      np.random.RandomState(7).randn(
                          sum(w for _, w, _ in SPECS), 1).astype(
                              np.float32))}}
        opt_state = init_fn(params)
        rng2 = np.random.RandomState(6)
        for _ in range(3):
            cats = [jnp.asarray(rng2.randint(0, v, size=(BATCH, 2)))
                    for v, _, _ in SPECS]
            labels = jnp.asarray(rng2.randn(BATCH).astype(np.float32))
            params, opt_state, loss = step_fn(params, opt_state,
                                              jnp.zeros((BATCH, 1)), cats,
                                              labels)
        return float(loss), model.embedding.get_weights(params["embedding"])

    l_rt, w_rt = run("roundtrip")
    l_ps, w_ps = run("pershard")
    np.testing.assert_allclose(l_ps, l_rt, rtol=1e-5, atol=1e-6)
    for t, (a, b) in enumerate(zip(w_rt, w_ps)):
        np.testing.assert_allclose(b, a, rtol=2e-5, atol=2e-5,
                                   err_msg=f"table {t}")


def test_unknown_host_apply_rejected():
    """Only optimizers with a host apply rule may touch offloaded buckets
    (adam gained one this round; a fake kind still raises)."""
    from distributed_embeddings_tpu.ops.sparse_update import SparseOptimizer

    mesh = create_mesh(jax.devices()[:8])
    model = TinyModel(SPECS, mesh, gpu_embedding_size=BUDGET)
    fake = SparseOptimizer("rmsprop", lambda t: (),
                           lambda t, s, g: (t, s), 0.01, ())
    params = {"embedding": model.embedding.init(jax.random.PRNGKey(0))}
    with pytest.raises(NotImplementedError, match="host-memory apply"):
        model.embedding.sparse_update(
            params["embedding"], {"tp": [], "row": []}, {}, None, fake)


def test_offload_checkpoint_roundtrip(tmp_path):
    """Orbax checkpoints preserve pinned-host placement: save the offloaded
    model's params, restore with param_shardings (which carry memory_kind),
    and verify placement + outputs."""
    from distributed_embeddings_tpu.utils import checkpoint as ckpt

    rng = np.random.RandomState(3)
    mesh = create_mesh(jax.devices()[:8])
    dist = _build(mesh, True)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]
    params = dist.set_weights(weights)
    off_buckets = [b for b, bk in enumerate(dist.plan.tp_buckets)
                   if bk.offload]
    assert off_buckets

    ckpt.save_checkpoint(str(tmp_path / "ck"), params)
    restored = ckpt.restore_checkpoint(str(tmp_path / "ck"), params,
                                       shardings=dist.param_shardings())
    for b in range(len(dist.plan.tp_buckets)):
        kind = restored["tp"][b].sharding.memory_kind
        assert kind == (HOST_KIND if b in off_buckets else DEV_KIND)

    inputs = [jnp.asarray(rng.randint(0, v, size=(BATCH,)).astype(np.int32))
              for v, _, _ in SPECS]
    out_a = dist.apply(params, inputs)
    out_b = dist.apply(restored, inputs)
    for a, b in zip(out_a, out_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_multibucket_offload_device_bytes_excluded():
    """Colossal-mechanism scale model (VERDICT r2 item 8): a multi-bucket
    offloaded model's device-resident bytes must exclude the offloaded
    buckets — measured from the placed buffers and the compiled forward's
    XLA memory analysis, not from sharding metadata."""
    rng = np.random.RandomState(11)
    mesh = create_mesh(jax.devices()[:8])
    # two width classes -> two fused buckets; the big tables in each class
    # blow the budget -> BOTH buckets get offloaded slices
    specs = [(200_000, 8, "sum"), (150_000, 16, "sum"),
             (120_000, 8, "sum"), (100_000, 16, "sum"),
             (400, 8, "sum"), (300, 16, "sum"),
             (200, 8, "sum"), (100, 16, "sum")]
    dist = DistributedEmbedding(
        [Embedding(v, w, combiner=c) for v, w, c in specs], mesh=mesh,
        gpu_embedding_size=50_000)
    off = [b for b, bk in enumerate(dist.plan.tp_buckets) if bk.offload]
    assert len(off) >= 2, f"want multi-bucket offload, got {off}"

    params = dist.init(jax.random.PRNGKey(0))

    def tree_bytes(tree, kind):
        return sum(x.nbytes for x in jax.tree.leaves(tree)
                   if x.sharding.memory_kind == kind)

    total = sum(x.nbytes for x in jax.tree.leaves(params))
    host_bytes = tree_bytes(params, HOST_KIND)
    dev_bytes = tree_bytes(params, DEV_KIND)
    off_bytes = sum(params["tp"][b].nbytes for b in off)
    if HOST_KIND != DEV_KIND:
        # placed buffers: device total excludes exactly the offloaded
        # buckets (vacuous on backends with a single memory space)
        assert host_bytes == off_bytes
        assert dev_bytes == total - off_bytes
        assert off_bytes > 10 * dev_bytes  # the offloaded part dominates
    else:
        assert off_bytes > 10 * (total - off_bytes)

    # compiled forward: XLA's buffer assignment confirms the step streams
    # only combined rows device-ward — temps + outputs are orders of
    # magnitude smaller than the offloaded tables it reads
    inputs = [jnp.asarray(rng.randint(0, v, size=(16,)).astype(np.int32))
              for v, _, _ in specs]
    compiled = jax.jit(lambda p, i: dist.apply(p, i)).lower(
        params, inputs).compile()
    ma = compiled.memory_analysis()
    if ma is not None and hasattr(ma, "temp_size_in_bytes"):
        assert ma.temp_size_in_bytes + ma.output_size_in_bytes \
            < off_bytes / 10, (ma.temp_size_in_bytes,
                               ma.output_size_in_bytes, off_bytes)
    # and the forward is actually correct on this plan
    out = dist.apply(params, inputs)
    assert len(out) == len(specs)
