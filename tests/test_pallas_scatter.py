"""Interpret-mode correctness for the Pallas sorted-unique scatter-add RMW
kernel (ops/pallas_scatter.py) vs the XLA .at[].add reference.

Compiled-path validation is hardware-gated (tools/tpu_mosaic_probe.py) —
the kernel exists because XLA's scatter costs 100-280 ns/row on TPU
(round-3 prims) and dedup_sum's sorted-unique output makes a conflict-free
DMA stream legal.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.ops import pallas_scatter as ps


def make_sorted_unique(rng, n_real, v, n_total):
    ids = np.sort(rng.choice(v, size=n_real, replace=False)).astype(np.int32)
    fill = (v + 1 + np.arange(n_total - n_real)).astype(np.int32)
    return np.concatenate([ids, fill])


@pytest.mark.parametrize("v,w,n_real,n_total", [
    (500, 8, 100, 128),       # padded tail of OOB fillers
    (1000, 16, 512, 512),     # no fillers, multiple tiles
    (300, 128, 77, 100),      # wide rows, odd counts
])
def test_scatter_add_sorted_unique_matches_xla(v, w, n_real, n_total):
    rng = np.random.default_rng(v + w)
    ids = make_sorted_unique(rng, n_real, v, n_total)
    delta = rng.standard_normal((n_total, w)).astype(np.float32)
    delta[n_real:] = 0.0                    # filler deltas are zero (contract)
    table = rng.standard_normal((v, w)).astype(np.float32)

    got = ps.scatter_add_sorted_unique(
        jnp.asarray(table), jnp.asarray(ids), jnp.asarray(delta))
    want = jnp.asarray(table).at[jnp.asarray(ids)].add(
        jnp.asarray(delta), mode="drop")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_scatter_add_sorted_unique_bf16_table():
    rng = np.random.default_rng(9)
    v, w, n = 400, 16, 96
    ids = make_sorted_unique(rng, n, v, 128)
    delta = np.zeros((128, w), np.float32)
    delta[:n] = rng.standard_normal((n, w)).astype(np.float32)
    table = (rng.standard_normal((v, w)) * 0.1).astype(jnp.bfloat16)

    got = ps.scatter_add_sorted_unique(
        jnp.asarray(table), jnp.asarray(ids), jnp.asarray(delta))
    want = jnp.asarray(table).at[jnp.asarray(ids)].add(
        jnp.asarray(delta).astype(jnp.bfloat16), mode="drop")
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2)


def test_scatter_add_under_jit():
    rng = np.random.default_rng(2)
    v, w = 600, 8
    ids = make_sorted_unique(rng, 200, v, 256)
    delta = np.zeros((256, w), np.float32)
    delta[:200] = rng.standard_normal((200, w))
    table = rng.standard_normal((v, w)).astype(np.float32)

    f = jax.jit(lambda t, i, d: ps.scatter_add_sorted_unique(t, i, d))
    got = f(jnp.asarray(table), jnp.asarray(ids), jnp.asarray(delta))
    want = jnp.asarray(table).at[jnp.asarray(ids)].add(
        jnp.asarray(delta), mode="drop")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("v,w,n_real,n_total", [
    (500, 8, 100, 128), (800, 16, 512, 512),
])
def test_adagrad_rows_fused_matches_formula(v, w, n_real, n_total):
    """Fused adagrad RMW kernel == the row-wise adagrad formula on unique
    rows, with untouched rows (and OOB fillers) left intact."""
    rng = np.random.default_rng(v)
    ids = make_sorted_unique(rng, n_real, v, n_total)
    sums = np.zeros((n_total, w), np.float32)
    sums[:n_real] = rng.standard_normal((n_real, w))
    table = rng.standard_normal((v, w)).astype(np.float32)
    acc = np.full((v, w), 0.1, np.float32)
    lr, eps = 0.05, 1e-10

    t2, a2 = ps.adagrad_rows_sorted_unique(
        jnp.asarray(table), jnp.asarray(acc), jnp.asarray(ids),
        jnp.asarray(sums), lr, eps)

    want_t, want_a = table.copy(), acc.copy()
    for k in range(n_real):
        r = ids[k]
        want_a[r] = acc[r] + sums[k] * sums[k]
        want_t[r] = table[r] - lr * sums[k] / np.sqrt(want_a[r] + eps)
    np.testing.assert_allclose(np.asarray(a2), want_a, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(t2), want_t, rtol=1e-5, atol=1e-5)
