"""Wire compression for the dp<->mp exchange (ISSUE 5).

Contracts pinned here:
  * the f32 (default) wire is BIT-EXACT vs the plain-lax collectives —
    same outputs, same gradients, zero bf16 bytes in the lowered HLO;
  * the bf16 wire keeps f32 math on both sides and stays within the
    documented tolerance on forward, backward and full sparse train
    steps, while the lowered float collective bytes shrink >= 1.9x;
  * the int16 id wire is LOSSLESS (clip semantics keep out-of-range ids
    out of range and distinct from the hot sentinel) and gated on the
    planner's proof that the key space fits;
  * `exchange_padding_report` exposes the byte accounting the acceptance
    gate audits (exchanged_bytes / true_bytes / wire_dtype per group).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.ops import wire as wire_ops
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.utils.profiling import hlo_collective_bytes

BATCH = 16


def make_dist(specs, **kw):
    mesh = create_mesh(jax.devices()[:8])
    embeddings = [Embedding(s[0], s[1],
                            combiner=(s[2] if len(s) > 2 else None))
                  for s in specs]
    return DistributedEmbedding(embeddings, mesh=mesh, **kw)


SPECS = [(96, 8, "sum"), (50, 8, "sum"), (100, 8, "mean"), (120, 8, "sum"),
         (40, 8, "sum"), (70, 8, "sum"), (60, 8, "sum"), (81, 8, "sum")]


def _inputs(rng, specs, hot=2, weighted=False):
    out = []
    for v, _, _ in specs:
        ids = jnp.asarray(rng.randint(0, v, size=(BATCH, hot)))
        if weighted:
            w = jnp.asarray(np.abs(rng.rand(BATCH, hot)).astype(np.float32))
            out.append((ids, w))
        else:
            out.append(ids)
    return out


# ---------------------------------------------------------------- units
def test_encode_decode_unit():
    x = jnp.asarray(np.random.RandomState(0).randn(512).astype(np.float32))
    # f32 is the identity (bit-exact contract of the default)
    assert wire_ops.encode_fwd(x, "f32") is x
    assert wire_ops.encode_bwd(x, "f32") is x
    # bf16 RNE round-trip error is bounded by one ulp (2^-8 relative)
    y = wire_ops.encode_fwd(x, "bf16").astype(jnp.float32)
    rel = np.abs(np.asarray(y - x)) / np.maximum(np.abs(np.asarray(x)), 1e-9)
    assert rel.max() <= 2.0 ** -8
    # stochastic rounding: deterministic per (array, salt), bounded by
    # one bf16 step, and each value lands on one of its two neighbors
    a = wire_ops.stochastic_round_bf16(x)
    b = wire_ops.stochastic_round_bf16(x)
    assert (np.asarray(a) == np.asarray(b)).all()
    sr = np.asarray(a, np.float32)
    rel = np.abs(sr - np.asarray(x)) / np.maximum(np.abs(np.asarray(x)), 1e-9)
    assert rel.max() <= 2.0 ** -7
    # non-finite values survive the SR path
    bad = jnp.asarray([np.inf, -np.inf, np.nan], jnp.float32)
    out = np.asarray(wire_ops.stochastic_round_bf16(bad), np.float32)
    assert np.isinf(out[0]) and np.isinf(out[1]) and np.isnan(out[2])


def test_stochastic_round_is_unbiased_vs_rne():
    # over many values the SR error must center on zero much tighter
    # than its per-value magnitude (the reason bf16-sr exists for the
    # gradient direction); RNE is compared on the same data
    x = jnp.asarray((np.random.RandomState(3).rand(1 << 16).astype(
        np.float32) + 0.5))
    sr_err = np.asarray(wire_ops.stochastic_round_bf16(x), np.float32) \
        - np.asarray(x)
    step = np.abs(np.asarray(x)) * 2.0 ** -8
    assert np.abs(sr_err.mean()) < step.mean() * 0.05


def test_id_wire_encode_clip_semantics():
    ids = jnp.asarray([[-70000, -5, 0, 100, 16000, 32767, 40000]], jnp.int32)
    enc = wire_ops.encode_ids(ids, "int16")
    assert enc.dtype == jnp.int16
    dec = np.asarray(wire_ops.decode_ids(enc, "int16"))
    # in-range values exact; out-of-range values stay out of range on
    # the respective side (clip, never wrap)
    assert dec.tolist() == [[-32768, -5, 0, 100, 16000, 32767, 32767]]
    # int32 wire is the identity
    assert wire_ops.encode_ids(ids, "int32") is ids
    # the planner gate: every legal value must sit strictly below the
    # clip ceiling
    assert wire_ops.int16_id_wire_ok(32766)
    assert not wire_ops.int16_id_wire_ok(32767)


def test_latency_histogram_merge():
    from distributed_embeddings_tpu.utils.metrics import LatencyHistogram
    a, b, ref = LatencyHistogram(), LatencyHistogram(), LatencyHistogram()
    rng = np.random.RandomState(0)
    for i, s in enumerate(rng.rand(200) * 0.1):
        (a if i % 2 else b).record(s)
        ref.record(s)
    out = a.merge(b)
    assert out is a
    assert a.count == ref.count == 200
    sa, sr = a.summary(), ref.summary()
    for k in ("count", "mean_ms", "p50_ms", "p99_ms", "max_ms"):
        assert sa[k] == pytest.approx(sr[k]), k
    with pytest.raises(ValueError):
        a.merge(LatencyHistogram(lo=1e-3))


# ------------------------------------------------------- plan-level gates
def test_plan_wire_gating(monkeypatch):
    specs = [(96, 8, "sum"), (50, 8, None), (100, 8, "mean")]
    d = make_dist(specs, exchange_wire="bf16")
    by_comb = {b.combiner: b.wire_dtype for b in d.plan.tp_buckets}
    # combiner-None passthrough buckets keep the exact wire
    assert by_comb[None] == "f32"
    assert by_comb["sum"] == "bf16" and by_comb["mean"] == "bf16"
    # default is f32 everywhere
    d0 = make_dist(specs)
    assert all(b.wire_dtype == "f32" for b in d0.plan.tp_buckets)
    # env default (constructor arg absent) — read at construction
    monkeypatch.setenv("DET_EXCHANGE_WIRE", "bf16")
    d1 = make_dist(specs)
    assert any(b.wire_dtype == "bf16" for b in d1.plan.tp_buckets)
    # explicit arg wins over env
    d2 = make_dist(specs, exchange_wire="f32")
    assert all(b.wire_dtype == "f32" for b in d2.plan.tp_buckets)
    monkeypatch.delenv("DET_EXCHANGE_WIRE")
    with pytest.raises(ValueError):
        make_dist(specs, exchange_wire="fp8")


def test_plan_id_wire_gating(monkeypatch):
    # small-vocab buckets narrow; a bucket whose rows_max overflows the
    # int16 proof stays int32
    small = make_dist([(500, 8, "sum")] * 8)
    assert all(b.id_wire_dtype == "int16" for b in small.plan.tp_buckets)
    big = make_dist([(40000, 8, "sum")] * 8)
    assert all(b.id_wire_dtype == "int32" for b in big.plan.tp_buckets)
    # DET_ID_WIRE=int32 forces the wide wire everywhere
    monkeypatch.setenv("DET_ID_WIRE", "int32")
    forced = make_dist([(500, 8, "sum")] * 8)
    assert all(b.id_wire_dtype == "int32" for b in forced.plan.tp_buckets)


# ------------------------------------------------- forward / HLO parity
def test_forward_parity_and_collective_bytes():
    rng = np.random.RandomState(0)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1
               for v, w, _ in SPECS]
    inputs = _inputs(np.random.RandomState(1), SPECS)

    def build(**kw):
        d = make_dist(SPECS, input_max_hotness=[2] * len(SPECS), **kw)
        return d, d.set_weights(weights)

    d0, p0 = build()
    df, pf = build(exchange_wire="f32")
    db, pb = build(exchange_wire="bf16")
    o0 = [np.asarray(o) for o in d0.apply(p0, inputs)]
    of = [np.asarray(o) for o in df.apply(pf, inputs)]
    ob = [np.asarray(o) for o in db.apply(pb, inputs)]
    for i, (a, b) in enumerate(zip(o0, of)):
        assert (a == b).all(), f"f32 wire not bit-exact at output {i}"
    for i, (a, b) in enumerate(zip(o0, ob)):
        np.testing.assert_allclose(b, a, rtol=2e-2, atol=2e-2,
                                   err_msg=f"output {i}")

    # lowered HLO: the default moves ZERO bf16 collective bytes; bf16
    # halves the float collective bytes of the same forward
    def low(d, p):
        return jax.jit(lambda p, i: d.apply(p, i)).lower(p, inputs).as_text()

    b0 = hlo_collective_bytes(low(d0, p0))
    bb = hlo_collective_bytes(low(db, pb))
    assert b0["total"].get("bf16", 0) == 0
    assert b0["float_bytes"] > 0
    assert b0["float_bytes"] / bb["float_bytes"] >= 1.9
    # the id wire narrowed (small vocabs) in BOTH: i16 a2a, no i32 ids
    assert b0["total"].get("i16", 0) > 0


def test_grad_direction_compressed():
    # the transposed (dp->mp gradient) all_to_all must also ride the
    # wire: value_and_grad of a scalar over the forward halves its float
    # collective bytes too
    rng = np.random.RandomState(2)
    specs = SPECS[:4]
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in specs]
    inputs = _inputs(np.random.RandomState(3), specs)

    def low(wire):
        d = make_dist(specs, exchange_wire=wire)
        p = d.set_weights(weights)

        def loss(p, i):
            return sum(jnp.sum(o) for o in d.apply(p, i))

        return hlo_collective_bytes(
            jax.jit(jax.value_and_grad(loss)).lower(p, inputs).as_text())

    b_f32, b_bf16 = low("f32"), low("bf16")
    assert b_f32["total"].get("bf16", 0) == 0
    assert b_f32["float_bytes"] / b_bf16["float_bytes"] >= 1.9


def test_wire_collective_grads_raw():
    """Numeric fwd+grad parity of the custom-vjp wrapped collectives at
    the shard_map level (cheap — no model compile): f32 bit-exact vs the
    plain lax ops, bf16 within one rounding. The full row-sliced-model
    twin runs in the slow tier (test_row_slice_wire_parity)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from distributed_embeddings_tpu import compat

    mesh = create_mesh(jax.devices()[:8])
    rng = np.random.RandomState(8)
    Z = jnp.asarray(rng.randn(8, 16, 8).astype(np.float32))

    def run(kind, wire):
        def body(v):
            x = v[0]
            if kind == "ps":
                out = (lax.psum_scatter(x, "mp", scatter_dimension=0,
                                        tiled=True) if wire == "base" else
                       wire_ops.wire_psum_scatter(x, "mp", wire, 8))
            else:
                out = (lax.all_gather(x, "mp", axis=0, tiled=True)
                       if wire == "base" else
                       wire_ops.wire_all_gather(x, "mp", wire, 8))
            return out[None]

        def outer(x):
            o = compat.shard_map(body, mesh=mesh, in_specs=(P("mp"),),
                                 out_specs=P("mp"), check_vma=False)(x)
            return jnp.sum(o ** 2), o

        (_, o), g = jax.value_and_grad(outer, has_aux=True)(Z)
        return np.asarray(o), np.asarray(g)

    for kind in ("ps", "ag"):
        ob, gb = run(kind, "base")
        of, gf = run(kind, "f32")
        assert (of == ob).all() and (gf == gb).all(), kind
        ow, gw = run(kind, "bf16")
        np.testing.assert_allclose(ow, ob, rtol=2e-2, atol=2e-1,
                                   err_msg=kind)
        np.testing.assert_allclose(gw, gb, rtol=3e-2, atol=2e-1,
                                   err_msg=kind)


@pytest.mark.slow
def test_row_slice_wire_parity():
    # row-sliced path: all_gather ids + weight broadcast + psum_scatter
    # return behind the wire seam, forward AND backward
    rng = np.random.RandomState(4)
    specs = [(4000, 8, "sum"), (96, 8, "sum"), (50, 8, "sum"), (80, 8, "sum")]
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in specs]
    inputs = _inputs(np.random.RandomState(5), specs, weighted=True)

    def run(wire):
        d = make_dist(specs, row_slice_threshold=16000, exchange_wire=wire,
                      input_max_hotness=[2] * 4)
        assert d.plan.row_tables, "row slicing did not engage"
        if wire == "bf16":
            assert all(rt.wire_dtype == "bf16" for rt in d.plan.row_tables)
        p = d.set_weights(weights)
        cots = [jnp.asarray(rng2.randn(BATCH, w).astype(np.float32))
                for _, w, _ in specs]

        def loss(p):
            outs = d.apply(p, inputs)
            return sum(jnp.vdot(o, c) for o, c in zip(outs, cots))

        outs = [np.asarray(o) for o in d.apply(p, inputs)]
        grads = jax.grad(loss)(p)
        return outs, jax.tree.leaves(grads)

    rng2 = np.random.RandomState(6)
    o_f32, g_f32 = run("f32")
    rng2 = np.random.RandomState(6)
    o_bf, g_bf = run("bf16")
    for i, (a, b) in enumerate(zip(o_f32, o_bf)):
        np.testing.assert_allclose(b, a, rtol=2e-2, atol=2e-2,
                                   err_msg=f"output {i}")
    for i, (a, b) in enumerate(zip(g_f32, g_bf)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=3e-2,
                                   atol=3e-2, err_msg=f"grad leaf {i}")


# ---------------------------------------------------------- train parity
def _train(specs, wire, optimizer="adagrad", steps=2, ragged=False,
           weighted=False, seed=0, hot_rows=None):
    from test_sparse_train import TinyModel
    from distributed_embeddings_tpu.training import make_sparse_train_step

    rng = np.random.RandomState(seed)
    mesh = create_mesh(jax.devices()[:8])
    kw = {"input_max_hotness": [2] * len(specs)}
    if wire is not None:
        kw["exchange_wire"] = wire
    if hot_rows:
        kw["hot_rows"] = hot_rows
    model = TinyModel(specs, mesh, **kw)
    weights = [rng.randn(s[0], s[1]).astype(np.float32) * 0.1 for s in specs]
    params = {"embedding": model.embedding.set_weights(weights),
              "head": {"w": jnp.asarray(
                  np.random.RandomState(7).randn(
                      sum(w for _, w, _ in specs), 1).astype(np.float32))}}
    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.1)
    state = init_fn(params)
    r2 = np.random.RandomState(seed + 1)
    losses = []
    for _ in range(steps):
        cats = []
        for v, _, _ in specs:
            ids = jnp.asarray(r2.randint(0, v, size=(BATCH, 2)))
            if weighted:
                cats.append((ids, jnp.asarray(
                    np.abs(r2.rand(BATCH, 2)).astype(np.float32))))
            else:
                cats.append(ids)
        labels = jnp.asarray(r2.randn(BATCH).astype(np.float32))
        params, state, loss = step_fn(params, state, jnp.zeros((BATCH, 1)),
                                      cats, labels)
        losses.append(float(loss))
    return losses, model.embedding.get_weights(params["embedding"])


TRAIN_SPECS = [(96, 8, "sum"), (50, 8, "sum"), (70, 8, "sum"),
               (300, 8, "sum"), (64, 8, "sum"), (120, 8, "sum"),
               (80, 8, "sum"), (45, 8, "sum")]


def test_sparse_train_f32_wire_bit_exact():
    l0, w0 = _train(TRAIN_SPECS, None)
    lf, wf = _train(TRAIN_SPECS, "f32")
    assert l0 == lf
    for t, (a, b) in enumerate(zip(w0, wf)):
        assert (a == b).all(), f"table {t}"


def test_sparse_train_bf16_wire_tolerance():
    l0, w0 = _train(TRAIN_SPECS, "f32")
    lb, wb = _train(TRAIN_SPECS, "bf16")
    np.testing.assert_allclose(lb, l0, rtol=2e-2, atol=2e-2)
    for t, (a, b) in enumerate(zip(w0, wb)):
        np.testing.assert_allclose(b, a, rtol=2e-2, atol=2e-3,
                                   err_msg=f"table {t}")
    assert lb != l0, "bf16 wire should round at least one loss bit"


def test_sparse_train_bf16_sr_wire_tolerance():
    l0, w0 = _train(TRAIN_SPECS[:4], "f32")
    lb, wb = _train(TRAIN_SPECS[:4], "bf16-sr")
    np.testing.assert_allclose(lb, l0, rtol=2e-2, atol=2e-2)
    for t, (a, b) in enumerate(zip(w0, wb)):
        np.testing.assert_allclose(b, a, rtol=2e-2, atol=2e-3,
                                   err_msg=f"table {t}")


def test_sparse_train_bf16_wire_with_hot_rows():
    # the hot split's exchange (sentinel-masked send + receiver-side
    # weight reconstruction) must survive the compressed wire
    l0, w0 = _train(TRAIN_SPECS[:4], "f32", hot_rows=64, seed=11)
    lb, wb = _train(TRAIN_SPECS[:4], "bf16", hot_rows=64, seed=11)
    np.testing.assert_allclose(lb, l0, rtol=2e-2, atol=2e-2)
    for t, (a, b) in enumerate(zip(w0, wb)):
        np.testing.assert_allclose(b, a, rtol=2e-2, atol=2e-3,
                                   err_msg=f"table {t}")


# --------------------------------------------------------------- report
def test_report_byte_fields():
    d = make_dist(SPECS, exchange_wire="bf16",
                  input_max_hotness=[2] * len(SPECS))
    rep = d.exchange_padding_report()
    for k in ("exchanged_bytes", "true_bytes", "act_bytes", "act_bytes_f32",
              "act_wire_reduction", "wire_dtypes", "id_narrowed_groups"):
        assert k in rep, k
    assert rep["exchanged_bytes"] == sum(
        g["exchanged_bytes"] for g in rep["groups"])
    assert rep["true_bytes"] == sum(g["true_bytes"] for g in rep["groups"])
    for g in rep["groups"]:
        assert g["wire_dtype"] in ("f32", "bf16", "bf16-sr")
        assert g["id_wire_dtype"] in ("int32", "int16")
        assert g["exchanged_bytes"] >= g["true_bytes"]
        id_b = 2 if g["id_wire_dtype"] == "int16" else 4
        assert g["exchanged_bytes"] == (g["exchanged_ids"] * id_b
                                        + g["act_bytes"])
        if g["wire_dtype"] == "bf16":
            assert g["act_bytes"] * 2 == g["act_bytes_f32"]
    # all buckets here are sum/mean -> all bf16 -> exactly 2.0
    assert rep["act_wire_reduction"] == pytest.approx(2.0)
    # the acceptance gate's >= 1.9x activation-byte reduction for bf16
    # buckets, straight from the report
    assert rep["act_wire_reduction"] >= 1.9
    # default wire reports 1.0 (no compression claimed)
    rep0 = make_dist(SPECS).exchange_padding_report()
    assert rep0["act_wire_reduction"] == 1.0
    assert all(g["wire_dtype"] == "f32" for g in rep0["groups"])


# --------------------------------------- HLO-vs-report byte reconciliation
@pytest.mark.parametrize(
    "wire,vocab,weighted,train",
    [
        ("f32", 512, False, True),     # int16 ids, plain train step
        ("bf16", 512, True, True),     # int16 ids + weighted bf16 wire
        ("f32", 40_000, False, False), # int32 ids, forward-only
        ("bf16", 40_000, False, True), # int32 ids + bf16 wire
        ("bf16-sr", 512, False, True), # SR gradient wire: bf16 payloads
    ],
    ids=["f32-i16-train", "bf16-i16-weighted-train", "f32-i32-fwd",
         "bf16-i32-train", "bf16sr-i16-train"])
def test_collective_bytes_match_report_model(wire, vocab, weighted, train):
    """The HLO-measured and report-modeled collective bytes agree
    EXACTLY on every wire config (ISSUE 10 reconciliation):
    `analysis.programs.expected_collective_bytes` turns the
    per-global-sample `exchange_padding_report` fields into per-device
    payload bytes — id wire at the NARROWED dtype (an int16 bucket's
    all_to_all carries i16 at 2 B/element, which is also how
    `hlo_collective_bytes` measures the operand), activations twice in
    a train step (forward + gradient transpose), the weight block
    forward-ONLY (weights are inputs, not params — no gradient flows
    back through the weight exchange). One formula, shared by this test
    and the collective-bytes audit pass, so the static claim and the
    compiled program cannot drift apart again."""
    from distributed_embeddings_tpu.analysis import ir, programs
    from distributed_embeddings_tpu.training import make_sparse_train_step

    tables, width, hot = 2, 8, 2
    mesh = create_mesh(jax.devices()[:8])
    model = programs.build_model(vocab, width, "sum", tables=tables,
                                 mesh=mesh, exchange_wire=wire,
                                 weighted=weighted)
    emb = model.embedding
    params = {"embedding": emb.init(jax.random.PRNGKey(0))}
    cats = [jnp.zeros((BATCH, hot), jnp.int32) for _ in range(tables)]
    if train:
        init_fn, step_fn = make_sparse_train_step(model, "adagrad",
                                                  lr=0.01, donate=False)
        state = init_fn(params)
        num = jnp.zeros((BATCH, 1), jnp.float32)
        lab = jnp.zeros((BATCH,), jnp.float32)
        text = jax.jit(step_fn).lower(params, state, num, cats,
                                      lab).as_text()
    else:
        ins = ([(c, jnp.ones(c.shape, jnp.float32)) for c in cats]
               if weighted else list(cats))
        text = jax.jit(
            lambda p, i: emb.apply(p["embedding"], list(i))).lower(
            params, ins).as_text()
    want = programs.expected_collective_bytes(
        emb, [hot] * tables, batch=BATCH, weighted=weighted, train=train)
    got = ir.collective_bytes(text)["total"]
    assert got == want, (got, want)
    # the id dtype matches the planner's narrowing verdict
    id_dt = "i16" if vocab < 2**15 - 1 else "i32"
    assert id_dt in got
