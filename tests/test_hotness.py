"""Unit tests for the shared counter-based admission core
(utils/hotness.py, ISSUE 4 satellite): serving's HBM cache and the
training hot-row shard admit through this one module, so its policy —
threshold promotion, strictly-hotter eviction, bounded counters,
resident-set resets — is pinned here once for both."""

import numpy as np
import pytest

from distributed_embeddings_tpu.utils.hotness import HotnessTracker


def test_lookup_slots_miss_then_hit_and_stats():
    tr = HotnessTracker(capacity=4, promote_threshold=2)
    keys = np.array([5, 5, 7])
    out = tr.lookup_slots(keys)
    assert (out == -1).all()
    assert tr.misses == 3 and tr.hits == 0
    plan = tr.plan_admissions()            # key 5 crossed threshold (2)
    assert [k for _, k in plan] == [5]
    assert tr.commit_admissions(plan) == 1
    out = tr.lookup_slots(keys)
    assert (out[:2] >= 0).all() and out[2] == -1
    assert tr.hits == 2
    assert tr.stats()["resident"] == 1


def test_valid_mask_excludes_padding_lanes():
    tr = HotnessTracker(capacity=2, promote_threshold=1)
    keys = np.array([[1, 2], [3, 4]])
    valid = np.array([[True, False], [True, False]])
    out = tr.lookup_slots(keys, valid=valid)
    assert out.shape == keys.shape
    assert (out[:, 1] == -1).all()
    # invalid lanes never touched counters or stats
    assert set(tr._counts) == {1, 3}
    assert tr.hits + tr.misses == 2


def test_eviction_only_for_strictly_hotter():
    tr = HotnessTracker(capacity=1, promote_threshold=1)
    tr.observe(np.array([10, 10]))
    tr.commit_admissions(tr.plan_admissions())
    assert tr.resident_keys().tolist() == [10]
    # equally-hot candidate must NOT evict
    tr.observe(np.array([11, 11]))
    assert tr.plan_admissions() == []
    assert tr.evictions == 0
    # strictly hotter candidate evicts the coldest resident
    tr.observe(np.array([11]))
    plan = tr.plan_admissions()
    assert [k for _, k in plan] == [11]
    tr.commit_admissions(plan)
    assert tr.evictions == 1
    assert tr.resident_keys().tolist() == [11]


def test_prune_keeps_residents_and_hottest():
    tr = HotnessTracker(capacity=2, promote_threshold=1, max_tracked=8)
    hot = np.repeat(np.array([100, 101]), 5)
    tr.observe(hot)
    tr.commit_admissions(tr.plan_admissions())
    tr.observe(np.arange(20))              # flood of cold singletons
    assert len(tr._counts) <= 8
    assert {100, 101} <= set(tr._counts)   # residents survive pruning


def test_set_resident_and_top_keys():
    tr = HotnessTracker(capacity=3, promote_threshold=1)
    tr.observe(np.array([1, 1, 1, 2, 2, 3, 4]))
    top = tr.top_keys(2)
    assert top.tolist() == [1, 2]
    tr.set_resident(top)
    assert sorted(tr.resident_keys().tolist()) == [1, 2]
    out = tr.lookup_slots(np.array([1, 2, 3]), observe=False)
    assert (out[:2] >= 0).all() and out[2] == -1
    with pytest.raises(ValueError):
        tr.set_resident(np.array([1, 1]))  # duplicates rejected
    with pytest.raises(ValueError):
        tr.set_resident(np.arange(4))      # over capacity


def test_invalidate_reenters_pending():
    tr = HotnessTracker(capacity=2, promote_threshold=2)
    tr.observe(np.array([9, 9]))
    tr.commit_admissions(tr.plan_admissions())
    tr.invalidate()
    assert tr.resident == 0
    plan = tr.plan_admissions()            # still hot: re-promotable
    assert [k for _, k in plan] == [9]


def test_serving_cache_delegates_to_tracker():
    """The cache's host-side surface IS the tracker (no drift possible):
    its dict/array views alias the tracker's own state."""
    from distributed_embeddings_tpu.serving.cache import HotRowCache

    assert HotRowCache._index.fget is not None   # property, not a dict
    # the tracker type is shared, not a reimplementation
    import inspect
    src = inspect.getsource(HotRowCache.admit)
    assert "plan_admissions" in src and "commit_admissions" in src
