"""Unit tests for the shared counter-based admission core
(utils/hotness.py, ISSUE 4 satellite): serving's HBM cache and the
training hot-row shard admit through this one module, so its policy —
threshold promotion, strictly-hotter eviction, bounded counters,
resident-set resets — is pinned here once for both."""

import numpy as np
import pytest

from distributed_embeddings_tpu.utils.hotness import HotnessTracker


def test_lookup_slots_miss_then_hit_and_stats():
    tr = HotnessTracker(capacity=4, promote_threshold=2)
    keys = np.array([5, 5, 7])
    out = tr.lookup_slots(keys)
    assert (out == -1).all()
    assert tr.misses == 3 and tr.hits == 0
    plan = tr.plan_admissions()            # key 5 crossed threshold (2)
    assert [k for _, k in plan] == [5]
    assert tr.commit_admissions(plan) == 1
    out = tr.lookup_slots(keys)
    assert (out[:2] >= 0).all() and out[2] == -1
    assert tr.hits == 2
    assert tr.stats()["resident"] == 1


def test_valid_mask_excludes_padding_lanes():
    tr = HotnessTracker(capacity=2, promote_threshold=1)
    keys = np.array([[1, 2], [3, 4]])
    valid = np.array([[True, False], [True, False]])
    out = tr.lookup_slots(keys, valid=valid)
    assert out.shape == keys.shape
    assert (out[:, 1] == -1).all()
    # invalid lanes never touched counters or stats
    assert set(tr._counts) == {1, 3}
    assert tr.hits + tr.misses == 2


def test_eviction_only_for_strictly_hotter():
    tr = HotnessTracker(capacity=1, promote_threshold=1)
    tr.observe(np.array([10, 10]))
    tr.commit_admissions(tr.plan_admissions())
    assert tr.resident_keys().tolist() == [10]
    # equally-hot candidate must NOT evict
    tr.observe(np.array([11, 11]))
    assert tr.plan_admissions() == []
    assert tr.evictions == 0
    # strictly hotter candidate evicts the coldest resident
    tr.observe(np.array([11]))
    plan = tr.plan_admissions()
    assert [k for _, k in plan] == [11]
    tr.commit_admissions(plan)
    assert tr.evictions == 1
    assert tr.resident_keys().tolist() == [11]


def test_prune_keeps_residents_and_hottest():
    tr = HotnessTracker(capacity=2, promote_threshold=1, max_tracked=8)
    hot = np.repeat(np.array([100, 101]), 5)
    tr.observe(hot)
    tr.commit_admissions(tr.plan_admissions())
    tr.observe(np.arange(20))              # flood of cold singletons
    assert len(tr._counts) <= 8
    assert {100, 101} <= set(tr._counts)   # residents survive pruning


def test_set_resident_and_top_keys():
    tr = HotnessTracker(capacity=3, promote_threshold=1)
    tr.observe(np.array([1, 1, 1, 2, 2, 3, 4]))
    top = tr.top_keys(2)
    assert top.tolist() == [1, 2]
    tr.set_resident(top)
    assert sorted(tr.resident_keys().tolist()) == [1, 2]
    out = tr.lookup_slots(np.array([1, 2, 3]), observe=False)
    assert (out[:2] >= 0).all() and out[2] == -1
    with pytest.raises(ValueError):
        tr.set_resident(np.array([1, 1]))  # duplicates rejected
    with pytest.raises(ValueError):
        tr.set_resident(np.arange(4))      # over capacity


def test_invalidate_reenters_pending():
    tr = HotnessTracker(capacity=2, promote_threshold=2)
    tr.observe(np.array([9, 9]))
    tr.commit_admissions(tr.plan_admissions())
    tr.invalidate()
    assert tr.resident == 0
    plan = tr.plan_admissions()            # still hot: re-promotable
    assert [k for _, k in plan] == [9]


def test_decay_none_is_all_time_counts():
    """decay=None (and decay=1.0) must keep the original integer
    all-time counters — the pre-decay callers' policy, bit for bit."""
    a = HotnessTracker(capacity=4, promote_threshold=2)
    b = HotnessTracker(capacity=4, promote_threshold=2, decay=1.0)
    for tr in (a, b):
        assert tr.decay is None
        tr.observe(np.array([5]))
        tr.observe(np.array([5]))
        assert tr._counts[5] == 2
        assert [k for _, k in tr.plan_admissions()] == [5]


def test_decay_ages_counts_and_pending():
    """Windowed aging (ISSUE 7): each observing call ages every tracked
    count (lazily — no per-batch dict sweep), so long-running admission
    reflects RECENT frequency — an old-hot key must lose promotion
    eligibility (and eventually tracking) once the stream drifts away
    from it."""
    tr = HotnessTracker(capacity=8, promote_threshold=3, decay=0.5)
    tr.DECAY_SWEEP_EVERY = 4          # test-speed sweep cadence
    tr.observe(np.repeat(np.array([7]), 6))          # count 6 -> pending
    assert 7 in tr._pending
    # drift: key 7 disappears; its true count halves per observation
    for _ in range(3):
        tr.observe(np.array([1, 2]))
    assert tr.counts_for(np.array([7]))[0] < 3
    assert [k for _, k in tr.pending_candidates()] == []   # aged under
    assert 7 not in tr._pending
    # fully aged-out keys leave the dict at the amortized sweep
    for _ in range(8):
        tr.observe(np.array([1, 2]))
    assert 7 not in tr._counts


def test_decay_steady_state_crosses_threshold():
    """A key seen steadily crosses the threshold even under decay (the
    geometric series converges to rate / (1 - decay)), while a one-off
    burst below that equilibrium does not stick."""
    tr = HotnessTracker(capacity=8, promote_threshold=2, decay=0.9)
    for _ in range(5):
        tr.observe(np.array([42]))
    assert [k for _, k in tr.pending_candidates()] == [42]
    # resident keys keep their (decayed) counts trackable for eviction
    # ranking even when aged below epsilon
    tr.commit_admissions(tr.plan_admissions())
    for _ in range(60):
        tr.observe(np.array([1]))
    assert 42 in tr._counts
    assert tr.counts_for(np.array([42, 1]))[0] < tr.counts_for(
        np.array([42, 1]))[1]


def test_pending_candidates_and_drop_pending():
    """The external-binding surface (vocab manager): candidates are
    exposed without slot planning and can be cleared once the caller
    binds them through its own structure."""
    tr = HotnessTracker(capacity=4, promote_threshold=2)
    tr.observe(np.array([5, 5, 9, 9, 9]))
    cands = tr.pending_candidates()
    assert [k for _, k in cands] == [9, 5]           # hottest first
    tr.drop_pending(np.array([9]))
    assert [k for _, k in tr.pending_candidates()] == [5]
    np.testing.assert_array_equal(tr.counts_for(np.array([9, 5, 777])),
                                  [3.0, 2.0, 0.0])


def test_decay_rejects_bad_factor():
    with pytest.raises(ValueError):
        HotnessTracker(capacity=2, decay=0.0)
    with pytest.raises(ValueError):
        HotnessTracker(capacity=2, decay=1.5)


def test_serving_cache_delegates_to_tracker():
    """The cache's host-side surface IS the tracker (no drift possible):
    its dict/array views alias the tracker's own state."""
    from distributed_embeddings_tpu.serving.cache import HotRowCache

    assert HotRowCache._index.fget is not None   # property, not a dict
    # the tracker type is shared, not a reimplementation
    import inspect
    src = inspect.getsource(HotRowCache.admit)
    assert "plan_admissions" in src and "commit_admissions" in src
