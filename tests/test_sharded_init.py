"""Scale-safety tests for parameter materialization.

Round-1 gap #4: init/set_weights built the full [world, rows_max, w] stack on
the host before device_put — impossible at synthetic-small scale (26 GiB).
Now every shard is computed/staged per-device; these tests pin that down by
(a) forbidding global stacking in the mesh path and (b) checking the
resulting arrays are P(axis)-sharded with the right per-rank content.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.layers import dist_model_parallel as dmp
from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.parallel.mesh import create_mesh

SPECS = [(96, 8), (50, 8), (1000, 16), (2000, 16)]


def make_dist(**kw):
    mesh = create_mesh(jax.devices()[:8])
    return dmp.DistributedEmbedding([Embedding(v, w) for v, w in SPECS],
                                    mesh=mesh, strategy="memory_balanced",
                                    **kw)


def test_init_never_stacks_globally(monkeypatch):
    dist = make_dist()

    def no_stack(*a, **k):
        raise AssertionError("global jnp.stack in mesh init path")

    monkeypatch.setattr(dmp.jnp, "stack", no_stack)
    params = dist.init(jax.random.PRNGKey(0))
    for arr in params["tp"] + params["row"]:
        assert arr.shape[0] == 8
        # sharded one rank per device along axis 0
        assert len(arr.sharding.device_set) == 8
        for sh in arr.addressable_shards:
            assert sh.data.shape[0] == 1


def test_set_weights_never_stacks_globally(monkeypatch):
    dist = make_dist(column_slice_threshold=400, row_slice_threshold=30000)
    rng = np.random.RandomState(0)
    weights = [rng.randn(v, w).astype(np.float32) for v, w in SPECS]

    def no_stack(*a, **k):
        raise AssertionError("global jnp.stack in mesh set_weights path")

    monkeypatch.setattr(dmp.jnp, "stack", no_stack)
    params = dist.set_weights(weights)
    monkeypatch.undo()
    got = dist.get_weights(params)
    for a, b in zip(weights, got):
        np.testing.assert_allclose(b, a, rtol=1e-6)


def test_init_deterministic_across_layouts():
    # same seed -> same global weights regardless of mesh presence
    dist = make_dist()
    params = dist.init(jax.random.PRNGKey(42))
    w_mesh = dist.get_weights(params)

    dist1 = dmp.DistributedEmbedding([Embedding(v, w) for v, w in SPECS],
                                     mesh=None, strategy="memory_balanced")
    w_single = dist1.get_weights(dist1.init(jax.random.PRNGKey(42)))
    # table partitioning differs between world sizes, so only tables that
    # happen to be unsliced whole tables in both layouts are comparable;
    # check shapes always, and dp/whole-table contents where layouts agree
    for a, b in zip(w_mesh, w_single):
        assert a.shape == b.shape


def test_get_weights_reads_shards(monkeypatch):
    dist = make_dist()
    params = dist.init(jax.random.PRNGKey(1))
    # np.asarray on a fully-sharded global jax.Array would assemble the whole
    # stack host-side; get_weights must only convert single-shard data
    real_asarray = np.asarray

    def guarded_asarray(a, *args, **kw):
        if isinstance(a, jax.Array) and hasattr(a, "sharding"):
            if len(a.sharding.device_set) > 1 and a.ndim == 3:
                raise AssertionError("whole stacked param pulled to host")
        return real_asarray(a, *args, **kw)

    monkeypatch.setattr(np, "asarray", guarded_asarray)
    got = dist.get_weights(params)
    assert len(got) == len(SPECS)
