"""Example entry points must actually run (the reference's examples are
its acceptance workloads, SURVEY §2.5) — each main() is driven as a real
subprocess in force-CPU mode at smoke scale, including the dlrm example's
checkpoint save -> params-only-aware resume path."""

import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def run_example(args, timeout=900):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    p = subprocess.run([sys.executable, "-u"] + args, cwd=REPO,
                       capture_output=True, text=True, timeout=timeout,
                       env=env)
    assert p.returncode == 0, (p.stdout[-2000:], p.stderr[-2000:])
    return p.stdout


@pytest.mark.slow
def test_criteo_example_synthetic():
    out = run_example(["examples/criteo/main.py", "--synthetic",
                       "--steps", "6", "--batch_size", "256",
                       "--max_tokens", "2000", "--embedding_dim", "8",
                       "--mlp", "16,1", "--force_cpu"])
    assert "IntegerLookup backend:" in out
    assert "done: 6 steps" in out


@pytest.mark.slow
def test_dlrm_example_synthetic_with_resume(tmp_path):
    ck = str(tmp_path / "ck")
    common = ["examples/dlrm/main.py", "--synthetic", "--force_cpu",
              "--devices", "8", "--batch_size", "64", "--table_scale",
              "0.001", "--embedding_dim", "8", "--top_mlp", "32,1",
              "--bottom_mlp", "16,8", "--warmup_steps", "2",
              "--decay_start_step", "6", "--decay_steps", "2",
              "--lr", "0.1", "--log_every", "2", "--eval_steps", "2",
              "--checkpoint_dir", ck]
    out1 = run_example(common + ["--steps", "4"])
    assert "samples/sec" in out1
    # resume from the saved step (full {params, opt_state} checkpoint)
    out2 = run_example(common + ["--steps", "6"])
    assert "resumed from step" in out2


@pytest.mark.slow
def test_lookup_microbench_interpret():
    out = run_example(["examples/benchmarks/benchmark.py", "--vocab", "600",
                       "--width", "8", "--batch", "64", "--hotness", "4",
                       "--steps", "2", "--interpret", "--force_cpu"])
    assert "pallas" in out.lower() or "xla" in out.lower()


def test_checkpoint_keys_detection(tmp_path):
    """checkpoint_keys distinguishes params-only from full checkpoints
    (the dlrm resume fix) and returns None for unreadable paths."""
    import jax.numpy as jnp
    from distributed_embeddings_tpu.utils import checkpoint as ckpt

    full = {"params": {"w": jnp.ones((2, 2))},
            "opt_state": {"m": jnp.zeros((2, 2))}}
    ckpt.save_checkpoint(str(tmp_path / "full"), full, step=3)
    ckpt.save_checkpoint(str(tmp_path / "ponly"),
                         {"params": full["params"]}, step=3)
    assert ckpt.checkpoint_keys(str(tmp_path / "full"), step=3) == \
        ["opt_state", "params"]
    assert ckpt.checkpoint_keys(str(tmp_path / "ponly"), step=3) == \
        ["params"]
    assert ckpt.checkpoint_keys(str(tmp_path / "nope"), step=1) is None


def test_padding_report_hotness_override():
    """exchange_padding_report accepts an explicit per-tp-input hotness
    vector and validates its length."""
    import jax
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(jax.devices()[:8])
    dist = DistributedEmbedding(
        [Embedding(100 + i, 8, combiner="sum") for i in range(8)],
        mesh=mesh)
    rep1 = dist.exchange_padding_report()                 # hints absent -> 1s
    rep2 = dist.exchange_padding_report(hotness=[5] * 8)
    assert rep2["true_ids"] == 5 * rep1["true_ids"]
    with pytest.raises(ValueError, match="entries"):
        dist.exchange_padding_report(hotness=[1, 2])
