"""training.fit / evaluate — the Keras-model.fit-parity loop driver
(reference synthetic main.py:104-114 model.fit path + dlrm eval loop)."""

import numpy as np
import jax
import jax.numpy as jnp

from distributed_embeddings_tpu import training
from distributed_embeddings_tpu.parallel.mesh import create_mesh

from test_sparse_train import TinyModel

SPECS = [(50, 8, "sum")] * 6


def _data(step):
    r = np.random.RandomState(step % 4)
    cats = [r.randint(0, 50, (16, 2)) for _ in SPECS]
    return (np.zeros((16, 1), np.float32), cats,
            r.randn(16).astype(np.float32))


def _eval_data(step):
    r = np.random.RandomState(100 + step)
    cats = [r.randint(0, 50, (16, 2)) for _ in SPECS]
    return (np.zeros((16, 1), np.float32), cats,
            r.randint(0, 2, 16).astype(np.float32))


def test_fit_sparse_and_dense_paths():
    mesh = create_mesh(jax.devices()[:8])
    for sparse in (True, False):
        model = TinyModel(SPECS, mesh)
        rng = np.random.RandomState(0)
        params = {
            "embedding": model.embedding.init(jax.random.PRNGKey(0)),
            "head": {"w": jnp.asarray(
                rng.randn(48, 1).astype(np.float32) * 0.1)},
        }
        steps_seen = []

        class CB:
            def on_step(self, step, params, loss):
                steps_seen.append(step)

        params, opt_state, hist = training.fit(
            model, params, _data, steps=25, optimizer="adagrad", lr=0.3,
            sparse=sparse,
            callbacks=(training.BroadcastGlobalVariablesCallback(), CB()),
            log_every=0, log_fn=lambda *_: None)
        assert hist["loss"][-1] < hist["loss"][0] * 0.5, (sparse,
                                                          hist["loss"][::8])
        assert steps_seen == list(range(25))


def test_fit_pipelined_iterable_matches_serial():
    # iterable data goes through the background ingestion pipeline by
    # default; the losses must be bit-identical to the serial inline form
    # (same batches, same order)
    mesh = create_mesh(jax.devices()[:8])
    histories = {}
    for pipelined in (True, False):
        model = TinyModel(SPECS, mesh)
        rng = np.random.RandomState(0)
        params = {
            "embedding": model.embedding.init(jax.random.PRNGKey(0)),
            "head": {"w": jnp.asarray(
                rng.randn(48, 1).astype(np.float32) * 0.1)},
        }
        params, _, hist = training.fit(
            model, params, (_data(i) for i in range(12)), steps=12,
            optimizer="adagrad", lr=0.3, pipelined=pipelined,
            log_every=0, log_fn=lambda *_: None)
        histories[pipelined] = hist
    np.testing.assert_array_equal(histories[True]["loss"],
                                  histories[False]["loss"])
    # per-stage ingestion accounting rides the history
    stages = histories[True]["ingest_stages"]
    assert set(stages) == {"read", "stage"}
    assert all(v["count"] == 12 for v in stages.values())


def test_evaluate_auc_range():
    mesh = create_mesh(jax.devices()[:8])
    model = TinyModel(SPECS, mesh)
    rng = np.random.RandomState(1)
    params = {
        "embedding": model.embedding.init(jax.random.PRNGKey(1)),
        "head": {"w": jnp.asarray(rng.randn(48, 1).astype(np.float32) * 0.1)},
    }
    auc = training.evaluate(model, params, _eval_data, steps=4)
    assert 0.0 <= auc <= 1.0
