"""Comm-design tests for the dp->mp exchange.

Round-2 guarantee (VERDICT round-1 items 1/3/4): table-parallel ids move via
fixed-shape `lax.all_to_all` exchange groups — per-device id traffic is
O(owned features x true hotness), like the reference's hvd.alltoall with
per-destination splits (reference dist_model_parallel.py:169-288), NOT an
all_gather of every feature's ids to every device; and one-hot inputs are
never padded to the model's global max hotness.
"""

import re

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.parallel.mesh import create_mesh

BATCH = 16


def make_dist(specs, **kw):
    mesh = create_mesh(jax.devices()[:8])
    embeddings = []
    for spec in specs:
        v, w = spec[0], spec[1]
        c = spec[2] if len(spec) > 2 else None
        embeddings.append(Embedding(v, w, combiner=c))
    dist = DistributedEmbedding(embeddings, mesh=mesh, **kw)
    weights = [np.zeros((s[0], s[1]), np.float32) for s in specs]
    params = dist.set_weights(weights)
    return dist, params


def lowered_text(dist, params, inputs):
    return jax.jit(lambda p, i: dist.apply(p, i)).lower(params, inputs).as_text()


def test_tp_exchange_is_all_to_all_not_all_gather():
    specs = [(96, 8), (50, 8), (100, 16), (120, 8), (40, 16), (70, 8),
             (60, 8), (81, 8)]
    dist, params = make_dist(specs, strategy="memory_balanced")
    inputs = [jnp.zeros((BATCH,), jnp.int32) for _ in specs]
    txt = lowered_text(dist, params, inputs)
    assert len(re.findall(r"all_to_all", txt)) > 0
    # pure table-parallel model: no all_gather anywhere in the forward
    assert len(re.findall(r"all_gather", txt)) == 0


def test_row_slice_still_uses_all_gather():
    # row slicing legitimately all_gathers ids (reference grouped_allgather
    # :893); make sure the tp rewrite did not break that path's lowering
    specs = [(4000, 8), (96, 8), (50, 8), (80, 8)]
    dist, params = make_dist(specs, strategy="memory_balanced",
                             row_slice_threshold=16000)
    inputs = [jnp.zeros((BATCH,), jnp.int32) for _ in specs]
    txt = lowered_text(dist, params, inputs)
    assert len(re.findall(r"all_gather", txt)) > 0


def test_no_global_hotness_padding():
    # one hotness-64 input next to one-hot inputs: the one-hot ids must
    # exchange in their own k=1 group, not be padded 64x (round-1 Weak #3)
    specs = [(500, 8, "sum")] + [(100 + i, 8) for i in range(7)]
    dist, params = make_dist(specs, strategy="memory_balanced")
    prep = dist._prepare_inputs(
        [jnp.zeros((BATCH, 64), jnp.int32)]
        + [jnp.zeros((BATCH,), jnp.int32)] * 7)
    tp_prep = [prep[i] for i in dist.strategy.input_groups[1]]
    groups, assembly = dist._exchange_groups(tp_prep)
    ks = sorted(g.k for g in groups)
    assert ks[0] == 1 and ks[-1] == 64
    # total exchanged id elements per batch row = sum over groups of
    # world * f_max * k; must be far below the padded-K_max cost
    vol = sum(g.sel.size * g.k for g in groups)
    padded_vol = 8 * max(g.f_max for g in groups) * 64 * len(groups)
    n_tp = len(tp_prep)
    # old design: every input padded to k=64 and gathered to all 8 devices
    old_vol = 8 * n_tp * 64
    assert vol < old_vol / 4, (vol, old_vol)
    # every input appears exactly once per owning slot in the assembly
    assert sorted(i for g in groups for i in g.class_inputs) == sorted(
        set(range(n_tp)))
    assert all(len(a) >= 1 for a in assembly)


def test_group_cache_hit():
    specs = [(96, 8), (50, 8)]
    dist, params = make_dist(specs)
    prep = dist._prepare_inputs([jnp.zeros((BATCH,), jnp.int32)] * 2)
    tp_prep = [prep[i] for i in dist.strategy.input_groups[1]]
    g1 = dist._exchange_groups(tp_prep)
    g2 = dist._exchange_groups(tp_prep)
    assert g1 is g2


def test_multihot_mixed_hotness_equivalence():
    # inputs of different hotness to same-width tables: correctness of the
    # group split + reassembly (the old path padded these to a common K)
    rng = np.random.RandomState(0)
    specs = [(96, 8, "sum"), (50, 8, "sum"), (70, 8, "mean"), (60, 8, "sum")]
    dist, _ = make_dist(specs, strategy="memory_balanced")
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in specs]
    params = dist.set_weights(weights)
    hot = [1, 7, 3, 7]
    inputs = [jnp.asarray(rng.randint(0, specs[t][0], size=(BATCH, hot[t])))
              for t in range(4)]
    outs = dist.apply(params, inputs)
    for t, (v, w, c) in enumerate(specs):
        emb = weights[t][np.asarray(inputs[t])]       # [B, k, w]
        ref = emb.sum(1) if c == "sum" else emb.mean(1)
        np.testing.assert_allclose(np.asarray(outs[t]), ref, rtol=1e-5,
                                   atol=1e-5, err_msg=f"table {t}")


def test_exchange_padding_report_and_auto_strategy():
    """VERDICT r2 item 4: with comm_balanced (the 'auto' default for
    multi-hot models) the fixed-shape exchange moves close to the
    reference's true-splits volume — within 1.2x of true nnz on the jumbo
    synthetic config — and strictly less padding than memory_balanced."""
    from distributed_embeddings_tpu.models.synthetic import (
        SYNTHETIC_MODELS, SyntheticModel)

    mesh = create_mesh(jax.devices()[:8])

    def build(strategy):
        return SyntheticModel(SYNTHETIC_MODELS["jumbo"], mesh=mesh,
                              strategy=strategy).embedding

    auto = build("auto")
    # the auto default resolved to comm_balanced (jumbo is multi-hot)
    assert auto.strategy.strategy == "comm_balanced"
    rep_auto = auto.exchange_padding_report()
    rep_mem = build("memory_balanced").exchange_padding_report()
    # same true volume (placement-independent), less padded volume
    assert rep_auto["true_ids"] == rep_mem["true_ids"]
    assert rep_auto["exchanged_ids"] <= rep_mem["exchanged_ids"]
    assert rep_auto["ratio"] <= 1.2, rep_auto
    # report internals are consistent
    assert rep_auto["exchanged_ids"] == sum(
        g["exchanged_ids"] for g in rep_auto["groups"])
    assert all(g["f_max"] == max(g["features_per_rank"])
               for g in rep_auto["groups"])
    # byte-level wire accounting (ISSUE 5): every group carries its wire
    # formats and the id+activation byte totals; top-level sums agree
    for g in rep_auto["groups"]:
        for k in ("wire_dtype", "id_wire_dtype", "act_width", "act_bytes",
                  "act_bytes_f32", "exchanged_bytes", "true_bytes",
                  "weight_bytes_if_weighted"):
            assert k in g, k
        assert g["exchanged_bytes"] >= g["true_bytes"]
    assert rep_auto["exchanged_bytes"] == sum(
        g["exchanged_bytes"] for g in rep_auto["groups"])
    assert rep_auto["true_bytes"] == sum(
        g["true_bytes"] for g in rep_auto["groups"])
    # default wire: no compression claimed, all-f32 buckets
    assert rep_auto["act_wire_reduction"] == 1.0
    assert set(rep_auto["wire_dtypes"].values()) <= {"f32"}
    assert isinstance(rep_auto["id_narrowed_groups"], list)


def test_exchange_report_bf16_wire_bytes():
    """A bf16-wire layer's report must show the >= 1.9x activation-byte
    reduction the acceptance gate audits, per bf16 bucket and in total."""
    specs = [(96, 8, "sum"), (50, 8, "sum"), (100, 16, "sum"), (120, 8, "sum")]
    dist, _ = make_dist(specs, exchange_wire="bf16",
                        input_max_hotness=[4, 4, 4, 4])
    rep = dist.exchange_padding_report()
    assert all(g["wire_dtype"] == "bf16" for g in rep["groups"])
    for g in rep["groups"]:
        assert g["act_bytes_f32"] / g["act_bytes"] == pytest.approx(2.0)
    assert rep["act_wire_reduction"] >= 1.9
    # small vocabs: the id wire narrowed too, and the narrowing is
    # visible per group
    assert all(g["id_wire_dtype"] == "int16" for g in rep["groups"])
    assert rep["id_narrowed_groups"] == list(range(len(rep["groups"])))


def test_touched_rows_per_step_schema():
    """Touched-row accounting (ISSUE 6): every report group carries
    `touched_rows_per_step` (the dedup'd post-sentinel-mask ids the
    sparse update writes per step — the number the row-delta size model
    is built on) and `delta_bytes_per_step` = touched * (8 id bytes +
    4 * width); batch scales it, the bucket's total rows bound it, and
    hot-hit lanes subtract (they skip the canonical scatter)."""
    specs = [(96, 8, "sum"), (50, 8, "sum"), (100, 16, "sum"),
             (120, 8, "sum")]
    dist, _ = make_dist(specs, input_max_hotness=[4, 4, 4, 4])
    rep = dist.exchange_padding_report()
    for g in rep["groups"]:
        bucket = dist.plan.tp_buckets[g["bucket"]]
        assert g["touched_rows_per_step"] == g["true_ids"]  # per-sample
        assert g["delta_bytes_per_step"] == (
            g["touched_rows_per_step"] * (8 + 4 * bucket.width))
    assert rep["touched_rows_per_step"] == sum(
        g["touched_rows_per_step"] for g in rep["groups"])
    assert rep["delta_bytes_per_step"] == sum(
        g["delta_bytes_per_step"] for g in rep["groups"])

    # batch scaling caps at the bucket's total row count (dedup bound)
    rep_b = dist.exchange_padding_report(batch=10 ** 6)
    for g in rep_b["groups"]:
        bucket = dist.plan.tp_buckets[g["bucket"]]
        cap = dist.world_size * max(bucket.rows_max, 1)
        assert g["touched_rows_per_step"] == cap
    assert (rep_b["touched_rows_per_step"]
            > rep["touched_rows_per_step"])

    # hot-hit lanes are sentinel-masked: they leave the canonical
    # touched set (the delta still republishes them via the merged
    # view, but the SPARSE UPDATE's write volume is post-hot)
    hot_specs = [(500, 8, "sum")] + [(100 + i, 8) for i in range(7)]
    hot_dist, _ = make_dist(hot_specs, hot_rows=64,
                            input_max_hotness=[4] + [1] * 7)
    assert hot_dist._hot_buckets
    r0 = hot_dist.exchange_padding_report()
    r1 = hot_dist.exchange_padding_report(hot_hit_rate=0.5)
    hot_g0 = [g for g in r0["groups"]
              if g["bucket"] in hot_dist._hot_buckets]
    hot_g1 = [g for g in r1["groups"]
              if g["bucket"] in hot_dist._hot_buckets]
    assert sum(g["touched_rows_per_step"] for g in hot_g1) < sum(
        g["touched_rows_per_step"] for g in hot_g0)
    for g in hot_g1:
        assert g["touched_rows_per_step"] == g["true_ids_post_hot"]
        # ... but the BYTE model re-adds the republished hot-hit rows
        # (the delta carries their merged values), so it exceeds the
        # canonical-write term alone
        bucket = hot_dist.plan.tp_buckets[g["bucket"]]
        assert g["delta_bytes_per_step"] == (
            (g["touched_rows_per_step"]
             + min(g["hot_hit_ids"], bucket.hot_rows))
            * (8 + 4 * bucket.width))
        assert g["delta_bytes_per_step"] > (
            g["touched_rows_per_step"] * (8 + 4 * bucket.width))


def test_delta_bytes_storage_dtype_aware():
    """ISSUE 15 satellite: `delta_bytes_per_step` charges the STREAM's
    storage dtype through the ONE shared formula
    (`wire.delta_row_bytes` — 8 key bytes + width x payload itemsize +
    per-row scale), not a hardcoded f32 row; every group also reports
    its bucket's at-rest `storage_dtype`, and `DET_DELTA_DTYPE` is the
    report's default."""
    from distributed_embeddings_tpu.ops import wire as wire_ops

    specs = [(96, 8, "sum"), (50, 8, "sum"), (100, 16, "sum"),
             (120, 8, "sum")]
    dist, _ = make_dist(specs, input_max_hotness=[4, 4, 4, 4])
    r32 = dist.exchange_padding_report()
    r8 = dist.exchange_padding_report(delta_dtype="int8")
    assert r32["delta_dtype"] == "f32" and r8["delta_dtype"] == "int8"
    for g32, g8 in zip(r32["groups"], r8["groups"]):
        bucket = dist.plan.tp_buckets[g32["bucket"]]
        # device-resident buckets: at-rest storage stays f32 by the gate
        assert g32["storage_dtype"] == "f32"
        assert g32["delta_bytes_per_step"] == (
            g32["touched_rows_per_step"]
            * wire_ops.delta_row_bytes(bucket.width, "f32"))
        assert g8["delta_bytes_per_step"] == (
            g8["touched_rows_per_step"]
            * wire_ops.delta_row_bytes(bucket.width, "int8"))
        assert g8["delta_bytes_per_step"] < g32["delta_bytes_per_step"]
    assert r8["delta_bytes_per_step"] == sum(
        g["delta_bytes_per_step"] for g in r8["groups"])
    assert set(r32["storage_dtypes"]) == set(
        range(len(dist.plan.tp_buckets)))

    # the env default drives the report like DET_EXCHANGE_WIRE drives
    # the wire (explicit argument wins)
    import os
    os.environ["DET_DELTA_DTYPE"] = "int8"
    try:
        assert dist.exchange_padding_report()["delta_dtype"] == "int8"
        assert dist.exchange_padding_report(
            delta_dtype="f32")["delta_dtype"] == "f32"
    finally:
        del os.environ["DET_DELTA_DTYPE"]


def test_lookahead_prefetch_report_schema():
    """Overlap-window accounting (ISSUE 9): with `lookahead > 0` every
    report group carries `prefetch_patch_rows_per_step` (worst case —
    the previous step's touched rows all reappearing in the prefetched
    batch, i.e. exactly `touched_rows_per_step` with its dedup bound)
    and `prefetch_patch_bytes_per_step` (id wire + one activation slot
    at the bucket's wire per patched row — the EXTRA exchange traffic
    the overlap window adds). lookahead=0 reports zeros: the sequential
    step has no patch."""
    from distributed_embeddings_tpu.ops import wire as wire_ops

    specs = [(96, 8, "sum"), (50, 8, "sum"), (100, 16, "sum"),
             (120, 8, "sum")]
    dist, _ = make_dist(specs, input_max_hotness=[4, 4, 4, 4])

    r0 = dist.exchange_padding_report()
    assert r0["lookahead"] == 0
    assert r0["prefetch_patch_rows_per_step"] == 0
    assert r0["prefetch_patch_bytes_per_step"] == 0
    for g in r0["groups"]:
        assert g["prefetch_patch_rows_per_step"] == 0
        assert g["prefetch_patch_bytes_per_step"] == 0

    r1 = dist.exchange_padding_report(lookahead=1, batch=64)
    assert r1["lookahead"] == 1
    for g in r1["groups"]:
        bucket = dist.plan.tp_buckets[g["bucket"]]
        assert (g["prefetch_patch_rows_per_step"]
                == g["touched_rows_per_step"])
        id_b = wire_ops.id_wire_itemsize(bucket.id_wire_dtype)
        wire_b = wire_ops.wire_itemsize(bucket.wire_dtype)
        assert g["prefetch_patch_bytes_per_step"] == (
            g["prefetch_patch_rows_per_step"]
            * (id_b + g["act_width"] * wire_b))
    assert r1["prefetch_patch_rows_per_step"] == sum(
        g["prefetch_patch_rows_per_step"] for g in r1["groups"])
    assert r1["prefetch_patch_bytes_per_step"] == sum(
        g["prefetch_patch_bytes_per_step"] for g in r1["groups"])
    # batch scales the window until the dedup bound caps it
    r_big = dist.exchange_padding_report(lookahead=1, batch=10 ** 6)
    assert (r_big["prefetch_patch_rows_per_step"]
            >= r1["prefetch_patch_rows_per_step"])
    for g in r_big["groups"]:
        bucket = dist.plan.tp_buckets[g["bucket"]]
        assert (g["prefetch_patch_rows_per_step"]
                <= dist.world_size * max(bucket.rows_max, 1))


def test_vocab_occupancy_report_schema():
    """Capacity accounting (ISSUE 7): every report group carries
    `occupancy` (live rows / capacity rows), `slack_rows` (pre-reserved
    growth rows in the bucket) and `evictions_per_step`; a static plan
    reads fully-bound/zero, a slack plan with a live VocabManager reads
    the measured binding state."""
    specs = [(96, 8, "sum"), (50, 8, "sum"), (100, 16, "sum"),
             (120, 8, "sum")]
    dist, _ = make_dist(specs, input_max_hotness=[4, 4, 4, 4])
    rep = dist.exchange_padding_report()
    for g in rep["groups"]:
        assert g["occupancy"] == 1.0          # static vocab: all rows live
        assert g["slack_rows"] == 0
        assert g["evictions_per_step"] == 0.0
    assert rep["occupancy"] == 1.0
    assert rep["slack_rows"] == 0
    assert rep["evictions_per_step"] == 0.0

    from distributed_embeddings_tpu.vocab import VocabManager
    dist_s = DistributedEmbedding(
        [Embedding(v, w, combiner=c) for v, w, c in specs],
        mesh=create_mesh(jax.devices()[:8]),
        input_max_hotness=[4, 4, 4, 4], vocab_slack=16)
    mgr = VocabManager(dist_s, admit_threshold=1, use_native=False)
    mgr.vocabs[0].bind([10**9, 10**9 + 1, 10**9 + 2])
    mgr.maintain_cycles = 2
    mgr.vocabs[0].evictions = 4
    rep_s = dist_s.exchange_padding_report(vocab=mgr)
    assert rep_s["slack_rows"] == sum(
        b.slack_rows for b in dist_s.plan.tp_buckets)
    assert rep_s["slack_rows"] > 0
    assert 0.0 < rep_s["occupancy"] < 1.0     # mostly-unbound manager
    assert rep_s["evictions_per_step"] == pytest.approx(2.0)
    for g in rep_s["groups"]:
        assert 0.0 < g["occupancy"] <= 1.0
        assert g["slack_rows"] >= 0
        assert g["evictions_per_step"] >= 0.0


def test_one_hot_auto_resolves_basic():
    specs = [(96, 8), (50, 8), (100, 16), (120, 8)]
    dist, _ = make_dist(specs, input_max_hotness=[1, 1, 1, 1])
    assert dist.strategy.strategy == "basic"


def test_ragged_exchange_auto_policy(monkeypatch):
    """DET_RAGGED_EXCHANGE=auto (the round-4 default): per-group policy
    picks the true-splits exchange on TPU iff padded volume > 1.5x true
    ids; CPU always takes padded; '1'/'0' force."""
    import types
    specs = [(96, 8, "sum"), (50, 8, "sum")]
    dist, _ = make_dist([(v, w) for v, w, _ in specs],
                        input_max_hotness=[4, 4])

    grp_pad = types.SimpleNamespace(rank_slots=[[0], [], [], [], [], [], [],
                                                []], k=4, f_max=1, bucket=0)
    grp_tight = types.SimpleNamespace(rank_slots=[[0]] * 8, k=4, f_max=1,
                                      bucket=1)
    monkeypatch.delenv("DET_RAGGED_EXCHANGE", raising=False)
    # CPU backend: auto never takes the ragged path
    assert not dist._use_ragged_exchange(grp_pad, 8)
    # force flags work regardless of backend
    monkeypatch.setenv("DET_RAGGED_EXCHANGE", "1")
    assert dist._use_ragged_exchange(grp_pad, 8)
    assert not dist._use_ragged_exchange(grp_pad, 1)   # world 1: no exchange
    monkeypatch.setenv("DET_RAGGED_EXCHANGE", "0")
    assert not dist._use_ragged_exchange(grp_pad, 8)
    # auto on a (mocked) TPU backend: ratio decides
    monkeypatch.setenv("DET_RAGGED_EXCHANGE", "auto")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert dist._use_ragged_exchange(grp_pad, 8)       # 8x padding
    assert not dist._use_ragged_exchange(grp_tight, 8)  # 1.0x padding


# execution-bound on the single-core CPU test host (see
# .claude/skills/verify/SKILL.md): runs in the `-m slow` tier so the
# not-slow tier-1 sweep completes inside its time budget
@pytest.mark.slow
def test_ragged_exchange_equivalence(monkeypatch):
    """DET_RAGGED_EXCHANGE=1 (true-splits exchange, CPU emulation) must be
    numerically identical to the padded exchange across mixed hotness,
    shared tables, combiners AND input forms — dense, RaggedIds and
    explicit (ids, weights) all ride the exchange (ragged/sparse inputs
    synthesize mask weights, so the weight exchange is load-bearing for
    exactly the workloads the padding problem is about). Metadata, layout
    and reassembly are the parts the CPU can prove; the op itself is
    validated on hardware by tools/tpu_ragged_check.py."""
    from distributed_embeddings_tpu.ops.embedding_ops import RaggedIds

    rng = np.random.RandomState(17)
    specs = [(96, 8, "sum"), (50, 8, "sum"), (70, 8, "mean"), (300, 8, "sum"),
             (64, 8, "sum"), (120, 8, "mean"), (80, 8, "sum"), (45, 8, "sum")]
    table_map = list(range(8)) + [1]
    hot = [1, 7, 3, 5, 1, 2, 4, 1, 7]
    inputs = []
    for i, t in enumerate(table_map):
        v, k = specs[t][0], hot[i]
        if i % 3 == 1 and k > 1:          # RaggedIds (synthesized weights)
            lengths = rng.randint(1, k + 1, size=BATCH)
            values = rng.randint(0, v, size=int(lengths.sum()))
            splits = np.cumsum([0] + list(lengths))
            inputs.append(RaggedIds(jnp.asarray(values.astype(np.int32)),
                                    jnp.asarray(splits.astype(np.int32))))
        elif i % 3 == 2 and k > 1:        # explicit weights
            ids = rng.randint(0, v, size=(BATCH, k))
            w = np.abs(rng.rand(BATCH, k)).astype(np.float32)
            inputs.append((jnp.asarray(ids), jnp.asarray(w)))
        else:                             # dense, weightless
            inputs.append(jnp.asarray(rng.randint(0, v, size=(BATCH, k))))
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in specs]

    outs = {}
    for ragged in (False, True):
        monkeypatch.setenv("DET_RAGGED_EXCHANGE", "1" if ragged else "0")
        dist, _ = make_dist(specs, input_table_map=table_map,
                            input_max_hotness=hot,
                            strategy="comm_balanced")
        params = dist.set_weights(weights)
        outs[ragged] = [np.asarray(o) for o in dist.apply(params, inputs)]
    for i, (a, b) in enumerate(zip(outs[False], outs[True])):
        np.testing.assert_allclose(b, a, rtol=1e-6, atol=1e-6,
                                   err_msg=f"output {i}")


def test_ragged_exchange_sparse_train(monkeypatch):
    """Sparse train steps (residual ids flow through the exchange) under
    the ragged flag match the padded path bit-for-bit."""
    import jax
    from test_sparse_train import TinyModel
    from distributed_embeddings_tpu.training import make_sparse_train_step

    rng = np.random.RandomState(23)
    specs = [(96, 8, "sum"), (50, 8, "sum"), (70, 8, "sum"), (300, 8, "sum"),
             (64, 8, "sum"), (120, 8, "sum"), (80, 8, "sum"), (45, 8, "sum")]
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in specs]
    mesh = create_mesh(jax.devices()[:8])
    results = []
    for ragged in (False, True):
        monkeypatch.setenv("DET_RAGGED_EXCHANGE", "1" if ragged else "0")
        model = TinyModel(specs, mesh, input_max_hotness=[3] * 8)
        init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=0.1)
        params = {"embedding": model.embedding.set_weights(weights),
                  "head": {"w": jnp.asarray(np.random.RandomState(7).randn(
                      sum(w for _, w, _ in specs), 1).astype(np.float32))}}
        state = init_fn(params)
        r2 = np.random.RandomState(3)
        losses = []
        for _ in range(2):
            cats = [jnp.asarray(r2.randint(0, v, size=(BATCH, 3)))
                    for v, _, _ in specs]
            labels = jnp.asarray(r2.randn(BATCH).astype(np.float32))
            params, state, loss = step_fn(params, state,
                                          jnp.zeros((BATCH, 1)), cats,
                                          labels)
            losses.append(float(loss))
        results.append((losses,
                        model.embedding.get_weights(params["embedding"])))
    (l_pad, w_pad), (l_rag, w_rag) = results
    np.testing.assert_allclose(l_rag, l_pad, rtol=1e-6, atol=1e-7)
    for t, (a, b) in enumerate(zip(w_pad, w_rag)):
        np.testing.assert_allclose(b, a, rtol=1e-6, atol=1e-6,
                                   err_msg=f"table {t}")


@pytest.mark.skipif(not hasattr(jax.lax, "ragged_all_to_all"),
                    reason="this jax has no lax.ragged_all_to_all; the "
                           "emulation path is covered by "
                           "test_ragged_exchange_equivalence")
def test_ragged_exchange_native_lowering(monkeypatch):
    """With DET_RAGGED_NATIVE=1 the exchange lowers to the real
    lax.ragged_all_to_all op (compile needs a TPU backend — XLA:CPU has no
    lowering — but the STABLEHLO lowering is backend-checkable here)."""
    monkeypatch.setenv("DET_RAGGED_EXCHANGE", "1")
    monkeypatch.setenv("DET_RAGGED_NATIVE", "1")
    specs = [(96, 8, "sum"), (50, 8, "sum"), (70, 8, "sum"), (45, 8, "sum")]
    dist, params = make_dist(specs, input_max_hotness=[3] * 4)
    inputs = [jnp.zeros((BATCH, 3), jnp.int32) for _ in specs]
    txt = jax.jit(lambda p, i: dist.apply(p, i)).lower(params,
                                                       inputs).as_text()
    assert "ragged_all_to_all" in txt, txt[:2000]




def test_exchange_report_matches_registry_gauges_after_driven_run():
    """ISSUE 11 consistency seam: the `exchange/*` gauges a driven
    `training.fit` exports must EQUAL a fresh
    `exchange_padding_report` over the same (batch, vocab, lookahead)
    arguments — touched_rows_per_step, occupancy and
    prefetch_patch_rows_per_step at both the top level and per group.
    The model is static accounting either way; what this pins is the
    WIRING (fit exporting the report's numbers, with the live manager,
    at the run's true batch size, after the tail vocab cycle)."""
    from distributed_embeddings_tpu import obs, training
    from distributed_embeddings_tpu.vocab import VocabManager
    from distributed_embeddings_tpu.obs.instrument import (
        EXCHANGE_GAUGE_FIELDS, EXCHANGE_GROUP_GAUGE_FIELDS)

    sizes = [(48, 8), (32, 8), (100, 8), (64, 8)]
    dist = DistributedEmbedding(
        [Embedding(v, w, combiner="sum") for v, w in sizes],
        mesh=create_mesh(jax.devices()[:8]),
        strategy="memory_balanced", vocab_slack=16)

    class _M:
        def __init__(self, emb):
            self.embedding = emb

        def loss_fn(self, params, numerical, cats, labels, taps=None,
                    return_residuals=False):
            outs, res = self.embedding.apply(
                params["embedding"], cats, taps=taps,
                return_residuals=True)
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    model = _M(dist)
    mgr = VocabManager(dist, admit_threshold=1, decay=0.99,
                       use_native=False)
    rng = np.random.RandomState(3)

    def data(step):
        cats = [rng.randint(10**8, 10**8 + 40,
                            size=(16, 2)).astype(np.int64) for _ in sizes]
        return (np.zeros((16, 1), np.float32), cats,
                rng.randn(16).astype(np.float32))

    reg = obs.MetricRegistry()
    params = {"embedding": dist.init(jax.random.PRNGKey(0))}
    params, _, hist = training.fit(
        model, params, data, steps=6, optimizer="adagrad", lr=0.05,
        vocab=mgr, vocab_every=3, registry=reg, log_every=0)
    assert "metrics_error" not in hist, hist.get("metrics_error")

    gauges = reg.snapshot()["gauges"]
    rep = dist.exchange_padding_report(batch=16, vocab=mgr, lookahead=0)
    for field in EXCHANGE_GAUGE_FIELDS:
        assert gauges[f"exchange/{field}"] == pytest.approx(rep[field]), \
            field
    for gi, entry in enumerate(rep["groups"]):
        for field in EXCHANGE_GROUP_GAUGE_FIELDS:
            key = (f"exchange/{field}"
                   f"{{bucket={entry['bucket']},group={gi}}}")
            assert gauges[key] == pytest.approx(entry[field]), key
    # the manager actually moved the needle: a live binding, not the
    # static 1.0 occupancy
    assert 0.0 < gauges["exchange/occupancy"] < 1.0
    assert gauges["exchange/touched_rows_per_step"] > 0
