"""True multi-process SPMD validation over a shared CPU mesh.

The reference's test harness runs every distributed case under real
multiprocess Horovod (`horovodrun -np N`, reference
dist_model_parallel_test.py; SURVEY.md §4). The single-process 8-device
tests elsewhere in this suite cover the SPMD *math*; this file covers the
multi-process *mechanics* the math can't see: jax.distributed bootstrap
(gloo), per-process shard staging in set_weights/init, cross-process
collectives inside shard_map, and process-local input staging.

Topology: 2 processes x 4 virtual CPU devices = the same 8-device mesh the
rest of the suite uses, so checksums are comparable with a 1-process run of
the identical worker (world-size-generic, like the reference's tests).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
WORKER = os.path.join(HERE, "mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run(nproc: int, local_devices: int, out: str, ckpt=None, timeout=600):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    procs = [
        subprocess.Popen(
            [sys.executable, "-u", WORKER, "--pid", str(pid),
             "--nproc", str(nproc), "--port", str(port),
             "--local_devices", str(local_devices), "--out", out]
            + (["--ckpt", ckpt] if ckpt else []),
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for pid in range(nproc)
    ]
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(stdout)
    for pid, (p, log) in enumerate(zip(procs, logs)):
        assert p.returncode == 0, (
            f"worker {pid}/{nproc} rc={p.returncode}:\n{log[-3000:]}")
    with open(out) as f:
        return json.load(f)


@pytest.mark.slow
def test_two_process_matches_single_process(tmp_path):
    multi = _run(2, 4, str(tmp_path / "mp2.json"),
                 ckpt=str(tmp_path / "ck2"))
    single = _run(1, 8, str(tmp_path / "mp1.json"),
                  ckpt=str(tmp_path / "ck1"))
    assert "ckpt_fwd" in multi  # the distributed-checkpoint phase ran
    assert multi == single, (multi, single)


@pytest.mark.slow
def test_four_process_matches_single_process(tmp_path):
    """Same worker over 4 gloo processes x 2 local devices — a different
    process/device factorization of the same 8-device mesh."""
    multi = _run(4, 2, str(tmp_path / "mp4.json"))
    single = _run(1, 8, str(tmp_path / "mp1b.json"))
    assert multi == single, (multi, single)
