"""Training shims, checkpoint round-trips, metrics."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_embeddings_tpu import training
from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.utils import checkpoint as ckpt
from distributed_embeddings_tpu.utils.metrics import StreamingAUC, auc_exact

SIZES = [(96, 8), (50, 8), (1000, 16), (2000, 16)]


def make_dist(world=8, **kw):
    mesh = create_mesh(jax.devices()[:world])
    dist = DistributedEmbedding([Embedding(v, w) for v, w in SIZES],
                                mesh=mesh, strategy="memory_balanced", **kw)
    return dist


def test_make_train_step_converges():
    dist = make_dist()
    params = dist.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    inputs = [jnp.asarray(rng.randint(0, v, (16,)).astype(np.int32))
              for v, _ in SIZES]
    targets = [jnp.asarray(rng.randn(16, w).astype(np.float32))
               for _, w in SIZES]

    def loss_fn(p, inputs):
        outs = dist.apply(p, inputs)
        return sum(jnp.mean((o - t) ** 2) for o, t in zip(outs, targets))

    opt = training.DistributedOptimizer(optax.adam(5e-2))
    opt_state = opt.init(params)
    step = training.make_train_step(loss_fn, opt, donate=False)
    losses = []
    for _ in range(60):
        params, opt_state, loss = step(params, opt_state, inputs)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.2, losses[::20]


def test_sparse_train_checkpoint_resume(tmp_path):
    """Save {params, sparse opt_state} mid-training, restore, continue:
    must match the uninterrupted run exactly (the reference's resume
    contract via get/set_weights, extended to optimizer state)."""
    from distributed_embeddings_tpu.training import make_sparse_train_step
    from distributed_embeddings_tpu.utils import checkpoint as ckpt_lib

    class _M:
        def __init__(self):
            self.embedding = make_dist()

        def loss_fn(self, params, numerical, cats, labels, taps=None,
                    return_residuals=False):
            if taps is not None or return_residuals:
                outs, res = self.embedding.apply(
                    params["embedding"], cats, taps=taps,
                    return_residuals=True)
            else:
                outs, res = self.embedding.apply(params["embedding"],
                                                 cats), None
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    rng = np.random.RandomState(4)
    batches = []
    for _ in range(4):
        batches.append((
            [jnp.asarray(rng.randint(0, v, (16,)).astype(np.int32))
             for v, _ in SIZES],
            jnp.asarray(rng.randn(16).astype(np.float32))))

    def train(n, params, state, step_fn):
        for i in range(n):
            cats, labels = batches[i % len(batches)]
            params, state, loss = step_fn(params, state,
                                          jnp.zeros((16, 1)), cats, labels)
        return params, state

    m1 = _M()
    init_fn, step_fn = make_sparse_train_step(m1, "adagrad", lr=0.1)
    params = {"embedding": m1.embedding.init(jax.random.PRNGKey(0))}
    state = init_fn(params)
    params, state = train(2, params, state, step_fn)
    ckpt_lib.save_checkpoint(str(tmp_path / "ck"),
                             {"params": params, "opt_state": state},
                             force=True)
    params_c, state_c = train(2, params, state, step_fn)

    m2 = _M()
    init2, step2 = make_sparse_train_step(m2, "adagrad", lr=0.1)
    tmpl_params = {"embedding": m2.embedding.init(jax.random.PRNGKey(1))}
    tmpl = {"params": tmpl_params, "opt_state": init2(tmpl_params)}
    restored = ckpt_lib.restore_checkpoint(str(tmp_path / "ck"), tmpl)
    params_r, state_r = train(2, restored["params"], restored["opt_state"],
                              step2)
    got = m2.embedding.get_weights(params_r["embedding"])
    want = m1.embedding.get_weights(params_c["embedding"])
    for t, (a, b) in enumerate(zip(want, got)):
        np.testing.assert_allclose(b, a, rtol=1e-6, atol=1e-6,
                                   err_msg=f"table {t}")


# weight-streaming delta round-trips (ISSUE 6): full snapshot -> K delta
# applies -> bit-exact reconstruction of the live training tables, across
# optimizer x exchange-path x hot-rows. Two combos run in tier-1; the
# rest of the cross product rides the slow tier (each combo compiles its
# own train step on the CPU mesh).
_DELTA_FAST = {("adagrad", False, False), ("sgd", False, True)}
_DELTA_MATRIX = [
    pytest.param(o, r, h,
                 marks=([] if (o, r, h) in _DELTA_FAST
                        else [pytest.mark.slow]))
    for o in ("sgd", "adagrad", "adam")
    for r in (False, True)
    for h in (False, True)
]


@pytest.mark.parametrize("optimizer,ragged,hot", _DELTA_MATRIX)
def test_store_delta_roundtrip_bitexact(optimizer, ragged, hot, tmp_path,
                                        monkeypatch):
    """Live training publishes (snapshot + K row-deltas); a consumer
    reconstructs the MERGED tables bit-exactly at the final version —
    with the hot-row shard resident and re-admitted mid-stream when
    `hot`, and over the true-splits (ragged) exchange when `ragged`."""
    from distributed_embeddings_tpu.store import (TableStore,
                                                  restore_from_published)
    from distributed_embeddings_tpu.training import make_sparse_train_step

    monkeypatch.setenv("DET_RAGGED_EXCHANGE", "1" if ragged else "0")
    mesh = create_mesh(jax.devices()[:8])
    # reducing combiner throughout: the inputs are multi-hot (real dedup
    # work in every delta) and hot shards require it anyway
    emb = DistributedEmbedding(
        [Embedding(v, w, combiner="sum") for v, w in SIZES],
        mesh=mesh, strategy="memory_balanced", row_slice_threshold=30000,
        hot_rows=(8 if hot else None))
    if hot:
        assert emb._hot_buckets

    class _M:
        def __init__(self):
            self.embedding = emb

        def loss_fn(self, params, numerical, cats, labels, taps=None,
                    return_residuals=False):
            if taps is not None or return_residuals:
                outs, res = self.embedding.apply(
                    params["embedding"], cats, taps=taps,
                    return_residuals=True)
            else:
                outs, res = self.embedding.apply(params["embedding"],
                                                 cats), None
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    rng = np.random.RandomState(13)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w in SIZES]
    model = _M()
    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.1)
    p = {"embedding": emb.set_weights(weights)}
    s = init_fn(p)
    store = TableStore(emb, p["embedding"], s["emb"])
    d = str(tmp_path / "stream")
    store.commit(p["embedding"], s["emb"])
    assert store.publish(d)["kind"] == "snapshot"

    def batch():
        cats = [jnp.asarray(rng.randint(0, v, (16, 2)).astype(np.int32))
                for v, _ in SIZES]
        return cats, jnp.asarray(rng.randn(16).astype(np.float32))

    for step in range(3):
        cats, labels = batch()
        store.observe(cats)
        p, s, _ = step_fn(p, s, jnp.zeros((16, 1)), cats, labels)
        store.commit(p["embedding"], s["emb"])
        if hot and step == 0:
            # admit mid-stream: residency changes between deltas, and
            # the merged-view payload must absorb it invisibly
            emb.observe_hot_ids(cats)
            store.sync_hot_rows(admit=True)
            p = {"embedding": store.params}
            s = {**s, "emb": store.opt_states}
            assert emb.hot_resident_rows(store.params)
        store.publish(d)

    want = emb.get_weights(p["embedding"])
    rstore = restore_from_published(emb, d)
    assert rstore.version == store.version
    for t, (a, b) in enumerate(zip(want, emb.get_weights(rstore.params))):
        np.testing.assert_array_equal(
            b, a, err_msg=f"table {t} ({optimizer}, ragged={ragged}, "
                          f"hot={hot})")


# mid-growth vocab round-trips (ISSUE 7): adagrad rides tier-1, the
# other optimizers the slow tier (each combo compiles its own train step)
_VOCAB_CKPT_MATRIX = [
    pytest.param(o, marks=([] if o == "adagrad" else [pytest.mark.slow]))
    for o in ("sgd", "adagrad", "adam")
]


@pytest.mark.parametrize("optimizer", _VOCAB_CKPT_MATRIX)
def test_vocab_midgrowth_store_roundtrip_bitexact(optimizer, tmp_path):
    """A mid-growth table (admit -> evict -> re-admit between training
    steps, with the row inits/restores that implies) must round-trip
    the publish stream bit-exactly: restore_from_published reconstructs
    the publisher's get_weights at the final version, and the binding
    sidecar reconstructs the key->row map — across every sparse
    optimizer (the eviction/rebind path zeroes optimizer-state rows, so
    each rule's laziness is exercised)."""
    import warnings
    from distributed_embeddings_tpu.store import (TableStore,
                                                  restore_from_published)
    from distributed_embeddings_tpu.training import make_sparse_train_step
    from distributed_embeddings_tpu.vocab import VocabManager

    mesh = create_mesh(jax.devices()[:8])
    emb = DistributedEmbedding(
        [Embedding(v, w, combiner="sum") for v, w in SIZES],
        mesh=mesh, strategy="memory_balanced", row_slice_threshold=30000,
        vocab_slack=16)
    mgr = VocabManager(emb, admit_threshold=1, decay=0.9, use_native=False,
                       high_watermark=0.5, low_watermark=0.25)

    class _M:
        def __init__(self):
            self.embedding = emb

        def loss_fn(self, params, numerical, cats, labels, taps=None,
                    return_residuals=False):
            if taps is not None or return_residuals:
                outs, res = self.embedding.apply(
                    params["embedding"], cats, taps=taps,
                    return_residuals=True)
            else:
                outs, res = self.embedding.apply(params["embedding"],
                                                 cats), None
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    rng = np.random.RandomState(13)
    model = _M()
    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.1)
    p = {"embedding": emb.init(jax.random.PRNGKey(0))}
    s = init_fn(p)
    store = TableStore(emb, p["embedding"], s["emb"])
    d = str(tmp_path / "stream")
    store.commit(p["embedding"], s["emb"])
    assert store.publish(d)["kind"] == "snapshot"
    mgr.save_state(str(tmp_path / "stream" / "vocab_v00000001.npz"))

    def raw_batch(universe):
        cats = [np.asarray(rng.randint(universe, universe + 40, (16, 2)),
                           np.int64) for _ in SIZES]
        return cats, jnp.asarray(rng.randn(16).astype(np.float32))

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        for step in range(4):
            # rotate the key universe: admissions AND evictions between
            # every publish (the mid-growth part of the contract)
            cats_raw, labels = raw_batch(10**8 + step * 25)
            for _ in range(2):
                mgr.translate(cats_raw, observe=True)
            cats = mgr.translate(cats_raw, observe=True)
            p_emb, s_emb = mgr.maintain(p["embedding"], s["emb"])
            p = {"embedding": p_emb}
            s = {**s, "emb": s_emb}
            store.observe(cats)
            p, s, _ = step_fn(p, s, jnp.zeros((16, 1)),
                              [jnp.asarray(c) for c in cats], labels)
            store.commit(p["embedding"], s["emb"],
                         touched=mgr.drain_touched())
            info = store.publish(d)
            mgr.save_state(str(
                tmp_path / "stream" / f"vocab_v{info['version']:08d}.npz"))
    st = mgr.stats()
    assert st["admissions"] > 0 and st["evictions"] > 0, st

    want = emb.get_weights(p["embedding"])
    rstore = restore_from_published(emb, d)
    assert rstore.version == store.version
    for t, (a, b) in enumerate(zip(want, emb.get_weights(rstore.params))):
        np.testing.assert_array_equal(
            b, a, err_msg=f"table {t} ({optimizer})")
    # the binding sidecar restores the key->row map at the same version
    from distributed_embeddings_tpu.vocab import latest_vocab_state
    mgr2 = VocabManager(emb, use_native=False)
    mgr2.load_state(latest_vocab_state(d, upto=rstore.version))
    for t in mgr.vocabs:
        np.testing.assert_array_equal(mgr2.vocabs[t].resident_keys(),
                                      mgr.vocabs[t].resident_keys())
        np.testing.assert_array_equal(
            mgr2.vocabs[t].binding.free_slots(),
            mgr.vocabs[t].binding.free_slots())


def test_distributed_optimizer_postprocess():
    """DistributedOptimizer's gradient-postprocess hook must actually shape
    the update (reference: gradient postprocessing via the wrapped
    optimizer)."""
    dist = make_dist()
    params = dist.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(2)
    inputs = [jnp.asarray(rng.randint(0, v, (16,)).astype(np.int32))
              for v, _ in SIZES]

    def loss_fn(p, inputs):
        outs = dist.apply(p, inputs)
        return sum(jnp.sum(o) for o in outs)

    calls = []

    def zero_grads(grads):
        calls.append(1)
        return jax.tree.map(jnp.zeros_like, grads)

    opt = training.DistributedOptimizer(optax.sgd(0.5),
                                        postprocess=zero_grads)
    opt_state = opt.init(params)
    loss, grads = jax.value_and_grad(loss_fn)(params, inputs)
    updates, opt_state = opt.update(grads, opt_state, params)
    new_params = training.apply_updates(params, updates)
    assert calls, "postprocess hook never invoked"
    # zeroed grads -> parameters unchanged
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(new_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # scaling postprocess == scaling the lr for sgd
    opt2 = training.DistributedOptimizer(
        optax.sgd(0.5), postprocess=lambda g: jax.tree.map(lambda x: 2 * x, g))
    st2 = opt2.init(params)
    upd2, _ = opt2.update(grads, st2, params)
    opt3 = training.DistributedOptimizer(optax.sgd(1.0))
    st3 = opt3.init(params)
    upd3, _ = opt3.update(grads, st3, params)
    for a, b in zip(jax.tree.leaves(upd2), jax.tree.leaves(upd3)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_distributed_gradient_tape_shim():
    dist = make_dist()
    params = dist.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    inputs = [jnp.asarray(rng.randint(0, v, (16,)).astype(np.int32))
              for v, _ in SIZES]

    def loss_fn(p):
        return sum(jnp.sum(o) for o in dist.apply(p, inputs))

    tape = training.DistributedGradientTape()
    loss, grads = tape.gradient(loss_fn, params)
    assert np.isfinite(float(loss))
    flat, _ = jax.tree.flatten(grads)
    assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)


def test_broadcast_callback_idempotent():
    cb = training.BroadcastGlobalVariablesCallback()
    params = {"a": jnp.ones((2,))}
    out = cb.on_train_begin(params)
    np.testing.assert_allclose(out["a"], params["a"])
    assert cb.on_train_begin(params) is params  # second call: no-op


def test_orbax_checkpoint_roundtrip(tmp_path):
    dist = make_dist(row_slice_threshold=30000)
    params = dist.init(jax.random.PRNGKey(2))
    path = str(tmp_path / "ckpt")
    ckpt.save_checkpoint(path, params, step=7)
    assert ckpt.latest_step(path) == 7
    restored = ckpt.restore_checkpoint(
        path, params, step=7, shardings=dist.param_shardings())
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # restored arrays carry the plan shardings
    assert restored["tp"][0].sharding == dist.param_shardings()["tp"][0]


def test_global_weights_roundtrip(tmp_path):
    dist = make_dist()
    rng = np.random.RandomState(3)
    weights = [rng.randn(v, w).astype(np.float32) for v, w in SIZES]
    params = dist.set_weights(weights)
    got = dist.get_weights(params)

    npz = ckpt.save_global_weights(str(tmp_path / "emb.npz"), got)
    loaded = ckpt.load_global_weights(npz)
    for a, b in zip(weights, loaded):
        np.testing.assert_allclose(b, a, rtol=1e-6)

    # directory form: file paths feed set_weights' mmap path directly
    d = ckpt.save_global_weights(str(tmp_path / "emb_dir"), got, npz=False)
    files = [os.path.join(d, f"table_{i}.npy") for i in range(len(SIZES))]
    params2 = dist.set_weights(files)
    for a, b in zip(dist.get_weights(params2), weights):
        np.testing.assert_allclose(np.asarray(a), b, rtol=1e-6)


def test_streaming_auc_matches_exact():
    rng = np.random.RandomState(4)
    n = 5000
    labels = (rng.rand(n) > 0.7).astype(np.float32)
    logits = rng.randn(n).astype(np.float32) + labels * 1.5
    metric = StreamingAUC(bins=4096)
    state = metric.init()
    upd = jax.jit(metric.update)
    for i in range(0, n, 1000):
        state = upd(state, jnp.asarray(labels[i:i + 1000]),
                    jnp.asarray(logits[i:i + 1000]))
    got = metric.result(state)
    want = auc_exact(labels, 1 / (1 + np.exp(-logits)))
    assert abs(got - want) < 5e-3, (got, want)
    assert got > 0.7


def test_profiling_benchmark_harness():
    from distributed_embeddings_tpu.utils import profiling

    @jax.jit
    def f(x):
        return x * 2 + 1

    x = jnp.ones((64, 64))
    res = profiling.benchmark(f, x, iters=5, warmup=1)
    assert res.iters == 5
    assert res.mean_s > 0 and res.min_s <= res.mean_s
    with profiling.annotate("region"):
        jax.block_until_ready(f(x))
    assert "mean=" in str(res)
