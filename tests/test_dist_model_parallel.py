"""Distributed equivalence tests.

Mirrors the reference's core test pattern (dist_model_parallel_test.py:
reference-model equivalence): an unsharded pure-JAX model and the sharded
DistributedEmbedding get identical weights, run the same batch, and must
produce identical outputs AND identical post-SGD-update weights — exercising
forward collectives and sharded autodiff in one go. Runs on an 8-virtual-CPU
device mesh (conftest.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.ops.embedding_ops import (
    RaggedIds, SparseIds, embedding_lookup)
from distributed_embeddings_tpu.parallel.mesh import create_mesh

BATCH = 16
LR = 0.5


def make_mesh(n=8):
    return create_mesh(jax.devices()[:n])


def ref_apply(weights, inputs, table_map, combiners):
    outs = []
    for i, t in enumerate(table_map):
        x = inputs[i]
        if isinstance(x, (RaggedIds, SparseIds)):
            out = embedding_lookup(weights[t], x, combiners[t])
        elif isinstance(x, tuple) and len(x) == 2:
            ids, w = x
            emb = jnp.take(weights[t], jnp.asarray(ids), axis=0)
            w = jnp.asarray(w).astype(emb.dtype)
            out = jnp.einsum("bk,bkw->bw", w, emb)
            if combiners[t] == "mean":
                denom = jnp.maximum(jnp.sum(w, axis=-1), 1.0)
                out = out / denom[:, None]
        else:
            x = jnp.asarray(x)
            if x.ndim == 1:
                out = jnp.take(weights[t], x, axis=0)
            else:
                out = embedding_lookup(weights[t], x, combiners[t])
        outs.append(out)
    return outs


def _sgd_respecting_placement(p, g):
    """p - LR*g, keeping offloaded (pinned-host) params in their memory
    space: the update runs in device space, the result is placed back."""
    def dev_sharding(x):
        import jax.sharding as shd
        s = x.sharding
        if isinstance(s, shd.NamedSharding):
            return shd.NamedSharding(s.mesh, s.spec)
        return shd.SingleDeviceSharding(list(x.devices())[0])

    if getattr(getattr(p, "sharding", None), "memory_kind", None) == \
            "pinned_host":
        pd = jax.device_put(p, dev_sharding(p))
        gd = g
        if getattr(getattr(g, "sharding", None), "memory_kind", None) == \
                "pinned_host":
            gd = jax.device_put(g, dev_sharding(g))
        return jax.device_put(pd - LR * gd, p.sharding)
    return p - LR * g


def check_equivalence(specs, world=8, input_table_map=None, inputs=None,
                      seed=0, check_train=True, input_max_hotness=None,
                      rtol=1e-5, atol=1e-5, train_rtol=1e-4, train_atol=1e-5,
                      store_roundtrip=False, vocab_axis=False,
                      lookahead_axis=False,
                      **dist_kwargs):
    """specs: list of (vocab, width) or (vocab, width, combiner).

    store_roundtrip (ISSUE 6): materialize the params through the
    versioned table store's publish/consume path (snapshot file ->
    consumer apply) before running the checks, so every equivalence
    property also holds for store-backed parameters.

    lookahead_axis (ISSUE 9): additionally train this exact plan for a
    few steps through the `schedule.LookaheadEngine` staged pipeline
    and require BIT-exact agreement with the monolithic sparse step
    (losses and final tables) — the prefetch/patch/drain restructuring
    must be invisible across the whole random config space. Configs the
    engine refuses by design (host-offloaded buckets, all-dp plans) are
    skipped for this axis only.

    vocab_axis (ISSUE 7): run the batch as RAW int64 keys through a
    `vocab.VocabManager` over a slack-inflated plan — inputs reach the
    forward as manager-translated physical rows, so every equivalence
    property also holds for dynamically-bound vocabularies (the
    reference model sees the same translated rows over zero-padded
    tables; what this axis exercises is the slack plan + binding
    composition, per-table and shared-table alike)."""
    rng = np.random.RandomState(seed)
    embeddings = []
    combiners = []
    for spec in specs:
        v, w = spec[0], spec[1]
        c = spec[2] if len(spec) > 2 else None
        embeddings.append(Embedding(v, w, combiner=c))
        combiners.append(c)
    table_map = (list(input_table_map) if input_table_map
                 else list(range(len(specs))))

    if inputs is None:
        inputs = []
        for i, t in enumerate(table_map):
            v = specs[t][0]
            c = combiners[t]
            if c is None:
                inputs.append(jnp.asarray(rng.randint(0, v, size=(BATCH,))))
            else:
                inputs.append(jnp.asarray(
                    rng.randint(0, v, size=(BATCH, 2 + (i % 3)))))

    weights = [rng.randn(s[0], s[1]).astype(np.float32) * 0.1 for s in specs]

    mesh = make_mesh(world) if world > 1 else None
    if vocab_axis:
        dist_kwargs.setdefault("vocab_slack", 16)
    dist = DistributedEmbedding(embeddings, mesh=mesh,
                                input_table_map=input_table_map,
                                input_max_hotness=input_max_hotness,
                                **dist_kwargs)
    if check_train and getattr(dist, "quantized_buckets", []):
        # quantized (int8/fp8) offloaded buckets have non-differentiable
        # table leaves: the dense-grad SGD comparison below cannot run,
        # and the SUPPORTED training path for them is the tapped sparse
        # step — its per-optimizer parity matrix lives in
        # test_store_dtype.py. Forward equivalence still checks here.
        check_train = False
    if vocab_axis:
        from distributed_embeddings_tpu.vocab import VocabManager

        # physical shapes are slack-inflated: pad the reference weights
        # with zero growth rows (both sides read the same padded tables)
        weights = [
            np.pad(np.asarray(w, np.float32),
                   ((0, dist.strategy.global_configs[t]["input_dim"]
                     - np.asarray(w).shape[0]), (0, 0)))
            for t, w in enumerate(weights)]
        mgr = VocabManager(dist, admit_threshold=1, use_native=False)

        def to_raw(vals):
            # injective map into a far-away int64 raw-key space
            return np.asarray(jax.device_get(vals),
                              np.int64) * 97 + 3_000_000_017

        raw_inputs, per_table_raw = [], {}
        for i, x in enumerate(inputs):
            t = (list(input_table_map) if input_table_map
                 else list(range(len(specs))))[i]
            if t not in mgr.vocabs:
                raw_inputs.append(x)
                continue
            if isinstance(x, RaggedIds):
                raw = to_raw(x.values)
                raw_inputs.append(RaggedIds(raw, x.row_splits))
            elif isinstance(x, SparseIds):
                raw = to_raw(x.values)
                raw_inputs.append(SparseIds(x.indices, raw, x.dense_shape))
            elif isinstance(x, tuple) and len(x) == 2:
                raw = to_raw(x[0])
                raw_inputs.append((raw, x[1]))
            else:
                raw = to_raw(x)
                raw_inputs.append(raw)
            per_table_raw.setdefault(t, []).append(raw.reshape(-1))
        for t, chunks in per_table_raw.items():
            mgr.vocabs[t].bind(np.unique(np.concatenate(chunks)))
        inputs = mgr.translate(raw_inputs)
    params = dist.set_weights(weights)
    if store_roundtrip:
        import tempfile
        from distributed_embeddings_tpu.store import (TableStore,
                                                      restore_from_published)
        with tempfile.TemporaryDirectory() as stream_dir:
            st = TableStore(dist, params)
            st.commit(params)
            st.publish(stream_dir)
            params = restore_from_published(dist, stream_dir).params

    ref_w = [jnp.asarray(w) for w in weights]
    ref_outs = ref_apply(ref_w, inputs, table_map, combiners)
    dist_outs = dist.apply(params, inputs)

    assert len(ref_outs) == len(dist_outs)
    for i, (a, b) in enumerate(zip(ref_outs, dist_outs)):
        np.testing.assert_allclose(np.asarray(b, np.float32), np.asarray(a),
                                   rtol=rtol, atol=atol,
                                   err_msg=f"output {i}")

    if not check_train:
        return dist, params

    # training equivalence: same loss, compare post-SGD weights
    cots = [jnp.asarray(rng.randn(*o.shape).astype(np.float32))
            for o in ref_outs]

    def dist_loss(p):
        outs = dist.apply(p, inputs)
        return sum(jnp.vdot(o.astype(jnp.float32), c)
                   for o, c in zip(outs, cots))

    def ref_loss(ws):
        outs = ref_apply(ws, inputs, table_map, combiners)
        return sum(jnp.vdot(o, c) for o, c in zip(outs, cots))

    dist_grads = jax.grad(dist_loss)(params)
    new_params = jax.tree.map(_sgd_respecting_placement, params, dist_grads)

    ref_grads = jax.grad(ref_loss)(ref_w)
    new_ref = [w - LR * g for w, g in zip(ref_w, ref_grads)]

    got = dist.get_weights(new_params)
    for t, (a, b) in enumerate(zip(new_ref, got)):
        np.testing.assert_allclose(b, np.asarray(a), rtol=train_rtol,
                                   atol=train_atol,
                                   err_msg=f"updated table {t}")
    if lookahead_axis:
        _check_lookahead_parity(dist, params, inputs, rng)
    return dist, params


def _check_lookahead_parity(dist, params, inputs, rng, steps=3):
    """Lookahead axis (ISSUE 9): the staged pipeline must be bit-exact
    against the monolithic sparse step on THIS plan — same weights,
    same batches (labels vary per step; ids repeat, which maximizes the
    touched-row/prefetch intersection the patch has to fix)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from distributed_embeddings_tpu.schedule import LookaheadEngine
    from distributed_embeddings_tpu.training import make_sparse_train_step

    class _Head:
        def __init__(self, emb):
            self.embedding = emb

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            if taps is not None or return_residuals:
                outs, res = self.embedding(p["embedding"], list(cats),
                                           taps=taps, return_residuals=True)
            else:
                outs = self.embedding(p["embedding"], list(cats))
                res = None
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1).astype(jnp.float32)
            loss = jnp.mean(((x @ p["head"])[:, 0]
                             - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    model = _Head(dist)
    outs = dist.apply(params, inputs)
    feat = sum(int(np.prod(o.shape[1:])) for o in outs)
    batch = int(outs[0].shape[0])
    head = jnp.asarray(rng.randn(feat, 1).astype(np.float32)) * 0.1
    if dist.mesh is not None:
        head = jax.device_put(head,
                              NamedSharding(dist.mesh, PartitionSpec()))
    full = {"embedding": params, "head": head}
    num = jnp.zeros((batch, 1), jnp.float32)
    labels = [jnp.asarray(rng.randn(batch).astype(np.float32))
              for _ in range(steps)]

    try:
        eng = LookaheadEngine(model, "adagrad", lr=0.05, donate=False,
                              patch_capacity=batch)
    except (NotImplementedError, ValueError):
        return      # engine refuses this config by design (offload/all-dp)
    init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=0.05,
                                              donate=False)
    p, s = full, init_fn(full)
    mono = []
    for i in range(steps):
        p, s, loss = step_fn(p, s, num, list(inputs), labels[i])
        mono.append(float(loss))
    p2, s2 = full, eng.init(full)
    batches = [(num, list(inputs), labels[i]) for i in range(steps)]
    got = []
    for i in range(steps):
        nxt = batches[i + 1] if i + 1 < steps else None
        p2, s2, loss = eng.step(p2, s2, batches[i], nxt)
        got.append(float(loss))
    assert mono == got, f"lookahead axis: loss trace diverged {mono} {got}"
    w1 = dist.get_weights(p["embedding"])
    w2 = dist.get_weights(p2["embedding"])
    for t, (a, b) in enumerate(zip(w1, w2)):
        np.testing.assert_array_equal(a, b,
                                      err_msg=f"lookahead axis table {t}")


ONE_HOT_8 = [(96, 8), (50, 8), (100, 16), (120, 8), (40, 16), (70, 8),
             (60, 8), (81, 8)]


def test_basic():
    check_equivalence(ONE_HOT_8, strategy="basic")


def test_memory_balanced():
    check_equivalence(ONE_HOT_8, strategy="memory_balanced")


def test_memory_optimized():
    check_equivalence(ONE_HOT_8, strategy="memory_optimized")


def test_column_slice():
    check_equivalence(ONE_HOT_8, strategy="memory_balanced",
                      column_slice_threshold=400)


def test_row_slice():
    check_equivalence(ONE_HOT_8, strategy="memory_balanced",
                      row_slice_threshold=1600)


def test_data_parallel():
    check_equivalence(ONE_HOT_8, strategy="memory_balanced",
                      data_parallel_threshold=500)


# execution-bound on the single-core CPU test host (see
# .claude/skills/verify/SKILL.md): runs in the `-m slow` tier so the
# not-slow tier-1 sweep completes inside its time budget
@pytest.mark.slow
def test_all_parallelism_modes():
    specs = [(10, 4), (96, 8), (50, 8), (1000, 16), (2000, 16), (30, 4),
             (800, 8), (64, 8)]
    check_equivalence(specs, strategy="memory_balanced",
                      column_slice_threshold=400,
                      row_slice_threshold=12800,
                      data_parallel_threshold=200)


class _ScaledEmbedding(Embedding):
    """Custom forward: 2x-scaled gather (non-gather semantics marker)."""

    SCALE = 2.0

    def __call__(self, params, inputs):
        return self.SCALE * jnp.take(params["embeddings"],
                                     jnp.asarray(inputs), axis=0)


class _GatherOkEmbedding(Embedding):
    """Overrides __call__ but asserts plain gather semantics."""

    det_gather_semantics = True

    def __call__(self, params, inputs):
        return jnp.take(params["embeddings"], jnp.asarray(inputs), axis=0)


def test_custom_layer_class_dp_runs_real_forward():
    """VERDICT r4 item 6: a custom layer_class placed data-parallel must run
    ITS forward (reference :820-834), not a plain gather."""
    rng = np.random.RandomState(0)
    mesh = make_mesh(8)
    specs = [(40, 8), (48, 8), (56, 8), (64, 8),
             (3000, 8), (3200, 8), (3400, 8), (3600, 8)]
    embs = [( _ScaledEmbedding if v < 100 else Embedding)(v, w)
            for v, w in specs]
    dist = DistributedEmbedding(embs, mesh=mesh,
                                strategy="memory_balanced",
                                data_parallel_threshold=600)
    assert dist._dp_custom_layers, "small tables should have placed DP"
    weights = [rng.randn(v, w).astype(np.float32) for v, w in specs]
    params = dist.set_weights(weights)
    inputs = [jnp.asarray(rng.randint(0, v, size=(BATCH,))) for v, _ in specs]
    outs = dist.apply(params, inputs)
    for i, (v, w) in enumerate(specs):
        want = np.asarray(weights[i])[np.asarray(inputs[i])]
        if v < 100:
            want = _ScaledEmbedding.SCALE * want      # the REAL forward
        np.testing.assert_allclose(np.asarray(outs[i]), want, rtol=1e-5,
                                   atol=1e-5, err_msg=f"table {i}")


def test_custom_layer_class_mp_rejected_loudly():
    """A custom-forward layer in a fused model-parallel group must fail at
    construction, not silently run gather semantics."""
    mesh = make_mesh(8)
    embs = [_ScaledEmbedding(3000, 8)] + [Embedding(v, 8)
                                          for v in (3200, 3400, 3600,
                                                    3800, 4000, 4200, 4400)]
    with pytest.raises(ValueError, match="custom embedding layer class"):
        DistributedEmbedding(embs, mesh=mesh, strategy="memory_balanced")


def test_custom_layer_class_gather_optout_allowed():
    """det_gather_semantics=True asserts gather equivalence: the subclass
    may place model-parallel and the fused executor's result is correct."""
    rng = np.random.RandomState(1)
    mesh = make_mesh(8)
    specs = [(3000, 8), (3200, 8), (3400, 8), (3600, 8),
             (3800, 8), (4000, 8), (4200, 8), (4400, 8)]
    embs = [_GatherOkEmbedding(v, w) for v, w in specs]
    dist = DistributedEmbedding(embs, mesh=mesh, strategy="memory_balanced")
    weights = [rng.randn(v, w).astype(np.float32) for v, w in specs]
    params = dist.set_weights(weights)
    inputs = [jnp.asarray(rng.randint(0, v, size=(BATCH,))) for v, _ in specs]
    outs = dist.apply(params, inputs)
    for i, _ in enumerate(specs):
        want = np.asarray(weights[i])[np.asarray(inputs[i])]
        np.testing.assert_allclose(np.asarray(outs[i]), want, rtol=1e-5,
                                   atol=1e-5, err_msg=f"table {i}")


def test_shared_tables_mp():
    check_equivalence([(96, 8), (50, 16)], input_table_map=[0, 1, 0, 1, 0])


def test_shared_tables_all_modes():
    specs = [(10, 4), (1000, 8), (4000, 16)]
    check_equivalence(specs, input_table_map=[0, 1, 2, 1, 0],
                      data_parallel_threshold=100,
                      row_slice_threshold=60000,
                      column_slice_threshold=1000,
                      strategy="memory_balanced")


def test_fewer_tables_than_workers():
    check_equivalence([(64, 16), (80, 16)], strategy="basic")


def test_multihot_sum():
    specs = [(96, 8, "sum"), (50, 8, "sum"), (100, 16, "sum"),
             (120, 8, "sum")]
    check_equivalence(specs, strategy="memory_balanced")


def test_multihot_mean():
    specs = [(96, 8, "mean"), (50, 8, "mean"), (100, 16, "mean"),
             (120, 8, "mean")]
    check_equivalence(specs, strategy="memory_balanced")


def test_multihot_mixed_combiners():
    specs = [(96, 8, "sum"), (50, 8, "mean"), (100, 16, None), (120, 8, None),
             (60, 8, "sum"), (70, 8, "mean"), (110, 16, "sum"), (90, 8, None)]
    check_equivalence(specs, strategy="memory_balanced")


def test_multihot_row_slice():
    specs = [(2000, 8, "sum"), (96, 8, "sum"), (50, 8, "sum"), (80, 8, "sum")]
    check_equivalence(specs, strategy="memory_balanced",
                      row_slice_threshold=8000)


def test_ragged_input():
    rng = np.random.RandomState(3)
    specs = [(96, 8, "sum"), (50, 8, "mean"), (70, 8, "sum"), (60, 8, "sum")]
    inputs = []
    for t, (v, w, c) in enumerate(specs):
        lengths = rng.randint(1, 5, size=BATCH)
        values = rng.randint(0, v, size=int(lengths.sum())).astype(np.int32)
        splits = np.cumsum([0] + list(lengths)).astype(np.int32)
        inputs.append(RaggedIds(jnp.asarray(values), jnp.asarray(splits)))
    check_equivalence(specs, inputs=inputs, input_max_hotness=[8] * 4,
                      strategy="memory_balanced")


def test_single_device_fallback():
    check_equivalence(ONE_HOT_8[:4], world=1)


def test_get_set_weights_roundtrip():
    rng = np.random.RandomState(7)
    specs = [(96, 8), (50, 8), (1000, 16), (2000, 16)]
    dist, params = check_equivalence(
        specs, strategy="memory_balanced", check_train=False,
        column_slice_threshold=2000, row_slice_threshold=30000)
    weights = [rng.randn(v, w).astype(np.float32) for v, w in specs]
    params = dist.set_weights(weights)
    got = dist.get_weights(params)
    for a, b in zip(weights, got):
        np.testing.assert_allclose(b, a, rtol=1e-6)


def test_gather_global_chunked_bounded(monkeypatch):
    """VERDICT r4 item 5: the multi-process get_weights gather must move at
    most GATHER_CHUNK_ELEMS elements per collective (reference _split_1d,
    :1024-1089), and chunked == unchunked bit-for-bit."""
    from jax.experimental import multihost_utils

    rng = np.random.RandomState(3)
    specs = [(96, 8), (50, 8), (1000, 8), (2000, 8)]
    mesh = make_mesh(8)
    dist = DistributedEmbedding([Embedding(v, w) for v, w in specs],
                                mesh=mesh, strategy="memory_balanced")
    params = dist.set_weights(
        [rng.randn(v, w).astype(np.float32) for v, w in specs])
    arr = max(params["tp"], key=lambda a: a.size)   # multi-shard bucket
    bound = 4096                    # elements; forces many chunks
    monkeypatch.setattr(DistributedEmbedding, "GATHER_CHUNK_ELEMS", bound)

    calls = []
    real = multihost_utils.process_allgather

    def spy(x, *a, **kw):
        calls.append(int(np.prod(x.shape)))
        return real(x, *a, **kw)

    monkeypatch.setattr(multihost_utils, "process_allgather", spy)
    got = dist._gather_global_chunked(arr)
    np.testing.assert_array_equal(got, np.asarray(arr))
    assert len(calls) > 1, "bound should have forced chunking"
    world, tail = arr.shape[0], int(np.prod(arr.shape[2:]))
    per_row = world * tail
    assert max(calls) <= max(bound, per_row), (max(calls), bound)


def test_indivisible_batch_raises():
    mesh = make_mesh(8)
    dist = DistributedEmbedding([Embedding(32, 8)], mesh=mesh)
    params = dist.set_weights([np.zeros((32, 8), np.float32)])
    with pytest.raises(ValueError, match="not divisible"):
        dist.apply(params, [jnp.zeros((12,), jnp.int32)])


def test_jit_apply():
    mesh = make_mesh(8)
    embeddings = [Embedding(v, w) for v, w in ONE_HOT_8]
    dist = DistributedEmbedding(embeddings, mesh=mesh,
                                strategy="memory_balanced")
    rng = np.random.RandomState(0)
    weights = [rng.randn(v, w).astype(np.float32) for v, w in ONE_HOT_8]
    params = dist.set_weights(weights)
    inputs = [jnp.asarray(rng.randint(0, v, size=(BATCH,)))
              for v, w in ONE_HOT_8]
    outs = jax.jit(lambda p: dist.apply(p, inputs))(params)
    ref = ref_apply([jnp.asarray(w) for w in weights], inputs,
                    list(range(8)), [None] * 8)
    for a, b in zip(ref, outs):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-5,
                                   atol=1e-5)


# ---------------------------------------------------------------- mp input
def check_mp_equivalence(specs, world=8, input_table_map=None, seed=0,
                         check_train=True, **dist_kwargs):
    """Same equivalence check through the model-parallel input path
    (reference dp_input=False): each rank gets global-batch ids for the
    features it owns (strategy.input_ids_list order)."""
    rng = np.random.RandomState(seed)
    embeddings, combiners = [], []
    for spec in specs:
        v, w = spec[0], spec[1]
        c = spec[2] if len(spec) > 2 else None
        embeddings.append(Embedding(v, w, combiner=c))
        combiners.append(c)
    table_map = (list(input_table_map) if input_table_map
                 else list(range(len(specs))))

    inputs = []
    for i, t in enumerate(table_map):
        v, c = specs[t][0], combiners[t]
        if c is None:
            inputs.append(jnp.asarray(rng.randint(0, v, size=(BATCH,))))
        else:
            inputs.append(jnp.asarray(
                rng.randint(0, v, size=(BATCH, 2 + (i % 3)))))
    weights = [rng.randn(s[0], s[1]).astype(np.float32) * 0.1 for s in specs]

    mesh = make_mesh(world) if world > 1 else None
    dist = DistributedEmbedding(embeddings, mesh=mesh, dp_input=False,
                                input_table_map=input_table_map,
                                **dist_kwargs)
    params = dist.set_weights(weights)

    def to_mp(inps):
        return [[inps[dist.strategy.input_groups[1][pos]] for pos in rank_ids]
                for rank_ids in dist.strategy.input_ids_list]

    ref_w = [jnp.asarray(w) for w in weights]
    ref_outs = ref_apply(ref_w, inputs, table_map, combiners)
    dist_outs = dist.apply_mp(params, to_mp(inputs))

    assert len(ref_outs) == len(dist_outs)
    for i, (a, b) in enumerate(zip(ref_outs, dist_outs)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-5,
                                   atol=1e-5, err_msg=f"mp output {i}")
    if not check_train:
        return dist, params

    cots = [jnp.asarray(rng.randn(*o.shape).astype(np.float32))
            for o in ref_outs]

    def dist_loss(p):
        outs = dist.apply_mp(p, to_mp(inputs))
        return sum(jnp.vdot(o, c) for o, c in zip(outs, cots))

    def ref_loss(ws):
        outs = ref_apply(ws, inputs, table_map, combiners)
        return sum(jnp.vdot(o, c) for o, c in zip(outs, cots))

    dist_grads = jax.grad(dist_loss)(params)
    new_params = jax.tree.map(_sgd_respecting_placement, params, dist_grads)
    ref_grads = jax.grad(ref_loss)(ref_w)
    new_ref = [w - LR * g for w, g in zip(ref_w, ref_grads)]
    got = dist.get_weights(new_params)
    for t, (a, b) in enumerate(zip(new_ref, got)):
        np.testing.assert_allclose(b, np.asarray(a), rtol=1e-4, atol=1e-5,
                                   err_msg=f"mp updated table {t}")
    return dist, params


def test_mp_input_basic():
    check_mp_equivalence(ONE_HOT_8, strategy="basic")


def test_mp_input_memory_balanced():
    check_mp_equivalence(ONE_HOT_8, strategy="memory_balanced")


def test_mp_input_column_slice():
    # slices of one table land on several ranks -> the same feature's ids are
    # fed on every owning rank (reference :846-851)
    check_mp_equivalence(ONE_HOT_8, strategy="memory_balanced",
                         column_slice_threshold=400)


def test_mp_input_multihot():
    specs = [(96, 8, "sum"), (50, 8, "mean"), (100, 16, "sum"),
             (120, 8, "sum"), (60, 8, "mean"), (70, 8, None)]
    check_mp_equivalence(specs, strategy="memory_balanced")


def test_mp_input_shared_tables():
    check_mp_equivalence([(96, 8), (50, 16)], input_table_map=[0, 1, 0, 1, 0])


def test_mp_input_single_device_flat():
    check_mp_equivalence(ONE_HOT_8[:4], world=1)


def test_mp_call_dispatch():
    mesh = make_mesh(8)
    dist = DistributedEmbedding([Embedding(64, 8) for _ in range(8)],
                                mesh=mesh, dp_input=False)
    params = dist.set_weights(
        [np.zeros((64, 8), np.float32) for _ in range(8)])
    with pytest.raises(ValueError, match="dp_input=False"):
        dist.apply(params, [jnp.zeros((BATCH,), jnp.int32)] * 8)
    mp_inputs = [[jnp.zeros((BATCH,), jnp.int32) for _ in rank_ids]
                 for rank_ids in dist.strategy.input_ids_list]
    outs = dist(params, mp_inputs)
    assert len(outs) == 8 and outs[0].shape == (BATCH, 8)


# ------------------------------------------------------- mixed precision
# reference parameterizes a mixed_precision_policy over its whole matrix
# (dist_model_parallel_test.py:30-34); params stay fp32, compute in bf16.
BF16_TOL = dict(rtol=4e-2, atol=4e-2, train_rtol=4e-2, train_atol=4e-2)


def test_bf16_basic():
    dist, _ = check_equivalence(ONE_HOT_8, strategy="memory_balanced",
                                compute_dtype=jnp.bfloat16, **BF16_TOL)
    inputs = [jnp.zeros((BATCH,), jnp.int32)] * 8
    params = dist.set_weights(
        [np.zeros((v, w), np.float32) for v, w in ONE_HOT_8])
    outs = dist.apply(params, inputs)
    assert all(o.dtype == jnp.bfloat16 for o in outs)


def test_bf16_column_slice():
    check_equivalence(ONE_HOT_8, strategy="memory_balanced",
                      column_slice_threshold=400,
                      compute_dtype=jnp.bfloat16, **BF16_TOL)


# execution-bound on the single-core CPU test host (see
# .claude/skills/verify/SKILL.md): runs in the `-m slow` tier so the
# not-slow tier-1 sweep completes inside its time budget
@pytest.mark.slow
def test_bf16_row_slice():
    check_equivalence(ONE_HOT_8, strategy="memory_balanced",
                      row_slice_threshold=1600,
                      compute_dtype=jnp.bfloat16, **BF16_TOL)


def test_bf16_multihot_all_modes():
    specs = [(10, 4, "sum"), (96, 8, "sum"), (1000, 16, "mean"),
             (2000, 16, "sum"), (800, 8, "sum")]
    check_equivalence(specs, strategy="memory_balanced",
                      column_slice_threshold=400, row_slice_threshold=12800,
                      data_parallel_threshold=200,
                      compute_dtype=jnp.bfloat16, **BF16_TOL)


def test_cpu_offload_equivalence():
    # gpu_embedding_size flags the largest tp tables for offload; they land
    # in separate buckets and stay numerically exact (reference :449-476)
    dist, params = check_equivalence(
        ONE_HOT_8, strategy="memory_balanced", gpu_embedding_size=800)
    assert any(b.offload for b in dist.plan.tp_buckets)
    assert any(not b.offload for b in dist.plan.tp_buckets)


def test_cpu_offload_bucket_separation():
    # offloaded tables must never be concat-fused with on-budget tables
    mesh = make_mesh(8)
    dist = DistributedEmbedding([Embedding(v, w) for v, w in ONE_HOT_8],
                                mesh=mesh, strategy="memory_balanced",
                                gpu_embedding_size=800)
    assert any(b.offload for b in dist.plan.tp_buckets)
    assert any(not b.offload for b in dist.plan.tp_buckets)


def test_cpu_offload_multihot():
    specs = [(96, 8, "sum"), (50, 8, "sum"), (100, 8, "mean"), (120, 8, "sum")]
    check_equivalence(specs, strategy="memory_balanced",
                      gpu_embedding_size=500)


class CustomEmbedding:
    """User-defined layer: anything exposing get_config() with
    input_dim/output_dim is distributable (reference CustomEmbedding
    dist_model_parallel_test.py:48-66 — gather semantics, config contract)."""

    def __init__(self, input_dim, output_dim):
        self.input_dim = input_dim
        self.output_dim = output_dim

    def get_config(self):
        return {"input_dim": self.input_dim, "output_dim": self.output_dim}


def test_custom_embedding_layer():
    rng = np.random.RandomState(11)
    specs = [(96, 8), (50, 8), (100, 16), (120, 8)]
    embeddings = [CustomEmbedding(v, w) for v, w in specs]
    mesh = make_mesh(8)
    dist = DistributedEmbedding(embeddings, mesh=mesh, strategy="basic")
    weights = [rng.randn(v, w).astype(np.float32) for v, w in specs]
    params = dist.set_weights(weights)
    inputs = [jnp.asarray(rng.randint(0, v, size=(BATCH,))) for v, w in specs]
    outs = dist.apply(params, inputs)
    for w, x, o in zip(weights, inputs, outs):
        np.testing.assert_allclose(np.asarray(o), w[np.asarray(x)],
                                   rtol=1e-6)
