"""Convergence evidence (VERDICT r2 item 5): AUC must actually climb.

The reference's analogous evidence is DLRM AUC 0.80248 on Criteo-1TB
(reference examples/dlrm/README.md:7-8). Here a scaled-down DLRM trains on
ClickGenerator's planted-structure stream (Bayes AUC ~0.85): reaching the
0.70 threshold requires the embeddings to learn per-row structure — random
embeddings score 0.5 — proving LR schedule + sparse tapped path + streaming
AUC eval jointly. The full 2000-step curve is committed as
docs/convergence_r03.json (tools/convergence_demo.py).
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


@pytest.mark.slow
def test_dlrm_auc_climbs_past_070():
    from convergence_demo import run

    result = run(steps=600, batch=512, eval_every=200, eval_steps=4,
                 log_fn=lambda *_: None)
    aucs = result["eval_auc"]
    assert aucs, "no eval ran"
    assert aucs[-1] > 0.70, result
    # and the loss actually fell
    assert result["loss_last100_mean"] < result["loss_first100_mean"]
