"""Fault injection + hardened delta streaming (ISSUE 13).

The contract under test: (a) the `faults.FaultPlan` seam is
deterministic per seed and validates scenarios at construction; (b) the
stream-file container (v2) carries verifiable checksums and legacy
(v1) files still load, counted; (c) every injected fault DEGRADES
instead of crashing — corrupt files (delta AND snapshot kinds)
quarantine inside `DeltaConsumer.poll`, transient read errors retry
with bounded backoff, crash-before-rename leaves a swept orphan and a
retryable publisher, pause keeps pending keys riding; (d) the consumer
recovers BIT-exactly once a clean snapshot re-anchors the chain, and
`InferenceEngine.poll_updates` never raises — it mirrors degradation
into the ``serve/degraded{reason=}`` gauges and clears them on heal;
(e) the ingest pipeline retries transient stage errors in place; (f)
SLO rules opt into presence-conditional gating with ``if_present``.
"""

import json
import os
import warnings

import numpy as np
import jax
import pytest

from distributed_embeddings_tpu import faults
from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.obs.registry import MetricRegistry
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.serving import InferenceEngine
from distributed_embeddings_tpu.store import (DeltaConsumer, TableStore,
                                              scan_published)
from distributed_embeddings_tpu.utils import checkpoint as ckpt_lib

SIZES = [(96, 8), (200, 8)]


def make_dist():
    mesh = create_mesh(jax.devices()[:8])
    return DistributedEmbedding([Embedding(v, w) for v, w in SIZES],
                                mesh=mesh, strategy="memory_balanced",
                                row_slice_threshold=30000)


def _weights(rng):
    return [rng.randn(v, w).astype(np.float32) * 0.1 for v, w in SIZES]


def _touched(dist, rng, n=8):
    import jax.numpy as jnp
    cats = [jnp.asarray(rng.randint(0, v, (n,)).astype(np.int32))
            for v, _ in SIZES]
    return dist.touched_row_keys(cats)


def _spec(point, kind, **kw):
    return faults.FaultSpec(point, kind, **kw)


# ------------------------------------------------------------- fault plan
def test_fault_plan_validates_at_construction():
    """A scenario naming an impossible fault refuses at load, not
    mid-soak (a fault that can never fire voids the reconciliation
    ledger silently)."""
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.FaultPlan([{"point": "nope", "kind": "truncate",
                           "at": [0]}])
    with pytest.raises(ValueError, match="cannot fire at point"):
        faults.FaultPlan([{"point": "store.scan", "kind": "bit_flip",
                           "at": [0]}])
    with pytest.raises(ValueError, match="never fires"):
        faults.FaultPlan([{"point": "store.load", "kind": "io_error"}])
    with pytest.raises(ValueError, match="'at' must be a"):
        faults.FaultPlan([{"point": "store.load", "kind": "io_error",
                           "at": 3}])


def test_fault_plan_deterministic_per_seed():
    """Two plans from the same JSON fire on identical occurrence
    sequences — the property that makes a soak run replayable from its
    scenario file alone."""
    doc = {"seed": 11, "faults": [{"point": "store.load",
                                   "kind": "io_error", "prob": 0.3,
                                   "max_fires": 50}]}
    fires = []
    for _ in range(2):
        plan = faults.FaultPlan.from_json(json.dumps(doc))
        fires.append([bool(plan.check("store.load"))
                      for _ in range(200)])
    assert fires[0] == fires[1]
    assert 20 < sum(fires[0]) <= 50          # prob actually draws, capped


def test_ledger_kind_survives_caller_context():
    """The event ledger's identity fields win over caller context keys:
    `TableStore.publish` passes its own stream kind, and a collision
    used to clobber event["kind"] — breaking `corrupted_paths()` and
    every downstream reconciliation."""
    plan = faults.FaultPlan([{"point": "store.publish",
                              "kind": "bit_flip", "at": [0]}])
    spec = plan.check("store.publish", path="/x/f.npz", kind="delta",
                      occurrence="shadow")
    assert spec is not None and spec.kind == "bit_flip"
    (ev,) = plan.events
    assert ev["kind"] == "bit_flip" and ev["point"] == "store.publish"
    assert ev["occurrence"] == 0
    assert plan.corrupted_paths() == ["/x/f.npz"]
    assert plan.counts(kind="bit_flip") == 1


def test_env_var_and_scoped_install(monkeypatch):
    """DET_FAULT_PLAN installs a plan process-wide (inline JSON);
    `use_plan` scopes one and restores the previous state."""
    faults.reset_plan()
    monkeypatch.setenv("DET_FAULT_PLAN", json.dumps(
        {"faults": [{"point": "consumer.poll", "kind": "io_error",
                     "at": [0]}]}))
    try:
        plan = faults.active_plan()
        assert plan is not None and len(plan.specs) == 1
        with faults.use_plan(None):
            assert faults.active_plan() is None
            assert faults.check("consumer.poll") is None
        assert faults.active_plan() is plan
        with pytest.raises(faults.InjectedIOError):
            faults.check_raise("consumer.poll", path="p")
    finally:
        faults.reset_plan()
        monkeypatch.delenv("DET_FAULT_PLAN")
        faults.reset_plan()


# ------------------------------------------------------- container v2
def test_container_v2_checksums_roundtrip_and_detect(tmp_path):
    """v2 stream files verify on load; a payload bit-flip and a
    mid-payload truncation both raise (zip CRC or container checksum —
    either way the consumer's corrupt classification), and a tampered
    header fails its own crc even through the meta-only read."""
    arrays = {"a": np.arange(24, dtype=np.float32).reshape(4, 6),
              "b": np.ones((3,), np.int64)}
    path = ckpt_lib.save_row_delta(str(tmp_path / "f.npz"),
                                   {"kind": "delta", "version": 3}, arrays)
    meta, back = ckpt_lib.load_row_delta(path)
    assert meta["container"] == ckpt_lib.STREAM_CONTAINER_VERSION
    assert set(meta["crc"]) == {"a", "b"}
    np.testing.assert_array_equal(back["a"], arrays["a"])
    assert ckpt_lib.verify_stream_payload(meta, back, path)

    # every parse-level damage class funnels into StreamIntegrityError
    # — the ONE type the consumer classifies as corrupt, so config
    # errors (e.g. a shape-signature mismatch) cannot be mistaken for
    # corruption
    flip = str(tmp_path / "flip.npz")
    trunc = str(tmp_path / "trunc.npz")
    for dst in (flip, trunc):
        with open(path, "rb") as s, open(dst, "wb") as d:
            d.write(s.read())
    faults.corrupt_file(flip, _spec("store.publish", "bit_flip", at=[0]))
    with pytest.raises(ckpt_lib.StreamIntegrityError):
        ckpt_lib.load_row_delta(flip)
    faults.corrupt_file(trunc, _spec("store.publish", "truncate", at=[0]))
    with pytest.raises(ckpt_lib.StreamIntegrityError):
        ckpt_lib.load_row_delta(trunc)
    with open(str(tmp_path / "junk.npz"), "wb") as f:
        f.write(b"not a zip at all")
    with pytest.raises(ckpt_lib.StreamIntegrityError):
        ckpt_lib.load_row_delta_meta(str(tmp_path / "junk.npz"))

    # header tamper: rewrite __meta__ with a changed field, keep crc
    data = dict(np.load(path, allow_pickle=False))
    meta2 = json.loads(str(data["__meta__"]))
    meta2["version"] = 999
    data["__meta__"] = np.asarray(json.dumps(meta2))
    hdr = str(tmp_path / "hdr.npz")
    np.savez(hdr, **data)
    with pytest.raises(ckpt_lib.StreamIntegrityError, match="header"):
        ckpt_lib.load_row_delta_meta(hdr)

    # verify must also catch a checksummed array going missing
    meta3, back3 = ckpt_lib.load_row_delta(path)
    del back3["b"]
    with pytest.raises(ckpt_lib.StreamIntegrityError, match="missing"):
        ckpt_lib.verify_stream_payload(meta3, back3, path)


def test_legacy_v1_files_load_with_counter(tmp_path):
    """Checksum-less (pre-v2) stream files still load — warned once,
    counted — so a rolling upgrade's old publishers keep serving."""
    path = str(tmp_path / "legacy.npz")
    np.savez(path, __meta__=np.asarray(json.dumps(
        {"kind": "delta", "version": 1})),
        a=np.zeros((2, 2), np.float32))
    before = ckpt_lib.legacy_load_count()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        meta, arrays = ckpt_lib.load_row_delta(path)
    assert "crc" not in meta and "a" in arrays
    assert ckpt_lib.legacy_load_count() == before + 1


def test_publish_atomic_and_orphan_sweep(tmp_path):
    d = str(tmp_path)
    tmp = os.path.join(d, "stream_v00000009_delta.npz.tmp")
    with open(tmp, "wb") as f:
        f.write(b"partial")
    keep = os.path.join(d, "stream_v00000001_snapshot.npz")
    with open(keep, "wb") as f:
        f.write(b"x")
    # tmp names never match the stream pattern: invisible to consumers
    assert [p for _, _, p in scan_published(d)] == [keep]
    removed = ckpt_lib.sweep_orphan_tmp(d)
    assert removed == [tmp] and not os.path.exists(tmp)
    assert os.path.exists(keep)
    assert ckpt_lib.sweep_orphan_tmp(str(tmp_path / "missing")) == []

    src = os.path.join(d, "w.tmp")
    with open(src, "wb") as f:
        f.write(b"payload")
    dst = os.path.join(d, "w.npz")
    assert ckpt_lib.publish_atomic(src, dst) == dst
    assert not os.path.exists(src)
    with open(dst, "rb") as f:
        assert f.read() == b"payload"


# ------------------------------------------- quarantine + re-anchor
def test_corrupt_delta_and_snapshot_quarantined_then_bitexact(tmp_path):
    """The acceptance spine: a bit-flipped DELTA and a truncated
    SNAPSHOT are quarantined (not raised) with one warning each, the
    consumer stays on its last-good version and reports degradation,
    and the publisher's next clean snapshot re-anchors it BIT-exactly.
    Quarantined files evict from bookkeeping once compaction deletes
    them."""
    dist = make_dist()
    rng = np.random.RandomState(3)
    reg = MetricRegistry()
    store = TableStore(dist, dist.set_weights(_weights(rng)))
    d = str(tmp_path / "pub")
    store.commit(store.params)
    store.publish(d)                              # v1 clean snapshot

    w2 = [w + 0.5 for w in dist.get_weights(store.params)]
    store.commit(dist.set_weights(w2), touched=_touched(dist, rng))
    info2 = store.publish(d)                      # v2 delta -> bit-flip
    assert info2["kind"] == "delta"
    faults.corrupt_file(info2["path"],
                        _spec("store.publish", "bit_flip", at=[0]))

    w3 = [w - 0.25 for w in w2]
    store.commit(dist.set_weights(w3))
    info3 = store.publish(d, force_snapshot=True)  # v3 snap -> truncate
    assert info3["kind"] == "snapshot"
    faults.corrupt_file(info3["path"],
                        _spec("store.publish", "truncate", at=[0]))

    cons_store = TableStore(
        dist, dist.set_weights([np.zeros((v, w), np.float32)
                                for v, w in SIZES]), registry=reg)
    cons = DeltaConsumer(cons_store, d)
    with pytest.warns(RuntimeWarning, match="quarantined"):
        applied = cons.poll()
    # only the clean v1 snapshot applied; both corrupt files quarantined
    assert [i["version"] for i in applied] == [1]
    assert sorted(cons.quarantined) == sorted(
        [info2["path"], info3["path"]])
    assert reg.counter("store/corrupt_files_total").value == 2
    assert cons.degraded_reasons() == frozenset({"corrupt_stream"})
    # second poll: nothing new, still behind the publisher -> degraded
    assert cons.poll() == [] and cons.degraded_reasons()

    # the publisher's next snapshot re-anchors the chain
    store.commit(store.params, touched=_touched(dist, rng, 4))
    store.publish(d, force_snapshot=True)          # v4 clean
    out = cons.poll()
    assert [i["kind"] for i in out] == ["snapshot"]
    assert cons.degraded_reasons() == frozenset()
    for t, (a, b) in enumerate(zip(dist.get_weights(store.params),
                                   dist.get_weights(cons_store.params))):
        np.testing.assert_array_equal(b, a, err_msg=f"table {t}")
    st = cons.stats()
    assert st["quarantined_files"] == 2
    assert st["degraded_reasons"] == []

    # compaction deletes the corrupt files: quarantine + meta cache
    # follow the live stream
    os.remove(info2["path"])
    os.remove(info3["path"])
    cons.poll()
    assert cons.quarantined == {}
    assert all(os.path.exists(p) for p in cons._meta_cache)


def test_transient_io_error_retries_then_applies(tmp_path):
    """An injected transient read error (an `OSError`) retries with
    backoff inside ONE poll and the file still applies — no quarantine,
    no crash, retries counted."""
    dist = make_dist()
    rng = np.random.RandomState(4)
    reg = MetricRegistry()
    store = TableStore(dist, dist.set_weights(_weights(rng)))
    d = str(tmp_path / "pub")
    store.commit(store.params)
    store.publish(d)
    cons_store = TableStore(
        dist, dist.set_weights([np.zeros((v, w), np.float32)
                                for v, w in SIZES]), registry=reg)
    cons = DeltaConsumer(cons_store, d, retry_backoff_s=1e-4)
    plan = faults.FaultPlan([{"point": "store.load", "kind": "io_error",
                              "at": [0], "repeat": 2}])
    with faults.use_plan(plan):
        applied = cons.poll()
    assert [i["version"] for i in applied] == [1]
    assert cons._retries_total == 2
    assert reg.counter("store/poll_retries_total").value == 2
    assert cons.quarantined == {}
    assert cons.degraded_reasons() == frozenset()
    for a, b in zip(dist.get_weights(store.params),
                    dist.get_weights(cons_store.params)):
        np.testing.assert_array_equal(b, a)


def test_exhausted_retries_give_up_this_poll_only(tmp_path):
    """When the transient error outlives the in-poll retry budget the
    consumer reports io_transient and serves last-good — and the NEXT
    poll (fault gone) catches up."""
    dist = make_dist()
    rng = np.random.RandomState(5)
    store = TableStore(dist, dist.set_weights(_weights(rng)))
    d = str(tmp_path / "pub")
    store.commit(store.params)
    store.publish(d)
    cons_store = TableStore(
        dist, dist.set_weights([np.zeros((v, w), np.float32)
                                for v, w in SIZES]))
    cons = DeltaConsumer(cons_store, d, max_transient_retries=1,
                         retry_backoff_s=1e-4)
    plan = faults.FaultPlan([{"point": "store.load", "kind": "io_error",
                              "prob": 1.0, "max_fires": 100}])
    with faults.use_plan(plan):
        assert cons.poll() == []
    assert cons.degraded_reasons() == frozenset({"io_transient"})
    assert cons_store.version == 0
    assert [i["version"] for i in cons.poll()] == [1]
    assert cons.degraded_reasons() == frozenset()


def test_crash_before_rename_orphan_swept_and_retryable(tmp_path):
    """An injected crash between write and rename leaves exactly one
    orphaned tmp, no stream file, and a publisher whose pending state
    survives — the retried publish ships the same rows, and a restarted
    publisher sweeps the orphan."""
    dist = make_dist()
    rng = np.random.RandomState(6)
    reg = MetricRegistry()
    store = TableStore(dist, dist.set_weights(_weights(rng)),
                       registry=reg)
    d = str(tmp_path / "pub")
    plan = faults.FaultPlan([{"point": "store.publish",
                              "kind": "crash_before_rename", "at": [0]}])
    store.commit(store.params)
    with faults.use_plan(plan):
        with pytest.raises(faults.InjectedCrash):
            store.publish(d)
    orphans = [n for n in os.listdir(d) if ".tmp" in n]
    assert len(orphans) == 1
    assert scan_published(d) == []               # invisible to consumers
    assert plan.counts(kind="crash_before_rename") == 1

    # same publisher retries (occurrence 1: clean) without a new commit;
    # the version is unchanged, so the retry's tmp write lands on the
    # orphan's own name and the rename consumes it
    info = store.publish(d)
    assert info["kind"] == "snapshot" and os.path.exists(info["path"])
    assert [n for n in os.listdir(d) if ".tmp" in n] == []

    # restart: a crashed publisher that never retried leaves its orphan
    # for the NEXT publisher's startup sweep
    orphan = os.path.join(d, "stream_v00000007_delta.npz.tmp")
    with open(orphan, "wb") as f:
        f.write(b"dead")
    store2 = TableStore(dist, store.params, registry=reg)
    store2.commit(store2.params)
    with pytest.warns(RuntimeWarning, match="swept"):
        store2.publish(d)
    assert [n for n in os.listdir(d) if ".tmp" in n] == []
    assert reg.counter("store/orphan_tmp_swept_total").value == 1


def test_publisher_pause_keeps_pending_keys(tmp_path):
    """A paused publish writes nothing and advances nothing; the
    pending touched keys ride into the resumed publish and a consumer
    ends bit-exact."""
    dist = make_dist()
    rng = np.random.RandomState(7)
    store = TableStore(dist, dist.set_weights(_weights(rng)))
    d = str(tmp_path / "pub")
    store.commit(store.params)
    store.publish(d)                              # v1 anchor
    cons_store = TableStore(
        dist, dist.set_weights([np.zeros((v, w), np.float32)
                                for v, w in SIZES]))
    cons = DeltaConsumer(cons_store, d)
    cons.poll()

    import jax.numpy as jnp
    w2 = [w.copy() for w in dist.get_weights(store.params)]
    for w in w2:
        w[:4] += 1.0                             # only touched rows move
    hot = [jnp.asarray(np.arange(4, dtype=np.int32)) for _ in SIZES]
    store.commit(dist.set_weights(w2),
                 touched=dist.touched_row_keys(hot))
    plan = faults.FaultPlan([{"point": "store.publish", "kind": "pause",
                              "at": [0]}])
    with faults.use_plan(plan):
        info = store.publish(d)
    assert info["kind"] == "paused" and info["path"] is None
    assert len(scan_published(d)) == 1           # nothing new on disk
    assert cons.poll() == []

    resumed = store.publish(d)                   # pending keys ride here
    assert resumed["kind"] == "delta" and resumed["rows"] > 0
    assert [i["version"] for i in cons.poll()] == [resumed["version"]]
    for a, b in zip(dist.get_weights(store.params),
                    dist.get_weights(cons_store.params)):
        np.testing.assert_array_equal(b, a)


def test_delayed_visibility_hides_then_reveals(tmp_path):
    """The store.scan fault hides a fresh file for N scans (lagging
    directory views); the consumer just stays on last-good and catches
    up when the file appears."""
    dist = make_dist()
    rng = np.random.RandomState(8)
    store = TableStore(dist, dist.set_weights(_weights(rng)))
    d = str(tmp_path / "pub")
    store.commit(store.params)
    store.publish(d)
    plan = faults.FaultPlan([{"point": "store.scan",
                              "kind": "delay_visibility", "at": [0],
                              "arg": 2}])
    with faults.use_plan(plan):
        assert scan_published(d) == []           # hidden scan 1
        assert scan_published(d) == []           # hidden scan 2
        assert len(scan_published(d)) == 1       # revealed
    assert plan.counts(kind="delay_visibility") == 1


def test_meta_cache_bounded_by_live_stream(tmp_path):
    """ISSUE 13 satellite: `_meta_cache` entries whose files left the
    directory evict at poll end — cache size tracks the live stream,
    not run length."""
    dist = make_dist()
    rng = np.random.RandomState(9)
    store = TableStore(dist, dist.set_weights(_weights(rng)))
    d = str(tmp_path / "pub")
    store.commit(store.params)
    store.publish(d)
    cons_store = TableStore(
        dist, dist.set_weights([np.zeros((v, w), np.float32)
                                for v, w in SIZES]))
    cons = DeltaConsumer(cons_store, d)
    deltas = []
    for i in range(3):
        store.commit(store.params, touched=_touched(dist, rng, 4))
        deltas.append(store.publish(d))
        cons.poll()
    assert set(cons._meta_cache) == {i["path"] for i in deltas}
    # compaction: snapshot supersedes, deltas deleted
    store.commit(store.params, touched=_touched(dist, rng, 4))
    store.publish(d, force_snapshot=True)
    for i in deltas:
        os.remove(i["path"])
    cons.poll()
    assert cons._meta_cache == {}                # only deltas were cached


def test_config_errors_propagate_not_quarantined(tmp_path):
    """A stream published for a DIFFERENT model raises out of the
    consumer loudly (config error), it is never quarantined — only
    parse-level damage (`StreamIntegrityError`) is corruption. The
    engine still converts it to degraded serving (reason poll_error)
    rather than crashing the request loop."""
    dist = make_dist()
    rng = np.random.RandomState(12)
    store = TableStore(dist, dist.set_weights(_weights(rng)))
    d = str(tmp_path / "pub")
    store.commit(store.params)
    store.publish(d)

    other = DistributedEmbedding([Embedding(7, 4)], mesh=None)
    ostore = TableStore(other, other.set_weights(
        [np.zeros((7, 4), np.float32)]))
    cons = DeltaConsumer(ostore, d)
    with pytest.raises(ValueError, match="different model"):
        cons.poll()
    assert cons.quarantined == {}

    eng = InferenceEngine(other, other.set_weights(
        [np.zeros((7, 4), np.float32)]))
    assert eng.poll_updates(d) == []             # degraded, no raise
    assert eng.degraded_reasons() == frozenset({"poll_error"})
    assert "different model" in eng.last_poll_error


def test_stream_dtype_stamped_and_unsupported_refused(tmp_path):
    """ISSUE 15 satellite: every written container header carries the
    payload ``dtype`` (stamped 'f32' when the publisher set none, so
    legacy-shaped saves stay self-describing), and a dtype the consumer
    does not support refuses LOUDLY as ValueError — a config error,
    never `StreamIntegrityError`, never a quarantine (the file is
    healthy; the fleet is mismatched)."""
    arrays = {"a": np.arange(8, dtype=np.float32).reshape(2, 4)}
    path = ckpt_lib.save_row_delta(str(tmp_path / "f.npz"),
                                   {"kind": "delta", "version": 1}, arrays)
    assert ckpt_lib.load_row_delta_meta(path)["dtype"] == "f32"

    # the save layer refuses a non-registry dtype at write time
    with pytest.raises(ValueError, match="not a stream container dtype"):
        ckpt_lib.save_row_delta(str(tmp_path / "bad.npz"),
                                {"kind": "delta", "dtype": "int4"}, arrays)

    # a future publisher's dtype (crafted header, valid checksums):
    # both read layers refuse with the config error, NOT the corrupt one
    import zlib
    meta = {"kind": "delta", "version": 2, "dtype": "int4",
            "container": ckpt_lib.STREAM_CONTAINER_VERSION,
            "crc": {"a": zlib.crc32(
                np.ascontiguousarray(arrays["a"]).tobytes()) & 0xFFFFFFFF}}
    meta["header_crc"] = zlib.crc32(
        json.dumps(meta, sort_keys=True).encode()) & 0xFFFFFFFF
    future = str(tmp_path / "future.npz")
    np.savez(future, __meta__=np.asarray(json.dumps(meta)), **arrays)
    with pytest.raises(ValueError, match="not supported"):
        ckpt_lib.load_row_delta(future)
    with pytest.raises(ValueError, match="not supported"):
        ckpt_lib.load_row_delta_meta(future)
    try:
        ckpt_lib.load_row_delta(future)
    except ValueError as e:
        assert not isinstance(e, ckpt_lib.StreamIntegrityError)

    # consumer path: the refusal PROPAGATES (config class), the file is
    # not quarantined — exactly the sig-mismatch contract
    dist = make_dist()
    rng = np.random.RandomState(5)
    store = TableStore(dist, dist.set_weights(_weights(rng)))
    pub = str(tmp_path / "pub")
    os.makedirs(pub)
    import shutil
    shutil.copy(future, os.path.join(pub, "stream_v00000001_delta.npz"))
    cons = DeltaConsumer(store, pub)
    with pytest.raises(ValueError, match="not supported"):
        cons.poll()
    assert cons.quarantined == {}

    # an fp8 stream on a backend without float8 refuses the same way
    with pytest.MonkeyPatch.context() as mp:
        from distributed_embeddings_tpu.ops import wire as wire_ops
        mp.setattr(wire_ops, "fp8_supported", lambda: False)
        meta8 = {"kind": "delta", "version": 3, "dtype": "fp8"}
        p8 = ckpt_lib.save_row_delta(str(tmp_path / "f8.npz"), meta8,
                                     arrays)
        with pytest.raises(ValueError, match="float8"):
            ckpt_lib.load_row_delta(p8)


# ------------------------------------------------- engine degradation
def test_engine_poll_never_raises_and_degraded_gauge(tmp_path):
    """`poll_updates` converts every consumer-side fault into degraded
    serving: the injected poll error and a corrupt stream both land in
    the `serve/degraded{reason=}` gauges (1 while active) and clear on
    heal, `serve/poll_errors_total` counts, and predictions keep
    serving the last-good version throughout."""
    dist = make_dist()
    rng = np.random.RandomState(10)
    reg = MetricRegistry()
    store = TableStore(dist, dist.set_weights(_weights(rng)))
    d = str(tmp_path / "pub")
    store.commit(store.params)
    store.publish(d)

    eng = InferenceEngine(
        dist, dist.set_weights([np.zeros((v, w), np.float32)
                                for v, w in SIZES]), registry=reg)
    plan = faults.FaultPlan([{"point": "consumer.poll",
                              "kind": "io_error", "at": [0]}])
    with faults.use_plan(plan):
        assert eng.poll_updates(d) == []         # injected: no raise
    assert eng.degraded_reasons() == frozenset({"poll_error"})
    assert reg.gauge("serve/degraded", reason="poll_error").value == 1
    assert reg.counter("serve/poll_errors_total").value == 1
    assert "InjectedIOError" in eng.last_poll_error
    # still serving (the last-good all-zeros tables)
    req = [np.zeros((4,), np.int32) for _ in SIZES]
    outs = eng.predict(req)
    assert all(np.asarray(o).shape[0] == 4 for o in outs)

    # healthy poll: catches up, gauge resets to 0
    assert [i["version"] for i in eng.poll_updates(d)] == [1]
    assert eng.degraded_reasons() == frozenset()
    assert reg.gauge("serve/degraded", reason="poll_error").value == 0

    # corrupt DELTA mid-stream: degraded while behind, healed after the
    # re-anchoring snapshot, final tables bit-exact
    store.commit(store.params, touched=_touched(dist, rng))
    bad = store.publish(d)
    faults.corrupt_file(bad["path"],
                        _spec("store.publish", "bit_flip", at=[0]))
    with pytest.warns(RuntimeWarning, match="quarantined"):
        assert eng.poll_updates(d) == []
    assert eng.degraded_reasons() == frozenset({"corrupt_stream"})
    assert reg.gauge("serve/degraded", reason="corrupt_stream").value == 1
    store.commit(store.params, touched=_touched(dist, rng, 4))
    store.publish(d, force_snapshot=True)
    assert [i["kind"] for i in eng.poll_updates(d)] == ["snapshot"]
    assert eng.degraded_reasons() == frozenset()
    assert reg.gauge("serve/degraded",
                     reason="corrupt_stream").value == 0
    for a, b in zip(dist.get_weights(store.params),
                    dist.get_weights(eng.store.params)):
        np.testing.assert_array_equal(b, a)


# ---------------------------------------------------- ingest pipeline
def test_ingest_stage_transient_error_retries_in_place():
    """An injected `OSError` in a stage body retries in place (counted)
    and the pipeline's output stays bit-identical to serial; a
    persistent error still propagates via drain-then-raise."""
    from distributed_embeddings_tpu.utils.pipeline import (IngestPipeline,
                                                           SerialPipeline)

    def batches(n):
        for i in range(n):
            yield np.full((4,), i, np.float32)

    stages = [("xform", lambda b: b * 2.0)]
    reg = MetricRegistry()
    serial = list(SerialPipeline(batches(5), stages))
    plan = faults.FaultPlan([{"point": "ingest.stage",
                              "kind": "io_error", "at": [1, 3]}])
    with faults.use_plan(plan):
        with IngestPipeline(batches(5), stages, registry=reg) as pipe:
            got = list(pipe)
    assert len(got) == len(serial) == 5
    for a, b in zip(serial, got):
        np.testing.assert_array_equal(a, b)
    assert reg.counter("ingest/stage_retries_total",
                       stage="xform").value == 2

    # a fault outliving the retry budget propagates (contract unchanged)
    plan = faults.FaultPlan([{"point": "ingest.stage",
                              "kind": "io_error", "prob": 1.0,
                              "max_fires": 1000}])
    with faults.use_plan(plan):
        with pytest.raises(OSError):
            list(IngestPipeline(batches(3), stages))


# ------------------------------------------------------ SLO if_present
def test_slo_if_present_gates_only_when_metric_exists():
    from distributed_embeddings_tpu.obs import slo

    rules = [{"name": "opt", "metric": "lookahead/compiles",
              "op": "==", "threshold": 1, "if_present": True},
             {"name": "req", "metric": "train/steps",
              "op": ">=", "threshold": 1}]
    snap = {"counters": {"train/steps": 4}, "gauges": {}, "histograms": {}}
    assert slo.evaluate_rules(rules, snap) == []   # absent + opted out
    snap["gauges"]["lookahead/compiles"] = 3
    bad = slo.evaluate_rules(rules, snap)
    assert [f.fid for f in bad] == ["slo:opt"]     # present: it gates
    with pytest.raises(ValueError, match="if_present"):
        slo.validate_rule({"name": "x", "metric": "m", "op": "==",
                           "threshold": 0, "if_present": "yes"})

    # windowed: a breach observed while the metric WAS present is not
    # silenced by a later absent snapshot (the subsystem going quiet
    # must not launder an earlier recompile)
    wrules = [{"name": "w", "metric": "g", "op": "==", "threshold": 1,
               "if_present": True, "window": 2}]
    breach = {"counters": {}, "gauges": {"g": 2}, "histograms": {}}
    absent = {"counters": {}, "gauges": {}, "histograms": {}}
    assert [f.fid for f in slo.evaluate_rules(wrules, [breach, absent])] \
        == ["slo:w"]
    assert slo.evaluate_rules(wrules, [absent, absent]) == []


# ------------------------------------------------------ soak scenarios
def test_soak_scenarios_load_and_validate():
    """Every shipped scenario file parses, validates, and constructs
    its fault plan; scenario validation refuses unknown keys and the
    lookahead x vocab-maintenance composition."""
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from bench import SOAK_SCENARIO_DEFAULTS, load_soak_scenario

    sdir = os.path.join(root, "tools", "soak_scenarios")
    names = sorted(os.listdir(sdir))
    assert len(names) >= 5
    for name in names:
        sc = load_soak_scenario(os.path.join(sdir, name))
        assert set(SOAK_SCENARIO_DEFAULTS) <= set(sc)
    with pytest.raises(ValueError, match="unknown keys"):
        load_soak_scenario({"name": "x", "stepz": 3})
    with pytest.raises(ValueError, match="lookahead"):
        load_soak_scenario({"name": "x", "lookahead": 1,
                            "vocab_manage": {"every": 4}})
    with pytest.raises(ValueError, match="cannot fire"):
        load_soak_scenario({"name": "x", "fault_plan": {"faults": [
            {"point": "store.scan", "kind": "truncate", "at": [0]}]}})
