"""Tiled one-hot-matmul sparse kernels (ops/pallas_tiled.py) vs XLA
reference semantics, interpret mode.

These kernels are the round-4 answer to the measured scatter bottleneck
(docs/round3_notes.md): every memory access is a regular BlockSpec block
stream, duplicates aggregate inside an MXU matmul. The tests pin:
  * gather == jnp.take for valid ids, zero rows for invalid ids
  * sgd/adagrad == the sparse_update XLA paths (duplicates, invalid ids,
    all-filler and empty corners, non-divisible vocab/tile shapes)
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_embeddings_tpu.ops import pallas_tiled as pt
from distributed_embeddings_tpu.ops import sparse_update as su


def _mk(v, w, n, seed=0, frac_invalid=0.0, hot_skew=True):
    rng = np.random.RandomState(seed)
    if hot_skew:
        # power-law-ish: many duplicates at low ids plus a uniform tail
        ids = np.minimum(
            rng.zipf(1.3, n) - 1, v - 1).astype(np.int32)
    else:
        ids = rng.randint(0, v, n).astype(np.int32)
    if frac_invalid:
        k = int(n * frac_invalid)
        pos = rng.choice(n, k, replace=False)
        ids[pos[: k // 2]] = -1 - rng.randint(0, 5, k // 2)
        ids[pos[k // 2:]] = v + rng.randint(0, 5, k - k // 2)
    table = rng.randn(v, w).astype(np.float32)
    contribs = rng.randn(n, w).astype(np.float32)
    return jnp.asarray(table), jnp.asarray(ids), jnp.asarray(contribs)


@pytest.mark.parametrize("v,w,n,tile,chunk", [
    (1000, 16, 700, 128, 128),      # non-divisible vocab/tile
    (513, 8, 1300, 256, 128),       # odd vocab, heavy dup
    (4096, 128, 512, 1024, 128),    # wide rows
    (64, 16, 2000, 1024, 512),      # tile > vocab, chunk > n/4
])
def test_tiled_gather_matches_take(v, w, n, tile, chunk):
    table, ids, _ = _mk(v, w, n, seed=v + n)
    got = pt.tiled_gather(table, ids, chunk=chunk, tile=tile, interpret=True)
    want = jnp.take(table, jnp.clip(ids, 0, v - 1), axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_tiled_gather_invalid_ids_zero_rows():
    table, ids, _ = _mk(500, 16, 400, seed=3, frac_invalid=0.25)
    got = np.asarray(pt.tiled_gather(table, ids, interpret=True))
    idn = np.asarray(ids)
    bad = (idn < 0) | (idn >= 500)
    assert bad.any()
    np.testing.assert_allclose(got[bad], 0.0)
    np.testing.assert_allclose(
        got[~bad], np.asarray(table)[idn[~bad]], rtol=1e-5, atol=1e-5)


def test_tiled_gather_sorted_direct():
    table, ids, _ = _mk(2000, 32, 900, seed=11)
    sid = jnp.sort(ids)
    got = pt.tiled_gather_sorted(table, sid, interpret=True)
    want = jnp.take(table, sid, axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("v,w,n,frac_invalid", [
    (1000, 16, 900, 0.0),
    (777, 8, 1500, 0.2),       # invalid ids must be dropped
    (4096, 128, 600, 0.0),
    (50, 16, 3000, 0.0),       # extreme duplication, tiny vocab
])
def test_tiled_sgd_matches_xla(v, w, n, frac_invalid):
    table, ids, contribs = _mk(v, w, n, seed=v, frac_invalid=frac_invalid)
    lr = 0.07
    got = pt.tiled_sgd(table, ids, contribs, lr, interpret=True)
    want = table.at[jnp.clip(ids, 0, v)].add(
        -lr * jnp.where(((ids >= 0) & (ids < v))[:, None], contribs, 0.0))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("v,w,n,frac_invalid,tile,chunk", [
    (1000, 16, 900, 0.0, 1024, 512),
    (777, 8, 1500, 0.2, 128, 128),
    (4096, 128, 600, 0.0, 512, 256),
    (50, 16, 3000, 0.1, 1024, 512),
])
def test_tiled_adagrad_matches_sparse_update(v, w, n, frac_invalid, tile,
                                             chunk):
    table, ids, contribs = _mk(v, w, n, seed=7 * v, frac_invalid=frac_invalid)
    accum = jnp.full((v, w), 0.1, jnp.float32)
    lr = 0.05
    got_t, got_a = pt.tiled_adagrad(table, accum, ids, contribs, lr,
                                    tile=tile, chunk=chunk, interpret=True)
    want_t, want_a = su.sparse_adagrad(
        table, accum, su.SparseRowGrad(ids, contribs), lr, strategy="sort")
    np.testing.assert_allclose(got_a, want_a, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got_t, want_t, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("v,w,n,frac_invalid", [
    (1000, 16, 900, 0.0),
    (777, 8, 1500, 0.2),
])
def test_tiled_adam_matches_sparse_update(v, w, n, frac_invalid):
    table, ids, contribs = _mk(v, w, n, seed=3 * v, frac_invalid=frac_invalid)
    mu = jnp.zeros((v, w), jnp.float32)
    nu = jnp.zeros((v, w), jnp.float32)
    cnt = jnp.zeros((), jnp.int32)
    lr = 0.02
    got = pt.tiled_adam(table, mu, nu, cnt, ids, contribs, lr,
                        interpret=True)
    want = su.sparse_adam(table, mu, nu, cnt,
                          su.SparseRowGrad(ids, contribs), lr,
                          strategy="sort")
    for g, wv, name in zip(got, want, ("table", "mu", "nu", "count")):
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(wv, np.float32),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


def test_tiled_adam_two_steps_touched_only_decay():
    """Second step with DIFFERENT ids: rows touched only in step 1 must not
    decay in step 2 (lazy adam contract)."""
    v, w = 200, 8
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randn(v, w).astype(np.float32))
    mu = jnp.zeros((v, w), jnp.float32)
    nu = jnp.zeros((v, w), jnp.float32)
    cnt = jnp.zeros((), jnp.int32)
    ids1 = jnp.asarray(np.arange(0, 50, dtype=np.int32))
    ids2 = jnp.asarray(np.arange(100, 150, dtype=np.int32))
    g1 = jnp.asarray(rng.randn(50, w).astype(np.float32))
    g2 = jnp.asarray(rng.randn(50, w).astype(np.float32))
    s_t, s_mu, s_nu, s_c = table, mu, nu, cnt
    w_t, w_mu, w_nu, w_c = table, mu, nu, cnt
    for ids, g in ((ids1, g1), (ids2, g2)):
        s_t, s_mu, s_nu, s_c = pt.tiled_adam(s_t, s_mu, s_nu, s_c, ids, g,
                                             0.05, interpret=True)
        w_t, w_mu, w_nu, w_c = su.sparse_adam(
            w_t, w_mu, w_nu, w_c, su.SparseRowGrad(ids, g), 0.05,
            strategy="sort")
    np.testing.assert_allclose(s_t, w_t, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(s_mu, w_mu, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(s_nu, w_nu, rtol=1e-4, atol=1e-5)
    assert int(s_c) == int(w_c) == 2


def test_tiled_adagrad_traced_lr_and_jit():
    v, w, n = 600, 16, 800
    table, ids, contribs = _mk(v, w, n, seed=42)
    accum = jnp.full((v, w), 0.1, jnp.float32)

    @jax.jit
    def step(t, a, i, c, lr):
        return pt.tiled_adagrad(t, a, i, c, lr, interpret=True)

    got_t, got_a = step(table, accum, ids, contribs, jnp.float32(0.03))
    want_t, want_a = su.sparse_adagrad(
        table, accum, su.SparseRowGrad(ids, contribs), 0.03, strategy="sort")
    np.testing.assert_allclose(got_a, want_a, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got_t, want_t, rtol=1e-4, atol=1e-5)


def test_tiled_all_invalid_and_empty():
    v, w = 300, 8
    table = jnp.asarray(np.random.RandomState(0).randn(v, w), jnp.float32)
    accum = jnp.full((v, w), 0.1, jnp.float32)
    ids = jnp.full((256,), v + 3, jnp.int32)          # all invalid
    contribs = jnp.ones((256, w), jnp.float32)
    got_t, got_a = pt.tiled_adagrad(table, accum, ids, contribs, 0.1,
                                    interpret=True)
    np.testing.assert_allclose(got_t, table, rtol=1e-6)
    np.testing.assert_allclose(got_a, accum, rtol=1e-6)
    # empty
    t2 = pt.tiled_sgd(table, jnp.zeros((0,), jnp.int32),
                      jnp.zeros((0, w), jnp.float32), 0.1, interpret=True)
    assert t2 is table
    g2 = pt.tiled_gather(table, jnp.zeros((0,), jnp.int32), interpret=True)
    assert g2.shape == (0, w)


def test_tiled_strategy_full_train_equivalence():
    """strategy='tiled' through make_sparse_train_step: distributed sparse
    training with the tiled kernels (interpret mode on the 8-CPU mesh) must
    match the dense optax reference — the same contract the sort/dense
    strategies are held to."""
    from test_sparse_train import run_equivalence
    run_equivalence([(40, 16), (200, 16), (64, 8)], "adagrad",
                    strategy="tiled", rtol=1e-4, atol=1e-4)


def test_tiled_strategy_multihot_train_equivalence():
    from test_sparse_train import run_equivalence
    run_equivalence([(60, 16, "sum"), (500, 8, "sum")], "adagrad",
                    strategy="tiled", rtol=1e-4, atol=1e-4)


def test_tiled_embedding_lookup_matches_fused_contract():
    """tiled_embedding_lookup == the XLA gather+einsum formulation, incl.
    mean normalization, padded zero-weight slots and OOB clamping — and its
    custom VJP matches the dense-path gradients."""
    rng = np.random.RandomState(5)
    v, w, b, k = 400, 16, 64, 4
    table = jnp.asarray(rng.randn(v, w).astype(np.float32))
    ids = jnp.asarray(rng.randint(-3, v + 3, (b, k)).astype(np.int32))
    wts = jnp.asarray((rng.rand(b, k) * (rng.rand(b, k) > 0.3))
                      .astype(np.float32))
    from distributed_embeddings_tpu.ops import pallas_tiled as pt2

    for comb in ("sum", "mean"):
        def ref(tbl, wv):
            ww = wv
            if comb == "mean":
                ww = wv / jnp.maximum(jnp.sum(wv, 1, keepdims=True), 1.0)
            rows = jnp.take(tbl, jnp.clip(ids, 0, v - 1), axis=0)
            return jnp.einsum("bk,bkw->bw", ww, rows)

        got = pt2.tiled_embedding_lookup(table, ids, wts, comb,
                                         interpret=True)
        np.testing.assert_allclose(got, ref(table, wts), rtol=1e-5,
                                   atol=1e-5)
        # gradient parity (dense path)
        g = jnp.asarray(rng.randn(b, w).astype(np.float32))
        f_tiled = lambda t, wv: jnp.vdot(
            pt2.tiled_embedding_lookup(t, ids, wv, comb, interpret=True), g)
        f_ref = lambda t, wv: jnp.vdot(ref(t, wv), g)
        gt_t, gt_w = jax.grad(f_tiled, argnums=(0, 1))(table, wts)
        gr_t, gr_w = jax.grad(f_ref, argnums=(0, 1))(table, wts)
        np.testing.assert_allclose(gt_t, gr_t, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(gt_w, gr_w, rtol=1e-4, atol=1e-5)


def test_tiled_lookup_dense_grad_scatter_free():
    """Round 5 (ADVICE r4): differentiating the tiled lookup on the DENSE
    path must not materialize a zeros.at[ids].add table-gradient scatter —
    the backward aggregates via the sgd kernel reusing the forward's
    sort, so grad-of-lookup lowers with zero stablehlo.scatter ops."""
    import re
    from distributed_embeddings_tpu.ops import pallas_tiled as pt2

    v, w, b, k = 4096, 16, 32, 4
    table = jax.ShapeDtypeStruct((v, w), jnp.float32)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, v, (b, k))
                      .astype(np.int32))

    def loss(t):
        return jnp.sum(pt2.tiled_embedding_lookup(t, ids, None, "sum",
                                                  interpret=True))

    txt = jax.jit(jax.grad(loss)).lower(table).as_text()
    scatters = re.findall(r'"stablehlo\.scatter"', txt)
    assert not scatters, f"{len(scatters)} scatter ops in tiled-lookup grad"


def test_presorted_matches_fresh_sort():
    """tiled_sgd/adagrad/adam/gather with a caller-provided (sid, perm)
    must equal the fresh-sort path bit for bit."""
    from distributed_embeddings_tpu.ops import pallas_tiled as pt2

    rng = np.random.RandomState(9)
    v, w, n = 600, 16, 256
    ids = jnp.asarray(rng.randint(-5, v + 5, n).astype(np.int32))
    contribs = jnp.asarray(rng.randn(n, w).astype(np.float32))
    table = jnp.asarray(rng.randn(v, w).astype(np.float32))
    acc = jnp.abs(jnp.asarray(rng.randn(v, w).astype(np.float32))) + 0.1
    pre = pt2._sort_ids(ids, None, v)
    presorted = (pre[0], pre[2])

    a = pt2.tiled_sgd(table, ids, contribs, 0.05, interpret=True)
    b = pt2.tiled_sgd(table, ids, contribs, 0.05, interpret=True,
                      presorted=presorted)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    a = pt2.tiled_adagrad(table, acc, ids, contribs, 0.05, interpret=True)
    b = pt2.tiled_adagrad(table, acc, ids, contribs, 0.05, interpret=True,
                          presorted=presorted)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    mu = jnp.zeros((v, w), jnp.float32)
    nu = jnp.zeros((v, w), jnp.float32)
    cnt = jnp.zeros((), jnp.int32)
    a = pt2.tiled_adam(table, mu, nu, cnt, ids, contribs, 0.01,
                       interpret=True)
    b = pt2.tiled_adam(table, mu, nu, cnt, ids, contribs, 0.01,
                       interpret=True, presorted=presorted)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    a = pt2.tiled_gather(table, ids, interpret=True)
    b = pt2.tiled_gather(table, ids, interpret=True, presorted=presorted)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_tiled_lookup_path_forward_equivalence(monkeypatch):
    """DET_LOOKUP_PATH=tiled through DistributedEmbedding matches the
    default XLA forward on the 8-CPU mesh (interpret mode)."""
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    rng = np.random.RandomState(17)
    mesh = create_mesh(jax.devices()[:8])
    specs = [(60, 16, "sum"), (300, 8, "sum"), (40, 16, None)]

    def build():
        return DistributedEmbedding(
            [Embedding(vv, ww, combiner=cc) for vv, ww, cc in specs],
            mesh=mesh)

    weights = [rng.randn(vv, ww).astype(np.float32) for vv, ww, _ in specs]
    cats = [jnp.asarray(rng.randint(0, specs[i][0], (16, 3) if specs[i][2]
                                    else (16,))) for i in range(3)]
    emb = build()
    params = emb.set_weights(weights)
    want = emb(params, list(cats))
    monkeypatch.setenv("DET_LOOKUP_PATH", "tiled")
    emb2 = build()
    params2 = emb2.set_weights(weights)
    got = emb2(params2, list(cats))
    for a, b2 in zip(want, got):
        np.testing.assert_allclose(
            np.asarray(b2).reshape(np.asarray(a).shape), np.asarray(a),
            rtol=1e-5, atol=1e-5)


def test_tiled_step_hlo_scatter_free(monkeypatch):
    """The fully-tiled train step (tiled updates + tiled forward) must
    lower with NO stablehlo.scatter ops at all — removing the 100-280
    ns/row scatter lowering is the entire point of the round-4 kernels.
    (Lowered on CPU: the pallas interpreter emulates kernels with
    while/dynamic-update-slice, not scatter, so any scatter in the text is
    a real framework scatter.)"""
    import re
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.training import make_sparse_train_step

    class _Tiny:
        def __init__(self, emb):
            self.embedding = emb

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            out = self.embedding(p["embedding"], list(cats), taps=taps,
                                 return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    monkeypatch.setenv("DET_LOOKUP_PATH", "tiled")
    emb = DistributedEmbedding([Embedding(30_000_000, 8, combiner="sum")],
                               mesh=None)
    model = _Tiny(emb)
    init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=0.01,
                                              strategy="tiled")
    params = jax.eval_shape(
        lambda: {"embedding": emb.init(jax.random.PRNGKey(0))})
    state = jax.eval_shape(init_fn, params)
    num = jax.ShapeDtypeStruct((8, 1), jnp.float32)
    cats = [jax.ShapeDtypeStruct((8, 4), jnp.int32)]
    lab = jax.ShapeDtypeStruct((8,), jnp.float32)
    txt = jax.jit(step_fn).lower(params, state, num, cats, lab).as_text()
    scatters = re.findall(r'"stablehlo.scatter"', txt)
    assert not scatters, (
        f"tiled step still lowers {len(scatters)} scatter ops")


def test_tiled_bf16_table():
    v, w, n = 512, 16, 700
    table, ids, contribs = _mk(v, w, n, seed=9)
    table16 = table.astype(jnp.bfloat16)
    got = pt.tiled_sgd(table16, ids, contribs, 0.05, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = su.sparse_sgd(table16, su.SparseRowGrad(ids, contribs), 0.05)
    # XLA scatter rounds to bf16 per contribution; the kernel aggregates in
    # f32 and rounds once — heavily-duplicated rows accumulate visible
    # (one-sided, kernel-favoring) rounding differences
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=5e-2, atol=1e-1)
