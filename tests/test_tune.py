"""The tune subsystem (ISSUE 18): registry, resolution seam, offline
search machinery, runtime tuner, and the seams that consume them.

Covers the acceptance spine: `measured_default()`/`knob_value()` resolve
through env > tuned config-of-record > measured defaults > fallback with
flight-recorder adoption events; a malformed/stale tuned file falls
through LOUDLY (warning + counter) and never crashes dispatch; the
search prunes with a full audit trail; the RuntimeTuner only ever flips
runtime-safety knobs; and the generated docs knob table cannot drift
from the registry."""

import json
import os
import warnings

import jax
import pytest

from distributed_embeddings_tpu.obs.registry import default_registry
from distributed_embeddings_tpu.obs.trace import default_recorder
from distributed_embeddings_tpu.tune import registry as tune_registry
from distributed_embeddings_tpu.tune import resolve as tune_resolve
from distributed_embeddings_tpu.tune import runtime as tune_runtime
from distributed_embeddings_tpu.tune import search as tune_search

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_resolution(monkeypatch):
    """Every test resolves from a clean slate: no tuned selectors, no
    measured-defaults file, empty caches."""
    monkeypatch.delenv("DET_TUNED_PATH", raising=False)
    monkeypatch.delenv("DET_TUNED_WORKLOAD", raising=False)
    monkeypatch.delenv("DET_MEASURED_DEFAULTS_CONSULT", raising=False)
    monkeypatch.setenv("DET_MEASURED_DEFAULTS_PATH", os.devnull)
    tune_resolve.reset_cache()
    yield
    tune_resolve.reset_cache()


def _tuned_doc(winner, workload="dlrm", **extra):
    """A minimal schema-valid tuned-config-v1 doc."""
    doc = {
        "schema": tune_search.TUNED_SCHEMA, "workload": workload,
        "created_at": "2026-08-07T00:00:00Z", "git_sha": "abc123",
        "backend": "cpu", "winner": dict(winner),
        "arms": [{"key": "defaults", "overrides": {}, "step_ms": 1.0}],
        "pruned": [], "prune_order": ["collective_bytes"],
        "prune_audit_ok": True, "beats_default": {},
        "staged_tpu_arms": [],
    }
    doc.update(extra)
    return doc


# ------------------------------------------------------------- registry

def test_registry_invariants():
    knobs = tune_registry.all_knobs()
    assert len({k.env for k in knobs}) == len(knobs)
    assert len({k.name for k in knobs}) == len(knobs)
    for k in knobs:
        assert k.is_legal(k.fallback), k.name
        assert k.safety in (tune_registry.OFFLINE, tune_registry.RUNTIME)
        # lookup by either handle
        assert tune_registry.get_knob(k.name) is k
        assert tune_registry.get_knob(k.env) is k


def test_registry_covers_the_scattered_call_sites():
    """The knobs the library actually reads must all be registry-owned —
    the registry is only a single source of truth if it is COMPLETE."""
    for env in ("DET_SCATTER_IMPL", "DET_LOOKUP_PATH", "DET_DEDUP_IMPL",
                "DET_EXCHANGE_WIRE", "DET_ID_WIRE", "DET_STORE_DTYPE",
                "DET_DELTA_DTYPE", "DET_HOT_ROWS", "DET_LOOKAHEAD",
                "DET_PIPELINE_DEPTH", "DET_PUBLISH_EVERY",
                "DET_STORE_SNAPSHOT_EVERY", "DET_VOCAB_ADMIT",
                "DET_FLEET_MAX_QUEUE_DEPTH", "DET_FLEET_MAX_QUEUE_ROWS"):
        tune_registry.get_knob(env)      # KeyError = registry hole


def test_registry_unknown_knob_raises():
    with pytest.raises(KeyError):
        tune_registry.get_knob("DET_NOT_A_KNOB")
    assert tune_registry.maybe_get("DET_NOT_A_KNOB") is None


def test_validate_override():
    assert tune_registry.validate_override("DET_SCATTER_IMPL",
                                           "tiled") is None
    assert "illegal value" in tune_registry.validate_override(
        "DET_SCATTER_IMPL", "warp")
    assert "unknown knob" in tune_registry.validate_override(
        "DET_NOPE", "x")
    assert "STRINGS" in tune_registry.validate_override(
        "DET_HOT_ROWS", 5)
    # int-domain bounds
    assert tune_registry.validate_override("DET_HOT_ROWS", "0") is None
    assert "illegal value" in tune_registry.validate_override(
        "DET_HOT_ROWS", "-1")
    # unset-able open-domain knob: "" legal only where fallback is ""
    assert tune_registry.validate_override(
        "DET_FLEET_MAX_QUEUE_ROWS", "") is None
    assert "illegal value" in tune_registry.validate_override(
        "DET_PIPELINE_DEPTH", "")


def test_dedup_is_numerics_class():
    """The cumsum trade stays a human decision — the registry class the
    whole no-auto-flip policy keys off."""
    assert tune_registry.get_knob(
        "DET_DEDUP_IMPL").parity == tune_registry.PARITY_NUMERICS


# ------------------------------------------------------------ resolution

def test_precedence_env_beats_tuned(tmp_path, monkeypatch):
    path = tmp_path / "t.json"
    path.write_text(json.dumps(_tuned_doc(
        {"DET_SCATTER_IMPL": "tiled"})))
    monkeypatch.setenv("DET_TUNED_PATH", str(path))
    monkeypatch.setenv("DET_SCATTER_IMPL", "pallas")
    tune_resolve.reset_cache()
    assert tune_resolve.knob_value("DET_SCATTER_IMPL", "xla") == "pallas"


def test_tuned_beats_measured_and_fallback(tmp_path, monkeypatch):
    path = tmp_path / "t.json"
    path.write_text(json.dumps(_tuned_doc(
        {"DET_SCATTER_IMPL": "tiled"})))
    measured = tmp_path / "m.json"
    measured.write_text(json.dumps({"DET_SCATTER_IMPL": "pallas",
                                    "DET_LOOKUP_PATH": "tiled"}))
    monkeypatch.setenv("DET_TUNED_PATH", str(path))
    monkeypatch.setenv("DET_MEASURED_DEFAULTS_PATH", str(measured))
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    tune_resolve.reset_cache()
    # tuned layer wins over the measured file...
    assert tune_resolve.knob_value("DET_SCATTER_IMPL", "xla") == "tiled"
    # ...and a knob absent from the tuned winner falls to measured
    assert tune_resolve.knob_value("DET_LOOKUP_PATH", "auto") == "tiled"
    # ...and a knob in neither bottoms out at the fallback
    assert tune_resolve.knob_value("DET_EXCHANGE_WIRE", "f32") == "f32"


def test_tuned_consulted_on_cpu_only_with_explicit_env(tmp_path,
                                                       monkeypatch):
    """No DET_TUNED_* env -> no tuned consult, ANY backend: CPU test
    equivalence cannot silently change because a tuner ran."""
    assert jax.default_backend() == "cpu"
    assert tune_resolve.knob_value("DET_SCATTER_IMPL", "xla") == "xla"
    # with the env, the tuned layer applies on CPU too (explicit opt-in)
    path = tmp_path / "t.json"
    path.write_text(json.dumps(_tuned_doc({"DET_SCATTER_IMPL": "tiled"})))
    monkeypatch.setenv("DET_TUNED_PATH", str(path))
    tune_resolve.reset_cache()
    assert tune_resolve.knob_value("DET_SCATTER_IMPL", "xla") == "tiled"


def test_tuned_workload_name_resolves_repo_path(monkeypatch):
    monkeypatch.setenv("DET_TUNED_WORKLOAD", "dlrm")
    path, workload = tune_resolve.tuned_source()
    assert workload == "dlrm"
    assert path == os.path.join(REPO_ROOT, "tools", "tuned", "dlrm.json")


def test_malformed_tuned_file_falls_through_loudly(tmp_path, monkeypatch):
    """Warning + counter + fallback — never a crash (satellite 3)."""
    path = tmp_path / "t.json"
    path.write_text("{not json")
    monkeypatch.setenv("DET_TUNED_PATH", str(path))
    tune_resolve.reset_cache()
    before = default_registry().snapshot()["counters"].get(
        "tune/tuned_config_invalid_total", 0)
    with pytest.warns(RuntimeWarning, match="malformed/stale"):
        assert tune_resolve.knob_value("DET_SCATTER_IMPL",
                                       "xla") == "xla"
    after = default_registry().snapshot()["counters"].get(
        "tune/tuned_config_invalid_total", 0)
    assert after == before + 1
    # the warning fires ONCE per process per file, not per knob read
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert tune_resolve.knob_value("DET_LOOKUP_PATH",
                                       "auto") == "auto"


def test_stale_schema_tuned_file_falls_through(tmp_path, monkeypatch):
    doc = _tuned_doc({"DET_SCATTER_IMPL": "tiled"})
    doc["schema"] = "tuned-config-v0"          # a future/old format
    path = tmp_path / "t.json"
    path.write_text(json.dumps(doc))
    monkeypatch.setenv("DET_TUNED_PATH", str(path))
    tune_resolve.reset_cache()
    with pytest.warns(RuntimeWarning):
        assert tune_resolve.knob_value("DET_SCATTER_IMPL",
                                       "xla") == "xla"
    assert tune_resolve.tuned_info()["errors"]


def test_workload_mismatch_refuses(tmp_path, monkeypatch):
    path = tmp_path / "t.json"
    path.write_text(json.dumps(_tuned_doc({"DET_SCATTER_IMPL": "tiled"},
                                          workload="serve")))
    monkeypatch.setenv("DET_TUNED_PATH", str(path))
    monkeypatch.setenv("DET_TUNED_WORKLOAD", "dlrm")
    tune_resolve.reset_cache()
    with pytest.warns(RuntimeWarning, match="workload mismatch"):
        assert tune_resolve.knob_value("DET_SCATTER_IMPL",
                                       "xla") == "xla"


def test_illegal_winner_entry_rejected_individually(tmp_path,
                                                    monkeypatch):
    """One bad entry is dropped (counter + warning); the legal rest of
    the winner still applies."""
    path = tmp_path / "t.json"
    path.write_text(json.dumps(_tuned_doc({
        "DET_SCATTER_IMPL": "warp-drive",       # illegal value
        "DET_LOOKUP_PATH": "tiled",             # legal
    })))
    monkeypatch.setenv("DET_TUNED_PATH", str(path))
    tune_resolve.reset_cache()
    before = default_registry().snapshot()["counters"].get(
        "tune/tuned_knob_rejected_total", 0)
    with pytest.warns(RuntimeWarning, match="rejected"):
        assert tune_resolve.knob_value("DET_SCATTER_IMPL",
                                       "xla") == "xla"
    assert tune_resolve.knob_value("DET_LOOKUP_PATH", "auto") == "tiled"
    after = default_registry().snapshot()["counters"].get(
        "tune/tuned_knob_rejected_total", 0)
    assert after == before + 1


def test_adoption_emits_flight_recorder_event(tmp_path, monkeypatch):
    path = tmp_path / "t.json"
    path.write_text(json.dumps(_tuned_doc({"DET_SCATTER_IMPL": "tiled"})))
    monkeypatch.setenv("DET_TUNED_PATH", str(path))
    tune_resolve.reset_cache()
    assert tune_resolve.knob_value("DET_SCATTER_IMPL", "xla") == "tiled"
    evs = [e for e in default_recorder().events()
           if e[0] == "i" and e[1] == "tune/adopt"
           and (e[6] or {}).get("knob") == "DET_SCATTER_IMPL"]
    assert evs, "tuned adoption left no tune/adopt instant"
    assert evs[-1][6]["value"] == "tiled"
    assert evs[-1][6]["source"].startswith("tuned:")
    counters = default_registry().snapshot()["counters"]
    assert counters.get('tune/adoptions_total{source=tuned}', 0) >= 1


def test_measured_default_delegates(monkeypatch):
    """ops.sparse_update.measured_default is the same seam (historical
    entry point, PR-2 signature preserved)."""
    from distributed_embeddings_tpu.ops import sparse_update
    monkeypatch.setenv("DET_SCATTER_IMPL", "tiled")
    assert sparse_update.measured_default("DET_SCATTER_IMPL",
                                          "xla") == "tiled"


# --------------------------------------------------------------- search

def test_enumerate_arms_validates_and_dedups():
    arms = tune_search.enumerate_arms(
        {"DET_EXCHANGE_WIRE": ["f32", "bf16"],
         "DET_ID_WIRE": ["auto", "int32"]})
    assert arms[0].key == "defaults"
    # baseline (f32, auto) equals one product point -> deduped: 1 + 3
    assert len(arms) == 4
    with pytest.raises(KeyError):
        tune_search.enumerate_arms({"DET_NOPE": ["x"]})
    with pytest.raises(ValueError, match="search space"):
        tune_search.enumerate_arms({"DET_EXCHANGE_WIRE": ["f64"]})


def test_prune_by_cost_logs_every_pruned_arm():
    arms = tune_search.enumerate_arms(
        {"DET_EXCHANGE_WIRE": ["f32", "bf16", "bf16-sr"],
         "DET_ID_WIRE": ["auto", "int32"]})
    bytes_for = {"f32": 100.0, "bf16": 50.0, "bf16-sr": 50.0}

    def cost(arm):
        b = bytes_for[arm.overrides["DET_EXCHANGE_WIRE"]]
        if arm.overrides["DET_ID_WIRE"] == "int32":
            b += 10.0
        return {"collective_bytes": b}

    kept, pruned, audit_ok = tune_search.prune_by_cost(
        arms, cost, keep=2, order=("collective_bytes",))
    assert audit_ok
    assert len(kept) + len(pruned) == len(arms)
    # the baseline survives even at the worst predicted cost
    assert any(a.key == "defaults" for a in kept)
    # every pruned arm carries its predicted costs and a rationale
    for p in pruned:
        assert p["rationale"] and "predicted" in p and p["overrides"]
    # no silent caps: nothing dropped without a log line
    assert {p["arm"] for p in pruned} | {a.key for a in kept} \
        == {a.key for a in arms}


def test_prune_audit_flags_ordering_violation():
    """A cost_fn that lies between calls (non-deterministic ranking)
    must be caught by the ordering audit, not shipped."""
    arms = [tune_search.Arm({"DET_EXCHANGE_WIRE": "f32"}, key="a"),
            tune_search.Arm({"DET_EXCHANGE_WIRE": "bf16"}, key="b"),
            tune_search.Arm({"DET_EXCHANGE_WIRE": "bf16-sr"}, key="c")]
    kept, pruned, audit_ok = tune_search.prune_by_cost(
        arms, lambda a: {"x": 1.0}, keep=1, order=("x",),
        always_keep=("zzz",))
    # all ranks equal: ties never violate ordering
    assert audit_ok

    # force a violation: always_keep pins the WORST arm while a cheaper
    # one is pruned -> that is fine (forced); but a kept non-forced arm
    # ranking above a pruned one is a bug
    costs = {"a": 3.0, "b": 1.0, "c": 2.0}
    kept, pruned, audit_ok = tune_search.prune_by_cost(
        arms, lambda arm: {"x": costs[arm.key]}, keep=2, order=("x",),
        always_keep=())
    assert audit_ok
    assert [a.key for a in kept] == ["b", "c"]
    assert pruned[0]["arm"] == "a"


def test_split_adoptable():
    adoptable, staged = tune_search.split_adoptable({
        "DET_SCATTER_IMPL": "tiled",      # exact -> adoptable
        "DET_EXCHANGE_WIRE": "bf16",      # bounded -> staged
        "DET_ID_WIRE": "auto",            # equals fallback -> adoptable
        "DET_DEDUP_IMPL": "cumsum",       # numerics -> staged
    })
    assert adoptable == {"DET_SCATTER_IMPL": "tiled",
                         "DET_ID_WIRE": "auto"}
    assert staged == {"DET_EXCHANGE_WIRE": "bf16",
                      "DET_DEDUP_IMPL": "cumsum"}


def test_record_round_trip_and_validator():
    doc = tune_search.build_record(
        workload="dlrm", winner={"DET_SCATTER_IMPL": "tiled"},
        arms=[{"key": "defaults", "overrides": {}, "step_ms": 2.0},
              {"key": "scatter_impl=tiled",
               "overrides": {"DET_SCATTER_IMPL": "tiled"},
               "step_ms": 1.0}],
        pruned=[{"arm": "x", "overrides": {}, "predicted": {},
                 "rationale": "outside keep"}],
        prune_order=["collective_bytes"], prune_audit_ok=True,
        beats_default={"collective_bytes": True},
        staged_tpu_arms=[], git_sha="abc", backend="cpu",
        created_at="2026-08-07T00:00:00Z")
    assert tune_search.validate_tuned_record(doc) == []
    # round trip through JSON stays valid
    assert tune_search.validate_tuned_record(
        json.loads(json.dumps(doc))) == []

    # the writer refuses to emit an invalid record
    with pytest.raises(ValueError, match="invalid tuned record"):
        tune_search.build_record(
            workload="dlrm", winner={}, arms=[], pruned=[],
            prune_order=[], prune_audit_ok=True, beats_default={},
            staged_tpu_arms=[], git_sha="abc", backend="cpu",
            created_at="2026-08-07T00:00:00Z")

    # validator failure shapes
    assert tune_search.validate_tuned_record("nope")
    bad = dict(doc, schema="v0")
    assert any("stale or foreign" in e
               for e in tune_search.validate_tuned_record(bad))
    bad = dict(doc, prune_audit_ok=False)
    assert any("ordering audit" in e
               for e in tune_search.validate_tuned_record(bad))
    bad = dict(doc, pruned=[{"arm": "x"}])
    assert any("rationale" in e
               for e in tune_search.validate_tuned_record(bad))


# -------------------------------------------------------- runtime tuner

def test_runtime_tuner_refuses_offline_knobs():
    with pytest.raises(ValueError, match="offline"):
        tune_runtime.RuntimeTuner(
            {"DET_EXCHANGE_WIRE": lambda v: None},
            rules=[{"match": "x", "knob": "DET_EXCHANGE_WIRE",
                    "action": "scale", "factor": 2.0}])


def test_runtime_tuner_flips_bounded_with_events():
    applied_values = []
    tuner = tune_runtime.RuntimeTuner(
        {"DET_FLEET_MAX_QUEUE_DEPTH": applied_values.append},
        initial={"DET_FLEET_MAX_QUEUE_DEPTH": 64},
        cooldown_reacts=1)
    flips = tuner.react([{"id": "slo:queue_depth_p99"}])
    assert flips == [{"knob": "DET_FLEET_MAX_QUEUE_DEPTH",
                      "from": 64, "to": 32,
                      "finding": "slo:queue_depth_p99"}]
    assert applied_values == [32]
    assert tuner.value("DET_FLEET_MAX_QUEUE_DEPTH") == 32
    # cooldown: the immediate next react flips nothing
    assert tuner.react([{"id": "slo:queue_depth_p99"}]) == []
    # after the cooldown expires it may flip again, bounded below
    assert tuner.react([{"id": "slo:queue_depth_p99"}])[0]["to"] == 16
    # audit trail: flight-recorder instants + counter
    evs = [e for e in default_recorder().events()
           if e[0] == "i" and e[1] == "tune/autoflip"]
    assert len(evs) >= 2
    counters = default_registry().snapshot()["counters"]
    assert counters.get(
        "tune/autoflips_total{knob=DET_FLEET_MAX_QUEUE_DEPTH}", 0) >= 2
    assert len(tuner.flips) == 2


def test_runtime_tuner_respects_bounds():
    tuner = tune_runtime.RuntimeTuner(
        {"DET_FLEET_MAX_QUEUE_DEPTH": lambda v: None},
        initial={"DET_FLEET_MAX_QUEUE_DEPTH": 4},
        rules=[{"match": "queue", "knob": "DET_FLEET_MAX_QUEUE_DEPTH",
                "action": "scale", "factor": 0.5, "min": 4, "max": 4096}],
        cooldown_reacts=0)
    # already at the rule's floor: no flip, no event
    assert tuner.react([{"id": "slo:queue"}]) == []
    assert tuner.value("DET_FLEET_MAX_QUEUE_DEPTH") == 4


def test_runtime_tuner_slo_finding_objects():
    """Accepts obs.slo Finding-shaped objects (fid attribute), not just
    dicts — the evaluator's native output."""
    class _F:
        fid = "slo:publish_lag"

    seen = []
    tuner = tune_runtime.RuntimeTuner(
        {"DET_PUBLISH_EVERY": seen.append},
        initial={"DET_PUBLISH_EVERY": 4})
    flips = tuner.react([_F()])
    assert flips and flips[0]["to"] == 8 and seen == [8]


# ---------------------------------------------------- docs + scenarios

def test_perf_model_knob_table_matches_registry():
    """docs/perf_model.md embeds the GENERATED knob table — drift between
    the registry and the doc fails here, with the regeneration command
    in the assertion message."""
    path = os.path.join(REPO_ROOT, "docs", "perf_model.md")
    with open(path) as f:
        text = f.read()
    begin, end = "<!-- knob-table:begin -->", "<!-- knob-table:end -->"
    assert begin in text and end in text, \
        "docs/perf_model.md lost its knob-table markers"
    embedded = text.split(begin)[1].split(end)[0].strip()
    expected = tune_registry.knob_table_markdown().strip()
    assert embedded == expected, (
        "docs/perf_model.md knob table drifted from the registry — "
        "regenerate with `python -m distributed_embeddings_tpu.tune."
        "registry` and paste between the markers")


def test_checked_in_scenarios_lint_clean():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "det_lint", os.path.join(REPO_ROOT, "tools",
                                 "lint_invariants.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    findings = lint.lint_scenario_knobs()
    assert findings == [], [str(f) for f in findings]


def test_scenario_lint_catches_bad_knobs(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "det_lint", os.path.join(REPO_ROOT, "tools",
                                 "lint_invariants.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    (tmp_path / "bad.json").write_text(json.dumps(
        {"name": "bad", "knobs": {"DET_NOPE": "x",
                                  "DET_DEDUP_IMPL": "zzz"}}))
    (tmp_path / "good.json").write_text(json.dumps(
        {"name": "good", "knobs": {"DET_DEDUP_IMPL": "sort"}}))
    (tmp_path / "broken.json").write_text("{not json")
    findings = lint.lint_scenario_knobs(str(tmp_path))
    msgs = "\n".join(str(f) for f in findings)
    assert "unknown knob 'DET_NOPE'" in msgs
    assert "illegal value" in msgs
    assert "unparsable" in msgs
    assert len(findings) == 3


def test_fit_env_knobs_resolve_through_seam(monkeypatch):
    """DET_PIPELINE_DEPTH / DET_PUBLISH_EVERY land through knob_value
    (tuned-config adoptable); the explicit argument still wins."""
    monkeypatch.setenv("DET_PIPELINE_DEPTH", "5")
    assert int(tune_resolve.knob_value("DET_PIPELINE_DEPTH", "2")) == 5
    monkeypatch.delenv("DET_PIPELINE_DEPTH")
    assert int(tune_resolve.knob_value("DET_PIPELINE_DEPTH", "2")) == 2
