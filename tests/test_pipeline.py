"""utils/pipeline.py — bounded background ingestion pipeline lifecycle.

The contract under test (ISSUE 3): worker exceptions surface at the
consumer (after the already-staged items drain, within one batch), shutdown
joins every worker thread (no leaks across pipeline lifetimes),
backpressure caps in-flight memory, and pipelined output is bit-identical
to serial iteration order. Plus the prefetch_to_device tail-behavior fix
(drain staged entries, then raise)."""

import threading
import time

import numpy as np
import pytest

from distributed_embeddings_tpu.utils.pipeline import (
    IngestPipeline, SerialPipeline, staged_batches)
from distributed_embeddings_tpu.utils.prefetch import prefetch_to_device


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        yield (rng.rand(8, 3).astype(np.float32),
               rng.randint(0, 100, (8, 2)).astype(np.int64))


def _stages():
    return [
        ("preprocess", lambda b: (b[0] * 2.0, b[1].astype(np.int32))),
        ("stage", lambda b: (b[0].copy(), b[1] + 1)),
    ]


def test_pipelined_bit_identical_to_serial():
    serial = list(SerialPipeline(_batches(7), _stages()))
    with IngestPipeline(_batches(7), _stages(), depth=2) as pipe:
        pipelined = list(pipe)
    assert len(serial) == len(pipelined) == 7
    for (sn, si), (pn, pi) in zip(serial, pipelined):
        np.testing.assert_array_equal(sn, pn)   # exact — same bits
        np.testing.assert_array_equal(si, pi)
        assert sn.dtype == pn.dtype and si.dtype == pi.dtype


def test_source_exception_surfaces_after_drain():
    def bad_source():
        yield from _batches(3)
        raise ValueError("disk on fire")

    pipe = IngestPipeline(bad_source(), _stages(), depth=2)
    got = []
    with pytest.raises(ValueError, match="disk on fire"):
        for item in pipe:
            got.append(item)
    # every batch produced before the failure was drained first
    assert len(got) == 3
    assert all(not t.is_alive() for t in pipe._threads)


def test_stage_exception_surfaces_within_one_batch():
    calls = {"n": 0}

    def flaky(b):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("bad decode")
        return b

    pipe = IngestPipeline(_batches(10), [("flaky", flaky)], depth=1)
    got = []
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="bad decode"):
        for item in pipe:
            got.append(item)
    # the 2 items preprocessed before the failure arrive, then the error —
    # promptly (no hang, no timeout-length stall)
    assert len(got) == 2
    assert time.monotonic() - t0 < 5.0


def test_close_joins_all_threads_no_leak():
    before = {t for t in threading.enumerate()}
    # exhaustion closes implicitly
    pipe = IngestPipeline(_batches(4), _stages(), depth=2)
    list(pipe)
    # close() mid-stream joins too
    pipe2 = IngestPipeline(_batches(100), _stages(), depth=2)
    next(iter(pipe2))
    pipe2.close()
    leaked = [t for t in threading.enumerate()
              if t not in before and t.is_alive()]
    assert not leaked, f"leaked ingestion threads: {leaked}"
    # idempotent
    pipe.close()
    pipe2.close()


def test_backpressure_bounds_in_flight_batches():
    pulled = {"n": 0}

    def counting_source():
        while True:
            pulled["n"] += 1
            yield np.zeros((4,), np.float32)

    depth, nstages = 2, 2
    pipe = IngestPipeline(counting_source(),
                          [("a", lambda x: x), ("b", lambda x: x)],
                          depth=depth)
    next(iter(pipe))
    deadline = time.monotonic() + 1.0
    while time.monotonic() < deadline:
        time.sleep(0.05)   # consumer stalls; workers must block, not grow
    # bound: one item per queue slot + one in each worker's hands + the
    # consumed one; anything near this is fine — the test is that it does
    # NOT keep growing unboundedly while the consumer stalls
    bound = (nstages + 1) * depth + nstages + 2
    assert pulled["n"] <= bound, (pulled["n"], bound)
    pipe.close()


def test_empty_source_and_no_stages():
    assert list(IngestPipeline(iter(()), [("s", lambda x: x)])) == []
    # no stages: a pure background reader
    assert list(IngestPipeline(iter([1, 2, 3]), [])) == [1, 2, 3]


def test_stage_summaries_account_every_stage():
    pipe = IngestPipeline(_batches(5), _stages(), depth=2)
    list(pipe)
    s = pipe.stage_summaries()
    assert set(s) == {"read", "preprocess", "stage"}
    assert all(v["count"] == 5 for v in s.values())
    assert pipe.bottleneck() in s


def test_staged_batches_serial_vs_pipelined_parity():
    import jax.numpy as jnp
    data = [(np.full((2, 2), i, np.float32),) for i in range(5)]
    serial = list(staged_batches(iter(data), pipelined=False))
    pipe = staged_batches(iter(data), pipelined=True)
    pipelined = list(pipe)
    for (s,), (p,) in zip(serial, pipelined):
        assert isinstance(p, jnp.ndarray)
        np.testing.assert_array_equal(np.asarray(s), np.asarray(p))


def test_duplicate_stage_names_rejected():
    with pytest.raises(ValueError):
        IngestPipeline(iter(()), [("x", id), ("x", id)])
    with pytest.raises(ValueError):
        IngestPipeline(iter(()), [("read", id)])   # reserved


def test_ingest_bench_record_fields():
    # the bench.py --mode ingest path end-to-end at smoke shapes: record
    # carries the schema CI and docs/perf_model.md rely on (no speedup
    # assertion — 2-vCPU test hosts are too noisy for a perf gate)
    import importlib.util
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "det_bench_under_test", os.path.join(root, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    rec = bench.run_ingest_bench(batches=4, batch=512, features=3,
                                 numerical=2, dim=4, max_tokens=4096,
                                 distinct=2, reps=1)
    for k in ("ingest_serial_samples_per_sec",
              "ingest_pipelined_samples_per_sec", "ingest_speedup",
              "ingest_serial_stage_ms", "ingest_pipelined_stage_ms",
              "ingest_bottleneck_stage", "ingest_stage_bound_samples_per_sec",
              "ingest_vs_stage_bound"):
        assert k in rec, (k, rec)
    assert rec["ingest_pipelined_samples_per_sec"] > 0
    assert set(rec["ingest_pipelined_stage_ms"]) == {
        "read", "preprocess", "stage", "consume"}


# ---------------------------------------------------------------- prefetch
def test_prefetch_drains_staged_then_raises():
    staged = []

    def bad_source():
        yield 1
        yield 2
        raise OSError("pread failed")

    it = prefetch_to_device(bad_source(), size=4,
                            stage=lambda x: staged.append(x) or x * 10)
    got = []
    with pytest.raises(OSError, match="pread failed"):
        for v in it:
            got.append(v)
    # both staged batches were yielded BEFORE the error surfaced
    assert got == [10, 20]
    assert staged == [1, 2]


def test_prefetch_happy_path_order():
    it = prefetch_to_device(iter(range(5)), size=2, stage=lambda x: x + 100)
    assert list(it) == [100, 101, 102, 103, 104]
