"""Versioned table store: train-to-serve weight streaming (ISSUE 6).

Acceptance contract: (a) a training job publishing row-deltas every N
steps and a concurrently-running consumer stay within BIT-exact parity
at each consumed version; (b) versions are monotonic and per-table;
(c) the delta chain is integrity-checked (out-of-order apply raises,
snapshots resync); (d) host-offloaded buckets consume deltas through
the XLA-free host row-set seam and HBM cache slots patch straight off
the wire; (e) `get_weights`'s hot overlay and the store's versioned
`read_rows` share ONE resident-row derivation, so the old two-path
staleness cannot occur.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.serving import InferenceEngine
from distributed_embeddings_tpu.store import (DeltaChainError, DeltaConsumer,
                                              TableStore,
                                              restore_from_published,
                                              scan_published)
from distributed_embeddings_tpu.training import make_sparse_train_step

SIZES = [(96, 8), (50, 8), (1000, 16), (2000, 16)]
BATCH = 16


class EmbOnlyModel:
    """Embedding-only tapped model (the bench/serve idiom): loss over the
    concatenated embedding outputs, no dense head."""

    def __init__(self, emb):
        self.embedding = emb

    def loss_fn(self, p, numerical, cats, labels, taps=None,
                return_residuals=False):
        out = self.embedding(p["embedding"], list(cats), taps=taps,
                             return_residuals=return_residuals)
        outs, res = out if return_residuals else (out, None)
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                            axis=1)
        loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
        return (loss, res) if return_residuals else loss


def make_dist(**kw):
    mesh = create_mesh(jax.devices()[:8])
    return DistributedEmbedding([Embedding(v, w) for v, w in SIZES],
                                mesh=mesh, strategy="memory_balanced",
                                row_slice_threshold=30000, **kw)


def test_touched_row_keys_cover_update():
    """The host-side touched mirror is a superset of the rows one sparse
    step actually changes — and every key maps back into a real table
    row (OOB ids excluded)."""
    dist = make_dist()
    rng = np.random.RandomState(0)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w in SIZES]
    params = dist.set_weights(weights)
    model = EmbOnlyModel(dist)
    init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=0.1)
    p = {"embedding": params}
    s = init_fn(p)
    cats = [jnp.asarray(rng.randint(0, v, (BATCH,)).astype(np.int32))
            for v, _ in SIZES]
    touched = dist.touched_row_keys(cats)
    assert all(len(v) for v in touched.values())
    p2, _, _ = step_fn(p, s, jnp.zeros((BATCH, 1)), cats,
                       jnp.asarray(rng.randn(BATCH).astype(np.float32)))

    # the superset property a SET-payload delta needs: every row the
    # update changed carries a touched key (equivalently: rows OUTSIDE
    # the touched set are bit-identical before/after), and something did
    # change inside it
    changed_inside = 0
    for b, bk in enumerate(dist.plan.tp_buckets):
        rows_max = max(bk.rows_max, 1)
        before = np.asarray(p["embedding"]["tp"][b])
        after = np.asarray(p2["embedding"]["tp"][b])
        keys = touched.get(("tp", b), np.zeros((0,), np.int64))
        assert ((keys >= 0) & (keys < before.shape[0] * rows_max)).all()
        mask = np.zeros(before.shape[:2], bool)
        mask[keys // rows_max, keys % rows_max] = True
        diff = (before != after).any(axis=-1)
        assert not (diff & ~mask).any(), f"bucket {b}: untouched row moved"
        changed_inside += int((diff & mask).sum())
    for t, rt in enumerate(dist.plan.row_tables):
        before = np.asarray(p["embedding"]["row"][t])
        after = np.asarray(p2["embedding"]["row"][t])
        keys = touched.get(("row", t), np.zeros((0,), np.int64))
        base = np.asarray(rt.row_base, np.int64)
        w_idx = np.searchsorted(base, keys, side="right") - 1
        mask = np.zeros(before.shape[:2], bool)
        mask[w_idx, keys - base[w_idx]] = True
        diff = (before != after).any(axis=-1)
        assert not (diff & ~mask).any(), f"row table {t}: untouched moved"
        changed_inside += int((diff & mask).sum())
    assert changed_inside > 0
    # an over-range id neither appears nor crashes
    bad = [jnp.asarray(np.full((BATCH,), 10 ** 6, np.int32))
           for _ in SIZES]
    assert dist.touched_row_keys(bad) == {}


def test_store_publish_consume_roundtrip(tmp_path):
    """Train-publish-consume: snapshot anchor + chained deltas reproduce
    the live tables BIT-exactly; versions are monotonic per table; the
    chain guard rejects replays; restore_from_published rebuilds from
    (snapshot + deltas)."""
    dist = make_dist()
    rng = np.random.RandomState(1)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w in SIZES]
    model = EmbOnlyModel(dist)
    init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=0.1)
    p = {"embedding": dist.set_weights(weights)}
    s = init_fn(p)
    store = TableStore(dist, p["embedding"], s["emb"])
    d = str(tmp_path / "stream")

    assert store.version == 0 and store.table_versions == [0] * len(SIZES)
    store.commit(p["embedding"], s["emb"])
    info0 = store.publish(d)
    assert info0["kind"] == "snapshot" and info0["version"] == 1

    # double publish without a commit is refused (stream files are
    # keyed by version)
    with pytest.raises(ValueError, match="nothing committed"):
        store.publish(d)

    czero = dist.set_weights([np.zeros_like(w) for w in weights])
    cstore = TableStore(dist, czero)
    cons = DeltaConsumer(cstore, d)
    assert [i["kind"] for i in cons.poll()] == ["snapshot"]

    versions = [cstore.version]
    delta_infos = []
    for _ in range(2):
        cats = [jnp.asarray(rng.randint(0, v, (BATCH,)).astype(np.int32))
                for v, _ in SIZES]
        labels = jnp.asarray(rng.randn(BATCH).astype(np.float32))
        store.observe(cats)
        p, s, _ = step_fn(p, s, jnp.zeros((BATCH, 1)), cats, labels)
        store.commit(p["embedding"], s["emb"])
        delta_infos.append(store.publish(d))
    applied = cons.poll()
    assert [i["kind"] for i in applied] == ["delta", "delta"]
    versions += [i["version"] for i in applied]
    assert versions == sorted(versions) and len(set(versions)) == 3
    stats = cons.stats()
    assert stats["version_monotonic"] and stats["applied"] == 3
    assert stats["rows_applied"] > 0 and stats["delta_bytes_total"] > 0

    # bit-exact at the consumed version — the acceptance property
    for t, (a, b) in enumerate(zip(dist.get_weights(p["embedding"]),
                                   dist.get_weights(cstore.params))):
        np.testing.assert_array_equal(b, a, err_msg=f"table {t}")

    # delta bytes stay far under a full copy at these touched rates
    d_bytes = [i["bytes"] for i in delta_infos]
    assert max(d_bytes) < 0.1 * store.full_table_bytes(), (
        d_bytes, store.full_table_bytes())

    # chain integrity: replaying an already-consumed delta raises
    with pytest.raises(DeltaChainError):
        cstore.apply_published(delta_infos[0]["path"])

    # per-table versions: every table this workload touches moved
    assert all(v == store.version for v in store.table_versions)

    # (snapshot + deltas) checkpoint restore
    rstore = restore_from_published(dist, d)
    assert rstore.version == store.version
    for a, b in zip(dist.get_weights(p["embedding"]),
                    dist.get_weights(rstore.params)):
        np.testing.assert_array_equal(b, a)

    # compaction + resync: snapshot the stream, delete the (now
    # superseded) delta files, and a consumer that fell off the chain
    # recovers from the snapshot alone
    import os
    store.commit(p["embedding"], s["emb"],
                 touched=dist.touched_row_keys(
                     [jnp.asarray(np.zeros((4,), np.int32))
                      for _ in SIZES]))
    snap = store.publish(d, force_snapshot=True)
    for di in delta_infos:
        os.remove(di["path"])
    lost = TableStore(dist, dist.set_weights(
        [np.zeros_like(w) for w in weights]))
    lost.version = 2                         # mid-chain orphan
    out = DeltaConsumer(lost, d).poll()
    assert [i["kind"] for i in out] == ["snapshot"]
    assert lost.version == snap["version"]
    for a, b in zip(dist.get_weights(p["embedding"]),
                    dist.get_weights(lost.params)):
        np.testing.assert_array_equal(b, a)
    assert len(scan_published(d)) == 2


def test_store_sig_guard_and_replace(tmp_path):
    """A stream published for a different model is refused; `replace`
    breaks the chain so the next publish snapshots."""
    dist = make_dist()
    rng = np.random.RandomState(2)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w in SIZES]
    store = TableStore(dist, dist.set_weights(weights))
    d = str(tmp_path / "s")
    store.commit(store.params)
    store.publish(d)
    store.commit(store.params, touched={("tp", 0): np.arange(4)})
    info = store.publish(d)
    assert info["kind"] == "delta"

    other = DistributedEmbedding([Embedding(7, 4)], mesh=None)
    ostore = TableStore(other, other.set_weights(
        [np.zeros((7, 4), np.float32)]))
    with pytest.raises(ValueError, match="different model"):
        ostore.apply_published(info["path"])

    store.replace(store.params)
    assert all(v == store.version for v in store.table_versions)
    assert store.publish(d)["kind"] == "snapshot"


def test_consistency_seam_single_source():
    """(e) `read_rows` and `get_weights` agree on hot-resident rows by
    construction — and the test pins the OLD two-path failure mode: a
    canonical-only table read IS stale while rows are hot-resident, so
    any consumer that bypasses the shared `hot_resident_rows` source
    (as `get_weights`/`refresh` used to) serves wrong bytes."""
    vocab, width, B = 500, 8, 32
    rng = np.random.RandomState(3)
    emb = DistributedEmbedding([Embedding(vocab, width, combiner="sum")],
                               mesh=None, hot_rows=16)
    model = EmbOnlyModel(emb)
    init_fn, step_fn = make_sparse_train_step(model, "adagrad", lr=0.1)
    p = {"embedding": emb.init(jax.random.PRNGKey(0))}
    s = init_fn(p)
    store = TableStore(emb, p["embedding"], s["emb"])

    warm = (rng.zipf(1.3, size=(B, 2)) % vocab).astype(np.int32)
    emb.observe_hot_ids([warm])
    v0 = store.version
    store.sync_hot_rows(admit=True)
    assert store.version == v0 + 1           # consistency step is versioned
    p = {"embedding": store.params}
    s = {**s, "emb": store.opt_states}

    # train so hot-resident rows drift away from their canonical copies
    for _ in range(2):
        cats = [jnp.asarray((rng.zipf(1.3, size=(B, 2)) % vocab)
                            .astype(np.int32))]
        p, s, _ = step_fn(p, s, jnp.zeros((B, 1)), cats,
                          jnp.asarray(rng.randn(B).astype(np.float32)))
    store.commit(p["embedding"], s["emb"])

    keys, rows = emb.hot_resident_rows(store.params)[0]
    assert len(keys) > 0
    # one-source property: versioned read == hot shard == get_weights
    np.testing.assert_array_equal(store.read_rows(0, keys), rows)
    merged = emb.get_weights(store.params)[0]
    rows_max = max(emb.plan.tp_buckets[0].rows_max, 1)
    np.testing.assert_array_equal(merged[(keys % rows_max)], rows)

    # the pinned failure case: the canonical table alone (what the old
    # two-path consumers read) is STALE for resident rows mid-residency
    canonical = np.asarray(store.params["tp"][0])[
        (keys // rows_max).astype(int), (keys % rows_max).astype(int)]
    assert not np.array_equal(canonical, rows), \
        "expected canonical copies to lag the authoritative hot rows"

    # after the store-routed sync, canonical catches up and the merged
    # view is unchanged (sync is invisible to read_rows)
    before = store.read_rows(0, keys)
    store.sync_hot_rows()
    np.testing.assert_array_equal(store.read_rows(0, keys), before)
    canonical2 = np.asarray(store.params["tp"][0])[
        (keys // rows_max).astype(int), (keys % rows_max).astype(int)]
    np.testing.assert_array_equal(canonical2, rows)

    # a consumer with live hot residents refuses deltas (its overlay
    # would shadow the canonical writes)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        store.commit(store.params, touched={("tp", 0): np.arange(4)})
        store.publish(d)                      # snapshot (first publish)
        store.commit(store.params, touched={("tp", 0): np.arange(4)})
        info = store.publish(d)
        assert info["kind"] == "delta"
        hot_consumer = TableStore(emb, store.params)
        hot_consumer.version = info["base_version"]
        with pytest.raises(ValueError, match="EMPTY hot set"):
            hot_consumer.apply_published(info["path"])


def test_engine_streaming_consumption(tmp_path):
    """Serving replica consumption without training: the engine polls a
    publish directory, applies a snapshot then a delta (offloaded bucket
    -> the XLA-free host row-set path), patches resident HBM cache slots
    straight off the wire, and serves BIT-exactly at the new version."""
    from test_serving import SPECS, _build_offloaded

    rng = np.random.RandomState(4)
    mesh = create_mesh(jax.devices()[:8])
    dist = _build_offloaded(mesh)
    w0 = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]
    prod = TableStore(dist, dist.set_weights(w0))
    d = str(tmp_path / "pub")
    prod.commit(prod.params)
    prod.publish(d)

    engine = InferenceEngine(
        dist, dist.set_weights([np.zeros_like(w) for w in w0]),
        cache_capacity=1024, promote_threshold=1)
    assert [i["kind"] for i in engine.poll_updates(d)] == ["snapshot"]
    assert engine.store.version == 1

    hot = [np.tile(np.arange(4, dtype=np.int32), BATCH // 4)
           for _ in SPECS]
    for _ in range(3):                        # count -> promote -> cache
        engine.predict(hot)
    assert engine.cache_stats()["hits"] > 0

    # publisher mutates the rows the cache holds, publishes a DELTA
    w1 = [w.copy() for w in w0]
    for w in w1:
        w[:4] += 1.0
    prod.commit(dist.set_weights(w1), touched=dist.touched_row_keys(hot))
    info = prod.publish(d)
    assert info["kind"] == "delta"
    assert [i["version"] for i in engine.poll_updates(d)] == [2]

    got = [np.asarray(o) for o in engine.predict(hot)]
    uncached = jax.jit(lambda pp, c: dist.apply(pp, c))
    want = uncached(prod.params, [jnp.asarray(c) for c in hot])
    for i, (a, b) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(b, np.asarray(a), err_msg=f"out {i}")
    stats = engine.update_stats(d)
    assert stats["version_monotonic"] and stats["applied"] == 2
    assert stats["staleness_versions_max"] >= 1
    assert engine.cache_stats()["store_version"] == 2
    for cache in engine.caches.values():
        assert cache.refreshed_version == 2

    # set_params breaks the chain — including the ALIASING interleaving
    # where the publisher's next-next delta's base_version numerically
    # equals the consumer's post-replace version (engine at 2 ->
    # set_params bumps to 3; publisher's second delta below is v4 with
    # base 3): a bare version match must NOT let it chain onto the
    # swapped-in tables. A poll then recovers by re-anchoring on the
    # newest snapshot and replaying the chain from it.
    engine.set_params(dist.set_weights(w0), refresh=True)
    assert engine.store.version == 3
    prod.commit(prod.params, touched=dist.touched_row_keys(hot))
    assert prod.publish(d)["kind"] == "delta"         # v3 (base 2)
    prod.commit(prod.params, touched=dist.touched_row_keys(hot))
    aliasing = prod.publish(d)
    assert aliasing["kind"] == "delta"                # v4 (base 3)
    assert aliasing["base_version"] == engine.store.version
    with pytest.raises(DeltaChainError, match="out of band"):
        engine.store.apply_published(aliasing["path"])
    applied = engine.poll_updates(d)
    assert [i["kind"] for i in applied] == ["snapshot", "delta", "delta",
                                            "delta"]
    assert engine.store.version == prod.version == 4
    for a, b in zip(prod.get_weights(), engine.store.get_weights()):
        np.testing.assert_array_equal(b, a)


def test_vocab_binding_sidecar_roundtrip(tmp_path):
    """Dynamic-vocabulary sidecars (ISSUE 7): the binding table + slot
    free-list publish next to the row stream (`vocab_v{V}.npz`), scan by
    version, and rebuild a fresh manager's binding bit-exactly — the
    piece of vocab state that must survive train-to-serve handoff and
    checkpoint restore alongside the rows."""
    from distributed_embeddings_tpu.vocab import (VocabManager,
                                                  latest_vocab_state,
                                                  vocab_state_path)

    mesh = create_mesh(jax.devices()[:8])
    emb = DistributedEmbedding(
        [Embedding(v, w, combiner="sum") for v, w in SIZES],
        mesh=mesh, strategy="memory_balanced", row_slice_threshold=30000,
        vocab_slack=8)
    mgr = VocabManager(emb, admit_threshold=1, decay=0.9, use_native=False)
    rng = np.random.RandomState(5)
    params = emb.init(jax.random.PRNGKey(0))
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        for r in range(4):
            raw = [rng.randint(10**8 + r * 30, 10**8 + r * 30 + 40,
                               (16, 2)).astype(np.int64) for _ in SIZES]
            mgr.translate(raw, observe=True)
            params, _ = mgr.maintain(params)
    assert mgr.stats()["admissions"] > 0

    d = str(tmp_path)
    mgr.save_state(vocab_state_path(d, 3))
    mgr.save_state(vocab_state_path(d, 7))
    assert latest_vocab_state(d) == vocab_state_path(d, 7)
    assert latest_vocab_state(d, upto=5) == vocab_state_path(d, 3)
    assert latest_vocab_state(d, upto=1) is None

    fresh = VocabManager(emb, use_native=False)
    fresh.load_state(latest_vocab_state(d))
    probe = rng.randint(10**8, 10**8 + 200, 256).astype(np.int64)
    for t in mgr.vocabs:
        np.testing.assert_array_equal(fresh.vocabs[t].resident_keys(),
                                      mgr.vocabs[t].resident_keys())
        np.testing.assert_array_equal(
            fresh.vocabs[t].binding.free_slots(),
            mgr.vocabs[t].binding.free_slots())
        np.testing.assert_array_equal(fresh.vocabs[t].binding.lookup(probe),
                                      mgr.vocabs[t].binding.lookup(probe))
        # decayed counters survive too (eviction ranking after restore)
        np.testing.assert_allclose(
            fresh.vocabs[t].tracker.counts_for(probe),
            mgr.vocabs[t].tracker.counts_for(probe))

    # the ADMISSION POLICY restores with the state: a manager built with
    # different defaults resumes the SAVED threshold/decay, not its own
    assert fresh.admit_threshold == 1
    assert all(mv.tracker.promote_threshold == 1
               and mv.tracker.decay == 0.9
               for mv in fresh.vocabs.values())

    # a manager over a DIFFERENT slack (capacity) refuses the state
    emb2 = DistributedEmbedding(
        [Embedding(v, w, combiner="sum") for v, w in SIZES],
        mesh=mesh, strategy="memory_balanced", row_slice_threshold=30000,
        vocab_slack=32)
    other = VocabManager(emb2, use_native=False)
    with pytest.raises(ValueError, match="capacity"):
        other.load_state(latest_vocab_state(d))
