"""Sanity of the capacity planner's accounting (tools/capacity.py)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from capacity import per_chip_bytes  # noqa: E402


def test_tiny_single_chip_accounting():
    acct = per_chip_bytes("tiny", 1, 65536)
    # tiny tables are 4.19 GiB fp32; adagrad doubles it
    assert abs(acct["tables"] / 2**30 - 4.19) < 0.1
    assert acct["opt_state"] == acct["tables"]
    assert acct["total"] < 16 * 2**30  # fits one v5e


def test_per_chip_shrinks_with_world():
    sizes = [per_chip_bytes("small", w, 65536)["tables"]
             for w in (1, 8, 64)]
    assert sizes[0] > sizes[1] > sizes[2]
    # at 64 chips the per-chip share is within 4x of perfect balance
    perfect = sizes[0] / 64
    assert sizes[2] < 4 * perfect


def test_sgd_has_no_state():
    acct = per_chip_bytes("tiny", 8, 65536, optimizer="sgd")
    assert acct["opt_state"] == 0


def test_colossal_planning_completes():
    """The planner must handle the 2002-table colossal config (22.3 TiB)
    at pod scale: every table placed, every rank non-empty."""
    import time
    t0 = time.perf_counter()
    acct = per_chip_bytes("colossal", 128, 65536)
    dt = time.perf_counter() - t0
    # 22.3 TiB / 128 chips ≈ 178 GiB fair share; padding-inclusive
    # accounting must land within 3x of that
    per_chip = acct["tables"] / 2**30
    assert 100 < per_chip < 600, per_chip
    assert dt < 120, f"planning took {dt:.0f}s"
