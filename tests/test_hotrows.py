"""Hot-row replication (ISSUE 4): frequency-based hybrid parallelism in
the training step.

Parity contract: a hot-sharded step must match the no-hot-shard step.
At hotness 1 (the DLRM shape) every (sample, slot) lane is entirely hit
or miss, and the observed deviation is at float-rounding scale; for
k > 1 the split reorders float summation (hit einsum + miss einsum vs
one fused combine, dense scatter-add + psum vs segment-sum), so the
documented tolerance is allclose at 1e-5 — see docs/perf_model.md
"Hot-row replication".
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.parallel.mesh import create_mesh
from distributed_embeddings_tpu.training import fit, make_sparse_train_step

BATCH = 16
SPECS = [(40, 4, "sum"), (60, 8, "sum"), (30, 4, "sum"), (50, 8, "mean")]


class _TapModel:
    def __init__(self, mesh, specs=SPECS, **kw):
        self.embedding = DistributedEmbedding(
            [Embedding(v, w, combiner=c) for v, w, c in specs],
            mesh=mesh, **kw)

    def loss_fn(self, params, numerical, cats, labels, taps=None,
                return_residuals=False):
        out = self.embedding(params["embedding"], list(cats), taps=taps,
                             return_residuals=return_residuals)
        outs, res = out if return_residuals else (out, None)
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                            axis=1).astype(jnp.float32)
        loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
        return (loss, res) if return_residuals else loss

    def apply(self, params, numerical, cats):
        outs = self.embedding(params["embedding"], list(cats))
        x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                            axis=1)
        return jnp.sum(x, axis=1)


def _zipf_cats(data, specs=SPECS, hotness=2, batch=BATCH, weighted=False):
    cats = [jnp.asarray(np.minimum(
        data.zipf(1.3, size=(batch, hotness)) - 1, v - 1).astype(np.int32))
        for v, _, _ in specs]
    if not weighted:
        return cats
    return [(c, jnp.asarray(
        data.rand(batch, hotness).astype(np.float32) + 0.5)) for c in cats]


def _run(hot_rows, optimizer="adagrad", steps=3, admit_at=1, specs=SPECS,
         hotness=2, seed=0, strategy="auto", weighted=False, **kw):
    rng = np.random.RandomState(seed)
    mesh = create_mesh(jax.devices()[:8])
    model = _TapModel(mesh, specs=specs, hot_rows=hot_rows, **kw)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in specs]
    params = {"embedding": model.embedding.set_weights(weights)}
    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.05,
                                              strategy=strategy)
    state = init_fn(params)
    data = np.random.RandomState(7)
    losses = []
    for s in range(steps):
        cats = _zipf_cats(data, specs, hotness, weighted=weighted)
        labels = jnp.asarray(data.randn(BATCH).astype(np.float32))
        if hot_rows:
            model.embedding.observe_hot_ids(cats)
            if s == admit_at:
                p, st = model.embedding.sync_hot_rows(
                    params["embedding"], state["emb"], admit=True)
                params = {**params, "embedding": p}
                state = {**state, "emb": st}
                assert any(t.resident for t
                           in model.embedding._hot_trackers.values())
        params, state, loss = step_fn(params, state, jnp.zeros((BATCH, 1)),
                                      cats, labels)
        losses.append(float(loss))
    return losses, params, state, model


def _assert_parity(optimizer, strategy="auto", weighted=False, **env):
    import os
    for k, v in env.items():
        os.environ[k] = v
    try:
        l0, p0, _, m0 = _run(0, optimizer, strategy=strategy,
                             weighted=weighted)
        l1, p1, s1, m1 = _run(8, optimizer, strategy=strategy,
                              weighted=weighted)
    finally:
        for k in env:
            os.environ.pop(k, None)
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-6)
    w0 = m0.embedding.get_weights(p0["embedding"])
    w1 = m1.embedding.get_weights(p1["embedding"])
    for t, (a, b) in enumerate(zip(w0, w1)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5,
                                   err_msg=f"table {t} ({optimizer})")
    # and the synced canonical params agree with the overlayed dump
    p_sync, _ = m1.embedding.sync_hot_rows(p1["embedding"], s1["emb"])
    for a, b in zip(w1, m1.embedding.get_weights(p_sync)):
        np.testing.assert_allclose(a, b, atol=1e-6)


@pytest.mark.parametrize("exchange", ["padded", "ragged"])
def test_hot_parity_adagrad(exchange):
    """Hot-split vs no-hot-shard training parity, both exchange paths."""
    _assert_parity("adagrad", DET_RAGGED_EXCHANGE=(
        "1" if exchange == "ragged" else "0"))


def test_hot_parity_weighted_inputs():
    """(ids, weights) inputs take the EXPLICIT weight-exchange branch of
    the hot split — unweighted inputs skip that exchange and reconstruct
    the 0/scale effective weights receiver-side from the sentinel, so
    this is the only path that moves a weight block over the wire."""
    _assert_parity("adagrad", weighted=True)


# execution-bound on the single-core CPU test host: remaining optimizer x
# exchange combos run in the `-m slow` tier (same split as sort folding)
@pytest.mark.slow
@pytest.mark.parametrize("optimizer", ["sgd", "adam"])
@pytest.mark.parametrize("exchange", ["padded", "ragged"])
def test_hot_parity_optimizers(optimizer, exchange):
    _assert_parity(optimizer, DET_RAGGED_EXCHANGE=(
        "1" if exchange == "ragged" else "0"))


@pytest.mark.slow
def test_hot_parity_tiled_forward():
    """Hot split x tiled forward gather (DET_LOOKUP_PATH=tiled, interpret
    mode off-TPU): the presorted artifact covers the sentinel-masked
    stream — the tiled gather clamps sid internally, the update drops the
    sentinel lanes. Fold still holds (sort-bound gate lives in
    test_hlo_hot_step_adds_zero_sorts / hlo_audit)."""
    _assert_parity("adagrad", strategy="tiled", DET_LOOKUP_PATH="tiled")


def test_empty_hot_set_is_identity():
    """Before any admission the hot shard is behaviorally inert: every
    lookup misses and the membership is all-sentinel."""
    mesh = create_mesh(jax.devices()[:8])
    rng = np.random.RandomState(1)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]
    m0 = _TapModel(mesh)
    m1 = _TapModel(mesh, hot_rows=8)
    p0 = m0.embedding.set_weights(weights)
    p1 = m1.embedding.set_weights(weights)
    assert "hot" not in p0 and "hot" in p1
    cats = _zipf_cats(np.random.RandomState(2))
    out0 = m0.embedding(p0, cats)
    out1 = m1.embedding(p1, cats)
    for a, b in zip(out0, out1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_hot_forward_hits_read_hot_shard():
    """Resident rows are served from the replicated hot param: perturbing
    the hot rows changes the output; perturbing the canonical rows of
    resident ids does NOT (the canonical table is out of the hit path)."""
    mesh = create_mesh(jax.devices()[:2])
    specs = [(32, 4, "sum")]
    rng = np.random.RandomState(3)
    m = _TapModel(mesh, specs=specs, hot_rows=4)
    weights = [rng.randn(32, 4).astype(np.float32)]
    params = m.embedding.set_weights(weights)
    emb = m.embedding
    b = emb._hot_buckets[0]
    # admit ids 0 and 1 of input 0 across EVERY slot the input feeds
    # (column slices live on several ranks, each with its own key space)
    keys = []
    for (rank, bb, slot_idx) in emb.plan.tp_input_slots[0]:
        off = emb.plan.tp_buckets[bb].slots[rank][slot_idx].row_offset
        rows_max = max(emb.plan.tp_buckets[bb].rows_max, 1)
        keys += [rank * rows_max + off + 0, rank * rows_max + off + 1]
    params, _ = emb.sync_hot_rows(params, None, new_keys={b: np.asarray(keys)})
    cats = [jnp.asarray(np.array([[0, 1], [2, 3]], np.int32))]
    base = np.asarray(emb(params, cats)[0])
    # 1. poke the hot rows -> row-0/1 outputs move
    poked = dict(params)
    poked["hot"] = list(params["hot"])
    poked["hot"][b] = {"ids": params["hot"][b]["ids"],
                       "rows": params["hot"][b]["rows"] + 1.0}
    out = np.asarray(emb(poked, cats)[0])
    assert np.abs(out[0] - base[0]).max() > 0.5
    np.testing.assert_allclose(out[1], base[1], atol=1e-6)
    # 2. poke the canonical table everywhere -> only MISS ids move
    poked2 = dict(params)
    poked2["tp"] = [t + 1.0 for t in params["tp"]]
    out2 = np.asarray(emb(poked2, cats)[0])
    np.testing.assert_allclose(out2[0], base[0], atol=1e-6)
    assert np.abs(out2[1] - base[1]).max() > 0.5


def test_hot_adam_does_not_touch_masked_rows():
    """Regression (review finding): hit lanes are SENTINEL-masked, not
    id-0-masked — a zero-contribution touch at a real row is NOT the
    identity for lazy adam (moment decay runs on every touched row). Train
    a row's moments, admit a DIFFERENT id, keep hitting it: the trained
    row must stay bit-identical to the hot-less baseline."""
    specs = [(32, 8, "sum")]

    def drive(hot):
        model = _TapModel(None, specs=specs, hot_rows=hot)
        rng = np.random.RandomState(4)
        weights = [rng.randn(32, 8).astype(np.float32) * 0.1]
        params = {"embedding": model.embedding.set_weights(weights)}
        init_fn, step_fn = make_sparse_train_step(model, "adam", lr=0.05)
        state = init_fn(params)
        emb = model.embedding
        # step 0 trains id 0's moments (so a later spurious touch would
        # visibly bleed its momentum into the table)
        cats0 = [jnp.asarray(np.array([[0], [0]], np.int32))]
        params, state, _ = step_fn(params, state, jnp.zeros((2, 1)),
                                   cats0, jnp.ones((2,)))
        if hot:
            b = emb._hot_buckets[0]
            (rank, bb, slot_idx) = emb.plan.tp_input_slots[0][0]
            off = emb.plan.tp_buckets[bb].slots[rank][slot_idx].row_offset
            rows_max = max(emb.plan.tp_buckets[bb].rows_max, 1)
            p, s = emb.sync_hot_rows(
                params["embedding"], state["emb"],
                new_keys={b: np.asarray([rank * rows_max + off + 5])})
            params = {**params, "embedding": p}
            state = {**state, "emb": s}
        # steps with id 5 (the hot hit) and id 7, never id 0
        cats = [jnp.asarray(np.array([[5], [7]], np.int32))]
        for _ in range(4):
            params, state, _ = step_fn(params, state, jnp.zeros((2, 1)),
                                       cats, jnp.ones((2,)))
        return model.embedding.get_weights(params["embedding"])[0]

    w_base = drive(0)
    w_hot = drive(4)
    np.testing.assert_array_equal(w_base[0], w_hot[0])   # untouched row
    np.testing.assert_allclose(w_base, w_hot, rtol=1e-5, atol=1e-6)


def test_checkpoint_roundtrip_merges_hot_rows():
    """The portable dump (get_weights) overlays resident hot rows, a
    set_weights round-trip restarts empty-hot with identical numerics,
    and sync_hot_rows writes the rows back into the canonical arrays."""
    losses, params, state, model = _run(8, "adagrad", steps=3)
    emb = model.embedding
    # resident hot rows diverge from the canonical (stale) rows pre-sync
    w_overlay = emb.get_weights(params["embedding"])
    stale = dict(params["embedding"])
    stale.pop("hot")
    w_stale = emb.get_weights({**stale})
    assert any(np.abs(a - b).max() > 1e-7
               for a, b in zip(w_overlay, w_stale)), \
        "hot rows never diverged; test admits nothing?"
    # sync writes them back: canonical-only dump now matches the overlay
    p_sync, _ = emb.sync_hot_rows(params["embedding"], state["emb"])
    no_hot = dict(p_sync)
    no_hot.pop("hot")
    for a, b in zip(w_overlay, emb.get_weights(no_hot)):
        np.testing.assert_allclose(a, b, atol=1e-6)
    # portable round-trip: reload into a fresh layer, outputs agree
    mesh = create_mesh(jax.devices()[:8])
    m2 = _TapModel(mesh, hot_rows=8)
    p2 = {"embedding": m2.embedding.set_weights(w_overlay)}
    cats = _zipf_cats(np.random.RandomState(11))
    out1 = model.embedding(p_sync, cats)
    out2 = m2.embedding(p2["embedding"], cats)
    for a, b in zip(out1, out2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_sync_admission_gathers_canonical_state():
    """Admission copies rows AND optimizer-state rows from the canonical
    arrays, so admitting is numerically a no-op for the next update."""
    losses, params, state, model = _run(8, "adagrad", steps=2, admit_at=1)
    emb = model.embedding
    for pos_h, b in enumerate(emb._hot_buckets):
        entry = params["embedding"]["hot"][b]
        ids = np.asarray(jax.device_get(entry["ids"])).astype(np.int64)
        rows = np.asarray(jax.device_get(entry["rows"]))
        sent = emb._hot_sentinel(b)
        valid = ids < sent
        if not valid.any():
            continue
        # hot acc rows must be >= the adagrad init fill (gathered, not
        # re-initialized) wherever resident
        acc = np.asarray(jax.device_get(state["emb"]["hot"][pos_h][0]))
        assert (acc[valid] >= 0.1 - 1e-6).all()
        # membership is sorted with sentinel padding at the tail
        assert (np.diff(ids) >= 0).all()
        assert rows.shape[0] == emb.plan.tp_buckets[b].hot_rows


def test_hot_keys_from_counts_ranks_by_frequency():
    specs = [(32, 4, "sum")]
    m = _TapModel(None, specs=specs, hot_rows=4)   # world 1: single slot
    emb = m.embedding
    # over-length counts (IntegerLookup.counts() is [capacity+1] with the
    # OOV slot): entries past the table's input_dim must be DROPPED, not
    # attributed to neighboring tables'/ranks' rows (review finding)
    counts = [np.zeros((40,), np.int64)]
    counts[0][[3, 7, 9]] = [50, 40, 30]
    counts[0][20] = 5
    counts[0][35] = 1000           # past input_dim 32: must not admit
    new_keys = emb.hot_keys_from_counts(counts)
    b = emb._hot_buckets[0]
    (rank, bb, slot_idx) = emb.plan.tp_input_slots[0][0]
    off = emb.plan.tp_buckets[bb].slots[rank][slot_idx].row_offset
    rows_max = max(emb.plan.tp_buckets[bb].rows_max, 1)
    got_rows = sorted(k % rows_max - off for k in new_keys[b].tolist())
    assert got_rows == [3, 7, 9, 20]


def test_negative_ids_never_hit():
    """Regression (review finding): a negative id folds onto a LOWER
    slot/rank's key range and could alias a resident hot key there — it
    must always MISS and take the baseline's deterministic invalid-id
    path instead of being served another table's hot row."""
    specs = [(32, 4, "sum")]
    m0 = _TapModel(None, specs=specs)
    m1 = _TapModel(None, specs=specs, hot_rows=4)
    rng = np.random.RandomState(9)
    weights = [rng.randn(32, 4).astype(np.float32)]
    p0 = m0.embedding.set_weights(weights)
    p1 = m1.embedding.set_weights(weights)
    emb = m1.embedding
    b = emb._hot_buckets[0]
    (rank, bb, slot_idx) = emb.plan.tp_input_slots[0][0]
    off = emb.plan.tp_buckets[bb].slots[rank][slot_idx].row_offset
    rows_max = max(emb.plan.tp_buckets[bb].rows_max, 1)
    # admit id 2; then query id -1 whose folded key is base+(-1) = key of
    # id 1... and id (2 - 32) whose folded key aliases resident id 2
    p1, _ = emb.sync_hot_rows(p1, None,
                              new_keys={b: np.asarray(
                                  [rank * rows_max + off + 2])})
    cats = [jnp.asarray(np.array([[2 - 32], [-1]], np.int32))]
    out0 = np.asarray(m0.embedding(p0, cats)[0])
    out1 = np.asarray(m1.embedding(p1, cats)[0])
    np.testing.assert_allclose(out1, out0, rtol=1e-6, atol=1e-7)


def test_padding_report_post_hot_accounting():
    _, params, state, model = _run(8, "adagrad", steps=2)
    rep = model.embedding.exchange_padding_report()
    assert "hot_hit_ids" in rep and "true_ids_post_hot" in rep
    assert rep["hot_hit_ids"] >= 0
    # residual USEFUL volume subtracts from true ids, never from the
    # (padded, unchanged) wire-slot count
    assert rep["true_ids_post_hot"] \
        == rep["true_ids"] - rep["hot_hit_ids"]
    hot_entries = [g for g in rep["groups"] if "hot_hit_ids" in g]
    assert hot_entries, rep
    for g in hot_entries:
        assert g["true_ids_post_hot"] == g["true_ids"] - g["hot_hit_ids"]
        assert 0 <= g["true_ids_post_hot"] <= g["true_ids"]
    # projection override
    rep2 = model.embedding.exchange_padding_report(hot_hit_rate=0.5)
    assert rep2["hot_hit_ids"] > 0


def test_hlo_hot_step_adds_zero_sorts():
    """Acceptance gate (ISSUE 4): the hot-split tapped step lowers with NO
    additional sort instructions per exchange group versus the folded
    baseline — membership is a searchsorted (binary search), the hot
    update a dense scatter."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "det_hlo_audit", os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools",
            "hlo_audit.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    base = mod.audit_tapped_step(strategy="sort", hot_rows=0)
    hot = mod.audit_tapped_step(strategy="sort", hot_rows=1024)
    assert hot["hlo_sort"] <= base["hlo_sort"], (base, hot)
    assert hot["hlo_sort"] <= hot["sort_bound"], hot


def test_fit_hot_sync_every_smoke():
    """fit()'s hot_sync_every cadence: observes, admits, returns
    canonical-consistent params + hot stats in the history."""
    mesh = create_mesh(jax.devices()[:8])
    model = _TapModel(mesh, hot_rows=8)
    rng = np.random.RandomState(5)
    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w, _ in SPECS]
    params = {"embedding": model.embedding.set_weights(weights)}
    data = np.random.RandomState(6)

    def batch(step):
        return (np.zeros((BATCH, 1), np.float32),
                [np.asarray(c) for c in _zipf_cats(data)],
                data.randn(BATCH).astype(np.float32))

    params, opt_state, hist = fit(model, params, batch, steps=4,
                                  optimizer="adagrad", lr=0.05,
                                  log_every=0, hot_sync_every=2)
    assert "hot_stats" in hist and hist["hot_stats"]
    assert any(s["resident"] for s in hist["hot_stats"].values())
    assert len(hist["loss"]) == 4
    assert np.isfinite(hist["loss"]).all()


def test_integer_lookup_counts_feed_admission():
    """IntegerLookup exposes per-index frequencies (native in-probe
    counting / numpy per-occurrence counting) in the shape
    hot_keys_from_counts consumes."""
    from distributed_embeddings_tpu.layers.embedding import IntegerLookup

    lk = IntegerLookup(16)
    lk(np.array([100, 100, 100, 200, 200, 300]))
    c = lk.counts()
    assert c.shape == (17,)
    # indices are assigned in first-appearance order: 100->1, 200->2, 300->3
    assert c[1] == 3 and c[2] == 2 and c[3] == 1


def test_tapped_forward_without_hot_taps_raises():
    """A hand-built tap pytree ({'tp', 'row'} — the pre-hot-shard
    contract) on an active hot split must be rejected: the split masks
    resident rows' canonical gradients to zero by design, so their
    updates flow ONLY through taps['hot'] — accepting such taps would
    silently freeze the hottest rows."""
    mesh = create_mesh(jax.devices()[:8])
    model = _TapModel(mesh, hot_rows=8)
    params = {"embedding": model.embedding.init(jax.random.PRNGKey(0))}
    cats = _zipf_cats(np.random.RandomState(0))
    taps = model.embedding.make_taps(cats)
    assert "hot" in taps
    # tapless and make_taps-built forwards both work
    model.embedding(params["embedding"], list(cats))
    model.embedding(params["embedding"], list(cats), taps=taps)
    with pytest.raises(ValueError, match=r"taps\['hot'\]"):
        model.embedding(params["embedding"], list(cats),
                        taps={"tp": taps["tp"], "row": taps["row"]})


def test_observe_hot_ids_ignores_out_of_range_ids():
    """The host-side observer mirrors the device split's lane_rows guard:
    ids outside [0, segment rows) neither count toward a NEIGHBORING
    segment's flat key (phantom admission) nor toward hit/miss stats the
    padding report folds in (the device split forces them to miss)."""
    mesh = create_mesh(jax.devices()[:8])
    model = _TapModel(mesh, hot_rows=8)
    tr_before = dict(model.embedding.hot_stats())
    model.embedding.observe_hot_ids(
        [np.full((BATCH, 2), v + 1000, np.int32) for v, _, _ in SPECS])
    stats = model.embedding.hot_stats()
    assert all(s["tracked"] == 0 and s["hits"] == 0 and s["misses"] == 0
               for s in stats.values()), (tr_before, stats)
    # in-range ids still count
    model.embedding.observe_hot_ids(
        [np.zeros((BATCH, 2), np.int32) for _ in SPECS])
    assert all(s["tracked"] > 0 for s in model.embedding.hot_stats().values())
