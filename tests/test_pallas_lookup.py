"""Pallas fused lookup kernels vs the XLA-native reference path.

Mirrors the reference's op-level numeric tests (embedding_lookup_ops_test.py:
custom kernel vs tf.nn.embedding_lookup_sparse). Kernels run in interpreter
mode on CPU; the same code compiles on TPU."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_embeddings_tpu.ops import pallas_lookup
from distributed_embeddings_tpu.ops.pallas_lookup import (
    _onehot_lookup, _dma_gather_lookup, fused_embedding_lookup)


def ref_weighted(table, ids, weights, combiner="sum"):
    embs = jnp.take(table, ids, axis=0)
    out = jnp.einsum("bk,bkw->bw", weights, embs)
    if combiner == "mean":
        out = out / jnp.maximum(jnp.sum(weights, axis=1), 1.0)[:, None]
    return out


def make_case(batch, hot, vocab, width, seed=0, pad_frac=0.3):
    rng = np.random.RandomState(seed)
    table = jnp.asarray(rng.randn(vocab, width).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, vocab, size=(batch, hot)).astype(np.int32))
    weights = jnp.asarray(
        (rng.rand(batch, hot) > pad_frac).astype(np.float32))
    return table, ids, weights


@pytest.mark.parametrize("batch,hot,vocab,width", [
    (32, 1, 100, 128),
    (64, 5, 1000, 128),
    (48, 10, 511, 256),   # odd vocab -> padded vocab tile
    (100, 3, 70, 128),    # batch not a tile multiple
])
def test_onehot_kernel_vs_ref(batch, hot, vocab, width):
    table, ids, weights = make_case(batch, hot, vocab, width)
    got = _onehot_lookup(table, ids, weights, tile_b=32, tile_v=128,
                         interpret=True)
    want = ref_weighted(table, ids, weights)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("batch,hot,vocab,width", [
    (16, 1, 20000, 128),
    (16, 4, 20000, 128),
    (20, 7, 50000, 256),  # batch not a tile multiple
])
def test_dma_gather_kernel_vs_ref(batch, hot, vocab, width):
    table, ids, weights = make_case(batch, hot, vocab, width, seed=1)
    got = _dma_gather_lookup(table, ids, weights, interpret=True)
    want = ref_weighted(table, ids, weights)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
@pytest.mark.parametrize("vocab", [500, 20000])
def test_fused_dispatch_and_combiners(vocab, combiner):
    table, ids, weights = make_case(24, 4, vocab, 128, seed=2)
    got = fused_embedding_lookup(table, ids, weights, combiner=combiner,
                                 interpret=True)
    want = ref_weighted(table, ids, weights, combiner=combiner)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fused_xla_fallback_width():
    # width 72 is not lane-aligned and vocab is big -> XLA fallback path
    table, ids, weights = make_case(16, 3, 20000, 72, seed=3)
    got = fused_embedding_lookup(table, ids, weights, interpret=True)
    want = ref_weighted(table, ids, weights)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("vocab", [300, 20000])
def test_fused_gradients(vocab):
    table, ids, weights = make_case(16, 3, vocab, 128, seed=4)
    cot = jnp.asarray(np.random.RandomState(5).randn(16, 128)
                      .astype(np.float32))

    def loss_fused(t, w):
        return jnp.vdot(fused_embedding_lookup(t, ids, w, interpret=True), cot)

    def loss_ref(t, w):
        return jnp.vdot(ref_weighted(t, ids, w), cot)

    gt, gw = jax.grad(loss_fused, argnums=(0, 1))(table, weights)
    rt, rw = jax.grad(loss_ref, argnums=(0, 1))(table, weights)
    np.testing.assert_allclose(gt, rt, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(gw, rw, rtol=1e-5, atol=1e-5)


def test_fused_under_jit():
    table, ids, weights = make_case(32, 2, 400, 128, seed=6)
    f = jax.jit(lambda t, i, w: fused_embedding_lookup(t, i, w,
                                                       interpret=True))
    got = f(table, ids, weights)
    want = ref_weighted(table, ids, weights)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_oob_ids_clamp_like_xla():
    # XLA jnp.take clamps OOB ids; the fused path must match
    table, ids, weights = make_case(16, 3, 500, 128, seed=7)
    bad = ids.at[0, 0].set(10_000).at[3, 2].set(-5)
    got = fused_embedding_lookup(table, bad, weights, interpret=True)
    want = ref_weighted(table, jnp.clip(bad, 0, 499), weights)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_output_dtype_matches_table():
    table, ids, weights = make_case(16, 3, 500, 128, seed=8)
    bf16 = table.astype(jnp.bfloat16)
    out = fused_embedding_lookup(bf16, ids, weights, interpret=True)
    assert out.dtype == jnp.bfloat16
