"""IntegerLookup tests — semantics mirror of the reference's
integer_lookup_test.py (tested against keras IntegerLookup behavior):
on-the-fly vocab build, OOV -> 0, get_vocabulary ordering. Both the native
C++ backend and the numpy fallback are covered."""

import numpy as np
import pytest

from distributed_embeddings_tpu.layers.embedding import IntegerLookup


@pytest.mark.parametrize("use_native", [True, False])
def test_build_and_lookup(use_native):
    layer = IntegerLookup(max_tokens=10, use_native=use_native)
    keys = np.array([[42, 7], [42, 99], [7, 7]], dtype=np.int64)
    out = layer(keys)
    assert out.shape == keys.shape
    # same key -> same index, distinct keys -> distinct indices, none are OOV
    assert out[0, 0] == out[1, 0]
    assert out[0, 1] == out[2, 0] == out[2, 1]
    assert out[0, 0] != out[0, 1]
    assert (np.asarray(out) > 0).all()


@pytest.mark.parametrize("use_native", [True, False])
def test_oov_when_full(use_native):
    layer = IntegerLookup(max_tokens=2, use_native=use_native)
    out = layer(np.array([10, 20, 30, 40], dtype=np.int64))
    assert out[0] == 1 and out[1] == 2
    assert out[2] == 0 and out[3] == 0  # table full -> OOV index 0


@pytest.mark.parametrize("use_native", [True, False])
def test_get_vocabulary(use_native):
    layer = IntegerLookup(max_tokens=10, use_native=use_native)
    layer(np.array([5, 3, 5, 8], dtype=np.int64))
    vocab = layer.get_vocabulary()
    # reference returns [-1] + keys in lookup-index order (embedding.py:271)
    assert vocab == [-1, 5, 3, 8]


@pytest.mark.parametrize("use_native", [True, False])
def test_query_only_lookup(use_native):
    layer = IntegerLookup(max_tokens=10, use_native=use_native)
    layer(np.array([5, 3], dtype=np.int64))
    out = layer.lookup(np.array([3, 999], dtype=np.int64))
    assert out[0] == 2 and out[1] == 0


def test_native_matches_numpy():
    rng = np.random.RandomState(0)
    keys = rng.randint(0, 50, size=500).astype(np.int64)
    a = IntegerLookup(max_tokens=30, use_native=True)
    b = IntegerLookup(max_tokens=30, use_native=False)
    np.testing.assert_array_equal(a(keys), b(keys))
    np.testing.assert_array_equal(a(keys[::-1]), b(keys[::-1]))
    assert a.get_vocabulary() == b.get_vocabulary()


@pytest.mark.parametrize("use_native", [True, False])
def test_erase_and_free_slot_reuse(use_native):
    """Eviction surface (ISSUE 7): erase releases a key's index back to
    a free list that later insertions reuse (LIFO) before minting new
    indices; erased keys read as OOV; counts reset so a reused index
    never inherits its previous tenant's frequency."""
    layer = IntegerLookup(max_tokens=6, use_native=use_native)
    if use_native and not layer.native:
        pytest.skip("native backend unavailable")
    assert layer(np.array([10, 20, 30], np.int64)).tolist() == [1, 2, 3]
    freed = layer.erase(np.array([20, 99], np.int64))
    assert freed.tolist() == [2, 0]          # 99 was never bound
    assert layer.free_slots().tolist() == [2]
    assert layer.lookup(np.array([20]))[0] == 0
    assert layer.size == 3                   # 10, 30 + OOV
    # get_vocabulary keeps later keys index-aligned via a None hole
    assert layer.get_vocabulary() == [-1, 10, None, 30]
    # reuse: freed index first, then a fresh one past the high water
    assert layer(np.array([40, 50], np.int64)).tolist() == [2, 4]
    assert layer.free_slots().tolist() == []
    assert layer.get_vocabulary() == [-1, 10, 40, 30, 50]
    c = layer.counts()
    assert c[2] == 1                         # 40's count, not 20's


@pytest.mark.parametrize("use_native", [True, False])
def test_erase_capacity_recovers(use_native):
    """A full table that erases a key can admit a new one — the bounded
    table follows an unbounded key space."""
    layer = IntegerLookup(max_tokens=2, use_native=use_native)
    if use_native and not layer.native:
        pytest.skip("native backend unavailable")
    assert layer(np.array([10, 20, 30], np.int64)).tolist() == [1, 2, 0]
    layer.erase(np.array([10], np.int64))
    assert layer(np.array([30], np.int64)).tolist() == [1]


@pytest.mark.parametrize("use_native", [True, False])
def test_reserved_sentinel_keys_map_to_oov(use_native):
    """The native map's slot sentinels (INT64_MIN, INT64_MIN+1 — empty
    and tombstone) are RESERVED key values on both backends: they
    translate to OOV on every path and are never stored (a stored
    sentinel would corrupt probe chains / hole exports)."""
    layer = IntegerLookup(max_tokens=8, use_native=use_native)
    if use_native and not layer.native:
        pytest.skip("native backend unavailable")
    lo = np.iinfo(np.int64).min
    keys = np.array([lo, lo + 1, 5], np.int64)
    out = layer(keys)
    assert out.tolist() == [0, 0, 1]          # sentinels -> OOV, 5 binds
    assert layer.lookup(keys).tolist() == [0, 0, 1]
    assert layer.erase(keys[:2]).tolist() == [0, 0]
    assert layer.size == 2                    # only {5} + OOV
    assert layer.get_vocabulary() == [-1, 5]
    # a probe chain crossing where a sentinel "key" would have sat stays
    # intact under further churn
    layer(np.array([lo, 6, 7], np.int64))
    assert layer.lookup(np.array([5, 6, 7])).tolist() == [1, 2, 3]


def test_erase_native_matches_numpy_under_churn():
    """Random insert/erase churn (deep enough to trigger the native
    map's tombstone rehash) keeps both backends byte-identical —
    indices, free lists, vocabulary and query lookups."""
    nat = IntegerLookup(max_tokens=200, use_native=True)
    if not nat.native:
        pytest.skip("native backend unavailable")
    ref = IntegerLookup(max_tokens=200, use_native=False)
    rng = np.random.RandomState(0)
    for _ in range(30):
        keys = rng.randint(0, 400, size=300).astype(np.int64)
        np.testing.assert_array_equal(nat(keys), ref(keys))
        dead = rng.choice(400, size=40, replace=False).astype(np.int64)
        np.testing.assert_array_equal(nat.erase(dead), ref.erase(dead))
        np.testing.assert_array_equal(nat.free_slots(), ref.free_slots())
        assert nat.get_vocabulary() == ref.get_vocabulary()
        probe = rng.randint(0, 500, size=64).astype(np.int64)
        np.testing.assert_array_equal(nat.lookup(probe), ref.lookup(probe))


def test_io_callback_under_jit():
    import jax
    import jax.numpy as jnp
    layer = IntegerLookup(max_tokens=10)

    @jax.jit
    def f(x):
        return layer.as_callback(x)

    out = f(jnp.asarray(np.array([9, 9, 4], np.int64)))
    assert out[0] == out[1] != out[2]


def test_native_build_failure_warns(monkeypatch):
    """A broken native build must be loud (VERDICT r2 weak 5): the criteo
    pipeline silently becoming host-bound is the failure mode."""
    import builtins
    import warnings

    real_import = builtins.__import__

    def broken(name, *a, **kw):
        if "native" in name and "hashmap" in str(a) + name:
            raise OSError("simulated compiler failure")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", broken)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        layer = IntegerLookup(max_tokens=10)
    assert not layer.native
    assert any("falling back to the pure-Python" in str(x.message)
               for x in w), [str(x.message) for x in w]
    # fallback still functions
    assert layer(np.array([5, 5, 9])).tolist() == [1, 1, 2]


def test_disable_env_is_silent(monkeypatch):
    import warnings
    monkeypatch.setenv("DET_DISABLE_NATIVE", "1")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        layer = IntegerLookup(max_tokens=10)
    assert not layer.native
    assert not [x for x in w if "pure-Python" in str(x.message)]


def test_native_parallel_large_batch_matches_sequential():
    """The parallel two-phase native path (multi-thread probe + ordered
    insert) must be indistinguishable from the sequential numpy reference:
    same indices, same insertion order, duplicate-heavy batches well past
    the threading threshold (32k keys)."""
    nat = IntegerLookup(max_tokens=50_000, use_native=True)
    if not nat.native:
        import pytest
        pytest.skip("native backend unavailable")
    ref = IntegerLookup(max_tokens=50_000, use_native=False)
    rng = np.random.RandomState(0)
    for size_hint in (30_000, 45_000):     # growth batch, then mostly-hits
        keys = rng.randint(0, size_hint, size=200_000).astype(np.int64)
        np.testing.assert_array_equal(nat(keys), ref(keys))
    assert nat.get_vocabulary() == ref.get_vocabulary()
    # overflow batch: indices past capacity must map to OOV identically
    keys = rng.randint(50_000, 120_000, size=200_000).astype(np.int64)
    np.testing.assert_array_equal(nat(keys), ref(keys))
    assert nat.size == ref.size == 50_001


def test_native_pool_survives_fork():
    """The persistent worker pool (PR 3) spawns detached threads that a
    fork()ed child does not inherit; the pool must respawn its workers in
    the child instead of waiting forever on dead ones (fork-start data
    loaders do exactly this)."""
    import os
    nat = IntegerLookup(max_tokens=500_000, use_native=True)
    if not nat.native:
        pytest.skip("native backend unavailable")
    rng = np.random.RandomState(3)
    keys = rng.randint(0, 400_000, size=200_000).astype(np.int64)  # pool path
    expect = nat(keys)
    pid = os.fork()
    if pid == 0:
        # child: only native-lookup work, then hard-exit (no pytest
        # machinery, no jax) — a hang here means the pool dispatched to
        # worker threads that do not exist in this process
        ok = np.array_equal(nat(keys), expect)
        os._exit(0 if ok else 1)
    _, status = os.waitpid(pid, 0)
    assert os.WIFEXITED(status) and os.WEXITSTATUS(status) == 0, status
