"""DLRM training on TPU — the flagship acceptance workload.

TPU-native re-design of the reference DLRM example
(reference: examples/dlrm/main.py): bottom MLP -> 26 embeddings via
DistributedEmbedding -> dot interaction -> top MLP, trained with a single
jit-compiled SPMD step over a device mesh (no Horovod choreography, no
broadcast bootstrapping — same program + seed everywhere).

Datasets:
  * --data_path pointing at the Criteo-1TB split-binary layout
    (label.bin / numerical.bin / cat_*.bin, see models/data.py) — read with
    native pread prefetch.
  * --synthetic (default): random ids at the MLPerf DLRM shapes.

Examples:
  python examples/dlrm/main.py --synthetic --steps 64 --batch_size 2048 \
      --devices 8 --force_cpu          # 8 virtual CPU devices, smoke run
  python examples/dlrm/main.py --data_path /data/criteo --amp
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))  # repo root

import argparse
import time
from contextlib import nullcontext

# Criteo-1TB MLPerf vocab sizes (reference examples/dlrm/main.py:47)
CRITEO_TABLE_SIZES = [
    39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63, 38532951,
    2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14, 39979771, 25641295,
    39664984, 585935, 12972, 108, 36,
]


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data_path", default=None,
                   help="Criteo split-binary dir (train/ + test/)")
    p.add_argument("--synthetic", action="store_true", default=False)
    p.add_argument("--batch_size", type=int, default=65536)
    p.add_argument("--steps", type=int, default=0,
                   help="0 = one epoch (or 512 synthetic steps)")
    p.add_argument("--eval_steps", type=int, default=64)
    p.add_argument("--embedding_dim", type=int, default=128)
    p.add_argument("--num_numerical", type=int, default=13)
    p.add_argument("--top_mlp", default="1024,1024,512,256,1")
    p.add_argument("--bottom_mlp", default="512,256,128")
    p.add_argument("--lr", type=float, default=24.0)
    p.add_argument("--warmup_steps", type=int, default=8000)
    p.add_argument("--decay_start_step", type=int, default=48000)
    p.add_argument("--decay_steps", type=int, default=24000)
    p.add_argument("--sparse_strategy", default="auto",
                   choices=["auto", "sort", "dense", "tiled"],
                   help="sparse aggregation strategy: tiled = the Pallas "
                        "one-hot-matmul kernels (hardware-validated)")
    p.add_argument("--dense_grads", action="store_true",
                   help="dense table grads + optax instead of the default "
                        "sparse row-wise update path")
    p.add_argument("--amp", action="store_true",
                   help="bfloat16 compute (reference AMP analogue)")
    p.add_argument("--dist_strategy", default="memory_balanced",
                   choices=["basic", "memory_balanced", "memory_optimized",
                            "comm_balanced", "auto"],
                   help="table placement: the three reference strategies "
                        "plus comm_balanced (exchange-padding-aware) and "
                        "auto (the library default)")
    p.add_argument("--column_slice_threshold", type=int, default=None)
    p.add_argument("--row_slice_threshold", type=int, default=None)
    p.add_argument("--data_parallel_threshold", type=int, default=None)
    p.add_argument("--table_scale", type=float, default=1.0,
                   help="scale Criteo vocab sizes (CPU smoke runs)")
    p.add_argument("--serial_ingest", action="store_true",
                   help="run read/decode/stage inline in the consumer "
                        "thread instead of the background ingestion "
                        "pipeline (A/B baseline)")
    p.add_argument("--pipeline_depth", type=int, default=2,
                   help="bound of each ingestion-pipeline queue")
    p.add_argument("--devices", type=int, default=0, help="0 = all")
    p.add_argument("--force_cpu", action="store_true",
                   help="run on virtual CPU devices (testing)")
    p.add_argument("--save_weights", default=None,
                   help="save global embedding weights npz here at the end")
    p.add_argument("--checkpoint_dir", default=None)
    p.add_argument("--log_every", type=int, default=32)
    p.add_argument("--seed", type=int, default=12345)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.synthetic and args.data_path:
        raise SystemExit("--synthetic and --data_path are mutually exclusive")
    if args.force_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        n = args.devices or 8
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={n}").strip()

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")

    from distributed_embeddings_tpu.models.dlrm import DLRM, make_lr_schedule
    from distributed_embeddings_tpu.models.data import (DummyDataset,
                                                        RawBinaryDataset)
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.training import make_train_step
    from distributed_embeddings_tpu.utils.metrics import StreamingAUC
    from distributed_embeddings_tpu.utils import checkpoint as ckpt_lib

    devices = jax.devices()
    if args.devices:
        devices = devices[:args.devices]
    mesh = create_mesh(devices) if len(devices) > 1 else None
    print(f"devices: {len(devices)} x {devices[0].platform}", flush=True)

    table_sizes = [max(4, int(v * args.table_scale))
                   for v in CRITEO_TABLE_SIZES]
    model = DLRM(
        table_sizes=table_sizes,
        embedding_dim=args.embedding_dim,
        bottom_mlp_dims=[int(x) for x in args.bottom_mlp.split(",")],
        top_mlp_dims=[int(x) for x in args.top_mlp.split(",")],
        num_numerical_features=args.num_numerical,
        mesh=mesh,
        dist_strategy=args.dist_strategy,
        column_slice_threshold=args.column_slice_threshold,
        row_slice_threshold=args.row_slice_threshold,
        data_parallel_threshold=args.data_parallel_threshold,
        compute_dtype=jnp.bfloat16 if args.amp else jnp.float32)

    params = model.init(jax.random.PRNGKey(args.seed))
    schedule = make_lr_schedule(args.lr, args.warmup_steps,
                                args.decay_start_step, args.decay_steps)

    if args.data_path:
        train_data = RawBinaryDataset(
            args.data_path, batch_size=args.batch_size,
            numerical_features=args.num_numerical,
            categorical_features=list(range(len(table_sizes))),
            categorical_feature_sizes=table_sizes, dp_input=True,
            offset=0, local_batch_size=args.batch_size)
        steps = args.steps or len(train_data)
    else:
        rng = np.random.RandomState(args.seed)
        batches = []
        for _ in range(8):
            numerical = rng.rand(args.batch_size,
                                 args.num_numerical).astype(np.float32)
            cats = [rng.randint(0, v, args.batch_size).astype(np.int32)
                    for v in table_sizes]
            labels = rng.randint(0, 2, (args.batch_size, 1)).astype(np.float32)
            batches.append((numerical, cats, labels))
        train_data = batches
        steps = args.steps or 512

    if args.dense_grads:
        opt = optax.sgd(schedule)
        opt_state = opt.init(params)

        def loss_fn(p, numerical, cats, labels):
            return model.loss_fn(p, numerical, cats, labels)

        step_fn = make_train_step(loss_fn, opt, donate=False)
    else:
        # production path: row-wise sparse embedding updates
        from distributed_embeddings_tpu.training import make_sparse_train_step
        init_fn, step_fn = make_sparse_train_step(
            model, "sgd", lr=schedule, donate=False,
            strategy=args.sparse_strategy)
        opt_state = init_fn(params)

    # resume: restore params + optimizer state from the newest step under
    # --checkpoint_dir (orbax keeps the saved shardings; same-topology
    # resume, reference-parity mechanism is save/load_global_weights)
    start_step = 0
    if args.checkpoint_dir:
        last = ckpt_lib.latest_step(args.checkpoint_dir)
        if last is not None:
            saved = ckpt_lib.checkpoint_keys(args.checkpoint_dir, step=last)
            # unreadable metadata (saved is None) -> attempt the full
            # restore and let orbax surface the real error; only a
            # positively-identified params-only save skips opt_state
            if saved is None or "opt_state" in saved:
                restored = ckpt_lib.restore_checkpoint(
                    args.checkpoint_dir,
                    {"params": params, "opt_state": opt_state}, step=last)
                params, opt_state = restored["params"], restored["opt_state"]
            else:
                # params-only checkpoint (written before opt_state was
                # saved): restore params, keep the fresh opt_state
                restored = ckpt_lib.restore_checkpoint(
                    args.checkpoint_dir, {"params": params}, step=last)
                params = restored["params"]
                print("params-only checkpoint: optimizer state reset",
                      flush=True)
            start_step = last
            print(f"resumed from step {last}", flush=True)

    # ingestion pipeline: read (pread) -> preprocess (decode) -> stage
    # (device_put) in persistent background workers so host batch prep
    # hides under the device step (utils/pipeline.py; --serial_ingest
    # keeps the old inline form — identical batch order)
    from distributed_embeddings_tpu.utils.pipeline import (IngestPipeline,
                                                           SerialPipeline)

    def stage_batch(batch):
        # per-leaf jnp.asarray, NOT jax.device_put: uncommitted placement
        # preserves the pre-pipeline loop's behavior under a mesh (jit
        # places inputs; a committed device-0 array would force a reshard)
        numerical, cats, labels = batch
        return (jnp.asarray(numerical),
                [jnp.asarray(c) for c in cats],
                jnp.asarray(labels))

    if args.data_path:
        source = train_data.raw_batches(steps)
        stages = [("preprocess", train_data.preprocess),
                  ("stage", stage_batch)]
    else:
        source = (train_data[i % len(train_data)] for i in range(steps))
        stages = [("stage", stage_batch)]
    if args.serial_ingest:
        pipe = SerialPipeline(source, stages)
    else:
        pipe = IngestPipeline(source, stages, depth=args.pipeline_depth)

    ctx = mesh or nullcontext()
    t_start = time.perf_counter()
    samples = 0
    with ctx:
        it = iter(pipe)
        # warmup/compile on batch 0
        numerical, cats, labels = next(it)
        params, opt_state, loss = step_fn(params, opt_state, numerical, cats,
                                          labels)
        float(loss)   # fetch = real sync (axon: block_until_ready lies)
        print(f"compiled in {time.perf_counter() - t_start:.1f}s", flush=True)

        t0 = time.perf_counter()
        for i in range(1, steps):
            numerical, cats, labels = next(it)
            params, opt_state, loss = step_fn(params, opt_state, numerical,
                                              cats, labels)
            samples += args.batch_size
            if i % args.log_every == 0 or i == steps - 1:
                lv = float(loss)
                dt = time.perf_counter() - t0
                print(f"step {i}/{steps} loss={lv:.5f} "
                      f"throughput={samples / dt:,.0f} samples/s", flush=True)
        float(loss)   # fetch-sync before the throughput claim (see above)
        dt = time.perf_counter() - t0
        pipe.close()
        if samples:
            print(f"TRAIN DONE: {samples / dt:,.0f} samples/sec "
                  f"({dt / max(steps - 1, 1) * 1e3:.2f} ms/step)", flush=True)
        stage_ms = {k: v["mean_ms"]
                    for k, v in pipe.stage_summaries().items()}
        print(f"ingest stages mean ms "
              f"({'serial' if args.serial_ingest else 'pipelined'}): "
              f"{stage_ms}", flush=True)

        # ---- eval: streaming AUC over held-out batches -------------------
        metric = StreamingAUC()
        state = metric.init()

        @jax.jit
        def eval_step(p, state, numerical, cats, labels):
            logits = model.apply(p, numerical, cats)
            return metric.update(state, labels, logits[:, 0])

        if args.data_path:
            valid = RawBinaryDataset(
                args.data_path, batch_size=args.batch_size,
                numerical_features=args.num_numerical,
                categorical_features=list(range(len(table_sizes))),
                categorical_feature_sizes=table_sizes, dp_input=True,
                valid=True, offset=0, local_batch_size=args.batch_size)
            n_eval = min(args.eval_steps, len(valid))
            eval_src = valid
        else:
            n_eval = min(args.eval_steps, len(train_data))
            eval_src = train_data
        for i in range(n_eval):
            numerical, cats, labels = eval_src[i]
            state = eval_step(params, state, jnp.asarray(numerical),
                              [jnp.asarray(c) for c in cats],
                              jnp.asarray(labels))
        print(f"eval AUC = {metric.result(state):.5f}", flush=True)

    if args.save_weights:
        weights = model.embedding.get_weights(params["embedding"])
        out = ckpt_lib.save_global_weights(args.save_weights, weights)
        print(f"saved global embedding weights to {out}", flush=True)
    if args.checkpoint_dir:
        out = ckpt_lib.save_checkpoint(
            args.checkpoint_dir, {"params": params, "opt_state": opt_state},
            step=start_step + steps, force=True)
        print(f"saved checkpoint to {out}", flush=True)



if __name__ == "__main__":
    main()
