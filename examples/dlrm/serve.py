"""DLRM serving demo: InferenceEngine + HBM hot-row cache + micro-batcher.

Loads (or initializes) a DLRM, wraps it in the serving subsystem, and
drives a zipfian request stream — the skewed access pattern real
recommender traffic exhibits — printing throughput, cache hit rate, batch
occupancy and latency percentiles.

Examples:
  # CPU smoke run: scaled-down tables, offload forced, cache on
  python examples/dlrm/serve.py --force_cpu --table_scale 2e-4 \
      --requests 64 --batch_size 64 --cache_capacity 4096

  # serve a trained checkpoint
  python examples/dlrm/serve.py --checkpoint_dir /ckpts/dlrm --amp
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))  # repo root

import argparse
import json
import time

from main import CRITEO_TABLE_SIZES


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint_dir", default=None,
                   help="restore params saved by examples/dlrm/main.py")
    p.add_argument("--batch_size", type=int, default=4096,
                   help="padded serving batch (compile-ahead shape)")
    p.add_argument("--requests", type=int, default=256)
    p.add_argument("--flush_every", type=int, default=4,
                   help="micro-batcher flush cadence (requests)")
    p.add_argument("--zipf_alpha", type=float, default=1.2)
    p.add_argument("--cache_capacity", type=int, default=65536,
                   help="HBM hot-row cache rows per offloaded bucket "
                        "(0 = serve offloaded buckets host-side only)")
    p.add_argument("--promote_threshold", type=int, default=2)
    p.add_argument("--gpu_embedding_size", type=int, default=None,
                   help="device-memory budget; overflow buckets host-offload"
                        " (default: forced small under --force_cpu so the "
                        "cache path exercises)")
    p.add_argument("--embedding_dim", type=int, default=128)
    p.add_argument("--num_numerical", type=int, default=13)
    p.add_argument("--top_mlp", default="1024,1024,512,256,1")
    p.add_argument("--bottom_mlp", default="512,256,128")
    p.add_argument("--amp", action="store_true")
    p.add_argument("--table_scale", type=float, default=1.0)
    p.add_argument("--devices", type=int, default=0)
    p.add_argument("--force_cpu", action="store_true")
    p.add_argument("--seed", type=int, default=12345)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.force_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        n = args.devices or 8
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={n}").strip()

    import numpy as np
    import jax
    import jax.numpy as jnp

    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")

    from distributed_embeddings_tpu.models.dlrm import DLRM
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.serving import (InferenceEngine,
                                                    MicroBatcher)
    from distributed_embeddings_tpu.utils import checkpoint as ckpt_lib

    devices = jax.devices()
    if args.devices:
        devices = devices[:args.devices]
    mesh = create_mesh(devices) if len(devices) > 1 else None
    print(f"devices: {len(devices)} x {devices[0].platform}", flush=True)

    table_sizes = [max(4, int(v * args.table_scale))
                   for v in CRITEO_TABLE_SIZES]
    budget = args.gpu_embedding_size
    if budget is None and args.force_cpu:
        # force the biggest fused bucket out to host memory so the demo
        # actually exercises the cache path on a laptop-sized run
        budget = max(table_sizes) * args.embedding_dim // 2
    model = DLRM(
        table_sizes=table_sizes,
        embedding_dim=args.embedding_dim,
        bottom_mlp_dims=[int(x) for x in args.bottom_mlp.split(",")],
        top_mlp_dims=[int(x) for x in args.top_mlp.split(",")],
        num_numerical_features=args.num_numerical,
        mesh=mesh,
        compute_dtype=jnp.bfloat16 if args.amp else jnp.float32)
    # rebuild the embedding with a budget (DLRM does not expose it directly)
    if budget is not None:
        from distributed_embeddings_tpu.layers.dist_model_parallel import (
            DistributedEmbedding)
        from distributed_embeddings_tpu.layers.embedding import Embedding
        from distributed_embeddings_tpu.models.dlrm import dlrm_initializer
        model.embedding = DistributedEmbedding(
            [Embedding(v, args.embedding_dim,
                       embeddings_initializer=dlrm_initializer())
             for v in table_sizes],
            mesh=mesh, gpu_embedding_size=budget)

    params = model.init(jax.random.PRNGKey(args.seed))
    if args.checkpoint_dir:
        last = ckpt_lib.latest_step(args.checkpoint_dir)
        if last is not None:
            restored = ckpt_lib.restore_checkpoint(
                args.checkpoint_dir, {"params": params}, step=last)
            params = restored["params"]
            print(f"restored params from step {last}", flush=True)

    offloaded = [b for b, bk in enumerate(model.embedding.plan.tp_buckets)
                 if bk.offload]
    print(f"offloaded buckets: {offloaded}", flush=True)

    engine = InferenceEngine(model, params,
                             cache_capacity=args.cache_capacity,
                             promote_threshold=args.promote_threshold)
    t0 = time.perf_counter()
    engine.warmup([args.batch_size])
    print(f"compiled in {time.perf_counter() - t0:.1f}s", flush=True)
    batcher = MicroBatcher(engine, max_batch=args.batch_size)

    rng = np.random.RandomState(args.seed)

    def zipf(vocab, n):
        ranks = np.arange(1, vocab + 1, dtype=np.float64)
        p = ranks ** -args.zipf_alpha
        p /= p.sum()
        return rng.choice(vocab, size=n, p=p).astype(np.int32)

    rows = 0
    t0 = time.perf_counter()
    for i in range(args.requests):
        n = int(rng.randint(1, max(args.batch_size // 2, 2)))
        numerical = rng.rand(n, args.num_numerical).astype(np.float32)
        cats = [zipf(v, n) for v in table_sizes]
        batcher.submit((numerical, cats))
        rows += n
        if (i + 1) % args.flush_every == 0:
            batcher.flush()
    out = batcher.flush()
    if out:
        jax.tree.map(np.asarray, next(iter(out.values())))   # fetch-sync
    dt = time.perf_counter() - t0

    summary = batcher.summary()
    print(json.dumps({
        "serve_rows_per_sec": round(rows / dt),
        "serve_requests_per_sec": round(args.requests / dt, 1),
        **summary,
        "cache": engine.cache_stats(),
    }, indent=1), flush=True)


if __name__ == "__main__":
    main()
