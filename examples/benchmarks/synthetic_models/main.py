"""Synthetic model benchmark driver.

Mirror of the reference benchmark driver
(reference: examples/benchmarks/synthetic_models/main.py): picks one of the
7 model scales (tiny ... colossal), generates power-law ids, and times the
jit-compiled hybrid-parallel train step. The step-time numbers are directly
comparable to BASELINE.md's tables (same table configs, same global batch,
same optimizer).

  python examples/benchmarks/synthetic_models/main.py --model tiny \
      --batch_size 65536 --optimizer adagrad
  python examples/benchmarks/synthetic_models/main.py --model tiny \
      --force_cpu --batch_size 1024 --steps 8 --table_scale 0.01  # smoke

CPU smoke note: pass --table_scale on few-core hosts. XLA:CPU's collective
rendezvous aborts the process (F-level check, 40s budget) if any virtual
device's partition cannot reach the all_to_all in time — full-size tables
on a 1-core container starve it. Scaled tables keep per-device work far
under the budget; real TPU backends have no such limit.
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..", "..")))  # repo root

import argparse
from contextlib import nullcontext


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="tiny",
                   choices=["criteo", "tiny", "small", "medium", "large",
                            "jumbo", "colossal"])
    p.add_argument("--batch_size", type=int, default=65536)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--warmup_steps", type=int, default=4)
    p.add_argument("--optimizer", default="adagrad",
                   choices=["sgd", "adagrad", "adam"])
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--alpha", type=float, default=1.05,
                   help="power-law exponent for ids (0 = uniform)")
    p.add_argument("--num_data_batches", type=int, default=4)
    p.add_argument("--dist_strategy", default="memory_balanced")
    p.add_argument("--column_slice_threshold", type=int, default=None)
    p.add_argument("--dp_input", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="--no-dp_input benchmarks the model-parallel input "
                        "path (feature-sharded data, no id exchange)")
    p.add_argument("--amp", action="store_true")
    p.add_argument("--sparse_strategy", default="auto",
                   choices=["auto", "sort", "dense", "tiled"])
    p.add_argument("--dense_grads", action="store_true",
                   help="use dense table gradients + optax instead of the "
                        "default sparse row-wise update path")
    p.add_argument("--devices", type=int, default=0)
    p.add_argument("--force_cpu", action="store_true")
    p.add_argument("--table_scale", type=float, default=1.0,
                   help="scale vocab sizes down for small-memory smoke runs")
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.force_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        n = args.devices or 8
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={n}").strip()

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")

    from distributed_embeddings_tpu.models.synthetic import (
        SYNTHETIC_MODELS, SyntheticModel, InputGenerator)
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.training import make_train_step
    from distributed_embeddings_tpu.utils import profiling

    cfg = SYNTHETIC_MODELS[args.model]
    if args.table_scale != 1.0:
        cfg = cfg._replace(embedding_configs=[
            c._replace(num_rows=max(4, int(c.num_rows * args.table_scale)))
            for c in cfg.embedding_configs])

    devices = jax.devices()
    if args.devices:
        devices = devices[:args.devices]
    mesh = create_mesh(devices) if len(devices) > 1 else None
    print(f"model={cfg.name} devices={len(devices)} "
          f"batch={args.batch_size} opt={args.optimizer}", flush=True)

    model = SyntheticModel(
        cfg, mesh=mesh, distributed=True, strategy=args.dist_strategy,
        column_slice_threshold=args.column_slice_threshold,
        dp_input=args.dp_input,
        compute_dtype=jnp.bfloat16 if args.amp else jnp.float32)
    params = model.init(jax.random.PRNGKey(args.seed))

    def to_model_inputs(cats):
        if args.dp_input:
            return cats
        # feature-sharded (mp) input: nested per-rank lists in
        # strategy.input_ids_list order
        strat = model.embedding.strategy
        return [[cats[strat.input_groups[1][pos]] for pos in rank_ids]
                for rank_ids in strat.input_ids_list]

    use_sparse = args.dp_input and not args.dense_grads
    if use_sparse:
        # production path: row-wise sparse embedding updates (no dense
        # [V, w] grads, no full-table optimizer pass)
        from distributed_embeddings_tpu.training import make_sparse_train_step
        init_fn, step_fn = make_sparse_train_step(
            model, args.optimizer, lr=args.lr, donate=False,
            strategy=args.sparse_strategy)
        opt_state = init_fn(params)
    else:
        opt = {"sgd": optax.sgd, "adagrad": optax.adagrad,
               "adam": optax.adam}[args.optimizer](args.lr)
        opt_state = opt.init(params)
        step_fn = make_train_step(model.loss_fn, opt, donate=False)

    gen = InputGenerator(cfg, args.batch_size, alpha=args.alpha,
                         num_batches=args.num_data_batches, seed=args.seed)

    batches = [(params, opt_state, gen[i][0], to_model_inputs(gen[i][1]),
                gen[i][2]) for i in range(len(gen))]

    ctx = mesh if mesh is not None else nullcontext()
    with ctx:
        res = profiling.benchmark_batches(step_fn, batches, iters=args.steps,
                                          warmup=args.warmup_steps)
    print(f"step time: {res}", flush=True)
    print(f"throughput: {args.batch_size / res.mean_s:,.0f} samples/sec",
          flush=True)



if __name__ == "__main__":
    main()
