"""Fused-lookup micro-benchmark.

Mirror of the reference lookup micro-benchmark
(reference: examples/benchmarks/benchmark.py: ragged multi-hot lookup
fwd/bwd/SGD, vocab=1M, width=128, batch=16384, hotness<=500, custom kernel
vs tf.nn.embedding_lookup_sparse). Here the comparison is the Pallas fused
kernel vs the XLA-native gather+einsum path.

  python examples/benchmarks/benchmark.py                  # TPU defaults
  python examples/benchmarks/benchmark.py --vocab 10000 \
      --batch 512 --hotness 16 --steps 5 --interpret       # CPU smoke
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))  # repo root

import argparse


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--vocab", type=int, default=1000_000)
    p.add_argument("--width", type=int, default=128)
    p.add_argument("--batch", type=int, default=16384)
    p.add_argument("--hotness", type=int, default=64)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--interpret", action="store_true",
                   help="run Pallas in interpreter mode (CPU testing)")
    p.add_argument("--force_cpu", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    import numpy as np
    import jax

    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from distributed_embeddings_tpu.ops import pallas_lookup
    from distributed_embeddings_tpu.utils import profiling

    rng = np.random.RandomState(args.seed)
    table = jnp.asarray(
        rng.randn(args.vocab, args.width).astype(np.float32) * 0.01)
    ids = jnp.asarray(rng.randint(
        0, args.vocab, (args.batch, args.hotness)).astype(np.int32))
    weights = jnp.asarray(
        (rng.rand(args.batch, args.hotness) > 0.3).astype(np.float32))

    interpret = True if args.interpret else None

    def sgd_fused(t):
        def loss(tt):
            return jnp.sum(pallas_lookup.fused_embedding_lookup(
                tt, ids, weights, interpret=interpret) ** 2)
        return t - args.lr * jax.grad(loss)(t)

    def sgd_xla(t):
        def loss(tt):
            embs = jnp.take(tt, ids, axis=0)
            return jnp.sum(jnp.einsum("bk,bkw->bw", weights, embs) ** 2)
        return t - args.lr * jax.grad(loss)(t)

    print(f"vocab={args.vocab} width={args.width} batch={args.batch} "
          f"hotness={args.hotness} backend={jax.default_backend()}",
          flush=True)

    # steady-state: chained single-program timing (per-call timing is
    # distorted by dispatch latency on remote-attached chips)
    def chain_fwd(fn):
        def step(t):
            out = fn(t)
            return t + out[0, 0].astype(t.dtype) * 1e-20
        return step

    for name, step in [
            ("fwd fused", chain_fwd(lambda t: pallas_lookup
                                    .fused_embedding_lookup(
                                        t, ids, weights,
                                        interpret=interpret))),
            ("fwd xla", chain_fwd(lambda t: jnp.einsum(
                "bk,bkw->bw", weights, jnp.take(t, ids, axis=0)))),
            ("fwd+bwd+sgd fused", sgd_fused),
            ("fwd+bwd+sgd xla", sgd_xla)]:
        res = profiling.benchmark_chained(step, table, iters=args.steps)
        print(f"{name:>20s}: {res.mean_ms:8.3f} ms "
              f"({args.batch / res.mean_s:,.0f} samples/sec)", flush=True)


if __name__ == "__main__":
    main()
