"""Criteo-Kaggle end-to-end with on-the-fly vocabulary (IntegerLookup).

Mirror of the reference Criteo example (reference: examples/criteo/main.py):
raw categorical keys -> IntegerLookup (vocabulary built on the fly during
training) -> Embedding(vocab, 128, combiner-less) -> MLP -> logit.

The TPU-native shape of this pipeline: IntegerLookup runs on the TPU-VM host
as a data transform (C++ open-addressing hash via ctypes — the reference's
cuCollections device hash has no TPU analogue), the jit-compiled device step
sees only dense contiguous indices.

  python examples/criteo/main.py --csv train.txt --steps 200
  python examples/criteo/main.py --synthetic --steps 50 --force_cpu
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))  # repo root

import argparse
import csv
import itertools
import time


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--csv", default=None,
                   help="Criteo Kaggle train.txt (tab-separated)")
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--bench_lookup", action="store_true",
                   help="microbenchmark native vs numpy IntegerLookup")
    p.add_argument("--bench_lookup_keys", type=int, default=1 << 20,
                   help="total keys for --bench_lookup; use >=10M (with "
                        "--max_tokens sized above the expected uniques) "
                        "for reference-like scale (docs/parity.md)")
    p.add_argument("--bench_lookup_batch", type=int, default=65536,
                   help="keys per lookup call in --bench_lookup (input-"
                        "pipeline batch granularity)")
    p.add_argument("--batch_size", type=int, default=4096)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--max_tokens", type=int, default=100000,
                   help="IntegerLookup capacity per feature (reference :75)")
    p.add_argument("--embedding_dim", type=int, default=128)
    p.add_argument("--mlp", default="512,256,1")
    p.add_argument("--num_categorical", type=int, default=26)
    p.add_argument("--num_numerical", type=int, default=13)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--serial_ingest", action="store_true",
                   help="run the IntegerLookup hash + staging inline in "
                        "the consumer thread instead of the background "
                        "ingestion pipeline (A/B baseline)")
    p.add_argument("--pipeline_depth", type=int, default=2)
    p.add_argument("--force_cpu", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


def csv_batches(path, batch_size, n_num, n_cat):
    """Yield (numerical, raw_keys, labels) from the Kaggle TSV format:
    label \t 13 ints \t 26 hex strings."""
    import numpy as np
    with open(path) as f:
        reader = csv.reader(f, delimiter="\t")
        while True:
            rows = list(itertools.islice(reader, batch_size))
            if len(rows) < batch_size:
                return
            labels = np.array([[float(r[0])] for r in rows], np.float32)
            numerical = np.array(
                [[float(x) if x else 0.0 for x in r[1:1 + n_num]]
                 for r in rows], np.float32)
            raw = np.array(
                [[int(x, 16) if x else -1 for x in r[1 + n_num:1 + n_num + n_cat]]
                 for r in rows], np.int64)
            yield numerical, raw, labels


def synthetic_batches(batch_size, n_num, n_cat, seed):
    import numpy as np
    rng = np.random.RandomState(seed)
    while True:
        numerical = rng.rand(batch_size, n_num).astype(np.float32)
        # raw keys from a large sparse space (hex-hash-like)
        raw = rng.zipf(1.3, size=(batch_size, n_cat)).astype(np.int64) * 2654435761
        labels = rng.randint(0, 2, (batch_size, 1)).astype(np.float32)
        yield numerical, raw, labels


def main(argv=None):
    args = parse_args(argv)
    if args.force_cpu:
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=1")

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")

    from distributed_embeddings_tpu.layers.embedding import (Embedding,
                                                             IntegerLookup)
    from distributed_embeddings_tpu.models.dlrm import _mlp_init, _mlp_apply

    n_cat, n_num = args.num_categorical, args.num_numerical
    if args.bench_lookup:
        # IntegerLookup microbenchmark: native C++ hash vs numpy fallback,
        # duplicate-heavy power-law keys (the realistic regime — the batch
        # pre-unique makes per-unique hash cost the denominator). At
        # --bench_lookup_keys >= 10M this is the reference-like-scale
        # measurement docs/parity.md records: the host hash is the
        # ingestion bound of the raw-keys pipeline (the reference's
        # cuCollections map is a GPU kernel, .cu:383-516 — TPUs have no
        # device hash, so the TPU-VM host rate IS the number that matters).
        import json as _json
        rng = np.random.RandomState(0)
        bsz = args.bench_lookup_batch
        nb = max(2, -(-args.bench_lookup_keys // bsz))
        keys = (rng.zipf(1.2, size=(nb, bsz)) * 2654435761 % (1 << 40)
                ).astype(np.int64)
        bench_rec = {"total_keys": int((nb - 1) * bsz), "batch": bsz,
                     "max_tokens": args.max_tokens, "zipf_alpha": 1.2,
                     "unique_keys": int(np.unique(keys[1:]).size)}
        # the numpy fallback loops Python dict inserts per unique key —
        # orders of magnitude slower; bound its arm so a >=10M-key native
        # run doesn't stall behind it (rates, not totals, are compared)
        numpy_nb = min(nb, max(2, (1 << 21) // bsz))
        for use_native, label, arm_nb in ((True, "native", nb),
                                          (False, "numpy", numpy_nb)):
            lk = IntegerLookup(args.max_tokens, use_native=use_native)
            if use_native and not lk.native:
                print("IntegerLookup[native]: backend unavailable, skipped",
                      flush=True)
                continue
            lk(keys[0])  # warm
            t0 = time.perf_counter()
            for i in range(1, arm_nb):
                lk(keys[i])
            dt = time.perf_counter() - t0
            rate = (arm_nb - 1) * bsz / dt
            bench_rec[f"{label}_keys_per_sec"] = round(rate)
            bench_rec[f"{label}_measured_keys"] = int((arm_nb - 1) * bsz)
            bench_rec[f"{label}_vocab_after"] = int(lk.size)
            # ingestion bound: one hashed key per categorical feature per
            # sample (26 one-hot features in the Criteo layout)
            bench_rec[f"{label}_samples_per_sec_bound"] = round(
                rate / args.num_categorical)
            print(f"IntegerLookup[{label}]: {rate:,.0f} keys/sec over "
                  f"{(arm_nb - 1) * bsz:,} keys (vocab {lk.size}; implies "
                  f"<= {rate / args.num_categorical:,.0f} samples/sec at "
                  f"{args.num_categorical} cat features)", flush=True)
        print(_json.dumps({"bench_lookup": bench_rec}), flush=True)
        return

    lookups = [IntegerLookup(args.max_tokens) for _ in range(n_cat)]
    print(f"IntegerLookup backend: "
          f"{'native C++' if lookups[0].native else 'numpy (SLOW fallback)'}",
          flush=True)
    tables = [Embedding(args.max_tokens + 1, args.embedding_dim)
              for _ in range(n_cat)]

    key = jax.random.PRNGKey(args.seed)
    keys = jax.random.split(key, n_cat + 1)
    params = {
        "tables": [t.init(k) for t, k in zip(tables, keys[:-1])],
        "mlp": _mlp_init(keys[-1], [int(x) for x in args.mlp.split(",")],
                         n_num + n_cat * args.embedding_dim),
    }

    def loss_fn(p, numerical, idx, labels):
        embs = [tables[i](p["tables"][i], idx[:, i]) for i in range(n_cat)]
        x = jnp.concatenate([numerical] + embs, axis=1)
        logits = _mlp_apply(p["mlp"], x)[:, 0]
        y = labels.reshape(-1)
        return jnp.mean(jnp.maximum(logits, 0) - logits * y
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    opt = optax.adam(args.lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, s, numerical, idx, labels):
        loss, g = jax.value_and_grad(loss_fn)(p, numerical, idx, labels)
        updates, s = opt.update(g, s, p)
        return jax.tree.map(lambda a, b: a + b, p, updates), s, loss


    if args.csv:
        batches = csv_batches(args.csv, args.batch_size, n_num, n_cat)
    else:
        batches = synthetic_batches(args.batch_size, n_num, n_cat, args.seed)

    # ingestion pipeline: the IntegerLookup hash (the measured host bound,
    # docs/parity.md) and the device staging run in background workers so
    # they overlap the train step; --serial_ingest keeps them inline (the
    # old behavior, identical batch order)
    from distributed_embeddings_tpu.utils.pipeline import (IngestPipeline,
                                                           SerialPipeline)

    def lookup_batch(batch):
        # host-side vocab build + translation, fused into one pass: per-
        # feature hash translate + int32 cast into the preallocated
        # feature-stacked index matrix
        numerical, raw, labels = batch
        idx = np.empty((raw.shape[0], n_cat), np.int32)
        for j in range(n_cat):
            idx[:, j] = lookups[j](raw[:, j])
        return numerical, idx, labels

    source = itertools.islice(batches, args.steps)
    # staging = plain device_put (single-device example; the pipeline's
    # default stage semantics, utils/pipeline.staged_batches)
    stages = [("lookup", lookup_batch), ("stage", jax.device_put)]
    if args.serial_ingest:
        pipe = SerialPipeline(source, stages)
    else:
        pipe = IngestPipeline(source, stages, depth=args.pipeline_depth)

    t0 = time.perf_counter()
    for i, (numerical, idx, labels) in enumerate(pipe):
        params, opt_state, loss = step(params, opt_state, numerical, idx,
                                       labels)
        if i % 20 == 0:
            vocab = sum(l.size for l in lookups)
            print(f"step {i}: loss={float(loss):.5f} "
                  f"vocab={vocab} keys", flush=True)
    dt = time.perf_counter() - t0
    pipe.close()
    stage_ms = {k: v["mean_ms"] for k, v in pipe.stage_summaries().items()}
    print(f"done: {args.steps} steps in {dt:.1f}s "
          f"({args.steps * args.batch_size / dt:,.0f} samples/sec); "
          f"ingest stages mean ms: {stage_ms}; "
          f"final vocab sizes: {[l.size for l in lookups[:4]]}...", flush=True)


if __name__ == "__main__":
    main()
