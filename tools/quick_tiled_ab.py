"""Minimal tiled-vs-default A/B for ultra-short tunnel windows.

The full bench.py run (tiny + DLRM + all arms) needs a ~30+ minute window;
round 3's only window was ~35 minutes and round 4 got none. This stage
answers the ONE round-5 question — do the tiled one-hot-matmul kernels
beat the XLA path at the tiny benchmark shape (docs/perf_model.md decision
rule 5) — in the fewest minutes that can produce an honest number:
one batch-65536 tiny config, default arm then tiled arms, slope-timed with
the fetch-sync methodology, one JSON line to stdout.

Runs FIRST in tools/r05_stages.txt; bench.py still follows for the full
record when the window lasts.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    dev = jax.devices()[0]
    out = {"device": f"{dev.platform}:{getattr(dev, 'device_kind', '?')}",
           "started": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    if dev.platform == "cpu" and os.environ.get(
            "DET_QUICKAB_ALLOW_CPU") != "1":
        # DET_QUICKAB_ALLOW_CPU=1: the unattended-window rehearsal
        # (tools/window_rehearsal.py) runs this stage on CPU with shrunken
        # shapes (DET_QUICKAB_BATCH/ITERS) to validate the plumbing
        out["verdict"] = "SKIP cpu backend"
        print(json.dumps(out), flush=True)
        return

    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "det_bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench._isolate_from_measured_defaults()

    from distributed_embeddings_tpu.models.synthetic import (SYNTHETIC_MODELS,
                                                             SyntheticModel)
    from distributed_embeddings_tpu.ops import sparse_update

    cfg = SYNTHETIC_MODELS["tiny"]
    batch = int(os.environ.get("DET_QUICKAB_BATCH", 65536))
    iters = int(os.environ.get("DET_QUICKAB_ITERS", 8))
    out["git_sha"] = bench._git_sha()
    t0 = time.perf_counter()
    try:
        dt = bench.run_at_batch(SyntheticModel(cfg, mesh=None,
                                               distributed=True),
                                batch, iters=iters)
        out["tiny_default_ms"] = round(dt * 1e3, 3)
        out["tiny_default_raw"] = getattr(bench.run_at_batch, "last_raw",
                                          None)
    except Exception as e:  # noqa: BLE001
        out["tiny_default_error"] = str(e)[:300]
        dt = None
    out["default_wall_s"] = round(time.perf_counter() - t0, 1)
    print(json.dumps(out), flush=True)      # partial evidence ASAP

    for key, env, validate in (
            ("tiny_ab_tiled", {"DET_SCATTER_IMPL": "tiled"},
             sparse_update.prevalidate_tiled),
            ("tiny_ab_tiled_full",
             {"DET_SCATTER_IMPL": "tiled", "DET_LOOKUP_PATH": "tiled"},
             sparse_update.prevalidate_tiled)):
        t0 = time.perf_counter()
        bench.run_ab_arm(out, key, env, cfg, batch, iters,
                         validate=validate)
        out[f"{key}_wall_s"] = round(time.perf_counter() - t0, 1)
        print(json.dumps(out), flush=True)  # refresh after every arm

    if dt is not None and out.get("tiny_ab_tiled_ms"):
        out["tiled_speedup"] = round(out["tiny_default_ms"]
                                     / out["tiny_ab_tiled_ms"], 2)
    out["finished"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
