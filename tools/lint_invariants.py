"""Python-side AST lint for repo invariants the HLO auditor can only see
AFTER lowering (ISSUE 10) — run in CI next to ruff, so a seam escape is
flagged at the source line that writes it, before it ever compiles.

Rules (each exercised by a fixture test in
tests/test_lint_invariants.py):

  naked-collective   no ``jax.lax.{all_to_all, psum_scatter, all_gather,
                     ppermute, ragged_all_to_all}`` call outside
                     ``ops/wire.py`` — every
                     exchange collective lives behind the wire seam, the
                     static source-side twin of the wire-seam HLO pass.
  hot-params-access  no ``params["hot"]`` subscript outside
                     ``layers/dist_model_parallel.py`` /
                     ``ops/sparse_update.py`` — the replicated hot shard
                     has exactly two owners (the forward split and the
                     dense hot update); anything else touching it
                     bypasses the sync_hot_rows consistency seam.
  wallclock-in-jit   no ``time.time()`` / ``datetime.now()`` in
                     jitted-code modules (ops/, layers/, parallel/,
                     schedule/) — a wall clock read inside a traced
                     function freezes ONE timestamp into the compiled
                     program; host-side timing belongs in utils/, obs/
                     or the drivers.
  shadow-metric      no direct ``LatencyHistogram`` / ``Counter`` /
                     ``Gauge`` construction (the obs.registry metric
                     classes) outside ``obs/`` — ONE metric namespace
                     (ISSUE 11): components obtain instruments through
                     a `MetricRegistry` (``registry.histogram(...)``),
                     never by hand-rolling a private histogram the
                     snapshot/SLO layer cannot see. Import-tracked, so
                     ``from ...utils.metrics import LatencyHistogram``
                     aliases and module-attribute forms cannot evade —
                     and ``collections.Counter`` stays untouched (only
                     names imported from the metric modules count).

  scenario-knobs     every ``"knobs"`` override in a checked-in
                     ``tools/soak_scenarios/*.json`` scenario names a
                     tune-registry knob with a legal value (ISSUE 18) —
                     the same validation ``bench.load_soak_scenario``
                     enforces at load, moved up to CI so a typo'd env
                     var or out-of-domain value is flagged at review,
                     not on the soak host. JSON rule: runs whenever the
                     default file set is linted (no per-line escape —
                     fix the scenario).

Escapes: append ``# lint: allow(<rule>)`` to the offending line (or the
line directly above). Escapes are themselves greppable, which is the
point — an allowed violation is a reviewed decision, not an accident.

Usage:
  python tools/lint_invariants.py            # lint the package, exit 1
                                             # on findings
  python tools/lint_invariants.py --json     # machine-readable findings
  python tools/lint_invariants.py PATH...    # lint specific files
"""

import argparse
import ast
import json
import os
import re
import sys
from typing import List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "distributed_embeddings_tpu"

COLLECTIVES = ("all_to_all", "psum_scatter", "all_gather", "ppermute",
               "ragged_all_to_all")
COLLECTIVE_ALLOWED = (os.path.join("ops", "wire.py"),)
HOT_ALLOWED = (os.path.join("layers", "dist_model_parallel.py"),
               os.path.join("ops", "sparse_update.py"))
# modules whose code runs under jit traces: a wall-clock call here is
# either traced (frozen constant) or a host sync hazard
JIT_MODULE_DIRS = ("ops", "layers", "parallel", "schedule")

# obs.registry metric classes: construction belongs to the registry
# (obs/ is the whole allowed subtree — registry.py constructs, spans.py
# and instrument.py are the instrumentation home)
METRIC_CLASSES = ("LatencyHistogram", "Counter", "Gauge")
METRIC_MODULES = (
    "distributed_embeddings_tpu.obs.registry",
    "distributed_embeddings_tpu.obs",
    "distributed_embeddings_tpu.utils.metrics",   # the re-export
)
METRIC_ALLOWED_DIR = "obs"

_ALLOW_RE = re.compile(
    r'#.*?lint:\s*allow\(([\w-]+(?:\s*,\s*[\w-]+)*)\)')


class Finding:
    def __init__(self, rule: str, path: str, line: int, message: str):
        self.rule, self.path, self.line, self.message = \
            rule, path, line, message

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _allowed_rules(src_lines: List[str], lineno: int) -> set:
    """Rules escaped at `lineno` (1-based): an allow comment on the line
    itself or on the line directly above."""
    out = set()
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(src_lines):
            m = _ALLOW_RE.search(src_lines[ln - 1])
            if m:
                out.update(r.strip() for r in m.group(1).split(","))
    return out


def _attr_chain(node: ast.AST) -> Optional[str]:
    """'jax.lax.all_to_all' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _rel(path: str) -> str:
    return os.path.relpath(path, REPO_ROOT)


def lint_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    """Lint one file. ``rel`` overrides the repo-relative path the
    path-scoped rules key on (fixture tests lint tmp files AS IF they
    lived at a package path)."""
    if rel is None:
        rel = _rel(path)
    in_package = rel.startswith(PACKAGE + os.sep)
    pkg_rel = rel[len(PACKAGE) + 1:] if in_package else rel
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("parse-error", rel, e.lineno or 0, str(e))]
    lines = src.splitlines()
    findings: List[Finding] = []

    def emit(rule: str, node: ast.AST, message: str):
        if rule not in _allowed_rules(lines, node.lineno):
            findings.append(Finding(rule, rel, node.lineno, message))

    check_collectives = pkg_rel not in COLLECTIVE_ALLOWED
    check_hot = pkg_rel not in HOT_ALLOWED
    check_clock = in_package and pkg_rel.split(os.sep)[0] in \
        JIT_MODULE_DIRS
    check_metric = pkg_rel.split(os.sep)[0] != METRIC_ALLOWED_DIR

    # ---- import tracking, so from-imports and aliases cannot evade the
    # rules: `from jax.lax import all_to_all`, `import jax.lax as jl`,
    # `from time import time`, `from datetime import datetime as dt`
    lax_names = {}        # local name -> collective leaf name
    lax_modules = {"lax", "jax.lax"}   # names that mean the lax module
    clock_names = {}      # local name -> canonical 'time.time' chain
    clock_modules = {}    # local module alias -> 'time' | 'datetime'
    metric_names = {}     # local name -> metric class name
    metric_modules = set()  # local aliases that mean a metric module
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module in METRIC_MODULES:
                for a in node.names:
                    if a.name in METRIC_CLASSES:
                        metric_names[a.asname or a.name] = a.name
                    elif a.name in ("registry", "metrics"):
                        metric_modules.add(a.asname or a.name)
            elif node.module in ("distributed_embeddings_tpu.utils",
                                 "distributed_embeddings_tpu.obs"):
                for a in node.names:
                    if a.name in ("metrics", "registry"):
                        metric_modules.add(a.asname or a.name)
            elif node.module == "distributed_embeddings_tpu":
                for a in node.names:
                    if a.name == "obs":
                        metric_modules.add(a.asname or "obs")
            if node.module == "jax.lax":
                for a in node.names:
                    if a.name in COLLECTIVES:
                        lax_names[a.asname or a.name] = a.name
            elif node.module == "jax":
                for a in node.names:
                    if a.name == "lax":
                        lax_modules.add(a.asname or "lax")
            elif node.module == "time":
                for a in node.names:
                    if a.name == "time":
                        clock_names[a.asname or "time"] = "time.time"
            elif node.module == "datetime":
                for a in node.names:
                    if a.name == "datetime":
                        clock_modules[a.asname or "datetime"] = \
                            "datetime"
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.lax" and a.asname:
                    lax_modules.add(a.asname)
                elif a.name in ("time", "datetime"):
                    clock_modules[a.asname or a.name] = a.name
                elif a.name in METRIC_MODULES:
                    # `import ...obs.registry as r` -> r.Counter(...);
                    # unaliased deep imports resolve through the chain's
                    # last segment below
                    metric_modules.add(a.asname or a.name.rsplit(
                        ".", 1)[-1])

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain is None:
                continue
            leaf = chain.rsplit(".", 1)[-1]
            base = chain.rsplit(".", 1)[0] if "." in chain else ""
            naked = (leaf in COLLECTIVES
                     and (base.split(".")[-1] in lax_modules
                          or base in lax_modules)) or \
                (chain in lax_names)
            if check_collectives and naked:
                emit("naked-collective", node,
                     f"{chain}(...) outside ops/wire.py — route the "
                     "exchange through the wire seam "
                     "(wire_all_to_all / wire_id_all_to_all / "
                     "wire_all_gather / wire_psum_scatter)")
            clock = chain in clock_names or (
                "." in chain
                and clock_modules.get(chain.split(".")[0]) is not None
                and (chain.endswith(".time")
                     if clock_modules.get(chain.split(".")[0]) == "time"
                     else chain.endswith(".now")))
            if check_clock and clock:
                emit("wallclock-in-jit", node,
                     f"{chain}() in a jitted-code module — a traced "
                     "wall-clock read freezes one timestamp into the "
                     "compiled program; time at the driver layer")
            shadow = (chain in metric_names) or (
                leaf in METRIC_CLASSES and base
                and base.split(".")[-1] in metric_modules)
            if check_metric and shadow:
                emit("shadow-metric", node,
                     f"{chain}(...) outside obs/ — metric instruments "
                     "come from a MetricRegistry "
                     "(registry.histogram/counter/gauge), one namespace "
                     "the snapshot/SLO layer can see; no shadow "
                     "accounting")
        elif isinstance(node, ast.Subscript) and check_hot:
            sl = node.slice
            if isinstance(sl, ast.Constant) and sl.value == "hot":
                emit("hot-params-access", node,
                     '["hot"] subscript outside dist_model_parallel/'
                     "sparse_update — the replicated hot shard's only "
                     "owners; go through sync_hot_rows/get_weights")
    return findings


def lint_scenario_knobs(scenario_dir: Optional[str] = None
                        ) -> List[Finding]:
    """Validate every scenario file's ``"knobs"`` overrides against the
    tune registry (ISSUE 18). A scenario naming an unknown env var or an
    out-of-domain value would refuse at `bench.load_soak_scenario` —
    this rule surfaces it in CI instead. An unparsable scenario file is
    itself a finding (the soak host would hit the same wall)."""
    if scenario_dir is None:
        scenario_dir = os.path.join(REPO_ROOT, "tools", "soak_scenarios")
    sys.path.insert(0, REPO_ROOT)
    from distributed_embeddings_tpu.tune import registry as tune_registry
    findings: List[Finding] = []
    if not os.path.isdir(scenario_dir):
        return findings
    for name in sorted(os.listdir(scenario_dir)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(scenario_dir, name)
        rel = _rel(path)
        try:
            with open(path) as f:
                doc = json.load(f)
        except ValueError as e:
            findings.append(Finding("scenario-knobs", rel, 1,
                                    f"unparsable scenario JSON: {e}"))
            continue
        knobs = doc.get("knobs")
        if knobs is None:
            continue
        if not isinstance(knobs, dict):
            findings.append(Finding(
                "scenario-knobs", rel, 1,
                "'knobs' must be an env -> value object"))
            continue
        for env, value in knobs.items():
            err = tune_registry.validate_override(env, value)
            if err is not None:
                findings.append(Finding("scenario-knobs", rel, 1, err))
    return findings


def default_files() -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(REPO_ROOT, PACKAGE)):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out.extend(os.path.join(dirpath, f) for f in filenames
                   if f.endswith(".py"))
    return sorted(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="*",
                   help="files to lint (default: the package)")
    p.add_argument("--json", action="store_true",
                   help="print findings as one JSON document")
    args = p.parse_args(argv)
    files = args.paths or default_files()
    findings: List[Finding] = []
    for path in files:
        findings.extend(lint_file(path))
    if not args.paths:
        # the JSON scenario rule rides the default sweep (explicit
        # paths mean "lint exactly these python files")
        findings.extend(lint_scenario_knobs())
    if args.json:
        print(json.dumps([f.to_dict() for f in findings], indent=1))
    else:
        for f in findings:
            print(f)
        print(f"lint_invariants: {len(findings)} finding(s) over "
              f"{len(files)} file(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
