#!/bin/bash
# TPU claim watcher (round 5).
# Round-5 mandate (VERDICT r4 item 1): get on the chip and MEASURE the tiled
# kernels — one fresh, honest hardware bench of HEAD. On tunnel recovery this
# runs the stages in tools/r05_stages.txt (cheapest first, one killable
# subprocess each) so the stage list can evolve mid-round without restarting
# the watcher.
# Logs: tools/claim_watch_r05.log   Sentinel: /tmp/tpu_alive_r05
set -u
LOG=/root/repo/tools/claim_watch_r05.log
BUSY=/tmp/det_tpu_busy
STAGES=/root/repo/tools/r05_stages.txt
# hard deadline: stay clear of the driver's round-end bench (round ends
# ~08:45 Aug 1; stop probing at 07:30 so the chip claim is free)
DEADLINE_EPOCH=${DET_WATCH_DEADLINE:-$(date -d "2026-08-01 07:30 UTC" +%s)}
cd /root/repo
export JAX_COMPILATION_CACHE_DIR=/tmp/jax_cache_det_tpu
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1
echo "$(date +%H:%M:%S) r05 watcher start" >> "$LOG"
n=0
while true; do
  if [ "$(date +%s)" -ge "$DEADLINE_EPOCH" ]; then
    echo "$(date +%H:%M:%S) deadline reached; watcher exits" >> "$LOG"
    rm -f "$BUSY"
    exit 0
  fi
  n=$((n+1))
  # must see a real accelerator (JAX can silently fall back to CPU).
  # -k: a wedged axon client can ignore SIGTERM indefinitely (observed
  # 2026-07-31: one probe blocked the loop for 2h) — follow up with KILL
  if timeout -k 15 90 python -c "
import jax
d = jax.devices()
print(d)
assert d and d[0].platform != 'cpu', f'cpu fallback: {d}'
import jax.numpy as jnp
print('fetch', float(jnp.sum(jnp.ones((128, 128)) @ jnp.ones((128, 128)))))
" >> "$LOG" 2>&1; then
    echo "$(date +%H:%M:%S) probe $n SUCCESS — tunnel alive" >> "$LOG"
    touch /tmp/tpu_alive_r05
    bench_rc=1
    echo $$ > "$BUSY"
    trap 'rm -f "$BUSY"' EXIT
    while IFS=: read -r cmd secs name; do
      [ -z "${cmd:-}" ] && continue
      case "$cmd" in \#*) continue ;; esac
      if [ "$(date +%s)" -ge "$DEADLINE_EPOCH" ]; then
        echo "$(date +%H:%M:%S) deadline mid-stages; stopping" >> "$LOG"
        break
      fi
      echo "$(date +%H:%M:%S) running $name" >> "$LOG"
      # shellcheck disable=SC2086
      DET_BENCH_SKIP_BUSY_WAIT=1 timeout -k 30 "$secs" python -u $cmd \
        > "tools/watch_${name}_r05.out" 2>&1
      rc=$?
      echo "$(date +%H:%M:%S) $name rc=$rc" >> "$LOG"
      [ "$name" = bench ] && bench_rc=$rc
      sleep 20
    done < "$STAGES"
    rm -f "$BUSY"
    git add -- tools/watch_*_r05.out tools/bench_last_tpu.json \
        tools/measured_defaults.json \
        tools/claim_watch_r05.log 2>/dev/null || true
    git commit -q -m "Hardware window artifacts (r05 claim watcher)" \
        -- tools/watch_*_r05.out tools/bench_last_tpu.json \
        tools/measured_defaults.json \
        tools/claim_watch_r05.log 2>/dev/null || true
    if [ "$bench_rc" -eq 0 ] \
       && grep -q '"metric"' tools/watch_bench_r05.out \
       && ! grep -q '"cached": true' tools/watch_bench_r05.out; then
      touch /tmp/tpu_measured_r05
      echo "$(date +%H:%M:%S) fresh bench landed; continuing watch for reruns" >> "$LOG"
    fi
    echo "$(date +%H:%M:%S) stages done; resuming watch" >> "$LOG"
  else
    echo "$(date +%H:%M:%S) probe $n failed" >> "$LOG"
  fi
  sleep 240
done
