"""Tier-1 observability smoke (ISSUE 11/14): one registry across a real
fit -> publish -> serve loop, schema-checked, SLO-gated, device-time
attributed.

What it drives (tiny shapes, CPU, ~a minute):

  1. `training.fit` with the lookahead engine AND a publishing
     `TableStore`, all reporting into ONE `obs.MetricRegistry` — train
     spans/counters, ingest stage histograms, lookahead patch/compile
     metrics, store publish counters land in the same namespace. The
     fit runs under a REAL jax profiler capture (CPU backend), so the
     attribution parser below works on genuine profiler output.
  2. An `InferenceEngine` replica consuming the published stream
     (`poll_updates`) and serving requests through a `MicroBatcher` on
     the SAME registry — apply/staleness/latency metrics join the
     snapshot.
  3. The static audit matrix (tools/hlo_audit.py), its finding count
     exported as the ``audit/findings`` gauge.
  4. Snapshot SCHEMA assertions (the keys the soak harness will script
     against), a JSONL export/parse round trip, a Prometheus dump
     sanity check, and the checked-in SLO rule file
     (tools/slo_tier1.json) evaluated over the snapshot — compile-count
     and audit-findings rules active, NO perf rules (CI hosts are
     steal-noisy; perf gates live in docs/perf_model.md).
  5. Device-time attribution (ISSUE 14): the fit's profiler capture is
     parsed by `obs.attribution`, asserting NONZERO span coverage
     (device ops attributed to the span annotations PR 11 opened), the
     attribution-record schema (spans + unattributed == total), and
     the exported ``device/*`` gauges in the snapshot.
  6. Flight-recorder checks: the ring holds the run's spans, the
     chrome-trace export loads and balances, and the lineage tracks
     cover every published version.
  7. Metric-catalog drift gate: every metric FAMILY this driven run
     observes in the snapshot must appear in docs/observability.md's
     catalog — a new metric can no longer ship undocumented.

Exit 1 on any schema violation or SLO finding. Run:

    env JAX_PLATFORMS=cpu python tools/obs_smoke.py
"""

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the CPU suite's donation posture (see training.default_donate):
# donated executables + the persistent cache are not trustworthy on
# jaxlib 0.4.36 XLA:CPU
os.environ.setdefault("DET_STEP_DONATE", "0")

from distributed_embeddings_tpu.analysis import programs as _programs  # noqa: E402

# meshed lowerings need the virtual world BEFORE the backend wakes
WORLD = _programs.ensure_world(8)

import numpy as np  # noqa: E402
import jax  # noqa: E402

from distributed_embeddings_tpu import obs, training  # noqa: E402
from distributed_embeddings_tpu.serving import (InferenceEngine,  # noqa: E402
                                                MicroBatcher)
from distributed_embeddings_tpu.store import TableStore  # noqa: E402

VOCAB, WIDTH, TABLES, HOTNESS = 2000, 16, 4, 2
BATCH, STEPS, PUBLISH_EVERY = 256, 8, 4
REQUESTS = 6


def make_batches(rng, n):
    out = []
    for _ in range(n):
        num = np.zeros((BATCH, 1), np.float32)
        cats = [rng.randint(0, VOCAB, size=(BATCH, HOTNESS))
                .astype(np.int32) for _ in range(TABLES)]
        lab = rng.randn(BATCH).astype(np.float32)
        out.append((num, cats, lab))
    return out


def check(cond, msg):
    if not cond:
        print(f"obs smoke FAIL: {msg}", file=sys.stderr)
        sys.exit(1)


def main() -> int:
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.utils import profiling
    mesh = create_mesh(jax.devices()[:WORLD])
    rng = np.random.RandomState(0)
    reg = obs.default_registry()
    obs.reset_default_recorder()      # this run's ring only (check 6)
    tmp = tempfile.mkdtemp(prefix="det_obs_smoke_")
    profile_dir = os.path.join(tmp, "profile")
    try:
        # ---- 1. publisher fit: lookahead engine + weight streaming --
        # under a REAL profiler capture (CPU): the attribution check
        # below must parse genuine jax profiler output, not a fixture
        model = _programs.build_model(VOCAB, WIDTH, "sum", tables=TABLES,
                                      mesh=mesh)
        params = {"embedding": model.embedding.init(jax.random.PRNGKey(0))}
        store = TableStore(model.embedding, params["embedding"])
        # python tracer off: per-call python events would overflow the
        # host buffer and drop late span annotations (profiling.trace)
        with profiling.trace(profile_dir, python_tracer_level=0):
            params, opt_state, history = training.fit(
                model, params, make_batches(rng, STEPS), steps=STEPS,
                optimizer="adagrad", lr=0.05, log_every=0, lookahead=1,
                store=store, publish_every=PUBLISH_EVERY, publish_dir=tmp,
                registry=reg)
        check("metrics_snapshot" in history,
              "fit history has no metrics_snapshot")
        check("metrics_error" not in history,
              f"fit metrics_error: {history.get('metrics_error')}")

        # ---- 2. serving replica consuming the published stream ------
        emb2 = _programs.build_model(VOCAB, WIDTH, "sum", tables=TABLES,
                                     mesh=mesh).embedding
        engine = InferenceEngine(emb2, emb2.init(jax.random.PRNGKey(1)),
                                 registry=reg)
        applied = engine.poll_updates(tmp)
        check(len(applied) >= 1, "replica applied no published files")
        engine.warmup([64])
        batcher = MicroBatcher(engine, max_batch=64, registry=reg)
        for _ in range(REQUESTS):
            n = int(rng.randint(1, 32))
            batcher.submit([rng.randint(0, VOCAB, size=(n, HOTNESS))
                            .astype(np.int64) for _ in range(TABLES)])
        batcher.flush()

        # ---- 3. static audit -> gauge ------------------------------
        import importlib.util as ilu
        spec = ilu.spec_from_file_location(
            "det_hlo_audit", os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "hlo_audit.py"))
        ha = ilu.module_from_spec(spec)
        spec.loader.exec_module(ha)
        recs, _ = ha.run_matrix(ha.load_baseline(), world=WORLD)
        audit_ids = sorted({f"{r['program']}:{f['fid']}"
                            for r in recs for f in r["findings"]})
        reg.gauge("audit/findings").set(len(audit_ids))
        if audit_ids:
            print(f"audit findings: {audit_ids}", file=sys.stderr)

        # ---- 5. device-time attribution over the real capture ------
        att = obs.attribution.attribute_logdir(profile_dir, registry=reg)
        for field in ("total_device_seconds", "spans",
                      "unattributed_seconds", "ambiguous_seconds",
                      "coverage_frac", "device_op_count",
                      "span_window_count", "collective"):
            check(field in att, f"attribution record missing {field!r}")
        check(att["device_op_count"] > 0, "no device ops in the capture")
        check(att["span_window_count"] > 0,
              "no span annotation windows in the capture")
        check(att["spans"] and sum(att["spans"].values()) > 0,
              "zero span coverage: no device time attributed to spans")
        total = sum(att["spans"].values()) + att["unattributed_seconds"]
        check(abs(total - att["total_device_seconds"]) < 1e-6,
              f"attribution does not sum: {total} != "
              f"{att['total_device_seconds']}")
        check(any(p.startswith("train/step") for p in att["spans"]),
              f"train/step not among attributed spans: "
              f"{sorted(att['spans'])}")

        # ---- 6. flight recorder: ring, export, lineage --------------
        rec = obs.default_recorder()
        doc = rec.export(os.path.join(tmp, "flight_trace.json"))
        with open(os.path.join(tmp, "flight_trace.json")) as f:
            doc2 = json.load(f)
        check(doc2["traceEvents"] == doc["traceEvents"],
              "flight-recorder export round trip")
        depth = {}
        for ev in doc["traceEvents"]:
            if ev["ph"] == "B":
                depth[ev["tid"]] = depth.get(ev["tid"], 0) + 1
            elif ev["ph"] == "E":
                depth[ev["tid"]] = depth.get(ev["tid"], 0) - 1
                check(depth[ev["tid"]] >= 0, "unbalanced E in export")
        check(all(v == 0 for v in depth.values()),
              f"unbalanced spans in export: {depth}")
        pub_versions = {i["version"] for i in history.get("published", [])
                        if i["kind"] != "paused"}
        lineage = set(rec.lineage_versions())
        check(pub_versions <= lineage,
              f"published versions {sorted(pub_versions - lineage)} "
              "missing from lineage tracks")

        # ---- 4a. snapshot schema -----------------------------------
        snap = reg.snapshot()
        for section in ("counters", "gauges", "histograms"):
            check(section in snap, f"snapshot missing {section!r}")
        c, g, h = snap["counters"], snap["gauges"], snap["histograms"]
        check(c.get("train/steps") == STEPS,
              f"train/steps {c.get('train/steps')} != {STEPS}")
        check(c.get("train/examples") == STEPS * BATCH, "train/examples")
        check(c.get("lookahead/steps") == STEPS, "lookahead/steps")
        check(c.get("store/publishes", 0) >= 2, "store/publishes")
        check(c.get("store/applies", 0) >= 1, "store/applies")
        check(c.get("serve/requests") == REQUESTS, "serve/requests")
        check(g.get("lookahead/compiles{stage=fused}") == 1.0,
              f"fused compiles {g.get('lookahead/compiles{stage=fused}')}")
        check(g.get("train/examples_per_sec", 0) > 0, "examples_per_sec")
        check("exchange/touched_rows_per_step" in g, "exchange gauges")
        # ISSUE 12: the run must say which sparse-update kernel family
        # it could dispatch to (-1 = CPU interpret, the expected value
        # here) and which path the step spans were attributed to
        check("kernels/gate_verdict{impl=pallas}" in g,
              "kernel gate-verdict gauges")
        check(any(k.startswith("span_seconds{span=train/step/update/")
                  for k in h), "per-strategy update-phase span")
        check(h["span_seconds{span=train/step}"]["count"] == STEPS,
              "train/step span count")
        check(h["serve/request_seconds"]["count"] == REQUESTS,
              "request latency count")
        check(any(k.startswith("ingest/stage_seconds") for k in h),
              "ingest stage histograms")
        # ISSUE 14: the attribution gauges joined the same namespace
        check(any(k.startswith("device/span_seconds") for k in g),
              "device/span_seconds gauges")
        check("device/unattributed_seconds" in g
              and "device/total_seconds" in g, "device totals gauges")

        # ---- 4b. export round trips --------------------------------
        jsonl = os.path.join(tmp, "metrics.jsonl")
        reg.export_jsonl(jsonl, extra={"source": "obs_smoke"})
        reg.export_jsonl(jsonl)
        lines = [json.loads(ln) for ln in open(jsonl)]
        check(len(lines) == 2 and lines[0]["counters"] == snap["counters"],
              "JSONL export round trip")
        prom = reg.to_prometheus()
        check("span_seconds" in prom and "train_steps_total" in prom,
              "prometheus dump")

        # ---- 4c. the checked-in SLO rules --------------------------
        rules_path = os.path.join(os.path.dirname(os.path.abspath(
            __file__)), "slo_tier1.json")
        findings = obs.evaluate_rules(obs.load_rules(rules_path), snap)
        for f in findings:
            print(f"SLO violation: {f.fid}: {f.message}", file=sys.stderr)
        check(not findings, f"{len(findings)} SLO finding(s)")

        # ---- 7. metric-catalog drift gate --------------------------
        # every family name this driven run observes must appear in
        # docs/observability.md's catalog (wildcard rows like
        # ``exchange/*`` cover their prefix) — new metrics can no
        # longer ship undocumented
        doc_path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "docs", "observability.md")
        with open(doc_path) as f:
            doc_text = f.read()
        import re as _re
        wildcards = [m.group(1) + "/"
                     for m in _re.finditer(r"`([\w/]+)/\*`", doc_text)]
        families = sorted({key.split("{", 1)[0]
                           for section in snap.values()
                           for key in section})
        undocumented = [fam for fam in families
                        if fam not in doc_text
                        and not any(fam.startswith(w) for w in wildcards)]
        check(not undocumented,
              f"metric families missing from docs/observability.md: "
              f"{undocumented}")

        print(json.dumps({
            "obs_smoke": "ok", "world": WORLD,
            "train_steps": c["train/steps"],
            "publishes": c["store/publishes"],
            "applies": c["store/applies"],
            "requests": c["serve/requests"],
            "fused_compiles": g["lookahead/compiles{stage=fused}"],
            "audit_findings": len(audit_ids),
            "slo_rules_evaluated": len(obs.load_rules(rules_path)),
            "device_coverage_frac": att["coverage_frac"],
            "device_spans": len(att["spans"]),
            "flight_events": len(doc["traceEvents"]),
            "lineage_versions": sorted(lineage),
            "metric_families_checked": len(families),
        }))
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
