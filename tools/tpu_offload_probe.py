"""Prove the NATIVE host-offload sparse apply on real TPU hardware.

VERDICT r3 weak #3: the `compute_on("device_host")` + pinned-host-output
apply path (layers/dist_model_parallel.py) has only ever taken the CPU
fallback — XLA:CPU rejects replicated side-effect HLO, so the 8-device
dryrun always warns and round-trips the bucket through the device. On a
real chip (world 1, no replication) the native path should run. This probe:

  1. builds a 2-bucket model where one bucket exceeds a small
     gpu_embedding_size budget -> pinned_host placement;
  2. runs forward + a sparse adagrad/adam step on the single TPU chip;
  3. reports whether the host-apply fallback RuntimeWarning fired (native
     path taken = no warning), verifies post-step memory kinds, and
     equivalence against an all-device twin;
  4. slope-times the offloaded vs device-resident step (per-step offload
     cost, docs/capacity.md note).

Usage: python tools/tpu_offload_probe.py
"""

import json
import os
import sys
import time
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

RESULTS = {}


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    try:
        kinds = {m.kind for m in dev.addressable_memories()}
    except Exception as e:  # noqa: BLE001
        kinds = set()
        print(f"addressable_memories failed: {e}", flush=True)
    RESULTS["memory_kinds"] = sorted(kinds)
    if "pinned_host" not in kinds:
        RESULTS["verdict"] = "SKIP no pinned_host memory space"
        print(json.dumps(RESULTS), flush=True)
        return

    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.training import make_sparse_train_step

    rng = np.random.RandomState(0)
    # 8 one-hot tables; the 200k-row ones blow a 150k-element budget
    specs = [(200_000, 16), (400, 16), (200_000, 16), (512, 16),
             (640, 16), (768, 16), (896, 16), (1024, 16)]
    batch = 4096

    class _Tiny:
        def __init__(self, emb):
            self.embedding = emb

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            out = self.embedding(p["embedding"], list(cats), taps=taps,
                                 return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x.astype(jnp.float32), axis=1)
                             - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    weights = [rng.randn(v, w).astype(np.float32) * 0.1 for v, w in specs]
    cats = [jnp.asarray(rng.randint(0, v, size=(batch, 2)).astype(np.int32))
            for v, _ in specs]
    labels = jnp.asarray(rng.randn(batch).astype(np.float32))
    numerical = jnp.zeros((batch, 1), jnp.float32)

    def build(budget):
        return _Tiny(DistributedEmbedding(
            [Embedding(v, w, combiner="sum") for v, w in specs],
            gpu_embedding_size=budget))

    for optimizer in ("adagrad", "adam"):
        off_model = build(150_000 * 16)
        dev_model = build(None)
        assert any(b.offload for b in off_model.embedding.plan.tp_buckets)
        p_off = {"embedding": off_model.embedding.set_weights(weights)}
        p_dev = {"embedding": dev_model.embedding.set_weights(weights)}
        oi, ostep = make_sparse_train_step(off_model, optimizer, lr=0.05)
        di, dstep = make_sparse_train_step(dev_model, optimizer, lr=0.05)
        so, sd = oi(p_off), di(p_dev)
        with warnings.catch_warnings(record=True) as wlog:
            warnings.simplefilter("always")
            p_off, so, lo = ostep(p_off, so, numerical, cats, labels)
            lo = float(lo)
        fallback = [str(x.message) for x in wlog
                    if "falling back" in str(x.message)]
        RESULTS[f"{optimizer}_native_host_apply"] = not fallback
        RESULTS[f"{optimizer}_fallback_warnings"] = fallback[:2]
        p_dev, sd, ld = dstep(p_dev, sd, numerical, cats, labels)
        ld = float(ld)
        RESULTS[f"{optimizer}_loss_match"] = bool(abs(lo - ld) < 1e-4)
        got = off_model.embedding.get_weights(p_off["embedding"])
        want = dev_model.embedding.get_weights(p_dev["embedding"])
        err = max(float(np.max(np.abs(a - b))) for a, b in zip(got, want))
        RESULTS[f"{optimizer}_weights_maxerr"] = err
        # post-step placement intact
        for b, bk in enumerate(off_model.embedding.plan.tp_buckets):
            kind = p_off["embedding"]["tp"][b].sharding.memory_kind
            want_kind = "pinned_host" if bk.offload else "device"
            RESULTS[f"{optimizer}_bucket{b}_kind_ok"] = kind == want_kind
        print(f"{optimizer}: native={RESULTS[f'{optimizer}_native_host_apply']}"
              f" weights_err={err:.2e} loss={lo:.4f}/{ld:.4f}", flush=True)

        # per-step cost: offloaded vs device-resident (slope-timed, chained)
        def time_steps(step, params, state, iters=8):
            def once(p, s):
                for _ in range(iters):
                    p, s, l = step(p, s, numerical, cats, labels)
                return p, s, l
            p, s, l = once(params, state)
            float(l)
            t0 = time.perf_counter()
            p, s, l = once(p, s)
            float(l)
            t1 = time.perf_counter() - t0
            t0 = time.perf_counter()
            p, s, l = once(p, s)
            p, s, l = once(p, s)
            float(l)
            t2 = time.perf_counter() - t0
            return max(t2 - t1, 1e-9) / iters * 1e3, {"t1_ms": t1 * 1e3,
                                                      "t2_ms": t2 * 1e3}
        ms_off, raw_o = time_steps(ostep, p_off, so)
        ms_dev, raw_d = time_steps(dstep, p_dev, sd)
        RESULTS[f"{optimizer}_step_ms_offloaded"] = round(ms_off, 3)
        RESULTS[f"{optimizer}_step_ms_device"] = round(ms_dev, 3)
        RESULTS[f"{optimizer}_raw"] = {"off": raw_o, "dev": raw_d}
        modes = off_model.embedding.host_apply_modes()
        RESULTS[f"{optimizer}_apply_mode"] = sorted(
            f"b{b}:{m}" for (b, _k), m in modes.items())
        print(f"{optimizer}: offloaded {ms_off:.2f} ms/step vs device "
              f"{ms_dev:.2f} ms/step mode={RESULTS[f'{optimizer}_apply_mode']}",
              flush=True)

        # A/B: force the XLA-free per-shard apply (the pod answer where the
        # partitioner rejects host placements) against whatever auto chose
        os.environ["DET_HOST_APPLY"] = "pershard"
        try:
            ps_model = build(150_000 * 16)
            p_ps = {"embedding": ps_model.embedding.set_weights(weights)}
            pi, pstep = make_sparse_train_step(ps_model, optimizer, lr=0.05)
            sp = pi(p_ps)
            p_ps, sp, lp = pstep(p_ps, sp, numerical, cats, labels)
            RESULTS[f"{optimizer}_pershard_loss_match"] = bool(
                abs(float(lp) - ld) < 1e-4)
            ms_ps, raw_p = time_steps(pstep, p_ps, sp)
            RESULTS[f"{optimizer}_step_ms_pershard"] = round(ms_ps, 3)
            RESULTS[f"{optimizer}_pershard_raw"] = raw_p
            print(f"{optimizer}: pershard {ms_ps:.2f} ms/step", flush=True)
        except Exception as e:  # noqa: BLE001
            RESULTS[f"{optimizer}_pershard_error"] = str(e)[:300]
        finally:
            os.environ.pop("DET_HOST_APPLY", None)

    print(json.dumps(RESULTS), flush=True)


if __name__ == "__main__":
    main()
