"""Measure the TPU primitive costs that decide the sparse-update design.

Each measurement runs the op `iters` times inside ONE jitted computation with
a forced data dependency between iterations (the output perturbs the next
input), so XLA cannot hoist, DCE, or overlap the work away; the tunnel
dispatch cost is paid once.

Sync + timing (round-3 hardware finding): `block_until_ready` is unreliable
on the axon tunnel — it returned early and "timed" a 2.9M-key sort at 15us.
Every chain is therefore timed slope-style with a host FETCH as the sync:
run the loop program once (t1) and twice back-to-back (t2); per-iter =
(t2 - t1) / iters. Constant overheads (dispatch, fetch RTT, queue drain)
cancel in the subtraction. See utils/profiling.fetch_sync.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from distributed_embeddings_tpu.utils.profiling import fetch_sync

RESULTS = {}
_ITERS = 10


def timed_chain(make_fn, init_state, iters=None, label="", n_rows=None):
    """make_fn: state -> state (same pytree structure/shapes)."""
    iters = iters or _ITERS

    def loop(state):
        def body(i, s):
            return make_fn(s)
        return lax.fori_loop(0, iters, body, state)

    lf = jax.jit(loop)
    out = lf(init_state)
    fetch_sync(out)                      # warm + drain the queue
    t0 = time.perf_counter()
    out = lf(init_state)
    fetch_sync(out)
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = lf(init_state)
    out = lf(out)
    fetch_sync(out)
    t2 = time.perf_counter() - t0
    dt = max(t2 - t1, 1e-9) / iters
    print(f"{label}: {dt * 1e3:.3f} ms/iter "
          f"(t1={t1 * 1e3:.1f}ms t2={t2 * 1e3:.1f}ms)", flush=True)
    RESULTS[label] = {"ms": round(dt * 1e3, 3),
                      "t1_ms": round(t1 * 1e3, 1),
                      "t2_ms": round(t2 * 1e3, 1)}
    if n_rows:
        RESULTS[label]["ns_per_row"] = round(dt / n_rows * 1e9, 1)
    return dt


def main():
    global _ITERS
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()
    _ITERS = args.iters
    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    rng = np.random.default_rng(0)

    # 1. sort_key_val: key depends on previous output
    for n in (65536, 720896, 2883584):
        keys = jnp.asarray(rng.integers(0, 25_000_000, n).astype(np.int32))
        vals = jnp.arange(n, dtype=jnp.int32)

        def step(s, n=n):
            k, v = s
            ks, vs = lax.sort_key_val(k, v)
            # perturb: rotate sorted keys so next sort is real work
            return jnp.roll(ks, 1) ^ vs, vs
        timed_chain(step, (keys, vals), label=f"sort_key_val n={n}")

    # 2. dense scatter-add into [25M, 16] fresh zeros each iter
    v = 25_000_000
    for n in (720896, 65536):
        ids = jnp.asarray(rng.integers(0, v, n).astype(np.int32))
        rows = jnp.asarray(rng.standard_normal((n, 16), dtype=np.float32))

        def step(s, n=n):
            i, r = s
            buf = jnp.zeros((v, 16), jnp.float32).at[i].add(r)
            # derive next ids from the scattered buffer (forces execution)
            i2 = (i + buf[0, 0].astype(jnp.int32) + 1) % v
            return i2, r
        timed_chain(step, (ids, rows), label=f"dense-scatter-add V=25M n={n}")

    # 3. in-place scatter-add into a live table carried through the loop
    table = jnp.zeros((v, 16), jnp.float32)
    ids = jnp.asarray(rng.integers(0, v, 720896).astype(np.int32))
    rows = jnp.asarray(rng.standard_normal((720896, 16), dtype=np.float32))

    def step(s):
        t, i = s
        t = t.at[i].add(rows)
        return t, (i + 1) % v
    timed_chain(step, (table, ids), label="carried scatter-add V=25M n=720896")

    # 4. gather 65536 rows from 25M x 16
    ids1 = jnp.asarray(rng.integers(0, v, 65536).astype(np.int32))

    def step(s):
        t, i = s
        out = jnp.take(t, i, axis=0)
        return t, (i + out[0, 0].astype(jnp.int32) + 1) % v
    timed_chain(step, (table, ids1), label="gather 65536 from 25Mx16")

    # 4b. gather 720896 rows (multi-hot scale)
    def stepb(s):
        t, i = s
        out = jnp.take(t, i, axis=0)
        return t, (i + out[0, 0].astype(jnp.int32) + 1) % v
    timed_chain(stepb, (table, ids), label="gather 720896 from 25Mx16")

    # 5. dense adagrad pass over 16M x 16 (1 GiB param + 1 GiB acc)
    p = jnp.zeros((16_000_000, 16), jnp.float32)
    a = jnp.ones((16_000_000, 16), jnp.float32)

    def step5(s):
        p, a = s
        g = p * 1e-6 + 1e-3
        a = a + g * g
        p = p - 0.01 * g * lax.rsqrt(a + 1e-10)
        return p, a
    timed_chain(step5, (p, a), label="dense adagrad pass 16Mx16 (2GiB state)")

    # 6. segment_sum 720k x 16 -> 720k segments
    n = 720896
    seg = jnp.asarray(np.sort(rng.integers(0, n, n)).astype(np.int32))
    rows = jnp.asarray(rng.standard_normal((n, 16), dtype=np.float32))

    def step6(s):
        sg, r = s
        out = jax.ops.segment_sum(r, sg, num_segments=n)
        return (sg + out[0, 0].astype(jnp.int32) % 2) % n, r
    timed_chain(step6, (seg, rows), label="segment_sum n=720k w=16")

    # 7. permute 720k x 16 rows
    perm = jnp.asarray(rng.permutation(n).astype(np.int32))

    def step7(s):
        r, pm = s
        out = jnp.take(r, pm, axis=0)
        return out, pm
    timed_chain(step7, (rows, perm), label="permute 720k x 16 rows")

    # 8. fused sparse-adagrad row update (the bench's per-bucket backward
    # cost; decides DET_SPARSE_DENSE_MAX), both dedup strategies
    from distributed_embeddings_tpu.ops import sparse_update as su
    tbl = jnp.zeros((v, 16), jnp.float32)
    acc = jnp.full((v, 16), 0.1, jnp.float32)
    sids = jnp.asarray(rng.integers(0, v, n).astype(np.int32))
    contribs = jnp.asarray(rng.standard_normal((n, 16), dtype=np.float32))
    for strat in ("sort", "dense"):
        def step8(s, strat=strat):
            t, a, i = s
            t2, a2 = su.sparse_adagrad(t, a, su.SparseRowGrad(i, contribs),
                                       0.01, strategy=strat)
            return t2, a2, (i * 1103515245 + 12345) % v
        timed_chain(step8, (tbl, acc, sids),
                    label=f"sparse_adagrad[{strat}] n=720k V=25M",
                    n_rows=n)

    print(json.dumps(RESULTS), flush=True)


if __name__ == "__main__":
    sys.exit(main())
