"""DLRM convergence demo: AUC climbs on a learnable synthetic click stream.

The reference's convergence evidence is AUC 0.80248 on Criteo-1TB
(reference: examples/dlrm/README.md:7). That dataset is unavailable here, so
this driver trains a scaled-down DLRM (26 tables, power-law ids) on
`ClickGenerator`'s planted-structure stream (Bayes AUC ~0.85) over the
8-virtual-device CPU mesh, using the production sparse tapped path +
warmup/poly-decay LR schedule, and records the AUC curve as a committed
artifact (VERDICT r2 item 5).

  python tools/convergence_demo.py --steps 2000 --out docs/convergence_r03.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..")))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# config.update, not env: sitecustomize pre-imports jax (see conftest.py)
jax.config.update("jax_compilation_cache_dir", os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def run(steps=2000, batch=512, eval_every=250, eval_steps=8, lr=0.08,
        seed=0, log_fn=print):
    from distributed_embeddings_tpu import training
    from distributed_embeddings_tpu.models.dlrm import DLRM, make_lr_schedule
    from distributed_embeddings_tpu.models.synthetic import ClickGenerator
    from distributed_embeddings_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(jax.devices()[:8])
    sizes = [100 + 137 * i for i in range(26)]        # varied vocabs
    model = DLRM(sizes, embedding_dim=16, bottom_mlp_dims=(32, 16),
                 top_mlp_dims=(64, 32, 1), num_numerical_features=13,
                 mesh=mesh)
    gen = ClickGenerator(sizes, 13, batch, alpha=1.05, seed=seed + 1)
    eval_data = lambda j: gen.batch(1_000_000 + j)    # noqa: E731

    params = model.init(jax.random.PRNGKey(seed))
    schedule = make_lr_schedule(lr, warmup_steps=max(steps // 20, 1),
                                decay_start_step=steps // 2,
                                decay_steps=max(steps // 2, 1))
    params, _, hist = training.fit(
        model, params, gen, steps=steps, optimizer="adagrad", lr=schedule,
        sparse=True, eval_data=eval_data, eval_every=eval_every,
        eval_steps=eval_steps, log_every=max(eval_every // 2, 1),
        log_fn=log_fn)
    return {
        "model": {"tables": len(sizes), "vocab_total": sum(sizes),
                  "embedding_dim": 16, "batch": batch, "steps": steps,
                  "optimizer": "adagrad", "lr": lr, "alpha": 1.05},
        "loss_first100_mean": float(sum(hist["loss"][:100]) /
                                    max(len(hist["loss"][:100]), 1)),
        "loss_last100_mean": float(sum(hist["loss"][-100:]) /
                                   max(len(hist["loss"][-100:]), 1)),
        "eval_auc": [round(a, 5) for a in hist.get("eval_auc", [])],
        "eval_every": eval_every,
    }


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=2000)
    p.add_argument("--batch", type=int, default=512)
    p.add_argument("--eval_every", type=int, default=250)
    p.add_argument("--eval_steps", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.08)
    p.add_argument("--out", default=None)
    args = p.parse_args()
    result = run(args.steps, args.batch, args.eval_every, args.eval_steps,
                 args.lr)
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
