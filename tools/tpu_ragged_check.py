"""Does this backend lower `lax.ragged_all_to_all`? (single-chip check)

The true-splits exchange (reference dist_model_parallel.py:169-288 —
`hvd.alltoall` with per-destination `splits` paying exactly nnz) maps to
`lax.ragged_all_to_all` on TPU. Round 2 deferred it because XLA:CPU has no
lowering, making it untestable on the virtual mesh (docs/round2_notes.md).
This stage answers the half that needs only one real chip: does the TPU
backend compile AND execute the op with correct semantics on a 1-device
mesh? A pass green-lights building the true-splits exchange behind a flag;
a fail records the concrete error for the round notes.

Run via tools/tpu_validate.py (stage 'ragged') — own process + timeout.
"""

import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def main():
    d = jax.devices()
    assert d and d[0].platform != "cpu", f"cpu fallback: {d}"
    print("devices", d, flush=True)
    mesh = Mesh(np.array(d[:1]), ("x",))
    n = 16

    def body(x):
        out = jnp.full((n,), -1.0, x.dtype)
        in_off = jnp.array([0], jnp.int32)
        send = jnp.array([5], jnp.int32)
        out_off = jnp.array([2], jnp.int32)
        recv = jnp.array([5], jnp.int32)
        return lax.ragged_all_to_all(x, out, in_off, send, out_off, recv,
                                     axis_name="x")

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=(P("x"),),
                              out_specs=P("x")))
    # f32 AND int32: the distributed forward's ragged path
    # (DET_RAGGED_EXCHANGE) moves int32 ids
    for dtype in (jnp.float32, jnp.int32):
        x = jnp.arange(n, dtype=dtype)
        t0 = time.perf_counter()
        got = np.asarray(jax.block_until_ready(f(x)))
        dt = time.perf_counter() - t0
        want = np.full((n,), -1.0, np.float32).astype(dtype)
        want[2:7] = np.arange(5).astype(dtype)
        np.testing.assert_array_equal(got, want)
        print(f"ragged_all_to_all[{jnp.dtype(dtype).name}]: LOWERS + "
              f"CORRECT on {d[0].platform} (compile+run {dt:.1f}s)",
              flush=True)


if __name__ == "__main__":
    main()
