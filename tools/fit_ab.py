"""A/B: `training.fit` loop overhead vs a lax.scan-chained step.

VERDICT r2 weak 2: fit used to force a host sync every step (float(loss)),
so the user-facing loop would measure slower than the scan-chained number
bench.py reports. Round 3 removed the per-step sync (device-side loss
history, sync only at log/sync_every boundaries). This driver proves the
fix: steady-state per-step time of the fit loop (sync_every=0) must be
within ~10% of an equivalent lax.scan chain of the same jitted step.

Runs on ONE CPU device (no collectives — XLA:CPU's in-process collectives
are unsafe under deep async dispatch, which is exactly what this measures;
the TPU runtime has no such restriction, so the single-device CPU number
is the honest proxy for loop overhead).

  python tools/fit_ab.py --steps 300
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..")))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# config.update, not env: sitecustomize pre-imports jax (see conftest.py)
jax.config.update("jax_compilation_cache_dir", os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()

    from distributed_embeddings_tpu import training
    from distributed_embeddings_tpu.models.synthetic import (
        EmbeddingConfig, ModelConfig, SyntheticModel)

    cfg = ModelConfig(
        "fit-ab", [EmbeddingConfig(8, [1], 2000, 16, False),
                   EmbeddingConfig(2, [4], 5000, 16, False)],
        [64, 32], 4, None)
    model = SyntheticModel(cfg, mesh=None, distributed=True)

    def batch(step):
        r = np.random.RandomState(step % 8)
        cats = [r.randint(0, 2000, (args.batch, 1)) for _ in range(8)] + \
               [r.randint(0, 5000, (args.batch, 4)) for _ in range(2)]
        return (r.rand(args.batch, 4).astype(np.float32), cats,
                r.randint(0, 2, args.batch).astype(np.float32))

    init_fn, step_fn = training.make_sparse_train_step(model, "adagrad",
                                                       lr=0.05)

    def fresh(seed):
        p = model.init(jax.random.PRNGKey(seed))
        return p, init_fn(p)

    # --- A: fit loop, steady state (warmup run compiles) ----------------
    # pre-staged batches: measure the LOOP, not per-step data generation
    pre = []
    for i in range(8):
        n, c, l = batch(i)
        pre.append((jnp.asarray(n), [jnp.asarray(x) for x in c],
                    jnp.asarray(l)))
    data = lambda i: pre[i % 8]  # noqa: E731
    p0, _ = fresh(0)
    training.fit(model, p0, data, steps=2, optimizer="adagrad", lr=0.05,
                 sparse=True, log_every=0, sync_every=0,
                 log_fn=lambda *_: None)
    p0, _ = fresh(0)
    t0 = time.perf_counter()
    p_fit, _, _ = training.fit(
        model, p0, data, steps=args.steps, optimizer="adagrad",
        lr=0.05, sparse=True, log_every=0, sync_every=0,
        log_fn=lambda *_: None)
    jax.block_until_ready(jax.tree.leaves(p_fit)[0])
    fit_s = (time.perf_counter() - t0) / args.steps

    # --- A2: bare Python loop over the same jitted step -----------------
    # isolates what fit ADDS vs the irreducible per-call dispatch cost any
    # Python loop pays (pytree flatten + async dispatch)
    p0, s0 = fresh(0)
    t0 = time.perf_counter()
    for i in range(args.steps):
        n, c, l = data(i)
        p0, s0, loss = step_fn(p0, s0, n, c, l)
    jax.block_until_ready(jax.tree.leaves(p0)[0])
    bare_s = (time.perf_counter() - t0) / args.steps

    # --- B: lax.scan chain over the same jitted step --------------------
    # (bench.py's steady-state method: one dispatch, no Python loop at all)
    batches = [batch(i) for i in range(8)]
    nums = jnp.stack([jnp.asarray(b[0]) for b in batches])
    cats = [jnp.stack([jnp.asarray(b[1][j]) for b in batches])
            for j in range(10)]
    labs = jnp.stack([jnp.asarray(b[2]) for b in batches])

    def scan_body(carry, i):
        p, s = carry
        nb = nums[i % 8]
        cb = [c[i % 8] for c in cats]
        lb = labs[i % 8]
        p, s, loss = step_fn(p, s, nb, cb, lb)
        return (p, s), loss

    import functools

    @functools.partial(jax.jit, static_argnums=2)
    def chain(p, s, k):
        (p, s), losses = jax.lax.scan(scan_body, (p, s), jnp.arange(k))
        return p, s, losses

    p1, s1 = fresh(0)
    p3, s3, _ = chain(p1, s1, args.steps)  # compile
    jax.block_until_ready(jax.tree.leaves(p3)[0])
    p1, s1 = fresh(0)
    t0 = time.perf_counter()
    p3, s3, _ = chain(p1, s1, args.steps)
    jax.block_until_ready(jax.tree.leaves(p3)[0])
    scan_s = (time.perf_counter() - t0) / args.steps

    print(f"fit loop:   {fit_s * 1e3:8.3f} ms/step (sync_every=0)")
    print(f"bare loop:  {bare_s * 1e3:8.3f} ms/step (same jitted step)")
    print(f"scan chain: {scan_s * 1e3:8.3f} ms/step")
    print(f"fit vs scan: {fit_s / scan_s:.3f}x | fit vs bare loop: "
          f"{fit_s / bare_s:.3f}x | dispatch overhead "
          f"{(bare_s - scan_s) * 1e3:.3f} ms/step")
    ok = fit_s / bare_s < 1.10
    print("PASS: fit adds <10% over a bare loop" if ok
          else "FAIL: fit loop adds >10% over a bare loop")


if __name__ == "__main__":
    main()
