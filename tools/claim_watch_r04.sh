#!/bin/bash
# TPU claim watcher (round 4).
# Round-4 goal is PERFORMANCE: the chip must run the staged probes, validate
# the new tiled one-hot-matmul kernels, and produce a fresh non-cached bench
# of HEAD (VERDICT r3 items 1-4). On tunnel recovery this runs the stages in
# tools/r04_stages.txt (cheapest first, one killable subprocess each) so the
# stage list can evolve mid-round without restarting the watcher.
# Logs: tools/claim_watch_r04.log   Sentinel: /tmp/tpu_alive_r04
set -u
LOG=/root/repo/tools/claim_watch_r04.log
BUSY=/tmp/det_tpu_busy
STAGES=/root/repo/tools/r04_stages.txt
# hard deadline: stay clear of the driver's round-end bench (round ends
# ~04:45 Aug 1; stop probing at 03:30 so the chip claim is free)
DEADLINE_EPOCH=${DET_WATCH_DEADLINE:-$(date -d "2026-08-01 03:30 UTC" +%s)}
cd /root/repo
export JAX_COMPILATION_CACHE_DIR=/tmp/jax_cache_det_tpu
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1
echo "$(date +%H:%M:%S) r04 watcher start" >> "$LOG"
n=0
while true; do
  if [ "$(date +%s)" -ge "$DEADLINE_EPOCH" ]; then
    echo "$(date +%H:%M:%S) deadline reached; watcher exits" >> "$LOG"
    rm -f "$BUSY"
    exit 0
  fi
  n=$((n+1))
  # must see a real accelerator (JAX can silently fall back to CPU).
  # -k: a wedged axon client can ignore SIGTERM indefinitely (observed
  # 2026-07-31: one probe blocked the loop for 2h) — follow up with KILL
  if timeout -k 15 90 python -c "
import jax
d = jax.devices()
print(d)
assert d and d[0].platform != 'cpu', f'cpu fallback: {d}'
import jax.numpy as jnp
print('fetch', float(jnp.sum(jnp.ones((128, 128)) @ jnp.ones((128, 128)))))
" >> "$LOG" 2>&1; then
    echo "$(date +%H:%M:%S) probe $n SUCCESS — tunnel alive" >> "$LOG"
    touch /tmp/tpu_alive_r04
    bench_rc=1
    echo $$ > "$BUSY"
    trap 'rm -f "$BUSY"' EXIT
    while IFS=: read -r cmd secs name; do
      [ -z "${cmd:-}" ] && continue
      case "$cmd" in \#*) continue ;; esac
      if [ "$(date +%s)" -ge "$DEADLINE_EPOCH" ]; then
        echo "$(date +%H:%M:%S) deadline mid-stages; stopping" >> "$LOG"
        break
      fi
      echo "$(date +%H:%M:%S) running $name" >> "$LOG"
      # shellcheck disable=SC2086
      DET_BENCH_SKIP_BUSY_WAIT=1 timeout -k 30 "$secs" python -u $cmd \
        > "tools/watch_${name}_r04.out" 2>&1
      rc=$?
      echo "$(date +%H:%M:%S) $name rc=$rc" >> "$LOG"
      [ "$name" = bench ] && bench_rc=$rc
      sleep 20
    done < "$STAGES"
    rm -f "$BUSY"
    git add -- tools/watch_*_r04.out tools/bench_last_tpu.json \
        tools/claim_watch_r04.log 2>/dev/null || true
    git commit -q -m "Hardware window artifacts (r04 claim watcher)" \
        -- tools/watch_*_r04.out tools/bench_last_tpu.json \
        tools/claim_watch_r04.log 2>/dev/null || true
    if [ "$bench_rc" -eq 0 ] \
       && grep -q '"metric"' tools/watch_bench_r04.out \
       && ! grep -q '"cached": true' tools/watch_bench_r04.out; then
      touch /tmp/tpu_measured_r04
      echo "$(date +%H:%M:%S) fresh bench landed; continuing watch for reruns" >> "$LOG"
    fi
    echo "$(date +%H:%M:%S) stages done; resuming watch" >> "$LOG"
  else
    echo "$(date +%H:%M:%S) probe $n failed" >> "$LOG"
  fi
  sleep 240
done
