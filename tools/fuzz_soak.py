"""Extended fuzz soak: higher seeds than the suite's fixed range, with the
dispatch knobs randomized per case (DET_DEDUP_IMPL; DET_SGD_DEDUP and
DET_SORTED_GATHER were retired in round 5) so knob interactions get
coverage the named tests don't. Exact equivalence bar is the same as
tests/test_fuzz_equivalence.

Usage: python tools/fuzz_soak.py [first_seed] [n_seeds]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

# CPU + 8 virtual devices, same as tests/conftest.py
os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "")
     + " --xla_force_host_platform_device_count=8").strip())
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    first = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    count = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    from test_fuzz_equivalence import gen_config  # noqa: E402
    from test_dist_model_parallel import check_equivalence  # noqa: E402

    failures = 0
    for seed in range(first, first + count):
        rng = np.random.RandomState(7000 + seed)
        knobs = {}
        if rng.rand() < 0.4:
            knobs["DET_DEDUP_IMPL"] = "cumsum"
        specs, table_map, kw = gen_config(seed)
        # cumsum dedup is tolerance-equal, not exact
        if knobs.get("DET_DEDUP_IMPL") == "cumsum":
            for k, v in (("rtol", 1e-4), ("atol", 1e-4),
                         ("train_rtol", 1e-4), ("train_atol", 1e-4)):
                kw[k] = max(kw.get(k, 0.0) or 0.0, v)
        os.environ.update(knobs)
        try:
            check_equivalence(specs, input_table_map=table_map, **kw)
            print(f"seed {seed} OK knobs={knobs}", flush=True)
        except ValueError as e:
            # planner's legitimate unrunnable-config rejection (too few
            # tables for the device count after slicing — same contract as
            # the reference's empty-rank error, dist_model_parallel:799)
            if "Not enough tables" in str(e):
                print(f"seed {seed} SKIP (unrunnable config): {e}",
                      flush=True)
            else:
                failures += 1
                print(f"seed {seed} FAIL knobs={knobs}: {str(e)[:500]}",
                      flush=True)
        except Exception as e:  # noqa: BLE001 - report and continue
            failures += 1
            print(f"seed {seed} FAIL knobs={knobs}: {str(e)[:500]}",
                  flush=True)
        finally:
            for k in knobs:
                os.environ.pop(k, None)
    print(f"{'PASS' if failures == 0 else 'FAIL'}: "
          f"{count - failures}/{count} seeds OK", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
