"""HLO op-count and collective-byte audit for the compiled train step.

The sort-folding work (ISSUE 2, docs/perf_model.md "Sort folding") is a
TRACE-TIME property: the folded step must contain at most one stablehlo.sort
per (bucket, hotness) exchange group — one more (the inverse-permute sort)
when the tiled forward gather is active. That is checkable on any backend
without hardware, which makes it both the regression gate for the fold and
the attribution artifact for the day a TPU window opens: if the measured
step is slow AND the audit says the sort count regressed, the cause is
already isolated.

The collective-byte arm (ISSUE 5, "Wire compression") applies the same
honest-accounting pattern to the exchange WIRE: it lowers the tapped
sparse train step over an 8-device mesh at each wire format and sums the
`all_to_all`/`all_gather`/`reduce_scatter` operand bytes from the
StableHLO (`utils.profiling.hlo_collective_bytes`). The bf16 wire must
shrink the float collective bytes of the compiled step by >= 1.9x vs the
f32 wire, and the f32 (default) wire must contain ZERO bf16 collective
operands — both assertable without a TPU.

Usage:
  python tools/hlo_audit.py            # print one JSON line per arm
  python tools/hlo_audit.py --assert   # exit 1 if any folded arm exceeds
                                       # its sort bound, or the wire arm
                                       # misses its byte bound (CI gate)

Library use: ``audit_tapped_step(...)`` / ``audit_exchange_bytes(...)``
return the counts for one configuration; bench.py embeds compact audits
in its JSON records (``hlo_sort_audit``, ``wire_hlo``) so every hardware
measurement carries the op-count fingerprint of the step it timed.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_model(vocab: int, width: int, combiner: str, hot_rows: int = 0,
                 tables: int = 1, mesh=None, exchange_wire=None,
                 dense_head: bool = False):
    """Minimal tapped model (the shape make_sparse_train_step expects)
    around a DistributedEmbedding — THE one copy of this harness, shared
    by the sort-count arms, the collective-byte wire arms, the lookahead
    overlap arm, and bench.py's --mode wire / --mode lookahead A/Bs (via
    _load_hlo_audit), so the audit and the bench always lower the same
    program.

    ``dense_head=True`` puts a real matmul between the embedding outputs
    and the loss (params gain a ``head`` kernel, built by
    ``_head_params``). The lookahead overlap audit classifies collectives
    by dependency on dot ops — without a dot in the module the metric is
    vacuous — and a dense head is what the pipeline overlaps against in
    the first place."""
    import jax.numpy as jnp
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.layers.embedding import Embedding

    class _Tapped:
        def __init__(self, emb):
            self.embedding = emb

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            out = self.embedding(p["embedding"], list(cats), taps=taps,
                                 return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            if dense_head:
                pred = (x.astype(jnp.float32) @ p["head"])[:, 0]
            else:
                pred = jnp.sum(x, axis=1)
            loss = jnp.mean((pred - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    emb = DistributedEmbedding(
        [Embedding(vocab, width, combiner=combiner) for _ in range(tables)],
        mesh=mesh, hot_rows=hot_rows, exchange_wire=exchange_wire)
    return _Tapped(emb)


def _head_params(tables: int, width: int, hotness: int, combiner: str):
    """The replicated dense-head kernel matching _build_model's
    ``dense_head=True`` loss (one output column)."""
    import jax.numpy as jnp
    per = width * (1 if combiner else hotness)
    return jnp.zeros((tables * per, 1), jnp.float32)


def audit_tapped_step(vocab: int = 30_000_000, width: int = 8,
                      batch: int = 8, hotness: int = 4,
                      optimizer: str = "adagrad", strategy: str = "sort",
                      lookup_path: str = None, fold: bool = True,
                      combiner: str = "sum", hot_rows: int = 0) -> dict:
    """Lower one tapped sparse train step (abstract avals — no giant table
    is materialized) and count its StableHLO ops. Returns the counts plus
    the exchange-group count the sort bound is measured against.

    ``hot_rows > 0`` lowers the hot-row-replication step (ISSUE 4): the
    membership split is a searchsorted (binary search) and the replicated
    hot update is a dense scatter — the sort BOUND is identical to the
    hot-less step, which is exactly the acceptance gate ("the hot split
    adds zero sort instructions per exchange group")."""
    import jax
    import jax.numpy as jnp
    from distributed_embeddings_tpu.training import make_sparse_train_step
    from distributed_embeddings_tpu.utils.profiling import hlo_op_counts

    prev = os.environ.get("DET_LOOKUP_PATH")
    try:
        if lookup_path is None:
            os.environ.pop("DET_LOOKUP_PATH", None)
        else:
            os.environ["DET_LOOKUP_PATH"] = lookup_path
        model = _build_model(vocab, width, combiner, hot_rows=hot_rows)
        emb = model.embedding
        init_fn, step_fn = make_sparse_train_step(
            model, optimizer, lr=0.01, strategy=strategy, fold_sort=fold)
        params = jax.eval_shape(
            lambda: {"embedding": emb.init(jax.random.PRNGKey(0))})
        state = jax.eval_shape(init_fn, params)
        num = jax.ShapeDtypeStruct((batch, 1), jnp.float32)
        cats = [jax.ShapeDtypeStruct((batch, hotness), jnp.int32)]
        lab = jax.ShapeDtypeStruct((batch,), jnp.float32)
        lowered = jax.jit(step_fn).lower(params, state, num, cats, lab)
        counts = hlo_op_counts(lowered)
        key = ((hotness, False),)
        groups, _ = emb._exchange_groups_for_key(key)
        n_groups = len(groups)
    finally:
        if prev is None:
            os.environ.pop("DET_LOOKUP_PATH", None)
        else:
            os.environ["DET_LOOKUP_PATH"] = prev
    # the bound the fold ships under: one canonical sort per exchange
    # group, plus the tiled forward gather's inverse-permute sort (the one
    # residual sort — scatter-free inversion needs a second sort op)
    bound = n_groups * (2 if lookup_path == "tiled" else 1)
    return {
        "optimizer": optimizer, "strategy": strategy,
        "lookup_path": lookup_path or "default", "fold": fold,
        "hot_rows": hot_rows,
        "n_exchange_groups": n_groups, "sort_bound": bound,
        **{f"hlo_{k}": v for k, v in counts.items()},
    }


def _ensure_world(n: int = 8) -> int:
    """Request >= n virtual CPU devices (the wire-byte arms lower real
    collectives, which a world-1 model never emits). Must run before the
    backend initializes; returns the device count actually available."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except Exception:  # noqa: BLE001 - backend already up / older jax
        pass
    return len(jax.devices())


def audit_exchange_bytes(wire: str = "f32", vocab: int = 4096,
                         width: int = 32, tables: int = 8, batch: int = 16,
                         hotness: int = 2, optimizer: str = "adagrad",
                         world: int = 8) -> dict:
    """Lower the tapped sparse train step over a `world`-device mesh at
    one exchange-wire format and return its collective-byte accounting
    (plus the per-group padding-report byte fields, so the static claim
    and the compiled HLO can be cross-checked in one record)."""
    import jax
    import jax.numpy as jnp
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.training import make_sparse_train_step
    from distributed_embeddings_tpu.utils.profiling import (
        hlo_collective_bytes, hlo_op_counts)

    devs = jax.devices()
    if len(devs) < world:
        return {"wire": wire, "skipped":
                f"need {world} devices for the meshed lowering, "
                f"have {len(devs)}"}
    mesh = create_mesh(devs[:world])
    model = _build_model(vocab, width, "sum", tables=tables, mesh=mesh,
                         exchange_wire=wire)
    emb = model.embedding
    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.01)
    params = {"embedding": emb.init(jax.random.PRNGKey(0))}
    state = init_fn(params)
    num = jnp.zeros((batch, 1), jnp.float32)
    cats = [jnp.zeros((batch, hotness), jnp.int32) for _ in range(tables)]
    lab = jnp.zeros((batch,), jnp.float32)
    lowered = jax.jit(step_fn).lower(params, state, num, cats, lab)
    text = lowered.as_text()
    bytes_ = hlo_collective_bytes(text)
    rep = emb.exchange_padding_report(hotness=[hotness] * tables)
    return {
        "wire": wire, "optimizer": optimizer, "world": world,
        "vocab": vocab, "width": width, "tables": tables, "batch": batch,
        "hotness": hotness,
        "collective_float_bytes": bytes_["float_bytes"],
        "collective_int_bytes": bytes_["int_bytes"],
        "collective_bytes_by_dtype": bytes_["total"],
        "report_act_bytes": rep["act_bytes"],
        "report_act_bytes_f32": rep["act_bytes_f32"],
        "report_act_wire_reduction": round(rep["act_wire_reduction"], 3),
        "report_exchanged_bytes": rep["exchanged_bytes"],
        "report_true_bytes": rep["true_bytes"],
        "id_narrowed_groups": rep["id_narrowed_groups"],
        **{f"hlo_{k}": v for k, v in hlo_op_counts(text).items()},
    }


def audit_lookahead_overlap(vocab: int = 4096, width: int = 32,
                            tables: int = 4, batch: int = 64,
                            hotness: int = 2, optimizer: str = "adagrad",
                            world: int = 8, stale_ok: bool = False) -> dict:
    """Lower the lookahead engine's FUSED staged step over a
    `world`-device mesh and prove, on the dependency graph of the
    StableHLO, that batch N+1's exchange collectives carry NO data
    dependency on batch N's dense compute (ISSUE 9) — the static twin of
    an ICI/MXU overlap measurement, checkable without hardware.

    Three lowerings, one record:
      * the fused step — its `overlap_candidates` (collectives with dot
        ops on neither side, see profiling.hlo_collective_overlap) must
        cover the whole prefetch stage;
      * the standalone prefetch executable — defines how many
        collectives that stage contains;
      * the monolithic baseline step — must audit to ZERO candidates
        (every exchange is on the dense critical path there), which
        keeps the metric itself honest, and pins the sort bound: the
        fused step must lower with NO extra stablehlo.sort ops vs the
        monolithic step (the PR 2 gate carried over — the patch arm is a
        sort-free plain recompute).
    """
    import jax
    import jax.numpy as jnp
    from distributed_embeddings_tpu.parallel.mesh import create_mesh
    from distributed_embeddings_tpu.schedule import LookaheadEngine
    from distributed_embeddings_tpu.training import make_sparse_train_step
    from distributed_embeddings_tpu.utils.profiling import (
        hlo_collective_overlap, hlo_op_counts)

    devs = jax.devices()
    if len(devs) < world:
        return {"arm": "lookahead_overlap", "skipped":
                f"need {world} devices for the meshed lowering, "
                f"have {len(devs)}"}
    mesh = create_mesh(devs[:world])
    model = _build_model(vocab, width, "sum", tables=tables, mesh=mesh,
                         dense_head=True)
    emb = model.embedding
    params = {"embedding": emb.init(jax.random.PRNGKey(0)),
              "head": _head_params(tables, width, hotness, "sum")}
    engine = LookaheadEngine(model, optimizer, lr=0.01,
                             stale_ok=stale_ok, donate=False)
    state = engine.init(params)
    num = jnp.zeros((batch, 1), jnp.float32)
    cats = [jnp.zeros((batch, hotness), jnp.int32) for _ in range(tables)]
    lab = jnp.zeros((batch,), jnp.float32)
    b0 = (num, cats, lab)

    fused_txt = engine.lower_fused(params, state, b0, b0).as_text()
    pre_txt = engine.lower_prefetch(params, cats).as_text()
    init2, step2 = make_sparse_train_step(model, optimizer, lr=0.01,
                                          donate=False)
    base_txt = jax.jit(step2).lower(params, init2(params), num, cats,
                                    lab).as_text()

    fused_ov = hlo_collective_overlap(fused_txt)
    pre_ov = hlo_collective_overlap(pre_txt)
    base_ov = hlo_collective_overlap(base_txt)
    fused_sorts = hlo_op_counts(fused_txt)["sort"]
    base_sorts = hlo_op_counts(base_txt)["sort"]
    rec = {
        "arm": "lookahead_overlap", "optimizer": optimizer,
        "world": world, "vocab": vocab, "width": width, "tables": tables,
        "batch": batch, "hotness": hotness, "stale_ok": stale_ok,
        "fused_collectives": fused_ov["collectives_total"],
        "fused_overlap_candidates": fused_ov["overlap_candidates"],
        "fused_candidates_by_op": fused_ov["candidates_by_op"],
        "prefetch_collectives": pre_ov["collectives_total"],
        "baseline_collectives": base_ov["collectives_total"],
        "baseline_overlap_candidates": base_ov["overlap_candidates"],
        "fused_sorts": fused_sorts, "baseline_sorts": base_sorts,
        "extra_sorts": fused_sorts - base_sorts,
    }
    rec["over_bound"] = bool(
        rec["prefetch_collectives"] == 0
        or rec["fused_overlap_candidates"] < rec["prefetch_collectives"]
        or rec["baseline_overlap_candidates"] != 0
        or rec["extra_sorts"] > 0)
    return rec


# minimum float-collective-byte shrink the bf16 wire must show vs f32 on
# the same lowered step — the wire moves half the bits, so the compiled
# ratio is 2.0 minus whatever small float traffic is not behind the seam
WIRE_BYTE_MIN_REDUCTION = 1.9


def wire_byte_arms(**kw) -> list:
    """The f32-vs-bf16 collective-byte A/B records (+ derived reduction
    stamped on the bf16 record)."""
    base = audit_exchange_bytes(wire="f32", **kw)
    comp = audit_exchange_bytes(wire="bf16", **kw)
    if "skipped" not in comp and "skipped" not in base:
        fb = base["collective_float_bytes"]
        cb = comp["collective_float_bytes"]
        comp["float_bytes_reduction_vs_f32"] = (
            round(fb / cb, 3) if cb else None)
        comp["min_reduction_required"] = WIRE_BYTE_MIN_REDUCTION
        base["bf16_collective_bytes"] = (
            base["collective_bytes_by_dtype"].get("bf16", 0))
    return [base, comp]


DEFAULT_ARMS = (
    # (optimizer, strategy, lookup_path, hot_rows)
    ("adagrad", "sort", None, 0),
    ("adagrad", "tiled", None, 0),
    ("adam", "sort", None, 0),
    ("sgd", "tiled", None, 0),
    ("adagrad", "tiled", "tiled", 0),
    # hot-row replication (ISSUE 4): same sort bound as the hot-less arm —
    # the membership split (searchsorted) and the replicated dense hot
    # update must add ZERO sort instructions per exchange group
    ("adagrad", "sort", None, 1024),
    ("sgd", "sort", None, 1024),
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--assert", dest="do_assert", action="store_true",
                   help="exit 1 when a folded arm exceeds its sort bound")
    p.add_argument("--vocab", type=int, default=30_000_000)
    p.add_argument("--width", type=int, default=8)
    p.add_argument("--unfolded", action="store_true",
                   help="also report the fold_sort=False baseline arms")
    p.add_argument("--skip-wire", action="store_true",
                   help="skip the meshed collective-byte wire arms")
    p.add_argument("--skip-lookahead", action="store_true",
                   help="skip the meshed lookahead overlap arm")
    args = p.parse_args(argv)

    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS") or "cpu")
    # the wire-byte and lookahead arms lower over an 8-device mesh;
    # virtual devices must be requested BEFORE the first backend touch
    if not (args.skip_wire and args.skip_lookahead):
        _ensure_world(8)
    failures = []
    for optimizer, strategy, lookup, hot_rows in DEFAULT_ARMS:
        folds = (True, False) if args.unfolded else (True,)
        for fold in folds:
            rec = audit_tapped_step(vocab=args.vocab, width=args.width,
                                    optimizer=optimizer, strategy=strategy,
                                    lookup_path=lookup, fold=fold,
                                    hot_rows=hot_rows)
            if fold and rec["hlo_sort"] > rec["sort_bound"]:
                rec["over_bound"] = True
                failures.append(rec)
            print(json.dumps(rec), flush=True)
    if not args.skip_wire:
        arms = wire_byte_arms()
        for rec in arms:
            print(json.dumps(rec), flush=True)
        base, comp = arms
        if "skipped" not in comp:
            # the f32 default must move ZERO bf16 collective bytes (the
            # bit-exactness contract) and the bf16 wire must shrink the
            # float collective bytes of the SAME step by >= 1.9x
            if base.get("bf16_collective_bytes"):
                base["over_bound"] = True
                failures.append(base)
            red = comp.get("float_bytes_reduction_vs_f32")
            if red is None or red < WIRE_BYTE_MIN_REDUCTION:
                comp["over_bound"] = True
                failures.append(comp)
    if not args.skip_lookahead:
        # lookahead overlap arm (ISSUE 9): the fused staged step's
        # prefetch collectives must be dependency-free of the dense
        # compute (overlap candidates >= the whole prefetch stage), the
        # monolithic baseline must audit to zero candidates, and the
        # fused lowering must add ZERO sort ops vs the baseline
        rec = audit_lookahead_overlap()
        print(json.dumps(rec), flush=True)
        if "skipped" not in rec and rec.get("over_bound"):
            failures.append(rec)
    if args.do_assert and failures:
        print(f"hlo_audit: {len(failures)} arm(s) exceed their bound "
              "(sort count or collective bytes)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
