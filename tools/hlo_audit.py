"""HLO op-count audit for the compiled tapped sparse train step.

The sort-folding work (ISSUE 2, docs/perf_model.md "Sort folding") is a
TRACE-TIME property: the folded step must contain at most one stablehlo.sort
per (bucket, hotness) exchange group — one more (the inverse-permute sort)
when the tiled forward gather is active. That is checkable on any backend
without hardware, which makes it both the regression gate for the fold and
the attribution artifact for the day a TPU window opens: if the measured
step is slow AND the audit says the sort count regressed, the cause is
already isolated.

Usage:
  python tools/hlo_audit.py            # print one JSON line per arm
  python tools/hlo_audit.py --assert   # exit 1 if any folded arm exceeds
                                       # its sort bound (CI gate)

Library use: ``audit_tapped_step(...)`` returns the counts for one
configuration; bench.py embeds a compact audit in its JSON record
(``hlo_sort_audit``) so every hardware measurement carries the op-count
fingerprint of the step it timed.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_model(vocab: int, width: int, combiner: str, hot_rows: int = 0):
    import jax.numpy as jnp
    from distributed_embeddings_tpu.layers.dist_model_parallel import (
        DistributedEmbedding)
    from distributed_embeddings_tpu.layers.embedding import Embedding

    class _Tapped:
        """Minimal model shape make_sparse_train_step expects."""

        def __init__(self, emb):
            self.embedding = emb

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            out = self.embedding(p["embedding"], list(cats), taps=taps,
                                 return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            loss = jnp.mean((jnp.sum(x, axis=1) - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    emb = DistributedEmbedding([Embedding(vocab, width, combiner=combiner)],
                               mesh=None, hot_rows=hot_rows)
    return _Tapped(emb)


def audit_tapped_step(vocab: int = 30_000_000, width: int = 8,
                      batch: int = 8, hotness: int = 4,
                      optimizer: str = "adagrad", strategy: str = "sort",
                      lookup_path: str = None, fold: bool = True,
                      combiner: str = "sum", hot_rows: int = 0) -> dict:
    """Lower one tapped sparse train step (abstract avals — no giant table
    is materialized) and count its StableHLO ops. Returns the counts plus
    the exchange-group count the sort bound is measured against.

    ``hot_rows > 0`` lowers the hot-row-replication step (ISSUE 4): the
    membership split is a searchsorted (binary search) and the replicated
    hot update is a dense scatter — the sort BOUND is identical to the
    hot-less step, which is exactly the acceptance gate ("the hot split
    adds zero sort instructions per exchange group")."""
    import jax
    import jax.numpy as jnp
    from distributed_embeddings_tpu.training import make_sparse_train_step
    from distributed_embeddings_tpu.utils.profiling import hlo_op_counts

    prev = os.environ.get("DET_LOOKUP_PATH")
    try:
        if lookup_path is None:
            os.environ.pop("DET_LOOKUP_PATH", None)
        else:
            os.environ["DET_LOOKUP_PATH"] = lookup_path
        model = _build_model(vocab, width, combiner, hot_rows=hot_rows)
        emb = model.embedding
        init_fn, step_fn = make_sparse_train_step(
            model, optimizer, lr=0.01, strategy=strategy, fold_sort=fold)
        params = jax.eval_shape(
            lambda: {"embedding": emb.init(jax.random.PRNGKey(0))})
        state = jax.eval_shape(init_fn, params)
        num = jax.ShapeDtypeStruct((batch, 1), jnp.float32)
        cats = [jax.ShapeDtypeStruct((batch, hotness), jnp.int32)]
        lab = jax.ShapeDtypeStruct((batch,), jnp.float32)
        lowered = jax.jit(step_fn).lower(params, state, num, cats, lab)
        counts = hlo_op_counts(lowered)
        key = ((hotness, False),)
        groups, _ = emb._exchange_groups_for_key(key)
        n_groups = len(groups)
    finally:
        if prev is None:
            os.environ.pop("DET_LOOKUP_PATH", None)
        else:
            os.environ["DET_LOOKUP_PATH"] = prev
    # the bound the fold ships under: one canonical sort per exchange
    # group, plus the tiled forward gather's inverse-permute sort (the one
    # residual sort — scatter-free inversion needs a second sort op)
    bound = n_groups * (2 if lookup_path == "tiled" else 1)
    return {
        "optimizer": optimizer, "strategy": strategy,
        "lookup_path": lookup_path or "default", "fold": fold,
        "hot_rows": hot_rows,
        "n_exchange_groups": n_groups, "sort_bound": bound,
        **{f"hlo_{k}": v for k, v in counts.items()},
    }


DEFAULT_ARMS = (
    # (optimizer, strategy, lookup_path, hot_rows)
    ("adagrad", "sort", None, 0),
    ("adagrad", "tiled", None, 0),
    ("adam", "sort", None, 0),
    ("sgd", "tiled", None, 0),
    ("adagrad", "tiled", "tiled", 0),
    # hot-row replication (ISSUE 4): same sort bound as the hot-less arm —
    # the membership split (searchsorted) and the replicated dense hot
    # update must add ZERO sort instructions per exchange group
    ("adagrad", "sort", None, 1024),
    ("sgd", "sort", None, 1024),
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--assert", dest="do_assert", action="store_true",
                   help="exit 1 when a folded arm exceeds its sort bound")
    p.add_argument("--vocab", type=int, default=30_000_000)
    p.add_argument("--width", type=int, default=8)
    p.add_argument("--unfolded", action="store_true",
                   help="also report the fold_sort=False baseline arms")
    args = p.parse_args(argv)

    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS") or "cpu")
    failures = []
    for optimizer, strategy, lookup, hot_rows in DEFAULT_ARMS:
        folds = (True, False) if args.unfolded else (True,)
        for fold in folds:
            rec = audit_tapped_step(vocab=args.vocab, width=args.width,
                                    optimizer=optimizer, strategy=strategy,
                                    lookup_path=lookup, fold=fold,
                                    hot_rows=hot_rows)
            if fold and rec["hlo_sort"] > rec["sort_bound"]:
                rec["over_bound"] = True
                failures.append(rec)
            print(json.dumps(rec), flush=True)
    if args.do_assert and failures:
        print(f"hlo_audit: {len(failures)} folded arm(s) exceed the sort "
              "bound", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
