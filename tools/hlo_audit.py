"""Static program auditor driver: the full pass suite over the standard
program matrix (ISSUE 10).

The heavy lifting lives in `distributed_embeddings_tpu.analysis`:
`ir` parses a lowered StableHLO module ONCE, `passes` proves the repo's
invariants over it (sort bounds, exact collective bytes vs the
padding-report model, overlap classification, wire-seam coverage,
donation policy, dtype promotion, dead/duplicate collectives — run
``--list-passes`` for the catalog, docs/analysis.md for the long form),
and `programs` builds the audited matrix: monolithic train step (f32 +
bf16 wire), lookahead fused + prefetch, serve forward, vocab-slack
plan, each lowered once over an 8-virtual-device mesh and shared across
all passes (the <=60s CI budget).

This file is the thin CLI on top:

  python tools/hlo_audit.py                # one JSON line per record
  python tools/hlo_audit.py --assert      # CI gate: exit 1 on any
                                           # finding not allowlisted in
                                           # tools/audit_baseline.json,
                                           # any legacy arm over bound,
                                           # or any mutation fixture its
                                           # pass FAILS to flag
  python tools/hlo_audit.py --list-passes  # pass catalog

The baseline (``tools/audit_baseline.json``) is a checked-in allowlist
of ``"program:finding-id"`` strings, diffed like a snapshot — it ships
EMPTY: every known invariant violation is a bug, not an exception. The
mutation arm is the auditor auditing itself: for every pass, a program
seeded with the violation it exists to catch (a naked lax.all_to_all
around the seam, a forced f64 upcast, a self-duplicated collective, ...)
must produce exactly the expected finding — an auditor that cannot fail
is not a gate.

Legacy per-arm records (`audit_tapped_step` sort gates at 30M-row
vocabs/tiled/hot shards, `wire_byte_arms`, `audit_lookahead_overlap`)
still run and still gate: bench.py embeds them in every hardware record
so each measurement carries the op-count fingerprint of the step it
timed.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_embeddings_tpu.analysis import programs as _programs  # noqa: E402
from distributed_embeddings_tpu.analysis import ir, passes  # noqa: E402

# bench.py and the test suite reach these by their historical names
_build_model = _programs.build_model
_head_params = _programs.head_params
_ensure_world = _programs.ensure_world
audit_tapped_step = _programs.audit_tapped_step
audit_exchange_bytes = _programs.audit_exchange_bytes
audit_lookahead_overlap = _programs.audit_lookahead_overlap
wire_byte_arms = _programs.wire_byte_arms
WIRE_BYTE_MIN_REDUCTION = _programs.WIRE_BYTE_MIN_REDUCTION

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "audit_baseline.json")

DEFAULT_ARMS = (
    # (optimizer, strategy, lookup_path, hot_rows)
    ("adagrad", "sort", None, 0),
    ("adagrad", "tiled", None, 0),
    ("adam", "sort", None, 0),
    ("sgd", "tiled", None, 0),
    ("adagrad", "tiled", "tiled", 0),
    # fused pallas strategy (ISSUE 12): the deduped-row tile walk must
    # consume the folded forward sort — same one-sort-per-group bound as
    # the sort/tiled arms; the fully-fused arm (fused forward + pallas
    # update) shares the tiled-forward 2/group bound (the residual
    # inverse-permute sort)
    ("adagrad", "pallas", None, 0),
    ("adam", "pallas", None, 0),
    ("sgd", "pallas", None, 0),
    ("adagrad", "pallas", "fused", 0),
    # hot-row replication (ISSUE 4): same sort bound as the hot-less arm —
    # the membership split (searchsorted) and the replicated dense hot
    # update must add ZERO sort instructions per exchange group
    ("adagrad", "sort", None, 1024),
    ("sgd", "sort", None, 1024),
)


def load_baseline(path: str = BASELINE_PATH) -> set:
    """The allowlist: a set of "program:finding-id" strings."""
    try:
        with open(path) as f:
            return set(json.load(f).get("allow", []))
    except FileNotFoundError:
        return set()


def run_matrix(baseline: set, **kw) -> tuple:
    """Lower the program matrix once, run every applicable pass on each
    parsed module; returns (records, failures) where a failure is any
    finding whose "program:fid" key is not allowlisted."""
    records, failures = [], []
    for prog in _programs.program_matrix(**kw):
        names = [n for n in passes.PASS_REGISTRY
                 if n not in prog.skip_passes]
        findings = passes.run_passes(prog.module, prog.ctx, passes=names)
        rec = {"program": prog.name, "passes_run": len(names),
               "findings": [f.to_dict() for f in findings]}
        for f in findings:
            key = f"{prog.name}:{f.fid}"
            if key not in baseline:
                failures.append({"program": prog.name, **f.to_dict()})
        records.append(rec)
    return records, failures


def run_mutations() -> tuple:
    """Every pass must FLAG its seeded violation — a mutation that does
    NOT produce exactly its expected findings is itself a failure (the
    gate went blind)."""
    records, failures = [], []
    for case in _programs.mutation_cases():
        mod = ir.parse_module(case.text)
        got = tuple(f.fid for f in passes.run_passes(
            mod, case.ctx, passes=[case.pass_name]))
        ok = got == case.expect_fids
        rec = {"mutation": case.name, "pass": case.pass_name,
               "expected_findings": list(case.expect_fids),
               "got_findings": list(got), "flagged": ok}
        records.append(rec)
        if not ok:
            failures.append(rec)
    return records, failures


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--assert", dest="do_assert", action="store_true",
                   help="exit 1 on any non-allowlisted finding, legacy "
                        "arm over bound, or unflagged mutation")
    p.add_argument("--list-passes", action="store_true",
                   help="print the pass catalog and exit")
    p.add_argument("--baseline", default=BASELINE_PATH,
                   help="allowlist JSON (default tools/audit_baseline.json)")
    p.add_argument("--vocab", type=int, default=30_000_000)
    p.add_argument("--width", type=int, default=8)
    p.add_argument("--unfolded", action="store_true",
                   help="also report the fold_sort=False baseline arms")
    p.add_argument("--skip-wire", action="store_true",
                   help="skip the meshed collective-byte wire arms")
    p.add_argument("--skip-lookahead", action="store_true",
                   help="skip the meshed lookahead overlap arm")
    p.add_argument("--skip-matrix", action="store_true",
                   help="skip the pass-framework program matrix")
    p.add_argument("--skip-mutations", action="store_true",
                   help="skip the mutation-fixture self-check")
    args = p.parse_args(argv)

    if args.list_passes:
        for name, doc in passes.list_passes():
            print(f"{name:22s} {doc}")
        return 0

    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS") or "cpu")
    # meshed lowerings need the virtual world BEFORE the backend wakes
    if not (args.skip_wire and args.skip_lookahead and args.skip_matrix
            and args.skip_mutations):
        _ensure_world(8)
    failures = []

    # ---- legacy per-arm sort gates (bench.py embeds the same records)
    for optimizer, strategy, lookup, hot_rows in DEFAULT_ARMS:
        folds = (True, False) if args.unfolded else (True,)
        for fold in folds:
            rec = audit_tapped_step(vocab=args.vocab, width=args.width,
                                    optimizer=optimizer, strategy=strategy,
                                    lookup_path=lookup, fold=fold,
                                    hot_rows=hot_rows)
            if fold and rec["hlo_sort"] > rec["sort_bound"]:
                rec["over_bound"] = True
                failures.append(rec)
            print(json.dumps(rec), flush=True)

    # ---- legacy wire byte arms (ratio + zero-bf16 contract)
    if not args.skip_wire:
        arms = wire_byte_arms()
        for rec in arms:
            print(json.dumps(rec), flush=True)
        base, comp = arms
        if "skipped" not in comp:
            if base.get("bf16_collective_bytes"):
                base["over_bound"] = True
                failures.append(base)
            red = comp.get("float_bytes_reduction_vs_f32")
            if red is None or red < WIRE_BYTE_MIN_REDUCTION:
                comp["over_bound"] = True
                failures.append(comp)

    # ---- legacy lookahead overlap arm
    if not args.skip_lookahead:
        rec = audit_lookahead_overlap()
        print(json.dumps(rec), flush=True)
        if "skipped" not in rec and rec.get("over_bound"):
            failures.append(rec)

    # ---- the pass-framework matrix (ISSUE 10)
    if not args.skip_matrix:
        baseline = load_baseline(args.baseline)
        records, fs = run_matrix(baseline)
        for rec in records:
            print(json.dumps(rec), flush=True)
        failures.extend(fs)

    # ---- mutation self-check: every pass must flag its seeded violation
    if not args.skip_mutations:
        records, fs = run_mutations()
        print(json.dumps({
            "mutations_total": len(records),
            "mutations_flagged": sum(r["flagged"] for r in records),
            "unflagged": [r for r in records if not r["flagged"]],
        }), flush=True)
        failures.extend(fs)

    if args.do_assert and failures:
        print(f"hlo_audit: {len(failures)} failure(s) — non-allowlisted "
              "findings, arms over bound, or blind mutation gates",
              file=sys.stderr)
        for f in failures:
            print(f"  {json.dumps(f)[:300]}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
