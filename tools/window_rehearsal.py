"""End-to-end CPU rehearsal of the unattended hardware-window pipeline.

VERDICT r5 weak #5: the window's plan rides on search → config-of-record
write → dispatch flip firing correctly in a single unattended window,
and a plumbing bug discovered DURING the first real window is the single
most expensive failure mode available. Since ISSUE 18 the whole
measure→decide loop lives in ONE place — ``bench.py --mode tune`` — so
this script is a thin wrapper over it rather than a second copy of the
stage list and knob choreography it used to carry:

  1. run ``bench.py --mode tune --rehearse`` (CPU backend, tiny shapes,
     scratch output dir) and assert the emitted record: schema-valid
     tuned-config-v1 via the REAL validator, prune-ordering audit green,
     a non-empty prune log (no silent caps), and >= 2 measured arms
     including the defaults baseline.
  2. dispatch flip: a FRESH python process pointed at the record via
     ``DET_TUNED_PATH`` asserts ``measured_default()`` output actually
     changed (and stays the fallback without it) — the end the whole
     pipeline exists to reach. CPU arms cannot genuinely win, so the
     flip check runs against a copy of the real record grafted with a
     synthetic winner (marked ``rehearsal_synthetic_winner``); what is
     rehearsed is the READER seam, not the CPU's timing verdict.

Writes tools/window_rehearsal_cpu.out (the committed green-log artifact)
and prints one JSON line. Exit 0 = every stage green.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# .out, not .log: the green-log artifact is committed (like the watcher's
# watch_<stage>_r05.out files) and *.log is gitignored
LOG_PATH = os.path.join(ROOT, "tools", "window_rehearsal_cpu.out")

TUNE_TIMEOUT_S = 1700


class _Log:
    def __init__(self, path):
        self.f = open(path, "w")

    def line(self, msg):
        stamp = time.strftime("%H:%M:%S")
        print(f"{stamp} {msg}", flush=True)
        self.f.write(f"{stamp} {msg}\n")
        self.f.flush()


def run_tune_rehearsal(log, outdir):
    """One ``bench.py --mode tune --rehearse`` subprocess; returns the
    emitted record after asserting the artifact contract."""
    art = os.path.join(outdir, "watch_tune_rehearsal.out")
    env = dict(os.environ, DET_BENCH_FORCE_CPU="1", JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-u", "bench.py", "--mode", "tune",
           "--rehearse", "--out", outdir]
    log.line(f"running tune ({' '.join(cmd[2:])}, "
             f"timeout {TUNE_TIMEOUT_S}s)")
    t0 = time.perf_counter()
    with open(art, "w") as f:
        p = subprocess.run(cmd, stdout=f, stderr=subprocess.STDOUT,
                           timeout=TUNE_TIMEOUT_S, env=env, cwd=ROOT)
    wall = time.perf_counter() - t0
    log.line(f"tune rc={p.returncode} wall={wall:.0f}s -> {art}")
    assert p.returncode == 0, f"tune stage failed (rc={p.returncode})"
    records = []
    with open(art) as f:
        for ln in f:
            if ln.startswith("{"):
                try:
                    records.append(json.loads(ln))
                except ValueError:
                    pass
    assert records, f"tune stage left no JSON artifact in {art}"
    record = records[-1]
    assert record.get("rehearsal") is True, record.get("metric")
    assert not record.get("tune_error"), record["tune_error"]
    return record


def check_record(log, record):
    """Assert the config-of-record through the REAL reader-side
    validator, plus the evidence-trail gates the CI tune smoke uses."""
    from distributed_embeddings_tpu.tune import search as tune_search

    path = record.get("tuned_path")
    assert path and os.path.exists(path), f"no config-of-record at {path}"
    with open(path) as f:
        doc = json.load(f)
    errors = tune_search.validate_tuned_record(doc)
    assert not errors, f"schema-invalid record: {errors}"
    assert doc["prune_audit_ok"] is True, "prune-ordering audit failed"
    assert doc["pruned"], "empty prune log: the search never pruned " \
        "anything, or pruned silently"
    measured = [a for a in doc["arms"] if "step_ms" in a]
    assert len(measured) >= 2, f"need >= 2 measured arms, have " \
        f"{[a['key'] for a in measured]}"
    assert any(a["key"] == "defaults" for a in measured), \
        "defaults baseline was not measured"
    log.line(f"record OK -> {path} (winner={doc['winner']}, "
             f"{len(measured)} measured, {len(doc['pruned'])} pruned, "
             f"{len(doc['staged_tpu_arms'])} staged TPU arm(s))")
    return doc


def rehearse_dispatch_flip(log, doc, outdir):
    """Assert a tuned record changes measured_default() output in a
    fresh process via DET_TUNED_PATH, and that without it the fallback
    still rules — both directions of the flip. The copy under test
    grafts a synthetic winner (CPU cannot genuinely win tiled arms);
    the READER seam is what is being rehearsed."""
    flip_doc = dict(doc)
    flip_doc["winner"] = {"DET_SCATTER_IMPL": "tiled"}
    flip_doc["rehearsal_synthetic_winner"] = True
    flip_path = os.path.join(outdir, "flip_rehearsal.json")
    with open(flip_path, "w") as f:
        json.dump(flip_doc, f)
    code = (
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        f"sys.path.insert(0, {ROOT!r})\n"
        "from distributed_embeddings_tpu.ops import sparse_update as su\n"
        "print(su.measured_default('DET_SCATTER_IMPL', 'xla'))\n"
    )
    for tuned_path, want in ((flip_path, "tiled"), (None, "xla")):
        env = dict(os.environ)
        env.pop("DET_TUNED_PATH", None)
        env.pop("DET_TUNED_WORKLOAD", None)
        env.pop("DET_SCATTER_IMPL", None)
        if tuned_path is not None:
            env["DET_TUNED_PATH"] = tuned_path
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=300,
                           env=env, cwd=ROOT)
        got = p.stdout.strip().splitlines()[-1] if p.stdout.strip() else ""
        assert p.returncode == 0 and got == want, (
            f"flip check with DET_TUNED_PATH={tuned_path}: want {want!r}, "
            f"got {got!r} (rc={p.returncode}, stderr={p.stderr[-300:]})")
    log.line("dispatch flip OK: measured_default() = tiled with the "
             "record, fallback without")


def main() -> int:
    log = _Log(LOG_PATH)
    log.line("window rehearsal start (CPU backend, tiny shapes, "
             "--mode tune --rehearse)")
    summary = {"started": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime())}
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="det_rehearsal_") as outdir:
        record = run_tune_rehearsal(log, outdir)
        doc = check_record(log, record)
        rehearse_dispatch_flip(log, doc, outdir)
        summary.update({
            "stages": ["tune"],
            "tune_workload": doc["workload"],
            "tune_winner": doc["winner"],
            "tune_measured_arms": sum(1 for a in doc["arms"]
                                      if "step_ms" in a),
            "tune_pruned": len(doc["pruned"]),
            "tune_prune_audit_ok": doc["prune_audit_ok"],
            "tune_staged_tpu_arms": len(doc["staged_tpu_arms"]),
            "flip_verified": True,
            "wall_s": round(time.perf_counter() - t0, 1),
        })
    summary["verdict"] = "GREEN"
    log.line(f"rehearsal GREEN in {summary['wall_s']}s")
    print(json.dumps(summary), flush=True)
    log.f.write(json.dumps(summary) + "\n")
    log.f.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
