"""End-to-end CPU rehearsal of the unattended hardware-window pipeline.

VERDICT r5 weak #5: the round's whole plan rides on watcher-recovery →
quickab → bench → measured_defaults.json write → dispatch flip firing
correctly in a single unattended window, and the composed sequence had run
zero times — "a plumbing bug discovered DURING the first real window is the
single most expensive failure mode available". This script executes the
same composition on the CPU backend, tiny shapes, asserting each stage's
artifact:

  1. stage-runner: the claim watcher's `cmd:timeout:name` stage loop
     (claim_watch_r05.sh) over a rehearsal stage list — quickab first
     (DET_QUICKAB_ALLOW_CPU=1, shrunken batch), then the full bench
     (DET_BENCH_FORCE_CPU=1). Asserts each stage exits 0 and leaves its
     JSON artifact, exactly like `tools/watch_<name>_r05.out`.
  2. defaults-writer: the REAL `bench._maybe_write_measured_defaults`
     (DET_BENCH_ALLOW_CPU_DEFAULTS_WRITE=1) against a scratch defaults
     path, fed the real bench record with synthetic winning tiled margins
     (marked `rehearsal_synthetic_arms`; CPU cannot produce real tiled
     wins). Asserts the knob values + provenance land in the file.
  3. dispatch flip: a FRESH python process with
     DET_MEASURED_DEFAULTS_CONSULT=1 pointed at the scratch file asserts
     `measured_default()` output actually changed (and stays the fallback
     without the file) — the end the whole pipeline exists to reach.

Writes tools/window_rehearsal_cpu.log (the committed green-log artifact)
and prints one JSON line. Exit 0 = every stage green.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# .out, not .log: the green-log artifact is committed (like the watcher's
# watch_<stage>_r05.out files) and *.log is gitignored
LOG_PATH = os.path.join(ROOT, "tools", "window_rehearsal_cpu.out")

# the watcher's stage format, verbatim (cmd:timeout_secs:name) — parsing
# and dispatch below mirror claim_watch_r05.sh's loop
REHEARSAL_STAGES = """\
tools/quick_tiled_ab.py:1500:quickab
bench.py:1700:bench
"""

STAGE_ENV = {
    "quickab": {"DET_QUICKAB_ALLOW_CPU": "1", "DET_QUICKAB_BATCH": "256",
                "DET_QUICKAB_ITERS": "2", "JAX_PLATFORMS": "cpu"},
    "bench": {"DET_BENCH_FORCE_CPU": "1", "DET_BENCH_INNER": "1",
              "DET_BENCH_SKIP_BUSY_WAIT": "1"},
}


class _Log:
    def __init__(self, path):
        self.f = open(path, "w")

    def line(self, msg):
        stamp = time.strftime("%H:%M:%S")
        print(f"{stamp} {msg}", flush=True)
        self.f.write(f"{stamp} {msg}\n")
        self.f.flush()


def run_stages(log, outdir):
    """The claim watcher's stage loop, rehearsed: one killable subprocess
    per `cmd:timeout:name` line, artifact to watch_<name>_rehearsal.out."""
    records = {}
    for line in REHEARSAL_STAGES.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        cmd, secs, name = line.rsplit(":", 2)
        art = os.path.join(outdir, f"watch_{name}_rehearsal.out")
        env = dict(os.environ, **STAGE_ENV.get(name, {}))
        log.line(f"running {name} ({cmd}, timeout {secs}s)")
        t0 = time.perf_counter()
        with open(art, "w") as f:
            p = subprocess.run([sys.executable, "-u"] + cmd.split(),
                               stdout=f, stderr=subprocess.STDOUT,
                               timeout=int(secs), env=env, cwd=ROOT)
        wall = time.perf_counter() - t0
        log.line(f"{name} rc={p.returncode} wall={wall:.0f}s -> {art}")
        assert p.returncode == 0, f"stage {name} failed (rc={p.returncode})"
        # artifact contract: at least one JSON line, like the watcher's
        # grep '"metric"' gate on the bench stage
        json_lines = []
        with open(art) as f:
            for ln in f:
                if ln.startswith("{"):
                    try:
                        json_lines.append(json.loads(ln))
                    except ValueError:
                        pass
        assert json_lines, f"stage {name} left no JSON artifact in {art}"
        records[name] = json_lines[-1]
    assert "tiny_default_ms" in records["quickab"], records["quickab"]
    assert "metric" in records["bench"] and "value" in records["bench"], (
        records["bench"])
    assert not records["bench"].get("cached"), (
        "bench stage emitted a CACHED record during rehearsal")
    return records


def rehearse_defaults_write(log, bench_record, defaults_path):
    """Run the real measured-defaults writer against a scratch path.

    CPU arms cannot genuinely win, so the margins rule is fed synthetic
    winning tiled arms grafted onto the real record — marked as such. What
    is being rehearsed is the WRITER: margin arithmetic, provenance
    fields, file shape, and the flip surface the reader consumes."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "det_bench", os.path.join(ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    record = dict(bench_record)
    record.update({
        "rehearsal_synthetic_arms": True,
        "tiny_best_path": "tiled-fwd+bwd",
        "dlrm_best_path": "tiled-fwd+bwd",
        "tiny_ab_default_ms": 100.0, "tiny_ab_tiled_ms": 50.0,
        "tiny_ab_tiled_full_ms": 40.0,
        "dlrm_ab_sort_ms": 100.0, "dlrm_ab_tiled_ms": 60.0,
        "dlrm_ab_tiled_full_ms": 55.0,
    })
    os.environ["DET_BENCH_ALLOW_CPU_DEFAULTS_WRITE"] = "1"
    try:
        bench._MEASURED_DEFAULTS_PATH = defaults_path
        bench._maybe_write_measured_defaults(record)
    finally:
        os.environ.pop("DET_BENCH_ALLOW_CPU_DEFAULTS_WRITE", None)
    assert record.get("measured_defaults_written") == {
        "DET_SCATTER_IMPL": "tiled", "DET_LOOKUP_PATH": "tiled"}, (
        f"writer did not flip both knobs: "
        f"{record.get('measured_defaults_written')}")
    with open(defaults_path) as f:
        data = json.load(f)
    for knob in ("DET_SCATTER_IMPL", "DET_LOOKUP_PATH"):
        assert data[knob]["value"] == "tiled", data
        assert "git_sha" in data[knob] and "evidence" in data[knob], data
        margins = data[knob]["evidence"]["margins"]
        assert all(m is not None and m >= 1.03 for m in margins.values()), (
            f"writer flipped on sub-threshold margins: {margins}")
    log.line(f"defaults write OK -> {defaults_path} "
             f"({sorted(data)} with provenance)")
    return data


def rehearse_dispatch_flip(log, defaults_path):
    """Assert the written file changes measured_default() output in a fresh
    process (the reader caches per process), and that WITHOUT the file the
    fallback still rules — both directions of the flip."""
    code = (
        "import os, sys\n"
        "os.environ['DET_MEASURED_DEFAULTS_CONSULT'] = '1'\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        f"sys.path.insert(0, {ROOT!r})\n"
        "from distributed_embeddings_tpu.ops import sparse_update as su\n"
        "impl = su.measured_default('DET_SCATTER_IMPL', 'xla')\n"
        "path = su.measured_default('DET_LOOKUP_PATH', 'auto')\n"
        "print(impl, path)\n"
    )
    for path, want in ((defaults_path, "tiled tiled"),
                       (os.devnull, "xla auto")):
        env = dict(os.environ, DET_MEASURED_DEFAULTS_PATH=path)
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=300,
                           env=env, cwd=ROOT)
        got = p.stdout.strip().splitlines()[-1] if p.stdout.strip() else ""
        assert p.returncode == 0 and got == want, (
            f"flip check against {path}: want {want!r}, got {got!r} "
            f"(rc={p.returncode}, stderr={p.stderr[-300:]})")
    log.line("dispatch flip OK: measured_default() = tiled with the file, "
             "fallback without")


def main() -> int:
    log = _Log(LOG_PATH)
    log.line("window rehearsal start (CPU backend, tiny shapes)")
    summary = {"started": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="det_rehearsal_") as outdir:
        records = run_stages(log, outdir)
        defaults_path = os.path.join(outdir, "measured_defaults.json")
        data = rehearse_defaults_write(log, records["bench"], defaults_path)
        rehearse_dispatch_flip(log, defaults_path)
        summary.update({
            "stages": sorted(records),
            "quickab_tiny_default_ms": records["quickab"].get(
                "tiny_default_ms"),
            "bench_metric": records["bench"].get("metric"),
            "bench_value_ms": records["bench"].get("value"),
            "bench_hlo_sort_audit": records["bench"].get("hlo_sort_audit"),
            "defaults_knobs_written": sorted(data),
            "flip_verified": True,
            "wall_s": round(time.perf_counter() - t0, 1),
        })
    summary["verdict"] = "GREEN"
    log.line(f"rehearsal GREEN in {summary['wall_s']}s")
    print(json.dumps(summary), flush=True)
    log.f.write(json.dumps(summary) + "\n")
    log.f.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
