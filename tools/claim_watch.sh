#!/bin/bash
# TPU claim watcher (round 3).
# Probes the axon tunnel every 4 minutes with a killable subprocess.
# On the FIRST successful probe it runs the full serialized validation
# pipeline (tools/tpu_validate.py) and then bench.py, committing artifacts.
# Serializes all TPU access: never runs two TPU-touching processes at once.
# Log: /tmp/claim_watch_r03.log   Sentinel on success: /tmp/tpu_alive_r03
set -u
LOG=/tmp/claim_watch_r03.log
cd /root/repo
echo "$(date +%H:%M:%S) watcher start" >> "$LOG"
n=0
while true; do
  n=$((n+1))
  # the probe must see a real accelerator: JAX can silently fall back to
  # the CPU backend (exit 0, [CpuDevice(0)]) — that is NOT a live tunnel
  if timeout 90 python -c "
import jax
d = jax.devices()
print(d)
assert d and d[0].platform != 'cpu', f'cpu fallback: {d}'
" >> "$LOG" 2>&1; then
    echo "$(date +%H:%M:%S) probe $n SUCCESS — tunnel alive" >> "$LOG"
    touch /tmp/tpu_alive_r03
    echo "$(date +%H:%M:%S) running tpu_validate" >> "$LOG"
    timeout 3600 python tools/tpu_validate.py >> "$LOG" 2>&1
    rc_val=$?
    echo "$(date +%H:%M:%S) tpu_validate rc=$rc_val" >> "$LOG"
    echo "$(date +%H:%M:%S) running bench.py" >> "$LOG"
    timeout 3600 python bench.py > /tmp/bench_r03_out.json 2>> "$LOG"
    rc_bench=$?
    echo "$(date +%H:%M:%S) bench rc=$rc_bench" >> "$LOG"
    # success sentinel only when the measurements actually landed
    if [ "$rc_bench" -eq 0 ] && [ -s /tmp/bench_r03_out.json ]; then
      touch /tmp/tpu_measured_r03
      exit 0
    fi
    echo "$(date +%H:%M:%S) measurement failed; resuming watch" >> "$LOG"
  else
    echo "$(date +%H:%M:%S) probe $n failed" >> "$LOG"
  fi
  sleep 240
done
