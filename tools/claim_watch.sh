#!/bin/bash
# TPU claim watcher (round 3, phase 2 — post-first-measurement).
# The round's headline numbers landed (tools/tpu_validate_out.json, commit
# a2b335e); the tunnel then wedged again. On recovery this watcher runs the
# remaining OPEN measurements, cheapest-first, each in its own killable
# subprocess:
#   1. tpu_mosaic_probe   — which Pallas feature crashes the compile helper
#   2. tpu_scatter_probe  — unique/sorted scatter-gather flag effect
#   3. tpu_pallas_check   — kernel vs XLA timing with the FIXED slope timer
#   4. bench.py           — refreshed headline (picks up sparse-update tuning)
# Logs: /root/repo/tools/claim_watch_r03c.log  Sentinel: /tmp/tpu_alive_r03c
set -u
LOG=/root/repo/tools/claim_watch_r03c.log
BUSY=/tmp/det_tpu_busy
# hard deadline: stop probing well before the driver's round-end bench so
# the two never fight over the single chip claim (driver deadline ~15:44)
DEADLINE_EPOCH=${DET_WATCH_DEADLINE:-$(date -d "2026-07-31 14:15 UTC" +%s)}
cd /root/repo
export JAX_COMPILATION_CACHE_DIR=/tmp/jax_cache_det_tpu
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1
echo "$(date +%H:%M:%S) watcher start (phase 2)" >> "$LOG"
n=0
while true; do
  if [ "$(date +%s)" -ge "$DEADLINE_EPOCH" ]; then
    echo "$(date +%H:%M:%S) deadline reached; watcher exits" >> "$LOG"
    rm -f "$BUSY"
    exit 0
  fi
  n=$((n+1))
  # the probe must see a real accelerator: JAX can silently fall back to
  # the CPU backend (exit 0, [CpuDevice(0)]) — that is NOT a live tunnel
  if timeout 90 python -c "
import jax
d = jax.devices()
print(d)
assert d and d[0].platform != 'cpu', f'cpu fallback: {d}'
import jax.numpy as jnp
print('fetch', float(jnp.sum(jnp.ones((128, 128)) @ jnp.ones((128, 128)))))
" >> "$LOG" 2>&1; then
    echo "$(date +%H:%M:%S) probe $n SUCCESS — tunnel alive" >> "$LOG"
    touch /tmp/tpu_alive_r03c
    bench_rc=1
    echo $$ > "$BUSY"  # bench.py's supervisor waits while this pid is live
    trap 'rm -f "$BUSY"' EXIT
    for stage in "tools/tpu_mosaic_probe.py:900:mosaic" \
                 "tools/tpu_scatter_probe.py:2700:scatter" \
                 "tools/tpu_pallas_check.py --quick:2700:pallas" \
                 "bench.py:7200:bench"; do
      if [ "$(date +%s)" -ge "$DEADLINE_EPOCH" ]; then
        echo "$(date +%H:%M:%S) deadline mid-stages; stopping" >> "$LOG"
        break
      fi
      cmd=${stage%%:*}; rest=${stage#*:}; secs=${rest%%:*}; name=${rest#*:}
      echo "$(date +%H:%M:%S) running $name" >> "$LOG"
      # shellcheck disable=SC2086
      DET_BENCH_SKIP_BUSY_WAIT=1 timeout "$secs" python -u $cmd \
        > "tools/watch_${name}_r03c.out" 2>&1
      rc=$?
      echo "$(date +%H:%M:%S) $name rc=$rc" >> "$LOG"
      [ "$name" = bench ] && bench_rc=$rc
      sleep 20
    done
    rm -f "$BUSY"
    # success sentinel only when the headline measurement actually landed
    # (a fresh one, not the cached-record fallback)
    # timestamp whatever landed (even partial stages are evidence);
    # pathspec-limited commit: must not sweep unrelated staged work in
    git add -- tools/watch_*_r03c.out tools/bench_last_tpu.json \
        tools/claim_watch_r03c.log 2>/dev/null || true
    git commit -q -m "Hardware window artifacts (claim watcher)" \
        -- tools/watch_*_r03c.out tools/bench_last_tpu.json \
        tools/claim_watch_r03c.log 2>/dev/null || true
    if [ "$bench_rc" -eq 0 ] \
       && grep -q '"metric"' tools/watch_bench_r03c.out \
       && ! grep -q '"cached": true' tools/watch_bench_r03c.out; then
      touch /tmp/tpu_measured_r03c
      exit 0
    fi
    echo "$(date +%H:%M:%S) measurement did not land; resuming watch" >> "$LOG"
  else
    echo "$(date +%H:%M:%S) probe $n failed" >> "$LOG"
  fi
  sleep 240
done
