"""Bisect WHICH Pallas/Mosaic feature crashes this tunnel's compile helper.

Round-3 hardware: the one-hot MXU kernel compiles and runs, but every
DMA-gather kernel compile dies with `remote_compile HTTP 500:
tpu_compile_helper subprocess exit code 1` (no Mosaic diagnostic crosses the
tunnel). The same kernels compiled in round 2's hardware window, so the
toolchain changed. This probe compiles a ladder of minimal kernels, each
adding ONE feature the DMA kernel uses, and reports the first rung that
fails:

  1. vmem      — trivial VMEM elementwise kernel (control)
  2. anyspace  — table input left in ANY (HBM) memory space, static slice
  3. dma       — one explicit make_async_copy HBM->VMEM + semaphore
  4. dyn_dma   — async copy with a DYNAMIC row index (table.at[row])
  5. prefetch  — PrefetchScalarGridSpec with ids in SMEM driving the index
  6. loop_dma  — fori_loop issuing start()/wait() pairs (the full pattern)

Each rung compiles in a fresh jit; failures print the rung name + error head
and continue, so one run gives the full feature matrix.

Usage: python tools/tpu_mosaic_probe.py
"""

import functools
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

V, W, B = 4096, 128, 256


def rung_vmem():
    def kern(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0

    x = jnp.ones((B, W), jnp.float32)
    out = pl.pallas_call(
        kern, out_shape=jax.ShapeDtypeStruct((B, W), jnp.float32))(x)
    assert float(out[0, 0]) == 2.0


def rung_anyspace():
    def kern(t_ref, o_ref, s_ref):
        pltpu.make_async_copy(t_ref.at[0:B], s_ref, None)  # build only
        o_ref[:] = jnp.zeros_like(o_ref)

    t = jnp.ones((V, W), jnp.float32)
    out = pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_shape=jax.ShapeDtypeStruct((B, W), jnp.float32),
        scratch_shapes=[pltpu.VMEM((B, W), jnp.float32)],
    )(t)
    assert out.shape == (B, W)


def rung_dma():
    def kern(t_ref, o_ref, s_ref, sem):
        cp = pltpu.make_async_copy(t_ref.at[0:B], s_ref, sem)
        cp.start()
        cp.wait()
        o_ref[:] = s_ref[:]

    t = jnp.full((V, W), 3.0, jnp.float32)
    out = pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_shape=jax.ShapeDtypeStruct((B, W), jnp.float32),
        scratch_shapes=[pltpu.VMEM((B, W), jnp.float32),
                        pltpu.SemaphoreType.DMA],
    )(t)
    assert float(out[0, 0]) == 3.0


def rung_dyn_dma():
    def kern(i_ref, t_ref, o_ref, s_ref, sem):
        row = i_ref[0]
        cp = pltpu.make_async_copy(t_ref.at[row], s_ref.at[0], sem)
        cp.start()
        cp.wait()
        o_ref[:] = s_ref[:]

    t = jnp.full((V, W), 5.0, jnp.float32)
    idx = jnp.asarray([7], jnp.int32)
    out = pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_shape=jax.ShapeDtypeStruct((1, W), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, W), jnp.float32),
                        pltpu.SemaphoreType.DMA],
    )(idx, t)
    assert float(out[0, 0]) == 5.0


def rung_prefetch():
    def kern(ids_ref, t_ref, o_ref, s_ref, sem):
        row = ids_ref[pl.program_id(0)]
        cp = pltpu.make_async_copy(t_ref.at[row], s_ref.at[0], sem)
        cp.start()
        cp.wait()
        o_ref[:] = s_ref[:]

    t = jnp.full((V, W), 7.0, jnp.float32)
    ids = jnp.arange(4, dtype=jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(4,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((1, W), lambda i, ids_ref: (i, 0)),
        scratch_shapes=[pltpu.VMEM((1, W), jnp.float32),
                        pltpu.SemaphoreType.DMA],
    )
    out = pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((4, W), jnp.float32))(ids, t)
    assert float(out[0, 0]) == 7.0


def rung_loop_dma():
    n = 8

    def kern(i_ref, t_ref, o_ref, s_ref, sems):
        def issue(j, _):
            row = i_ref[j]
            pltpu.make_async_copy(t_ref.at[row], s_ref.at[j],
                                  sems.at[j]).start()
            return 0

        jax.lax.fori_loop(0, n, issue, 0)

        def drain(j, _):
            row = i_ref[j]
            pltpu.make_async_copy(t_ref.at[row], s_ref.at[j],
                                  sems.at[j]).wait()
            return 0

        jax.lax.fori_loop(0, n, drain, 0)
        o_ref[:] = jnp.sum(s_ref[:], axis=0, keepdims=True)

    t = jnp.ones((V, W), jnp.float32)
    idx = jnp.arange(n, dtype=jnp.int32)
    out = pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_shape=jax.ShapeDtypeStruct((1, W), jnp.float32),
        scratch_shapes=[pltpu.VMEM((n, W), jnp.float32),
                        pltpu.SemaphoreType.DMA((n,))],
    )(idx, t)
    assert float(out[0, 0]) == float(n)


def rung_rmw_scatter():
    """The full production candidate: sorted-unique scatter-add RMW kernel
    (ops/pallas_scatter.py) at a small shape."""
    import sys as _sys
    import os as _os
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    from distributed_embeddings_tpu.ops import pallas_scatter as ps
    rng = np.random.default_rng(0)
    v, w, n = 4096, 128, 256
    ids = jnp.asarray(np.sort(rng.choice(v, n, replace=False))
                      .astype(np.int32))
    delta = jnp.asarray(rng.standard_normal((n, w)).astype(np.float32))
    table = jnp.zeros((v, w), jnp.float32)
    got = ps.scatter_add_sorted_unique(table, ids, delta, interpret=False)
    want = table.at[ids].add(delta, mode="drop")
    err = float(jnp.max(jnp.abs(got - want)))
    assert err < 1e-5, f"rmw mismatch {err}"


def rung_blockspec_gather():
    """The round-4 tiled kernels' ONLY nonstandard feature combo, minimal:
    scalar-prefetched arrays driving BlockSpec index maps on inputs AND a
    revisited output block, SMEM scalar input, input_output_aliasing — no
    make_async_copy anywhere. If this compiles, ops/pallas_tiled.py
    compiles."""
    tile, chunk, w = 8, 128, W

    def kern(tof_ref, cof_ref, ids_ref, hp_ref, t_ref, o_ref, acc):
        g = pl.program_id(0)
        t = tof_ref[g]
        local = (ids_ref[0, :] - t * tile)[None, :]
        r = jax.lax.broadcasted_iota(jnp.int32, (tile, chunk), 0)
        oh = (r == local).astype(jnp.float32)
        part = jnp.sum(oh, axis=1, keepdims=True) * hp_ref[0, 0]

        @pl.when(g == 0)
        def _():
            acc[:] = jnp.zeros_like(acc)
        acc[:] = acc[:] + part

        @pl.when(g == pl.num_programs(0) - 1)
        def _():
            o_ref[:] = t_ref[:] + acc[:]

    v = 4 * tile
    tof = jnp.zeros((2,), jnp.int32)          # both steps hit tile 0
    cof = jnp.arange(2, dtype=jnp.int32)
    ids = jnp.arange(2 * chunk, dtype=jnp.int32).reshape(2, chunk) % tile
    hp = jnp.full((1, 1), 2.0, jnp.float32)
    table = jnp.zeros((v, w), jnp.float32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(2,),
        in_specs=[
            pl.BlockSpec((1, chunk), lambda g, tof, cof: (cof[g], 0)),
            pl.BlockSpec((1, 1), lambda g, tof, cof: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((tile, w), lambda g, tof, cof: (tof[g], 0)),
        ],
        out_specs=pl.BlockSpec((tile, w), lambda g, tof, cof: (tof[g], 0)),
        scratch_shapes=[pltpu.VMEM((tile, 1), jnp.float32)],
    )
    out = pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((v, w), jnp.float32),
        input_output_aliases={4: 0},
    )(tof, cof, ids, hp, table)
    # each tile-0 row id appears 2*chunk/tile times per... each chunk has
    # chunk/tile occurrences of each local row; 2 chunks * 2.0 scaling
    want = 2 * (chunk // tile) * 2.0
    assert float(out[0, 0]) == want, f"{float(out[0, 0])} != {want}"


def rung_tiled_kernels():
    """The full round-4 production candidates (ops/pallas_tiled.py) at a
    small shape: tiled adagrad + tiled gather vs XLA."""
    import sys as _sys
    import os as _os
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    from distributed_embeddings_tpu.ops import sparse_update as su
    assert su._validate_tiled(), "tiled kernels compiled but mismatch XLA"


RUNGS = [("vmem", rung_vmem), ("anyspace", rung_anyspace), ("dma", rung_dma),
         ("dyn_dma", rung_dyn_dma), ("prefetch", rung_prefetch),
         ("loop_dma", rung_loop_dma), ("rmw_scatter", rung_rmw_scatter),
         ("blockspec_gather", rung_blockspec_gather),
         ("tiled_kernels", rung_tiled_kernels)]


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    results = {}
    for name, fn in RUNGS:
        t0 = time.perf_counter()
        try:
            fn()
            results[name] = "ok"
            print(f"ok   {name} ({time.perf_counter() - t0:.1f}s)",
                  flush=True)
        except Exception as e:  # noqa: BLE001 - report every rung
            results[name] = f"FAIL {str(e)[:160]}"
            print(f"FAIL {name}: {str(e)[:300]}", flush=True)
    import json
    print(json.dumps(results), flush=True)
    return 0 if all(v == "ok" for v in results.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
