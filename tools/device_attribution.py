"""Device-time attribution CLI (ISSUE 14): parse a jax profiler
capture, attribute device-op time to the span annotations that
dispatched it, and print the measured-vs-perf_model reconciliation
table — the artifact every tunnel-window arm files next to its bench
record (docs/perf_model.md "Tunnel-window runbook").

Usage:

    python tools/device_attribution.py <logdir> \
        [--snapshot metrics_snapshot.json] \
        [--projections projections.json] [--tolerance 0.5] [--json]

`<logdir>` is the directory `utils.profiling.trace` (or `bench.py
--profile`) captured into — the newest ``plugins/profile/<run>/
*.trace.json.gz`` under it is parsed. ``--snapshot`` (a bench record's
``metrics_snapshot`` or a bare registry snapshot) pins the span-window
set to the run's recorded ``span_seconds{span=}`` paths; without it a
shape-based fallback matches annotation-looking events.
``--projections`` is a flat ``{phase: projected_ms}`` JSON (e.g. the
``kernels_tpu_projections`` block of a kernels record); each row
settles or falsifies against the measured per-span device time.
Exit 0 always unless parsing fails — the table is evidence, not a
gate; pipe ``--json`` into jq for gating.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_embeddings_tpu.obs import attribution  # noqa: E402


def _span_paths_from_snapshot(path: str):
    with open(path) as f:
        doc = json.load(f)
    return attribution.span_paths_from_snapshot(doc)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="attribute profiler device time to span annotations")
    p.add_argument("logdir", help="profiler capture directory")
    p.add_argument("--snapshot", default=None,
                   help="bench record / registry snapshot JSON whose "
                        "span_seconds keys pin the window set")
    p.add_argument("--projections", default=None,
                   help="{phase: projected_ms} JSON to reconcile against")
    p.add_argument("--tolerance", type=float, default=0.5,
                   help="relative tolerance for a projection to settle")
    p.add_argument("--json", action="store_true",
                   help="emit the attribution dict as one JSON line")
    args = p.parse_args(argv)

    span_paths = (_span_paths_from_snapshot(args.snapshot)
                  if args.snapshot else None)
    try:
        att = attribution.attribute_logdir(args.logdir,
                                           span_paths=span_paths)
    except FileNotFoundError as e:
        print(f"device_attribution: {e}", file=sys.stderr)
        return 1
    if args.projections:
        with open(args.projections) as f:
            proj = json.load(f)
        att["reconciliation"] = attribution.reconciliation_table(
            att, proj, tolerance_frac=args.tolerance)
    if args.json:
        print(json.dumps(att))
        return 0

    total_ms = att["total_device_seconds"] * 1e3
    print(f"trace: {att['trace_file']}")
    print(f"device total: {total_ms:.3f} ms over "
          f"{att['device_op_count']} ops; "
          f"{att['span_window_count']} span windows; "
          f"coverage {att['coverage_frac']:.1%}")
    width = max([len(s) for s in att["spans"]] + [12])
    for span, sec in sorted(att["spans"].items(),
                            key=lambda kv: -kv[1]):
        print(f"  {span:<{width}}  {sec * 1e3:10.3f} ms"
              f"  {sec * 1e3 / max(total_ms, 1e-9):6.1%}")
    print(f"  {'(unattributed)':<{width}}  "
          f"{att['unattributed_seconds'] * 1e3:10.3f} ms")
    coll = att["collective"]
    if coll["device_seconds"]:
        print(f"collectives: {coll['device_seconds'] * 1e3:.3f} ms, "
              f"exposed {coll['exposed_seconds'] * 1e3:.3f} ms "
              f"(fraction {coll['exposed_fraction']})")
    for row in att.get("reconciliation", []):
        print(f"  [{row['verdict']:>10}] {row['phase']}: projected "
              f"{row['projected_ms']} ms, measured {row['measured_ms']} ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
