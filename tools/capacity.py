"""Pod capacity planner: which synthetic scale fits which TPU pod?

Uses the (device-free) sharding planner to place every synthetic model at a
range of world sizes and reports per-chip HBM need — allocated stacked
buckets (padding included), optimizer state, and a batch-dependent
activation estimate — against v5e/v5p HBM. This answers BASELINE.json's
"max embedding params shardable per pod" capacity metric without hardware:
the plan IS the allocation.

Usage: python tools/capacity.py [--models tiny,small,...] [--batch 65536]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..")))  # repo root

HBM_BYTES = {"v5e": 16 * 2**30, "v5p": 95 * 2**30}


def per_chip_bytes(model_key: str, world: int, batch: int,
                   optimizer: str = "adagrad",
                   gpu_embedding_size=None):
    """Plan `model_key` at `world` chips; return per-chip byte accounting.

    gpu_embedding_size: per-chip element budget — buckets past it are
    flagged for host offload (pinned host memory) and accounted under
    'host' instead of HBM, like the runtime places them.
    """
    from distributed_embeddings_tpu.models.synthetic import (
        SYNTHETIC_MODELS, expand_embedding_configs)
    from distributed_embeddings_tpu.layers.embedding import Embedding
    from distributed_embeddings_tpu.parallel.planner import (
        DistEmbeddingStrategy)
    from distributed_embeddings_tpu.parallel.plan import lower_strategy

    cfg = SYNTHETIC_MODELS[model_key]
    specs, input_table_map, hotness = expand_embedding_configs(cfg)
    embs = [Embedding(v, w, combiner="sum") for (v, w) in specs]
    # fair-share slicing thresholds: any table bigger than its per-chip
    # share is column-sliced; the monsters (> 4 shares) are row-sliced
    # across the whole pod. Stacked buckets allocate rows_max on EVERY
    # chip, so unsliced giants would cost their full size per chip.
    total = sum(v * w for v, w in specs)
    share = max(total // world, 1)
    strat = DistEmbeddingStrategy(
        embs, world, "memory_balanced", input_table_map=input_table_map,
        column_slice_threshold=share,
        row_slice_threshold=(4 * share if world > 1 else None),
        gpu_embedding_size=gpu_embedding_size)
    plan = lower_strategy(strat)

    # stacked allocations are [world, rows_max, width]: every chip holds
    # rows_max rows per bucket/row-table (padding included — that is what
    # the runtime actually allocates per chip). Offloaded buckets live in
    # pinned host memory instead of HBM.
    host_b = sum(max(b.rows_max, 1) * b.width * 4
                 for b in plan.tp_buckets if b.offload)
    table_b = sum(max(b.rows_max, 1) * b.width * 4
                  for b in plan.tp_buckets if not b.offload)
    table_b += sum(max(rt.rows_max, 1) * rt.width * 4
                   for rt in plan.row_tables)
    # dp tables are replicated on every chip
    table_b += sum(c["input_dim"] * c["output_dim"] * 4
                   for c in strat.dp_configs)
    opt_mult = {"sgd": 0, "adagrad": 1, "adam": 2}[optimizer]
    state_b = table_b * opt_mult
    host_b *= 1 + opt_mult

    # activation estimate: per-chip batch shard of looked-up rows (fwd out +
    # tap grads ~ 2x) plus exchanged id blocks
    b_local = max(batch // world, 1)
    act_rows = sum(h * specs[t][1] for t, h in
                   zip(input_table_map, hotness))
    act_b = 2 * b_local * act_rows * 4 + b_local * sum(hotness) * 4 * 2
    return {"tables": table_b, "opt_state": state_b, "activations": act_b,
            "host": host_b, "total": table_b + state_b + act_b}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", default="tiny,small,medium,large,jumbo,colossal")
    ap.add_argument("--worlds", default="1,8,16,32,64,128,256,512")
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--optimizer", default="adagrad")
    ap.add_argument("--gpu_embedding_size", type=int, default=None,
                    help="per-chip element budget; overflow buckets are "
                         "host-offloaded (accounted under 'host_gib')")
    args = ap.parse_args()

    worlds = [int(w) for w in args.worlds.split(",")]
    out = {}
    for m in args.models.split(","):
        rows = {}
        for w in worlds:
            try:
                acct = per_chip_bytes(m, w, args.batch, args.optimizer,
                                      args.gpu_embedding_size)
            except Exception as e:  # noqa: BLE001 - report placement failure
                rows[w] = {"error": str(e)[:120]}
                continue
            fits = {gen: acct["total"] <= cap * 0.9  # 10% runtime headroom
                    for gen, cap in HBM_BYTES.items()}
            rows[w] = {"per_chip_gib": round(acct["total"] / 2**30, 2),
                       "tables_gib": round(acct["tables"] / 2**30, 2),
                       **({"host_gib": round(acct["host"] / 2**30, 2)}
                          if acct["host"] else {}),
                       **{f"fits_{g}": f for g, f in fits.items()}}
        out[m] = rows
        min_fit = {g: next((w for w in worlds
                            if rows.get(w, {}).get(f"fits_{g}")), None)
                   for g in HBM_BYTES}
        print(f"{m:9s} min chips: "
              + "  ".join(f"{g}={min_fit[g]}" for g in HBM_BYTES)
              + "   (per-chip GiB at that size: "
              + "  ".join(
                  f"{g}:{rows[min_fit[g]]['per_chip_gib']}"
                  if min_fit[g] else f"{g}:-" for g in HBM_BYTES) + ")",
              flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
