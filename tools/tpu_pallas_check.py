"""Hardware validation: run the Pallas lookup kernels COMPILED on a real TPU.

Round-1 verdict: every Pallas test ran interpret=True on CPU; the compiled
path had never executed. This script runs both kernels (one-hot MXU matmul
and DMA-gather) with interpret=False on the attached chip, compares against
the XLA-native reference, and times them vs the plain take+einsum path.

Usage: python tools/tpu_pallas_check.py [--quick]
Exit 0 = all cases pass; nonzero = mismatch or compile failure.
"""

import argparse
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
from distributed_embeddings_tpu.ops import pallas_lookup  # noqa: E402


def xla_ref(table, ids, weights, combiner):
    ids = jnp.clip(ids, 0, table.shape[0] - 1)
    if combiner == "mean":
        denom = jnp.maximum(jnp.sum(weights, axis=1, keepdims=True), 1.0)
        weights = weights / denom
    embs = jnp.take(table, ids, axis=0).astype(jnp.float32)
    return jnp.einsum("bk,bkw->bw", weights.astype(jnp.float32), embs)


def make_case(rng, batch, vocab, width, hot):
    table = rng.standard_normal((vocab, width), dtype=np.float32)
    ids = rng.integers(0, vocab, size=(batch, hot)).astype(np.int32)
    k_true = rng.integers(1, hot + 1, size=(batch,))
    weights = (np.arange(hot)[None, :] < k_true[:, None]).astype(np.float32)
    return jnp.asarray(table), jnp.asarray(ids), jnp.asarray(weights)


def bench(fn, table, ids, weights, iters=20):
    """Chained-program slope timing with fetch sync.

    Round-3 axon findings, in order of discovery: (1) block_until_ready
    returns early, so a host FETCH of the result is the only real sync;
    (2) repeated identical calls whose outputs are never fetched may never
    execute at all (50 queued lookups "ran" in 0.000 ms), so the measured
    program must CHAIN — each iteration's input depends on the previous
    iteration's output. One jitted fori_loop carries a zero-valued
    dependency (input values stay identical; the data dependency is real),
    and per-iter time is (t(2N) - t(N)) / N so constant dispatch/fetch
    overhead cancels."""
    from jax import lax

    def loop(w):
        def body(i, s):
            w, acc = s
            out = fn(table, ids, w)
            dep = (out[:1, :1] * 0).astype(w.dtype)
            return (w + dep, acc + out[0, 0].astype(jnp.float32))
        return lax.fori_loop(0, iters, body, (w, jnp.float32(0)))

    lf = jax.jit(loop)

    def fetch(o):
        return float(o[1])

    out = lf(weights)
    fetch(out)
    t0 = time.perf_counter()
    out = lf(weights)
    fetch(out)
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = lf(weights)
    out = lf(out[0])
    fetch(out)
    t2 = time.perf_counter() - t0
    # raw provenance rides along (VERDICT r3 item 10): t2 ~ 2x t1 confirms
    # the slope is clean; t1 ~ t2 means overhead-dominated — treat the
    # per-iter number with suspicion
    bench.last_raw = {"t1_ms": round(t1 * 1e3, 3),
                      "t2_ms": round(t2 * 1e3, 3), "iters": iters}
    return max(t2 - t1, 1e-9) / iters * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    on_tpu = dev.platform == "tpu"
    if not on_tpu:
        print("WARNING: not a TPU — compiled-path check is meaningless here")

    rng = np.random.default_rng(0)
    # (batch, vocab, width, hot, combiner) — covers both kernels, unaligned
    # batches (ADVICE: tile_b sublane alignment), hotness 1..200
    cases = [
        (4096, 1000, 64, 8, "sum"),       # onehot kernel, unaligned width
        (4096, 8192, 128, 26, "mean"),    # onehot kernel upper vocab bound
        (100, 1000, 128, 5, "sum"),       # odd batch < 256
        (65536, 100000, 128, 1, "sum"),   # dma kernel, hotness 1
        (16384, 1000000, 128, 10, "sum"),  # dma kernel, 1M vocab
        (8192, 100000, 256, 30, "mean"),  # dma kernel, wide rows
    ]
    if not args.quick:
        cases += [
            (4096, 1000000, 128, 200, "sum"),  # jumbo hotness (VERDICT weak#2)
            (999, 50000, 128, 7, "sum"),       # unaligned batch, dma kernel
        ]
    # narrow-row DMA cases (the tiny model's actual table shapes): only
    # reachable with DET_PALLAS_NARROW=1 — measures whether sub-lane rows
    # are worth DMA-gathering vs XLA's native gather
    if os.environ.get("DET_PALLAS_NARROW", "0") == "1":
        cases += [
            (16384, 1000000, 16, 10, "sum"),   # tiny multi-hot shape
            (65536, 25000000, 16, 1, "sum"),   # tiny one-hot monster table
            (16384, 60160, 8, 10, "sum"),      # tiny width-8 fused bucket
        ]

    failures = 0
    for batch, vocab, width, hot, comb in cases:
        tag = f"B{batch} V{vocab} W{width} K{hot} {comb}"
        table, ids, weights = make_case(rng, batch, vocab, width, hot)
        try:
            t0 = time.perf_counter()
            fused = jax.jit(
                lambda t, i, w: pallas_lookup.fused_embedding_lookup(
                    t, i, w, comb, interpret=False))
            out = fused(table, ids, weights)
            jax.block_until_ready(out)
            compile_s = time.perf_counter() - t0
        except Exception as e:  # noqa: BLE001
            print(f"FAIL {tag}: compile/run error: {str(e)[:400]}")
            failures += 1
            continue
        ref = jax.jit(lambda t, i, w: xla_ref(t, i, w, comb))(
            table, ids, weights)
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
        scale = float(jnp.max(jnp.abs(ref))) + 1e-6
        ok = err / scale < 1e-5
        t_pallas = bench(fused, table, ids, weights, iters=20)
        raw_p = bench.last_raw
        t_xla = bench(jax.jit(lambda t, i, w: xla_ref(t, i, w, comb)),
                      table, ids, weights, iters=20)
        raw_x = bench.last_raw
        status = "ok  " if ok else "BAD "
        if not ok:
            failures += 1
        print(f"{status}{tag}: relerr={err / scale:.2e} "
              f"pallas={t_pallas:.3f}ms xla={t_xla:.3f}ms "
              f"speedup={t_xla / t_pallas:.2f}x compile={compile_s:.1f}s "
              f"raw_pallas={raw_p} raw_xla={raw_x}",
              flush=True)

    # grad path (XLA scatter-add through custom_vjp) on one mid case
    table, ids, weights = make_case(rng, 4096, 100000, 128, 10)

    def loss(t):
        return jnp.sum(pallas_lookup.fused_embedding_lookup(
            t, ids, weights, "sum", interpret=False) ** 2)

    def loss_ref(t):
        return jnp.sum(xla_ref(t, ids, weights, "sum") ** 2)

    try:
        g = jax.jit(jax.grad(loss))(table)
        gr = jax.jit(jax.grad(loss_ref))(table)
        gerr = float(jnp.max(jnp.abs(g - gr))) / (
            float(jnp.max(jnp.abs(gr))) + 1e-6)
        print(f"grad relerr={gerr:.2e} {'ok' if gerr < 1e-5 else 'BAD'}")
        if gerr >= 1e-5:
            failures += 1
    except Exception as e:  # noqa: BLE001
        print(f"FAIL grad: {str(e)[:400]}")
        failures += 1

    print(f"{'PASS' if failures == 0 else 'FAIL'}: {failures} failures")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
