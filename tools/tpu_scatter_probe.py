"""Measure how much XLA:TPU scatter/gather cost drops when the indices are
promised unique and/or sorted.

Round-3 prims data: row scatter-add is THE bottleneck on this chip
(~100-280 ns/row — a 720k-row update costs 74 ms while the same bytes
stream in ~0.2 ms), and the sparse-update sort path scatters with ids that
ARE sorted+unique post-dedup but never says so, forcing XLA's conservative
duplicate-safe lowering. This probe times every (flags x shape) combination
the framework's update paths use, chained + fetch-synced (see
utils/profiling.fetch_sync for why).

Usage: python tools/tpu_scatter_probe.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

RESULTS = {}


def timed_chain(step, state, iters=8, label=""):
    def loop(s):
        return lax.fori_loop(0, iters, lambda i, x: step(x), s)

    lf = jax.jit(loop)
    out = lf(state)
    _fetch(out)
    t0 = time.perf_counter()
    out = lf(state)
    _fetch(out)
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = lf(state)
    out = lf(out)
    _fetch(out)
    t2 = time.perf_counter() - t0
    dt = max(t2 - t1, 1e-9) / iters
    print(f"{label}: {dt * 1e3:.3f} ms/iter", flush=True)
    RESULTS[label] = round(dt * 1e3, 3)
    return dt


def _fetch(out):
    total = 0.0
    for leaf in jax.tree.leaves(out):
        if hasattr(leaf, "dtype"):
            total += float(jnp.sum(leaf.astype(jnp.float32)))
    return total


def unique_sorted_ids(rng, n, v):
    """Strictly increasing in-bounds ids: sorted sample + arange offset."""
    return np.sort(rng.integers(0, v - n, n).astype(np.int64)) + np.arange(n)


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    rng = np.random.default_rng(0)

    # --- width 16 (tiny-model class): V=25M, n=720896 rows
    for (v, n, w) in ((25_000_000, 720_896, 16), (2_600_000, 1_703_936, 128)):
        tag = f"V={v//1000}k n={n} w={w}"
        dup_ids = jnp.asarray(rng.integers(0, v, n).astype(np.int32))
        uniq = jnp.asarray(unique_sorted_ids(rng, n, v).astype(np.int32))
        rows = jnp.asarray(rng.standard_normal((n, w), dtype=np.float32))
        table = jnp.zeros((v, w), jnp.float32)

        def mk_scatter(ids, unique, sorted_):
            def step(s):
                t, r = s
                t = t.at[ids].add(r, mode="drop", unique_indices=unique,
                                  indices_are_sorted=sorted_)
                # chain: next iteration's rows depend on this scatter
                return t, r + t[0, :1] * 0
            return step

        timed_chain(mk_scatter(dup_ids, False, False), (table, rows),
                    label=f"scatter-add dupes noflags {tag}")
        timed_chain(mk_scatter(uniq, False, False), (table, rows),
                    label=f"scatter-add uniqsorted noflags {tag}")
        timed_chain(mk_scatter(uniq, True, False), (table, rows),
                    label=f"scatter-add uniqsorted unique {tag}")
        timed_chain(mk_scatter(uniq, True, True), (table, rows),
                    label=f"scatter-add uniqsorted unique+sorted {tag}")

        def mk_gather(ids, unique, sorted_):
            def step(s):
                t, i = s
                out = jnp.take(t, i, axis=0, mode="clip",
                               unique_indices=unique,
                               indices_are_sorted=sorted_)
                return t, (i + out[0, 0].astype(jnp.int32) % 2)
            return step

        timed_chain(mk_gather(dup_ids, False, False), (table, dup_ids),
                    label=f"gather dupes noflags {tag}")
        timed_chain(mk_gather(uniq, True, True), (table, uniq),
                    label=f"gather uniqsorted unique+sorted {tag}")
        # sorted-with-duplicates gather: the shape a sorted-lookup forward
        # would issue (sort ids once, gather with locality, inverse-permute)
        sdup = jnp.sort(dup_ids)
        timed_chain(mk_gather(sdup, False, True), (table, sdup),
                    label=f"gather dupes sorted {tag}")

        # composite: sort + sorted-gather + inverse-permute vs the raw
        # unsorted gather above — the end-to-end decision for a
        # sorted-lookup forward path. Inverse permute is SCATTER-FREE
        # (argsort + take): an .at[perm].set would reintroduce the
        # 106 ns/row scatter this path exists to avoid
        def composite(s):
            t, i = s
            iota = jnp.arange(i.shape[0], dtype=jnp.int32)
            sid, perm = lax.sort_key_val(i, iota)
            inv = lax.sort_key_val(perm, iota)[1]
            rows_srt = jnp.take(t, sid, axis=0, mode="clip",
                                indices_are_sorted=True)
            out = jnp.take(rows_srt, inv, axis=0)
            return t, (i + out[0, 0].astype(jnp.int32) % 2)

        timed_chain(composite, (table, dup_ids),
                    label=f"sort+sortedgather+unperm {tag}")
        del table, rows, dup_ids, uniq, sdup

    # segment aggregation alternatives: jax.ops.segment_sum(sorted) measured
    # 45 ns/row in round-3a (it is a sorted-dupes scatter underneath); a
    # cumsum-difference formulation is pure streaming if XLA lowers cumsum
    # at bandwidth (cost: ~N*eps precision, acceptable as an opt-in)
    for w in (16, 128):
        n = 720_896
        seg_ids = jnp.asarray(np.sort(rng.integers(0, n, n)).astype(np.int32))
        rows = jnp.asarray(rng.standard_normal((n, w), dtype=np.float32))
        starts = jnp.concatenate([jnp.ones((1,), bool),
                                  seg_ids[1:] != seg_ids[:-1]])
        seg = jnp.cumsum(starts.astype(jnp.int32)) - 1

        def seg_scatter(s):
            sg, r = s
            out = jax.ops.segment_sum(r, sg, num_segments=n,
                                      indices_are_sorted=True)
            return (sg + out[0, 0].astype(jnp.int32) % 2) % n, r

        timed_chain(seg_scatter, (seg, rows),
                    label=f"segment_sum scatter n=720k w={w}")

        sid_sorted = jnp.sort(jnp.asarray(
            rng.integers(0, n, n).astype(np.int32)))

        def seg_cumsum(s):
            # scatter-FREE per-segment totals over sorted ids: cumsum +
            # cummax + one sorted gather; totals land at each segment's
            # END row (other rows zero), which downstream unique-promise
            # scatters consume just as well as a compacted layout
            sid, r = s
            iota = jnp.arange(n, dtype=jnp.int32)
            is_start = jnp.concatenate(
                [jnp.ones((1,), bool), sid[1:] != sid[:-1]])
            is_end = jnp.concatenate(
                [sid[1:] != sid[:-1], jnp.ones((1,), bool)])
            p = jnp.cumsum(r, axis=0)
            begin = lax.cummax(jnp.where(is_start, iota, -1))
            p_prev = jnp.where(
                (begin > 0)[:, None],
                jnp.take(p, jnp.maximum(begin - 1, 0), axis=0,
                         indices_are_sorted=True), 0.0)
            sums_at_end = jnp.where(is_end[:, None], p - p_prev, 0.0)
            return (sid + sums_at_end[0, 0].astype(jnp.int32) % 2) % n, r

        timed_chain(seg_cumsum, (sid_sorted, rows),
                    label=f"segment_sum cumsum-scatterfree n=720k w={w}")
        del rows

    # the real update path, now carrying the unique+sorted promises — direct
    # comparison against round-3a prims (sort 200.2ms / dense 93.7ms)
    from distributed_embeddings_tpu.ops import sparse_update as su
    v, n = 25_000_000, 720_896
    tbl = jnp.zeros((v, 16), jnp.float32)
    acc = jnp.full((v, 16), 0.1, jnp.float32)
    sids = jnp.asarray(rng.integers(0, v, n).astype(np.int32))
    contribs = jnp.asarray(rng.standard_normal((n, 16), dtype=np.float32))
    for strat, dedup in (("sort", "sort"), ("sort", "cumsum"),
                         ("dense", "sort")):
        os.environ["DET_DEDUP_IMPL"] = dedup

        def step8(s, strat=strat):
            t, a, i = s
            t2, a2 = su.sparse_adagrad(t, a, su.SparseRowGrad(i, contribs),
                                       0.01, strategy=strat)
            return t2, a2, (i * 1103515245 + 12345) % v
        timed_chain(step8, (tbl, acc, sids), iters=6,
                    label=f"sparse_adagrad[{strat}|{dedup}]+flags "
                          "n=720k V=25M")
    os.environ.pop("DET_DEDUP_IMPL", None)

    # Pallas RMW scatter kernel vs the flagged XLA scatter — only if this
    # toolchain can compile it (see tools/tpu_mosaic_probe.py)
    try:
        from distributed_embeddings_tpu.ops import pallas_scatter as ps
        n_u = 655_360                       # unique sorted rows
        uniq2 = jnp.asarray(unique_sorted_ids(rng, n_u, v).astype(np.int32))
        deltas = jnp.asarray(
            rng.standard_normal((n_u, 16), dtype=np.float32))
        # correctness first at a small shape, compiled
        small_ids = jnp.asarray(
            np.sort(rng.choice(10_000, 512, replace=False)).astype(np.int32))
        small_d = jnp.asarray(
            rng.standard_normal((512, 16), dtype=np.float32))
        small_t = jnp.zeros((10_000, 16), jnp.float32)
        got = ps.scatter_add_sorted_unique(small_t, small_ids, small_d,
                                           interpret=False)
        want = small_t.at[small_ids].add(small_d, mode="drop")
        assert float(jnp.max(jnp.abs(got - want))) < 1e-5

        def step_rmw(s):
            t, d = s
            t = ps.scatter_add_sorted_unique(t, uniq2, d, interpret=False)
            return t, d + t[0, :1] * 0

        timed_chain(step_rmw, (tbl, deltas), iters=6,
                    label=f"pallas_rmw_scatter n={n_u} V=25M w=16")

        def step_fused(s):
            t, a, d = s
            t, a = ps.adagrad_rows_sorted_unique(t, a, uniq2, d, 0.01,
                                                 interpret=False)
            return t, a, d + t[0, :1] * 0

        timed_chain(step_fused, (tbl, acc, deltas), iters=6,
                    label=f"pallas_fused_adagrad n={n_u} V=25M w=16")
    except Exception as e:  # noqa: BLE001 - toolchain may reject the kernel
        RESULTS["pallas_rmw_scatter"] = f"FAIL {str(e)[:200]}"
        print(f"pallas_rmw_scatter: FAIL {str(e)[:300]}", flush=True)

    # round-4 tiled one-hot-matmul kernels (ops/pallas_tiled.py): BlockSpec
    # streams only — the form this toolchain compiles (the one-hot lookup
    # kernel compiles; the DMA kernels do not). Timed at the two real
    # workload shapes with duplicate ids straight in (no dedup pass), plus
    # a (tile, chunk) sweep on the tiny-class shape.
    try:
        from distributed_embeddings_tpu.ops import pallas_tiled as ptl
        # compiled correctness at a small shape first
        small_ids = jnp.asarray(rng.integers(0, 10_000, 4096)
                                .astype(np.int32))
        small_d = jnp.asarray(
            rng.standard_normal((4096, 16), dtype=np.float32))
        small_t = jnp.asarray(
            rng.standard_normal((10_000, 16), dtype=np.float32))
        small_a = jnp.full((10_000, 16), 0.1, jnp.float32)
        got_t, got_a = ptl.tiled_adagrad(small_t, small_a, small_ids,
                                         small_d, 0.01, interpret=False)
        want_t, want_a = su.sparse_adagrad(
            small_t, small_a, su.SparseRowGrad(small_ids, small_d), 0.01,
            strategy="sort")
        err = float(jnp.max(jnp.abs(got_t - want_t)))
        assert err < 1e-3, f"tiled_adagrad mismatch {err}"
        RESULTS["tiled_correctness"] = "PASS"
        print("tiled correctness: PASS", flush=True)

        for (v2, n2, w2) in ((25_000_000, 720_896, 16),
                             (2_600_000, 1_703_936, 128)):
            tbl2 = jnp.zeros((v2, w2), jnp.float32)
            acc2 = jnp.full((v2, w2), 0.1, jnp.float32)
            ids2 = jnp.asarray(rng.integers(0, v2, n2).astype(np.int32))
            d2 = jnp.asarray(
                rng.standard_normal((n2, w2), dtype=np.float32))

            def step_tiled(s, v2=v2, d2=d2):
                t, a, i = s
                t, a = ptl.tiled_adagrad(t, a, i, d2, 0.01,
                                         interpret=False)
                return t, a, (i * 1103515245 + 12345) % v2

            timed_chain(step_tiled, (tbl2, acc2, ids2), iters=6,
                        label=f"tiled_adagrad dupes n={n2} V={v2//1000}k "
                              f"w={w2}")

            def step_tgather(s, d2=d2):
                t, i = s
                out = ptl.tiled_gather(t, i, interpret=False)
                return t, (i + out[0, 0].astype(jnp.int32) % 2)

            timed_chain(step_tgather, (tbl2, ids2), iters=6,
                        label=f"tiled_gather dupes n={n2} V={v2//1000}k "
                              f"w={w2}")
            del tbl2, acc2, ids2, d2

        # block-size sweep at the tiny-class shape
        v3, n3, w3 = 25_000_000, 720_896, 16
        tbl3 = jnp.zeros((v3, w3), jnp.float32)
        acc3 = jnp.full((v3, w3), 0.1, jnp.float32)
        ids3 = jnp.asarray(rng.integers(0, v3, n3).astype(np.int32))
        d3 = jnp.asarray(rng.standard_normal((n3, w3), dtype=np.float32))
        for tile in (1024, 2048, 4096):
            for chunk in (512, 1024):
                def step_sweep(s, tile=tile, chunk=chunk):
                    t, a, i = s
                    t, a = ptl.tiled_adagrad(t, a, i, d3, 0.01, tile=tile,
                                             chunk=chunk, interpret=False)
                    return t, a, (i * 1103515245 + 12345) % v3
                timed_chain(step_sweep, (tbl3, acc3, ids3), iters=6,
                            label=f"tiled_adagrad T={tile} C={chunk} "
                                  f"n=720k V=25M w=16")
    except Exception as e:  # noqa: BLE001 - toolchain may reject the kernel
        RESULTS["tiled_kernels"] = f"FAIL {str(e)[:200]}"
        print(f"tiled_kernels: FAIL {str(e)[:300]}", flush=True)

    print(json.dumps(RESULTS), flush=True)


if __name__ == "__main__":
    main()
