"""Measure how much XLA:TPU scatter/gather cost drops when the indices are
promised unique and/or sorted.

Round-3 prims data: row scatter-add is THE bottleneck on this chip
(~100-280 ns/row — a 720k-row update costs 74 ms while the same bytes
stream in ~0.2 ms), and the sparse-update sort path scatters with ids that
ARE sorted+unique post-dedup but never says so, forcing XLA's conservative
duplicate-safe lowering. This probe times every (flags x shape) combination
the framework's update paths use, chained + fetch-synced (see
utils/profiling.fetch_sync for why).

Usage: python tools/tpu_scatter_probe.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

RESULTS = {}


def timed_chain(step, state, iters=8, label=""):
    def loop(s):
        return lax.fori_loop(0, iters, lambda i, x: step(x), s)

    lf = jax.jit(loop)
    out = lf(state)
    _fetch(out)
    t0 = time.perf_counter()
    out = lf(state)
    _fetch(out)
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = lf(state)
    out = lf(out)
    _fetch(out)
    t2 = time.perf_counter() - t0
    dt = max(t2 - t1, 1e-9) / iters
    print(f"{label}: {dt * 1e3:.3f} ms/iter", flush=True)
    RESULTS[label] = round(dt * 1e3, 3)
    return dt


def _fetch(out):
    total = 0.0
    for leaf in jax.tree.leaves(out):
        if hasattr(leaf, "dtype"):
            total += float(jnp.sum(leaf.astype(jnp.float32)))
    return total


def unique_sorted_ids(rng, n, v):
    """Strictly increasing in-bounds ids: sorted sample + arange offset."""
    return np.sort(rng.integers(0, v - n, n).astype(np.int64)) + np.arange(n)


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    rng = np.random.default_rng(0)

    # --- width 16 (tiny-model class): V=25M, n=720896 rows
    for (v, n, w) in ((25_000_000, 720_896, 16), (2_600_000, 1_703_936, 128)):
        tag = f"V={v//1000}k n={n} w={w}"
        dup_ids = jnp.asarray(rng.integers(0, v, n).astype(np.int32))
        uniq = jnp.asarray(unique_sorted_ids(rng, n, v).astype(np.int32))
        rows = jnp.asarray(rng.standard_normal((n, w), dtype=np.float32))
        table = jnp.zeros((v, w), jnp.float32)

        def mk_scatter(ids, unique, sorted_):
            def step(s):
                t, r = s
                t = t.at[ids].add(r, mode="drop", unique_indices=unique,
                                  indices_are_sorted=sorted_)
                # chain: next iteration's rows depend on this scatter
                return t, r + t[0, :1] * 0
            return step

        timed_chain(mk_scatter(dup_ids, False, False), (table, rows),
                    label=f"scatter-add dupes noflags {tag}")
        timed_chain(mk_scatter(uniq, False, False), (table, rows),
                    label=f"scatter-add uniqsorted noflags {tag}")
        timed_chain(mk_scatter(uniq, True, False), (table, rows),
                    label=f"scatter-add uniqsorted unique {tag}")
        timed_chain(mk_scatter(uniq, True, True), (table, rows),
                    label=f"scatter-add uniqsorted unique+sorted {tag}")

        def mk_gather(ids, unique, sorted_):
            def step(s):
                t, i = s
                out = jnp.take(t, i, axis=0, mode="clip",
                               unique_indices=unique,
                               indices_are_sorted=sorted_)
                return t, (i + out[0, 0].astype(jnp.int32) % 2)
            return step

        timed_chain(mk_gather(dup_ids, False, False), (table, dup_ids),
                    label=f"gather dupes noflags {tag}")
        timed_chain(mk_gather(uniq, True, True), (table, uniq),
                    label=f"gather uniqsorted unique+sorted {tag}")

    print(json.dumps(RESULTS), flush=True)


if __name__ == "__main__":
    main()
