"""One-shot hardware validation: run serially when the TPU tunnel is alive.

Stages (each skippable via --skip):
  1. probe    — backend init + tiny matmul (fail fast if tunnel is wedged)
  2. prims    — ground-truth gather/scatter/sort rates via scanned chains
                (one device program per measurement; wall-clock is device time)
  3. pallas   — compiled-kernel correctness vs XLA (tools/tpu_pallas_check)
  4. bench    — bench.py end to end

Writes a JSON summary to tools/tpu_validate_out.json.

Usage: python tools/tpu_validate.py [--skip prims,pallas] [--iters 8]
"""

import argparse
import json
import subprocess
import sys
import time

import numpy as np


def stage_probe():
    import jax
    import jax.numpy as jnp
    t0 = time.perf_counter()
    devs = jax.devices()
    out = {"devices": str(devs), "init_s": round(time.perf_counter() - t0, 1)}
    t0 = time.perf_counter()
    jax.block_until_ready(jnp.ones((512, 512)) @ jnp.ones((512, 512)))
    out["matmul_s"] = round(time.perf_counter() - t0, 1)
    return out


def _chain_time(body, state, iters):
    """Wall-time of ONE jitted program executing `body` iters times with a
    forced inter-iteration data dependency."""
    import jax
    from jax import lax
    lf = jax.jit(lambda s: lax.fori_loop(0, iters, lambda i, s: body(s), s))
    out = lf(state)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = lf(state)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def stage_prims(iters):
    import jax
    import jax.numpy as jnp
    from jax import lax
    rng = np.random.default_rng(0)
    res = {}
    v = 25_000_000
    tab16 = jnp.zeros((v, 16), jnp.float32)
    tab128 = jnp.zeros((2_000_000, 128), jnp.float32)

    # gather rate, narrow + wide rows (ids derived from prior output)
    for label, tab, vv, n in (("gather_65k_w16_v25M", tab16, v, 65536),
                              ("gather_720k_w16_v25M", tab16, v, 720896),
                              ("gather_65k_w128_v2M", tab128, 2_000_000,
                               65536)):
        ids = jnp.asarray(rng.integers(0, vv, n).astype(np.int32))

        def body(s, tab=tab, vv=vv):
            i, acc = s
            out = jnp.take(tab, i, axis=0)
            return ((i * 1103515245 + 12345) % vv,
                    acc + out[0, 0].astype(jnp.float32))
        dt = _chain_time(body, (ids, jnp.float32(0)), iters)
        res[label] = {"ms": round(dt * 1e3, 3),
                      "ns_per_row": round(dt / n * 1e9, 1)}

    # scatter-add rate into a big table
    ids = jnp.asarray(rng.integers(0, v, 720896).astype(np.int32))
    rows = jnp.asarray(rng.standard_normal((720896, 16), dtype=np.float32))

    def body_sc(s):
        i, acc = s
        buf = jnp.zeros((v, 16), jnp.float32).at[i].add(rows)
        return (i * 1103515245 + 12345) % v, acc + buf[0, 0]
    dt = _chain_time(body_sc, (ids, jnp.float32(0)), max(2, iters // 2))
    res["scatter_720k_w16_v25M"] = {"ms": round(dt * 1e3, 3),
                                    "ns_per_row": round(dt / 720896 * 1e9, 1)}

    # sort rate (key feeds back)
    for n in (720896, 2883584):
        k = jnp.asarray(rng.integers(0, v, n).astype(np.int32))
        pv = jnp.arange(n, dtype=jnp.int32)

        def body_s(s):
            k, p = s
            ks, vs = lax.sort_key_val(k, p)
            return (ks * 1103515245 + 12345) & 0x7fffffff, vs
        dt = _chain_time(body_s, (k, pv), iters)
        res[f"sort_{n}"] = {"ms": round(dt * 1e3, 3)}

    # fused sparse-adagrad update (the bench's per-bucket backward cost)
    from distributed_embeddings_tpu.ops import sparse_update as su
    tbl = jnp.zeros((v, 16), jnp.float32)
    acc = jnp.full((v, 16), 0.1, jnp.float32)
    contribs = jnp.asarray(rng.standard_normal((720896, 16),
                                               dtype=np.float32))

    def body_up(s):
        t, a, i = s
        t2, a2 = su.sparse_adagrad(t, a, su.SparseRowGrad(i, contribs), 0.01,
                                   strategy="sort")
        return t2, a2, (i * 1103515245 + 12345) % v
    dt = _chain_time(body_up, (tbl, acc, ids), max(2, iters // 2))
    res["sparse_adagrad_720k_v25M"] = {"ms": round(dt * 1e3, 3)}
    return res


def stage_pallas():
    p = subprocess.run([sys.executable, "tools/tpu_pallas_check.py",
                       "--quick"], capture_output=True, text=True,
                      timeout=1800)
    return {"rc": p.returncode, "out": p.stdout[-2000:],
            "err": p.stderr[-500:] if p.returncode else ""}


def stage_bench():
    p = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                       text=True, timeout=3600)
    line = None
    for ln in p.stdout.splitlines():
        if ln.startswith("{"):
            line = ln
    return {"rc": p.returncode, "json": line,
            "err": p.stderr[-800:] if p.returncode else ""}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip", default="")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--out", default="tools/tpu_validate_out.json")
    args = ap.parse_args()
    skip = set(args.skip.split(",")) if args.skip else set()
    summary = {}
    for name, fn in (("probe", stage_probe),
                     ("prims", lambda: stage_prims(args.iters)),
                     ("pallas", stage_pallas),
                     ("bench", stage_bench)):
        if name in skip:
            continue
        t0 = time.perf_counter()
        try:
            summary[name] = fn()
        except Exception as e:  # noqa: BLE001
            summary[name] = {"error": str(e)[:500]}
            print(f"stage {name} FAILED: {str(e)[:200]}", flush=True)
            if name == "probe":
                break
        summary[name]["stage_s"] = round(time.perf_counter() - t0, 1)
        print(f"stage {name}: {json.dumps(summary[name])[:400]}", flush=True)
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    print("WROTE", args.out)


if __name__ == "__main__":
    main()
