"""One-shot hardware validation: run serially when the TPU tunnel is alive.

Design (round-2 hardware postmortem): the tunnel claim can wedge and a
wedged `jax.devices()` HANGS rather than raising, so the orchestrator must
never touch the TPU itself. Every stage runs in its OWN subprocess with a
timeout; a killed stage loses only that stage. A cooldown between stages
lets the previous claim release cleanly before the next process claims.

Stages (each skippable via --skip):
  1. probe    — backend init + tiny matmul (fail fast if tunnel is wedged)
  2. pallas   — compiled-kernel correctness vs XLA (tools/tpu_pallas_check)
  3. bench    — bench.py end to end (its own supervisor adds retries)
  4. prims    — ground-truth gather/scatter/sort rates via scanned chains

Writes a JSON summary to tools/tpu_validate_out.json.

Usage: python tools/tpu_validate.py [--skip prims,pallas] [--iters 8]
"""

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

PROBE_SRC = (
    "import time,jax,jax.numpy as jnp;"
    "t0=time.perf_counter();d=jax.devices();"
    "print('devices',d,round(time.perf_counter()-t0,1));"
    "assert d and d[0].platform != 'cpu', f'cpu fallback: {d}';"
    "t0=time.perf_counter();"
    # fetch, not block_until_ready: the latter is not a sync on axon
    "s=float(jnp.sum(jnp.ones((512,512))@jnp.ones((512,512))));"
    "print('matmul_s',round(time.perf_counter()-t0,1),'sum',s)"
)


def run_stage(cmd, timeout_s):
    """Run one stage in its own PROCESS GROUP so a timeout kills the whole
    tree (bench.py spawns an inner child; killing only the parent would
    leave the grandchild holding the TPU claim into the next stage).
    Partial stdout/stderr of a timed-out stage is preserved — it says where
    the stage hung."""
    import signal
    t0 = time.perf_counter()
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, cwd=REPO, start_new_session=True)

    # if WE are killed (driver timeout), take the stage's process group down
    # with us — an orphaned stage child would hold the TPU claim forever
    def _reap(signum, frame):
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        raise SystemExit(128 + signum)

    old = [signal.signal(s, _reap) for s in (signal.SIGTERM, signal.SIGINT)]
    try:
        stdout, stderr = p.communicate(timeout=timeout_s)
        out = {"rc": p.returncode, "out": stdout[-3000:]}
        if p.returncode:
            out["err"] = stderr[-1200:]
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        stdout, stderr = p.communicate()
        out = {"rc": -1,
               "err": f"timed out after {timeout_s:.0f}s "
                      "(wedged tunnel claim?)",
               "out": (stdout or "")[-2000:],
               "err_tail": (stderr or "")[-1200:]}
    finally:
        for s, h in zip((signal.SIGTERM, signal.SIGINT), old):
            signal.signal(s, h)
    out["stage_s"] = round(time.perf_counter() - t0, 1)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip", default="")
    ap.add_argument("--iters", type=int, default=6)
    ap.add_argument("--cooldown", type=float, default=20.0)
    ap.add_argument("--out", default="tools/tpu_validate_out.json")
    args = ap.parse_args()
    skip = set(args.skip.split(",")) if args.skip else set()
    # share one persistent compile cache across stages and retries
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/jax_cache_det_tpu")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

    stages = [
        ("probe", [sys.executable, "-u", "-c", PROBE_SRC], 240),
        ("pallas", [sys.executable, "-u", "tools/tpu_pallas_check.py",
                    "--quick"], 1800),
        ("ragged", [sys.executable, "-u", "tools/tpu_ragged_check.py"], 900),
        ("bench", [sys.executable, "-u", "bench.py"], 3600 * 3),
        ("prims", [sys.executable, "-u", "tools/tpu_primitives_bench.py",
                   "--iters", str(args.iters)], 1800),
    ]
    summary = {}
    for i, (name, cmd, timeout_s) in enumerate(stages):
        if name in skip:
            continue
        summary[name] = run_stage(cmd, timeout_s)
        print(f"stage {name}: {json.dumps(summary[name])[:500]}", flush=True)
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
        if name == "probe" and summary[name]["rc"] != 0:
            print("probe failed; aborting remaining stages", flush=True)
            break
        if i + 1 < len(stages):
            time.sleep(args.cooldown)
    print("WROTE", args.out)


if __name__ == "__main__":
    main()
