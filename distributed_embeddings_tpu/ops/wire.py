"""Wire formats for the dp<->mp exchange collectives (ISSUE 5).

Every float collective in the embedding forward/backward — the mp->dp
combined-activation `all_to_all` (layers/dist_model_parallel.py
`_tp_bucket_exchange`), its autodiff transpose moving gradients dp->mp,
the dp->mp weight exchange (padded and ragged), and the row-sliced path's
`psum_scatter`/`all_gather` pair — moves f32 in the reference stack. On
TPU the standard mixed-precision lever is a **bf16 wire format with f32
local math**: encode to bf16 immediately before the collective, decode
immediately after, so the only numerics change is ONE round-to-nearest
per wire crossing while every gather/combine/update stays f32. That
exactly halves the dominant exchange bytes (the `[world, B, f, w]`
activation blocks) without touching the int id wire.

Formats:
  * ``f32``      — identity. The default; callers early-return to the
                   plain `lax` collective, so the lowered program is
                   byte-identical to the pre-wire-seam code.
  * ``bf16``     — round-to-nearest-even bf16 on the wire, both
                   directions.
  * ``bf16-sr``  — bf16 forward; **stochastically rounded** bf16 for the
                   gradient direction. SR spreads the rounding over both
                   neighbors with distance-proportional probability, so
                   ACROSS the many distinct gradient values of a step the
                   wire error centers on zero instead of carrying RNE's
                   systematic bias (the classic low-precision-training
                   argument). The randomness is a counter-less hash of
                   (lane position, value bits) — deterministic per trace,
                   no PRNG key plumbing through the collective seam; the
                   flip side is that the SAME value at the SAME lane
                   rounds the same way every step, so per-coordinate
                   zero-mean over time is NOT guaranteed (pass a
                   different ``salt`` per step if that matters).

The gradient direction is wrapped in `jax.custom_vjp` so the transpose
collective compresses with the *gradient* wire format and local math
stays f32 on both sides — in particular `wire_psum_scatter` re-expresses
the reduce-scatter as encode -> all_to_all -> decode -> f32 local sum, so
cross-device ACCUMULATION never happens in bf16 (a plain bf16
`psum_scatter` would round once per ring hop).

Int id wire: `encode_ids`/`decode_ids` narrow int32 ids to int16 where
the planner proves every value that can legally cross the wire fits
(`parallel/plan.py` sets ``TPBucket.id_wire_dtype`` — the same
prove-the-key-space-fits gate style as PR 4's int32-key-overflow check).
Encoding CLIPS to the int16 range: the planner gate guarantees every
valid id and the hot sentinel sit strictly below the clip ceiling, so an
out-of-range user id stays out-of-range after the round-trip and the
downstream clamp/drop semantics are bit-identical to the int32 wire.
"""

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "WIRE_FORMATS",
    "ID_WIRE_FORMATS",
    "STORE_DTYPES",
    "default_exchange_wire",
    "default_id_wire",
    "default_store_dtype",
    "default_delta_dtype",
    "resolve_wire",
    "resolve_store_dtype",
    "fp8_supported",
    "wire_itemsize",
    "id_wire_itemsize",
    "store_itemsize",
    "store_scale_bytes",
    "delta_row_bytes",
    "snapshot_row_bytes",
    "encode_rows",
    "decode_rows",
    "encode_rows_np",
    "decode_rows_np",
    "store_decode_bound",
    "seam_storage_dtypes",
    "encode_fwd",
    "encode_bwd",
    "stochastic_round_bf16",
    "encode_ids",
    "decode_ids",
    "int16_id_wire_ok",
    "wire_all_to_all",
    "wire_all_gather",
    "wire_psum_scatter",
    "wire_all_to_all_t",
    "wire_psum_scatter_t",
    "wire_id_all_to_all",
    "wire_id_all_gather",
    "ragged_exchange",
    "seam_float_dtypes",
    "seam_id_dtypes",
    "RAGGED_METADATA_DTYPES",
]

WIRE_FORMATS = ("f32", "bf16", "bf16-sr")
ID_WIRE_FORMATS = ("int32", "int16")

# storage dtypes of rows AT REST (ISSUE 15): the wire seam extended to
# memory. 'f32' is the bit-exact default (every storage path
# early-returns to the pre-seam arrays/files); 'int8' stores a row as
# int8 payload + ONE f32 per-row scale (scale = amax/127 — symmetric
# linear quantization, the classic row-wise scheme); 'fp8' stores
# float8_e4m3fn payload + per-row scale (scale = amax/448, the e4m3
# finite max) where the backend ships the dtype. One codec covers every
# row store that rides the train-to-serve spine: cold/offloaded bucket
# tables (decode at gather time), `store/` delta + snapshot stream
# payloads, and the vocab demotion stash.
STORE_DTYPES = ("f32", "int8", "fp8")

# quantization grids: payload magnitudes the per-row scale normalizes to
INT8_AMAX = 127.0
FP8_AMAX = 448.0          # float8_e4m3fn largest finite value

# clip ceiling of the int16 id wire; the planner admits a bucket only when
# every legal wire value (valid ids AND the hot sentinel rows_max) is
# strictly below it, so clipped out-of-range ids can never alias either
INT16_ID_MAX = 2**15 - 1


def _knob(env: str, fallback: str) -> str:
    """One resolution seam for every wire/storage knob default (ISSUE
    18): env var > tuned config-of-record > measured defaults >
    fallback — see tune.resolve."""
    from ..tune import resolve as _tune_resolve
    return _tune_resolve.knob_value(env, fallback)


def default_exchange_wire() -> str:
    """The ``DET_EXCHANGE_WIRE`` default for the float exchange wire
    ('f32' unless overridden by env or an adopted tuned config); an
    explicit ``exchange_wire=`` constructor argument always wins."""
    return resolve_wire(_knob("DET_EXCHANGE_WIRE", ""))


def default_id_wire() -> str:
    """``DET_ID_WIRE``: 'auto' (default) lets the planner narrow the id
    wire to int16 per bucket where the key space provably fits; 'int32'
    forces the full-width id wire everywhere."""
    v = _knob("DET_ID_WIRE", "auto")
    if v not in ("auto", "int32"):
        raise ValueError(
            f"DET_ID_WIRE={v!r}: expected 'auto' or 'int32'")
    return v


def resolve_wire(name: Optional[str]) -> str:
    """Validate/normalize a wire-format name (None -> 'f32')."""
    if name is None or name == "":
        return "f32"
    if name not in WIRE_FORMATS:
        raise ValueError(
            f"unknown exchange wire format {name!r}; expected one of "
            f"{WIRE_FORMATS}")
    return name


def wire_itemsize(name: str) -> int:
    """Bytes per element the float wire moves (accounting)."""
    return 4 if resolve_wire(name) == "f32" else 2


def id_wire_itemsize(name: str) -> int:
    return 2 if name == "int16" else 4


# ------------------------------------------------------- storage codec
def default_store_dtype() -> str:
    """The ``DET_STORE_DTYPE`` environment default for the at-rest row
    storage dtype ('f32' unless overridden); an explicit
    ``storage_dtype=`` constructor argument always wins. Per-bucket
    eligibility (only cold/offloaded buckets quantize) is decided at
    plan lowering time, like the exchange wire."""
    return resolve_store_dtype(_knob("DET_STORE_DTYPE", ""))


def default_delta_dtype() -> str:
    """``DET_DELTA_DTYPE``: payload dtype of published `store/` delta and
    snapshot stream files ('f32' default — byte-identical files to the
    pre-seam container). Independent of the table storage dtype: a
    fleet can stream int8 deltas to serving replicas whose tables are
    f32-resident, and vice versa."""
    return resolve_store_dtype(_knob("DET_DELTA_DTYPE", ""))


def resolve_store_dtype(name: Optional[str]) -> str:
    """Validate/normalize a storage-dtype name (None -> 'f32')."""
    if name is None or name == "":
        return "f32"
    if name not in STORE_DTYPES:
        raise ValueError(
            f"unknown storage dtype {name!r}; expected one of "
            f"{STORE_DTYPES}")
    if name == "fp8" and not fp8_supported():
        raise ValueError(
            "storage dtype 'fp8' requested but this backend ships no "
            "float8_e4m3fn (jax.numpy / ml_dtypes too old) — use 'int8' "
            "or 'f32'")
    return name


def fp8_supported() -> bool:
    """True when the toolchain ships float8_e4m3fn end to end (jnp for
    the device codec, ml_dtypes for the host/stream codec)."""
    if not hasattr(jnp, "float8_e4m3fn"):
        return False
    try:
        import ml_dtypes  # noqa: F401
        return hasattr(ml_dtypes, "float8_e4m3fn")
    except ImportError:
        return False


def store_itemsize(name: str) -> int:
    """Bytes per element a row payload occupies at rest."""
    return 4 if resolve_store_dtype(name) == "f32" else 1


def store_scale_bytes(name: str) -> int:
    """Per-row scale overhead bytes (one f32 per quantized row)."""
    return 0 if resolve_store_dtype(name) == "f32" else 4


def delta_row_bytes(width: int, dtype: str) -> int:
    """Bytes ONE published delta row costs at `dtype`: the 8-byte int64
    flat key + the width-element payload + the per-row scale. THE shared
    byte model: `exchange_padding_report`'s `delta_bytes_per_step`, the
    store's publish accounting, and the bench's measured-vs-model
    reconciliation all charge through this one formula (the
    `expected_collective_bytes` discipline applied to the stream)."""
    return 8 + width * store_itemsize(dtype) + store_scale_bytes(dtype)


def snapshot_row_bytes(width: int, dtype: str) -> int:
    """Bytes one snapshot table row costs at `dtype` (no key — snapshots
    carry whole tables in row order)."""
    return width * store_itemsize(dtype) + store_scale_bytes(dtype)


def store_decode_bound(rows, dtype: str, sr: bool = False):
    """Per-element absolute error bound of one encode/decode round trip
    at `dtype`, given the f32 `rows` ([..., width]): int8 RNE rounds to
    the nearest grid point (half a step, amax/254 per row; a full step
    amax/127 under SR), fp8-e4m3 keeps 3 mantissa bits (relative 2^-4 of
    the row amax after scaling). 0.0 at f32 — the bit-exact contract.
    Returns a [...]-shaped per-row bound (numpy)."""
    import numpy as np
    rows = np.asarray(rows, np.float32)
    amax = np.max(np.abs(rows), axis=-1)
    dtype = resolve_store_dtype(dtype)
    if dtype == "f32":
        return np.zeros_like(amax)
    if dtype == "int8":
        return amax / INT8_AMAX * (1.0 if sr else 0.5)
    return amax * (2.0 ** -4) * (2.0 if sr else 1.0)


def _row_scale(amax, grid_amax: float):
    """Per-row scale from the row amax; zero rows take scale 1 so the
    round trip reproduces exact zeros."""
    return jnp.where(amax > 0, amax / grid_amax, 1.0)


def encode_rows(rows: jax.Array, store_dtype: str, sr: bool = False,
                salt: int = 0x85EBCA6B):
    """f32 rows [..., width] -> (payload [..., width], scale [..., 1]).

    'f32' is the identity (scale is None — callers on the default path
    never materialize a scale array, the bit-exact early return).
    'int8': symmetric per-row linear quantization; `sr=True` rounds
    stochastically with the SAME keyless (lane, value-bits, salt) hash
    as `stochastic_round_bf16` — the training write-back path, so the
    quantization error of repeated updates centers on zero across
    values instead of accumulating RNE bias. 'fp8': e4m3 cast after the
    per-row rescale (e4m3's own RNE; SR is int8-only — 3 mantissa bits
    leave no headroom for the hash trick)."""
    store_dtype = resolve_store_dtype(store_dtype)
    if store_dtype == "f32":
        return rows, None
    rows = rows.astype(jnp.float32)
    amax = jnp.max(jnp.abs(rows), axis=-1, keepdims=True)
    if store_dtype == "int8":
        scale = _row_scale(amax, INT8_AMAX)
        y = rows / scale
        if sr:
            bits = lax.bitcast_convert_type(y, jnp.uint32)
            idx = lax.iota(jnp.uint32, y.size).reshape(y.shape)
            h = bits ^ (idx * jnp.uint32(2654435761) + jnp.uint32(salt))
            h = (h ^ (h >> 15)) * jnp.uint32(0x2C1B3C6D)
            h = (h ^ (h >> 12)) * jnp.uint32(0x297A2D39)
            h = h ^ (h >> 15)
            u = (h & jnp.uint32(0xFFFF)).astype(jnp.float32) / 65536.0
            q = jnp.floor(y + u)
        else:
            q = jnp.rint(y)
        payload = jnp.clip(q, -INT8_AMAX, INT8_AMAX).astype(jnp.int8)
        return payload, scale
    scale = _row_scale(amax, FP8_AMAX)
    payload = (rows / scale).astype(jnp.float8_e4m3fn)
    return payload, scale


def decode_rows(payload: jax.Array, scale, store_dtype: str) -> jax.Array:
    """(payload, scale) -> f32 rows; the gather-time decode. 'f32' is
    the identity."""
    if resolve_store_dtype(store_dtype) == "f32":
        return payload
    return payload.astype(jnp.float32) * scale


def encode_rows_np(rows, store_dtype: str, sr: bool = False,
                   salt: int = 0x85EBCA6B):
    """Host-side (numpy) twin of `encode_rows`. Default RNE (published
    stream/stash bytes must be deterministic and reproducible);
    ``sr=True`` is the touched-rows host APPLY's write-back (ISSUE 17) —
    the identical keyless (lane, value-bits, salt) hash as the device
    encoder, int8 only (fp8's own RNE cast, as on device)."""
    import numpy as np
    store_dtype = resolve_store_dtype(store_dtype)
    rows = np.asarray(rows, np.float32)
    if store_dtype == "f32":
        return rows, None
    amax = np.max(np.abs(rows), axis=-1, keepdims=True) \
        if rows.size else np.zeros(rows.shape[:-1] + (1,), np.float32)
    if store_dtype == "int8":
        scale = np.where(amax > 0, amax / INT8_AMAX, 1.0).astype(np.float32)
        with np.errstate(invalid="ignore"):
            y = (rows / scale).astype(np.float32)
            if sr and y.size:
                bits = y.view(np.uint32)
                idx = np.arange(y.size, dtype=np.uint32).reshape(y.shape)
                with np.errstate(over="ignore"):
                    h = bits ^ (idx * np.uint32(2654435761)
                                + np.uint32(salt))
                    h = (h ^ (h >> np.uint32(15))) * np.uint32(0x2C1B3C6D)
                    h = (h ^ (h >> np.uint32(12))) * np.uint32(0x297A2D39)
                    h = h ^ (h >> np.uint32(15))
                u = (h & np.uint32(0xFFFF)).astype(np.float32) / 65536.0
                q = np.floor(y + u)
            else:
                q = np.rint(y)
        payload = np.clip(q, -INT8_AMAX, INT8_AMAX).astype(np.int8)
        return payload, scale
    import ml_dtypes
    scale = np.where(amax > 0, amax / FP8_AMAX, 1.0).astype(np.float32)
    payload = (rows / scale).astype(ml_dtypes.float8_e4m3fn)
    return payload, scale


def decode_rows_np(payload, scale, store_dtype: str):
    import numpy as np
    if resolve_store_dtype(store_dtype) == "f32":
        return np.asarray(payload, np.float32)
    payload = np.asarray(payload)
    if store_dtype == "fp8":
        import ml_dtypes
        if payload.dtype != np.dtype(ml_dtypes.float8_e4m3fn):
            # .npz containers round-trip the custom float8 dtype as raw
            # 1-byte void — same bits, lost descriptor; view it back
            payload = payload.view(ml_dtypes.float8_e4m3fn)
    return payload.astype(np.float32) * np.asarray(scale, np.float32)


# ------------------------------------------------------------- encoders
def encode_fwd(x: jax.Array, wire: str) -> jax.Array:
    """Forward-direction wire encode (deterministic RNE for bf16*)."""
    if wire == "f32":
        return x
    return x.astype(jnp.bfloat16)


def encode_bwd(g: jax.Array, wire: str) -> jax.Array:
    """Gradient-direction wire encode ('bf16-sr' -> stochastic round)."""
    if wire == "f32":
        return g
    if wire == "bf16-sr":
        return stochastic_round_bf16(g)
    return g.astype(jnp.bfloat16)


def stochastic_round_bf16(x: jax.Array, salt: int = 0x9E3779B9) -> jax.Array:
    """f32 -> bf16 with stochastic rounding: P(round up) equals the
    fractional distance to the upper representable neighbor, so over an
    ensemble of distinct values the rounding error centers on zero
    (E[sr(X)] == E[X] when the hash is exercised across many values).

    The random source is a hash of (flat lane index, value bits, salt) —
    no PRNG key crosses the collective seam, and the result is
    deterministic for a given (array, salt), which keeps traced programs
    reproducible. The trade: a value that REPEATS at the same lane
    rounds identically every time, so the zero-mean property is across
    values/lanes, not per coordinate over steps — mix a per-step
    ``salt`` in if per-coordinate unbiasedness over time is required.
    Non-finite and non-f32 inputs fall back to the deterministic cast
    (adding noise bits to an inf/NaN pattern would corrupt it)."""
    if x.dtype != jnp.float32:
        return x.astype(jnp.bfloat16)
    bits = lax.bitcast_convert_type(x, jnp.uint32)
    # cheap integer mix (xxhash-style avalanche) of position ^ value bits
    idx = lax.iota(jnp.uint32, x.size).reshape(x.shape)
    h = bits ^ (idx * jnp.uint32(2654435761) + jnp.uint32(salt))
    h = (h ^ (h >> 15)) * jnp.uint32(0x2C1B3C6D)
    h = (h ^ (h >> 12)) * jnp.uint32(0x297A2D39)
    h = h ^ (h >> 15)
    rnd = h & jnp.uint32(0xFFFF)
    up = ((bits + rnd) >> 16).astype(jnp.uint16)
    sr = lax.bitcast_convert_type(up, jnp.bfloat16)
    return jnp.where(jnp.isfinite(x), sr, x.astype(jnp.bfloat16))


def int16_id_wire_ok(max_wire_value: int) -> bool:
    """True when every legal wire value (valid pre-offset ids and the
    sentinel) sits STRICTLY below the int16 clip ceiling — the
    planner-side gate for narrowing one bucket's id wire."""
    return 0 <= max_wire_value < INT16_ID_MAX


def encode_ids(ids: jax.Array, id_wire: str) -> jax.Array:
    """Narrow an int id block for the wire. Clipping (not wrapping) keeps
    out-of-range ids out of range: the planner gate puts every legal
    value strictly below INT16_ID_MAX, so a clipped invalid id can alias
    neither a valid row nor the hot sentinel."""
    if id_wire != "int16":
        return ids
    return jnp.clip(ids, -2**15, INT16_ID_MAX).astype(jnp.int16)


def decode_ids(ids: jax.Array, id_wire: str,
               dtype=jnp.int32) -> jax.Array:
    if id_wire != "int16":
        return ids
    return ids.astype(dtype)


# -------------------------------------------------- wrapped collectives
@functools.lru_cache(maxsize=None)
def _wired_all_to_all(axis: str, wire: str, dtype_name: str):
    """custom_vjp all_to_all (split 0 / concat 0): wire-encoded operand
    both directions, output decoded back to the caller's dtype. The
    split0/concat0 all_to_all is its own transpose, so the bwd rule is
    the same collective over the gradient wire."""
    out_dtype = jnp.dtype(dtype_name)

    def run(x, enc):
        y = enc(x, wire)
        y = lax.all_to_all(y, axis, split_axis=0, concat_axis=0)
        return y.astype(out_dtype)

    @jax.custom_vjp
    def f(x):
        return run(x, encode_fwd)

    def fwd(x):
        return run(x, encode_fwd), None

    def bwd(_, g):
        return (run(g, encode_bwd),)

    f.defvjp(fwd, bwd)
    return f


def wire_all_to_all(x: jax.Array, axis: str, wire: str) -> jax.Array:
    """`lax.all_to_all(split 0 / concat 0)` behind the wire seam.

    'f32' returns the plain collective — the lowered program is
    byte-identical to pre-seam code (the bit-exactness contract of the
    default path). Other formats compress the operand on the wire and
    decode to the input dtype; the autodiff transpose compresses the
    gradient with the format's gradient encoder."""
    wire = resolve_wire(wire)
    if wire == "f32":
        return lax.all_to_all(x, axis, split_axis=0, concat_axis=0)
    return _wired_all_to_all(axis, wire, x.dtype.name)(x)


@functools.lru_cache(maxsize=None)
def _wired_all_gather(axis: str, wire: str, dtype_name: str, world: int):
    """custom_vjp tiled all_gather over axis 0. The transpose of a tiled
    all_gather is a tiled psum_scatter; it is expressed here as
    encode -> all_to_all -> decode -> f32-local sum so cross-device
    accumulation never happens at wire precision."""
    out_dtype = jnp.dtype(dtype_name)

    @jax.custom_vjp
    def f(x):
        y = lax.all_gather(encode_fwd(x, wire), axis, axis=0, tiled=True)
        return y.astype(out_dtype)

    def fwd(x):
        return f(x), None

    def bwd(_, g):                       # g: [B, ...] -> [B_l, ...]
        h = encode_bwd(g, wire)
        h = h.reshape((world, g.shape[0] // world) + g.shape[1:])
        h = lax.all_to_all(h, axis, split_axis=0, concat_axis=0)
        return (h.astype(out_dtype).sum(axis=0),)

    f.defvjp(fwd, bwd)
    return f


def wire_all_gather(x: jax.Array, axis: str, wire: str,
                    world: int) -> jax.Array:
    """Tiled `lax.all_gather` over axis 0 behind the wire seam (the
    row-sliced path's weight broadcast)."""
    wire = resolve_wire(wire)
    if wire == "f32":
        return lax.all_gather(x, axis, axis=0, tiled=True)
    return _wired_all_gather(axis, wire, x.dtype.name, world)(x)


@functools.lru_cache(maxsize=None)
def _wired_psum_scatter(axis: str, wire: str, dtype_name: str, world: int):
    """custom_vjp tiled psum_scatter over dim 0, wire-compressed:
    fwd = encode -> all_to_all -> decode -> f32-local sum over sources
    (same wire volume as the reduce-scatter ring, but every ADD runs at
    the caller's precision); bwd = the transpose, a tiled all_gather of
    the wire-encoded gradient."""
    out_dtype = jnp.dtype(dtype_name)

    @jax.custom_vjp
    def f(x):                            # x: [B, ...] -> [B_l, ...]
        y = encode_fwd(x, wire)
        y = y.reshape((world, x.shape[0] // world) + x.shape[1:])
        y = lax.all_to_all(y, axis, split_axis=0, concat_axis=0)
        return y.astype(out_dtype).sum(axis=0)

    def fwd(x):
        return f(x), None

    def bwd(_, g):
        h = lax.all_gather(encode_bwd(g, wire), axis, axis=0, tiled=True)
        return (h.astype(out_dtype),)

    f.defvjp(fwd, bwd)
    return f


def wire_psum_scatter(x: jax.Array, axis: str, wire: str,
                      world: int) -> jax.Array:
    """Tiled `lax.psum_scatter` over dim 0 behind the wire seam (the
    row-sliced path's partial-sum return)."""
    wire = resolve_wire(wire)
    if wire == "f32":
        return lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
    return _wired_psum_scatter(axis, wire, x.dtype.name, world)(x)


# ------------------------------------------------- explicit transposes
# The lookahead drain stage (ISSUE 9, schedule/lookahead.py) moves the
# dense stage's activation cotangents dp->mp OUTSIDE autodiff: the
# forward exchange ran one step earlier in the prefetch stage, in a
# different traced region, so the gradient transpose must be invoked
# explicitly. These are the exact bwd rules of the custom_vjp wrappers
# above, exported as plain functions — 'f32' lowers to the identical
# lax collective JAX's own transpose rules emit for the monolithic
# step, which is what makes lookahead=1 bit-exact against it.

def wire_all_to_all_t(g: jax.Array, axis: str, wire: str) -> jax.Array:
    """Transpose of `wire_all_to_all`: the split0/concat0 all_to_all is
    its own transpose, over the GRADIENT wire encoding."""
    wire = resolve_wire(wire)
    if wire == "f32":
        return lax.all_to_all(g, axis, split_axis=0, concat_axis=0)
    y = lax.all_to_all(encode_bwd(g, wire), axis,
                       split_axis=0, concat_axis=0)
    return y.astype(g.dtype)


def wire_psum_scatter_t(g: jax.Array, axis: str, wire: str,
                        world: int) -> jax.Array:
    """Transpose of `wire_psum_scatter`: a tiled all_gather of the
    wire-encoded gradient (the reduce-scatter's transpose)."""
    del world  # kept for signature symmetry with wire_psum_scatter
    wire = resolve_wire(wire)
    if wire == "f32":
        return lax.all_gather(g, axis, axis=0, tiled=True)
    h = lax.all_gather(encode_bwd(g, wire), axis, axis=0, tiled=True)
    return h.astype(g.dtype)


# ------------------------------------------------------ id-wire exchanges
# Int ids carry no gradient, so these are plain (not custom_vjp)
# collectives behind the encode/decode pair — but they ARE exchange
# collectives, and the repo invariant (ISSUE 10, tools/lint_invariants.py
# 'naked-collective') is that every one of those lives in this module:
# the static wire-seam audit (analysis/passes.py) attributes every
# lowered collective's payload dtype to a plan group's declared format,
# and an id exchange assembled inline at a call site is exactly the kind
# of seam escape it exists to catch.

def wire_id_all_to_all(ids: jax.Array, axis: str, id_wire: str) -> jax.Array:
    """dp->mp id-block `all_to_all` (split 0 / concat 0) behind the id
    wire seam: int16 on the wire where the planner proved the key space
    fits (lossless — see `encode_ids` clip semantics), the caller's
    dtype on both sides."""
    return decode_ids(
        lax.all_to_all(encode_ids(ids, id_wire), axis,
                       split_axis=0, concat_axis=0),
        id_wire, ids.dtype)


def wire_id_all_gather(ids: jax.Array, axis: str, id_wire: str) -> jax.Array:
    """Tiled id `all_gather` over axis 0 behind the id wire seam (the
    row-sliced path's id broadcast)."""
    return decode_ids(
        lax.all_gather(encode_ids(ids, id_wire), axis, axis=0,
                       tiled=True),
        id_wire, ids.dtype)


def ragged_exchange(operand, output, in_off, send_sz, out_off, recv_sz,
                    axis: str, native: bool):
    """One true-splits all-to-all: sends `send_sz[d]` rows of `operand`
    (starting at `in_off[d]`) to each device d, landing at `out_off[d]` in
    d's `output`; `recv_sz[s]` rows arrive from each source s. This is the
    reference's `hvd.alltoall(x, splits)` contract
    (dist_model_parallel.py:134, :211): wire bytes are the true nnz, not
    the padded block.

    native=True lowers to `lax.ragged_all_to_all` (TPU; XLA:CPU has no
    lowering — see tools/tpu_ragged_check.py). native=False runs a
    semantics-exact emulation from equal-shaped collectives (all_gather +
    masked gather) so the FULL exchange path — metadata, layouts,
    reassembly — is executable and equivalence-tested on the CPU mesh;
    only the op itself differs, and that op is validated on hardware by
    the 'ragged' stage of tools/tpu_validate.py.

    The OPERAND must already be wire-encoded by the caller (the bucket's
    float or id format); the emulation's three metadata all_gathers move
    int32 offsets/sizes — `RAGGED_METADATA_DTYPES`, the one int32
    collective payload the wire-seam audit admits beyond the declared id
    wires when a program takes the emulated ragged path."""
    if native:
        return lax.ragged_all_to_all(operand, output, in_off, send_sz,
                                     out_off, recv_sz, axis_name=axis)
    ops = lax.all_gather(operand, axis)            # [world, S, inner]
    g_in = lax.all_gather(in_off, axis)            # [world, world]
    g_send = lax.all_gather(send_sz, axis)
    g_out = lax.all_gather(out_off, axis)
    me = lax.axis_index(axis)
    n_out = output.shape[0]
    i = jnp.arange(n_out)
    starts = g_out[:, me]                          # my chunk starts, per src
    # receive extent honors BOTH sides' metadata (sender's send_sz and my
    # recv_sz), so a wrong recv_sz corrupts the emulation the same way it
    # would corrupt the native op — CPU tests catch it
    sizes = jnp.minimum(g_send[:, me], recv_sz)
    src0 = g_in[:, me]
    m = ((i[None, :] >= starts[:, None])
         & (i[None, :] < (starts + sizes)[:, None]))   # [world, n_out]
    valid = jnp.any(m, axis=0)
    s_idx = jnp.argmax(m, axis=0)
    src_row = jnp.clip(src0[s_idx] + i - starts[s_idx], 0,
                       operand.shape[0] - 1)
    gathered = ops[s_idx, src_row]
    return jnp.where(valid[:, None], gathered, output)


# --------------------------------------------- static-audit attribution
# Pass-readable byte/dtype attribution hooks (ISSUE 10): the wire-seam
# and dtype-promotion passes (analysis/passes.py) read the legal
# StableHLO payload element types off the SAME module that implements
# the encodings, so the audit and the seam cannot drift. NOT attributed
# here by design: cross-device ACCUMULATIONS (hot-shard psum, loss
# psum) lower to `all_reduce`, which is outside the audited exchange
# collective set — they are the declared-uncompressed remainder.

# the ragged emulation's offset/size metadata all_gathers (see
# `ragged_exchange`) — int32 regardless of the bucket's id wire
RAGGED_METADATA_DTYPES = ("i32",)


def seam_float_dtypes(wire: str):
    """StableHLO element types a float exchange at `wire` may put on a
    collective ('f32' early-returns to the plain lax collective; every
    compressed format crosses as bf16)."""
    return ("f32",) if resolve_wire(wire) == "f32" else ("bf16",)


def seam_id_dtypes(id_wire: str):
    """StableHLO element types the id wire at `id_wire` may put on a
    collective ('auto' covers both: the planner narrows per bucket)."""
    if id_wire == "int16":
        return ("i16",)
    if id_wire == "int32":
        return ("i32",)
    return ("i16", "i32")


def seam_storage_dtypes(store_dtype: str):
    """StableHLO element types a bucket's at-rest storage at
    `store_dtype` may put in a lowered program ('f32' declares NOTHING
    quantized: an i8/f8 buffer in an all-f32-storage program is a seam
    escape the storage-dtype pass flags). Read by analysis/passes.py
    off this module so the audit and the codec cannot drift."""
    store_dtype = resolve_store_dtype(store_dtype)
    if store_dtype == "int8":
        return ("i8",)
    if store_dtype == "fp8":
        return ("f8E4M3FN",)
    return ()
