"""Sparse embedding-table updates: the TPU answer to IndexedSlices.

The reference keeps embedding gradients sparse end to end: its backward kernel
emits (unique_ids, unique_grads) consumed as tf.IndexedSlices (reference:
cc/kernels/embedding_lookup_kernels.cu:603-775, python/ops/
embedding_lookup_ops.py:105-122), and TF optimizers apply them row-wise.
Under plain `jax.grad` + optax the table gradient is a *dense* [V, w] array:
for a 4.2 GiB table that is a 4.2 GiB scatter-add temp per step plus a
full-table optimizer pass (~21 GiB of HBM traffic for adagrad — already
slower than the reference's entire step). This module keeps both the
gradient and the optimizer update O(touched rows):

  * `SparseRowGrad(ids, contribs)` — per-contribution gradient rows, static
    shape [N] / [N, w] (N = batch x hotness), never host-synced (the
    reference's D2H `num_unique_ids` copy at .cu:665 is the failure mode
    static shapes avoid).
  * `dedup_sum` — sort-based duplicate aggregation (the reference uses
    cub radix sort + unique, .cu:645-661). Empty/padded slots get a
    `sentinel` row id == V; JAX scatters DROP out-of-bounds ids, so
    sentinel rows vanish in the update without a mask.
  * `sparse_sgd` / `sparse_adagrad` — row-wise updates via .at[ids] ops.
    With donated buffers XLA performs them in place, touching only looked-up
    rows.

Aggregation strategy is selectable (`strategy=`):
  * 'sort'  — lax.sort + cumulative-sum differencing (scatter-free until the
    final row update). O(N log^2 N) comparator passes but no [V, w] temp.
  * 'dense' — scatter-add into a dense [V, w] zeros then a *masked* row
    update. Simple and fast when V*w is small; O(V, w) memory.
  Auto mode picks 'dense' below `DENSE_ELEMS_MAX` elements, 'sort' above.
"""

from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

# auto-strategy threshold: buckets up to this many elements aggregate through
# a dense temp (64 MiB at f32 width 16); larger buckets use the sort path.
# Tunable per hardware via DET_SPARSE_DENSE_MAX.
import os

DENSE_ELEMS_MAX = int(os.environ.get("DET_SPARSE_DENSE_MAX",
                                     16 * 1024 * 1024))


def fp_round(x: jax.Array, zero: jax.Array) -> jax.Array:
    """Pin a PRODUCT to its f32-rounded value before it feeds another
    add/sub: add `zero`, a RUNTIME 0.0 the compiler cannot prove
    constant (an SMEM hyperparameter slot inside the Pallas kernels, a
    traced-scalar derivation here — see `round_pin`). Backend FMA
    contraction happens BELOW HLO (LLVM fuses fmul+fadd inside one
    fusion; `lax.optimization_barrier`, bitcast round-trips and
    mul-by-dynamic-one pins do not reliably survive — all measured), and
    it is context-dependent: the same expression contracts differently
    inside a Pallas kernel body than next to a scatter whose operand
    forces materialization. The add-zero pin is IDEMPOTENT under
    contraction — fused or not, ``fma(a, b, 0) == round(a*b)`` — so the
    value is the plain IEEE product either way, and the fused pallas
    kernels and the XLA sort path round at identical seams. The
    bit-exactness contract between the two strategies (ISSUE 12,
    tests/test_pallas_fused.py) rests on this. (Sole side effect:
    ``-0.0`` pins to ``+0.0`` — invisible to ``==``.)"""
    return x + zero


def round_pin(traced_int: jax.Array) -> jax.Array:
    """An opaque f32 0.0 derived from a traced INTEGER scalar/array (int
    -> float cast can never be NaN/inf, so the 0-mul identity is exact):
    ``x*0`` only folds under nnan/ninf fast-math, which XLA:CPU/TPU do
    not enable for f32. Pass a TRACED value (adam's step count, an id
    array lane) — a concrete closure constant would fold at trace
    time (eager flows need no pin: per-op dispatch rounds every
    product)."""
    return (traced_int.reshape(-1)[0].astype(jnp.float32)
            * jnp.float32(0.0))


def measured_default(knob: str, fallback: str) -> str:
    """Resolved default for a DET_* dispatch knob.

    Thin delegate to ``tune.resolve.knob_value`` (ISSUE 18), which owns
    the resolution order: env var > the workload's config-of-record
    ``tools/tuned/<workload>.json`` (explicit opt-in via
    DET_TUNED_WORKLOAD / DET_TUNED_PATH, written by ``bench.py --mode
    tune``) > ``tools/measured_defaults.json`` (the PR-2 hardware-A/B
    writer, TPU backend only — CPU test equivalence must not silently
    change when a TPU bench has run on the same checkout;
    DET_MEASURED_DEFAULTS_CONSULT=1 forces the read off-TPU for the
    window rehearsal) > ``fallback``. Every tuned/measured adoption
    leaves a ``tune/adopt`` flight-recorder event."""
    from ..tune import resolve as _tune_resolve
    return _tune_resolve.knob_value(knob, fallback)


def _dedup_impl() -> str:
    """'sort' (default): segment_sum aggregation — EXACT, and rep comes out
    strictly increasing so downstream ops promise unique+sorted.
    'cumsum': scatter-free aggregation (cumsum + cummax + one sorted
    gather) — round-3 prims measured jax.ops.segment_sum at ~45 ns/row on
    TPU (it is a sorted-dupes scatter underneath) while cumsum streams at
    bandwidth; costs ~sqrt(N)*eps relative precision and downgrades the
    rep promise to unique-only (totals stay at segment-END rows, so OOB
    fillers interleave). Opt-in until tools/tpu_scatter_probe.py data
    lands."""
    return measured_default("DET_DEDUP_IMPL", "sort")


def dedup_flags() -> dict:
    """Scatter/gather promise kwargs legal for dedup_sum's rep output under
    the active implementation (see _dedup_impl)."""
    return {"unique_indices": True,
            "indices_are_sorted": _dedup_impl() == "sort"}


# ------------- hardware-gated kernel dispatch (shared gate machinery)
# Each alternative kernel implementation rides the same pattern: an EAGER
# compiled correctness check against the XLA formulation on the attached
# backend, a per-process verdict cache, and a dispatch predicate that
# consults only the cache under a jit trace (the check itself fetches
# compiled results, which is illegal while tracing). Compile failures count
# as not-validated: the r03 tunnel toolchain rejected every DMA kernel, so
# the failure path is load-bearing.
class _KernelGate:
    def __init__(self, env_value: str, validator, what: str):
        self.env_value = env_value      # DET_SCATTER_IMPL value that opts in
        self.validator = validator      # () -> bool, may raise
        self.what = what
        self.verdict = None             # None = unvalidated this process

    def prevalidate(self) -> bool:
        if self.verdict is not None:
            return self.verdict
        import warnings
        try:
            ok = bool(self.validator())
        except Exception as e:  # noqa: BLE001 - toolchain may reject kernels
            warnings.warn(f"{self.what}: kernel failed to compile/run on "
                          f"this backend ({str(e)[:200]}); using XLA paths")
            ok = False
        self.verdict = ok
        return ok

    def active(self, ref_array) -> bool:
        if (measured_default("DET_SCATTER_IMPL", "xla") != self.env_value
                or jax.default_backend() != "tpu"):
            return False
        if isinstance(ref_array, jax.core.Tracer):
            if self.verdict is None:
                self._warn_unvalidated_trace()
            return bool(self.verdict)
        return self.prevalidate()

    def _warn_unvalidated_trace(self) -> None:
        """The env knob requests this kernel but prevalidation never ran
        before tracing — the request is quietly inert (ADVICE r4). Say so
        once: the fix is calling prevalidate_active_impl() (or
        make_sparse_train_step / DistributedEmbedding construction, which
        call it) BEFORE the jit trace, or setting the knob earlier."""
        if getattr(self, "_trace_warned", False):
            return
        self._trace_warned = True
        import warnings
        warnings.warn(
            f"{self.what} requested, but the kernel was never validated on "
            "this backend before the jit trace — falling back to the XLA "
            "path. Call distributed_embeddings_tpu.ops.sparse_update."
            "prevalidate_active_impl() before tracing (set the env knob "
            "before constructing the train step).", RuntimeWarning,
            stacklevel=4)


def _validate_tiled() -> bool:
    """Compiled correctness of the tiled one-hot-matmul kernels
    (ops/pallas_tiled.py): gather, sgd and fused adagrad vs XLA."""
    import numpy as np
    from distributed_embeddings_tpu.ops import pallas_tiled as ptl
    rng = np.random.RandomState(0)
    v, w, n = 4096, 16, 2048
    ids = jnp.asarray(rng.randint(0, v, n).astype(np.int32))
    delta = jnp.asarray(rng.randn(n, w).astype(np.float32))
    table = jnp.asarray(rng.randn(v, w).astype(np.float32))
    got = ptl.tiled_sgd(table, ids, delta, 0.05, interpret=False)
    want = table.at[ids].add(-0.05 * delta, mode="drop")
    ok = bool(jnp.max(jnp.abs(got - want)) < 1e-3)
    acc = jnp.full((v, w), 0.1, jnp.float32)
    t2, a2 = ptl.tiled_adagrad(table, acc, ids, delta, 0.05,
                               interpret=False)
    rep, sums = dedup_sum(ids, delta, sentinel=v)
    a_want = acc.at[rep].add(sums * sums, mode="drop", **dedup_flags())
    d_want = -0.05 * sums * lax.rsqrt(
        jnp.take(a_want, jnp.minimum(rep, v - 1), axis=0) + 1e-10)
    t_want = table.at[rep].add(d_want, mode="drop", **dedup_flags())
    ok = (ok and bool(jnp.max(jnp.abs(a2 - a_want)) < 1e-3)
          and bool(jnp.max(jnp.abs(t2 - t_want)) < 1e-3))
    g3 = ptl.tiled_gather(table, ids, interpret=False)
    ok = ok and bool(
        jnp.max(jnp.abs(g3 - jnp.take(table, ids, axis=0))) < 1e-4)
    mu = jnp.zeros((v, w), jnp.float32)
    nu = jnp.zeros((v, w), jnp.float32)
    cnt = jnp.zeros((), jnp.int32)
    t4, mu4, nu4, c4 = ptl.tiled_adam(table, mu, nu, cnt, ids, delta, 0.01,
                                      interpret=False)
    tw, muw, nuw, cw = sparse_adam(table, mu, nu, cnt,
                                   SparseRowGrad(ids, delta), 0.01,
                                   strategy="sort")
    return (ok and bool(jnp.max(jnp.abs(t4 - tw)) < 1e-3)
            and bool(jnp.max(jnp.abs(mu4 - muw)) < 1e-3)
            and bool(jnp.max(jnp.abs(nu4 - nuw)) < 1e-3))


def _validate_pallas_scatter() -> bool:
    """Compiled correctness of the per-row DMA RMW kernels
    (ops/pallas_scatter.py): scatter-add + fused adagrad vs XLA."""
    import numpy as np
    from distributed_embeddings_tpu.ops import pallas_scatter as ps
    rng = np.random.RandomState(0)
    v, w, n = 4096, 16, 512
    ids = jnp.asarray(np.sort(rng.choice(v, n, replace=False))
                      .astype(np.int32))
    delta = jnp.asarray(rng.randn(n, w).astype(np.float32))
    table = jnp.zeros((v, w), jnp.float32)
    got = ps.scatter_add_sorted_unique(table, ids, delta, interpret=False)
    want = table.at[ids].add(delta, mode="drop")
    ok = bool(jnp.max(jnp.abs(got - want)) < 1e-5)
    # the fused adagrad kernel rides the same gate
    acc = jnp.full((v, w), 0.1, jnp.float32)
    t2, a2 = ps.adagrad_rows_sorted_unique(table, acc, ids, delta, 0.05,
                                           interpret=False)
    a_want = acc.at[ids].add(delta * delta, mode="drop")
    d_want = -0.05 * delta * lax.rsqrt(jnp.take(a_want, ids, axis=0) + 1e-10)
    t_want = table.at[ids].add(d_want, mode="drop")
    return (ok and bool(jnp.max(jnp.abs(a2 - a_want)) < 1e-5)
            and bool(jnp.max(jnp.abs(t2 - t_want)) < 1e-5))


def _width_class(width: int) -> int:
    """Pow2 lane-width shape-class for the fused-kernel compile probes:
    the compiled form of a BlockSpec kernel depends on the lane padding
    of its width, not the exact value, so one compiled verdict covers
    every width of a class (clamped to [8, 512] — wider tables share the
    512 class's tiling)."""
    c = 8
    while c < width and c < 512:
        c *= 2
    return c


class _ShapedKernelGate:
    """_KernelGate twin for the fused pallas family (ISSUE 12) with one
    verdict per (backend, width shape-class): the eager compile-probe
    runs once per class per process; dispatch under a jit trace consults
    only the cached verdicts. Gate failure is LOUD — every probe
    failure, numerics mismatch, or unvalidated-trace request warns and
    names the fallback — never silent."""

    def __init__(self, validator, what: str):
        self.validator = validator      # (width_class) -> bool, may raise
        self.what = what
        self.verdicts: dict = {}        # width class -> bool
        self._trace_warned: set = set()

    def prevalidate(self, width: int = 16) -> bool:
        cls = _width_class(width)
        if cls in self.verdicts:
            return self.verdicts[cls]
        import warnings
        try:
            ok = bool(self.validator(cls))
            if not ok:
                warnings.warn(
                    f"{self.what}: compiled kernels disagree with the XLA "
                    f"formulation at width class {cls} on this backend; "
                    "falling back to the tiled/XLA paths", RuntimeWarning)
        except Exception as e:  # noqa: BLE001 - toolchain may reject kernels
            warnings.warn(
                f"{self.what}: kernels failed to compile/run at width "
                f"class {cls} on this backend ({str(e)[:200]}); falling "
                "back to the tiled/XLA paths", RuntimeWarning)
            ok = False
        self.verdicts[cls] = ok
        return ok

    def ok(self, ref_array) -> bool:
        """Verdict for dispatch keyed on `ref_array`'s width. Off-TPU the
        kernels run in interpret mode — always ok (tier-1 exercises them
        bit-exactly on CPU). Under a jit trace only cached verdicts count
        (the eager probe fetches compiled results — illegal while
        tracing)."""
        if jax.default_backend() != "tpu":
            return True
        cls = _width_class(ref_array.shape[-1])
        if isinstance(ref_array, jax.core.Tracer):
            if cls not in self.verdicts and cls not in self._trace_warned:
                self._trace_warned.add(cls)
                import warnings
                warnings.warn(
                    f"{self.what} requested, but width class {cls} was "
                    "never validated on this backend before the jit trace "
                    "— falling back to the tiled/XLA paths. Call "
                    "distributed_embeddings_tpu.ops.sparse_update."
                    "prevalidate_active_impl() (or make_sparse_train_step "
                    "/ DistributedEmbedding construction) before tracing.",
                    RuntimeWarning, stacklevel=4)
            return bool(self.verdicts.get(cls))
        return self.prevalidate(ref_array.shape[-1])


def _validate_pallas_fused(width: int) -> bool:
    """Compiled correctness of the fused deduped-row kernels (ISSUE 12,
    ops/pallas_tiled.py *_rows + the weighted gather) at one lane-width
    class, against the XLA sort-path formulations they must reproduce."""
    import numpy as np
    from distributed_embeddings_tpu.ops import pallas_tiled as ptl
    rng = np.random.RandomState(0)
    v, n, w = 4096, 1024, width
    ids = jnp.asarray(rng.randint(0, v, n).astype(np.int32))
    delta = jnp.asarray(rng.randn(n, w).astype(np.float32))
    table = jnp.asarray(rng.randn(v, w).astype(np.float32))
    rep, sums = dedup_sum(ids, delta, sentinel=v)
    fl = dedup_flags()
    got = ptl.tiled_sgd_rows(table, rep, sums, 0.05, interpret=False)
    want = table.at[rep].add(-0.05 * sums, mode="drop", **fl)
    ok = bool(jnp.max(jnp.abs(got - want)) < 1e-4)
    acc = jnp.full((v, w), 0.1, jnp.float32)
    t2, a2 = ptl.tiled_adagrad_rows(table, acc, rep, sums, 0.05,
                                    interpret=False)
    a_want = acc.at[rep].add(sums * sums, mode="drop", **fl)
    d_want = -0.05 * sums * lax.rsqrt(
        jnp.take(a_want, jnp.minimum(rep, v - 1), axis=0) + 1e-10)
    t_want = table.at[rep].add(d_want, mode="drop", **fl)
    ok = (ok and bool(jnp.max(jnp.abs(a2 - a_want)) < 1e-4)
          and bool(jnp.max(jnp.abs(t2 - t_want)) < 1e-4))
    mu = jnp.zeros((v, w), jnp.float32)
    nu = jnp.zeros((v, w), jnp.float32)
    cnt = jnp.zeros((), jnp.int32)
    t4, mu4, nu4, _ = ptl.tiled_adam_rows(table, mu, nu, cnt, rep, sums,
                                          0.01, interpret=False)
    tw, muw, nuw, _ = sparse_adam(table, mu, nu, cnt,
                                  SparseRowGrad(ids, delta), 0.01,
                                  strategy="sort")
    ok = (ok and bool(jnp.max(jnp.abs(t4 - tw)) < 1e-4)
          and bool(jnp.max(jnp.abs(mu4 - muw)) < 1e-4)
          and bool(jnp.max(jnp.abs(nu4 - nuw)) < 1e-4))
    # fused forward: weighted gather->combine vs the XLA gather+einsum
    ids2 = ids[:(n // 4) * 4].reshape(-1, 4)
    wts = jnp.asarray(np.abs(rng.rand(*ids2.shape)).astype(np.float32))
    got_f = ptl.fused_lookup_combine(table, ids2, wts, "sum",
                                     interpret=False)
    want_f = jnp.einsum("bk,bkw->bw", wts, jnp.take(table, ids2, axis=0))
    return ok and bool(jnp.max(jnp.abs(got_f - want_f)) < 1e-3)


_TILED_GATE = _KernelGate("tiled", _validate_tiled,
                          "DET_SCATTER_IMPL=tiled")
# the round-3 per-row DMA RMW kernels (ops/pallas_scatter.py) moved to
# DET_SCATTER_IMPL=pallas-dma in round 12: 'pallas' now names the fused
# deduped-row tile-walk strategy below. The DMA family keeps its gate —
# the r03 toolchain rejected every make_async_copy kernel, so its
# failure path stays load-bearing.
_PALLAS_GATE = _KernelGate("pallas-dma", _validate_pallas_scatter,
                           "DET_SCATTER_IMPL=pallas-dma")
_PALLAS_FUSED_GATE = _ShapedKernelGate(_validate_pallas_fused,
                                       "DET_SCATTER_IMPL=pallas")


def prevalidate_tiled() -> bool:
    return _TILED_GATE.prevalidate()


def tiled_kernels_ok(ref_array) -> bool:
    """Hardware-validation verdict for the tiled kernels, independent of
    which knob routed here (env knob or explicit strategy="tiled"). Off-TPU
    the kernels run in interpret mode — always ok. Under a jit trace only
    the cached verdict is consulted (prevalidate_active_impl runs the eager
    check); an unvalidated compiled path is NEVER dispatched."""
    if jax.default_backend() != "tpu":
        return True
    if isinstance(ref_array, jax.core.Tracer):
        if _TILED_GATE.verdict is None:
            _TILED_GATE._warn_unvalidated_trace()
        return bool(_TILED_GATE.verdict)
    return _TILED_GATE.prevalidate()


def _use_tiled(ref_array) -> bool:
    return _TILED_GATE.active(ref_array)


def tiled_fwd_ok_static() -> bool:
    """Trace-time twin of `tiled_kernels_ok` that never triggers an eager
    prevalidation: off-TPU the kernels run in interpret mode (always ok);
    on TPU only an already-cached hardware verdict counts (the layer /
    train-step constructors run `prevalidate_active_impl` eagerly, so by
    trace time the verdict exists whenever the tiled path is requested)."""
    if jax.default_backend() != "tpu":
        return True
    return bool(_TILED_GATE.verdict)


def _tiled_route(strategy: str, ref_array) -> bool:
    """True when the tiled kernels should serve this update: explicit
    strategy='tiled' (validation-gated on TPU, interpret off-TPU) or
    auto + DET_SCATTER_IMPL=tiled. An explicitly-requested but
    unvalidated tiled path falls back to the XLA sort path — the gate
    exists precisely because this toolchain rejects whole kernel classes."""
    if strategy == "tiled":
        return tiled_kernels_ok(ref_array)
    return strategy == "auto" and _use_tiled(ref_array)


def prevalidate_pallas_scatter() -> bool:
    return _PALLAS_GATE.prevalidate()


def prevalidate_pallas_fused(width: int = 16) -> bool:
    """Eager compile-probe of the fused pallas family at `width`'s
    shape-class (see _ShapedKernelGate)."""
    return _PALLAS_FUSED_GATE.prevalidate(width)


def pallas_kernels_ok(ref_array) -> bool:
    """Validation verdict for the fused pallas kernel family, keyed on
    `ref_array`'s width class. Off-TPU the kernels run in interpret mode
    — always ok; under a jit trace only cached verdicts count."""
    return _PALLAS_FUSED_GATE.ok(ref_array)


def pallas_fwd_ok_static(width: int) -> bool:
    """Trace-time twin of `pallas_kernels_ok` (the tiled_fwd_ok_static
    analogue): off-TPU always ok (interpret); on TPU only an
    already-cached verdict for `width`'s class counts."""
    if jax.default_backend() != "tpu":
        return True
    return bool(_PALLAS_FUSED_GATE.verdicts.get(_width_class(width)))


def _pallas_requested(strategy: str) -> bool:
    """Did this call opt into the fused pallas strategy: explicit
    strategy='pallas', or auto + DET_SCATTER_IMPL=pallas (TPU only —
    the env route never flips CPU test numerics)."""
    if strategy == "pallas":
        return True
    return (strategy == "auto"
            and measured_default("DET_SCATTER_IMPL", "xla") == "pallas"
            and jax.default_backend() == "tpu")


_PALLAS_FALLBACK_WARNED: set = set()


def _route_static(strategy: str, width: Optional[int]) -> str:
    """The ONE fallback lattice — 'pallas' | 'tiled' | 'xla' from the
    request knobs, the cumsum refusal and the CACHED gate verdicts (no
    probing, no warnings). Shared by dispatch (`_scatter_route`), the
    obs label (`active_scatter_impl`) and the fold planner
    (`update_consumes_sort`) so the three can never drift. ``width``
    keys the pallas verdict's shape class; None means "any validated
    class" — the process-level telemetry view."""
    if _pallas_requested(strategy):
        if _dedup_impl() != "cumsum":
            if jax.default_backend() != "tpu":
                return "pallas"         # interpret-mode kernels
            vs = _PALLAS_FUSED_GATE.verdicts
            if (any(vs.values()) if width is None
                    else bool(vs.get(_width_class(width)))):
                return "pallas"
        # requested but unavailable (gate / cumsum): the loud fallback
        if jax.default_backend() == "tpu" and bool(_TILED_GATE.verdict):
            return "tiled"
        return "xla"
    if strategy == "tiled":
        return ("tiled" if jax.default_backend() != "tpu"
                or bool(_TILED_GATE.verdict) else "xla")
    if (strategy == "auto"
            and measured_default("DET_SCATTER_IMPL", "xla") == "tiled"
            and jax.default_backend() == "tpu"):
        return "tiled" if bool(_TILED_GATE.verdict) else "xla"
    return "xla"


def _scatter_route(strategy: str, ref_array) -> str:
    """Which update family serves this call: `_route_static`'s lattice,
    plus the EAGER per-shape-class compile probe (`pallas_kernels_ok`
    may prevalidate outside a trace) and the loud-fallback warning. A
    requested-but-unavailable pallas path falls back to the
    hardware-validated tiled family, else to the XLA path — never
    silently. The cumsum dedup impl also falls back: its rep stream is
    unique but UNSORTED, which the tile walk's chunk layout cannot
    consume."""
    if (_pallas_requested(strategy) and _dedup_impl() != "cumsum"
            and pallas_kernels_ok(ref_array)):
        return "pallas"
    if not _pallas_requested(strategy):
        # the tiled routes keep their own eager probe (_KernelGate)
        return "tiled" if _tiled_route(strategy, ref_array) else "xla"
    # pallas requested but unavailable: resolve the fallback, loudly
    route = _route_static(strategy, ref_array.shape[-1])
    reason = ("cumsum-dedup" if _dedup_impl() == "cumsum"
              else "gate-failed")
    if reason not in _PALLAS_FALLBACK_WARNED:
        _PALLAS_FALLBACK_WARNED.add(reason)
        import warnings
        warnings.warn(
            f"DET_SCATTER_IMPL=pallas requested but unavailable "
            f"({reason}); this update dispatches to the {route} "
            "path instead", RuntimeWarning, stacklevel=3)
    return route


def gate_verdicts() -> dict:
    """{impl: verdict} for the ``kernels/gate_verdict{impl=}`` obs gauge:
    1 = hardware-validated, 0 = probe failed, -1 = never probed (off-TPU
    interpret mode, or the impl was never requested). The fused pallas
    gate aggregates its per-shape-class verdicts: 1 only when every
    probed class validated — so a run can legitimately show a
    pallas-labeled update span (SOME class dispatched) next to a 0 gauge
    (not ALL classes validated); the mixed-verdict case is visible, not
    averaged away."""
    def enc(v):
        return -1 if v is None else int(bool(v))
    vs = _PALLAS_FUSED_GATE.verdicts
    return {"tiled": enc(_TILED_GATE.verdict),
            "pallas-dma": enc(_PALLAS_GATE.verdict),
            "pallas": (-1 if not vs else int(all(vs.values())))}


def active_scatter_impl(strategy: str = "auto") -> str:
    """Static best answer to "which update family will a step traced now
    dispatch to" — the obs label for the per-strategy update-phase span
    and bench arm records. `_route_static` at the process level (no
    width, no eager probes)."""
    return _route_static(strategy, None)


def prevalidate_active_impl(strategy: Optional[str] = None,
                            widths=None) -> None:
    """Eagerly validate whichever kernel impl the env knobs (or an explicit
    strategy= argument) select so subsequently-traced train steps can
    dispatch to it. Call once before jitting a train step; no-op for the
    XLA default. Wired into make_sparse_train_step and
    DistributedEmbedding construction, so user code need not call it.

    `widths`: the table lane widths the caller will actually dispatch at
    (the layer/step factories pass their plan's bucket+row widths) — the
    fused pallas gate probes one compiled verdict per width SHAPE-CLASS,
    and a class never probed eagerly can never validate under the jit
    trace. None falls back to the two bench lane classes (16, 128)."""
    impl = measured_default("DET_SCATTER_IMPL", "xla")
    if jax.default_backend() != "tpu":
        return
    if (impl == "tiled" or strategy == "tiled"
            or measured_default("DET_LOOKUP_PATH", "auto") == "tiled"):
        _TILED_GATE.prevalidate()
    if (impl == "pallas" or strategy == "pallas"
            or measured_default("DET_LOOKUP_PATH", "auto") == "fused"):
        for w in sorted({_width_class(w)
                         for w in (widths or (16, 128))}):
            _PALLAS_FUSED_GATE.prevalidate(w)
    if impl == "pallas-dma":
        _PALLAS_GATE.prevalidate()


def _static_float(x):
    """float(x) when x is compile-time static (Python scalar or concrete
    array); None when traced — Pallas kernel hyperparameters must be
    static, so traced values route callers to the XLA path."""
    try:
        return float(x)
    except Exception:  # noqa: BLE001 - ConcretizationTypeError et al.
        return None


def _use_pallas_scatter(ref_array) -> bool:
    return _PALLAS_GATE.active(ref_array)


def _row_scatter_add(table: jax.Array, rep: jax.Array,
                     delta: jax.Array) -> jax.Array:
    """table[rep] += delta for dedup output (unique rep; OOB fillers carry
    zero delta). Routes to the per-row DMA RMW kernel under
    DET_SCATTER_IMPL=pallas-dma when hardware-validated (prevalidate
    above); default is the flagged XLA scatter."""
    if _use_pallas_scatter(table):
        from distributed_embeddings_tpu.ops import pallas_scatter as ps
        return ps.scatter_add_sorted_unique(
            table, rep, delta.astype(table.dtype))
    return table.at[rep].add(delta.astype(table.dtype), mode="drop",
                             **dedup_flags())


def take_rows(table: jax.Array, ids: jax.Array) -> jax.Array:
    """Row gather via raw lax.gather with PROMISE_IN_BOUNDS: emits no
    bounds-check constants, so it is legal inside `compute_on` host regions
    on host-memory operands (jnp.take's clamp constants live in device space
    and trip XLA's memory-space checker). Caller must pre-clamp ids."""
    dn = lax.GatherDimensionNumbers(offset_dims=(1,), collapsed_slice_dims=(0,),
                                    start_index_map=(0,))
    return lax.gather(table, ids[:, None], dn,
                      slice_sizes=(1, table.shape[1]),
                      mode=lax.GatherScatterMode.PROMISE_IN_BOUNDS)


def scatter_add_rows(table: jax.Array, ids: jax.Array,
                     rows: jax.Array) -> jax.Array:
    """Row scatter-add, PROMISE_IN_BOUNDS (see take_rows). Caller must
    pre-clamp ids and zero any masked rows."""
    dn = lax.ScatterDimensionNumbers(update_window_dims=(1,),
                                     inserted_window_dims=(0,),
                                     scatter_dims_to_operand_dims=(0,))
    return lax.scatter_add(table, ids[:, None], rows.astype(table.dtype), dn,
                           mode=lax.GatherScatterMode.PROMISE_IN_BOUNDS)


class SparseRowGrad(NamedTuple):
    """Per-contribution gradient for one table (shard): row `ids[n]` received
    gradient row `contribs[n]`. Duplicate ids allowed; padded slots must
    carry zero contribs (any id) or id >= V (dropped on scatter)."""
    ids: jax.Array       # [N] int32
    contribs: jax.Array  # [N, w]


def concat_grads(grads) -> "SparseRowGrad":
    grads = list(grads)
    if len(grads) == 1:
        return grads[0]
    return SparseRowGrad(
        jnp.concatenate([g.ids for g in grads]),
        jnp.concatenate([g.contribs for g in grads], axis=0))


def dedup_sum(ids: jax.Array, contribs: jax.Array, sentinel: int,
              presorted=None):
    """Aggregate duplicate row ids: returns (rep_ids [N], sums [N, w]) where
    segment s's id sits at rep_ids[s] with its total in sums[s]; unused slots
    carry rep_ids >= sentinel (dropped by the subsequent scatter).

    Sort by id, derive exact integer segment indices from the sorted key
    boundaries, and segment-sum the permuted rows. (A cumsum-difference
    formulation would avoid the segment scatter but loses ~N*eps relative
    precision at N in the millions — exactness wins here, matching the
    reference's sort+unique+sum contract, .cu:645-661.)

    `presorted` optionally carries this id stream's sort artifacts (an
    `embedding_ops.GroupSort` — sid/perm/seg_start under the SAME canonical
    key with `rows == sentinel`) from an earlier sort, e.g. the tapped
    forward's (TapResiduals): the dedup then runs zero sort ops and is
    bit-identical to the fresh-sort path, the analogue of the reference
    backward reusing forward-sorted ids (.cu:706-773).

    rep is STRICTLY INCREASING by construction: real segments carry the
    sorted unique ids (any OOB inputs are pre-collapsed onto `sentinel`,
    keeping one dropped segment), and each unused slot s carries
    `sentinel + s` — still out of bounds, but never equal to another slot.
    Downstream scatters/gathers may therefore promise
    ``unique_indices=True, indices_are_sorted=True``, which matters: the
    round-3 TPU prims data measured XLA's duplicate-safe scatter lowering
    at ~100-280 ns/row — the single dominant cost of the whole train step.
    (Requires sentinel + N < 2^31; per-shard vocab always satisfies this.)
    """
    n = ids.shape[0]
    iota = lax.iota(jnp.int32, n)
    if presorted is not None:
        sid, perm, is_start = (presorted.sid, presorted.perm,
                               presorted.seg_start)
    else:
        # collapse BOTH invalid sides onto the sentinel: a plain min() would
        # let negative ids through, and JAX scatters treat negative indices
        # as NumPy-style from-the-end (mode="drop" only drops ids outside
        # [-V, V)), silently updating the TAIL of the table (ADVICE r3)
        ids32 = ids.astype(jnp.int32)
        keys = jnp.where(ids32 < 0, jnp.int32(sentinel),
                         jnp.minimum(ids32, jnp.int32(sentinel)))
        sid, perm = lax.sort_key_val(keys, iota)
        is_start = jnp.concatenate(
            [jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    rows = jnp.take(contribs, perm, axis=0)
    if _dedup_impl() == "cumsum":
        return _dedup_sum_cumsum(sid, rows, is_start, sentinel, iota)
    seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1      # exact int prefix
    sums = jax.ops.segment_sum(rows, seg, num_segments=n,
                               indices_are_sorted=True)
    rep = (jnp.int32(sentinel) + iota).at[seg].set(
        sid, mode="drop", indices_are_sorted=True)
    return rep, sums.astype(contribs.dtype)


def _dedup_sum_cumsum(sid, rows, is_start, sentinel, iota):
    """Scatter-free aggregation (see _dedup_impl): per-segment totals land
    at each segment's END row; every other slot carries a unique OOB
    filler. rep is unique but NOT sorted (fillers interleave) — consumers
    must use dedup_flags() rather than hardcoding promises."""
    n = sid.shape[0]
    is_end = jnp.concatenate([sid[1:] != sid[:-1], jnp.ones((1,), bool)])
    p = jnp.cumsum(rows.astype(jnp.float32), axis=0)
    begin = lax.cummax(jnp.where(is_start, iota, -1))
    p_prev = jnp.where(
        (begin > 0)[:, None],
        jnp.take(p, jnp.maximum(begin - 1, 0), axis=0,
                 indices_are_sorted=True), 0.0)
    sums = jnp.where(is_end[:, None], p - p_prev, 0.0)
    # fillers start at sentinel+1: sid can itself equal sentinel (collapsed
    # OOB segment), and a filler must never collide with it
    rep = jnp.where(is_end, sid, jnp.int32(sentinel) + 1 + iota)
    return rep, sums.astype(rows.dtype)


def _dense_sum(ids, contribs, rows):
    """[V, w] dense aggregation: scatter-add (OOB ids dropped), plus a row
    contribution COUNT so the updater can skip untouched rows (and so
    per-device partial aggregates can be psummed before thresholding —
    the hot-row shard's replicated update does exactly that).

    One WIDENED scatter carries both: each contribution row is extended
    with a 1.0 count column, so the count comes out of the same scatter as
    the data. Round-3 prims: scatter cost is per-ROW (~55-106 ns), so two
    n-row scatters (data + count) cost twice one — the fusion halves
    the dense path's descriptor count. Returns (g [rows, w], counts [rows]
    f32)."""
    w = contribs.shape[-1]
    ext = jnp.concatenate(
        [contribs.astype(jnp.float32),
         jnp.ones((contribs.shape[0], 1), jnp.float32)], axis=1)
    # negative ids would wrap NumPy-style onto the table tail (see
    # dedup_sum); route them to the dropped OOB row instead
    safe_ids = jnp.where(ids < 0, rows, ids)
    dense_ext = jnp.zeros((rows, w + 1), jnp.float32).at[safe_ids].add(
        ext, mode="drop")
    return dense_ext[:, :w], dense_ext[:, w]


def apply_dense_rows(kind: str, table, state, g, touched, lr, **hp):
    """Apply a DENSE aggregated gradient `g` [rows, w] with a boolean
    `touched` row mask to a (small) table + optimizer state — the exact
    masked-dense rules of sparse_sgd/adagrad/adam's 'dense' strategy,
    factored so the hot-row shard's replicated update (which must psum
    per-device dense partials BEFORE applying) shares one set of numerics
    with the dense aggregation strategy. Returns (table, state)."""
    t = touched[:, None]
    if kind == "sgd":
        # untouched rows carry g == 0: the add is the identity there
        return table + (-lr * g).astype(table.dtype), tuple(state)
    if kind == "adagrad":
        (acc,) = state
        eps = hp.get("eps", 1e-10)
        acc_new = acc + jnp.where(t, g * g, 0.0)
        upd = jnp.where(t, -lr * g * lax.rsqrt(acc_new + eps), 0.0)
        return table + upd.astype(table.dtype), (acc_new,)
    if kind == "adam":
        mu, nu, count = state
        b1 = hp.get("b1", 0.9)
        b2 = hp.get("b2", 0.999)
        eps = hp.get("eps", 1e-8)
        count = count + 1
        c1 = 1.0 - b1 ** count.astype(jnp.float32)
        c2 = 1.0 - b2 ** count.astype(jnp.float32)
        mu_new = jnp.where(t, b1 * mu + (1 - b1) * g, mu)
        nu_new = jnp.where(t, b2 * nu + (1 - b2) * g * g, nu)
        upd = jnp.where(t, -lr * (mu_new / c1)
                        / (jnp.sqrt(nu_new / c2) + eps), 0.0)
        return table + upd.astype(table.dtype), (mu_new, nu_new, count)
    raise ValueError(f"Unknown sparse optimizer {kind!r}")


def _pick(strategy: str, rows: int, width: int) -> str:
    if strategy != "auto":
        return strategy
    # env read per call (not at import): lets the bench A/B strategies by
    # re-tracing with a different DET_SPARSE_DENSE_MAX
    mx = int(os.environ.get("DET_SPARSE_DENSE_MAX", DENSE_ELEMS_MAX))
    return "dense" if rows * width <= mx else "sort"


def _usable_presorted(presorted, grad: SparseRowGrad, rows: int):
    """The given GroupSort, or None when it cannot serve this grad: the
    artifact must cover exactly this id stream (same static length). A
    mismatched artifact (e.g. a per-group sort offered against a
    multi-group concat) degrades to the fresh-sort path rather than
    corrupting the update."""
    if presorted is None or presorted.sid.shape[0] != grad.ids.shape[0]:
        return None
    return presorted


# ------------------------------------------------------------------ SGD
def sparse_sgd(table: jax.Array, grad: SparseRowGrad, lr,
               strategy: str = "auto", presorted=None) -> jax.Array:
    """table[ids] -= lr * contribs. Under 'auto'/'dense', duplicates need
    no aggregation (add is associative) and the plain duplicate-safe
    scatter runs; OOB/padded ids are dropped. The EXPLICIT 'sort'
    strategy — and the fused 'pallas' strategy built on its aggregation
    — dedups first (one segment-sum total per row, the reference's
    unique-grad contract): the sort aggregation IS the strategy, it
    consumes the folded forward sort, and it is the seam that makes the
    fused pallas step bit-exact against the XLA sort path (ISSUE 12 —
    duplicate-heavy streams see last-ulp differences vs the sequential
    scatter, within every documented tolerance). (The round-3
    DET_SGD_DEDUP knob this resembles was removed in round 5 without a
    hardware number; the tiled kernel family and this seam subsume its
    hypothesis.) `presorted` (GroupSort) feeds the tiled/pallas sorted
    stream and the sort-strategy dedup; 'auto''s scatter ignores it."""
    rows = table.shape[0]
    ps = _usable_presorted(presorted, grad, rows)
    route = _scatter_route(strategy, table)
    if route == "tiled":
        from distributed_embeddings_tpu.ops import pallas_tiled as ptl
        return ptl.tiled_sgd(table, grad.ids, grad.contribs, lr,
                             presorted=(None if ps is None
                                        else (ps.sid, ps.perm)))
    if route == "pallas" or strategy == "sort":
        rep, sums = dedup_sum(grad.ids, grad.contribs, sentinel=rows,
                              presorted=ps)
        if route == "pallas":
            from distributed_embeddings_tpu.ops import pallas_tiled as ptl
            return ptl.tiled_sgd_rows(table, rep, sums, lr)
        return table.at[rep].add((-lr * sums).astype(table.dtype),
                                 mode="drop", **dedup_flags())
    # negative ids -> dropped OOB row, not NumPy wraparound (see dedup_sum)
    safe_ids = jnp.where(grad.ids < 0, table.shape[0], grad.ids)
    return table.at[safe_ids].add(
        (-lr * grad.contribs.astype(jnp.float32)).astype(table.dtype),
        mode="drop")


# -------------------------------------------------------------- Adagrad
def sparse_adagrad(table: jax.Array, accum: jax.Array, grad: SparseRowGrad,
                   lr, eps: float = 1e-10, strategy: str = "auto",
                   presorted=None):
    """Row-wise adagrad matching optax.adagrad on the touched rows:
        acc[r]   += (sum of contribs for r)^2
        table[r] -= lr * sum / sqrt(acc[r] + eps)
    Duplicates are aggregated first (the reference's unique-grad contract).
    `presorted` (GroupSort over this id stream, rows == table.shape[0])
    removes the sort from both the tiled kernel and the dedup pass —
    bit-identical results either way. Returns (new_table, new_accum).
    """
    rows = table.shape[0]
    ps = _usable_presorted(presorted, grad, rows)
    route = _scatter_route(strategy, table)
    if route == "tiled":
        # tiled one-hot-matmul kernel: sort + in-kernel aggregation, no
        # dedup pass, no scatter (see ops/pallas_tiled.py). Explicit
        # strategy="tiled" runs in interpret mode off-TPU (tests).
        from distributed_embeddings_tpu.ops import pallas_tiled as ptl
        return ptl.tiled_adagrad(table, accum, grad.ids, grad.contribs,
                                 lr, eps=eps,
                                 presorted=(None if ps is None
                                            else (ps.sid, ps.perm)))
    if route == "pallas":
        # fused sparse path (ISSUE 12): the EXACT dedup aggregation
        # (shared bit-for-bit with the sort path below, consuming the
        # folded forward sort) feeds one tile-walk RMW stream that reads
        # and writes each touched table+accumulator tile once — vs the
        # sort path's 2 scatters + 1 gather over the same rows.
        # Bit-exact vs the sort path (tests/test_pallas_fused.py).
        from distributed_embeddings_tpu.ops import pallas_tiled as ptl
        rep, sums = dedup_sum(grad.ids, grad.contribs, sentinel=rows,
                              presorted=ps)
        return ptl.tiled_adagrad_rows(table, accum, rep, sums, lr,
                                      eps=eps)
    how = _pick(strategy, rows, table.shape[-1])
    if how == "dense":
        g, counts = _dense_sum(grad.ids, grad.contribs, rows)
        t_new, (acc_new,) = apply_dense_rows(
            "adagrad", table, (accum,), g, counts > 0, lr, eps=eps)
        return t_new, acc_new
    rep, sums = dedup_sum(grad.ids, grad.contribs, sentinel=rows,
                          presorted=ps)
    lr_static = _static_float(lr)
    if _use_pallas_scatter(table) and lr_static is not None:
        # fused RMW stream: one pass reads+updates table and accumulator
        # rows together (vs two scatters + a gather of the same rows).
        # lr must be compile-time static (kernel hyperparameter); a traced
        # lr (schedule passed through jit args) takes the XLA path
        from distributed_embeddings_tpu.ops import pallas_scatter as ps
        return ps.adagrad_rows_sorted_unique(table, accum, rep, sums,
                                             lr_static, eps)
    # rep is strictly increasing under the default impl (dedup_sum
    # contract) => both scatter promises hold; without them XLA's
    # duplicate-safe lowering costs ~100-280 ns/row on TPU (round-3 prims
    # measurement). dedup_flags() downgrades to unique-only under
    # DET_DEDUP_IMPL=cumsum
    fl = dedup_flags()
    acc_new = _row_scatter_add(accum, rep, sums * sums)
    # gather with clamped index is safe: sentinel rows multiply a zero
    # update. Clamping collapses the dropped tail onto rows-1, so only the
    # sorted promise survives (and only under the sort impl)
    acc_rows = jnp.take(acc_new, jnp.minimum(rep, rows - 1), axis=0,
                        indices_are_sorted=fl["indices_are_sorted"])
    delta = -lr * sums * lax.rsqrt(acc_rows + eps)
    return _row_scatter_add(table, rep, delta), acc_new


# ----------------------------------------------------------------- Adam
def sparse_adam(table: jax.Array, mu: jax.Array, nu: jax.Array, count,
                grad: SparseRowGrad, lr, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, strategy: str = "auto", presorted=None):
    """Lazy row-wise Adam: moments decay only on touched rows (the standard
    sparse-Adam compromise — identical to dense Adam when every row is
    touched every step; avoids O(V) work otherwise). `presorted`: see
    sparse_adagrad. Returns (table, mu, nu, count).
    """
    rows = table.shape[0]
    ps = _usable_presorted(presorted, grad, rows)
    route = _scatter_route(strategy, table)
    if route == "tiled":
        from distributed_embeddings_tpu.ops import pallas_tiled as ptl
        return ptl.tiled_adam(table, mu, nu, count, grad.ids, grad.contribs,
                              lr, b1=b1, b2=b2, eps=eps,
                              presorted=(None if ps is None
                                         else (ps.sid, ps.perm)))
    if route == "pallas":
        # fused sparse path: exact dedup + one RMW stream over
        # table/mu/nu tiles (see sparse_adagrad); the kernel's count
        # column rebuilds the touched mask, so lazy moment decay is
        # bit-identical to the sort path's .at[rep].set
        from distributed_embeddings_tpu.ops import pallas_tiled as ptl
        rep, sums = dedup_sum(grad.ids, grad.contribs, sentinel=rows,
                              presorted=ps)
        return ptl.tiled_adam_rows(table, mu, nu, count, rep, sums, lr,
                                   b1=b1, b2=b2, eps=eps)
    how = _pick(strategy, rows, table.shape[-1])
    if how == "dense":
        g, counts = _dense_sum(grad.ids, grad.contribs, rows)
        t_new, (mu_new, nu_new, count) = apply_dense_rows(
            "adam", table, (mu, nu, count), g, counts > 0, lr,
            b1=b1, b2=b2, eps=eps)
        return t_new, mu_new, nu_new, count
    count = count + 1
    c1 = 1.0 - b1 ** count.astype(jnp.float32)
    c2 = 1.0 - b2 ** count.astype(jnp.float32)
    rep, sums = dedup_sum(grad.ids, grad.contribs, sentinel=rows,
                          presorted=ps)
    # promises per the active dedup impl (see sparse_adagrad); clamped
    # gathers keep at most the sorted promise
    fl = dedup_flags()
    srt = fl["indices_are_sorted"]
    safe = jnp.minimum(rep, rows - 1)
    # fp_round pins each moment product's rounding (no context-dependent
    # FMA fusion) — the identical pins live in the fused pallas kernels,
    # so the two strategies stay bit-exact (see fp_round). `count` is
    # traced in every jitted flow, making the pin opaque to the backend.
    # The square is parenthesized FIRST so neither side leaves the
    # association to a simplifier.
    zero = round_pin(count)
    mu_rows = (fp_round(b1 * jnp.take(mu, safe, axis=0,
                                      indices_are_sorted=srt), zero)
               + fp_round((1 - b1) * sums, zero))
    nu_rows = (fp_round(b2 * jnp.take(nu, safe, axis=0,
                                      indices_are_sorted=srt), zero)
               + fp_round((1 - b2) * fp_round(sums * sums, zero), zero))
    mu_new = mu.at[rep].set(mu_rows, mode="drop", **fl)
    nu_new = nu.at[rep].set(nu_rows, mode="drop", **fl)
    delta = -lr * (mu_rows / c1) / (jnp.sqrt(nu_rows / c2) + eps)
    return (table.at[rep].add(delta.astype(table.dtype), mode="drop", **fl),
            mu_new, nu_new, count)


# -------------------------- quantized (master-weight-free) row updates
# Optimizers whose quantized-table update is expressible row-wise without
# an f32 master copy of the TABLE: the update direction depends only on
# the aggregated gradient (+ f32 row-wise state), never on sub-grid-step
# table precision. Adam is deliberately absent — see quantized_row_update.
QUANTIZED_ROW_KINDS = ("sgd", "adagrad")


def quantized_row_update(kind: str, payload: jax.Array, scale: jax.Array,
                         state, grad: SparseRowGrad, store_dtype: str, lr,
                         eps: float = 1e-10, presorted=None):
    """Master-weight-free sparse update of a QUANTIZED table shard
    (ISSUE 17): decode ONLY the touched rows -> f32 optimizer math ->
    hash-SR re-encode, scattered back into the int8/fp8 payload and its
    per-row scale stack. No f32 shadow table ever exists, so a quantized
    HBM-resident bucket costs ~1/4 the f32 HBM with zero resident mirror.

    The optimizer state (adagrad's accumulator) stays full f32 — the
    master-weight-FREE claim is about the TABLE. SR (the wire seam's
    keyless hash, `wire.encode_rows(sr=True)`) centers the write-back
    rounding on zero across a step's many updated values; a zero-delta
    touched row round-trips exactly (the row amax element re-derives the
    identical scale).

    kind must be in QUANTIZED_ROW_KINDS. Adam REFUSES loudly: its
    per-element moment normalization produces effective steps orders of
    magnitude below the row's quantization grid (scale = amax/127), which
    systematically vanish under round-to-grid — SR preserves them only in
    expectation over many steps, exactly the early-training phase adam's
    bias correction depends on — and its two f32 moments already double
    the state, making the table saving marginal. Use f32 storage under
    adam, or a row-wise optimizer.

    Returns (payload, scale, state).
    """
    if kind not in QUANTIZED_ROW_KINDS:
        raise NotImplementedError(
            f"sparse optimizer {kind!r} has no master-weight-free "
            f"quantized-table update (available: {QUANTIZED_ROW_KINDS}); "
            "adam's moment-normalized steps fall below the row "
            "quantization grid — store this bucket at f32 or switch to "
            "sgd/row-wise adagrad")
    from distributed_embeddings_tpu.ops import wire as wire_ops
    rows = payload.shape[0]
    ps = _usable_presorted(presorted, grad, rows)
    rep, sums = dedup_sum(grad.ids, grad.contribs, sentinel=rows,
                          presorted=ps)
    fl = dedup_flags()
    srt = fl["indices_are_sorted"]
    # clamped gathers are safe: sentinel slots carry zero sums and their
    # scatter-back is dropped outright (rep >= rows under mode='drop')
    safe = jnp.minimum(rep, rows - 1)
    old = wire_ops.decode_rows(
        jnp.take(payload, safe, axis=0, indices_are_sorted=srt),
        jnp.take(scale, safe, axis=0, indices_are_sorted=srt),
        store_dtype)
    if kind == "sgd":
        new_rows = old - lr * sums
        new_state = tuple(state)
    else:  # adagrad — same accumulator math as sparse_adagrad's sort path
        (acc,) = state
        acc = _row_scatter_add(acc, rep, sums * sums)
        acc_rows = jnp.take(acc, safe, axis=0, indices_are_sorted=srt)
        new_rows = old - lr * sums * lax.rsqrt(acc_rows + eps)
        new_state = (acc,)
    p_rows, s_rows = wire_ops.encode_rows(new_rows, store_dtype, sr=True)
    return (payload.at[rep].set(p_rows, mode="drop", **fl),
            scale.at[rep].set(s_rows, mode="drop", **fl),
            new_state)


# ------------------------------------- host-memory (offloaded) row updates
def prepare_safe_grad(ids: jax.Array, contribs: jax.Array, rows: int):
    """Dedup + make scatter-safe for PROMISE_IN_BOUNDS host scatters: padded
    segments get id 0 with zero sums (additive identity for sgd/adagrad),
    so no drop-mode bounds machinery (whose constants are illegal in host
    regions) is needed. Returns (rep [N] in-bounds, sums [N, w],
    valid [N] f32 mask) — non-additive rules (adam's moment decay) must
    mask with `valid`; padded slots alias row 0."""
    rep, sums = dedup_sum(ids, contribs, sentinel=rows)
    valid = rep < rows
    return (jnp.where(valid, rep, 0),
            jnp.where(valid[:, None], sums, 0.0),
            valid.astype(jnp.float32))


def host_sparse_sgd(table, state, rep, sums, valid, lr):
    """Additive row update in host memory (inside compute_on). Args from
    prepare_safe_grad; `valid` unused — padded slots carry zero sums, the
    additive identity."""
    del state, valid
    return scatter_add_rows(table, rep, -lr * sums), ()


def host_sparse_adagrad(table, state, rep, sums, valid, lr,
                        eps: float = 1e-10):   # = sparse_adagrad's default
    del valid                       # zero sums -> zero delta on row 0
    (acc,) = state
    acc = scatter_add_rows(acc, rep, sums * sums)
    acc_rows = take_rows(acc, rep)
    delta = -lr * sums * lax.rsqrt(acc_rows + eps)
    return scatter_add_rows(table, rep, delta), (acc,)


def host_sparse_adam(table, state, rep, sums, valid, lr, b1: float = 0.9,
                     b2: float = 0.999, eps: float = 1e-8):
    """Lazy row-wise adam in host memory, matching `sparse_adam` on touched
    rows. The moment decay is multiplicative, so it is expressed as a
    masked additive delta (gather old rows, scatter-add new-minus-old);
    deduped valid reps are unique, making the scatter-add exact. Masking is
    arithmetic (multiply by the f32 `valid`) — no select/clamp constants,
    which XLA's memory-space checker rejects inside host regions."""
    mu, nu, count = state
    count = count + 1
    cf = count.astype(jnp.float32)
    c1 = 1.0 - lax.pow(jnp.float32(b1), cf)
    c2 = 1.0 - lax.pow(jnp.float32(b2), cf)
    v = valid[:, None]
    mu_rows = take_rows(mu, rep)
    nu_rows = take_rows(nu, rep)
    mu_new_rows = b1 * mu_rows + (1.0 - b1) * sums
    nu_new_rows = b2 * nu_rows + (1.0 - b2) * sums * sums
    mu = scatter_add_rows(mu, rep, (mu_new_rows - mu_rows) * v)
    nu = scatter_add_rows(nu, rep, (nu_new_rows - nu_rows) * v)
    delta = -lr * (mu_new_rows / c1) / (jnp.sqrt(nu_new_rows / c2) + eps) * v
    return scatter_add_rows(table, rep, delta), (mu, nu, count)


HOST_SPARSE_APPLY = {"sgd": host_sparse_sgd, "adagrad": host_sparse_adagrad,
                     "adam": host_sparse_adam}


def host_apply_rows_inplace(kind: str, table, state, rep, sums, valid, lr,
                            **hp) -> None:
    """Apply one shard's deduped update rows to host-resident numpy buffers
    IN PLACE — the XLA-free twin of HOST_SPARSE_APPLY (same args, same
    numerics) used by the per-shard offload apply, where the table never
    enters an XLA program (see host_apply.cpp for why). `table` and the
    array leaves of `state` are mutated; adam's scalar count must be
    incremented by the CALLER (mirroring `count + 1` in host_sparse_adam).
    Native C++ kernels when buildable, numpy otherwise."""
    import numpy as np

    bad = [a.dtype for a in (table, *(s for s in state
                                      if getattr(s, "ndim", 0) >= 1))
           if a.dtype != np.float32]
    if bad:
        raise TypeError(
            f"host_apply_rows_inplace is float32-only, got {bad}; use the "
            "roundtrip offload apply (DET_HOST_APPLY=roundtrip) for "
            "non-f32 buckets")
    # the C++ kernels below consume raw .ctypes.data pointers with a dense
    # row-major stride assumption: a non-contiguous view here is silent
    # memory corruption, not an error (ADVICE r5) — refuse it up front for
    # the numpy path too so both implementations reject the same inputs
    noncontig = [name for name, a in
                 (("table", table),
                  *((f"state[{i}]", s) for i, s in enumerate(state)
                    if getattr(s, "ndim", 0) >= 1))
                 if not a.flags["C_CONTIGUOUS"]]
    if noncontig:
        raise ValueError(
            f"host_apply_rows_inplace requires C-contiguous buffers; "
            f"{noncontig} are not (pass np.ascontiguousarray copies and "
            "write them back, or fix the caller's layout)")
    n, w = sums.shape
    lr = float(lr)
    rep = np.ascontiguousarray(rep, dtype=np.int32)
    sums = np.ascontiguousarray(sums, dtype=np.float32)
    valid = np.ascontiguousarray(valid, dtype=np.float32)
    if kind == "set":
        # weight-streaming row SET (store/table_store.py delta apply):
        # `sums` carries replacement row VALUES, not gradients — valid
        # reps are unique, so a plain masked assignment is exact. Rides
        # this seam so offloaded-bucket delta consumption shares the
        # contiguity/dtype contract (and the shard-walk callers) of the
        # optimizer applies; trivially bandwidth-bound, so no C++ twin.
        ok_set = valid > 0.0
        table[rep[ok_set]] = sums[ok_set]
        return
    lib = None
    try:
        from ..native import loader as _native_loader
        lib = _native_loader.load()
        if not hasattr(lib, "ha_sgd"):   # prebuilt .so without the kernels
            lib = None
    except Exception:            # no g++ and no prebuilt .so: numpy fallback
        lib = None
    if lib is not None:
        import ctypes

        def ptr(a):
            return ctypes.c_void_p(a.ctypes.data)

        if kind == "sgd":
            lib.ha_sgd(ptr(table), w, ptr(rep), ptr(sums), ptr(valid), n, lr)
        elif kind == "adagrad":
            (acc,) = state
            lib.ha_adagrad(ptr(table), ptr(acc), w, ptr(rep), ptr(sums),
                           ptr(valid), n, lr, float(hp.get("eps", 1e-10)))
        elif kind == "adam":
            mu, nu, count = state
            b1 = float(hp.get("b1", 0.9))
            b2 = float(hp.get("b2", 0.999))
            cf = float(count)             # already incremented by the caller
            lib.ha_adam(ptr(table), ptr(mu), ptr(nu), w, ptr(rep), ptr(sums),
                        ptr(valid), n, lr, b1, b2,
                        np.float32(1.0) - np.float32(b1) ** np.float32(cf),
                        np.float32(1.0) - np.float32(b2) ** np.float32(cf),
                        float(hp.get("eps", 1e-8)))
        else:
            raise NotImplementedError(
                f"no host-memory apply rule for optimizer {kind!r}")
        return

    ok = valid > 0.0              # invalid slots alias row 0 with zero sums
    r = rep[ok]
    s = sums[ok]
    if kind == "sgd":
        np.add.at(table, r, (-lr * s).astype(np.float32))
    elif kind == "adagrad":
        (acc,) = state
        eps = np.float32(hp.get("eps", 1e-10))
        np.add.at(acc, r, s * s)
        np.add.at(table, r,
                  (-lr * s / np.sqrt(acc[r] + eps)).astype(np.float32))
    elif kind == "adam":
        mu, nu, count = state
        b1 = np.float32(hp.get("b1", 0.9))
        b2 = np.float32(hp.get("b2", 0.999))
        eps = np.float32(hp.get("eps", 1e-8))
        cf = np.float32(count)
        c1 = np.float32(1.0) - b1 ** cf
        c2 = np.float32(1.0) - b2 ** cf
        mu_new = b1 * mu[r] + (np.float32(1.0) - b1) * s
        nu_new = b2 * nu[r] + (np.float32(1.0) - b2) * s * s
        mu[r] = mu_new            # valid reps are unique: plain set is exact
        nu[r] = nu_new
        np.add.at(
            table, r,
            (-lr * (mu_new / c1) / (np.sqrt(nu_new / c2) + eps)).astype(
                np.float32))
    else:
        raise NotImplementedError(
            f"no host-memory apply rule for optimizer {kind!r}")


# ------------------------------------------------- optimizer description
class SparseOptimizer(NamedTuple):
    """A (init, update) pair over a single table shard; `update` consumes a
    SparseRowGrad (plus an optional `presorted` GroupSort of its id
    stream — the sort-folding seam). `kind` selects the rule; hyper-params
    are closed over (and kept in `lr`/`hp` for the host-offload apply
    path)."""
    kind: str
    init: callable       # table -> state pytree (tuple)
    update: callable     # (table, state, SparseRowGrad, presorted=None)
    lr: Any = 0.0        #   -> (table, state)
    hp: tuple = ()       # sorted (key, value) pairs
    strategy: str = "auto"


def update_consumes_sort(kind: str, strategy: str, rows: int,
                         width: int) -> bool:
    """Static answer to "would `SparseOptimizer.update` use a presorted
    GroupSort for a [rows, width] shard?" — mirrors the dispatch in
    sparse_sgd/adagrad/adam exactly, so forwards can decide at trace time
    whether producing the artifact is worthwhile (an unconsumed sort is
    not free: DCE does not reach through shard_map boundaries)."""
    # one lattice with the actual dispatch (`_route_static`): both kernel
    # families consume the sorted stream, and a pallas/tiled request that
    # fell back onto the XLA path lands in its dedup branch (how is not
    # 'dense') — which consumes the artifact for adagrad/adam. Returning
    # False for a degraded route would strip the fallback of its sort
    # fold (review finding).
    route = _route_static(strategy, width)
    if route in ("pallas", "tiled"):
        return True                      # tile walks take (sid, perm)
    if _pick(strategy, rows, width) == "dense":
        return False                     # dense path aggregates scatterwise
    if kind == "sgd":
        # only the EXPLICIT sort strategy dedups for sgd (aggregate-first
        # seam, see sparse_sgd); auto's plain scatter needs no order, and
        # the degraded pallas/tiled->xla routes keep sgd's plain scatter
        return strategy == "sort"
    return kind in ("adagrad", "adam")


def make_sparse_optimizer(kind: str, lr, strategy: str = "auto",
                          **hp) -> SparseOptimizer:
    """kind in {'sgd', 'adagrad', 'adam'}; mirrors the optax rules used by
    the examples (reference synthetic main.py sgd/adagrad/adam flags)."""
    hp_t = tuple(sorted(hp.items()))
    if kind == "sgd":
        return SparseOptimizer(
            "sgd", lambda table: (),
            lambda table, state, g, presorted=None: (
                sparse_sgd(table, g, lr, strategy=strategy,
                           presorted=presorted), ()),
            lr, hp_t, strategy)
    if kind == "adagrad":
        init_acc = hp.get("initial_accumulator_value", 0.1)
        eps = hp.get("eps", 1e-10)

        def init(table):
            return (jnp.full(table.shape, init_acc, jnp.float32),)

        def update(table, state, g, presorted=None):
            t, acc = sparse_adagrad(table, state[0], g, lr, eps=eps,
                                    strategy=strategy, presorted=presorted)
            return t, (acc,)
        return SparseOptimizer("adagrad", init, update, lr, hp_t, strategy)
    if kind == "adam":
        b1, b2 = hp.get("b1", 0.9), hp.get("b2", 0.999)
        eps = hp.get("eps", 1e-8)

        def init(table):
            return (jnp.zeros(table.shape, jnp.float32),
                    jnp.zeros(table.shape, jnp.float32),
                    jnp.zeros((), jnp.int32))

        def update(table, state, g, presorted=None):
            t, mu, nu, c = sparse_adam(table, state[0], state[1], state[2],
                                       g, lr, b1=b1, b2=b2, eps=eps,
                                       strategy=strategy,
                                       presorted=presorted)
            return t, (mu, nu, c)
        return SparseOptimizer("adam", init, update, lr, hp_t, strategy)
    raise ValueError(f"Unknown sparse optimizer {kind!r}")


def drain_sparse_apply(emb, params_emb, state_emb, tap_grads, residuals,
                       opt, off_buckets=()):
    """Drain-stage entry (ISSUE 9): apply one batch's tap gradients to the
    embedding tables — the tail every train-step variant shares.

    Two producers feed it: the monolithic `make_sparse_train_step`, where
    autodiff delivered `tap_grads` (the backward already ran the dp->mp
    gradient transpose inside the custom-vjp exchange), and the lookahead
    pipeline (`schedule.LookaheadEngine`), where the engine's explicit
    `DistributedEmbedding.exchange_transpose` did. Both hand the exact
    `make_taps`-shaped pytree; the update itself is the layer's
    `sparse_update`.

    `off_buckets` slots of the RETURNED pytrees are zeroed out: host-
    resident leaves must never be jit outputs (XLA:CPU SPMD cannot place
    them; TPU would copy them device-ward) — the caller replaces those
    slots with the out-of-jit host-apply results, driven by the returned
    `pending` dict (see `make_sparse_train_step`).

    Returns (new_params_emb, new_state_emb, pending).
    """
    new_emb, new_state, pending = emb.sparse_update(
        params_emb, state_emb, tap_grads, residuals, opt)
    for b in off_buckets:
        new_emb["tp"][b] = jnp.zeros((0,), jnp.float32)
        new_state["tp"][b] = jax.tree.map(
            lambda _: jnp.zeros((0,), jnp.float32), new_state["tp"][b])
    return new_emb, new_state, pending
