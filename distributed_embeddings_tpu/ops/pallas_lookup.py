"""Pallas TPU kernels: fused multi-hot embedding lookup-combine.

TPU-native replacement for the reference's custom CUDA combiner kernels
(reference: cc/kernels/embedding_lookup_kernels.cu:33-336 — warp-level CSR
segment reduce with shared-memory index staging). The TPU design is shaped by
different hardware: there is no warp shuffle, but there is a 128x128 MXU and
explicit async DMA. Two kernels cover the vocab spectrum:

  * ``_onehot_lookup`` (small vocab): the weighted combine
    ``out[b] = sum_k w[b,k] * table[ids[b,k]]`` is algebraically
    ``A @ table`` with ``A[b,v] = sum_k w[b,k] * [ids[b,k] == v]``.
    The kernel builds each ``[tile_b, tile_v]`` slab of A on the fly in VMEM
    (never materializing the [B, V] one-hot in HBM) and accumulates partial
    matmuls on the MXU over vocab tiles. Lookup *is* a matmul on TPU.

  * ``_dma_gather_lookup`` (large vocab): ids are scalar-prefetched into SMEM
    (PrefetchScalarGridSpec), the table stays in HBM, and the kernel streams
    the addressed rows VMEM-ward with double-buffered async DMA — one buffer
    accumulates ``w[b,k] * row`` while the next hotness step's rows are in
    flight. This is the moral equivalent of the CUDA kernel's smem staging +
    register accumulation (.cu:33-107), with DMA latency instead of memory
    coalescing as the thing being hidden.

The backward is XLA-native scatter-add (static shapes, no D2H sync — the
reference grad kernel's `num_unique_ids` D2H copy at .cu:665 is the failure
mode this avoids), registered through ``jax.custom_vjp``.

Inputs are the framework's canonical padded multi-hot form: ids [B, K] with
arbitrary ids in padded slots, weights [B, K] carrying 0.0 there (and the
mean normalization pre-applied — see ``fused_embedding_lookup``).
"""

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Vocab size at or below which the MXU one-hot-matmul kernel is used.
# Default pending hardware re-measurement (round-3: the first A/B's timings
# were invalidated by the axon sync bug; the fixed slope-timed pallas check
# re-measures next window). DET_ONEHOT_MAX_VOCAB overrides per trace (read
# per call like DET_SPARSE_DENSE_MAX, so in-process A/B works); 0 disables
# the MXU kernel entirely.
ONEHOT_MAX_VOCAB = 8192


def _onehot_max_vocab() -> int:
    import os
    return int(os.environ.get("DET_ONEHOT_MAX_VOCAB", ONEHOT_MAX_VOCAB))
# The DMA kernel wants lane-aligned rows; others fall back to XLA.
_LANE = 128


def is_tpu_backend() -> bool:
    return jax.default_backend() == "tpu"


def _interpret_default(interpret: Optional[bool]) -> bool:
    # compiled on TPU; interpreter elsewhere (CPU tests)
    if interpret is None:
        return not is_tpu_backend()
    return interpret


# --------------------------------------------------------------------------
# small-vocab kernel: one-hot matmul on the MXU
# --------------------------------------------------------------------------
def _onehot_kernel(ids_ref, w_ref, table_ref, out_ref, *, tile_v: int):
    j = pl.program_id(1)
    ids = ids_ref[:]                               # [tb, K] int32
    w = w_ref[:]                                   # [tb, K] f32
    tb = ids.shape[0]
    v_iota = (jax.lax.broadcasted_iota(jnp.int32, (tb, tile_v), 1)
              + j * tile_v)
    a = jnp.zeros((tb, tile_v), jnp.float32)
    for k in range(ids.shape[1]):                  # K is small and static
        a = a + jnp.where(v_iota == ids[:, k:k + 1], w[:, k:k + 1], 0.0)
    # HIGHEST: the MXU's default bf16 passes lose ~2^-8 relative accuracy
    # (observed 2e-3 vs the f32 XLA path on hardware); the 3-pass f32
    # emulation keeps the kernel bit-comparable to gather+reduce
    part = jax.lax.dot_general(
        a, table_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)

    @pl.when(j == 0)
    def _():
        out_ref[:] = part

    @pl.when(j != 0)
    def _():
        out_ref[:] = out_ref[:] + part


def _onehot_lookup(table: jax.Array, ids: jax.Array, weights: jax.Array,
                   tile_b: int = 256, tile_v: int = 512,
                   interpret: Optional[bool] = None) -> jax.Array:
    batch, k = ids.shape
    vocab, width = table.shape
    # sublane-align the batch tile (Mosaic wants multiples of 8; odd sizes
    # compiled but returned wrong results on hardware)
    tile_b = min(tile_b, max(8, -(-batch // 8) * 8))
    pad_b = -batch % tile_b
    if pad_b:
        ids = jnp.pad(ids, ((0, pad_b), (0, 0)))
        weights = jnp.pad(weights, ((0, pad_b), (0, 0)))
    pad_v = -vocab % tile_v
    if pad_v:
        # zero-pad so OOB vocab tiles contribute exact zeros (never NaN*0)
        table = jnp.pad(table, ((0, pad_v), (0, 0)))
    grid = ((batch + pad_b) // tile_b, (vocab + pad_v) // tile_v)
    out = pl.pallas_call(
        functools.partial(_onehot_kernel, tile_v=tile_v),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_b, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_b, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_v, width), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_b, width), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((batch + pad_b, width), jnp.float32),
        interpret=_interpret_default(interpret),
    )(ids.astype(jnp.int32), weights.astype(jnp.float32), table)
    return out[:batch]


# --------------------------------------------------------------------------
# large-vocab kernel: scalar-prefetched ids + deep-pipelined row DMA
# --------------------------------------------------------------------------
# Row gathers from HBM are latency/descriptor-rate bound on TPU, so the
# kernel's job is to keep MANY row DMAs in flight: hotness is processed in
# chunks of `hc` slots x `tile_b` rows (tile_b*hc concurrent copies),
# double-buffered so chunk c+1's copies are in flight while chunk c combines.
# DMA issue loops are lax.fori_loop, not Python-unrolled — the round-1 kernel
# unrolled 2*tile_b*hot copy ops and crashed the compiler at hotness 200.
def _dma_gather_kernel(ids_ref, w_ref, table_ref, out_ref, rows_ref, sems,
                       *, tile_b: int, hot: int, hc: int):
    i = pl.program_id(0)
    base = i * tile_b * hot                        # ids are [B*K] row-major
    nchunks = hot // hc

    def dma(c, slot, j):
        # j enumerates (t, kk) in the chunk: t = j // hc, kk = j % hc
        t, kk = j // hc, j % hc
        row = ids_ref[base + t * hot + c * hc + kk]
        return pltpu.make_async_copy(
            table_ref.at[row], rows_ref.at[slot, t, kk], sems.at[slot, j])

    def start_chunk(c, slot):
        jax.lax.fori_loop(
            0, tile_b * hc,
            lambda j, _: (dma(c, slot, j).start(), 0)[1], 0)

    def wait_chunk(c, slot):
        jax.lax.fori_loop(
            0, tile_b * hc,
            lambda j, _: (dma(c, slot, j).wait(), 0)[1], 0)

    start_chunk(0, 0)
    out_ref[:] = jnp.zeros_like(out_ref)

    def body(c, _):
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nchunks)
        def _():
            start_chunk(c + 1, jax.lax.rem(c + 1, 2))

        wait_chunk(c, slot)
        w_chunk = w_ref[:, pl.ds(c * hc, hc)]      # [tile_b, hc]
        rows = rows_ref[slot].astype(jnp.float32)  # [tile_b, hc, width]
        out_ref[:] = out_ref[:] + jnp.sum(rows * w_chunk[..., None], axis=1)
        return 0

    jax.lax.fori_loop(0, nchunks, body, 0)


# target number of row copies in flight per buffer; bounds VMEM while hiding
# HBM latency (in-flight bytes = 2 * DMA_DEPTH * width * 4)
_DMA_DEPTH = 256


def _dma_gather_lookup(table: jax.Array, ids: jax.Array, weights: jax.Array,
                       interpret: Optional[bool] = None) -> jax.Array:
    batch, hot = ids.shape
    _, width = table.shape
    # batch tile: sublane-aligned, sized so tile_b * hc ~ _DMA_DEPTH
    tile_b = max(8, min(256, -(-batch // 8) * 8))
    hc = max(1, min(hot, _DMA_DEPTH // tile_b))
    pad_k = -hot % hc
    if pad_k:
        # zero-weight padded hotness slots (id 0 is a safe in-bounds row)
        ids = jnp.pad(ids, ((0, 0), (0, pad_k)))
        weights = jnp.pad(weights, ((0, 0), (0, pad_k)))
        hot += pad_k
    pad_b = -batch % tile_b
    if pad_b:
        ids = jnp.pad(ids, ((0, pad_b), (0, 0)))
        weights = jnp.pad(weights, ((0, pad_b), (0, 0)))
    n_tiles = (batch + pad_b) // tile_b
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile_b, hot), lambda i, ids_ref: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),      # table stays in HBM
        ],
        out_specs=pl.BlockSpec((tile_b, width), lambda i, ids_ref: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, tile_b, hc, width), table.dtype),
            pltpu.SemaphoreType.DMA((2, tile_b * hc)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_dma_gather_kernel, tile_b=tile_b, hot=hot, hc=hc),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch + pad_b, width), jnp.float32),
        interpret=_interpret_default(interpret),
    )(ids.reshape(-1).astype(jnp.int32), weights.astype(jnp.float32), table)
    return out[:batch]


# --------------------------------------------------------------------------
# dispatch + autodiff
# --------------------------------------------------------------------------
# widths validated against the XLA fallback on the compiled backend this
# process; maps width -> bool (False = hardware mismatch, stay on XLA)
_NARROW_VALIDATED = {}


def _narrow_path_ok(width: int, dtype) -> bool:
    """One-time per-(width, dtype) compiled-vs-XLA equivalence check for
    sub-lane rows (the suite only exercises interpret mode, so a TPU
    lowering bug in sub-lane row tiles would otherwise yield silently wrong
    embeddings; bf16 tables take a different Mosaic tiling than f32, so
    dtype is part of the key). Must run EAGERLY (it executes a compiled
    kernel and fetches the result — illegal under a jit trace); callers
    inside a trace consult the cache via ``prevalidate_narrow`` instead.
    A mismatch or compile failure warns and pins the combination to the
    XLA fallback for the process (round-3 hardware: the r03 tunnel's
    compile helper crashed on every DMA-kernel compile, so the failure
    path is load-bearing, not theoretical)."""
    # the probe table is sized off the per-call DET_ONEHOT_MAX_VOCAB, so the
    # resolved value is part of the cache key: changing the knob mid-process
    # must not reuse a verdict measured under a different routing threshold
    # (ADVICE r3)
    key = (width, jnp.dtype(dtype).name, _onehot_max_vocab())
    if key in _NARROW_VALIDATED:
        return _NARROW_VALIDATED[key]
    import warnings
    rng = np.random.RandomState(width)
    vocab = _onehot_max_vocab() + 64
    table = jnp.asarray(rng.randn(vocab, width), dtype=dtype)
    # batch 500: exercises the production tile configuration (tile_b
    # capped at 256) AND the padded final tile (500 % 256 != 0) — a
    # lowering bug specific to large or partial tiles must not slip past
    # a toy-shape probe
    ids = jnp.asarray(rng.randint(0, vocab, (500, 4)).astype(np.int32))
    w = jnp.asarray(rng.rand(500, 4).astype(np.float32))
    try:
        got = np.asarray(_dma_gather_lookup(table, ids, w, interpret=False))
    except Exception as e:  # noqa: BLE001 - any compile/run failure => XLA
        warnings.warn(
            f"DET_PALLAS_NARROW: DMA kernel failed to compile/run at "
            f"width {width} dtype {jnp.dtype(dtype).name} on this backend "
            f"({str(e)[:200]}); falling back to XLA")
        _NARROW_VALIDATED[key] = False
        return False
    want = np.einsum("bk,bkw->bw", np.asarray(w),
                     np.asarray(table, np.float32)[np.asarray(ids)])
    tol = 1e-5 if jnp.dtype(dtype) == jnp.float32 else 1e-2
    ok = bool(np.allclose(got, want, rtol=tol, atol=tol))
    if not ok:
        warnings.warn(
            f"DET_PALLAS_NARROW: DMA kernel mismatches XLA gather at "
            f"width {width} dtype {jnp.dtype(dtype).name} on this "
            "backend; falling back to XLA")
    _NARROW_VALIDATED[key] = ok
    return ok


def prevalidate_narrow(widths=(8, 16, 32, 64), dtype=jnp.float32) -> dict:
    """Eagerly run the narrow-width hardware validation for each width so
    traced code (jit/shard_map forwards) can consult the cached verdicts.
    Call BEFORE the first traced forward when DET_PALLAS_NARROW=1; inside a
    trace an unvalidated width silently takes the XLA fallback."""
    return {w: _narrow_path_ok(w, dtype) for w in widths}


def _fused_impl(params, ids, weights, interpret):
    import os
    vocab, width = params.shape
    if vocab <= _onehot_max_vocab():
        return _onehot_lookup(params, ids, weights, interpret=interpret)
    # narrow rows (< 1 lane) make per-row DMAs tiny; whether that still
    # beats XLA's gather is a hardware question — opt in via env until the
    # prims data answers it
    narrow_ok = (os.environ.get("DET_PALLAS_NARROW", "0") == "1"
                 and width in (8, 16, 32, 64))
    if narrow_ok and not _interpret_default(interpret):
        # under a jit trace the eager hardware check cannot run (it fetches
        # a compiled result); only a cached prevalidate_narrow verdict
        # enables the path there
        key = (width, jnp.dtype(params.dtype).name, _onehot_max_vocab())
        if isinstance(params, jax.core.Tracer):
            narrow_ok = _NARROW_VALIDATED.get(key, False)
        else:
            narrow_ok = _narrow_path_ok(width, params.dtype)
    use_narrow = narrow_ok
    if width % _LANE == 0 or use_narrow:
        return _dma_gather_lookup(params, ids, weights, interpret=interpret)
    # XLA fallback: gather + weighted reduce (still fused by XLA)
    embs = jnp.take(params, ids, axis=0)
    return jnp.einsum("bk,bkw->bw", weights.astype(embs.dtype),
                      embs).astype(jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_lookup(params, ids, weights, interpret):
    return _fused_impl(params, ids, weights, interpret)


def _fused_fwd(params, ids, weights, interpret):
    return _fused_impl(params, ids, weights, interpret), (params, ids, weights)


def _fused_bwd(interpret, res, g):
    params, ids, weights = res
    flat_ids = ids.reshape(-1)
    contrib = (weights[..., None].astype(g.dtype) * g[:, None, :]).reshape(
        -1, g.shape[-1])
    # dense-table scatter-add: static shapes, no sort/unique, no host sync
    dtable = jnp.zeros_like(params).at[flat_ids].add(
        contrib.astype(params.dtype))
    rows = jnp.take(params, ids, axis=0).astype(g.dtype)
    dweights = jnp.einsum("bkw,bw->bk", rows, g).astype(weights.dtype)
    return dtable, None, dweights


_fused_lookup.defvjp(_fused_fwd, _fused_bwd)


def fused_embedding_lookup(params: jax.Array, ids: jax.Array,
                           weights: Optional[jax.Array] = None,
                           combiner: str = "sum",
                           interpret: Optional[bool] = None) -> jax.Array:
    """Fused padded multi-hot lookup: [V,W] table, [B,K] ids -> [B,W].

    weights [B, K] carry 0.0 in padded slots (None = all-ones). Mean is
    handled by pre-normalizing weights so both kernels only ever compute a
    weighted sum (matching the reference Combiner semantics, .cu:96-99).
    Differentiable in params and weights.
    """
    if combiner not in ("sum", "mean"):
        raise ValueError(f"Unsupported combiner {combiner}")
    if weights is None:
        weights = jnp.ones(ids.shape, jnp.float32)
    if combiner == "mean":
        denom = jnp.maximum(jnp.sum(weights, axis=1, keepdims=True), 1.0)
        weights = weights / denom
    # match XLA gather semantics (clamp OOB) so results don't depend on which
    # kernel path ran; also keeps the DMA kernel from reading past the table
    ids = jnp.clip(ids, 0, params.shape[0] - 1)
    return _fused_lookup(params, ids, weights, interpret).astype(params.dtype)
