from distributed_embeddings_tpu.ops.embedding_ops import (
    embedding_lookup,
    RaggedIds,
    SparseIds,
    row_to_split,
)
