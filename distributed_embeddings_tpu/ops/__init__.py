from distributed_embeddings_tpu.ops.embedding_ops import (
    embedding_lookup,
    RaggedIds,
    SparseIds,
    row_to_split,
)

# NOTE: pallas_lookup is intentionally NOT imported here — the Pallas kernels
# are an optional TPU-only path, imported lazily by layers/embedding.py so the
# rest of the package has no hard jax.experimental.pallas dependency.
