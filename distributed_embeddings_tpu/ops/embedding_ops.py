"""Core single-device embedding lookup ops (TPU-native).

This module is the TPU equivalent of the reference's op-glue + CUDA kernels
(reference: distributed_embeddings/python/ops/embedding_lookup_ops.py:37-122 and
cc/kernels/embedding_lookup_kernels.cu:33-336). Instead of hand-written CSR
combiner kernels, the lookup is expressed as XLA-native gather + segment-sum,
which XLA:TPU tiles onto the VPU/MXU and fuses with surrounding ops. A Pallas
fused kernel is available for the hot multi-hot path (ops/pallas_lookup.py).

Design notes (TPU-first):
  * All shapes are static. Ragged inputs carry a statically-sized `values`
    buffer; any padding past ``row_splits[-1]`` is dropped by construction
    (out-of-range segment ids are dropped by XLA scatter semantics).
  * The backward pass is XLA's scatter-add on the dense table — no host sync,
    no sort/unique (the reference's CUDA grad does a D2H copy of
    `num_unique_ids`, embedding_lookup_kernels.cu:665, a latency bug class TPU
    avoids entirely by keeping static shapes).
  * Mean combiner divides by the true row length with a zero-guard, matching
    tf.nn.embedding_lookup_sparse semantics for empty rows.
"""

from typing import NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax


class RaggedIds(NamedTuple):
    """CSR-format ragged id batch: ``values`` are ids, ``row_splits`` offsets.

    Mirrors tf.RaggedTensor's (values, row_splits) contract used by the
    reference (embedding_lookup_ops.py:79-80). ``values`` may be padded past
    ``row_splits[-1]``; padded entries are ignored.
    """

    values: jax.Array      # [nnz_max] int32/int64 ids
    row_splits: jax.Array  # [batch + 1] monotonically increasing offsets

    @property
    def nrows(self) -> int:
        return self.row_splits.shape[0] - 1

    def row_lengths(self) -> jax.Array:
        return self.row_splits[1:] - self.row_splits[:-1]

    @staticmethod
    def from_row_lengths(values: jax.Array, row_lengths: jax.Array) -> "RaggedIds":
        row_splits = jnp.concatenate(
            [jnp.zeros((1,), row_lengths.dtype), jnp.cumsum(row_lengths)])
        return RaggedIds(values=values, row_splits=row_splits)


class SparseIds(NamedTuple):
    """COO-format sparse id batch, mirroring tf.SparseTensor inputs
    (reference embedding_lookup_ops.py:81-96). ``indices`` is [nnz, 2]
    (row, col) with rows sorted ascending; ``dense_shape`` is static.
    """

    indices: jax.Array          # [nnz, 2] int
    values: jax.Array           # [nnz] int ids
    dense_shape: Tuple[int, int]  # static (batch, max_hotness)


IdsLike = Union[jax.Array, RaggedIds, SparseIds]


class GroupSort(NamedTuple):
    """Sort artifacts of one id stream, shared by a lookup and its sparse
    update (the 'one-sort production step': the reference's CUDA backward
    reuses the forward kernel over already-sorted ids,
    embedding_lookup_kernels.cu:706-773 — this is the artifact that makes
    the same reuse legal here).

    The sort key is CANONICAL: valid ids keep their value, out-of-bounds
    ids (negative or >= rows) key to exactly `rows` — byte-identical to
    both `dedup_sum`'s sentinel keys and `pallas_tiled._sort_ids`'s keys,
    so one `lax.sort_key_val` serves the dedup aggregation, the tiled
    update kernels, and (clamped) the tiled forward gather.

      sid:       [N] int32 ascending canonical keys (OOB slots == rows).
      perm:      [N] int32, ids.reshape(-1)[perm[n]] has key sid[n].
      seg_start: [N] bool, True where sid starts a new segment.
      inv:       [N] int32 inverse permutation (inv[perm[n]] == n), or None
                 when no consumer needs original-order restoration. Costs a
                 second sort op — only produced when the tiled forward
                 gather's unpermute consumes it.
    """

    sid: jax.Array
    perm: jax.Array
    seg_start: jax.Array
    inv: Optional[jax.Array] = None


def canonical_id_sort(ids: jax.Array, rows: int,
                      want_inv: bool = False) -> GroupSort:
    """One stable sort of a (flattened) id stream under the canonical key
    (see GroupSort). `rows` must equal the consuming table shard's
    shape[0] — the same sentinel `dedup_sum` would use — or the folded and
    unfolded update paths stop being bit-exact."""
    flat = ids.reshape(-1).astype(jnp.int32)
    keys = jnp.where((flat >= 0) & (flat < rows), flat, jnp.int32(rows))
    iota = lax.iota(jnp.int32, flat.shape[0])
    sid, perm = lax.sort_key_val(keys, iota)
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    inv = lax.sort_key_val(perm, iota)[1] if want_inv else None
    return GroupSort(sid, perm, seg_start, inv)


def read_var_no_copy(params: jax.Array) -> jax.Array:
    """API-parity shim for the reference's ReadVariableNoCopy op
    (cc/kernels/embedding_lookup_kernels.cc:28-45), which existed to read a
    TF resource variable without a copy-on-read of the full table. JAX arrays
    are immutable and jit donation/aliasing provides the no-copy semantics,
    so this is the identity."""
    return params


def row_to_split(row_ids: jax.Array, nrows: int) -> jax.Array:
    """COO sorted row-indices -> CSR row_splits.

    TPU equivalent of the reference's RowToSplit CUDA kernel
    (embedding_lookup_kernels.cu:337-356); on TPU `searchsorted` lowers to a
    vectorized binary search with no D2H traffic, so no custom kernel needed.
    """
    return jnp.searchsorted(
        row_ids, jnp.arange(nrows + 1, dtype=row_ids.dtype), side="left"
    ).astype(row_ids.dtype)


def _segment_ids_from_splits(row_splits: jax.Array, nnz: int) -> jax.Array:
    """Expand CSR row_splits into a per-value segment (row) id vector.

    Values past row_splits[-1] get segment id == nrows (out of range), which
    segment_sum drops — this is how static-shape padding stays correct.
    """
    positions = jnp.arange(nnz, dtype=row_splits.dtype)
    return jnp.searchsorted(row_splits, positions, side="right") - 1


def _combine(
    embs: jax.Array,
    seg_ids: jax.Array,
    num_segments: int,
    combiner: str,
    row_lengths: Optional[jax.Array] = None,
) -> jax.Array:
    out = jax.ops.segment_sum(embs, seg_ids, num_segments=num_segments)
    if combiner == "mean":
        if row_lengths is None:
            ones = jnp.ones(seg_ids.shape, dtype=embs.dtype)
            row_lengths = jax.ops.segment_sum(ones, seg_ids, num_segments=num_segments)
        counts = row_lengths.astype(embs.dtype)
        out = out / jnp.maximum(counts, 1.0)[:, None]
    return out


def embedding_lookup(
    params: jax.Array,
    ids: IdsLike,
    combiner: Optional[str] = None,
) -> jax.Array:
    """Looks up embeddings for `ids` from table `params` with optional combine.

    API mirror of the reference dispatch (embedding_lookup_ops.py:37-102):
      * ``combiner=None``: plain gather; output ``ids.shape + [width]``.
      * dense 2-D ids [batch, hotness]: gather + reduce over hotness.
      * RaggedIds: CSR segment-sum/mean (the custom-CUDA-kernel path in the
        reference; here XLA gather + segment_sum).
      * SparseIds: COO rows -> segment ids directly (reference uses RowToSplit).

    Args:
      params: [vocab, width] embedding table.
      ids: 2-D integer array, RaggedIds or SparseIds.
      combiner: None | 'sum' | 'mean'.

    Returns:
      [batch, width] when combiner is set, else ids.shape + [width].
    """
    if combiner not in (None, "sum", "mean"):
        raise ValueError(f"Unsupported combiner {combiner}")

    if isinstance(ids, RaggedIds):
        if combiner is None:
            raise ValueError("Ragged input requires a combiner")
        nnz = ids.values.shape[0]
        batch = ids.nrows
        seg_ids = _segment_ids_from_splits(ids.row_splits, nnz)
        embs = jnp.take(params, ids.values, axis=0)
        # zero out padded values so dropped-by-range is not load-bearing for
        # mean's count computation
        return _combine(embs, seg_ids, batch, combiner,
                        row_lengths=ids.row_lengths())

    if isinstance(ids, SparseIds):
        if combiner is None:
            raise ValueError("Sparse input requires a combiner")
        batch = int(ids.dense_shape[0])
        seg_ids = ids.indices[:, 0]
        embs = jnp.take(params, ids.values, axis=0)
        return _combine(embs, seg_ids, batch, combiner)

    ids = jnp.asarray(ids)
    if not jnp.issubdtype(ids.dtype, jnp.integer):
        ids = ids.astype(jnp.int32)
    if combiner is None:
        return jnp.take(params, ids, axis=0)
    if ids.ndim != 2:
        raise ValueError(f"Only 2-D dense ids supported with combiner, got ndim={ids.ndim}")
    if ids.shape[1] == 1:
        # hotness-1 fast path (reference embedding_lookup_ops.py:98-99)
        return jnp.take(params, jnp.squeeze(ids, 1), axis=0)
    embs = jnp.take(params, ids, axis=0)
    if combiner == "sum":
        return jnp.sum(embs, axis=1)
    return jnp.mean(embs, axis=1)


def embedding_lookup_weighted(
    params: jax.Array,
    ids: jax.Array,
    weights: jax.Array,
    combiner: str = "sum",
) -> jax.Array:
    """Dense padded multi-hot lookup with per-id weights.

    The distributed runtime's canonical multi-hot form: ids [batch, k_max]
    padded with arbitrary ids, weights [batch, k_max] carrying 0 for padding
    (and 1/n for mean). The weighted reduction is an einsum, which XLA maps
    onto the MXU — the TPU-native replacement for the reference's warp-level
    CSR combiner (embedding_lookup_kernels.cu:175-336).
    """
    embs = jnp.take(params, ids, axis=0)  # [batch, k, width]
    out = jnp.einsum("bk,bkw->bw", weights.astype(embs.dtype), embs)
    if combiner == "mean":
        denom = jnp.maximum(jnp.sum(weights, axis=1), 1.0).astype(out.dtype)
        out = out / denom[:, None]
    return out


def sorted_member_positions(sorted_keys: jax.Array,
                            queries: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Membership of `queries` in a sorted key table, via binary search.

    The hot-row split's primitive (training hot shard,
    layers/dist_model_parallel.py, and the hotrows HLO-audit gate): a
    `searchsorted` lowers to a vectorized binary search — NO sort op and
    no host traffic — so splitting a lookup stream against a hot set adds
    zero sort instructions to the compiled step.

    Args:
      sorted_keys: [H] ascending int array; absent slots padded with a
        sentinel LARGER than any real query (padding must keep the array
        sorted).
      queries: integer array, any shape.

    Returns (pos, hit): pos [queries.shape] int32 clamped in [0, H), the
    index of each query's match (meaningless where hit is False); hit
    boolean, True where sorted_keys[pos] == query.
    """
    h = sorted_keys.shape[0]
    # scan_unrolled: the log2(H) binary-search steps unroll instead of
    # riding a lax.scan — same op mix (gathers + compares, NO sort), less
    # per-step dispatch overhead (measurably so on XLA:CPU; neutral on TPU)
    pos = jnp.searchsorted(sorted_keys, queries, method="scan_unrolled")
    pos = jnp.clip(pos, 0, max(h - 1, 0)).astype(jnp.int32)
    hit = jnp.take(sorted_keys, pos) == queries
    return pos, hit


def miss_only_ids(ids: jax.Array, slot_idx: jax.Array) -> jax.Array:
    """Clamp cache-hit lanes' ids to row 0 for the miss-side table gather.

    `slot_idx >= 0` marks lanes a row cache will serve from device memory;
    the fallback gather must still have a static shape, so hit lanes read a
    single dummy row (row 0) instead of their real row — the table sees no
    read traffic proportional to hits. Shapes broadcast elementwise.
    """
    return jnp.where(slot_idx >= 0, jnp.zeros((), ids.dtype), ids)


def masked_two_source_gather(slots: jax.Array, slot_idx: jax.Array,
                             fallback_rows: jax.Array) -> jax.Array:
    """Row-select between a cache tensor and pre-gathered fallback rows.

    The serving hot-row cache's combining primitive
    (serving/cache.py): lanes with ``slot_idx >= 0`` take row
    ``slots[slot_idx]`` (an HBM gather); the rest take the matching row of
    `fallback_rows` (typically gathered from a host-resident table with
    `miss_only_ids`). Keeping the select separate from the two gathers lets
    the caller place each gather in its own memory space.

    Args:
      slots: [capacity, width] cached rows.
      slot_idx: [...] int32, -1 (or any negative) = miss.
      fallback_rows: [..., width] rows for the miss lanes (hit lanes'
        values are ignored).

    Returns [..., width]: the merged rows.
    """
    hit = slot_idx >= 0
    safe = jnp.clip(slot_idx, 0, slots.shape[0] - 1)
    cached = jnp.take(slots, safe, axis=0)
    return jnp.where(hit[..., None], cached.astype(fallback_rows.dtype),
                     fallback_rows)


def ragged_to_padded(
    ids: RaggedIds, max_hotness: int, combiner: str = "sum"
) -> Tuple[jax.Array, jax.Array]:
    """Convert CSR ragged ids to (padded_ids [batch, k], weights [batch, k]).

    The weights are 1.0 for valid slots, 0.0 for padding (combiner='sum');
    for 'mean' they stay 1.0 — mean division happens in
    embedding_lookup_weighted from the weight row-sums.
    """
    batch = ids.nrows
    starts = ids.row_splits[:-1]
    lengths = ids.row_lengths()
    offs = jnp.arange(max_hotness, dtype=ids.row_splits.dtype)
    gather_pos = starts[:, None] + offs[None, :]
    valid = offs[None, :] < lengths[:, None]
    nnz = ids.values.shape[0]
    gather_pos = jnp.clip(gather_pos, 0, max(nnz - 1, 0))
    padded = jnp.take(ids.values, gather_pos, axis=0)
    padded = jnp.where(valid, padded, 0)
    weights = valid.astype(jnp.float32)
    del combiner, batch
    return padded, weights
